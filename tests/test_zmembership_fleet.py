"""Subprocess-fleet acceptance tests for live elastic membership.

These spawn REAL worker fleets (tests/membership_worker.py — each
member a single-process JAX subprocess coordinating through a shared
fleet directory) and are by far the most expensive tests in the suite;
the file is named to sort LAST so the cheap broad suites run first.
The in-process membership unit tests live in tests/test_membership.py.

The acceptance contract: a real 3-worker fleet survives, in ONE run, a
SIGTERM clean leave, a SIGKILL eviction, and a mid-run join — and
(quantized mode, integer features) the survivors' final model is
BYTE-IDENTICAL to the static single-worker reference trained on the
same global data.  Killing the coordinator (member 0) re-elects the
lowest surviving id and the fleet still completes with the identical
model.  The full churn matrix is marked ``slow``.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "membership_worker.py")

pytestmark = pytest.mark.membership

_STRIP = ("LIGHTGBM_TPU_", "MEMBER_", "XLA_")


def _clean_env(extra=None):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(_STRIP)}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["LIGHTGBM_TPU_NET_TIMEOUT"] = "8"
    if extra:
        env.update(extra)
    return env


def _spawn(member, fleet_dir, out, extra_env=None):
    return subprocess.Popen(
        [sys.executable, WORKER, str(member), fleet_dir, out],
        env=_clean_env(extra_env), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)


def _run_fleet(fleet_dir, nproc, per_member=None, with_joiner=False,
               timeout=240):
    """Launch a bootstrap fleet (plus optionally one mid-run joiner) and
    wait for every process; returns {member_key: (rc, stdout)}."""
    os.makedirs(fleet_dir, exist_ok=True)
    out = os.path.join(fleet_dir, "out")
    procs = {}
    for m in range(nproc):
        extra = {"MEMBER_NPROC": str(nproc)}
        extra.update((per_member or {}).get(m, {}))
        procs[m] = _spawn(m, fleet_dir, out, extra)
    if with_joiner:
        procs["join"] = _spawn("join", fleet_dir, out,
                               {"MEMBER_NPROC": str(nproc)})
    deadline = time.monotonic() + timeout
    results = {}
    for key, p in procs.items():
        try:
            o, _ = p.communicate(timeout=max(1, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            o, _ = p.communicate()
            o = (o or "") + "\n<<parent timeout — killed>>"
        results[key] = (p.returncode, o or "")
    return out, results


def _meta(out, mid):
    with open(out + f".m{mid}.json") as fh:
        return json.load(fh)


def _model(out, mid):
    with open(out + f".m{mid}.txt") as fh:
        return fh.read()


def _dump(results):
    return "\n".join(f"--- member {k} rc={rc} ---\n{o[-2500:]}"
                     for k, (rc, o) in results.items())


@pytest.fixture(scope="module")
def reference(tmp_path_factory):
    """The static reference: ONE member holding the whole global
    dataset.  Quantized training's seed chain depends only on the
    iteration index, so any static world — and any elastic trajectory
    that preserves state exactly — must reproduce these bytes."""
    d = str(tmp_path_factory.mktemp("member_ref"))
    out, results = _run_fleet(d, 1)
    rc, _o = results[0]
    assert rc == 0, _dump(results)
    return _model(out, 0), _meta(out, 0)


# ----------------------------------------------------------------------
# default-off guard (the pre-PR path must be bit-for-bit untouched)
# ----------------------------------------------------------------------
def test_elastic_off_and_armed_without_runtime_are_identical():
    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import Config

    assert Config().elastic_membership is False

    rng = np.random.default_rng(3)
    X = rng.integers(0, 5, size=(160, 6)).astype(np.float32)
    y = (rng.random(160) < 0.5).astype(np.float32)
    base = dict(objective="binary", num_leaves=5, learning_rate=0.2,
                max_bin=31, min_data_in_leaf=10, seed=11, verbose=-1,
                num_boost_round=4)

    def _train(extra):
        p = dict(base, **extra)
        ds = lgb.Dataset(X, label=y, params=dict(p))
        return lgb.train(p, ds, num_boost_round=4).model_to_string()

    # armed but no fleet runtime registered -> warning-decline to the
    # exact same path as unarmed (tree_learner held fixed: the knob
    # itself must change nothing)
    ref = _train({"tree_learner": "data", "pre_partition": True})
    armed = _train({"elastic_membership": True, "tree_learner": "data",
                    "pre_partition": True})
    assert armed == ref
    # the knob is fingerprint-volatile: flipping it must not invalidate
    # checkpoints (ckpt/state.py _FP_VOLATILE)
    from lightgbm_tpu.ckpt.state import config_fingerprint
    assert (config_fingerprint(Config(**{k: v for k, v in base.items()
                                         if k != "num_boost_round"}))
            == config_fingerprint(Config(elastic_membership=True,
                                         **{k: v for k, v in base.items()
                                            if k != "num_boost_round"})))


# ----------------------------------------------------------------------
# subprocess fleets (tier-1 acceptance)
# ----------------------------------------------------------------------
def test_fleet_churn_one_run_byte_identity(reference, tmp_path):
    """THE acceptance run: 3 bootstrap workers; member 1 SIGTERMs itself
    at iteration 2 (real signal -> handler -> clean leave), member 2 is
    SIGKILLed at iteration 5 (eviction), and a joiner arrives mid-run.
    The two finishers must produce the reference bytes."""
    ref_model, _ref_meta = reference
    per = {
        0: {"MEMBER_ITER_SLEEP": "0.4"},  # paces the lockstep fleet so
        #                                   the joiner lands mid-run
        1: {"MEMBER_SIGTERM_ITER": "2"},
        2: {"MEMBER_KILL_ITER": "5"},
    }
    out, results = _run_fleet(str(tmp_path), 3, per_member=per,
                              with_joiner=True)
    assert results[0][0] == 0, _dump(results)
    assert results[1][0] == 0, _dump(results)       # clean leave exits 0
    assert results[2][0] == -signal.SIGKILL, _dump(results)
    assert results["join"][0] == 0, _dump(results)

    leaver = _meta(out, 1)
    assert leaver["left_at_epoch"] >= 1
    assert not os.path.exists(out + ".m1.txt")      # leavers write no model
    joiner_id = max(int(f.split(".m")[1].split(".")[0])
                    for f in os.listdir(str(tmp_path))
                    if f.startswith("out.m") and f.endswith(".json"))
    assert joiner_id >= 3                           # monotonic fresh id
    for mid in (0, joiner_id):
        meta = _meta(out, mid)
        assert meta["trees"] == 12 and meta["iters"] == 12, meta
        assert meta["final_members"] == sorted(meta["final_members"])
        assert 1 not in meta["final_members"]
        assert 2 not in meta["final_members"]
        assert joiner_id in meta["final_members"]
        assert sum(meta["final_counts"]) == 600
        assert _model(out, mid) == ref_model, (
            f"member {mid} diverged from the static reference")
    # zero lost iterations: the survivor trained every round exactly once
    assert len(_meta(out, 0)["epochs_seen"]) == 12


def test_fleet_coordinator_sigkill_reelection(reference, tmp_path):
    """Rank 0 IS the coordinator; SIGKILLing it mid-run must re-elect
    member 1 (lowest survivor), bump the epoch, and still complete with
    the reference bytes."""
    ref_model, _ref_meta = reference
    out, results = _run_fleet(str(tmp_path), 3,
                              per_member={0: {"MEMBER_KILL_ITER": "4"}})
    assert results[0][0] == -signal.SIGKILL, _dump(results)
    for mid in (1, 2):
        assert results[mid][0] == 0, _dump(results)
        meta = _meta(out, mid)
        assert meta["final_members"] == [1, 2]
        assert meta["final_epoch"] >= 1
        assert meta["trees"] == 12
        assert _model(out, mid) == ref_model
    # deterministic re-election: both survivors agree the new
    # coordinator is the lowest surviving id
    m1 = _meta(out, 1)
    assert min(m1["final_members"]) == 1


# ----------------------------------------------------------------------
# churn matrix (slow)
# ----------------------------------------------------------------------
@pytest.mark.slow
@pytest.mark.parametrize("scenario", ["float_churn", "rebalance_churn",
                                      "double_join", "shrink_to_one"])
def test_fleet_churn_matrix(scenario, tmp_path):
    per = {}
    nproc, with_joiner = 3, False
    joiner_env = {}
    expect_finishers = None
    if scenario == "float_churn":
        # non-quantized float mode: world-size parity is not byte-exact,
        # so assert survival + roster, not bytes
        per = {0: {"MEMBER_QUANTIZED": "0", "MEMBER_ITER_SLEEP": "0.4"},
               1: {"MEMBER_QUANTIZED": "0", "MEMBER_SIGTERM_ITER": "2"},
               2: {"MEMBER_QUANTIZED": "0", "MEMBER_KILL_ITER": "5"}}
        joiner_env = {"MEMBER_QUANTIZED": "0"}
        with_joiner = True
    elif scenario == "rebalance_churn":
        per = {m: {"MEMBER_REBALANCE": "1"} for m in range(3)}
        per[1]["MEMBER_LEAVE_ITER"] = "3"
        expect_finishers = [0, 2]
    elif scenario == "double_join":
        nproc = 2
        per = {0: {"MEMBER_ITER_SLEEP": "0.5"}}
        with_joiner = True  # plus a second joiner below
    elif scenario == "shrink_to_one":
        per = {1: {"MEMBER_LEAVE_ITER": "1"}, 2: {"MEMBER_LEAVE_ITER": "3"}}
        expect_finishers = [0]

    os.makedirs(str(tmp_path), exist_ok=True)
    out = os.path.join(str(tmp_path), "out")
    procs = {}
    for m in range(nproc):
        extra = {"MEMBER_NPROC": str(nproc)}
        extra.update(per.get(m, {}))
        procs[m] = _spawn(m, str(tmp_path), out, extra)
    if with_joiner:
        procs["join"] = _spawn("join", str(tmp_path), out,
                               dict(joiner_env, MEMBER_NPROC=str(nproc)))
    if scenario == "double_join":
        time.sleep(2.0)
        procs["join2"] = _spawn("join", str(tmp_path), out,
                                dict(joiner_env, MEMBER_NPROC=str(nproc)))
    results = {}
    for key, p in procs.items():
        try:
            o, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            p.kill()
            o, _ = p.communicate()
        results[key] = (p.returncode, o or "")

    finisher_models = {}
    for f in os.listdir(str(tmp_path)):
        if f.startswith("out.m") and f.endswith(".txt"):
            mid = int(f.split(".m")[1].split(".")[0])
            finisher_models[mid] = _model(out, mid)
    assert finisher_models, _dump(results)
    metas = {mid: _meta(out, mid) for mid in finisher_models}
    rosters = {tuple(m["final_members"]) for m in metas.values()}
    assert len(rosters) == 1, (rosters, _dump(results))
    for mid, meta in metas.items():
        assert meta["trees"] == 12, _dump(results)
        assert sum(meta["final_counts"]) == 600
    assert len(set(finisher_models.values())) == 1, _dump(results)
    if expect_finishers is not None:
        assert sorted(finisher_models) == expect_finishers, _dump(results)
    if scenario == "double_join":
        assert len(next(iter(rosters))) == 4, _dump(results)
    if scenario == "shrink_to_one":
        assert next(iter(rosters)) == (0,), _dump(results)
