#!/bin/bash
# Regenerate the golden reference outputs in tests/golden/ from the
# actual reference implementation (/root/reference, built into
# /root/repo/refbuild/lightgbm — see refbuild/cmake.log).
#
# Sampling params are forced deterministic (feature_fraction=1.0, no
# bagging): the two implementations use different RNG streams, so only
# the sampling-free configuration is comparable tree-for-tree.
set -e
BIN=${LIGHTGBM_BIN:-/root/repo/refbuild/lightgbm}
EX=/root/reference/examples
OUT=$(cd "$(dirname "$0")" && pwd)
DET="feature_fraction=1.0 bagging_freq=0 bagging_fraction=1.0 num_trees=30 is_training_metric=false"

run() { # name confdir extra...
  local name=$1 dir=$2; shift 2
  local wd=$(mktemp -d)
  cp "$EX/$dir/"*.train "$EX/$dir/"*.test "$wd/" 2>/dev/null || true
  cp "$EX/$dir/"*.query "$wd/" 2>/dev/null || true
  # FULL per-iteration metric trace: the parity suite compares our
  # iteration-by-iteration valid metrics against the reference's, not
  # just the final value
  (cd "$wd" && "$BIN" config="$EX/$dir/train.conf" $DET "$@" \
      output_model="$OUT/${name}_model.txt" 2>&1 | grep -E "Iteration:[0-9]+," \
      > "$OUT/${name}_train_metrics.txt")
  (cd "$wd" && "$BIN" config="$EX/$dir/predict.conf" \
      input_model="$OUT/${name}_model.txt" \
      output_result="$OUT/${name}_pred.txt" > /dev/null 2>&1)
  rm -rf "$wd"
  echo "golden: $name"
}

run binary binary_classification
run regression regression
run multiclass multiclass_classification
run lambdarank lambdarank
