"""Parity tests: JAX ops vs the sequential float64 numpy oracle
(tests/oracle.py), per SURVEY §4's golden-comparison strategy."""

import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.ops.histogram import build_histogram
from lightgbm_tpu.ops.split import FeatureMeta, SplitHyper, best_split_all_features
from lightgbm_tpu.ops.grow import GrowParams, grow_tree

import oracle


def make_data(rng, n=4000, f=8, b=24, missing_frac=0.2):
    bins = rng.randint(0, b, (n, f)).astype(np.uint8)
    default_bin = rng.randint(0, b, f).astype(np.int32)
    # concentrate mass on the default bin to imitate zero-sparsity
    for j in range(f):
        m = rng.rand(n) < missing_frac
        bins[m, j] = default_bin[j]
    g = rng.randn(n).astype(np.float32)
    h = np.abs(rng.randn(n)).astype(np.float32) + 0.1
    return bins, default_bin, g, h


CFG = dict(lambda_l1=0.0, lambda_l2=0.0, min_data_in_leaf=20,
           min_sum_hessian_in_leaf=1e-3, min_gain_to_split=0.0)


def jax_meta(default_bin, b, f, is_cat=None):
    return FeatureMeta(
        jnp.full((f,), b, jnp.int32),
        jnp.asarray(default_bin),
        jnp.asarray(is_cat if is_cat is not None else np.zeros(f, bool)),
    )


def jax_hyper(cfg):
    return SplitHyper(*(jnp.float32(cfg[k]) for k in (
        "lambda_l1", "lambda_l2", "min_data_in_leaf",
        "min_sum_hessian_in_leaf", "min_gain_to_split")))


class TestHistogram:
    def test_matches_oracle(self, rng):
        bins, _, g, h = make_data(rng)
        sel = (rng.rand(len(g)) < 0.7).astype(np.float32)
        hist = np.asarray(build_histogram(jnp.asarray(bins), jnp.asarray(g),
                                          jnp.asarray(h), jnp.asarray(sel), 24, 512))
        want = oracle.build_histogram_np(bins, g.astype(np.float64),
                                         h.astype(np.float64), sel, 24)
        np.testing.assert_allclose(hist, want, rtol=1e-4, atol=1e-3)

    def test_unpadded_rows(self, rng):
        # n not a multiple of row_block: padding rows must contribute nothing
        bins = rng.randint(0, 8, (777, 3)).astype(np.uint8)
        g = rng.randn(777).astype(np.float32)
        h = np.ones(777, np.float32)
        sel = np.ones(777, np.float32)
        hist = np.asarray(build_histogram(jnp.asarray(bins), jnp.asarray(g),
                                          jnp.asarray(h), jnp.asarray(sel), 8, 256))
        assert hist[:, :, 2].sum() == pytest.approx(3 * 777)


class TestSplit:
    @pytest.mark.parametrize("seed", range(5))
    @pytest.mark.parametrize("cfg_over", [
        {}, {"lambda_l1": 0.5, "lambda_l2": 1.0},
        {"min_data_in_leaf": 200}, {"min_gain_to_split": 0.2},
    ])
    def test_numerical_vs_oracle(self, seed, cfg_over):
        rng = np.random.RandomState(seed)
        cfg = {**CFG, **cfg_over}
        n, f, b = 4000, 8, 24
        bins, default_bin, g, h = make_data(rng, n, f, b)
        hist = oracle.build_histogram_np(bins, g, h, np.ones(n), b)
        sum_g, sum_h = float(g.sum()), float(h.sum())

        want = oracle.best_split_all_features_np(
            hist, sum_g, sum_h, n, default_bin, np.zeros(f, bool),
            np.full(f, b), cfg)
        got = best_split_all_features(
            jnp.asarray(hist, jnp.float32), jnp.float32(sum_g), jnp.float32(sum_h),
            jnp.float32(n), jax_meta(default_bin, b, f), jax_hyper(cfg),
            jnp.ones((f,)))
        if not np.isfinite(want["gain"]):
            assert not np.isfinite(float(got.gain))
            return
        # JAX's best must match the oracle's gain; identical (feat, thr, dbz)
        # unless a float32-level tie
        assert float(got.gain) == pytest.approx(want["gain"], rel=1e-4, abs=1e-4)
        if abs(want["gain"]) > 1e-3:
            assert (int(got.feature), int(got.threshold_bin), int(got.default_bin_for_zero)) == \
                (want["feature"], want["threshold"], want["dbz"])
            lg, lh, lc = want["left"]
            assert float(got.left_cnt) == lc
            assert float(got.left_sum_g) == pytest.approx(lg, rel=1e-4, abs=1e-3)

    def test_categorical_vs_oracle(self, rng):
        n, f, b = 4000, 6, 12
        bins, default_bin, g, h = make_data(rng, n, f, b)
        is_cat = np.array([True, False, True, False, True, True])
        hist = oracle.build_histogram_np(bins, g, h, np.ones(n), b)
        want = oracle.best_split_all_features_np(
            hist, float(g.sum()), float(h.sum()), n, default_bin, is_cat,
            np.full(f, b), CFG)
        got = best_split_all_features(
            jnp.asarray(hist, jnp.float32), jnp.float32(g.sum()), jnp.float32(h.sum()),
            jnp.float32(n), jax_meta(default_bin, b, f, is_cat), jax_hyper(CFG),
            jnp.ones((f,)))
        assert float(got.gain) == pytest.approx(want["gain"], rel=1e-4, abs=1e-4)
        assert int(got.feature) == want["feature"]
        assert int(got.threshold_bin) == want["threshold"]

    def test_feature_mask(self, rng):
        n, f, b = 2000, 4, 16
        bins, default_bin, g, h = make_data(rng, n, f, b)
        hist = oracle.build_histogram_np(bins, g, h, np.ones(n), b).astype(np.float32)
        full = best_split_all_features(
            jnp.asarray(hist), jnp.float32(g.sum()), jnp.float32(h.sum()),
            jnp.float32(n), jax_meta(default_bin, b, f), jax_hyper(CFG), jnp.ones((f,)))
        mask = np.ones(f, np.float32)
        mask[int(full.feature)] = 0.0
        masked = best_split_all_features(
            jnp.asarray(hist), jnp.float32(g.sum()), jnp.float32(h.sum()),
            jnp.float32(n), jax_meta(default_bin, b, f), jax_hyper(CFG), jnp.asarray(mask))
        assert int(masked.feature) != int(full.feature)


class TestGrow:
    def grow(self, rng, num_leaves=16, n=4000, f=8, b=24, cfg=None, **kw):
        cfg = cfg or CFG
        bins, default_bin, g, h = make_data(rng, n, f, b)
        params = GrowParams(num_leaves=num_leaves, num_bins=b, **kw)
        res = grow_tree(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                        jnp.ones((n,)), jnp.ones((f,)),
                        jax_meta(default_bin, b, f), jax_hyper(cfg), params)
        return bins, default_bin, g, h, res

    def test_partition_consistency(self, rng):
        _, _, _, _, res = self.grow(rng)
        ns = int(res.num_splits)
        assert 1 <= ns <= 15
        counts = np.bincount(np.asarray(res.leaf_id), minlength=16)
        np.testing.assert_array_equal(counts[: ns + 1], np.asarray(res.leaf_cnt)[: ns + 1])
        assert counts[ns + 1:].sum() == 0

    def test_matches_oracle_tree(self, rng):
        """Full best-first sequence parity with a sequential oracle grower."""
        n, f, b, L = 3000, 6, 16, 8
        bins, default_bin, g, h = make_data(rng, n, f, b)
        params = GrowParams(num_leaves=L, num_bins=b)
        res = grow_tree(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                        jnp.ones((n,)), jnp.ones((f,)),
                        jax_meta(default_bin, b, f), jax_hyper(CFG), params)

        # oracle best-first grower
        leaf_rows = {0: np.arange(n)}
        best = {}

        def leaf_best(rows):
            hist = oracle.build_histogram_np(bins[rows], g[rows], h[rows],
                                             np.ones(len(rows)), b)
            return oracle.best_split_all_features_np(
                hist, float(g[rows].sum()), float(h[rows].sum()), len(rows),
                default_bin, np.zeros(f, bool), np.full(f, b), CFG)

        best[0] = leaf_best(leaf_rows[0])
        for s in range(int(res.num_splits)):
            bl = max(best, key=lambda k: best[k]["gain"])
            assert bl == int(res.rec_leaf[s]), f"split {s} leaf"
            r = best[bl]
            assert r["feature"] == int(res.rec_feat[s]), f"split {s} feature"
            assert r["threshold"] == int(res.rec_thr[s]), f"split {s} threshold"
            assert r["dbz"] == int(res.rec_dbz[s]), f"split {s} dbz"
            assert r["gain"] == pytest.approx(float(res.rec_gain[s]), rel=1e-3, abs=1e-3)
            rows = leaf_rows[bl]
            col = bins[rows, r["feature"]].astype(np.int64)
            fv = np.where(col == default_bin[r["feature"]], r["dbz"], col)
            lmask = fv <= r["threshold"]
            leaf_rows[bl] = rows[lmask]
            leaf_rows[s + 1] = rows[~lmask]
            best[bl] = leaf_best(leaf_rows[bl])
            best[s + 1] = leaf_best(leaf_rows[s + 1])

    def test_max_depth(self, rng):
        _, _, _, _, res = self.grow(rng, num_leaves=32, max_depth=2)
        # depth-2 tree has at most 4 leaves = 3 splits
        assert int(res.num_splits) <= 3

    def test_leaf_values(self, rng):
        bins, db, g, h, res = self.grow(rng, cfg={**CFG, "lambda_l2": 1.0})
        ns = int(res.num_splits)
        leaf_id = np.asarray(res.leaf_id)
        for leaf in range(ns + 1):
            rows = leaf_id == leaf
            want = oracle.leaf_output(g[rows].sum(), h[rows].sum(), 0.0, 1.0)
            assert float(res.leaf_value[leaf]) == pytest.approx(want, rel=1e-3, abs=1e-4)
