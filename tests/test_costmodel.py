"""Cost-model tests: roofline arithmetic on a synthetic spec, peak-spec
resolution + the LIGHTGBM_TPU_PEAK_SPECS override, the JitWatch
first-compile HLO capture on CPU, the efficiency join (program costs x
measured phase spans), the ``report costs`` / ``report bench-trend``
CLIs, JSONL trace rotation, and the bounded xprof capture harness.
"""

import glob
import json
import os
import pathlib

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import costmodel, report
from lightgbm_tpu.obs.compilewatch import JitWatch
from lightgbm_tpu.obs.trace import Tracer


# pf/pb chosen so the arithmetic is checkable by hand: ridge AI = 10
SPEC = {"key": "synthetic", "device_kind": "synthetic",
        "flops_per_s": 100.0, "hbm_bytes_per_s": 10.0, "source": "default"}


@pytest.fixture
def global_trace(tmp_path, monkeypatch):
    """Route the process-global tracer to a temp file and isolate the
    process-global cost inventory for one test."""
    from lightgbm_tpu.obs import tracer

    path = str(tmp_path / "trace.jsonl")
    monkeypatch.setenv("LIGHTGBM_TPU_TRACE", path)
    costmodel.reset()
    yield path
    tracer.close()
    tracer.path = None
    tracer.reset_aggregates()
    costmodel.reset()


def _read(path):
    return [json.loads(l) for l in open(path) if l.strip()]


def _toy(n=500, f=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + X[:, 1] > 0).astype(float)
    return X, y


class TestRoofline:
    def test_compute_bound_arithmetic(self):
        # work 200 flop / peak 100 flop/s = 2 s; 10 B / 10 B/s = 1 s
        rl = costmodel.roofline(200.0, 10.0, 0.0, SPEC)
        assert rl["bound"] == "compute"
        assert rl["lb_s"] == pytest.approx(2.0)
        assert rl["ai"] == pytest.approx(20.0)
        assert rl["ridge_ai"] == pytest.approx(10.0)

    def test_memory_bound_arithmetic(self):
        rl = costmodel.roofline(10.0, 100.0, 0.0, SPEC)
        assert rl["bound"] == "memory"
        assert rl["lb_s"] == pytest.approx(10.0)
        assert rl["ai"] == pytest.approx(0.1)

    def test_transcendentals_count_as_work(self):
        # 50 transcendentals at 1 flop each: 0.5 s compute vs 0.1 s memory
        rl = costmodel.roofline(0.0, 1.0, 50.0, SPEC)
        assert rl["bound"] == "compute"
        assert rl["lb_s"] == pytest.approx(0.5)

    def test_zero_bytes_means_no_ai(self):
        assert costmodel.roofline(5.0, 0.0, 0.0, SPEC)["ai"] is None


class TestPeakSpecs:
    def test_longest_substring_key_wins(self):
        # "tpu v5 lite" must beat the shorter "tpu v5e"-style keys
        spec = costmodel.resolve_peak_spec("TPU v5 lite")
        assert spec["key"] == "tpu v5 lite"
        assert spec["flops_per_s"] == pytest.approx(197e12)
        assert costmodel.resolve_peak_spec("TPU v4")["key"] == "tpu v4"

    def test_unknown_kind_falls_back_to_cpu(self):
        spec = costmodel.resolve_peak_spec("Weird FPGA rev7")
        assert spec["key"] == "cpu"
        assert spec["device_kind"] == "Weird FPGA rev7"

    def test_env_override_merges_and_marks_source(self, monkeypatch):
        monkeypatch.setenv(
            "LIGHTGBM_TPU_PEAK_SPECS",
            '{"cpu": {"flops_per_s": 123.0, "hbm_bytes_per_s": 456.0},'
            ' "tpu v6e": {"flops_per_s": 9e14, "hbm_bytes_per_s": 2e12}}')
        spec = costmodel.resolve_peak_spec("cpu")
        assert spec["flops_per_s"] == pytest.approx(123.0)
        assert spec["hbm_bytes_per_s"] == pytest.approx(456.0)
        assert spec["source"] == "env"
        # brand-new device kinds become matchable
        assert costmodel.resolve_peak_spec("TPU v6e")["key"] == "tpu v6e"

    def test_malformed_env_is_ignored(self, monkeypatch):
        monkeypatch.setenv("LIGHTGBM_TPU_PEAK_SPECS", "{not json")
        spec = costmodel.resolve_peak_spec("cpu")
        assert spec["flops_per_s"] == pytest.approx(
            costmodel.DEFAULT_PEAK_SPECS["cpu"]["flops_per_s"])
        assert spec["source"] == "default"


class TestCaptureOnCpu:
    def test_first_compile_per_signature_emits_jax_cost(
            self, global_trace, monkeypatch):
        import jax
        import jax.numpy as jnp

        from lightgbm_tpu.obs import tracer

        tracer.refresh_from_env()
        # force the deep (compiled) pass regardless of host speed
        monkeypatch.setenv("LIGHTGBM_TPU_COSTMODEL_DEEP_BUDGET", "60")

        def f(a, b):
            return jnp.tanh(a @ b).sum()

        w = JitWatch(jax.jit(f), "test.capture.matmul", phase="test_phase")
        a = jnp.ones((32, 32), jnp.float32)
        w(a, a)
        w(a, a)  # cached signature: must NOT capture again
        b = jnp.ones((16, 16), jnp.float32)
        w(b, b)  # new signature: second capture

        inv = costmodel.inventory()
        assert "test.capture.matmul" in inv
        entry = inv["test.capture.matmul"]
        assert entry["phase"] == "test_phase"
        recs = entry["records"]
        assert len(recs) == 2
        for r in recs:
            assert r["flops"] > 0
            assert r["bytes_accessed"] > 0
            assert r["level"] == "compiled"  # deep pass ran under budget
            assert "temp_bytes" in r
        # the 32x32 matmul does more work than the 16x16 one
        assert recs[0]["flops"] > recs[1]["flops"]

        tracer.close()
        events = [r for r in _read(global_trace)
                  if r.get("ev") == "event" and r.get("name") == "jax_cost"]
        assert len(events) == 2
        assert {e["program"] for e in events} == {"test.capture.matmul"}

    def test_same_program_and_sig_captured_once_per_process(
            self, global_trace):
        """JitWatch instances are rebuilt per trainer: a second watch
        with the same program name and argument signature must NOT
        re-pay the capture (the suite trains many boosters)."""
        import jax
        import jax.numpy as jnp

        from lightgbm_tpu.obs import tracer

        tracer.refresh_from_env()

        def f(a):
            return (a * 2).sum()

        x = jnp.ones((8,), jnp.float32)
        JitWatch(jax.jit(f), "test.capture.dedup", phase="p")(x)
        # fresh watch + fresh jit of a fresh callable: compiles again,
        # but the (program, signature) pair is already captured
        JitWatch(jax.jit(lambda a: (a * 2).sum()),
                 "test.capture.dedup", phase="p")(x)
        recs = costmodel.inventory()["test.capture.dedup"]["records"]
        assert len(recs) == 1

    def test_kill_switch_disables_capture(self, global_trace, monkeypatch):
        import jax
        import jax.numpy as jnp

        from lightgbm_tpu.obs import tracer

        tracer.refresh_from_env()
        monkeypatch.setenv("LIGHTGBM_TPU_COSTMODEL", "0")
        w = JitWatch(jax.jit(lambda x: x * 2), "test.capture.disabled")
        w(jnp.ones((4,)))
        assert "test.capture.disabled" not in costmodel.inventory()

    def test_non_aot_callable_is_skipped(self):
        class W:
            name = "test.capture.nolower"
            phase = None
            _fn = staticmethod(lambda x: x)

        assert costmodel.capture(W(), (1,), {}, 0.0) is None

    def test_traced_training_populates_inventory_and_joins(
            self, global_trace, monkeypatch):
        """Inventory completeness: a traced-phases training run must
        yield cost records for the traced per-phase programs, and the
        offline join must produce an efficiency table with a
        next-target pick — the `report costs` acceptance path."""
        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
        monkeypatch.setenv("LIGHTGBM_TPU_TRACE_PHASES", "1")
        monkeypatch.setenv("LIGHTGBM_TPU_COSTMODEL_DEEP_BUDGET", "60")
        # shape chosen to be unique across the test session so every
        # traced program sees a fresh signature
        X, y = _toy(613, 6, seed=3)
        lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1},
                  lgb.Dataset(X, label=y), num_boost_round=3,
                  verbose_eval=False)

        inv = costmodel.inventory()
        traced = {n for n in inv if n.startswith("ptrainer.traced.")}
        assert len(traced) >= 4, f"traced programs missing costs: {inv.keys()}"

        from lightgbm_tpu.obs import tracer

        tracer.close()
        recs = _read(global_trace)
        summary = costmodel.costs_summary(recs)
        assert summary["n_programs"] >= 4
        rows = summary["table"]
        assert rows, "no joinable phases"
        phases = {r["phase"] for r in rows}
        assert {"histogram", "partition"} <= phases
        for r in rows:
            assert r["calls"] > 0 and r["measured_s"] > 0
            assert r["roofline_s"] >= 0
        assert summary["next_target_line"].startswith("next kernel target:")
        text = costmodel.render_costs(summary)
        assert "program inventory" in text
        assert "next kernel target:" in text


def _cost_rec(program, phase, flops, nbytes, trans=0.0, backend="synthetic"):
    return {"ev": "event", "name": "jax_cost", "program": program,
            "phase": phase, "backend": backend, "level": "compiled",
            "flops": flops, "bytes_accessed": nbytes,
            "transcendentals": trans}


def _span_rec(name, dur):
    return {"ev": "span", "name": name, "dur_s": dur}


class TestEfficiencyJoin:
    def test_join_arithmetic_pinned(self):
        # one program, lb 1 s/call; 4 spans of 2 s -> 50% efficiency
        records = [_cost_rec("p.hist", "histogram", 100.0, 10.0)]
        records += [_span_rec("histogram", 2.0)] * 4
        summary = costmodel.costs_summary(records, spec=SPEC)
        (row,) = summary["table"]
        assert row["calls"] == 4
        assert row["measured_s"] == pytest.approx(8.0)
        assert row["roofline_s"] == pytest.approx(4.0)
        assert row["efficiency_pct"] == pytest.approx(50.0)
        assert row["headroom_s"] == pytest.approx(4.0)
        assert row["share_pct"] == pytest.approx(100.0)
        assert summary["next_target"]["program"] == "p.hist"
        assert "p.hist" in summary["next_target_line"]

    def test_representative_is_largest_roofline(self):
        # two programs tag the same phase: the heavier one represents it
        records = [_cost_rec("p.small", "histogram", 10.0, 1.0),
                   _cost_rec("p.big", "histogram", 1000.0, 10.0),
                   _span_rec("histogram", 30.0)]
        (row,) = costmodel.costs_summary(records, spec=SPEC)["table"]
        assert row["program"] == "p.big"
        assert row["roofline_s"] == pytest.approx(10.0)

    def test_next_target_is_max_headroom_not_max_share(self):
        # A: 10 s wall, 1 s roofline (headroom 9); B: 12 s wall, 11 s
        # roofline (headroom 1) — B has more share, A more headroom
        records = [_cost_rec("p.a", "phase_a", 100.0, 1.0),
                   _cost_rec("p.b", "phase_b", 1100.0, 1.0),
                   _span_rec("phase_a", 10.0),
                   _span_rec("phase_b", 12.0)]
        summary = costmodel.costs_summary(records, spec=SPEC)
        assert summary["next_target"]["phase"] == "phase_a"
        assert "phase_a" in summary["next_target_line"]

    def test_untagged_and_unspanned_programs_do_not_join(self):
        records = [_cost_rec("p.nophase", None, 100.0, 10.0),
                   _cost_rec("p.nospan", "ghost_phase", 100.0, 10.0),
                   _span_rec("unrelated", 1.0)]
        summary = costmodel.costs_summary(records, spec=SPEC)
        assert summary["table"] == []
        assert summary["next_target"] is None
        assert summary["n_programs"] == 2  # still inventoried

    def test_multi_signature_mean(self):
        records = [_cost_rec("p.multi", "h", 100.0, 10.0),
                   _cost_rec("p.multi", "h", 300.0, 30.0)]
        st = costmodel.program_stats(
            costmodel.programs_from_trace(records)["p.multi"], SPEC)
        assert st["signatures"] == 2
        assert st["flops_per_call"] == pytest.approx(200.0)
        assert st["bytes_per_call"] == pytest.approx(20.0)


class TestReportCostsCli:
    def _write_trace(self, tmp_path):
        p = str(tmp_path / "t.jsonl")
        records = [_cost_rec("p.hist", "histogram", 100.0, 10.0)]
        records += [_span_rec("histogram", 2.0)] * 4
        with open(p, "w") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")
        return p

    def test_renders_table_and_target(self, tmp_path, capsys, monkeypatch):
        monkeypatch.setenv(
            "LIGHTGBM_TPU_PEAK_SPECS",
            '{"synthetic": {"flops_per_s": 100, "hbm_bytes_per_s": 10}}')
        assert report.costs_main([self._write_trace(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "cost-model report" in out
        assert "p.hist" in out
        assert "next kernel target: histogram (p.hist)" in out
        assert "LIGHTGBM_TPU_PEAK_SPECS" in out  # env source is labeled

    def test_json_round_trip(self, tmp_path, capsys, monkeypatch):
        monkeypatch.setenv(
            "LIGHTGBM_TPU_PEAK_SPECS",
            '{"synthetic": {"flops_per_s": 100, "hbm_bytes_per_s": 10}}')
        assert report.costs_main(
            [self._write_trace(tmp_path), "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["peak_spec"]["key"] == "synthetic"
        (row,) = doc["table"]
        assert row["efficiency_pct"] == pytest.approx(50.0)
        assert doc["next_target_line"].startswith("next kernel target:")

    def test_missing_file_and_usage(self, capsys):
        assert report.costs_main(["/no/such/trace.jsonl"]) == 1
        assert report.costs_main([]) == 2

    def test_main_dispatches_costs(self, tmp_path, capsys):
        assert report.main(["costs", self._write_trace(tmp_path)]) == 0
        assert "cost-model report" in capsys.readouterr().out


class TestTraceRotation:
    def test_rotation_keeps_tail_in_order(self, tmp_path, monkeypatch):
        # ~4 KiB cap: a few hundred events force several rotations
        monkeypatch.setenv("LIGHTGBM_TPU_TRACE_MAX_MB",
                           str(4096 / (1024 * 1024)))
        path = str(tmp_path / "rot.jsonl")
        tr = Tracer()
        tr.configure(path)
        for i in range(300):
            tr.event("rot.seq", i=i)
        tr.close()

        assert os.path.exists(path + ".1")
        recs = report.load_trace(path, warn=False)
        seqs = [r["i"] for r in recs if r.get("name") == "rot.seq"]
        # older generations were clobbered, but what survives is the
        # contiguous tail, in emission order across the .1/current pair
        assert 0 < len(seqs) < 300
        assert seqs == list(range(seqs[0], 300))
        metas = [r for r in recs if r.get("ev") == "meta"]
        assert any(m.get("rotated") for m in metas)

    def test_no_cap_means_no_rotation(self, tmp_path, monkeypatch):
        monkeypatch.delenv("LIGHTGBM_TPU_TRACE_MAX_MB", raising=False)
        path = str(tmp_path / "flat.jsonl")
        tr = Tracer()
        tr.configure(path)
        for i in range(300):
            tr.event("rot.seq", i=i)
        tr.close()
        assert not os.path.exists(path + ".1")
        seqs = [r["i"] for r in report.load_trace(path, warn=False)
                if r.get("name") == "rot.seq"]
        assert seqs == list(range(300))

    def test_garbage_cap_disables_rotation(self, monkeypatch):
        from lightgbm_tpu.obs.trace import _max_bytes_from_env

        monkeypatch.setenv("LIGHTGBM_TPU_TRACE_MAX_MB", "lots")
        assert _max_bytes_from_env() == 0
        monkeypatch.setenv("LIGHTGBM_TPU_TRACE_MAX_MB", "2")
        assert _max_bytes_from_env() == 2 * 1024 * 1024


class TestBenchTrend:
    def _write_rounds(self, d):
        docs = {
            # ungated first capture, dead-tunnel fallback
            "BENCH_r1.json": {"n": 1, "rc": 0, "parsed": {
                "metric": "train.s_per_iter", "value": 2.0, "unit": "s",
                "vs_baseline": 1.0, "device": "cpu",
                "backend_fallback": True}},
            # gated and passing
            "BENCH_r2.json": {"n": 2, "rc": 0, "parsed": {
                "metric": "train.s_per_iter", "value": 1.0, "unit": "s",
                "vs_baseline": 2.0, "device": "TPU v4",
                "gate_s_per_iter": {"baseline": 2.0}}},
            # crashed round: no parsed payload
            "BENCH_r3.json": {"n": 3, "rc": 1, "parsed": None,
                              "tail": "boom"},
            # regressed on two legs
            "BENCH_r4.json": {"n": 4, "rc": 0, "parsed": {
                "metric": "train.s_per_iter", "value": 1.5, "unit": "s",
                "device": "TPU v4", "gate_s_per_iter": {"baseline": 1.0},
                "regression": True, "regression_comms_payload": True}},
        }
        for name, doc in docs.items():
            with open(os.path.join(d, name), "w") as f:
                json.dump(doc, f)

    def test_rounds_verdicts_and_best(self, tmp_path):
        d = str(tmp_path)
        self._write_rounds(d)
        # an unparsable file is skipped with a warning, not fatal
        with open(os.path.join(d, "BENCH_r0.json"), "w") as f:
            f.write("{truncated")
        rounds = report.load_bench_rounds(d)
        assert [n for n, _ in rounds] == [
            "BENCH_r1.json", "BENCH_r2.json", "BENCH_r3.json",
            "BENCH_r4.json"]
        t = report.bench_trend_summary(rounds)
        r1, r2, r3, r4 = t["rounds"]
        assert r1["gate_verdict"] == "-" and r1["backend_fallback"]
        assert r2["gate_verdict"] == "pass"
        assert r3["parsed"] is False and r3["rc"] == 1
        assert r4["gate_verdict"] == "FAIL:s_per_iter,comms_payload"
        trend = t["by_metric"]["train.s_per_iter"]
        assert trend["first"]["round"] == "r1"
        assert trend["last"]["round"] == "r4"
        assert trend["best"]["round"] == "r2"

    def test_render_and_cli_json(self, tmp_path, capsys):
        d = str(tmp_path)
        self._write_rounds(d)
        assert report.bench_trend_main([d]) == 0
        out = capsys.readouterr().out
        assert "bench trend" in out
        assert "[fallback]" in out
        assert "trend [train.s_per_iter]" in out
        assert "best r2" in out
        assert report.main(["bench-trend", d, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert len(doc["rounds"]) == 4

    def test_empty_dir_fails_cleanly(self, tmp_path, capsys):
        assert report.bench_trend_main([str(tmp_path / "empty")]) == 1


class TestXprofHarness:
    def test_env_gate(self, monkeypatch):
        from lightgbm_tpu.utils.profiling import maybe_xprof_capture

        monkeypatch.delenv("LIGHTGBM_TPU_XPROF", raising=False)
        assert maybe_xprof_capture() is None
        monkeypatch.setenv("LIGHTGBM_TPU_XPROF", "/tmp/xp")
        monkeypatch.setenv("LIGHTGBM_TPU_XPROF_ITERS", "2")
        monkeypatch.setenv("LIGHTGBM_TPU_XPROF_SKIP", "3")
        cap = maybe_xprof_capture()
        assert cap is not None and cap.log_dir == "/tmp/xp"
        assert cap.iters == 2 and cap.skip == 3

    def test_skip_window_defers_start(self, tmp_path):
        from lightgbm_tpu.utils.profiling import XprofCapture

        cap = XprofCapture(str(tmp_path / "xp"), skip=2, iters=1)
        cap.on_iter_start()
        assert not cap._active  # still inside the skip window
        cap.on_iter_end()
        cap.on_iter_start()
        assert not cap._active
        cap.on_iter_end()
        # close with nothing in flight is a no-op
        cap.close()
        assert not cap._done

    def test_capture_writes_loadable_xplane(self, tmp_path, global_trace):
        import jax.numpy as jnp

        from lightgbm_tpu.obs import tracer
        from lightgbm_tpu.utils.profiling import XprofCapture

        tracer.refresh_from_env()
        d = str(tmp_path / "xprof")
        cap = XprofCapture(d, skip=0, iters=1)
        cap.on_iter_start()
        assert cap._active
        jnp.ones((64, 64)).sum().block_until_ready()
        cap.on_iter_end()
        assert cap._done and not cap._active
        cap.close()  # idempotent after a completed window

        planes = list(pathlib.Path(d).rglob("*.xplane.pb"))
        assert planes, f"no xplane under {d}: {list(pathlib.Path(d).rglob('*'))}"
        assert planes[0].stat().st_size > 0

        tracer.close()
        evs = [r for r in _read(global_trace)
               if r.get("ev") == "event" and r.get("name") == "xprof.capture"]
        assert len(evs) == 1
        assert evs[0]["iters"] == 1 and evs[0]["dir"] == d
