"""Wide-data distributed learners (parallel/hostlearner.py): in-process
LocalComm rank simulations pin the two bit-parity contracts —
feature-parallel == serial, voting(2k >= F) == data-parallel — plus the
PV-Tree payload collapse and the config surface.  The real-subprocess
byte-identity and kill matrices live in test_multihost_wide.py /
test_net_fault.py."""

import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from lightgbm_tpu.ops.grow import GrowParams, grow_tree  # noqa: E402
from lightgbm_tpu.ops.split import FeatureMeta, SplitHyper  # noqa: E402
from lightgbm_tpu.parallel import (  # noqa: E402
    HostParallelLearner,
    LocalGroup,
)


def _meta(f, B):
    return FeatureMeta(jnp.full((f,), B, jnp.int32),
                       jnp.zeros((f,), jnp.int32),
                       jnp.zeros((f,), bool))


def _hyper(min_data=20.0):
    return SplitHyper(jnp.float32(0.0), jnp.float32(0.1),
                      jnp.float32(min_data), jnp.float32(1e-3),
                      jnp.float32(0.0))


def _run_group(mode, params, shards, meta, hyper, fmask):
    """Grow one tree on every simulated rank; returns (results, ledgers).
    ``shards`` = per-rank (bins, grad, hess) numpy triples."""
    nproc = len(shards)
    grp = LocalGroup(nproc)
    out = [None] * nproc
    errs = []

    def worker(r, comm):
        try:
            b, g, h = shards[r]
            n = b.shape[0]
            learner = HostParallelLearner(mode, comm, params)
            gr = learner.grow(
                jnp.asarray(b), jnp.asarray(g), jnp.asarray(h),
                jnp.ones((n,), jnp.float32), fmask, meta, hyper)
            out[r] = (jax.tree_util.tree_map(np.asarray, gr), comm.ledger)
        except BaseException as e:  # surface worker failures to pytest
            errs.append((r, e))

    ts = [threading.Thread(target=worker, args=(r, c))
          for r, c in enumerate(grp.comms())]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    if errs:
        raise errs[0][1]
    return out


def _assert_same_tree(a, b, skip=()):
    for name, x, y in zip(a._fields, a, b):
        if name in skip:
            continue
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=f"field {name}")


@pytest.fixture(scope="module")
def small():
    rng = np.random.default_rng(7)
    n, f, B = 2000, 41, 16
    bins = rng.integers(0, B, size=(n, f)).astype(np.uint8)
    grad = rng.normal(size=n).astype(np.float32)
    hess = np.ones(n, np.float32)
    return n, f, B, bins, grad, hess


class TestFeatureParallelSerialParity:
    @pytest.mark.parametrize("nproc", [1, 2, 4])
    def test_bitwise_equals_serial(self, small, nproc):
        n, f, B, bins, grad, hess = small
        params = GrowParams(num_leaves=15, num_bins=B)
        meta, hyper = _meta(f, B), _hyper()
        fmask = jnp.ones((f,), jnp.float32)
        ref = jax.tree_util.tree_map(np.asarray, grow_tree(
            jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.ones((n,), jnp.float32), fmask, meta, hyper, params))
        assert int(ref.num_splits) > 3
        # rows replicated on every rank; columns sharded inside
        res = _run_group("feature", params, [(bins, grad, hess)] * nproc,
                         meta, hyper, fmask)
        for gr, _ in res:
            _assert_same_tree(ref, gr)

    def test_more_ranks_than_column_blocks(self, small):
        # f=41, nproc=6 -> per=7 columns/rank, rank 5 owns none: it must
        # still stay in collective lockstep and produce the same tree
        n, f, B, bins, grad, hess = small
        params = GrowParams(num_leaves=7, num_bins=B)
        meta, hyper = _meta(f, B), _hyper()
        fmask = jnp.ones((f,), jnp.float32)
        ref = jax.tree_util.tree_map(np.asarray, grow_tree(
            jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.ones((n,), jnp.float32), fmask, meta, hyper, params))
        res = _run_group("feature", params, [(bins, grad, hess)] * 6,
                         meta, hyper, fmask)
        for gr, _ in res:
            _assert_same_tree(ref, gr)

    def test_payload_is_tiny_records_only(self, small):
        n, f, B, bins, grad, hess = small
        params = GrowParams(num_leaves=15, num_bins=B)
        res = _run_group("feature", params, [(bins, grad, hess)] * 2,
                         _meta(f, B), _hyper(), jnp.ones((f,), jnp.float32))
        ledger = res[0][1]
        # no histogram bytes ever cross ranks in feature mode
        assert "hist" not in ledger and "vote" not in ledger
        assert ledger["best_split"] > 0


class TestVotingDataParity:
    @pytest.mark.parametrize("nproc", [2, 3])
    def test_full_vote_bitwise_equals_data(self, small, nproc):
        n, f, B, bins, grad, hess = small
        params = GrowParams(num_leaves=15, num_bins=B, top_k=f)  # 2k >= F
        meta, hyper = _meta(f, B), _hyper()
        fmask = jnp.ones((f,), jnp.float32)
        cuts = np.linspace(0, n, nproc + 1).astype(int)
        shards = [(bins[cuts[r]:cuts[r + 1]], grad[cuts[r]:cuts[r + 1]],
                   hess[cuts[r]:cuts[r + 1]]) for r in range(nproc)]
        data = _run_group("data", params, shards, meta, hyper, fmask)
        vote = _run_group("voting", params, shards, meta, hyper, fmask)
        for (gd, _), (gv, _) in zip(data, vote):
            _assert_same_tree(gd, gv)
        assert int(data[0][0].num_splits) > 3

    def test_ranks_agree_with_each_other(self, small):
        n, f, B, bins, grad, hess = small
        params = GrowParams(num_leaves=15, num_bins=B, top_k=5)
        shards = [(bins[:1000], grad[:1000], hess[:1000]),
                  (bins[1000:], grad[1000:], hess[1000:])]
        res = _run_group("voting", params, shards, _meta(f, B), _hyper(),
                         jnp.ones((f,), jnp.float32))
        # leaf_id maps each LOCAL row to its leaf, so it differs per shard;
        # the tree structure itself must be identical on every rank
        _assert_same_tree(res[0][0], res[1][0], skip=("leaf_id",))


class TestWideVoting:
    """2000-feature synthetic: the workload class PV-Tree exists for."""

    @pytest.fixture(scope="class")
    def wide(self):
        rng = np.random.default_rng(3)
        n, f, B = 2400, 2000, 16
        bins = rng.integers(0, B, size=(n, f)).astype(np.uint8)
        # a handful of signal columns among 2000 noise columns
        signal = bins[:, :5].astype(np.float32)
        grad = (signal @ np.array([1.0, -0.8, 0.6, -0.4, 0.3],
                                  np.float32) / B
                + 0.05 * rng.normal(size=n)).astype(np.float32)
        hess = np.ones(n, np.float32)
        cut = n // 2
        shards = [(bins[:cut], grad[:cut], hess[:cut]),
                  (bins[cut:], grad[cut:], hess[cut:])]
        # small row_block: the one-hot histogram tile is
        # row_block x (F*B) f32 — 4096 rows x 32k cols would be 524 MB
        params = GrowParams(num_leaves=7, num_bins=B, row_block=256)
        return f, B, shards, params

    def test_small_k_within_accuracy_tolerance(self, wide):
        f, B, shards, params = wide
        meta, hyper = _meta(f, B), _hyper()
        fmask = jnp.ones((f,), jnp.float32)
        data = _run_group("data", params, shards, meta, hyper, fmask)
        vote = _run_group("voting", params._replace(top_k=20), shards,
                          meta, hyper, fmask)
        gd, gv = data[0][0], vote[0][0]
        assert int(gv.num_splits) > 0
        # the elected top-2k features retain nearly all the split gain
        gain_d = float(np.sum(gd.rec_gain))
        gain_v = float(np.sum(gv.rec_gain))
        assert gain_v >= 0.9 * gain_d, (gain_v, gain_d)

    def test_payload_collapse_at_least_5x(self, wide):
        f, B, shards, params = wide
        meta, hyper = _meta(f, B), _hyper()
        fmask = jnp.ones((f,), jnp.float32)
        data = _run_group("data", params, shards, meta, hyper, fmask)
        vote = _run_group("voting", params._replace(top_k=20), shards,
                          meta, hyper, fmask)
        d_hist = data[0][1]["hist"]
        v_hist = vote[0][1]["hist"]
        # the ISSUE contract: voting cuts the histogram allreduce payload
        # >= 5x vs data-parallel on >= 2000 features (here F/2k = 50x)
        assert v_hist * 5 <= d_hist, (v_hist, d_hist)
        v_total = sum(vote[0][1].values())
        d_total = sum(data[0][1].values())
        assert v_total * 5 <= d_total, (v_total, d_total)


class TestConfigSurface:
    def test_aliases_resolve(self):
        from lightgbm_tpu.config import Config

        cfg = Config.from_params({"tree_learner_type": "voting", "topk": 7})
        assert cfg.tree_learner == "voting" and cfg.top_k == 7
        cfg = Config.from_params({"tree_type": "feature"})
        assert cfg.tree_learner == "feature"

    def test_bad_learner_value_is_fatal(self):
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.utils.log import LightGBMError

        with pytest.raises(LightGBMError, match="tree_learner"):
            Config.from_params({"tree_learner": "exclusive"})

    def test_voting_with_forced_ooc_is_fatal(self):
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.utils.log import LightGBMError

        with pytest.raises(LightGBMError, match="out_of_core"):
            Config.from_params({"tree_learner": "voting",
                                "out_of_core": "true"})
        # auto stays allowed: the router resolves it
        cfg = Config.from_params({"tree_learner": "voting"})
        assert cfg.tree_learner == "voting"

    def test_top_k_must_be_positive(self):
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.utils.log import LightGBMError

        with pytest.raises(LightGBMError, match="top_k"):
            Config.from_params({"top_k": 0})

    def test_single_device_feature_downgrades_to_serial(self):
        # one visible device: tree_learner=feature must warn + train
        # serial rather than fail
        import lightgbm_tpu as lgb

        if len(jax.devices()) != 1:
            pytest.skip("needs a single-device runtime")
        rng = np.random.default_rng(0)
        X = rng.normal(size=(400, 8)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        p = dict(objective="binary", tree_learner="feature", num_leaves=7,
                 min_data_in_leaf=5, verbose=-1)
        bst = lgb.train(p, lgb.Dataset(X, label=y, params=dict(p)), 2,
                        verbose_eval=False)
        assert bst.num_trees == 2
