"""End-to-end engine tests — modeled on the reference's
tests/python_package_test/test_engine.py (:33-300): per-task metric
thresholds on the checked-in example datasets, early stopping, continued
training, cv, pickling.
"""

import os
import pickle

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _load(path):
    d = np.loadtxt(path)
    return d[:, 1:], d[:, 0]


@pytest.fixture(scope="module")
def regression_data(reference_examples):
    X, y = _load(f"{reference_examples}/regression/regression.train")
    Xt, yt = _load(f"{reference_examples}/regression/regression.test")
    return X, y, Xt, yt


@pytest.fixture(scope="module")
def binary_data():
    """The reference's test_binary setup (test_engine.py:32-35):
    breast_cancer with a 10% holdout."""
    from sklearn.datasets import load_breast_cancer
    from sklearn.model_selection import train_test_split

    X, y = load_breast_cancer(return_X_y=True)
    X, Xt, y, yt = train_test_split(X, y, test_size=0.1, random_state=42)
    return X, y, Xt, yt


@pytest.fixture(scope="module")
def binary_example_data(reference_examples):
    """The checked-in examples/binary_classification fixtures (a harder,
    Higgs-like dataset used by the reference's CLI tests)."""
    X, y = _load(f"{reference_examples}/binary_classification/binary.train")
    Xt, yt = _load(f"{reference_examples}/binary_classification/binary.test")
    return X, y, Xt, yt


def test_regression(regression_data):
    """MSE threshold from reference test_engine.py:60 (< 16)."""
    X, y, Xt, yt = regression_data
    params = {"objective": "regression", "metric": "l2", "verbose": -1}
    ds = lgb.Dataset(X, label=y)
    evals_result = {}
    bst = lgb.train(
        params, ds, num_boost_round=50,
        valid_sets=[lgb.Dataset(Xt, label=yt, reference=ds)],
        evals_result=evals_result, verbose_eval=False,
    )
    pred = bst.predict(Xt)
    mse = float(np.mean((pred - yt) ** 2))
    assert mse < 16
    assert abs(evals_result["valid_0"]["l2"][-1] - mse) < 1e-5


def test_binary(binary_data):
    """Logloss threshold from reference test_engine.py:33-50 (< 0.15)."""
    X, y, Xt, yt = binary_data
    params = {"objective": "binary", "metric": "binary_logloss", "verbose": -1}
    ds = lgb.Dataset(X, label=y)
    evals_result = {}
    bst = lgb.train(
        params, ds, num_boost_round=50,
        valid_sets=[lgb.Dataset(Xt, label=yt, reference=ds)],
        evals_result=evals_result, verbose_eval=False,
    )
    prob = bst.predict(Xt)
    logloss = -np.mean(yt * np.log(np.maximum(prob, 1e-15))
                       + (1 - yt) * np.log(np.maximum(1 - prob, 1e-15)))
    assert logloss < 0.15
    assert abs(evals_result["valid_0"]["binary_logloss"][-1] - logloss) < 1e-5


def test_binary_example_quality(binary_example_data):
    """On the harder examples data our quality must match sklearn's
    HistGradientBoosting at identical hyperparameters (~0.512 logloss)."""
    X, y, Xt, yt = binary_example_data
    params = {"objective": "binary", "metric": "binary_logloss", "verbose": -1}
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(params, ds, num_boost_round=50, verbose_eval=False)
    prob = bst.predict(Xt)
    logloss = -np.mean(yt * np.log(np.maximum(prob, 1e-15))
                       + (1 - yt) * np.log(np.maximum(1 - prob, 1e-15)))
    # sklearn HistGradientBoosting reaches ~0.512 at these params; a
    # quality bug > ~1.5% now fails instead of hiding under a loose band
    assert logloss < 0.52


def test_binary_auc(binary_example_data):
    X, y, Xt, yt = binary_example_data
    params = {"objective": "binary", "metric": "auc", "verbose": -1}
    ds = lgb.Dataset(X, label=y)
    evals_result = {}
    bst = lgb.train(params, ds, num_boost_round=50,
                    valid_sets=[lgb.Dataset(Xt, label=yt, reference=ds)],
                    evals_result=evals_result, verbose_eval=False)
    auc = evals_result["valid_0"]["auc"][-1]
    assert auc > 0.80
    # sklearn cross-check of the AUC implementation (ties + weights path)
    from sklearn.metrics import roc_auc_score

    prob = bst.predict(Xt)
    m = lgb.metric.AUCMetric(lgb.config.Config())
    ds_t = lgb.Dataset(Xt, label=yt).construct()
    m.init(ds_t.metadata, ds_t.num_data)
    ours = m.eval(prob)[0][1]
    theirs = roc_auc_score(yt, prob)
    assert abs(ours - theirs) < 1e-10


def test_multiclass():
    """Reference test_engine.py:71-90 multiclass: digits, 10% holdout,
    50 rounds, multi_logloss < 0.2."""
    from sklearn.datasets import load_digits
    from sklearn.model_selection import train_test_split

    X, y = load_digits(return_X_y=True)
    X, Xt, y, yt = train_test_split(X, y, test_size=0.1, random_state=42)
    params = {
        "objective": "multiclass", "num_class": 10,
        "metric": "multi_logloss", "verbose": -1,
    }
    ds = lgb.Dataset(X, label=y)
    evals_result = {}
    bst = lgb.train(
        params, ds, num_boost_round=50,
        valid_sets=[lgb.Dataset(Xt, label=yt, reference=ds)],
        evals_result=evals_result, verbose_eval=False,
    )
    pred = bst.predict(Xt)
    assert pred.shape == (len(yt), 10)
    acc = np.mean(np.argmax(pred, axis=1) == yt)
    assert acc > 0.9
    assert evals_result["valid_0"]["multi_logloss"][-1] < 0.2


def test_lambdarank(reference_examples):
    """Reference test_sklearn.py:55 lambdarank on examples data (LibSVM
    format, loaded through the parser)."""
    from lightgbm_tpu.io.parser import _load_libsvm

    X, y = _load_libsvm(f"{reference_examples}/lambdarank/rank.train")
    group = np.loadtxt(f"{reference_examples}/lambdarank/rank.train.query")
    Xt, yt = _load_libsvm(f"{reference_examples}/lambdarank/rank.test")
    gt = np.loadtxt(f"{reference_examples}/lambdarank/rank.test.query")
    if Xt.shape[1] < X.shape[1]:
        Xt = np.hstack([Xt, np.zeros((Xt.shape[0], X.shape[1] - Xt.shape[1]))])
    params = {"objective": "lambdarank", "metric": "ndcg",
              "ndcg_eval_at": [1, 3], "verbose": -1}
    ds = lgb.Dataset(X, label=y, group=group)
    evals_result = {}
    lgb.train(params, ds, num_boost_round=30,
              valid_sets=[lgb.Dataset(Xt, label=yt, group=gt, reference=ds)],
              evals_result=evals_result, verbose_eval=False)
    ndcg1 = evals_result["valid_0"]["ndcg@1"][-1]
    assert ndcg1 > 0.56  # reference sklearn test asserts > 0.5644


def test_early_stopping(binary_data):
    X, y, Xt, yt = binary_data
    params = {"objective": "binary", "metric": "binary_logloss", "verbose": -1}
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(
        params, ds, num_boost_round=200,
        valid_sets=[lgb.Dataset(Xt, label=yt, reference=ds)],
        early_stopping_rounds=5, verbose_eval=False,
    )
    assert bst.best_iteration > 0
    assert bst.best_iteration <= 200


def test_fused_chunked_eval_path(binary_data, monkeypatch):
    """engine.train's fused-chunks-between-eval-points path (taken when
    output_freq > 1 and the partitioned trainer is active): must produce
    the same model quality as the per-iteration loop and honor early
    stopping at chunk boundaries."""
    monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
    X, y, Xt, yt = binary_data
    params = {"objective": "binary", "metric": "binary_logloss",
              "output_freq": 8, "verbose": -1}
    ds = lgb.Dataset(X, label=y)
    evals = {}
    bst = lgb.train(
        params, ds, num_boost_round=32,
        valid_sets=[lgb.Dataset(Xt, label=yt, reference=ds)],
        early_stopping_rounds=16, verbose_eval=False, evals_result=evals,
    )
    assert bst.boosting.ptrainer is not None  # fused trainer engaged
    # eval happened at chunk boundaries only
    n_evals = len(evals["valid_0"]["binary_logloss"])
    assert 1 <= n_evals <= 4
    # quality matches the classic per-iteration path at the same budget
    monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "0")
    ref = lgb.train(dict(params, output_freq=1), lgb.Dataset(X, label=y),
                    num_boost_round=bst.current_iteration(),
                    verbose_eval=False)
    from sklearn.metrics import log_loss
    ll_fused = log_loss(yt, bst.predict(Xt))
    ll_ref = log_loss(yt, ref.predict(Xt))
    assert ll_fused == pytest.approx(ll_ref, rel=0.15, abs=0.02)


def test_pandas_categorical_auto_detection():
    """DataFrame ``category`` dtype columns become categorical features
    under categorical_feature="auto" (reference python-package pandas
    handling), survive model round-trips, and map predict-time category
    orders through the training levels."""
    pd = pytest.importorskip("pandas")
    rng = np.random.default_rng(11)
    n = 1200
    cats = np.array(["red", "green", "blue", "teal"])
    cat_col = cats[rng.integers(0, 4, n)]
    x1 = rng.standard_normal(n)
    # the categorical column carries most of the signal
    y = ((cat_col == "green") | (cat_col == "teal")).astype(float)
    y = np.where(rng.random(n) < 0.05, 1 - y, y)
    df = pd.DataFrame({"c": pd.Categorical(cat_col), "x1": x1})
    params = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 20,
              "verbose": -1}
    bst = lgb.train(params, lgb.Dataset(df, label=y), num_boost_round=10,
                    verbose_eval=False)

    def find_types(node, acc):
        if "split_feature" in node:
            acc.append((node["split_feature"], node["decision_type"]))
            find_types(node["left_child"], acc)
            find_types(node["right_child"], acc)

    splits = []
    for t in bst.dump_model()["tree_info"]:
        find_types(t["tree_structure"], splits)
    assert any(f == 0 and d == "==" for f, d in splits), splits

    pred = bst.predict(df)
    auc = _auc_of(y, pred)
    assert auc > 0.95

    # predict through a DataFrame whose category ORDER differs: codes
    # must be remapped through the training levels, not taken verbatim
    df2 = df.copy()
    df2["c"] = pd.Categorical(cat_col, categories=["teal", "blue", "red", "green"])
    np.testing.assert_allclose(bst.predict(df2), pred, rtol=1e-6)

    # pandas_categorical survives the model string round-trip
    bst2 = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(bst2.predict(df2), pred, rtol=1e-6)


def _auc_of(y, s):
    from sklearn.metrics import roc_auc_score

    return roc_auc_score(y, s)


def test_save_load_predict_roundtrip(regression_data, tmp_path):
    X, y, Xt, yt = regression_data
    params = {"objective": "regression", "verbose": -1}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=20,
                    verbose_eval=False)
    pred = bst.predict(Xt)
    path = str(tmp_path / "model.txt")
    bst.save_model(path)
    bst2 = lgb.Booster(model_file=path)
    pred2 = bst2.predict(Xt)
    np.testing.assert_allclose(pred, pred2, rtol=1e-6)
    # JSON dump is well-formed
    dumped = bst.dump_model()
    assert dumped["num_class"] == 1
    assert len(dumped["tree_info"]) == bst.num_trees


def test_pickle_roundtrip(regression_data):
    X, y, Xt, yt = regression_data
    bst = lgb.train({"objective": "regression", "verbose": -1},
                    lgb.Dataset(X, label=y), num_boost_round=10,
                    verbose_eval=False)
    blob = pickle.dumps(bst)
    bst2 = pickle.loads(blob)
    np.testing.assert_allclose(bst.predict(Xt), bst2.predict(Xt), rtol=1e-6)


def test_continued_training(regression_data):
    X, y, Xt, yt = regression_data
    params = {"objective": "regression", "metric": "l2", "verbose": -1}
    bst1 = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=10,
                     verbose_eval=False)
    mse1 = float(np.mean((bst1.predict(Xt) - yt) ** 2))
    bst2 = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=10,
                     init_model=bst1, verbose_eval=False)
    mse2 = float(np.mean((bst2.predict(Xt) - yt) ** 2))
    assert mse2 < mse1
    assert bst2.num_trees > bst1.num_trees


def test_bagging_and_feature_fraction(binary_data):
    X, y, Xt, yt = binary_data
    params = {
        "objective": "binary", "metric": "binary_logloss", "verbose": -1,
        "bagging_fraction": 0.7, "bagging_freq": 1, "feature_fraction": 0.8,
        "bagging_seed": 3,
    }
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(params, ds, num_boost_round=30, verbose_eval=False)
    prob = bst.predict(Xt)
    logloss = -np.mean(yt * np.log(np.maximum(prob, 1e-15))
                       + (1 - yt) * np.log(np.maximum(1 - prob, 1e-15)))
    assert logloss < 0.25
    # seeded determinism
    bst2 = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=30,
                     verbose_eval=False)
    np.testing.assert_allclose(prob, bst2.predict(Xt), rtol=1e-6)


def test_dart(binary_data):
    X, y, Xt, yt = binary_data
    params = {"objective": "binary", "boosting_type": "dart", "verbose": -1,
              "drop_rate": 0.1}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=30,
                    verbose_eval=False)
    prob = bst.predict(Xt)
    err = np.mean((prob > 0.5) != yt)
    assert err < 0.1


def test_goss(binary_data):
    X, y, Xt, yt = binary_data
    params = {"objective": "binary", "boosting_type": "goss", "verbose": -1,
              "top_rate": 0.2, "other_rate": 0.1}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=30,
                    verbose_eval=False)
    prob = bst.predict(Xt)
    err = np.mean((prob > 0.5) != yt)
    assert err < 0.1


def test_cv(regression_data):
    X, y, _, _ = regression_data
    res = lgb.cv({"objective": "regression", "metric": "l2", "verbose": -1},
                 lgb.Dataset(X, label=y), num_boost_round=10, nfold=3, seed=42)
    assert "l2-mean" in res
    assert len(res["l2-mean"]) == 10
    assert res["l2-mean"][-1] < res["l2-mean"][0]


def test_custom_objective(regression_data):
    X, y, Xt, yt = regression_data

    def l2_obj(preds, dataset):
        grad = preds - dataset.get_label()
        hess = np.ones_like(grad)
        return grad, hess

    params = {"objective": "none", "verbose": -1, "boost_from_average": False}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=30,
                    fobj=l2_obj, verbose_eval=False)
    mse = float(np.mean((bst.predict(Xt, raw_score=True) - yt) ** 2))
    assert mse < 16


def test_weighted_training(binary_example_data, reference_examples):
    X, y, Xt, yt = binary_example_data
    w = np.loadtxt(f"{reference_examples}/binary_classification/binary.train.weight")
    ds = lgb.Dataset(X, label=y, weight=w)
    bst = lgb.train({"objective": "binary", "verbose": -1}, ds,
                    num_boost_round=20, verbose_eval=False)
    prob = bst.predict(Xt)
    err = np.mean((prob > 0.5) != yt)
    assert err < 0.35


def test_valid_dataset_categorical_remap():
    """A validation Dataset whose pandas category LEVEL ORDER differs
    from the training frame must be remapped through the training
    pandas_categorical when reference= is set (ADVICE r5 medium) — and a
    categorical column-count mismatch must raise like the reference."""
    pd = pytest.importorskip("pandas")
    rng = np.random.default_rng(5)
    n = 1200
    cats = np.array(["red", "green", "blue", "teal"])
    cat_col = cats[rng.integers(0, 4, n)]
    x1 = rng.standard_normal(n)
    y = ((cat_col == "green") | (cat_col == "teal")).astype(float)
    df = pd.DataFrame({"c": pd.Categorical(cat_col), "x1": x1})
    params = {"objective": "binary", "metric": "binary_logloss",
              "num_leaves": 7, "min_data_in_leaf": 20, "verbose": -1}
    ds = lgb.Dataset(df, label=y, params=dict(params))

    # same rows, SHUFFLED level order: identical data, so eval on the
    # valid set must match eval on train exactly after the remap
    df2 = pd.DataFrame(
        {"c": pd.Categorical(cat_col, categories=["teal", "blue", "red", "green"]),
         "x1": x1})
    evals = {}
    bst = lgb.train(params, ds, num_boost_round=10,
                    valid_sets=[lgb.Dataset(df2, label=y, reference=ds)],
                    valid_names=["shuffled"],
                    evals_result=evals, verbose_eval=False)
    # identical rows -> the remapped valid logloss must equal the logloss
    # of the model's own (remap-verified) predictions on the train frame
    prob = np.clip(bst.predict(df), 1e-15, 1 - 1e-15)
    ll = float(-np.mean(y * np.log(prob) + (1 - y) * np.log(1 - prob)))
    assert evals["shuffled"]["binary_logloss"][-1] == pytest.approx(
        ll, rel=1e-5)
    # unseen valid-only level maps to missing, not to a wrong bin
    df3 = df2.copy()
    df3["c"] = pd.Categorical(cat_col, categories=list(cats) + ["mauve"])
    bst.predict(df3.iloc[:10])

    # categorical column-count mismatch raises (reference behavior)
    df_nocat = pd.DataFrame({"c": np.arange(n, dtype=float), "x1": x1})
    bad = lgb.Dataset(df_nocat, label=y, reference=ds)
    with pytest.raises(lgb.LightGBMError, match="do not match"):
        bad.construct()


def test_model_file_crlf_pandas_categorical(tmp_path):
    """_strip_pandas_categorical span arithmetic: a model file with CRLF
    line endings (or trailing whitespace on the pandas_categorical line)
    must load without corrupting the model body (ADVICE r5 low)."""
    pd = pytest.importorskip("pandas")
    rng = np.random.default_rng(7)
    n = 600
    cat_col = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    df = pd.DataFrame({"c": pd.Categorical(cat_col),
                       "x": rng.standard_normal(n)})
    y = (cat_col == "b").astype(float)
    bst = lgb.train({"objective": "binary", "num_leaves": 5, "verbose": -1},
                    lgb.Dataset(df, label=y), num_boost_round=3,
                    verbose_eval=False)
    ref_pred = bst.predict(df)
    s = bst.model_to_string()
    assert "pandas_categorical:" in s
    crlf = tmp_path / "model_crlf.txt"
    crlf.write_bytes(s.replace("\n", "\r\n").encode())
    loaded = lgb.Booster(model_file=str(crlf))
    assert loaded.pandas_categorical == bst.pandas_categorical
    np.testing.assert_allclose(loaded.predict(df), ref_pred, rtol=1e-6)
