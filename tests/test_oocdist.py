"""Distributed out-of-core training tests (boosting/oocdist.py,
data/chunksource.py — docs/PARALLEL.md mode matrix, docs/DATA.md
"Distributed streaming").

The acceptance contract: a multi-rank subprocess world where every rank
streams its OWN row shard through the prefetch ring trains successfully
past each rank's device budget, and with ``quantized_training`` on the
final model is BYTE-IDENTICAL across per-rank chunk grids and across
world sizes (integer chunk folds are associative — PR 14's wire plus
PR 8's streaming compose with zero exactness caveats).  A preempted
4-rank fleet resumes from the canonical checkpoint at worlds 4 AND 2:
the per-rank ``dist/`` chunk-schedule fingerprint is exempt from the
serial grid-refusal, while the global dataset fingerprint still gates.

Subprocess fleets reuse the elastic harness pattern of
test_ckpt_fault.py with tests/oocdist_worker.py (world-invariant data
recipe, contiguous pre-partitioned shards, whole-job SIGKILL
preemption).
"""

import json
import os
import shutil
import signal
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "oocdist_worker.py")

pytestmark = pytest.mark.oocdist


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _spawn_fleet(tag, world, ckdir="-", extra_env=None):
    """Start one world-``world`` phase of the oocdist worker; returns
    (out-prefix, procs) without waiting."""
    port = _free_port()
    base = {k: v for k, v in os.environ.items()
            if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "LIGHTGBM_TPU_FAULT",
                         "LIGHTGBM_TPU_FAULT_RANK", "LIGHTGBM_TPU_TRACE",
                         "LIGHTGBM_TPU_AUDIT", "LIGHTGBM_TPU_OOC",
                         "LIGHTGBM_TPU_DEVICE_BUDGET")}
    base["PYTHONPATH"] = REPO + os.pathsep + base.get("PYTHONPATH", "")
    base.update(extra_env or {})
    procs = []
    for r in range(world):
        procs.append(subprocess.Popen(
            [sys.executable, WORKER, str(r), str(world), str(port), tag,
             "train", ckdir],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=dict(base)))
    return tag, procs


def _join_fleet(procs, timeout=600):
    return [p.communicate(timeout=timeout)[0] for p in procs]


def _result(out, rank):
    with open(out + f".rank{rank}.json") as fh:
        return json.load(fh)


def _model(out, rank):
    with open(out + f".rank{rank}.txt") as fh:
        return fh.read()


def _assert_clean(procs, logs):
    assert all(p.returncode == 0 for p in procs), \
        "\n".join(l[-4000:] for l in logs)


# ======================================================================
# tier-1 smoke: 2 ranks, device budget forced below each rank's shard
# ======================================================================
def test_two_rank_budget_smoke(tmp_path):
    """A 2-rank world whose per-rank packed shard exceeds a forced
    device budget auto-routes to the distributed streaming learner and
    both ranks agree on the model bytes."""
    out, procs = _spawn_fleet(
        str(tmp_path / "smoke"), 2,
        extra_env={"OOCDIST_ROWS": "2048", "OOCDIST_TREES": "3",
                   "OOCDIST_OOC": "auto", "OOCDIST_QUANT": "1",
                   "OOCDIST_LEAVES": "7",
                   # 1024 rows/rank * 10 features * 1 B packed = 10240 B
                   "LIGHTGBM_TPU_DEVICE_BUDGET": "4096"})
    logs = _join_fleet(procs)
    _assert_clean(procs, logs)
    r0, r1 = _result(out, 0), _result(out, 1)
    assert r0["ooc"] and r1["ooc"]
    assert r0["learner"] == "DistributedOocTrainer"
    assert r0["schedule"].startswith("dist/2w/r0/")
    assert r1["schedule"].startswith("dist/2w/r1/")
    assert _model(out, 0) == _model(out, 1)
    assert r0["trees"] == 3


# ======================================================================
# the quantized byte-identity matrix: chunk grids x world sizes
# ======================================================================
def test_quantized_grid_world_parity(tmp_path):
    """With quantized_training on, integer chunk folds are associative:
    the model bytes are identical across per-rank chunk grids
    {1000, 2048, 9999} AND across 2-vs-4 rank worlds.  16384 global
    rows make the grids genuinely different plans at world 2 (1000 and
    2048 round up to one 4096-row block grid = 2 chunks/rank; 9999
    rounds to 12288 = 1 chunk/rank)."""
    env = {"OOCDIST_ROWS": "16384", "OOCDIST_TREES": "3",
           "OOCDIST_OOC": "true", "OOCDIST_QUANT": "1",
           "OOCDIST_LEAVES": "7"}
    fleets = []
    for world, grid in ((2, 1000), (2, 2048), (2, 9999), (4, 2048)):
        fleets.append((world, grid) + _spawn_fleet(
            str(tmp_path / f"w{world}g{grid}"), world,
            extra_env=dict(env, OOCDIST_CHUNK_ROWS=str(grid))))
    models = {}
    for world, grid, out, procs in fleets:
        logs = _join_fleet(procs)
        _assert_clean(procs, logs)
        m = _model(out, 0)
        assert all(_model(out, r) == m for r in range(world))
        models[(world, grid)] = m
        # the grids must be real: 2048 -> 2 chunks/rank at world 2,
        # 9999 -> 1 (both stream, the plans differ)
        chunks = _result(out, 0)["chunks_per_pass"]
        if world == 2:
            assert chunks == (1 if grid == 9999 else 2)
    ref = models[(2, 1000)]
    assert all(m == ref for m in models.values()), \
        "quantized model bytes diverged across chunk grids/world sizes"


# ======================================================================
# elastic resume: preempted 4-rank fleet resumes at worlds 4 and 2
# ======================================================================
@pytest.mark.faultinject
@pytest.mark.netfault
def test_elastic_resume_worlds(tmp_path):
    """A 4-rank streaming fleet SIGKILLed mid-run resumes from the
    canonical checkpoint at world 4 AND world 2 — the resumed world-2
    ranks stream a DIFFERENT per-rank grid (8192 rows/rank = 2 chunks
    vs the checkpoint's 1), which the ``dist/`` schedule exemption
    admits — and both final models are byte-identical to an unkilled
    reference (quantized folds are associative; the rounding counter is
    re-anchored on restore)."""
    env = {"OOCDIST_ROWS": "16384", "OOCDIST_TREES": "6",
           "OOCDIST_FREQ": "2", "OOCDIST_OOC": "true",
           "OOCDIST_QUANT": "1", "OOCDIST_LEAVES": "7",
           "OOCDIST_CHUNK_ROWS": "1000"}
    ck = str(tmp_path / "ck")
    ref_out, ref_procs = _spawn_fleet(
        str(tmp_path / "ref"), 4, str(tmp_path / "ck_ref"), dict(env))
    kill_out, kill_procs = _spawn_fleet(
        str(tmp_path / "kill"), 4, ck,
        dict(env, OOCDIST_KILL_ITER="5"))
    ref_logs = _join_fleet(ref_procs)
    kill_logs = _join_fleet(kill_procs)
    _assert_clean(ref_procs, ref_logs)
    ref_model = _model(ref_out, 0)
    assert all(_model(ref_out, r) == ref_model for r in range(4))

    assert all(p.returncode == -signal.SIGKILL for p in kill_procs), \
        "\n".join(l[-2000:] for l in kill_logs)
    assert not os.path.exists(kill_out + ".rank0.txt"), \
        "killed run must not have produced a model"

    resumes = []
    for world in (4, 2):
        ckw = str(tmp_path / f"ck_w{world}")
        shutil.copytree(ck, ckw)
        resumes.append((world,) + _spawn_fleet(
            str(tmp_path / f"resume{world}"), world, ckw, dict(env)))
    for world, out, procs in resumes:
        logs = _join_fleet(procs)
        _assert_clean(procs, logs)
        for r in range(world):
            res = _result(out, r)
            assert res["resume_from"] == 4, res
            assert res["learner"] == "DistributedOocTrainer"
        assert all(_model(out, r) == ref_model for r in range(world)), \
            f"world-{world} resume diverged from the reference"


# ======================================================================
# the at-scale leg: 4 ranks, dataset larger than any single rank budget
# ======================================================================
@pytest.mark.slow
def test_four_rank_over_budget(tmp_path):
    """65536 global rows at a 64 KiB per-rank device budget: every
    rank's packed shard (163840 B) exceeds the budget, so no single
    rank could hold even its own quarter resident — the fleet streams
    and the ranks agree byte-for-byte."""
    out, procs = _spawn_fleet(
        str(tmp_path / "big"), 4,
        extra_env={"OOCDIST_ROWS": "65536", "OOCDIST_TREES": "3",
                   "OOCDIST_OOC": "auto", "OOCDIST_QUANT": "1",
                   "OOCDIST_LEAVES": "15", "OOCDIST_CHUNK_ROWS": "2048",
                   "LIGHTGBM_TPU_DEVICE_BUDGET": str(64 << 10)})
    logs = _join_fleet(procs, timeout=900)
    _assert_clean(procs, logs)
    r0 = _result(out, 0)
    assert r0["ooc"] and r0["learner"] == "DistributedOocTrainer"
    assert r0["chunks_per_pass"] == 4  # 16384 rows/rank at 4096-row chunks
    m = _model(out, 0)
    assert all(_model(out, r) == m for r in range(4))


# ======================================================================
# in-process satellites: config surface, ckpt relaxation, report column
# ======================================================================
class TestConfigSurface:
    def test_feature_plus_ooc_names_the_matrix(self):
        from lightgbm_tpu import LightGBMError
        from lightgbm_tpu.config import Config

        with pytest.raises(LightGBMError,
                           match="serial.*|tree_learner=data"):
            Config.from_params({"tree_learner": "feature",
                                "out_of_core": "true"})

    def test_voting_plus_ooc_still_refused(self):
        from lightgbm_tpu import LightGBMError
        from lightgbm_tpu.config import Config

        with pytest.raises(LightGBMError, match="tree_learner=data"):
            Config.from_params({"tree_learner": "voting",
                                "out_of_core": "true"})

    def test_data_plus_ooc_is_accepted(self):
        from lightgbm_tpu.config import Config

        cfg = Config.from_params({"tree_learner": "data",
                                  "out_of_core": "true",
                                  "num_machines": 4})
        assert cfg.is_parallel

    def test_chunk_rows_message_names_distributed_rounding(self):
        from lightgbm_tpu import LightGBMError
        from lightgbm_tpu.config import Config

        with pytest.raises(LightGBMError, match="per rank"):
            Config.from_params({"ooc_chunk_rows": -1})


class TestDistScheduleRelaxation:
    def test_dist_fingerprints_exempt_from_grid_refusal(self):
        """A ``dist/``-prefixed schedule on BOTH sides resumes across
        differing per-rank grids; a serial mismatch still refuses."""
        import lightgbm_tpu as lgb
        from lightgbm_tpu.ckpt import CheckpointMismatch, capture, restore

        rng = np.random.RandomState(3)
        X = rng.randn(600, 8)
        y = (X[:, 0] + 0.2 * rng.randn(600) > 0).astype(float)
        P = {"objective": "binary", "num_leaves": 7, "verbose": -1,
             "out_of_core": "true", "ooc_chunk_rows": 512,
             "min_data_in_leaf": 20}
        bst = lgb.train(dict(P), lgb.Dataset(X, label=y, params=dict(P)),
                        2, verbose_eval=False)
        st = capture(bst)
        ooc = bst.boosting.ooc

        # serial mismatch: refused (the existing backstop)
        st.meta["ooc_schedule"] = "999r/512c/2"
        with pytest.raises(CheckpointMismatch, match="chunk schedule"):
            restore(bst, st)

        # dist-vs-dist mismatch: admitted (per-rank grids legitimately
        # differ across world sizes)
        st2 = capture(bst)
        st2.meta["ooc_schedule"] = "dist/4w/r0/4096r/4096c/1"
        ooc.schedule_fingerprint = lambda: "dist/2w/r0/8192r/4096c/2"
        try:
            restore(bst, st2)
        finally:
            del ooc.schedule_fingerprint

        # dist checkpoint into a serial run: still refused
        st3 = capture(bst)
        st3.meta["ooc_schedule"] = "dist/4w/r0/4096r/4096c/1"
        with pytest.raises(CheckpointMismatch, match="chunk schedule"):
            restore(bst, st3)


class TestReportOocColumn:
    def _recs(self, rank, stall_ms, fetch_ms):
        recs = [{"ev": "iter", "iter": 0, "wall_s": 2.0,
                 "phases": {"tree": 1.0}, "net_bytes": 100.0,
                 "rank": rank, "world": 2}]
        recs.append({"ev": "gauge", "name": "ooc.stall_ms",
                     "value": stall_ms, "rank": rank})
        recs.append({"ev": "gauge", "name": "ooc.fetch_ms",
                     "value": fetch_ms, "rank": rank})
        return recs

    def test_merge_carries_per_rank_stall_share(self):
        from lightgbm_tpu.obs.report import merge_summary, render_merge

        m = merge_summary({0: self._recs(0, 500.0, 900.0),
                           1: self._recs(1, 40.0, 800.0)})
        assert m["per_rank"][0]["ooc_stall_s"] == pytest.approx(0.5)
        assert m["per_rank"][0]["ooc_stall_share"] == pytest.approx(0.25)
        assert m["per_rank"][1]["ooc_stall_s"] == pytest.approx(0.04)
        txt = render_merge(m)
        assert "ooc_stall_s" in txt and "stall%" in txt

    def test_column_absent_without_streaming(self):
        from lightgbm_tpu.obs.report import merge_summary, render_merge

        recs = [{"ev": "iter", "iter": 0, "wall_s": 1.0, "phases": {},
                 "net_bytes": 0.0}]
        m = merge_summary({0: list(recs)})
        assert "ooc_stall_s" not in m["per_rank"][0]
        assert "ooc_stall_s" not in render_merge(m)
