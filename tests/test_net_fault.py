"""Real-subprocess fault-injection matrix for the hardened transport
(parallel/net.py, docs/ROBUSTNESS.md).

Acceptance contract (ISSUE 5): a SIGKILLed peer mid-collective is
detected by EVERY survivor as a typed ``PeerFailureError`` within ~2x
the configured deadline (no indefinite hang), survivors leave through
the checkpoint-flush path with the retryable exit code, and rerunning
the job auto-resumes to a byte-identical final model.

Tier-1 runs the smoke legs (3-rank SIGKILL mid-allgather, the bounded
bootstrap probe, and the kill -> flush -> resume training proof); the
wider matrix (mid-barrier kill, wedged-peer timeout, coordinator death)
is marked ``slow``.  Faults are injected via ``LIGHTGBM_TPU_FAULT`` in
the target rank's environment only (die:N = SIGKILL self at the Nth
collective; drop_collective:N = wedge while heartbeats keep beating).
"""

import json
import os
import signal
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "net_fault_worker.py")
DEADLINE = 4.0
# detection bound under test: wait window + staleness window (~2x the
# deadline) plus scheduling slack for a loaded CI box
DETECT_BOUND = 2 * DEADLINE + 1.5


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _spawn(rank, nproc, port, out, mode, extra_env=None, args=()):
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "LIGHTGBM_TPU_FAULT",
                        "LIGHTGBM_TPU_FAULT_RANK")}
    env["LIGHTGBM_TPU_NET_TIMEOUT"] = str(DEADLINE)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, WORKER, str(rank), str(nproc), str(port), out,
         mode, *map(str, args)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )


def _result(out, rank):
    with open(out + f".rank{rank}.json") as fh:
        return json.load(fh)


# ----------------------------------------------------------------------
# tier-1 smoke legs
# ----------------------------------------------------------------------
@pytest.mark.faultinject
@pytest.mark.netfault
def test_sigkill_mid_allgather_detected_by_all_survivors(tmp_path):
    """Rank 2 of 3 SIGKILLs itself entering the 3rd allgather; BOTH
    survivors must stop PROMPTLY — neither may hang.  Per survivor the
    same two legitimate outcomes as coordinator death
    (docs/ROBUSTNESS.md): our sweeper classifies a typed
    PeerFailureError naming rank 2 within the detection bound, or XLA's
    in-process error poller wins the race and fail-fast aborts the
    survivor from C++ (SIGABRT, "another task died") — that poller is
    not interceptable from Python and occasionally outruns the sweeper
    on a loaded box."""
    import time

    out = str(tmp_path / "g")
    port = _free_port()
    procs = [
        _spawn(r, 3, port, out, "gather",
               extra_env={"LIGHTGBM_TPU_FAULT": "die:3"} if r == 2 else None)
        for r in range(3)
    ]
    t0 = time.monotonic()
    logs = [p.communicate(timeout=240)[0] for p in procs]
    wall = time.monotonic() - t0
    assert procs[2].returncode == -signal.SIGKILL, logs[2][-2000:]
    typed = 0
    for r in (0, 1):
        rc = procs[r].returncode
        if rc == 0:  # sweeper classified before XLA's poller fired
            res = _result(out, r)
            assert res["error"] == "PeerFailureError", res
            assert 2 in res["ranks"], res
            assert res["wall"] <= DETECT_BOUND, res
            typed += 1
        else:  # XLA's fail-fast poller aborted the survivor from C++
            assert rc == -signal.SIGABRT, logs[r][-2000:]
            assert ("another task died" in logs[r]
                    or "UNAVAILABLE" in logs[r]), logs[r][-2000:]
    # the whole point: nobody hangs on the dead peer
    assert wall <= DETECT_BOUND + 30.0


@pytest.mark.faultinject
@pytest.mark.netfault
def test_bootstrap_timeout_is_loud_and_bounded(tmp_path):
    """Nothing listens at the coordinator address (the BENCH_r05 dead
    tunnel): the watchdogged initialize must raise a typed timeout
    within the retry budget instead of hanging forever."""
    out = str(tmp_path / "i")
    port = _free_port()  # bound+closed: nothing will ever listen
    p = _spawn(1, 2, port, out, "init",
               extra_env={"LIGHTGBM_TPU_NET_RETRIES": "0"})
    log = p.communicate(timeout=180)[0]
    assert p.returncode == 0, log[-2000:]
    res = _result(out, 1)
    assert res["error"] == "CollectiveTimeoutError", res
    # one attempt bounded by the RPC timeout plus the watchdog budget
    assert res["wall"] <= 3 * DEADLINE + 3.0, res


@pytest.mark.faultinject
@pytest.mark.netfault
def test_sigkill_mid_ckpt_barrier_flush_exit_and_bitidentical_resume(tmp_path):
    """The ISSUE-5 acceptance proof, on real subprocesses:

    1. reference: 2 ranks train to completion through the multihost
       checkpoint barrier — models byte-identical across ranks;
    2. kill: rank 1 SIGKILLs itself entering the 2nd checkpoint barrier
       (iteration 6); rank 0 detects PeerFailureError within the bound,
       flushes, and exits with the retryable code 75;
    3. resume: rerunning both ranks auto-resumes from the surviving
       iteration-3 checkpoint and the final model is byte-identical to
       the uninterrupted reference."""
    def run_pair(tag, ckdir, fault_rank=None):
        out = str(tmp_path / tag)
        port = _free_port()
        procs = []
        for r in range(2):
            # per-rank run trace: the survivor's typed failure must
            # flush the crash flight recorder next to it
            extra = {"LIGHTGBM_TPU_TRACE": out + f".rank{r}.trace.jsonl"}
            if r == fault_rank:
                extra["LIGHTGBM_TPU_FAULT"] = "die:2"
            procs.append(_spawn(r, 2, port, out, "train", args=(ckdir,),
                                extra_env=extra))
        logs = [p.communicate(timeout=420)[0] for p in procs]
        return out, procs, logs

    ck_ref = str(tmp_path / "ck_ref")
    ck = str(tmp_path / "ck")

    out_ref, procs, logs = run_pair("ref", ck_ref)
    assert all(p.returncode == 0 for p in procs), "\n".join(logs)
    with open(out_ref + ".rank0.txt") as fh:
        ref_model = fh.read()
    with open(out_ref + ".rank1.txt") as fh:
        assert fh.read() == ref_model
    assert _result(out_ref, 0)["resume_from"] is None

    assert not os.path.exists(out_ref + ".rank0.trace.crash.jsonl"), \
        "clean run must not leave a crash dump"

    out_k, procs, logs = run_pair("kill", ck, fault_rank=1)
    assert procs[1].returncode == -signal.SIGKILL, logs[1][-2000:]
    assert procs[0].returncode == 75, logs[0][-2000:]  # EXIT_PEER_FAILURE
    res = _result(out_k, 0)
    assert res["error"] == "PeerFailureError" and res["ranks"] == [1], res
    assert res["elapsed"] <= DETECT_BOUND, res
    assert not os.path.exists(out_k + ".rank0.txt"), \
        "killed run must not have produced a model"
    # crash flight recorder (ISSUE 7 acceptance): the survivor's typed
    # failure left a flushed .crash.jsonl containing the final spans
    # before the failure and the net.peer_failure event
    crash = out_k + ".rank0.trace.crash.jsonl"
    assert os.path.exists(crash), \
        "survivor left no flight-recorder dump"
    recs = [json.loads(l) for l in open(crash) if l.strip()]
    assert recs[0]["kind"] == "flight", recs[0]
    assert recs[0]["reason"] == "peer_failure", recs[0]
    assert recs[0]["rank"] == 0 and recs[0]["world"] == 2, recs[0]
    assert any(r.get("ev") == "span" for r in recs[1:]), \
        "crash dump carries no spans"
    assert any(r.get("ev") == "event"
               and r.get("name") == "net.peer_failure"
               and 1 in r.get("ranks", []) for r in recs[1:]), \
        "crash dump missing the net.peer_failure event"

    out_r, procs, logs = run_pair("resume", ck)
    assert all(p.returncode == 0 for p in procs), "\n".join(logs)
    for r in (0, 1):
        res = _result(out_r, r)
        assert res["resume_from"] == 3, res  # iter-3 ckpt survived the kill
        with open(out_r + f".rank{r}.txt") as fh:
            assert fh.read() == ref_model, f"rank {r} diverged after resume"


@pytest.mark.netfault
def test_report_merge_attributes_straggler_on_real_2rank_run(tmp_path):
    """ISSUE 7 acceptance: `report merge` over a REAL 2-rank run
    (subprocess pair, KV transport) produces a per-rank per-phase
    timeline and names the straggler rank with barrier-wait
    attribution.  Rank 1's per-iteration compute is ~6x rank 0's, so
    rank 0 parks in the hardened barrier behind it."""
    out = str(tmp_path / "m")
    port = _free_port()
    procs = [
        _spawn(r, 2, port, out, "mergetrace",
               extra_env={
                   "LIGHTGBM_TPU_TRACE": out + f".rank{r}.trace.jsonl",
                   "MERGETRACE_COMPUTE_S": "0.3" if r == 1 else "0.05",
               })
        for r in range(2)
    ]
    logs = [p.communicate(timeout=240)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), "\n".join(
        l[-2000:] for l in logs)
    assert all(_result(out, r)["error"] is None for r in (0, 1))

    from lightgbm_tpu.obs import report

    paths = [out + f".rank{r}.trace.jsonl" for r in (0, 1)]
    by_rank = report.load_rank_traces(paths)
    assert set(by_rank) == {0, 1}, "rank identity missing from records"
    m = report.merge_summary(by_rank)
    assert m["aligned_iterations"] == 4
    assert m["world_size"] == 2
    assert m["run_id"], "run_id missing (coordinator address fallback)"
    # straggler attribution: rank 1 computes, rank 0 waits
    st = m["straggler"]
    assert st["rank"] == 1, m
    assert st["slowest_rank_share"] > 0.5, m
    assert st["wait_behind_straggler_s"] > 0, m
    assert (m["per_rank"][0]["barrier_wait_s"]
            > m["per_rank"][1]["barrier_wait_s"]), m
    # per-phase per-rank timeline: the compute phase and the barrier
    # phase are both attributed per rank
    assert "histogram" in m["phases"] and "net.barrier" in m["phases"], m
    assert m["phases"]["histogram"][1] > m["phases"]["histogram"][0], m
    rendered = report.render_merge(m)
    assert "straggler: rank 1" in rendered
    assert "barrier wait" in rendered


# ----------------------------------------------------------------------
# wider matrix (slow)
# ----------------------------------------------------------------------
@pytest.mark.slow
@pytest.mark.faultinject
@pytest.mark.netfault
@pytest.mark.parametrize("mode", ["wfeature", "wvoting"])
def test_sigkill_during_wide_learner_training(tmp_path, mode):
    """The feature-parallel and voting-parallel learners inherit the
    hardened transport's failure semantics unchanged: SIGKILL one rank
    mid-training and the survivor classifies a typed PeerFailureError
    naming the corpse within the detection bound, then leaves with the
    retryable exit code 75 (docs/ROBUSTNESS.md)."""
    out = str(tmp_path / mode)
    port = _free_port()
    procs = [
        _spawn(r, 2, port, out, mode,
               extra_env={"LIGHTGBM_TPU_FAULT": "die:6"} if r == 1 else None)
        for r in range(2)
    ]
    logs = [p.communicate(timeout=240)[0] for p in procs]
    assert procs[1].returncode == -signal.SIGKILL, logs[1][-2000:]
    assert procs[0].returncode == 75, logs[0][-2000:]  # EXIT_PEER_FAILURE
    res = _result(out, 0)
    assert res["error"] == "PeerFailureError" and res["ranks"] == [1], res
    assert res["elapsed"] <= DETECT_BOUND, res


@pytest.mark.slow
@pytest.mark.faultinject
@pytest.mark.netfault
def test_sigkill_mid_barrier(tmp_path):
    """Same detection contract when the collective is a bare barrier."""
    out = str(tmp_path / "b")
    port = _free_port()
    procs = [
        _spawn(r, 2, port, out, "barrier",
               extra_env={"LIGHTGBM_TPU_FAULT": "die:3"} if r == 1 else None)
        for r in range(2)
    ]
    logs = [p.communicate(timeout=240)[0] for p in procs]
    assert procs[1].returncode == -signal.SIGKILL, logs[1][-2000:]
    assert procs[0].returncode == 0, logs[0][-2000:]
    res = _result(out, 0)
    assert res["error"] == "PeerFailureError" and res["ranks"] == [1], res
    assert res["wall"] <= DETECT_BOUND, res


@pytest.mark.slow
@pytest.mark.faultinject
@pytest.mark.netfault
def test_wedged_peer_is_timeout_not_peer_failure(tmp_path):
    """drop_collective wedges rank 1 while its heartbeat keeps beating:
    the survivor must classify a *lost collective with a live peer* as
    CollectiveTimeoutError, bounded by the budget."""
    out = str(tmp_path / "d")
    port = _free_port()
    procs = [
        _spawn(r, 2, port, out, "gather",
               extra_env={"LIGHTGBM_TPU_FAULT": "drop_collective:3"}
               if r == 1 else None)
        for r in range(2)
    ]
    log0 = procs[0].communicate(timeout=240)[0]
    procs[1].kill()  # the wedged rank sleeps forever by design
    procs[1].communicate()
    assert procs[0].returncode == 0, log0[-2000:]
    res = _result(out, 0)
    assert res["error"] == "CollectiveTimeoutError", res
    assert res["wall"] <= DETECT_BOUND, res


@pytest.mark.slow
@pytest.mark.faultinject
@pytest.mark.netfault
def test_coordinator_death_is_bounded_not_a_hang(tmp_path):
    """Killing rank 0 — the process hosting the coordination service —
    must stop the survivor PROMPTLY.  Two legitimate outcomes
    (docs/ROBUSTNESS.md): our sweeper classifies PeerFailureError and
    exits 0 through the flush path, or XLA's in-process error poller
    wins the race and fail-fast aborts the survivor from C++ (SIGABRT).
    Either way nothing hangs, and the atomic checkpoint store means the
    last durable checkpoint survives for auto-resume."""
    import time

    out = str(tmp_path / "c")
    port = _free_port()
    procs = [
        _spawn(r, 2, port, out, "gather",
               extra_env={"LIGHTGBM_TPU_FAULT": "die:3"} if r == 0 else None)
        for r in range(2)
    ]
    t0 = time.monotonic()
    logs = [p.communicate(timeout=240)[0] for p in procs]
    wall = time.monotonic() - t0
    assert procs[0].returncode == -signal.SIGKILL, logs[0][-2000:]
    rc1 = procs[1].returncode
    if rc1 == 0:  # our sweeper classified before XLA's poller fired
        res = _result(out, 1)
        assert res["error"] == "PeerFailureError", res
        assert res["wall"] <= DETECT_BOUND, res
    else:  # XLA's fail-fast poller aborted the survivor from C++
        assert rc1 == -signal.SIGABRT, logs[1][-2000:]
        assert "another task died" in logs[1] or "UNAVAILABLE" in logs[1], \
            logs[1][-2000:]
    # the whole point: no indefinite hang on a dead coordinator
    assert wall <= DETECT_BOUND + 30.0


# ----------------------------------------------------------------------
# elastic counterpart (ISSUE 19): armed membership re-elects, the
# default keeps every fail-fast contract above byte-for-byte
# ----------------------------------------------------------------------
@pytest.mark.netfault
@pytest.mark.membership
def test_membership_armed_reelects_deterministically(tmp_path):
    """The elastic counterpart to
    test_coordinator_death_is_bounded_not_a_hang: with a membership
    runtime armed, the coordinator's death is NOT a job-fatal transport
    error.  The survivors converge on the identical eviction decision
    and the new coordinator is DETERMINISTIC — the lowest surviving
    member id, by construction rather than by vote — so any two runs of
    the same churn re-elect the same member.  The default
    (``elastic_membership=false``, every other test in this file) keeps
    the bounded fail-fast semantics unchanged."""
    import threading

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.parallel.membership import MembershipRuntime

    # the knob defaults OFF: nothing in this file runs elastic code
    assert Config().elastic_membership is False

    rts = [MembershipRuntime(str(tmp_path), m) for m in range(3)]
    threads = [threading.Thread(target=rt.bootstrap,
                                args=(3, (200, 200, 200))) for rt in rts]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive()
    try:
        rts[0].stop()  # the coordinator freezes — SIGKILL equivalent
        decisions = [None, None]
        ts = [threading.Thread(target=lambda i=i: decisions.__setitem__(
            i - 1, rts[i].sync(known_dead=(0,)))) for i in (1, 2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
            assert not t.is_alive()
        # both survivors derived the IDENTICAL decision independently
        for d in decisions:
            assert d is not None
            assert d.dead == (0,) and d.new_members == (1, 2)
        for rt, d in zip(rts[1:], decisions):
            rt.commit_epoch(d, (300, 300), iteration=3, num_data=600)
        # re-election is positional: lowest surviving id — member 1
        assert rts[1].is_coordinator and not rts[2].is_coordinator
        assert min(rts[1].members) == 1
        assert rts[1].rank == 0 and rts[2].rank == 1
    finally:
        for rt in rts[1:]:
            rt.stop()
