"""Worker for the distributed out-of-core matrix (tests/test_oocdist.py,
bench.py's ``ooc_distributed`` section).

argv: ``rank nproc port out mode ckdir`` — the same shape as
elastic_worker.py, and the same world-invariant data recipe: the GLOBAL
dataset is generated identically on every rank from a fixed seed
(few-valued integer features so the bin mappers are bit-identical at
any world size) and each rank keeps its contiguous
``[rank*N/W, (rank+1)*N/W)`` slice under the pre_partition contract.
The difference: ``tree_learner=data`` PLUS out-of-core streaming, so
every rank streams its own shard through the prefetch ring and the node
histograms merge over the byte collectives
(boosting/oocdist.py DistributedOocTrainer).

Env knobs (set by the parent):
  OOCDIST_ROWS / OOCDIST_TREES / OOCDIST_FREQ — problem size
  OOCDIST_CHUNK_ROWS  — ooc_chunk_rows (0 = auto; rounded up to
      ROW_BLOCK per rank)
  OOCDIST_OOC         — out_of_core mode (default "true"; pass "auto"
      with LIGHTGBM_TPU_DEVICE_BUDGET to exercise the budget routing)
  OOCDIST_QUANT       — "1" turns quantized_training on (the
      grid/world byte-identity contract)
  OOCDIST_KILL_ITER=i — every rank SIGKILLs itself in the 0-based
      iteration-``i`` callback (whole-job preemption)
  OOCDIST_LEAVES      — num_leaves

Writes ``out.rankR.json`` (learner class, schedule fingerprint, stream
stats) and ``out.rankR.txt`` (final model) on clean completion.
"""

import json
import os
import signal
import sys

rank = int(sys.argv[1])
nproc = int(sys.argv[2])
port = sys.argv[3]
out = sys.argv[4]
mode = sys.argv[5]
ckdir = sys.argv[6]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["LIGHTGBM_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
os.environ["LIGHTGBM_TPU_NUM_PROCESSES"] = str(nproc)
os.environ["LIGHTGBM_TPU_PROCESS_ID"] = str(rank)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from lightgbm_tpu.parallel import net  # noqa: E402
from lightgbm_tpu.parallel.distributed import ensure_initialized  # noqa: E402

assert ensure_initialized() is True
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.process_count() == nproc

import numpy as np  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.ckpt import CheckpointManager  # noqa: E402
from lightgbm_tpu.ckpt.store import CheckpointStore  # noqa: E402
from lightgbm_tpu.cli import EXIT_PEER_FAILURE  # noqa: E402

N = int(os.environ.get("OOCDIST_ROWS", "16384"))
TREES = int(os.environ.get("OOCDIST_TREES", "4"))
FREQ = int(os.environ.get("OOCDIST_FREQ", "0"))
KILL_ITER = int(os.environ.get("OOCDIST_KILL_ITER", "-1"))
CHUNK_ROWS = int(os.environ.get("OOCDIST_CHUNK_ROWS", "0"))
OOC_MODE = os.environ.get("OOCDIST_OOC", "true")
QUANT = os.environ.get("OOCDIST_QUANT", "1") == "1"
LEAVES = int(os.environ.get("OOCDIST_LEAVES", "15"))


def _write(payload: dict) -> None:
    with open(out + f".rank{rank}.json", "w") as fh:
        json.dump(payload, fh)


def make_data(n):
    """The GLOBAL dataset, identical on every rank (see
    elastic_worker.make_data: few-valued integer features keep the
    locally-computed bin mappers bit-identical at any world size)."""
    rng = np.random.default_rng(42)
    F = 10
    X = rng.integers(0, 5, size=(n, F)).astype(np.float32)
    w = rng.standard_normal(F)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-((X - 2.0) @ w * 0.35)))
         ).astype(np.float32)
    return X, y


if mode != "train":
    print(f"unknown mode {mode}")
    sys.exit(2)

X, y = make_data(N)
lo, hi = rank * N // nproc, (rank + 1) * N // nproc
p = dict(objective="binary", tree_learner="data", num_machines=nproc,
         pre_partition=True, num_leaves=LEAVES, learning_rate=0.2,
         max_bin=31, min_data_in_leaf=20, verbose=-1,
         out_of_core=OOC_MODE, ooc_chunk_rows=CHUNK_ROWS,
         quantized_training=QUANT)
ds = lgb.Dataset(X[lo:hi], label=y[lo:hi], params=dict(p))

latest = CheckpointStore(ckdir).latest_valid() if ckdir != "-" else None
resume_from = latest[0] if latest is not None else None


def _kill(env):
    if KILL_ITER >= 0 and env.iteration >= KILL_ITER:
        # whole-job preemption: iteration KILL_ITER's collectives are
        # complete on every rank before any after-iteration callback
        # runs, so every rank reaches this line and dies here
        os.kill(os.getpid(), signal.SIGKILL)


_kill.order = 100  # after the CheckpointManager (order 40)

mgr = CheckpointManager(ckdir, freq=FREQ) if ckdir != "-" and FREQ > 0 \
    else None
booster = None
try:
    booster = lgb.train(
        dict(p), ds, TREES, verbose_eval=False,
        **({"checkpoint_manager": mgr} if mgr is not None else {}),
        callbacks=[_kill])
except net.PeerFailureError as e:
    if mgr is not None:
        mgr.flush()
    _write({"error": "PeerFailureError", "ranks": list(e.ranks),
            "resume_from": resume_from})
    print(f"rank {rank} detected peer failure after {e.elapsed_s:.1f}s")
    net.hard_exit(EXIT_PEER_FAILURE)
if mgr is not None:
    mgr.close()

ooc = booster.boosting.ooc
with open(out + f".rank{rank}.txt", "w") as fh:
    fh.write(booster.model_to_string())
_write({
    "error": None,
    "resume_from": resume_from,
    "trees": booster.num_trees,
    "iters": booster.current_iteration(),
    "world": nproc,
    "rows": [lo, hi],
    "learner": type(booster.boosting.learner).__name__,
    "ooc": ooc is not None,
    "schedule": ooc.schedule_fingerprint() if ooc is not None else None,
    "chunks_per_pass": ooc.plan.num_chunks if ooc is not None else None,
    "stream_stats": dict(ooc.stats.as_dict()) if ooc is not None else None,
})
print(f"rank {rank} oocdist train done (world={nproc}, "
      f"resume_from={resume_from})")
sys.exit(0)
