"""Prometheus metrics-registry tests: registry unit behavior (counter
monotonicity, cumulative histogram buckets, fn-backed gauges, the
tracer mirror and its no-double-count rule), exposition text-format
validity, and the ``GET /metrics`` acceptance contract — scraped during
a live microbatched load it must stay format-valid with monotone
counters and consistent histograms, agree with ``/stats`` on shared
values, and cause ZERO new XLA compiles.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import compilewatch
from lightgbm_tpu.obs.metrics import (
    BATCH_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    parse_text_format,
    registry,
    sanitize,
)


class TestRegistryUnit:
    def test_counter_monotone(self):
        r = MetricsRegistry()
        c = r.counter("lightgbm_tpu_test_total", "help text")
        c.inc()
        c.inc(2.5)
        assert c.value() == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)
        # get-or-create: same object by name
        assert r.counter("lightgbm_tpu_test_total") is c

    def test_gauge_set_and_fn(self):
        r = MetricsRegistry()
        g = r.gauge("lightgbm_tpu_test_gauge")
        g.set(4.0)
        assert g.value() == 4.0
        box = {"v": 7.0}
        g2 = r.gauge("lightgbm_tpu_test_fn_gauge", fn=lambda: box["v"])
        assert g2.value() == 7.0
        box["v"] = 9.0
        assert g2.value() == 9.0  # evaluated at read time

    def test_fn_re_registration_replaces_callback(self):
        r = MetricsRegistry()
        r.gauge("lightgbm_tpu_g", fn=lambda: 1.0)
        g = r.gauge("lightgbm_tpu_g", fn=lambda: 2.0)
        assert g.value() == 2.0

    def test_type_conflict_raises(self):
        r = MetricsRegistry()
        r.counter("lightgbm_tpu_x_total")
        with pytest.raises(TypeError):
            r.gauge("lightgbm_tpu_x_total")

    def test_invalid_name_rejected(self):
        r = MetricsRegistry()
        with pytest.raises(ValueError):
            r.counter("has space")

    def test_histogram_cumulative_buckets_sum_count(self):
        r = MetricsRegistry()
        h = r.histogram("lightgbm_tpu_h", buckets=(1.0, 2.0, 4.0))
        for v in (0.5, 1.5, 3.0, 100.0):
            h.observe(v)
        text = r.render()
        fam = parse_text_format(text)["lightgbm_tpu_h"]
        s = fam["samples"]
        assert fam["type"] == "histogram"
        assert s['lightgbm_tpu_h_bucket{le="1"}'] == 1
        assert s['lightgbm_tpu_h_bucket{le="2"}'] == 2
        assert s['lightgbm_tpu_h_bucket{le="4"}'] == 3
        assert s['lightgbm_tpu_h_bucket{le="+Inf"}'] == 4
        assert s["lightgbm_tpu_h_count"] == 4
        assert s["lightgbm_tpu_h_sum"] == pytest.approx(105.0)

    def test_render_parses_and_orders_type_before_samples(self):
        r = MetricsRegistry()
        r.counter("lightgbm_tpu_a_total", "a").inc()
        r.gauge("lightgbm_tpu_b", "b").set(1)
        r.histogram("lightgbm_tpu_c", "c", buckets=(1.0,)).observe(0.5)
        fams = parse_text_format(r.render())  # raises on malformed output
        assert set(fams) == {"lightgbm_tpu_a_total", "lightgbm_tpu_b",
                             "lightgbm_tpu_c"}

    def test_sanitize(self):
        assert sanitize("net.retry") == "net_retry"
        assert sanitize("a-b/c") == "a_b_c"

    def test_trace_mirror_maps_and_accumulates(self):
        r = MetricsRegistry()
        r.trace_counter("net.retry", 1)
        r.trace_counter("net.retry", 2)
        r.trace_gauge("ingest.host_rss_mb", 123.5)
        snap = r.snapshot()
        assert snap["lightgbm_tpu_net_retry_total"] == 3
        assert snap["lightgbm_tpu_ingest_host_rss_mb"] == 123.5

    def test_trace_mirror_never_double_counts_explicit_metrics(self):
        """The serve layer updates its registry metrics directly AND
        traces the same signal — the mirror must skip names that are
        already explicitly instrumented."""
        r = MetricsRegistry()
        c = r.counter("lightgbm_tpu_serve_shed_total")
        c.inc()  # the explicit instrumentation
        r.trace_counter("serve_shed", 1)  # the mirror of the same event
        assert r.snapshot()["lightgbm_tpu_serve_shed_total"] == 1
        # name collision across kinds (serve_batch_rows gauge vs the
        # explicit histogram) must be skipped, not raise
        r.histogram("lightgbm_tpu_serve_batch_rows",
                    buckets=BATCH_BUCKETS).observe(8)
        r.trace_gauge("serve_batch_rows", 8.0)
        assert r.snapshot()["lightgbm_tpu_serve_batch_rows"] == 1

    def test_global_registry_has_compile_collectors(self):
        snap = registry.snapshot()
        assert "lightgbm_tpu_xla_compiles_total" in snap
        assert "lightgbm_tpu_xla_compile_seconds_total" in snap


@pytest.fixture(scope="module")
def live_server():
    """A warmed server on an ephemeral port (the test_serve pattern)."""
    import tempfile

    from lightgbm_tpu.serve.server import make_server

    rng = np.random.RandomState(3)
    X = rng.randn(600, 12)
    y = (X[:, 0] + 0.5 * X[:, 1] - X[:, 2] ** 2 > -0.5).astype(np.float32)
    ds = lgb.Dataset(X, label=y, params={"min_data_in_leaf": 5})
    bst = lgb.train({"objective": "binary", "num_leaves": 15, "verbose": -1},
                    ds, num_boost_round=10, verbose_eval=False)
    path = tempfile.mktemp(suffix=".txt")
    bst.save_model(path)
    # the registry counters are process-global; earlier in-process serve
    # traffic (e.g. tests/test_fleet.py) would skew the /stats parity
    # assertions, which compare against THIS server's batchers only
    registry._reset_for_tests()
    srv = make_server(path, port=0, warmup_max_rows=256, max_delay_ms=1.0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    host, port = srv.server_address[:2]
    yield srv, f"http://{host}:{port}", X
    srv.shutdown()
    srv.server_close()


def _get(base, path):
    return urllib.request.urlopen(base + path, timeout=30).read().decode()


def _post_rows(base, rows):
    body = "\n".join(json.dumps([float(v) for v in r]) for r in rows)
    req = urllib.request.Request(base + "/predict", data=body.encode())
    return urllib.request.urlopen(req, timeout=30).read().decode()


class TestMetricsEndpoint:
    def test_scrape_under_live_load(self, live_server):
        """The acceptance run: scrape /metrics WHILE a concurrent
        microbatched load runs.  Every scrape must parse as valid
        exposition format, counters must be monotone across scrapes,
        histograms internally consistent, and the scrapes themselves
        must cause zero new XLA compiles."""
        srv, base, X = live_server
        stop = threading.Event()
        errors = []

        def load():
            i = 0
            while not stop.is_set():
                try:
                    _post_rows(base, X[i % 500: i % 500 + 7])
                except Exception as e:  # pragma: no cover
                    errors.append(e)
                    return
                i += 7

        threads = [threading.Thread(target=load, daemon=True)
                   for _ in range(4)]
        _post_rows(base, X[:8])  # ensure a warm request precedes scraping
        compiles_before = compilewatch.snapshot()["backend_compiles"]
        for t in threads:
            t.start()
        scrapes = []
        try:
            for _ in range(5):
                scrapes.append(parse_text_format(_get(base, "/metrics")))
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
        assert not errors, errors
        assert (compilewatch.snapshot()["backend_compiles"]
                == compiles_before), "scraping /metrics compiled something"

        names_required = {
            "lightgbm_tpu_serve_requests_total",
            "lightgbm_tpu_serve_rows_total",
            "lightgbm_tpu_serve_batches_total",
            "lightgbm_tpu_serve_shed_total",
            "lightgbm_tpu_serve_deadline_expired_total",
            "lightgbm_tpu_serve_batch_rows",
            "lightgbm_tpu_serve_latency_seconds",
            "lightgbm_tpu_serve_queue_rows",
            "lightgbm_tpu_serve_ready",
            "lightgbm_tpu_serve_draining",
            "lightgbm_tpu_xla_compiles_total",
        }
        for fams in scrapes:
            assert names_required <= set(fams), (
                names_required - set(fams))
        # counters monotone across consecutive scrapes
        for a, b in zip(scrapes, scrapes[1:]):
            for fam, fa in a.items():
                if fa["type"] != "counter":
                    continue
                for key, va in fa["samples"].items():
                    assert b[fam]["samples"][key] >= va, (fam, key)
        # histogram internal consistency on the last scrape
        for fam in ("lightgbm_tpu_serve_batch_rows",
                    "lightgbm_tpu_serve_latency_seconds"):
            s = scrapes[-1][fam]["samples"]
            buckets = sorted(
                ((k, v) for k, v in s.items() if "_bucket{" in k),
                key=lambda kv: float("inf") if "+Inf" in kv[0]
                else float(kv[0].split('le="')[1].rstrip('"}')))
            values = [v for _, v in buckets]
            assert values == sorted(values), f"{fam} buckets not cumulative"
            assert values[-1] == s[f"{fam}_count"]
        assert scrapes[-1]["lightgbm_tpu_serve_ready"]["samples"][
            "lightgbm_tpu_serve_ready"] == 1.0

    def test_metrics_agree_with_stats(self, live_server):
        """Shared values must agree between the human JSON (/stats, per
        batcher) and the Prometheus surface (aggregate) when the server
        is quiescent."""
        srv, base, X = live_server
        _post_rows(base, X[:5])
        stats = json.loads(_get(base, "/stats"))
        fams = parse_text_format(_get(base, "/metrics"))

        def metric(name):
            return fams[name]["samples"][name]

        both = [stats["batcher"], stats["raw_batcher"]]
        assert metric("lightgbm_tpu_serve_requests_total") == sum(
            b["requests"] for b in both)
        assert metric("lightgbm_tpu_serve_rows_total") == sum(
            b["rows"] for b in both)
        assert metric("lightgbm_tpu_serve_shed_total") == sum(
            b["shed"] for b in both)
        assert metric("lightgbm_tpu_serve_deadline_expired_total") == sum(
            b["timeouts"] for b in both)
        assert metric("lightgbm_tpu_serve_queue_rows") == 0
        assert metric("lightgbm_tpu_serve_ready") == float(stats["ready"])
        assert metric("lightgbm_tpu_serve_draining") == float(
            stats["draining"])
        assert metric("lightgbm_tpu_serve_inflight_requests") == 0
        assert fams["lightgbm_tpu_serve_predict_compiles_total"]["samples"][
            "lightgbm_tpu_serve_predict_compiles_total"
        ] == stats["compiles"]["predict_compiles"]

    def test_metrics_content_type(self, live_server):
        srv, base, _ = live_server
        resp = urllib.request.urlopen(base + "/metrics", timeout=30)
        assert resp.headers["Content-Type"].startswith(
            "text/plain; version=0.0.4")


class TestEndOfTrainDump:
    def test_cli_dump_knob(self, tmp_path, monkeypatch):
        """LIGHTGBM_TPU_METRICS=path: the CLI writes a valid exposition
        dump at end of train, carrying the compile collectors."""
        import os

        from lightgbm_tpu.cli import main

        rng = np.random.RandomState(0)
        X = rng.randn(300, 4)
        y = (X[:, 0] > 0).astype(np.float64)
        data = tmp_path / "train.tsv"
        np.savetxt(data, np.column_stack([y, X]), fmt="%.10g",
                   delimiter="\t")
        out = tmp_path / "model.txt"
        mpath = tmp_path / "metrics.txt"
        monkeypatch.setenv("LIGHTGBM_TPU_METRICS", str(mpath))
        rc = main([f"data={data}", f"output_model={out}", "task=train",
                   "objective=binary", "num_trees=2", "num_leaves=4",
                   "verbose=-1"])
        assert rc == 0 and os.path.exists(mpath)
        fams = parse_text_format(mpath.read_text())
        assert "lightgbm_tpu_xla_compiles_total" in fams
