"""Quantized-gradient training (ops/qhist.py, quantized_training=true).

Contracts pinned here:

  - flag OFF is the default and leaves the f32 path byte-identical
    (engine level, and the 2-rank data-parallel world still exchanges
    the f32 "hist" wire);
  - stochastic rounding is unbiased across iteration seeds and exact on
    grid points;
  - the int accumulation path is row-order invariant and rank-count
    invariant (integer adds are associative), where the f32 path is
    neither guaranteed nor tested to be;
  - quantized split gains sit inside the exported analytic drift bound
    at max_bin=255;
  - the "hist_q" wire is exactly F*B*4 bytes (int16), falls back to a
    length-discriminated int32 format on overflow, and round-trips.
"""

import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.ops import qhist  # noqa: E402
from lightgbm_tpu.ops.grow import GrowParams, grow_tree  # noqa: E402
from lightgbm_tpu.ops.histogram import build_histogram  # noqa: E402
from lightgbm_tpu.ops.split import (  # noqa: E402
    FeatureMeta,
    SplitHyper,
    best_split_per_feature,
)
from lightgbm_tpu.parallel import HostParallelLearner, LocalGroup  # noqa: E402


def _meta(f, B):
    return FeatureMeta(jnp.full((f,), B, jnp.int32),
                       jnp.zeros((f,), jnp.int32),
                       jnp.zeros((f,), bool))


def _hyper(min_data=20.0):
    return SplitHyper(jnp.float32(0.0), jnp.float32(0.1),
                      jnp.float32(min_data), jnp.float32(1e-3),
                      jnp.float32(0.0))


def _run_group(mode, params, shards, meta, hyper, fmask):
    """Grow one tree on every simulated rank; returns (results, ledgers)."""
    nproc = len(shards)
    grp = LocalGroup(nproc)
    out = [None] * nproc
    errs = []

    def worker(r, comm):
        try:
            b, g, h = shards[r]
            n = b.shape[0]
            learner = HostParallelLearner(mode, comm, params)
            gr = learner.grow(
                jnp.asarray(b), jnp.asarray(g), jnp.asarray(h),
                jnp.ones((n,), jnp.float32), fmask, meta, hyper)
            out[r] = (jax.tree_util.tree_map(np.asarray, gr), comm.ledger)
        except BaseException as e:  # surface worker failures to pytest
            errs.append((r, e))

    ts = [threading.Thread(target=worker, args=(r, c))
          for r, c in enumerate(grp.comms())]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    if errs:
        raise errs[0][1]
    return out


def _assert_same_tree(a, b, skip=()):
    for name, x, y in zip(a._fields, a, b):
        if name in skip:
            continue
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=f"field {name}")


def _quantize(grad, hess, seed=3, bits=qhist.QUANT_BITS):
    n = len(grad)
    mx = np.asarray(qhist.local_absmax(
        jnp.asarray(grad), jnp.asarray(hess), jnp.ones((n,), jnp.float32)))
    scales = qhist.scales_from_max(mx[0], mx[1], bits)
    qg, qh = qhist.quantize_rows(
        jnp.asarray(grad), jnp.asarray(hess), jnp.asarray(scales),
        np.uint32(seed), bits)
    return qg, qh, scales


@pytest.fixture(scope="module")
def small():
    rng = np.random.default_rng(11)
    n, f, B = 2000, 23, 16
    bins = rng.integers(0, B, size=(n, f)).astype(np.uint8)
    grad = rng.normal(size=n).astype(np.float32)
    hess = (0.5 + rng.random(n)).astype(np.float32)
    return n, f, B, bins, grad, hess


@pytest.fixture(scope="module")
def trainable():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(600, 10)).astype(np.float32)
    y = (X[:, 0] + 0.4 * X[:, 1] ** 2
         + rng.normal(scale=0.1, size=600) > 0.3).astype(np.float32)
    return X, y


# ----------------------------------------------------------------------
# flag OFF: the default path is untouched
# ----------------------------------------------------------------------
class TestFlagOffParity:
    def _train(self, X, y, extra):
        p = dict(objective="binary", num_leaves=15, learning_rate=0.1,
                 min_data_in_leaf=5, verbose=-1, seed=7)
        p.update(extra)
        bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=5)
        return bst.predict(X)

    def test_engine_default_is_off_and_identical(self, trainable):
        X, y = trainable
        base = self._train(X, y, {})
        off = self._train(X, y, {"quantized_training": False})
        np.testing.assert_array_equal(base, off)

    def test_use_quantized_grad_alias(self, trainable):
        X, y = trainable
        a = self._train(X, y, {"use_quantized_grad": True})
        b = self._train(X, y, {"quantized_training": True})
        np.testing.assert_array_equal(a, b)

    def test_data_world_flag_off_keeps_f32_wire(self, small):
        n, f, B, bins, grad, hess = small
        params = GrowParams(num_leaves=7, num_bins=B)
        cut = n // 2
        shards = [(bins[:cut], grad[:cut], hess[:cut]),
                  (bins[cut:], grad[cut:], hess[cut:])]
        meta, hyper = _meta(f, B), _hyper()
        fmask = jnp.ones((f,), jnp.float32)
        res = _run_group("data", params, shards, meta, hyper, fmask)
        ledger = res[0][1]
        assert ledger.get("hist", 0) > 0
        assert "hist_q" not in ledger
        # the flag-off world is deterministic: a repeat run is
        # byte-identical
        res2 = _run_group("data", params, shards, meta, hyper, fmask)
        for (a, _), (b, _) in zip(res, res2):
            _assert_same_tree(a, b)


# ----------------------------------------------------------------------
# stochastic rounding
# ----------------------------------------------------------------------
class TestStochasticRounding:
    def test_unbiased_across_seeds(self):
        scales = jnp.asarray(np.asarray([0.01, 0.02], np.float32))
        g = jnp.asarray(np.asarray([0.123], np.float32))  # g/s = 12.3
        h = jnp.asarray(np.asarray([0.031], np.float32))  # h/s = 1.55
        qs_g, qs_h = [], []
        for seed in range(400):
            qg, qh = qhist.quantize_rows(g, h, scales, np.uint32(seed))
            qs_g.append(int(qg[0]))
            qs_h.append(int(qh[0]))
        # floor(x/s + u) takes only the two bracketing integers, with
        # P(upper) = frac(x/s): the seed-mean converges to x/s
        assert set(qs_g) <= {12, 13}
        assert abs(np.mean(qs_g) - 12.3) < 0.11  # ~4 sigma at 400 draws
        assert abs(np.mean(qs_h) - 1.55) < 0.11

    def test_exact_on_grid_points(self):
        scales = jnp.asarray(np.asarray([0.25, 0.5], np.float32))
        g = jnp.asarray(np.asarray([2.5, -1.25, 0.0], np.float32))
        for seed in (0, 1, 99):
            qg, _ = qhist.quantize_rows(
                g, jnp.zeros(3, jnp.float32), scales, np.uint32(seed))
            np.testing.assert_array_equal(np.asarray(qg), [10, -5, 0])

    def test_hash_uniform_strictly_below_one(self):
        # 75196197 is a bit pattern whose murmur finalizer output lands
        # within 128 of 2**32: a raw uint32->f32 cast rounds it UP to
        # 2**32, so the old conversion returned u == 1.0 exactly and
        # floor(x/s + u) overshot by a full unit.  The 24-bit mask keeps
        # the int->float cast exact and u < 1 strictly.
        x = jnp.asarray(np.array([75196197], np.uint32).view(np.float32))
        u = np.asarray(qhist._hash_uniform(x, jnp.uint32(0)))
        assert float(u[0]) < 1.0
        # granularity: every draw is an exact multiple of 2**-24 in [0, 1)
        rng = np.random.default_rng(8)
        xs = jnp.asarray(rng.normal(size=4096).astype(np.float32))
        us = np.asarray(qhist._hash_uniform(xs, jnp.uint32(123)))
        assert float(us.max()) < 1.0 and float(us.min()) >= 0.0
        np.testing.assert_array_equal(us * 2.0 ** 24,
                                      np.round(us * 2.0 ** 24))

    def test_value_keyed_row_order_invariance(self, small):
        n, f, B, bins, grad, hess = small
        qg, qh, _ = _quantize(grad, hess, seed=17)
        perm = np.random.default_rng(0).permutation(n)
        qg_p, qh_p, _ = _quantize(grad[perm], hess[perm], seed=17)
        np.testing.assert_array_equal(np.asarray(qg)[perm], np.asarray(qg_p))
        np.testing.assert_array_equal(np.asarray(qh)[perm], np.asarray(qh_p))


# ----------------------------------------------------------------------
# int accumulation: exactness and determinism
# ----------------------------------------------------------------------
class TestIntHistogramDeterminism:
    def test_hist_row_order_invariant(self, small):
        n, f, B, bins, grad, hess = small
        qg, qh, _ = _quantize(grad, hess)
        sel = jnp.ones((n,), jnp.float32)
        ref = np.asarray(build_histogram(jnp.asarray(bins), qg, qh, sel, B))
        assert ref.dtype == np.int32
        for s in (1, 2):
            perm = np.random.default_rng(s).permutation(n)
            got = np.asarray(build_histogram(
                jnp.asarray(bins[perm]), qg[perm], qh[perm], sel, B))
            np.testing.assert_array_equal(ref, got)

    def test_serial_tree_shuffle_invariant(self, small):
        n, f, B, bins, grad, hess = small
        params = GrowParams(num_leaves=15, num_bins=B, quantized=True)
        meta, hyper = _meta(f, B), _hyper()
        fmask = jnp.ones((f,), jnp.float32)
        sel = jnp.ones((n,), jnp.float32)
        qg, qh, scales = _quantize(grad, hess)
        qs = jnp.asarray(scales)
        ref = jax.tree_util.tree_map(np.asarray, grow_tree(
            jnp.asarray(bins), qg, qh, sel, fmask, meta, hyper, params,
            qscale=qs))
        assert int(ref.num_splits) > 3
        perm = np.random.default_rng(2).permutation(n)
        got = jax.tree_util.tree_map(np.asarray, grow_tree(
            jnp.asarray(bins[perm]), qg[perm], qh[perm], sel, fmask, meta,
            hyper, params, qscale=qs))
        # leaf_id is a per-row partition — everything else must be
        # byte-identical under the permutation
        _assert_same_tree(ref, got, skip=("leaf_id",))
        np.testing.assert_array_equal(ref.leaf_id[perm], got.leaf_id)

    @pytest.mark.parametrize("nprocs", [(2, 4)])
    def test_data_world_rank_count_invariant(self, small, nprocs):
        n, f, B, bins, grad, hess = small
        params = GrowParams(num_leaves=7, num_bins=B, quantized=True)
        meta, hyper = _meta(f, B), _hyper()
        fmask = jnp.ones((f,), jnp.float32)
        trees = []
        for R in nprocs:
            cuts = np.linspace(0, n, R + 1).astype(int)
            shards = [(bins[a:b], grad[a:b], hess[a:b])
                      for a, b in zip(cuts[:-1], cuts[1:])]
            res = _run_group("data", params, shards, meta, hyper, fmask)
            ledger = res[0][1]
            assert ledger.get("hist_q", 0) > 0 and "hist" not in ledger
            trees.append(res[0][0])
        _assert_same_tree(trees[0], trees[1], skip=("leaf_id",))

    def test_voting_full_vote_equals_data(self, small):
        n, f, B, bins, grad, hess = small
        # top_k = f: every feature is elected, so PV-Tree must reduce to
        # the exact data-parallel tree — in integers, byte-identically
        meta, hyper = _meta(f, B), _hyper()
        fmask = jnp.ones((f,), jnp.float32)
        cut = n // 2
        shards = [(bins[:cut], grad[:cut], hess[:cut]),
                  (bins[cut:], grad[cut:], hess[cut:])]
        pd = GrowParams(num_leaves=7, num_bins=B, quantized=True)
        pv = GrowParams(num_leaves=7, num_bins=B, quantized=True, top_k=f)
        rd = _run_group("data", pd, shards, meta, hyper, fmask)
        rv = _run_group("voting", pv, shards, meta, hyper, fmask)
        for (a, _), (b, _) in zip(rd, rv):
            _assert_same_tree(a, b)


# ----------------------------------------------------------------------
# drift bound at max_bin=255
# ----------------------------------------------------------------------
class TestDriftBound:
    def test_gains_within_bound_max_bin_255(self):
        rng = np.random.default_rng(3)
        n, f, B = 4096, 8, 256
        bins = rng.integers(0, B, size=(n, f)).astype(np.uint8)
        grad = rng.normal(size=n).astype(np.float32)
        hess = (0.5 + rng.random(n)).astype(np.float32)
        sel = jnp.ones((n,), jnp.float32)
        meta, hyper = _meta(f, B), _hyper()
        fmask = jnp.ones((f,), jnp.float32)
        qg, qh, scales = _quantize(grad, hess)

        hist_f = build_histogram(jnp.asarray(bins), jnp.asarray(grad),
                                 jnp.asarray(hess), sel, B)
        hist_q = qhist.dequantize_hist(
            build_histogram(jnp.asarray(bins), qg, qh, sel, B),
            jnp.asarray(scales))
        sums_f = (float(np.sum(grad)), float(np.sum(hess)), float(n))
        sums_q = np.asarray(qhist.dequantize_sums(
            jnp.stack([jnp.sum(qg, dtype=jnp.int32),
                       jnp.sum(qh, dtype=jnp.int32),
                       jnp.int32(n)]), jnp.asarray(scales)))
        gains_f = np.asarray(best_split_per_feature(
            hist_f, jnp.float32(sums_f[0]), jnp.float32(sums_f[1]),
            jnp.float32(sums_f[2]), meta, hyper, fmask, True)[0])
        gains_q = np.asarray(best_split_per_feature(
            hist_q, jnp.float32(sums_q[0]), jnp.float32(sums_q[1]),
            jnp.float32(sums_q[2]), meta, hyper, fmask, True)[0])
        bound = qhist.quant_drift_bound(
            scales[0], scales[1], n, lambda_l2=0.1, min_hessian=1e-3)
        assert np.isfinite(bound) and bound > 0
        valid = np.isfinite(gains_f) & np.isfinite(gains_q)
        assert valid.any()
        assert float(np.abs(gains_f[valid] - gains_q[valid]).max()) <= bound

    def test_bound_shrinks_with_bits(self):
        # more bits -> smaller scale -> tighter bound (same maxima)
        bounds = [qhist.quant_drift_bound(
            1.0 / qhist.qmax_for(b), 1.0 / qhist.qmax_for(b), 1000,
            lambda_l2=2000.0, bits=b) for b in (3, 5, 8)]
        assert bounds[0] > bounds[1] > bounds[2] > 0


# ----------------------------------------------------------------------
# hist_q wire format
# ----------------------------------------------------------------------
class TestWireFormat:
    def test_int16_roundtrip_and_exact_size(self):
        rng = np.random.default_rng(1)
        F, B = 23, 16
        hist2 = rng.integers(-3000, 3000, size=(F, B, 2)).astype(np.int32)
        blob = qhist.pack_hist_q(hist2)
        assert len(blob) == qhist.wire_bytes_q(F, B) == F * B * 4
        assert qhist.wire_bytes_f32(F, B) == 3 * qhist.wire_bytes_q(F, B)
        np.testing.assert_array_equal(qhist.unpack_hist_q(blob, F, B), hist2)

    def test_int32_overflow_fallback(self):
        F, B = 5, 8
        hist2 = np.zeros((F, B, 2), np.int32)
        hist2[2, 3, 0] = 40_000  # exceeds int16
        blob = qhist.pack_hist_q(hist2)
        assert len(blob) == F * B * 8
        np.testing.assert_array_equal(qhist.unpack_hist_q(blob, F, B), hist2)

    def test_bad_length_raises(self):
        with pytest.raises(ValueError, match="neither"):
            qhist.unpack_hist_q(b"\x00" * 10, 5, 8)

    def test_count_plane_derivation(self, small):
        n, f, B, bins, grad, hess = small
        qg, qh, scales = _quantize(grad, hess)
        sel = jnp.ones((n,), jnp.float32)
        hist = np.asarray(build_histogram(jnp.asarray(bins), qg, qh, sel, B))
        asm = qhist.assemble_hist(hist[..., :2], scales, float(n))
        # derived counts track the exact counts (cnt_factor trick):
        # each bin rounds by < 0.5, so a feature's B bins sum to the
        # node count within B/2
        assert float(np.abs(asm[..., 2].sum(axis=1) - n).max()) <= B / 2
        assert float(np.abs(asm[..., 2] - hist[..., 2]).max()) <= 32.0

    def test_three_plane_roundtrip(self):
        rng = np.random.default_rng(4)
        F, B = 7, 8
        hist2 = rng.integers(-100, 100, size=(F, B, 2)).astype(np.int32)
        counts = rng.integers(0, 50, size=(F, B)).astype(np.int32)
        blob = qhist.pack_hist_q(hist2, counts)
        assert len(blob) == F * B * 6
        out = qhist.unpack_hist_q(blob, F, B)
        assert out.shape == (F, B, 3)
        np.testing.assert_array_equal(out[..., :2], hist2)
        np.testing.assert_array_equal(out[..., 2], counts)
        # int32 fallback when any plane overflows int16
        counts[0, 0] = 40_000
        blob = qhist.pack_hist_q(hist2, counts)
        assert len(blob) == F * B * 12
        np.testing.assert_array_equal(
            qhist.unpack_hist_q(blob, F, B)[..., 2], counts)

    def test_degenerate_node_exact_counts(self):
        # every hessian quantized to zero: derivation alone would zero
        # the count plane and min_data_in_leaf would prune every split;
        # the shipped exact plane must come through untouched
        F, B = 3, 4
        hist2 = np.zeros((F, B, 2), np.int64)
        hist2[..., 0] = 5  # gradient mass only
        counts = np.full((F, B), 7, np.int64)
        asm = qhist.assemble_hist(hist2, np.asarray([0.1, 0.1], np.float32),
                                  float(counts[0].sum()), counts=counts)
        np.testing.assert_array_equal(asm[..., 2], counts)

    def test_blended_exact_plus_derived_counts(self):
        # rank A has hessian mass (2-plane wire); rank B's hessians all
        # quantized to zero and it shipped exact counts.  B's rows count
        # exactly; A's derive from the merged hessian plane, to which
        # only A contributed.
        F, B = 2, 4
        merged = np.zeros((F, B, 2), np.int64)
        merged[:, 0, 1] = 30  # A: 10 rows in bin 0, qh=3 each
        exact_b = np.zeros((F, B), np.int64)
        exact_b[:, 1] = 6  # B: 6 rows in bin 1
        plane = qhist.derive_count_plane(merged, 16.0, exact=exact_b)
        assert float(plane[0, 0]) == 10.0
        assert float(plane[0, 1]) == 6.0

    def test_degenerate_without_exact_counts_stays_zero(self):
        # no sender shipped counts (e.g. negative hessians defeat the
        # local-zero test): behavior is unchanged — zeros plus a warning
        merged = np.zeros((3, 4, 2), np.int64)
        plane = qhist.derive_count_plane(merged, 9.0)
        np.testing.assert_array_equal(plane, 0.0)


class TestDegenerateNodeProtocol:
    def test_merge_mixed_plane_blobs(self):
        F, B = 4, 8
        h2 = np.ones((F, B, 2), np.int32)
        cnt = np.full((F, B), 2, np.int32)
        blobs = [qhist.pack_hist_q(h2), qhist.pack_hist_q(h2, cnt)]
        tot, exact = HostParallelLearner._merge_q(None, blobs, F, B)
        np.testing.assert_array_equal(tot, np.full((F, B, 2), 2))
        np.testing.assert_array_equal(exact, cnt)
        tot2, exact2 = HostParallelLearner._merge_q(
            None, [qhist.pack_hist_q(h2)] * 2, F, B)
        np.testing.assert_array_equal(tot2, np.full((F, B, 2), 2))
        assert exact2 is None

    def test_sender_ships_counts_only_when_hessless(self):
        F, B = 3, 4
        h3 = np.zeros((F, B, 3), np.int32)
        h3[..., 2] = 1  # rows present, zero hessian mass
        np.testing.assert_array_equal(
            HostParallelLearner._q_counts_if_degenerate(h3), h3[..., 2])
        h3[0, 0, 1] = 4  # hessian mass -> normal 2-plane wire
        assert HostParallelLearner._q_counts_if_degenerate(h3) is None
        # empty node: nothing to protect
        assert HostParallelLearner._q_counts_if_degenerate(
            np.zeros((F, B, 3), np.int32)) is None


class TestAccumulatorHeadroom:
    def test_max_rows_for(self):
        assert qhist.max_rows_for(5) == (2 ** 31 - 1) // 15
        assert qhist.max_rows_for(2) > qhist.max_rows_for(8)

    def test_engine_declines_past_headroom(self, trainable, monkeypatch):
        # past the int32 accumulation bound the flag is dropped with a
        # warning and training proceeds bit-identically to the f32 path
        X, y = trainable
        monkeypatch.setattr(qhist, "max_rows_for", lambda bits=5: 100)
        p = dict(objective="binary", num_leaves=15, learning_rate=0.1,
                 min_data_in_leaf=5, verbose=-1, seed=7)
        bst_q = lgb.train(dict(p, quantized_training=True),
                          lgb.Dataset(X, label=y), num_boost_round=3)
        bst_f = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=3)
        np.testing.assert_array_equal(bst_q.predict(X), bst_f.predict(X))


# ----------------------------------------------------------------------
# engine-level quantized runs
# ----------------------------------------------------------------------
class TestEngineQuantized:
    def _train(self, X, y, extra, rounds=5):
        p = dict(objective="binary", num_leaves=15, learning_rate=0.1,
                 min_data_in_leaf=5, verbose=-1, seed=7,
                 quantized_training=True)
        p.update(extra)
        bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=rounds)
        return bst.predict(X)

    def test_deterministic_across_runs(self, trainable):
        X, y = trainable
        a = self._train(X, y, {})
        b = self._train(X, y, {})
        np.testing.assert_array_equal(a, b)
        assert np.isfinite(a).all()

    def test_out_of_core_matches_in_memory(self, trainable):
        # integer accumulation makes the chunk grid irrelevant: the
        # streamed trainer must reproduce the in-memory quantized trees
        # byte for byte
        X, y = trainable
        a = self._train(X, y, {})
        b = self._train(X, y, {"out_of_core": True})
        np.testing.assert_array_equal(a, b)

    def test_bits_validation(self, trainable):
        X, y = trainable
        with pytest.raises(lgb.LightGBMError, match="quantized_grad_bits"):
            self._train(X, y, {"quantized_grad_bits": 99})

    def test_learns_signal(self, trainable):
        X, y = trainable
        pred = self._train(X, y, {}, rounds=20)
        acc = float(np.mean((pred > 0.5) == (y > 0.5)))
        assert acc > 0.85


# ----------------------------------------------------------------------
# report plumbing
# ----------------------------------------------------------------------
class TestReportQuantizedWire:
    def test_summary_and_ratio(self):
        from lightgbm_tpu.obs.report import (
            net_bytes_by_purpose,
            quantized_wire_summary,
        )

        recs = [{"ev": "counter", "name": "net.bytes", "value": 400.0,
                 "purpose": "hist_q"},
                {"ev": "counter", "name": "net.bytes", "value": 100.0,
                 "purpose": "best_split"}]
        pb = net_bytes_by_purpose(recs)
        assert pb == {"hist_q": 400.0, "best_split": 100.0}
        qw = quantized_wire_summary(pb, iters=2)
        assert qw["ratio"] == 3.0
        assert qw["hist_q_bytes_per_iter"] == 200.0
        # unquantized runs report ratio 1.0; no histogram purpose -> None
        assert quantized_wire_summary({"hist": 600.0}, 1)["ratio"] == 1.0
        assert quantized_wire_summary({"vote": 5.0}, 1) is None
