"""Tier-1 perf-path smoke: the traced (phase-attributed) mode and the
fused chunk mode must produce bit-identical MODELS on a tiny CPU run, so
future kernel edits can't silently defuse or diverge the traced path —
plus the report CLI's one-line phase attribution."""

import json

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import report, tracer


def _toy(n=800, f=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, f)).astype(np.float32)
    w = rng.standard_normal(f)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-(X @ w)))).astype(np.float32)
    return X, y


def _read(path):
    recs = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                recs.append(json.loads(line))
    return recs


def test_traced_and_fused_iterations_bit_identical_models(tmp_path, monkeypatch):
    """One traced-phase run vs fused runs (level-batched AND classic) of
    the same config: model strings must be byte-equal, and the traced
    trace must actually carry the four per-phase timings (the defuse
    tripwire)."""
    monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
    X, y = _toy()
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
              "min_data_in_leaf": 20}
    modes = {
        "fused_level": {"LIGHTGBM_TPU_LEVELGROW": "1",
                        "LIGHTGBM_TPU_TRACE_PHASES": "0"},
        "fused_classic": {"LIGHTGBM_TPU_LEVELGROW": "0",
                          "LIGHTGBM_TPU_TRACE_PHASES": "0"},
        "traced": {"LIGHTGBM_TPU_LEVELGROW": "0",
                   "LIGHTGBM_TPU_TRACE_PHASES": "1"},
    }
    models = {}
    try:
        for mode, env in modes.items():
            for k, v in env.items():
                monkeypatch.setenv(k, v)
            monkeypatch.setenv("LIGHTGBM_TPU_TRACE",
                               str(tmp_path / f"{mode}.jsonl"))
            bst = lgb.train(dict(params),
                            lgb.Dataset(X, label=y, params=dict(params)),
                            num_boost_round=2, verbose_eval=False)
            assert bst.boosting.ptrainer is not None
            models[mode] = bst.model_to_string()
    finally:
        tracer.close()
        tracer.path = None
    assert models["fused_level"] == models["fused_classic"], \
        "level-batched fused diverged from classic fused"
    assert models["traced"] == models["fused_classic"], \
        "traced-phase path diverged from the fused path"

    recs = _read(tmp_path / "traced.jsonl")
    iters = [r for r in recs if r["ev"] == "iter"]
    assert iters, "traced run emitted no iteration records"
    for r in iters:
        assert r.get("mode") == "traced", "traced run silently ran fused"
        assert {"histogram", "split", "partition", "score_update"} <= set(
            r["phases"]), f"missing phases: {sorted(r['phases'])}"
    # the fused run must NOT silently run traced (per-split dispatch tax)
    fused_recs = _read(tmp_path / "fused_level.jsonl")
    fused_iters = [r for r in fused_recs if r["ev"] == "iter"]
    assert fused_iters and all(r.get("amortized") for r in fused_iters)


def test_report_top_phases_line():
    summary = {
        "phases": {
            "partition": {"total_s": 6.0, "count": 3, "mean_ms": 2000.0},
            "histogram": {"total_s": 3.0, "count": 3, "mean_ms": 1000.0},
            "split": {"total_s": 0.8, "count": 3, "mean_ms": 266.7},
            "score_update": {"total_s": 0.2, "count": 3, "mean_ms": 66.7},
        },
    }
    line = report.top_phases_line(summary)
    assert line == "top phases: partition 60.0% | histogram 30.0% | split 8.0%"
    assert report.top_phases_line({"phases": {}}) == ""


def test_report_render_includes_top_phases(tmp_path):
    trace = tmp_path / "t.jsonl"
    recs = [
        {"ev": "iter", "iter": 0, "wall_s": 1.0,
         "phases": {"partition": 0.6, "histogram": 0.3, "split": 0.1}},
    ]
    trace.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    summary = report.summarize(report.load_trace(str(trace)))
    text = report.render(summary, str(trace))
    assert "top phases: partition 60.0% | histogram 30.0% | split 10.0%" in text
