"""Golden parity tests against the ACTUAL reference implementation.

The reference C++ binary (built from /root/reference via
refbuild/, see tests/golden/make_goldens.sh) trained deterministic
30-iteration models on its own example datasets; the model files, its
predictions on the test sets, and its final valid metrics are committed
under tests/golden/.  These tests prove three things the numpy oracle
cannot (SURVEY §4 golden strategy; gbdt.cpp:854-1008 model format,
tests/cpp_test/test.py:5-6 style):

1. cross-load: a reference-written model file loads through
   ``Booster(model_file=...)`` and our predictor reproduces the
   reference's own predictions to float tolerance;
2. train parity: training HERE with identical (sampling-free) params
   reaches the reference's final valid metric within a tight band;
3. reverse cross-load: models we save run through the reference binary's
   ``task=predict`` and agree with our own predictions (skipped when
   the binary is absent).
"""

import os
import subprocess
import tempfile

import numpy as np
import pytest

import lightgbm_tpu as lgb

GOLD = os.path.join(os.path.dirname(__file__), "golden")
EXAMPLES = "/root/reference/examples"
REF_BIN = os.environ.get("LIGHTGBM_BIN", "/root/repo/refbuild/lightgbm")

# name -> (example dir, train file, test file, deterministic params)
DET = {"feature_fraction": 1.0, "bagging_freq": 0, "bagging_fraction": 1.0,
       "num_trees": 30, "verbose": -1}
TASKS = {
    "binary": (
        "binary_classification", "binary.train", "binary.test",
        {"objective": "binary", "metric": ["auc", "binary_logloss"],
         "max_bin": 255, "num_leaves": 63, "learning_rate": 0.1,
         "min_data_in_leaf": 50, "min_sum_hessian_in_leaf": 5.0},
    ),
    "regression": (
        "regression", "regression.train", "regression.test",
        {"objective": "regression", "metric": "l2", "max_bin": 255,
         "num_leaves": 31, "learning_rate": 0.05,
         "min_data_in_leaf": 100, "min_sum_hessian_in_leaf": 5.0},
    ),
    "multiclass": (
        "multiclass_classification", "multiclass.train", "multiclass.test",
        {"objective": "multiclass", "metric": "multi_logloss",
         "num_class": 5, "max_bin": 255, "num_leaves": 31,
         "learning_rate": 0.05},
    ),
    "lambdarank": (
        "lambdarank", "rank.train", "rank.test",
        {"objective": "lambdarank", "metric": "ndcg",
         "ndcg_eval_at": [1, 3, 5], "max_bin": 255, "num_leaves": 31,
         "learning_rate": 0.1, "min_data_in_leaf": 50,
         "min_sum_hessian_in_leaf": 5.0},
    ),
}

# final-iteration valid metrics recorded from the reference run
# (tests/golden/*_train_metrics.txt).  Bands are set from MEASURED
# divergence (r5: binary auc max|Δ| 6e-4 over 30 iters, logloss 1.4e-4,
# l2 exact to 6 decimals, multi_logloss 2.1e-4) with ~3x headroom —
# fp32-scale, so a sub-percent quality bug now fails.
GOLDEN_METRIC = {
    "binary": ("auc", 0.826754, 0.002),
    "regression": ("l2", 0.188265, 0.002),
    "multiclass": ("multi_logloss", 1.4737, 0.002),
    # lambdarank band is wider: at iteration 1 all scores are tied and the
    # reference's std::sort applies an implementation-defined permutation
    # to equal keys (ours is a stable argsort), so the runs diverge from
    # tree 1 onward by construction; rank.test has only 50 queries, so one
    # query's ordering = 0.02 NDCG.  Verified non-systematic: continuing
    # from the reference's own 5-tree model reproduces its tree 6
    # node-for-node (same features/thresholds, gains within 1%).
    "lambdarank": ("ndcg@5", 0.681375, 0.035),
}

# iteration-by-iteration trace band (same evidence base; lambdarank
# excluded for the tie-order reason above)
TRACE_TOL = 0.002


def _golden_trace(name):
    """metric -> {iteration: value} parsed from the full reference log."""
    import re

    out = {}
    with open(os.path.join(GOLD, f"{name}_train_metrics.txt")) as f:
        for line in f:
            m = re.search(r"Iteration:(\d+), valid_1 (\S+) : ([-\d.eE]+)", line)
            if m:
                out.setdefault(m.group(2), {})[int(m.group(1))] = float(m.group(3))
    return out


def _test_path(name):
    d, _, test, _ = TASKS[name]
    return os.path.join(EXAMPLES, d, test)


@pytest.mark.parametrize("name", list(TASKS))
def test_reference_model_cross_load_predict_parity(name, reference_examples):
    """Load the reference-trained model file; our traversal must emit the
    reference's own predictions (same transform incl. sigmoid/softmax)."""
    bst = lgb.Booster(model_file=os.path.join(GOLD, f"{name}_model.txt"))
    pred = bst.predict(_test_path(name))
    gold = np.loadtxt(os.path.join(GOLD, f"{name}_pred.txt"))
    assert pred.shape == gold.shape
    np.testing.assert_allclose(pred, gold, rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("name", list(TASKS))
def test_train_metric_parity_vs_reference(name, reference_examples):
    """Sampling-free training here must land on the reference's final
    valid metric within the published CPU↔GPU tolerance band."""
    d, train, test, params = TASKS[name]
    params = {**params, **DET}
    dtrain = lgb.Dataset(os.path.join(EXAMPLES, d, train))
    dvalid = lgb.Dataset(os.path.join(EXAMPLES, d, test), reference=dtrain)
    evals = {}
    bst = lgb.train(params, dtrain, num_boost_round=30,
                    valid_sets=[dvalid], valid_names=["valid_1"],
                    callbacks=[lgb.record_evaluation(evals)])
    metric, golden, tol = GOLDEN_METRIC[name]
    got = evals["valid_1"][metric][-1]
    assert abs(got - golden) < tol, f"{metric}: {got} vs reference {golden}"
    # iteration-by-iteration trace: every eval point of the run must
    # track the reference's trajectory, not just the final value
    if name != "lambdarank":
        trace = _golden_trace(name).get(metric, {})
        ours = evals["valid_1"][metric]
        for it in sorted(trace):
            if it <= len(ours):
                d = abs(ours[it - 1] - trace[it])
                assert d < TRACE_TOL, (
                    f"{metric} iteration {it}: {ours[it - 1]} vs "
                    f"reference {trace[it]} (|Δ|={d:.6f})"
                )


@pytest.fixture(scope="session")
def ref_bin():
    """Build the reference binary when absent (refbuild/ is gitignored)
    so the reverse cross-load proof runs instead of silently skipping.
    The reference CMakeLists links into its own source dir; the binary is
    moved straight into refbuild/."""
    if os.path.exists(REF_BIN):
        return REF_BIN
    bdir = os.path.dirname(REF_BIN)
    os.makedirs(bdir, exist_ok=True)
    try:
        with open(os.path.join(bdir, "cmake.log"), "w") as log:
            subprocess.run(
                ["cmake", "/root/reference", "-DCMAKE_BUILD_TYPE=Release"],
                cwd=bdir, check=True, stdout=log, stderr=log, timeout=300)
            subprocess.run(
                ["make", "-j2", "lightgbm"],
                cwd=bdir, check=True, stdout=log, stderr=log, timeout=1500)
        built = "/root/reference/lightgbm"
        if os.path.exists(built) and not os.path.exists(REF_BIN):
            os.replace(built, REF_BIN)
    except (subprocess.SubprocessError, OSError) as e:
        pytest.skip(f"reference binary build failed: {e}")
    if not os.path.exists(REF_BIN):
        pytest.skip("reference binary not found after build")
    return REF_BIN


@pytest.mark.parametrize("name", list(TASKS))
def test_our_model_loads_into_reference_binary(name, reference_examples, ref_bin):
    """Reverse direction: a model we save must be consumable by the
    reference binary's task=predict, and its predictions must match ours."""
    d, train, test, params = TASKS[name]
    params = {**params, **DET, "num_trees": 5}
    dtrain = lgb.Dataset(os.path.join(EXAMPLES, d, train))
    bst = lgb.train(params, dtrain, num_boost_round=5)
    ours = bst.predict(_test_path(name))
    with tempfile.TemporaryDirectory() as td:
        model = os.path.join(td, "model.txt")
        out = os.path.join(td, "pred.txt")
        bst.save_model(model)
        subprocess.run(
            [REF_BIN, "task=predict", f"data={_test_path(name)}",
             f"input_model={model}", f"output_result={out}"],
            check=True, cwd=td, capture_output=True)
        theirs = np.loadtxt(out)
    np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-7)
