"""Serving subsystem tests: packed artifact save/load/predict parity,
the shape-bucketed compile cache (the acceptance contract: a warmed
predictor answers mixed-size batches with ZERO new compiles and
bit-identical outputs vs Booster.predict), the microbatcher
(coalescing, overload shedding, timeouts), and the HTTP front end.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import compilewatch
from lightgbm_tpu.ops.predict import TreeArrays
from lightgbm_tpu.serve import (
    BucketedRawPredictor,
    MicroBatcher,
    PackedPredictor,
    PredictorArtifact,
    RequestTimeout,
    ServerOverloaded,
    bucket_for,
    bucket_ladder,
)
from lightgbm_tpu.utils.log import LightGBMError


@pytest.fixture(scope="module")
def binary_booster():
    rng = np.random.RandomState(3)
    X = rng.randn(600, 12)
    y = (X[:, 0] + 0.5 * X[:, 1] - X[:, 2] ** 2 > -0.5).astype(np.float32)
    ds = lgb.Dataset(X, label=y, params={"min_data_in_leaf": 5})
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "verbose": -1},
        ds, num_boost_round=12, verbose_eval=False,
    )
    return bst, X


@pytest.fixture(scope="module")
def multiclass_booster():
    rng = np.random.RandomState(4)
    X = rng.randn(400, 8)
    y = (np.abs(X[:, 0]) + X[:, 1] > 1).astype(np.float32) + (X[:, 0] > 0)
    ds = lgb.Dataset(X, label=y, params={"min_data_in_leaf": 5})
    bst = lgb.train(
        {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
         "verbose": -1},
        ds, num_boost_round=6, verbose_eval=False,
    )
    return bst, X


class TestBuckets:
    def test_bucket_for(self):
        assert bucket_for(1) == 8
        assert bucket_for(8) == 8
        assert bucket_for(9) == 16
        assert bucket_for(3000) == 4096
        assert bucket_for(1, min_bucket=4) == 4

    def test_bucket_multiple_of_devices(self):
        # a 12-device host: buckets stay divisible by the device count
        assert bucket_for(9, multiple_of=12) % 12 == 0

    def test_ladder_covers_every_size(self):
        ladder = bucket_ladder(4096)
        assert ladder == [8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096]
        for n in (1, 7, 100, 3000, 4096):
            assert bucket_for(n) in ladder


class TestTreeArraysValidate:
    def _arrays(self, t=3, m=5, L=6):
        kw = {f: np.zeros((t, m), np.int32) for f in TreeArrays.FIELDS}
        kw["leaf_value"] = np.zeros((t, L), np.float32)
        return kw

    def test_ok(self):
        TreeArrays(**self._arrays()).validate()

    def test_mismatched_node_plane(self):
        kw = self._arrays()
        kw["threshold_bin"] = np.zeros((3, 4), np.int32)
        with pytest.raises(ValueError, match="threshold_bin"):
            TreeArrays(**kw).validate()

    def test_mismatched_leaf_tree_count(self):
        kw = self._arrays()
        kw["leaf_value"] = np.zeros((2, 6), np.float32)
        with pytest.raises(ValueError, match="leaf_value"):
            TreeArrays(**kw).validate()

    def test_non_2d(self):
        kw = self._arrays()
        kw["zero_bin"] = np.zeros((3,), np.int32)
        with pytest.raises(ValueError, match="zero_bin"):
            TreeArrays(**kw).validate()


class TestArtifact:
    def test_save_load_predict_parity(self, binary_booster, tmp_path):
        bst, X = binary_booster
        art = PredictorArtifact.from_booster(bst)
        path = art.save(str(tmp_path / "model"))
        assert path.endswith(".npz")
        loaded = PredictorArtifact.load(path)
        assert loaded.meta == art.meta
        packed = PackedPredictor(loaded)
        for n in (1, 33, 600):
            assert np.array_equal(packed.predict(X[:n]), bst.predict(X[:n]))
        # raw scores too
        assert np.array_equal(
            packed.predict(X[:50], raw_score=True),
            bst.predict(X[:50], raw_score=True),
        )

    def test_multiclass_parity(self, multiclass_booster, tmp_path):
        bst, X = multiclass_booster
        path = PredictorArtifact.from_booster(bst).save(str(tmp_path / "mc"))
        packed = PackedPredictor(PredictorArtifact.load(path))
        got, exp = packed.predict(X[:40]), bst.predict(X[:40])
        assert got.shape == (40, 3)
        assert np.array_equal(got, exp)

    def test_metadata(self, binary_booster):
        bst, _ = binary_booster
        art = PredictorArtifact.from_booster(bst)
        assert art.num_class == 1
        assert art.num_tree_per_iteration == 1
        assert art.num_features == 12
        assert art.meta["objective"].startswith("binary")

    def test_load_rejects_non_artifact(self, tmp_path):
        p = str(tmp_path / "junk.npz")
        np.savez(p, foo=np.zeros(3))
        with pytest.raises(LightGBMError, match="__meta__"):
            PredictorArtifact.load(p)

    def test_load_rejects_future_version(self, binary_booster, tmp_path):
        bst, _ = binary_booster
        art = PredictorArtifact.from_booster(bst)
        art.meta["format_version"] = 999
        # bypass validate-on-init by writing directly
        import json as _json

        payload = {f: getattr(art.arrays, f) for f in TreeArrays.FIELDS}
        payload["__meta__"] = np.asarray(_json.dumps(art.meta))
        p = str(tmp_path / "future.npz")
        np.savez(p, **payload)
        with pytest.raises(LightGBMError, match="format_version"):
            PredictorArtifact.load(p)

    def test_load_rejects_corrupt_file(self, tmp_path):
        """Satellite 1: garbage bytes get the actionable refusal, not a
        raw numpy/zipfile error."""
        p = str(tmp_path / "corrupt.npz")
        with open(p, "wb") as f:
            f.write(b"this is not an npz archive at all")
        with pytest.raises(LightGBMError,
                           match="corrupt, truncated, or not an artifact"):
            PredictorArtifact.load(p)

    def test_load_rejects_truncated_file(self, binary_booster, tmp_path):
        bst, _ = binary_booster
        path = PredictorArtifact.from_booster(bst).save(str(tmp_path / "t"))
        with open(path, "rb") as f:
            blob = f.read()
        p = str(tmp_path / "trunc.npz")
        with open(p, "wb") as f:
            f.write(blob[: len(blob) // 2])
        with pytest.raises(LightGBMError):
            PredictorArtifact.load(p)

    def test_load_bytes_roundtrip_and_refusal(self, binary_booster):
        import io

        bst, X = binary_booster
        art = PredictorArtifact.from_booster(bst)
        buf = io.BytesIO()
        art.save_to_bytes(buf)
        loaded = PredictorArtifact.load_bytes(buf.getvalue())
        assert loaded.meta == art.meta
        assert np.array_equal(
            PackedPredictor(loaded).predict(X[:8]), bst.predict(X[:8]))
        with pytest.raises(LightGBMError, match="corrupt or truncated"):
            PredictorArtifact.load_bytes(b"\x00\x01junk")

    def test_num_iteration_subset(self, binary_booster, tmp_path):
        bst, X = binary_booster
        art = PredictorArtifact.from_booster(bst, num_iteration=5)
        packed = PackedPredictor(art)
        assert np.array_equal(
            packed.predict(X[:30]), bst.predict(X[:30], num_iteration=5)
        )


class TestCompileCache:
    def test_warmed_mixed_sizes_zero_compiles_bit_identical(
            self, binary_booster, monkeypatch):
        """The PR acceptance criterion: after warmup(), mixed-size
        requests (N in {1, 7, 100, 3000}) trigger ZERO new compiles
        (obs compile accountant) and results are bit-identical to
        Booster.predict on the same rows.  The expected values come from
        the exact-shape legacy path so they cannot incidentally pre-warm
        the bucket-shaped programs being asserted on."""
        bst, X = binary_booster
        big = np.tile(X, (6, 1))[:3000]  # 3000 rows from the same rows
        monkeypatch.setenv("LIGHTGBM_TPU_PREDICT_BUCKETS", "0")
        expected = {n: bst.predict(big[:n]) for n in (1, 7, 100, 3000)}
        monkeypatch.delenv("LIGHTGBM_TPU_PREDICT_BUCKETS")
        packed = PackedPredictor(PredictorArtifact.from_booster(bst))
        stats = packed.warmup(4096)
        assert stats["buckets"][-1] >= 3000
        c0 = compilewatch.total_compiles()
        for n in (1, 7, 100, 3000):
            got = packed.predict(big[:n])
            assert got.shape == (n,)
            assert np.array_equal(got, expected[n]), f"N={n} not bit-identical"
        assert compilewatch.total_compiles() - c0 == 0, \
            "warmed predictor recompiled on a covered batch size"

    def test_booster_predict_uses_buckets(self, binary_booster, monkeypatch):
        """Repeated Booster.predict at varying N reuses the bucket
        programs: after touching a bucket once, more sizes inside it
        compile nothing new."""
        bst, X = binary_booster
        bst.predict(X[:40])  # compiles the 64-bucket
        c0 = compilewatch.total_compiles()
        for n in (33, 50, 64, 41):  # all inside the same 64-bucket
            bst.predict(X[:n])
        assert compilewatch.total_compiles() - c0 == 0

    def test_bucketed_matches_legacy_path(self, binary_booster, monkeypatch):
        bst, X = binary_booster
        bucketed = bst.predict(X[:77])
        monkeypatch.setenv("LIGHTGBM_TPU_PREDICT_BUCKETS", "0")
        legacy = bst.predict(X[:77])
        assert np.array_equal(bucketed, legacy)

    def test_sharded_predict_matches(self, binary_booster):
        """Row-sharded traversal over the 8-device CPU mesh returns the
        same bits as the single-device path."""
        import jax

        if len(jax.local_devices()) < 2:
            pytest.skip("needs >1 local device")
        bst, X = binary_booster
        b = bst.boosting
        sharded = BucketedRawPredictor.from_models(
            b._used_models(-1), b.num_tree_per_iteration, shard=True
        )
        got = sharded.predict_raw_scores(np.asarray(X[:100], np.float64))
        exp = b.predict_raw_scores(np.asarray(X[:100], np.float64))
        assert np.array_equal(got, exp)

    def test_model_invalidation(self, tmp_path):
        """Training more iterations invalidates the booster's cached
        bucketed predictor (keyed on tree count)."""
        rng = np.random.RandomState(5)
        X = rng.randn(200, 5)
        y = (X[:, 0] > 0).astype(np.float32)
        ds = lgb.Dataset(X, label=y, params={"min_data_in_leaf": 5})
        bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1},
                        ds, num_boost_round=3, verbose_eval=False)
        p3 = bst.predict(X[:20])
        bst.update()
        p4 = bst.predict(X[:20])
        assert not np.array_equal(p3, p4)


class TestMicroBatcher:
    def test_coalesces_concurrent_requests(self, binary_booster):
        bst, X = binary_booster
        packed = PackedPredictor(PredictorArtifact.from_booster(bst))
        packed.warmup(256)
        mb = MicroBatcher(packed.predict, max_batch_size=128, max_delay_ms=20)
        try:
            import concurrent.futures as cf

            with cf.ThreadPoolExecutor(16) as ex:
                futs = [ex.submit(mb.submit, X[i * 4:(i + 1) * 4])
                        for i in range(16)]
                outs = [f.result() for f in futs]
            exp = bst.predict(X[:64])
            for i, o in enumerate(outs):
                assert np.array_equal(o, exp[i * 4:(i + 1) * 4])
            st = mb.stats()
            assert st["requests"] == 16 and st["rows"] == 64
            assert st["batches"] < 16, "no coalescing happened"
            assert st["latency_p99_ms"] > 0
        finally:
            mb.close()

    def test_overload_shedding(self):
        release = threading.Event()

        def slow_predict(batch):
            release.wait(5.0)
            return np.zeros(batch.shape[0])

        mb = MicroBatcher(slow_predict, max_batch_size=4, max_delay_ms=1,
                          max_queue_rows=8)
        try:
            t = threading.Thread(
                target=lambda: mb.submit(np.zeros((8, 3)), timeout_ms=10_000),
                daemon=True)
            t.start()
            # wait until the first request is in flight or queued
            import time as _t

            _t.sleep(0.2)
            with pytest.raises(ServerOverloaded):
                mb.submit(np.zeros((9, 3)))
            assert mb.stats()["shed"] == 1
        finally:
            release.set()
            mb.close()

    def test_queued_request_timeout(self):
        release = threading.Event()

        def slow_predict(batch):
            release.wait(5.0)
            return np.zeros(batch.shape[0])

        mb = MicroBatcher(slow_predict, max_batch_size=2, max_delay_ms=1)
        try:
            t = threading.Thread(
                target=lambda: mb.submit(np.zeros((2, 3)), timeout_ms=10_000),
                daemon=True)
            t.start()
            with pytest.raises(RequestTimeout):
                mb.submit(np.zeros((2, 3)), timeout_ms=50)
            assert mb.stats()["timeouts"] == 1
        finally:
            release.set()
            mb.close()

    def test_predict_error_propagates(self):
        def bad_predict(batch):
            raise ValueError("boom")

        mb = MicroBatcher(bad_predict, max_delay_ms=1)
        try:
            with pytest.raises(ValueError, match="boom"):
                mb.submit(np.zeros((2, 3)))
            assert mb.stats()["errors"] == 1
        finally:
            mb.close()

    def test_submit_ex_surfaces_batch_info(self):
        """A predict_fn returning (outputs, info) stamps every request
        of the batch with that info (the model-version attribution
        channel); plain predict_fns surface info=None."""
        mb = MicroBatcher(lambda b: (np.arange(b.shape[0]) * 2.0, 7),
                          max_delay_ms=1)
        try:
            out, info = mb.submit_ex(np.zeros((3, 2)))
            assert info == 7
            assert np.array_equal(out, [0.0, 2.0, 4.0])
            # plain submit() still returns just the outputs
            assert np.array_equal(mb.submit(np.zeros((2, 2))), [0.0, 2.0])
        finally:
            mb.close()
        mb2 = MicroBatcher(lambda b: np.zeros(b.shape[0]), max_delay_ms=1)
        try:
            _, info = mb2.submit_ex(np.zeros((1, 2)))
            assert info is None
        finally:
            mb2.close()

    def test_drain_settles_to_zero_and_sheds(self):
        """Satellite 2 at the batcher level: drain() sheds new submits,
        finishes queued+executing rows, then settles inflight_rows and
        draining to a stable zero."""
        import time as _time

        gate = threading.Event()

        def predict(batch):
            gate.wait(5.0)
            return np.zeros(batch.shape[0])

        mb = MicroBatcher(predict, max_batch_size=4, max_delay_ms=1)
        try:
            t = threading.Thread(
                target=lambda: mb.submit(np.zeros((2, 3)), timeout_ms=10_000),
                daemon=True)
            t.start()
            _time.sleep(0.1)
            assert mb.stats()["inflight_rows"] > 0
            done = {}

            def drainer():
                done["ok"] = mb.drain(5.0)

            dt = threading.Thread(target=drainer, daemon=True)
            dt.start()
            _time.sleep(0.05)
            with pytest.raises(ServerOverloaded, match="draining"):
                mb.submit(np.zeros((1, 3)))
            gate.set()
            dt.join(timeout=10)
            t.join(timeout=10)
            assert done["ok"] is True
            st = mb.stats()
            assert st["inflight_rows"] == 0
            assert st["draining"] is False
        finally:
            gate.set()
            mb.close()


class TestHTTPServer:
    @pytest.fixture()
    def server(self, binary_booster, tmp_path):
        from lightgbm_tpu.serve.server import make_server

        bst, X = binary_booster
        path = PredictorArtifact.from_booster(bst).save(str(tmp_path / "m"))
        srv = make_server(path, port=0, warmup_max_rows=256, max_delay_ms=1.0)
        thread = threading.Thread(target=srv.serve_forever, daemon=True)
        thread.start()
        yield srv, bst, X
        srv.shutdown()
        srv.server_close()

    def _post(self, port, rows, query=""):
        body = "\n".join(json.dumps(list(map(float, r))) for r in rows).encode()
        r = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/predict{query}", data=body, timeout=30)
        return [json.loads(l) for l in r.read().decode().splitlines()]

    def test_predict_matches_booster(self, server):
        srv, bst, X = server
        port = srv.server_address[1]
        preds = self._post(port, X[:9])
        assert np.array_equal(np.asarray(preds), bst.predict(X[:9]))

    def test_raw_score_and_dict_rows(self, server):
        srv, bst, X = server
        port = srv.server_address[1]
        body = "\n".join(
            json.dumps({"features": list(map(float, r))}) for r in X[:3]
        ).encode()
        r = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/predict?raw_score=1", data=body, timeout=30)
        raw = [json.loads(l) for l in r.read().decode().splitlines()]
        assert np.array_equal(np.asarray(raw), bst.predict(X[:3], raw_score=True))

    def test_health_and_stats(self, server):
        srv, bst, X = server
        port = srv.server_address[1]
        self._post(port, X[:5])
        h = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30).read())
        assert h == {"status": "ok"}
        st = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=30).read())
        assert st["num_features"] == 12
        assert st["batcher"]["requests"] >= 1
        assert st["compiles"]["predict_retraces"] == 0

    def test_model_version_stamping(self, server):
        """Every predict reply names the model version that produced it:
        X-Model-Version header always, per-line dicts on request."""
        srv, bst, X = server
        port = srv.server_address[1]
        body = "\n".join(
            json.dumps(list(map(float, r))) for r in X[:3]).encode()
        r = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/predict", data=body, timeout=30)
        assert r.headers["X-Model-Version"] == "1"
        r = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/predict?model_version=1",
            data=body, timeout=30)
        lines = [json.loads(l) for l in r.read().decode().splitlines()]
        assert all(l["model_version"] == 1 for l in lines)
        assert np.array_equal(
            np.asarray([l["prediction"] for l in lines]), bst.predict(X[:3]))

    def test_bad_requests(self, server):
        srv, _, _ = server
        port = srv.server_address[1]
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/predict",
                                   data=b"[1,2]\n[1]\n", timeout=30)
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/predict",
                                   data=b"", timeout=30)
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope",
                                   data=b"[1]\n", timeout=30)
        assert ei.value.code == 404

    def test_server_accepts_model_text_file(self, binary_booster, tmp_path):
        """model= also accepts the reference-format text file (packed on
        the fly)."""
        from lightgbm_tpu.serve.server import load_predictor

        bst, X = binary_booster
        path = str(tmp_path / "model.txt")
        bst.save_model(path)
        packed = load_predictor(path)
        assert np.array_equal(packed.predict(X[:5]), bst.predict(X[:5]))


class TestCLI:
    def test_serve_without_model_errors(self, capsys):
        from lightgbm_tpu.cli import main

        assert main(["serve"]) == 1


class TestReadyAndDrain:
    """/readyz readiness gating and the SIGTERM graceful drain
    (docs/ROBUSTNESS.md): ready only after artifact load + warmup,
    503 while draining, in-flight microbatches finish before exit."""

    @pytest.fixture()
    def server(self, binary_booster, tmp_path):
        from lightgbm_tpu.serve.server import make_server

        bst, X = binary_booster
        path = PredictorArtifact.from_booster(bst).save(str(tmp_path / "m"))
        srv = make_server(path, port=0, warmup_max_rows=256, max_delay_ms=1.0)
        thread = threading.Thread(target=srv.serve_forever, daemon=True)
        thread.start()
        yield srv, bst, X
        srv.shutdown()
        srv.server_close()

    def _get_code(self, port, path):
        try:
            return urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=30).status
        except urllib.error.HTTPError as e:
            return e.code

    def test_readyz_ready_after_warmup(self, server):
        srv, _, _ = server
        port = srv.server_address[1]
        assert self._get_code(port, "/healthz") == 200
        assert self._get_code(port, "/readyz") == 200
        body = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/readyz", timeout=30).read())
        assert body == {"status": "ready"}
        st = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=30).read())
        assert st["ready"] is True and st["draining"] is False
        assert st["inflight"] == 0

    def test_readyz_503_before_ready_and_while_draining(self, server):
        srv, _, X = server
        port = srv.server_address[1]
        srv.ready = False
        try:
            assert self._get_code(port, "/readyz") == 503
            assert self._get_code(port, "/healthz") == 200  # liveness only
        finally:
            srv.ready = True
        srv.draining = True
        try:
            assert self._get_code(port, "/readyz") == 503
            body = "\n".join(
                json.dumps(list(map(float, r))) for r in X[:2]).encode()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/predict", data=body, timeout=30)
            assert ei.value.code == 503  # shed-not-queue during drain
        finally:
            srv.draining = False

    def test_drain_finishes_inflight_requests(self, binary_booster, tmp_path):
        import time as _time

        from lightgbm_tpu.serve.server import make_server

        bst, X = binary_booster
        path = PredictorArtifact.from_booster(bst).save(str(tmp_path / "m"))
        srv = make_server(path, port=0, warmup_max_rows=64, max_delay_ms=1.0)
        thread = threading.Thread(target=srv.serve_forever, daemon=True)
        thread.start()
        port = srv.server_address[1]
        try:
            orig = srv.predictor.predict
            srv.batcher.predict_fn = (
                lambda batch: (_time.sleep(0.4), orig(batch))[1]
            )
            result = {}

            def post():
                body = json.dumps(list(map(float, X[0]))).encode()
                r = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/predict", data=body, timeout=30)
                result["code"] = r.status
                result["pred"] = json.loads(r.read().decode().splitlines()[0])

            th = threading.Thread(target=post)
            th.start()
            _time.sleep(0.1)  # the request is now in flight
            assert srv.drain(5.0) is True  # waits for it, then stops
            th.join(timeout=10)
            assert result["code"] == 200  # in-flight work finished, not cut
            assert result["pred"] == pytest.approx(float(bst.predict(X[:1])[0]))
            thread.join(timeout=10)
            assert not thread.is_alive()  # serve_forever exited
        finally:
            srv.server_close()

    def test_sigterm_handler_drains(self, binary_booster, tmp_path):
        """main()'s SIGTERM path end-to-end in-process: the handler
        thread drains and serve_forever returns."""
        import time as _time

        from lightgbm_tpu.serve.server import make_server

        bst, _ = binary_booster
        path = PredictorArtifact.from_booster(bst).save(str(tmp_path / "m2"))
        srv = make_server(path, port=0, warmup_max_rows=64)
        thread = threading.Thread(target=srv.serve_forever, daemon=True)
        thread.start()
        try:
            drainer = threading.Thread(target=srv.drain, args=(5.0,),
                                       daemon=True)
            drainer.start()
            drainer.join(timeout=10)
            thread.join(timeout=10)
            assert not thread.is_alive()
            # a COMPLETED drain settles: drained latches, draining (and
            # every inflight count) reads a stable zero — not stuck at 1
            assert srv.drained is True
            assert srv.draining is False
            assert srv._inflight == 0
            assert srv.batcher.stats()["inflight_rows"] == 0
            assert srv.batcher.stats()["draining"] is False
        finally:
            srv.server_close()

    def test_drain_settles_metrics_gauges(self, binary_booster, tmp_path):
        """Satellite 2: after a completed drain the Prometheus gauges —
        not just /stats — read zero for draining and inflight (they are
        fn-backed, so this checks the live server state they sample)."""
        from lightgbm_tpu.obs.metrics import registry as metrics_registry
        from lightgbm_tpu.serve.server import make_server

        bst, X = binary_booster
        path = PredictorArtifact.from_booster(bst).save(str(tmp_path / "m3"))
        srv = make_server(path, port=0, warmup_max_rows=64, max_delay_ms=1.0)
        thread = threading.Thread(target=srv.serve_forever, daemon=True)
        thread.start()
        port = srv.server_address[1]
        try:
            body = json.dumps(list(map(float, X[0]))).encode()
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/predict", data=body, timeout=30)
            assert srv.drain(5.0) is True
            snap = metrics_registry.snapshot()
            assert snap["lightgbm_tpu_serve_draining"] == 0.0
            assert snap["lightgbm_tpu_serve_inflight_requests"] == 0.0
            assert snap["lightgbm_tpu_serve_queue_rows"] == 0.0
        finally:
            srv.server_close()
