"""Shared harness for the strategy-parity pin (tests/test_strategy_parity.py).

The tentpole refactor (lightgbm_tpu/tree/strategy.py) must be INVISIBLE:
model bytes and split-decision audit trails at the PR-7 parity configs
are captured from the pre-refactor tree into tests/golden/strategy_parity/
and every later session re-derives them byte-for-byte.  This module
holds the config matrix and the runner so the capture script and the
test cannot drift apart.

Run ``python tests/strategy_parity_lib.py <outdir>`` to (re)capture.
"""

import hashlib
import json
import os
import sys
import threading

import numpy as np

# the PR-7 audit shape: 15 leaves / min_data_in_leaf=20 / 6 rounds
_BASE = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 20,
         "verbose": -1, "seed": 7}

# name -> extra params for the booster-level configs (all trained with
# lgb.train; hostlearner feature/voting modes run below via LocalGroup)
BOOSTER_CONFIGS = {
    "bagging": {"bagging_fraction": 0.7, "bagging_freq": 1,
                "bagging_seed": 3},
    # learning_rate 0.5 -> GOSS's 1/lr warmup ends at round 2, so the
    # top-k/other-k sampling really runs inside the 6-round window
    "goss": {"boosting": "goss", "top_rate": 0.3, "other_rate": 0.2,
             "learning_rate": 0.5},
    "sharded": {"tree_learner": "data"},
    "ooc": {"out_of_core": "true", "ooc_chunk_rows": 512},
}

ROUNDS = 6


def _data(seed=11, n=1200, f=8):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + X[:, 1] > 0).astype(float)
    return X, y


def run_booster_config(name, audit_path):
    """Train one named config with the audit trail armed; returns
    (model_string, audit_bytes)."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs.audit import audit

    params = dict(_BASE)
    params.update(BOOSTER_CONFIGS[name])
    os.environ["LIGHTGBM_TPU_AUDIT"] = audit_path
    X, y = _data()
    try:
        bst = lgb.train(params, lgb.Dataset(X, label=y, params=dict(params)),
                        num_boost_round=ROUNDS, verbose_eval=False)
        model = bst.model_to_string()
    finally:
        audit.close()
        audit.path = None
        os.environ.pop("LIGHTGBM_TPU_AUDIT", None)
    with open(audit_path, "rb") as fh:
        trail = fh.read()
    return model, trail


def run_hostlearner_mode(mode, nproc=2):
    """Grow one tree on an in-process LocalGroup; returns a stable
    digest of rank 0's GrowResult arrays."""
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.ops.grow import GrowParams
    from lightgbm_tpu.ops.split import FeatureMeta, SplitHyper
    from lightgbm_tpu.parallel import HostParallelLearner, LocalGroup

    rng = np.random.default_rng(5)
    n, f, B = 2000, 24, 16
    bins = rng.integers(0, B, size=(n, f)).astype(np.uint8)
    grad = rng.normal(size=n).astype(np.float32)
    hess = np.ones(n, np.float32)
    meta = FeatureMeta(jnp.full((f,), B, jnp.int32),
                       jnp.zeros((f,), jnp.int32), jnp.zeros((f,), bool))
    hyper = SplitHyper(jnp.float32(0.0), jnp.float32(0.1), jnp.float32(20.0),
                       jnp.float32(1e-3), jnp.float32(0.0))
    params = GrowParams(num_leaves=15, num_bins=B,
                        top_k=f if mode == "voting" else 20)
    fmask = jnp.ones((f,), jnp.float32)
    rows = np.array_split(np.arange(n), nproc)
    grp = LocalGroup(nproc)
    out = [None] * nproc
    errs = []

    def worker(r, comm):
        try:
            idx = rows[r]
            learner = HostParallelLearner(mode, comm, params)
            gr = learner.grow(jnp.asarray(bins[idx]), jnp.asarray(grad[idx]),
                              jnp.asarray(hess[idx]),
                              jnp.ones((len(idx),), jnp.float32),
                              fmask, meta, hyper)
            out[r] = jax.tree_util.tree_map(np.asarray, gr)
        except BaseException as e:
            errs.append((r, e))

    ts = [threading.Thread(target=worker, args=(r, c))
          for r, c in enumerate(grp.comms())]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    if errs:
        raise errs[0][1]
    h = hashlib.sha256()
    for name, arr in zip(out[0]._fields, out[0]):
        h.update(name.encode())
        h.update(np.ascontiguousarray(np.asarray(arr)).tobytes())
    return h.hexdigest()


def capture(outdir):
    os.makedirs(outdir, exist_ok=True)
    digests = {}
    for name in BOOSTER_CONFIGS:
        audit_path = os.path.join(outdir, f"{name}.audit.jsonl")
        model, trail = run_booster_config(name, audit_path)
        with open(os.path.join(outdir, f"{name}.model.txt"), "w") as fh:
            fh.write(model)
        digests[name] = {
            "model_sha256": hashlib.sha256(model.encode()).hexdigest(),
            "audit_sha256": hashlib.sha256(trail).hexdigest(),
        }
    for mode in ("feature", "voting"):
        digests[f"hostlearner_{mode}"] = {
            "grow_sha256": run_hostlearner_mode(mode)}
    with open(os.path.join(outdir, "digests.json"), "w") as fh:
        json.dump(digests, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return digests


if __name__ == "__main__":
    out = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(__file__), "golden", "strategy_parity")
    print(json.dumps(capture(out), indent=2, sort_keys=True))
