"""Piecewise-linear leaf tests (tree/linear.py — the LeafFit plug-in).

Covers: the batched ridge fit and its degenerate-leaf fallback, model
text round-trips, checkpoint pack/unpack, the v3 serving artifact
(bit-exact bucketed serving, zero-new-compile same-shape swaps, the
quantized-serving decline), out-of-core streamed fits, the audit
trail's leaf-model records, and the config surface's actionable fatals.
"""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.utils.log import LightGBMError


def _linear_problem(seed=0, n=2000, f=6):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = 1.0 * X[:, 0] - 0.7 * X[:, 1] + 0.3 * X[:, 2] + 0.05 * rng.randn(n)
    return X, y


def _train(X, y, rounds=15, **extra):
    params = dict(objective="regression", num_leaves=15,
                  min_data_in_leaf=20, learning_rate=0.1, verbose=-1,
                  seed=7)
    params.update(extra)
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=rounds, verbose_eval=False)


# ----------------------------------------------------------------------
# fit quality + structure
# ----------------------------------------------------------------------
def test_linear_beats_const_on_linear_target():
    X, y = _linear_problem()
    Xv, yv = _linear_problem(seed=1, n=700)
    b0 = _train(X, y)
    b1 = _train(X, y, linear_tree=True, linear_lambda=0.01)
    mse0 = float(np.mean((b0.predict(Xv) - yv) ** 2))
    mse1 = float(np.mean((b1.predict(Xv) - yv) ** 2))
    assert mse1 < mse0, (mse1, mse0)
    # models[0] is the boost-from-average constant; every grown tree
    # after it must carry leaf models
    models = [t for t in b1.boosting.models[1:] if t.num_leaves > 1]
    assert models and all(t.is_linear for t in models)
    assert any(t.leaf_is_linear[: t.num_leaves].any() for t in models)


def test_linear_trees_alias():
    X, y = _linear_problem(n=600)
    b = _train(X, y, rounds=3, linear_trees=True)
    assert any(getattr(t, "is_linear", False) for t in b.boosting.models)


def test_solve_degenerate_leaves_fall_back():
    """Leaves with no valid features, too few rows, or a non-PD normal
    matrix must be flagged for the constant fallback."""
    from lightgbm_tpu.tree.linear import solve_linear_leaves

    L, k1 = 4, 3
    a = np.zeros((L, k1, k1), np.float32)
    b = np.zeros((L, k1), np.float32)
    fv = np.zeros((L, k1 - 1), np.float32)
    # leaf 0: healthy 1-feature fit over 50 rows
    fv[0, 0] = 1.0
    a[0] = np.diag([50.0, 10.0, 0.0]).astype(np.float32)
    b[0] = [5.0, -2.0, 0.0]
    # leaf 1: no valid features; leaf 2: too few rows; leaf 3: zero A
    fv[2, :] = 1.0
    fv[3, 0] = 1.0
    cnt = np.asarray([50.0, 50.0, 2.0, 50.0], np.float32)
    w, ok = solve_linear_leaves(jnp.asarray(a), jnp.asarray(b),
                                jnp.asarray(fv), jnp.asarray(cnt),
                                jnp.float32(0.0), jnp.float32(0.0))
    ok = np.asarray(ok)
    w = np.asarray(w)
    assert ok[0] and not ok[1] and not ok[2]
    np.testing.assert_allclose(w[0, :2], [-0.1, 0.2], atol=1e-6)
    np.testing.assert_array_equal(w[1], 0.0)
    np.testing.assert_array_equal(w[2], 0.0)


# ----------------------------------------------------------------------
# round trips
# ----------------------------------------------------------------------
def test_text_roundtrip_exact():
    X, y = _linear_problem(n=900)
    b = _train(X, y, rounds=6, linear_tree=True)
    s = b.model_to_string()
    assert "is_linear=1" in s
    b2 = lgb.Booster(model_str=s)
    Xq = np.random.RandomState(3).randn(200, X.shape[1])
    np.testing.assert_array_equal(b.predict(Xq), b2.predict(Xq))


def test_checkpoint_pack_roundtrip():
    from lightgbm_tpu.ckpt.state import pack_trees, unpack_trees

    X, y = _linear_problem(n=900)
    b = _train(X, y, rounds=5, linear_tree=True)
    models = b.boosting.models
    back = unpack_trees(pack_trees(models))
    Xq = np.asarray(np.random.RandomState(4).randn(150, X.shape[1]),
                    np.float64)
    p0 = sum(t.predict(Xq) for t in models)
    p1 = sum(t.predict(Xq) for t in back)
    np.testing.assert_array_equal(np.asarray(p0), np.asarray(p1))


def test_constant_checkpoint_keys_unchanged():
    """Constant-tree checkpoints must not grow linear keys (container
    bit-compat with pre-strategy checkpoints)."""
    from lightgbm_tpu.ckpt.state import pack_trees

    X, y = _linear_problem(n=600)
    b = _train(X, y, rounds=3)
    keys = set(pack_trees(b.boosting.models))
    assert not any(k.startswith("tree_leaf_feat") or k == "tree_is_linear"
                   for k in keys)


# ----------------------------------------------------------------------
# v3 serving artifact
# ----------------------------------------------------------------------
def test_v3_artifact_serves_bit_exact_and_swaps_free(tmp_path):
    from lightgbm_tpu.obs import compilewatch
    from lightgbm_tpu.serve.artifact import (PackedPredictor,
                                             PredictorArtifact)

    X, y = _linear_problem(n=1200)
    b = _train(X, y, rounds=8, linear_tree=True)
    art = PredictorArtifact.from_booster(b)
    assert art.meta["format_version"] == 3
    assert art.flavor == "linear"
    p = str(tmp_path / "m.npz")
    art.save(p)
    pp = PackedPredictor(PredictorArtifact.load(p))
    Xq = np.asarray(np.random.RandomState(5).randn(257, X.shape[1]),
                    np.float64)
    got = pp.raw.predict_raw_scores(Xq)
    want = b.predict(Xq, raw_score=True)
    np.testing.assert_allclose(got[0], want, atol=1e-6)
    # same-shape retrain swap: zero new compiles through the bucket cache
    b2 = _train(X, y, rounds=8, linear_tree=True, seed=11)
    art2 = PredictorArtifact.from_booster(b2)
    c0 = compilewatch.total_compiles()
    pp2 = PackedPredictor(art2)
    pp2.raw.predict_raw_scores(Xq)
    assert compilewatch.total_compiles() == c0


def test_v3_artifact_declines_quantization():
    from lightgbm_tpu.serve.artifact import PredictorArtifact

    X, y = _linear_problem(n=800)
    b = _train(X, y, rounds=4, linear_tree=True)
    art = PredictorArtifact.from_booster(b)
    with pytest.raises(LightGBMError, match="linear"):
        art.quantize()


def test_constant_artifact_stays_v1():
    from lightgbm_tpu.serve.artifact import PredictorArtifact

    X, y = _linear_problem(n=600)
    b = _train(X, y, rounds=3)
    art = PredictorArtifact.from_booster(b)
    assert art.meta["format_version"] == 1
    assert not hasattr(art.arrays, "leaf_coeff")


# ----------------------------------------------------------------------
# out-of-core streamed fit
# ----------------------------------------------------------------------
def test_ooc_linear_training_close_to_resident():
    """Streamed (A, b) folds run over the chunk grid instead of the
    resident row blocks — the f32 add order differs (documented drift,
    docs/TREES.md), so the check is closeness, not bit-parity."""
    X, y = _linear_problem(n=1600)
    b0 = _train(X, y, rounds=6, linear_tree=True)
    b1 = _train(X, y, rounds=6, linear_tree=True, out_of_core="true",
                ooc_chunk_rows=512)
    Xq = np.random.RandomState(6).randn(300, X.shape[1])
    np.testing.assert_allclose(b0.predict(Xq), b1.predict(Xq), atol=1e-4)


# ----------------------------------------------------------------------
# audit trail
# ----------------------------------------------------------------------
def test_audit_records_leaf_models(tmp_path):
    from lightgbm_tpu.obs.audit import audit

    X, y = _linear_problem(n=900)
    path = str(tmp_path / "trail.jsonl")
    os.environ["LIGHTGBM_TPU_AUDIT"] = path
    try:
        _train(X, y, rounds=3, linear_tree=True)
    finally:
        audit.close()
        audit.path = None
        os.environ.pop("LIGHTGBM_TPU_AUDIT", None)
    trees = [json.loads(line) for line in open(path)
             if json.loads(line).get("ev") == "tree"]
    assert trees
    lin = [t for t in trees if t.get("leaf_model") == "linear"]
    assert lin, "no linear leaf-model records in the audit trail"
    rec = lin[0]
    assert len(rec["coeff"]) == rec["leaves"]
    assert len(rec["const"]) == rec["leaves"]
    assert any(rec["linear_leaves"])


# ----------------------------------------------------------------------
# config surface
# ----------------------------------------------------------------------
@pytest.mark.parametrize("bad", [
    {"linear_tree": True, "quantized_training": True},
    {"linear_tree": True, "boosting": "dart"},
    {"linear_lambda": -1.0},
])
def test_config_fatals(bad):
    X, y = _linear_problem(n=400)
    params = dict(objective="regression", num_leaves=7, verbose=-1, **bad)
    with pytest.raises(LightGBMError):
        lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=1,
                  verbose_eval=False)
