"""Worker for the wide-data distributed learner parity tests (run via
subprocess).  Each process: CPU platform with 4 virtual devices, rank and
world size from argv, jax.distributed over localhost.

Modes:
  serial   -- single process, tree_learner=serial on the full data; the
              byte-identity REFERENCE.  It must run under the same
              XLA_FLAGS as the parallel workers: XLA:CPU partitions its
              thread pool by device count and f32 matmul accumulation
              order follows it, so histograms are only bitwise
              reproducible within one environment.
  feature  -- rows REPLICATED on every rank, columns sharded inside the
              learner; full lgb.train; rank 0 writes the model string.
  voting   -- rows pre-partitioned; tree_learner=voting with top_k=F
              (2k >= F, exact data-parallel recovery).
  datahost -- rows pre-partitioned; tree_learner=data over the
              host-driven learner (same shards as voting mode).
"""

import os
import sys

rank = int(sys.argv[1])
port = sys.argv[2]
out = sys.argv[3]
mode = sys.argv[4]
nproc = int(sys.argv[5]) if len(sys.argv) > 5 else 2

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
)
if mode != "serial":
    os.environ["LIGHTGBM_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
    os.environ["LIGHTGBM_TPU_NUM_PROCESSES"] = str(nproc)
    os.environ["LIGHTGBM_TPU_PROCESS_ID"] = str(rank)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

if mode != "serial":
    from lightgbm_tpu.parallel.distributed import ensure_initialized

    assert ensure_initialized() is True
import jax  # noqa: E402

# the axon TPU plugin ignores JAX_PLATFORMS; the config knob still wins
jax.config.update("jax_platforms", "cpu")

if mode != "serial":
    assert jax.process_count() == nproc, jax.process_count()

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.parallel import HostParallelLearner  # noqa: E402

# integer features -> distributed find-bin mappers are bit-identical to
# the single-process mappers, so model strings can be compared bytewise
rng = np.random.default_rng(29)
N, F = 3000, 30
X = rng.integers(0, 12, size=(N, F)).astype(np.float32)
wv = rng.standard_normal(F)
yp = 1.0 / (1.0 + np.exp(-((X - 6) @ wv * 0.1)))
y = (rng.random(N) < yp).astype(np.float32)

# boost_from_average off everywhere: the distributed label average is an
# allreduce of per-rank partials, which rounds differently from the
# single-process mean even on replicated data
base = dict(objective="binary", boost_from_average=False, num_leaves=15,
            learning_rate=0.2, max_bin=31, min_data_in_leaf=20, verbose=-1)

if mode == "serial":
    p = dict(base, tree_learner="serial")
    ds = lgb.Dataset(X, label=y, params=dict(p))
elif mode == "feature":
    # every rank sees the full matrix; the learner shards its columns
    p = dict(base, tree_learner="feature", num_machines=nproc)
    ds = lgb.Dataset(X, label=y, params=dict(p))
else:
    # unequal row shards via pre_partition
    cuts = [0] + [N * (r + 1) // nproc + (7 if r == 0 else 0)
                  for r in range(nproc - 1)] + [N]
    sl = slice(cuts[rank], cuts[rank + 1])
    learner = "voting" if mode == "voting" else "data"
    p = dict(base, tree_learner=learner, num_machines=nproc,
             pre_partition=True, top_k=F)
    ds = lgb.Dataset(X[sl], label=y[sl], params=dict(p))

bst = lgb.train(p, ds, 4, verbose_eval=False)

if mode != "serial":
    want = {"feature": "feature", "voting": "voting", "datahost": "data"}[mode]
    learner_obj = bst.boosting.learner
    assert isinstance(learner_obj, HostParallelLearner), type(learner_obj)
    assert learner_obj.mode == want, learner_obj.mode
    assert learner_obj.comm.ledger_total() > 0

if rank == 0:
    with open(out, "w") as fh:
        fh.write(bst.model_to_string())
print(f"rank {rank} {mode} done: {bst.num_trees} trees")
sys.exit(0)
