"""Worker for the elastic-training matrix (test_ckpt_fault.py topology
legs, test_rebalance.py, bench.py's ``elastic`` section).

argv: ``rank nproc port out mode ckdir``.  Every rank of one phase runs
this script; the parent varies ``nproc`` between phases — that is the
whole point: a checkpoint written at one world size is resumed at
another through the canonical global layout (ckpt/state.py,
docs/CHECKPOINT.md).

The global dataset is generated IDENTICALLY on every rank from a fixed
seed (integer-valued features so the distributed find-bin mappers are
bit-identical regardless of world size) and each rank keeps its
contiguous ``[rank*N/W, (rank+1)*N/W)`` row slice — the pre_partition
contract, so the concatenated shards are byte-for-byte the same global
matrix at every world size and the fingerprint handshake accepts the
resume.

modes:
  train — lgb.train over the host-driven data-parallel learner with a
          shared CheckpointManager; auto-resumes from ``ckdir`` when a
          valid checkpoint exists.  Env knobs (set by the parent):
            ELASTIC_ROWS / ELASTIC_TREES / ELASTIC_FREQ — problem size
            ELASTIC_KILL_ITER=i  — every rank SIGKILLs itself in the
                0-based iteration-``i`` callback (whole-job preemption:
                collectives for iteration i are complete, so nobody is
                left mid-barrier; the freq-boundary checkpoint is
                already durable two iterations back)
            ELASTIC_REBALANCE=1  — arm straggler-aware shard
                rebalancing (config knobs rebalance_*)
            ELASTIC_OBJECTIVE=lambdarank — ranking data: relevance
                labels, query groups, and GROUP-ALIGNED shard edges (a
                query group never spans ranks; rebalance must keep it
                that way via cut-point snapping)
            ELASTIC_QUANTIZED=1 — quantized training (world-invariant
                integer histograms -> byte-identical across worlds)
          plus the standard LIGHTGBM_TPU_FAULT / _FAULT_RANK / _TRACE /
          _AUDIT hooks.  Writes ``out.rankR.json`` (audit fields below)
          and ``out.rankR.txt`` (final model) on clean completion.
"""

import json
import os
import signal
import sys
import time

rank = int(sys.argv[1])
nproc = int(sys.argv[2])
port = sys.argv[3]
out = sys.argv[4]
mode = sys.argv[5]
ckdir = sys.argv[6]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["LIGHTGBM_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
os.environ["LIGHTGBM_TPU_NUM_PROCESSES"] = str(nproc)
os.environ["LIGHTGBM_TPU_PROCESS_ID"] = str(rank)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from lightgbm_tpu.parallel import net  # noqa: E402
from lightgbm_tpu.parallel.distributed import ensure_initialized  # noqa: E402

assert ensure_initialized() is (nproc > 1)  # world 1 = serial reference
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.process_count() == nproc

import numpy as np  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.ckpt import CheckpointManager  # noqa: E402
from lightgbm_tpu.ckpt.store import CheckpointStore  # noqa: E402
from lightgbm_tpu.cli import EXIT_PEER_FAILURE  # noqa: E402

N = int(os.environ.get("ELASTIC_ROWS", "1024"))
TREES = int(os.environ.get("ELASTIC_TREES", "16"))
FREQ = int(os.environ.get("ELASTIC_FREQ", "4"))
KILL_ITER = int(os.environ.get("ELASTIC_KILL_ITER", "-1"))
REBALANCE = os.environ.get("ELASTIC_REBALANCE", "0") == "1"
LEAVES = int(os.environ.get("ELASTIC_LEAVES", "15"))
OBJECTIVE = os.environ.get("ELASTIC_OBJECTIVE", "binary")
QUANTIZED = os.environ.get("ELASTIC_QUANTIZED", "0") == "1"


def _write(payload: dict) -> None:
    with open(out + f".rank{rank}.json", "w") as fh:
        json.dump(payload, fh)


def make_data(n):
    """The GLOBAL dataset, identical on every rank.  Few-valued integer
    features (5 distinct values) so EVERY shard of every world size sees
    the full value set and the locally-computed bin mappers — and hence
    the binned bytes the elastic fingerprint handshake covers — are
    bit-identical at any world."""
    rng = np.random.default_rng(42)
    F = 10
    X = rng.integers(0, 5, size=(n, F)).astype(np.float32)
    w = rng.standard_normal(F)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-((X - 2.0) @ w * 0.35)))
         ).astype(np.float32)
    return X, y


def make_rank_data(n):
    """Ranking data, identical on every rank: variable-size query groups
    (8..24 rows) with relevance 0..3 assigned by within-group score
    rank.  Returns (X, y, group_sizes)."""
    rng = np.random.default_rng(43)
    F = 10
    X = rng.integers(0, 5, size=(n, F)).astype(np.float32)
    sizes = []
    while sum(sizes) < n - 24:
        sizes.append(int(rng.integers(8, 25)))
    sizes.append(n - sum(sizes))
    w = rng.standard_normal(F)
    score = (X - 2.0) @ w * 0.3 + rng.standard_normal(n) * 0.5
    y = np.zeros(n, np.float32)
    off = 0
    for s in sizes:
        order = score[off:off + s].argsort().argsort()
        y[off:off + s] = np.minimum(3, (order * 4) // s)
        off += s
    return X, y, np.asarray(sizes, np.int64)


if mode != "train":
    print(f"unknown mode {mode}")
    sys.exit(2)

group_cum = None
if OBJECTIVE == "lambdarank":
    X, y, group_sizes = make_rank_data(N)
    group_cum = np.concatenate([[0], np.cumsum(group_sizes)])
    # pre_partition contract for ranking: every shard edge IS a group
    # boundary — each rank snaps the ideal even split to the nearest
    # cumulative boundary (identical arithmetic on every rank)
    lo = int(group_cum[np.abs(group_cum - rank * N // nproc).argmin()])
    hi = int(group_cum[np.abs(group_cum - (rank + 1) * N // nproc).argmin()])
    local_sizes = np.diff(group_cum[(group_cum >= lo) & (group_cum <= hi)])
else:
    X, y = make_data(N)
    lo, hi = rank * N // nproc, (rank + 1) * N // nproc
    local_sizes = None
p = dict(objective=OBJECTIVE, tree_learner="data", num_machines=nproc,
         pre_partition=True, num_leaves=LEAVES, learning_rate=0.2,
         max_bin=31, min_data_in_leaf=20, verbose=-1)
if QUANTIZED:
    p.update(quantized_training=True, seed=7)
if REBALANCE:
    p.update(rebalance=True, rebalance_threshold=1.5, rebalance_patience=3,
             rebalance_max_move_frac=float(
                 os.environ.get("ELASTIC_MOVE_FRAC", "0.25")))
ds = lgb.Dataset(X[lo:hi], label=y[lo:hi], group=local_sizes,
                 params=dict(p))

latest = CheckpointStore(ckdir).latest_valid()
resume_from = latest[0] if latest is not None else None

it_marks = []


def _clock(env):
    it_marks.append((env.iteration, time.perf_counter()))


_clock.order = 90


def _kill(env):
    if KILL_ITER >= 0 and env.iteration >= KILL_ITER:
        # whole-job preemption: iteration KILL_ITER's collectives are
        # complete on every rank before any after-iteration callback
        # runs, so every rank reaches this line and dies here
        os.kill(os.getpid(), signal.SIGKILL)


_kill.order = 100  # after the CheckpointManager (order 40)

mgr = CheckpointManager(ckdir, freq=FREQ)
booster = None
try:
    booster = lgb.train(dict(p), ds, TREES, verbose_eval=False,
                        checkpoint_manager=mgr, callbacks=[_clock, _kill])
except net.PeerFailureError as e:
    mgr.flush()
    _write({"error": "PeerFailureError", "ranks": list(e.ranks),
            "resume_from": resume_from})
    print(f"rank {rank} detected peer failure after {e.elapsed_s:.1f}s")
    net.hard_exit(EXIT_PEER_FAILURE)
mgr.close()

it_times = [round(b - a, 6)
            for (_, a), (_, b) in zip(it_marks, it_marks[1:])]
reb = getattr(booster.boosting, "_rebalance", None)
final_counts = list(reb["plan"].counts) if reb else None
_qb = booster.boosting.train_set.metadata.query_boundaries
group_aligned = None
if group_cum is not None and reb:
    edges = set(int(g) for g in group_cum)
    group_aligned = all(int(s) in edges
                        for s in reb["plan"].starts) and reb["plan"].total in edges
with open(out + f".rank{rank}.txt", "w") as fh:
    fh.write(booster.model_to_string())
_write({
    "error": None,
    "resume_from": resume_from,
    "trees": booster.num_trees,
    "iters": booster.current_iteration(),
    "world": nproc,
    "rows": [lo, hi],
    "rows_end": int(booster.boosting.num_data),
    "final_counts": final_counts,
    "group_aligned": group_aligned,
    "n_local_groups": (None if _qb is None else int(len(_qb) - 1)),
    "it_times": it_times,
})
print(f"rank {rank} train done (world={nproc}, resume_from={resume_from})")
sys.exit(0)
