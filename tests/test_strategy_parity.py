"""Strategy-refactor invisibility pin (the tentpole contract).

The composable trainer core (lightgbm_tpu/tree/strategy.py) rewired
every learner through the SplitGain/LeafFit/HistAccum/StateExport seams.
These tests re-train the PR-7 parity configs and require the model bytes
AND the split-decision audit trails to match the pre-refactor goldens
captured in tests/golden/strategy_parity/ byte for byte — plus a
``report diff`` run over the audit streams returning rc 0 (identical).

Regenerate goldens (only when behaviour is INTENTIONALLY changed):
``python tests/strategy_parity_lib.py``.
"""

import hashlib
import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(__file__))
import strategy_parity_lib as lib  # noqa: E402

GOLDEN = os.path.join(os.path.dirname(__file__), "golden", "strategy_parity")


def _digests():
    with open(os.path.join(GOLDEN, "digests.json")) as fh:
        return json.load(fh)


@pytest.mark.parametrize("name", sorted(lib.BOOSTER_CONFIGS))
def test_booster_config_parity(name, tmp_path):
    audit_path = str(tmp_path / f"{name}.audit.jsonl")
    model, trail = lib.run_booster_config(name, audit_path)
    want = _digests()[name]
    assert hashlib.sha256(model.encode()).hexdigest() == \
        want["model_sha256"], f"{name}: model bytes drifted vs pre-refactor"
    assert hashlib.sha256(trail).hexdigest() == want["audit_sha256"], \
        f"{name}: split-decision audit trail drifted vs pre-refactor"
    # the user-facing check the issue names: `report diff` over the
    # golden trail and this run's trail must say identical (rc 0)
    proc = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", "report", "diff",
         os.path.join(GOLDEN, f"{name}.audit.jsonl"), audit_path],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, (
        f"report diff found divergence for {name}:\n{proc.stdout}"
        f"{proc.stderr}")


@pytest.mark.parametrize("mode", ["feature", "voting"])
def test_hostlearner_parity(mode):
    got = lib.run_hostlearner_mode(mode)
    assert got == _digests()[f"hostlearner_{mode}"]["grow_sha256"], \
        f"hostlearner {mode}: GrowResult bytes drifted vs pre-refactor"


def test_model_bytes_match_golden_files():
    """The stored .model.txt goldens themselves hash to the digests —
    guards against hand-edits of one without the other."""
    d = _digests()
    for name in lib.BOOSTER_CONFIGS:
        with open(os.path.join(GOLDEN, f"{name}.model.txt")) as fh:
            model = fh.read()
        assert hashlib.sha256(model.encode()).hexdigest() == \
            d[name]["model_sha256"]
