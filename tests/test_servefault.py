"""Serving gray-failure drills (docs/ROBUSTNESS.md serving table).

Unit legs: the ``LIGHTGBM_TPU_SERVE_FAULT`` grammar, the latency-outlier
circuit breaker state machine, deadline propagation (header shrinks hop
by hop; a spent budget 504s before any device work), hedged requests
(rescue + budget), proxy overload shed with ``Retry-After``, the canary
connection-failure ejection, the 503 re-route tried-set bound, and
registry-staleness surfacing plus the factory's refusal to promote
against a stale fleet.

Chaos leg (tier-1, ``servefault`` marker): a 3-replica fleet under live
closed-loop traffic takes one hung replica, one delay-injected replica,
and one SIGKILL at once — zero dropped, zero mis-versioned responses,
bounded client p99, and the breaker observed OPEN then restored
HALF_OPEN -> CLOSED once the fault clears.  The sustained flap matrix is
additionally marked slow.
"""

import json
import signal
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import compilewatch
from lightgbm_tpu.obs.metrics import RollingQuantile
from lightgbm_tpu.serve import (
    FleetProxy,
    ModelRegistry,
    PackedPredictor,
    PredictorArtifact,
)
from lightgbm_tpu.serve import breaker as breaker_mod
from lightgbm_tpu.serve import faults
from lightgbm_tpu.serve.batcher import MicroBatcher, RequestTimeout


@pytest.fixture(scope="module")
def binary_booster():
    rng = np.random.RandomState(3)
    X = rng.randn(600, 12)
    y = (X[:, 0] + 0.5 * X[:, 1] - X[:, 2] ** 2 > -0.5).astype(np.float32)
    ds = lgb.Dataset(X, label=y, params={"min_data_in_leaf": 5})
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "verbose": -1},
        ds, num_boost_round=12, verbose_eval=False,
    )
    return bst, X


@pytest.fixture(autouse=True)
def _clear_fault_spec():
    """In-process tests share the faults module's globals with any
    in-process server — always leave the spec disarmed."""
    faults.set_spec("")
    yield
    faults.set_spec("")


# ----------------------------------------------------------------------
# fault-spec grammar (serve/faults.py)
# ----------------------------------------------------------------------
class TestFaultSpecGrammar:
    def test_parse_clauses(self):
        assert faults.parse_serve_fault_spec("hang:3") == [("hang", 3)]
        assert faults.parse_serve_fault_spec("error:1") == [("error", 1)]
        assert faults.parse_serve_fault_spec("delay:250") == \
            [("delay", 250.0, 1.0)]
        assert faults.parse_serve_fault_spec("delay:250:0.25") == \
            [("delay", 250.0, 0.25)]
        assert faults.parse_serve_fault_spec("flap:1.5") == [("flap", 1.5)]
        assert faults.parse_serve_fault_spec("delay:10:0.5,hang:9") == \
            [("delay", 10.0, 0.5), ("hang", 9)]
        assert faults.parse_serve_fault_spec("") == []
        assert faults.parse_serve_fault_spec(None) == []

    @pytest.mark.parametrize("bad", [
        "hang", "hang:x", "error:one", "delay:-5", "delay:10:0",
        "delay:10:1.5", "flap:0", "flap:-1", "bogus:1", "hang:1:2",
    ])
    def test_bad_specs_raise(self, bad):
        with pytest.raises(ValueError):
            faults.parse_serve_fault_spec(bad)

    def test_bad_env_spec_warns_and_stays_off(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_VAR, "bogus:spec")
        faults.refresh_from_env()
        assert faults.counters()["spec"] == ""
        assert faults.action() is None

    def test_error_clause_fires_from_nth_request(self):
        faults.set_spec("error:3")
        assert faults.action() is None
        assert faults.action() is None
        assert faults.action() == ("error",)
        assert faults.action() == ("error",)
        c = faults.counters()
        assert c["spec"] == "error:3"
        assert c["requests_seen"] == 4
        assert c["injected"] == {"error": 2}

    def test_delay_fraction_is_deterministic(self):
        faults.set_spec("delay:40:0.5")
        fired = [faults.action() for _ in range(10)]
        hits = [a for a in fired if a is not None]
        assert len(hits) == 5  # exactly frac of requests, no RNG
        assert all(a == ("delay", 40.0) for a in hits)

    def test_clear_disarms(self):
        faults.set_spec("error:1")
        assert faults.action() == ("error",)
        assert faults.set_spec("") == ""
        assert faults.action() is None
        assert faults.counters()["requests_seen"] == 0

    def test_flap_alternates_on_wall_clock(self):
        faults.set_spec("flap:0.3")
        assert faults.action() == ("hang",)  # hang phase first
        time.sleep(0.35)
        assert faults.action() is None  # healthy phase
        time.sleep(0.35)
        assert faults.action() == ("hang",)


# ----------------------------------------------------------------------
# rolling p95 window (obs/metrics.py) — the adaptive hedge trigger
# ----------------------------------------------------------------------
class TestRollingQuantile:
    def test_quantiles_and_window(self):
        rq = RollingQuantile(window=100)
        assert rq.quantile(0.95) == 0.0  # empty
        for v in range(1, 101):
            rq.observe(float(v))
        assert rq.count() == 100
        assert rq.quantile(0.0) == 1.0
        assert rq.quantile(0.95) == 96.0
        for _ in range(100):  # old values roll out of the window
            rq.observe(1000.0)
        assert rq.quantile(0.5) == 1000.0


# ----------------------------------------------------------------------
# latency-outlier circuit breaker (serve/breaker.py)
# ----------------------------------------------------------------------
class TestLatencyBreaker:
    def test_opens_on_latency_outlier_vs_fleet_median(self):
        b = breaker_mod.LatencyBreaker(k=3.0, m=3, open_s=60.0)
        for addr in ("a", "b", "c"):
            for _ in range(4):
                assert b.observe(addr, 0.01, ok=True) is None
        # one backend drifts to 100x the fleet median: m hot obs trip it
        assert b.observe("d", 1.0, ok=True) is None
        assert b.observe("d", 1.0, ok=True) is None
        assert b.observe("d", 1.0, ok=True) == "open"
        assert b.state("d") == breaker_mod.OPEN
        assert b.open_count() == 1
        assert b.state("a") == breaker_mod.CLOSED

    def test_opens_on_consecutive_errors(self):
        b = breaker_mod.LatencyBreaker(k=3.0, m=2, open_s=60.0)
        assert b.observe("x", 0.01, ok=False) is None
        assert b.observe("x", 0.01, ok=False) == "open"
        assert b.snapshot()["x"]["opens"] == 1

    def test_half_open_probe_close_and_reopen(self):
        b = breaker_mod.LatencyBreaker(k=3.0, m=2, open_s=0.05)
        for addr in ("a", "b", "c"):
            b.observe(addr, 0.01, ok=True)
        b.observe("x", 0.01, ok=False)
        assert b.observe("x", 0.01, ok=False) == "open"
        assert not b.trial_eligible("x")  # cooldown not yet served
        time.sleep(0.07)
        assert b.trial_eligible("x")
        b.begin_attempt("x")
        assert b.state("x") == breaker_mod.HALF_OPEN
        assert not b.trial_eligible("x")  # single trial slot claimed
        # good probe closes — judged on the probe's own latency, not the
        # failure-poisoned EWMA — and re-enters with fresh stats
        assert b.observe("x", 0.012, ok=True) == "close"
        snap = b.snapshot()["x"]
        assert snap["state"] == breaker_mod.CLOSED
        assert snap["ewma_ms"] == pytest.approx(12.0)
        # trip again; a failing probe re-opens for another cooldown
        b.observe("x", 0.01, ok=False)
        assert b.observe("x", 0.01, ok=False) == "open"
        time.sleep(0.07)
        b.begin_attempt("x")
        assert b.observe("x", 0.01, ok=False) == "reopen"
        assert b.state("x") == breaker_mod.OPEN
        assert b.snapshot()["x"]["opens"] == 3

    def test_good_observation_resets_hot_streak(self):
        b = breaker_mod.LatencyBreaker(k=3.0, m=3, open_s=60.0)
        b.observe("x", 0.01, ok=False)
        b.observe("x", 0.01, ok=False)
        b.observe("x", 0.01, ok=True)  # streak broken
        assert b.observe("x", 0.01, ok=False) is None
        assert b.state("x") == breaker_mod.CLOSED


# ----------------------------------------------------------------------
# proxy-side drills against in-process fake backends (no jax)
# ----------------------------------------------------------------------
class _FaultyBackend:
    """Replica double: /readyz 200 always (the gray-failure signature),
    /predict optionally delayed; records every X-Deadline-Ms it sees."""

    def __init__(self, version=1, delay_s=0.0):
        fake = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = b"{}\n"
                self.send_response(200 if self.path == "/readyz" else 404)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(n)
                with fake.lock:
                    fake.deadlines.append(
                        self.headers.get("X-Deadline-Ms"))
                if fake.delay_s > 0:
                    time.sleep(fake.delay_s)
                body = b"0.5\n"
                self.send_response(200)
                self.send_header("X-Model-Version", str(fake.version))
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.version = version
        self.delay_s = delay_s
        self.lock = threading.Lock()
        self.deadlines = []
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self.addr = f"127.0.0.1:{self.port}"
        self._t = threading.Thread(target=self.httpd.serve_forever,
                                   daemon=True)
        self._t.start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _start_proxy(backends, **kw):
    kw.setdefault("health_poll_s", 0.1)
    kw.setdefault("retry_deadline_s", 5.0)
    proxy = FleetProxy(("127.0.0.1", 0), [b.addr for b in backends], **kw)
    t = threading.Thread(target=proxy.serve_forever, daemon=True)
    t.start()
    return proxy, proxy.server_address[1]


def _proxy_predict(port, deadline_ms=None, timeout=30):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict", data=b"[1.0, 2.0]\n")
    if deadline_ms is not None:
        req.add_header("X-Deadline-Ms", str(deadline_ms))
    r = urllib.request.urlopen(req, timeout=timeout)
    return r.status, r.headers.get("X-Model-Version")


def _proxy_stats(port):
    return json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/fleet/stats", timeout=30).read())


class TestDeadlinePropagation:
    def test_budget_forwarded_shrunken_to_backend(self):
        backends = [_FaultyBackend()]
        proxy, port = _start_proxy(backends)
        try:
            status, _ = _proxy_predict(port, deadline_ms=5000)
            assert status == 200
            status, _ = _proxy_predict(port)  # no budget: no header
            assert status == 200
            seen = backends[0].deadlines
            assert len(seen) == 2
            assert seen[0] is not None
            assert 0 < float(seen[0]) <= 5000  # hop subtracted elapsed
            assert seen[1] is None
        finally:
            proxy.shutdown()
            proxy.server_close()
            backends[0].stop()

    def test_spent_budget_is_bounded_504_not_backend_timeout(self):
        """A 200 ms client budget against a 500 ms backend costs ~the
        budget, never the 30 s backend socket timeout."""
        backends = [_FaultyBackend(delay_s=0.5)]
        proxy, port = _start_proxy(backends)
        try:
            t0 = time.monotonic()
            with pytest.raises(urllib.error.HTTPError) as ei:
                _proxy_predict(port, deadline_ms=200)
            elapsed = time.monotonic() - t0
            assert ei.value.code == 504
            assert "deadline" in json.loads(ei.value.read())["error"]
            assert elapsed < 2.0
            assert _proxy_stats(port)["deadline_rejected"] >= 1
        finally:
            proxy.shutdown()
            proxy.server_close()
            backends[0].stop()

    def test_batcher_fails_fast_on_spent_budget(self):
        calls = []

        def predict(batch):
            calls.append(batch.shape[0])
            return batch[:, 0]

        b = MicroBatcher(predict, max_delay_ms=1.0)
        try:
            with pytest.raises(RequestTimeout):
                b.submit(np.ones((2, 3)), timeout_ms=0.0)
            with pytest.raises(RequestTimeout):
                b.submit(np.ones((2, 3)), timeout_ms=-15.0)
            assert b.stats()["timeouts"] == 2
            assert calls == []  # no queue slot, no device work
            assert np.allclose(
                b.submit(np.ones((2, 3)), timeout_ms=500.0), [1.0, 1.0])
        finally:
            b.close()


class TestHedgedRequests:
    def test_hedge_rescues_slow_backend(self):
        slow = _FaultyBackend(version=1, delay_s=0.8)
        fast = _FaultyBackend(version=2)
        proxy, port = _start_proxy([slow, fast], policy="rr",
                                   hedge_delay_ms=50.0,
                                   hedge_budget_pct=100.0)
        try:
            t0 = time.monotonic()
            for _ in range(8):
                status, _ = _proxy_predict(port)
                assert status == 200
            # unhedged, ~half the requests would cost 0.8 s each (>3 s
            # total); the hedge turns a slow first pick into ~50 ms
            assert time.monotonic() - t0 < 3.0
            st = _proxy_stats(port)
            assert st["hedges"]["launched"] >= 1
            assert st["hedges"]["wins"] >= 1
        finally:
            proxy.shutdown()
            proxy.server_close()
            slow.stop()
            fast.stop()

    def test_hedge_never_targets_the_inflight_backend(self):
        """With the slow backend already holding the first attempt, the
        hedge must land on the other backend — a hedge at the stuck
        backend is no hedge at all."""
        slow = _FaultyBackend(version=1, delay_s=0.6)
        fast = _FaultyBackend(version=2)
        proxy, port = _start_proxy([slow, fast], policy="rr",
                                   hedge_delay_ms=40.0,
                                   hedge_budget_pct=100.0)
        try:
            for _ in range(6):
                _proxy_predict(port)
            st = _proxy_stats(port)
            hedged = st["hedges"]["launched"]
            assert hedged >= 1
            # every hedge went to the fast backend and won there
            assert st["hedges"]["wins"] == hedged
        finally:
            proxy.shutdown()
            proxy.server_close()
            slow.stop()
            fast.stop()

    def test_hedge_knobs(self):
        a, b = _FaultyBackend(), _FaultyBackend()
        proxy, _ = _start_proxy([a, b], hedge_delay_ms=-1.0)
        try:
            assert proxy.hedge_delay_s() is None  # negative disables
            proxy.hedge_delay_ms = 75.0
            assert proxy.hedge_delay_s() == pytest.approx(0.075)
            proxy.hedge_delay_ms = 0.0  # adaptive: cold fallback first
            assert proxy.hedge_delay_s() == pytest.approx(0.05)
            for _ in range(40):
                proxy._lat_window.observe(0.2)
            assert proxy.hedge_delay_s() == pytest.approx(0.2)
        finally:
            proxy.shutdown()
            proxy.server_close()
            a.stop()
            b.stop()

    def test_single_backend_fleet_never_hedges(self):
        a = _FaultyBackend()
        proxy, _ = _start_proxy([a], hedge_delay_ms=50.0)
        try:
            assert proxy.hedge_delay_s() is None
        finally:
            proxy.shutdown()
            proxy.server_close()
            a.stop()

    def test_hedge_budget_caps_volume(self):
        a, b = _FaultyBackend(), _FaultyBackend()
        proxy, _ = _start_proxy([a, b], hedge_budget_pct=10.0)
        try:
            # floor: 5 tokens before any traffic, then denied
            grants = [proxy.take_hedge_token() for _ in range(6)]
            assert grants == [True] * 5 + [False]
            proxy._fwd_requests = 1000  # 10% of 1000 = 100 allowed
            assert proxy.take_hedge_token()
            proxy.hedge_budget_pct = 0.0
            assert not proxy.take_hedge_token()  # 0 disables outright
        finally:
            proxy.shutdown()
            proxy.server_close()
            a.stop()
            b.stop()


class TestOverloadControl:
    def test_sheds_with_retry_after_when_saturated(self):
        backend = _FaultyBackend(delay_s=0.4)
        proxy, port = _start_proxy([backend], max_concurrent=1,
                                   max_queue=0, hedge_delay_ms=-1.0)
        try:
            results = []
            lock = threading.Lock()

            def one():
                try:
                    status, _ = _proxy_predict(port)
                    with lock:
                        results.append((status, None))
                except urllib.error.HTTPError as e:
                    with lock:
                        results.append((e.code,
                                        e.headers.get("Retry-After")))

            threads = [threading.Thread(target=one) for _ in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            codes = sorted(r[0] for r in results)
            assert codes[0] == 200  # the admitted request completes
            assert 503 in codes  # the overflow is shed, not queued
            assert all(ra == "1" for code, ra in results if code == 503)
            st = _proxy_stats(port)
            assert st["overload"]["shed"] >= 1
            assert st["overload"]["max_concurrent"] == 1
        finally:
            proxy.shutdown()
            proxy.server_close()
            backend.stop()

    def test_bounded_queue_admits_within_deadline(self):
        backend = _FaultyBackend(delay_s=0.15)
        proxy, port = _start_proxy([backend], max_concurrent=1,
                                   max_queue=4, hedge_delay_ms=-1.0)
        try:
            results = []
            lock = threading.Lock()

            def one():
                status, _ = _proxy_predict(port)
                with lock:
                    results.append(status)

            threads = [threading.Thread(target=one) for _ in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert results == [200, 200, 200]  # queued, not shed
        finally:
            proxy.shutdown()
            proxy.server_close()
            backend.stop()


class TestCanaryEjection:
    def test_dead_canary_is_ejected_not_retimed(self):
        """A canary that stops answering is ejected like a main-pool
        backend: the first failure falls back to the pool, and later
        requests never re-pay the canary connection attempt."""
        main = _FaultyBackend(version=1)
        canary = _FaultyBackend(version=9)
        canary.stop()  # connection refused from now on
        proxy, port = _start_proxy([main])
        try:
            proxy.set_canary(canary.addr, fraction=1.0)
            status, ver = _proxy_predict(port)
            assert (status, ver) == (200, "1")  # pool fallback answered
            assert proxy.canary is not None
            assert not proxy.canary.healthy  # ejected on the failure
            t0 = time.monotonic()
            for _ in range(5):
                status, ver = _proxy_predict(port)
                assert (status, ver) == (200, "1")
            assert time.monotonic() - t0 < 1.0  # no repeated conn cost
        finally:
            proxy.shutdown()
            proxy.server_close()
            main.stop()


class TestTriedSetBound:
    def test_has_untried_counts_tried_set_not_list_length(self):
        a, b = _FaultyBackend(), _FaultyBackend()
        proxy, _ = _start_proxy([a, b])
        try:
            assert proxy.has_untried(set())
            assert proxy.has_untried({a.addr})
            assert not proxy.has_untried({a.addr, b.addr})
            # an ejection mid-request shrinks the healthy list; the
            # bound keyed on the tried set is unaffected by that
            proxy.eject(proxy.backends[0])
            assert not proxy.has_untried({b.addr})
            assert proxy.has_untried({a.addr})
        finally:
            proxy.shutdown()
            proxy.server_close()
            a.stop()
            b.stop()


# ----------------------------------------------------------------------
# replica-side fault injection + deadline + staleness (in-process, jax)
# ----------------------------------------------------------------------
class TestServerFaultPath:
    @pytest.fixture()
    def server(self, binary_booster, tmp_path):
        from lightgbm_tpu.serve.server import make_server

        bst, X = binary_booster
        model = PredictorArtifact.from_booster(bst).save(str(tmp_path / "m"))
        srv = make_server(model, port=0, warmup_max_rows=64,
                          max_delay_ms=1.0,
                          registry_dir=str(tmp_path / "reg"),
                          registry_poll_ms=50.0)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        yield srv, bst, X
        srv.shutdown()
        srv.server_close()

    def _post_rows(self, port, rows, headers=()):
        body = "\n".join(json.dumps(list(map(float, r)))
                         for r in rows).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict", data=body)
        for k, v in headers:
            req.add_header(k, v)
        return urllib.request.urlopen(req, timeout=30)

    def _post_fault(self, port, spec):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/fault",
            data=json.dumps({"spec": spec}).encode())
        return json.loads(urllib.request.urlopen(req, timeout=30).read())

    def test_spent_budget_504s_before_device_work(self, server):
        srv, _, X = server
        port = srv.server_address[1]
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post_rows(port, X[:2], headers=[("X-Deadline-Ms", "0")])
        assert ei.value.code == 504
        assert "deadline" in json.loads(ei.value.read())["error"]
        # a live budget still answers
        r = self._post_rows(port, X[:2],
                            headers=[("X-Deadline-Ms", "5000")])
        assert r.status == 200

    def test_fault_off_byte_identical_and_compile_neutral(self, server):
        """delay faults change timing, never bytes; arming/clearing the
        spec costs zero new XLA compiles on the serving path."""
        srv, _, X = server
        port = srv.server_address[1]
        base = self._post_rows(port, X[:4]).read()
        c0 = compilewatch.total_compiles()
        assert self._post_fault(port, "delay:30")["spec"] == "delay:30"
        t0 = time.monotonic()
        wounded = self._post_rows(port, X[:4]).read()
        assert time.monotonic() - t0 >= 0.03
        assert wounded == base  # byte-identical, just late
        st = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=30).read())
        assert st["fault"]["spec"] == "delay:30"
        assert st["fault"]["injected"]["delay"] >= 1
        assert self._post_fault(port, "")["spec"] == ""
        assert self._post_rows(port, X[:4]).read() == base
        st = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=30).read())
        assert "fault" not in st  # disarmed spec leaves no block
        assert compilewatch.total_compiles() == c0

    def test_error_fault_counts_and_bad_spec_400(self, server):
        srv, _, X = server
        port = srv.server_address[1]
        listing = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/fault", timeout=30).read())
        assert listing["spec"] == ""
        self._post_fault(port, "error:1")
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post_rows(port, X[:2])
        assert ei.value.code == 500
        assert "injected" in json.loads(ei.value.read())["error"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post_fault(port, "bogus:1")
        assert ei.value.code == 400
        self._post_fault(port, "")
        assert self._post_rows(port, X[:2]).status == 200

    def test_registry_staleness_rises_and_recovers(self, server):
        srv, _, _ = server
        port = srv.server_address[1]
        assert srv.registry_stale_seconds() == 0.0
        srv._registry_sync_failed(RuntimeError("disk gone"))
        time.sleep(0.05)
        s1 = srv.registry_stale_seconds()
        assert s1 > 0.0
        time.sleep(0.05)
        assert srv.registry_stale_seconds() > s1  # a clock, not a flag
        st = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=30).read())
        assert st["registry"]["stale_seconds"] > 0.0
        assert st["registry"]["consecutive_failures"] >= 1
        srv._registry_sync_ok()
        assert srv.registry_stale_seconds() == 0.0
        st = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=30).read())
        assert st["registry"]["stale_seconds"] == 0.0
        assert st["registry"]["consecutive_failures"] == 0


# ----------------------------------------------------------------------
# factory refuses to promote against a stale fleet
# ----------------------------------------------------------------------
class _CannedJSON:
    """One-trick HTTP server: canned JSON per path (a fake proxy or a
    fake replica, as seen by the factory's freshness gate)."""

    def __init__(self, pages):
        canned = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                obj = canned.pages.get(self.path)
                body = json.dumps(obj or {}).encode()
                self.send_response(200 if obj is not None else 404)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.pages = pages
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self.addr = f"127.0.0.1:{self.port}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class TestFactoryFleetFreshnessGate:
    def _supervisor(self, tmp_path, proxy):
        from lightgbm_tpu.factory.supervisor import FactorySupervisor

        return FactorySupervisor(
            data_dir=str(tmp_path / "data"),
            workdir=str(tmp_path / "work"),
            registry_dir=str(tmp_path / "reg"),
            proxy=proxy, max_registry_stale_s=30.0)

    def test_refuses_promotion_against_stale_fleet(self, tmp_path):
        replica = _CannedJSON(
            {"/stats": {"registry": {"stale_seconds": 120.0}}})
        proxy = _CannedJSON({"/fleet/stats": {"backends": [
            {"addr": replica.addr, "healthy": True}]}})
        try:
            sup = self._supervisor(tmp_path, proxy.addr)
            ok, detail = sup._fleet_fresh()
            assert not ok
            fl = detail["fleet"]
            assert "staleness" in fl["reason"]
            assert fl["stale_backends"] == {replica.addr: 120.0}
            assert fl["max_stale_s"] == 120.0
        finally:
            replica.stop()
            proxy.stop()

    def test_fresh_fleet_passes(self, tmp_path):
        replica = _CannedJSON(
            {"/stats": {"registry": {"stale_seconds": 0.0}}})
        proxy = _CannedJSON({"/fleet/stats": {"backends": [
            {"addr": replica.addr, "healthy": True},
            {"addr": "127.0.0.1:9", "healthy": False},  # prober's problem
        ]}})
        try:
            sup = self._supervisor(tmp_path, proxy.addr)
            ok, detail = sup._fleet_fresh()
            assert ok
            assert detail["fleet"]["max_stale_s"] == 0.0
        finally:
            replica.stop()
            proxy.stop()

    def test_unreadable_proxy_refuses(self, tmp_path):
        sup = self._supervisor(tmp_path, "127.0.0.1:9")  # nothing there
        ok, detail = sup._fleet_fresh()
        assert not ok
        assert "cannot read fleet stats" in detail["fleet"]["reason"]


# ----------------------------------------------------------------------
# the chaos harness: a wounded fleet under live closed-loop traffic
# ----------------------------------------------------------------------
def _spawn_fleet(registry_dir, n):
    from lightgbm_tpu.serve.fleet import _wait_ready, spawn_replicas

    procs = spawn_replicas(n, {
        "registry": registry_dir,
        "warmup_max_rows": "64",
        "max_delay_ms": "1",
        "registry_poll_ms": "100",
    })
    try:
        for _, port in procs:
            assert _wait_ready("127.0.0.1", port, 120.0), \
                f"replica on port {port} never became ready"
    except BaseException:
        for p, _ in procs:
            p.kill()
        raise
    return procs


def _arm_fault(port, spec):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/fault",
        data=json.dumps({"spec": spec}).encode())
    reply = json.loads(urllib.request.urlopen(req, timeout=30).read())
    assert reply["spec"] == spec


def _deadline_loop(port, rows, expected, duration_s, n_threads=4,
                   deadline_ms=8000):
    """Closed-loop traffic with an X-Deadline-Ms budget on every
    request; every reply must be 200 and stamped with exactly one KNOWN
    version whose predictions it matches."""
    body = "\n".join(json.dumps(list(map(float, r))) for r in rows).encode()
    stop = time.monotonic() + duration_s
    lock = threading.Lock()
    stats = {"n": 0, "errors": [], "versions": set(), "lat": []}

    def worker():
        while time.monotonic() < stop:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/predict?model_version=1",
                data=body)
            req.add_header("X-Deadline-Ms", str(deadline_ms))
            t0 = time.perf_counter()
            try:
                r = urllib.request.urlopen(req, timeout=60)
                lines = [json.loads(l)
                         for l in r.read().decode().splitlines()]
            except Exception as e:
                with lock:
                    stats["errors"].append(f"{type(e).__name__}: {e}")
                continue
            lat = time.perf_counter() - t0
            vers = {l["model_version"] for l in lines}
            err = None
            if len(vers) != 1:
                err = f"reply mixed versions {vers}"
            else:
                ver = vers.pop()
                if ver not in expected:
                    err = f"unknown version {ver}"
                elif not np.allclose([l["prediction"] for l in lines],
                                     expected[ver]):
                    err = f"v{ver} reply does not match v{ver} model"
            with lock:
                stats["n"] += 1
                stats["lat"].append(lat)
                if err:
                    stats["errors"].append(err)
                else:
                    stats["versions"].add(ver)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(n_threads)]
    for t in threads:
        t.start()
    return threads, stats


def _p99(lats):
    vals = sorted(lats)
    return vals[min(len(vals) - 1, int(0.99 * len(vals)))]


def _backend_breaker(proxy, addr):
    for b in proxy.stats()["backends"]:
        if b["addr"] == addr:
            return b["breaker"]
    raise AssertionError(f"{addr} not in fleet stats")


@pytest.mark.servefault
class TestServeChaosSmoke:
    """Tier-1 chaos: 3 subprocess replicas behind the hardened proxy;
    one replica hung (accepts connections, /readyz green, /predict
    never answers), one delay-injected, one SIGKILLed — all under live
    closed-loop deadline-carrying traffic."""

    def test_fleet_survives_hang_delay_and_kill(self, binary_booster,
                                                tmp_path):
        bst, X = binary_booster
        art = PredictorArtifact.from_booster(bst)
        rows = X[:2]
        expected = {1: PackedPredictor(art).predict(rows)}
        reg_dir = str(tmp_path / "reg")
        ModelRegistry(reg_dir).publish(art)

        procs = _spawn_fleet(reg_dir, n=3)
        ports = [p for _, p in procs]
        addrs = [f"127.0.0.1:{p}" for p in ports]
        proxy = FleetProxy(("127.0.0.1", 0), addrs,
                           health_poll_s=0.2, retry_deadline_s=20.0,
                           backend_timeout_s=2.0,
                           hedge_delay_ms=60.0, hedge_budget_pct=100.0,
                           breaker_k=3.0, breaker_m=2,
                           breaker_open_ms=1000.0)
        threading.Thread(target=proxy.serve_forever, daemon=True).start()
        port = proxy.server_address[1]
        try:
            # -- healthy baseline on the very fleet we are about to wound
            threads, base = _deadline_loop(port, rows, expected,
                                           duration_s=2.0)
            for t in threads:
                t.join(timeout=60)
            assert base["errors"] == [], base["errors"][:5]
            assert base["n"] > 0
            healthy_p99 = _p99(base["lat"])

            # -- wound it: replica 0 hangs every predict, replica 1
            # delays every predict; replica 2 will be SIGKILLed mid-run
            _arm_fault(ports[0], "hang:1")
            _arm_fault(ports[1], "delay:150")
            threads, chaos = _deadline_loop(port, rows, expected,
                                            duration_s=8.0)
            time.sleep(2.5)
            procs[2][0].send_signal(signal.SIGKILL)
            for t in threads:
                t.join(timeout=120)

            # zero dropped, zero mis-versioned
            assert chaos["errors"] == [], chaos["errors"][:5]
            assert chaos["n"] > 0
            assert chaos["versions"] == {1}
            # bounded tail: well under the backend socket timeout and
            # the 8 s client budget even with every replica wounded
            chaos_p99 = _p99(chaos["lat"])
            assert chaos_p99 < max(3.0 * healthy_p99, 1.2), \
                f"chaos p99 {chaos_p99:.3f}s vs healthy {healthy_p99:.3f}s"
            assert chaos_p99 < proxy.backend_timeout_s
            st = proxy.stats()
            assert st["hedges"]["launched"] >= 1  # hedges did the rescue
            # the hung replica's breaker tripped on its timeout streak
            assert _backend_breaker(proxy, addrs[0])["opens"] >= 1

            # -- clear the faults; the half-open probe must restore the
            # hung replica to CLOSED under ordinary traffic
            time.sleep(2.5)  # let straggler attempts time out and drain
            _arm_fault(ports[0], "")
            _arm_fault(ports[1], "")
            body = "\n".join(json.dumps(list(map(float, r)))
                             for r in rows).encode()
            deadline = time.monotonic() + 15.0
            state = None
            while time.monotonic() < deadline:
                for _ in range(4):
                    req = urllib.request.Request(
                        f"http://127.0.0.1:{port}/predict", data=body)
                    req.add_header("X-Deadline-Ms", "8000")
                    try:
                        urllib.request.urlopen(req, timeout=60).read()
                    except urllib.error.HTTPError:
                        pass  # routing noise while the fleet settles
                state = _backend_breaker(proxy, addrs[0])["state"]
                if state == breaker_mod.CLOSED:
                    break
                time.sleep(0.2)
            assert state == breaker_mod.CLOSED, \
                f"breaker never re-closed (state={state})"
        finally:
            proxy.shutdown()
            proxy.server_close()
            for p, _ in procs:
                p.kill()
                p.wait(timeout=30)


@pytest.mark.servefault
@pytest.mark.slow
class TestSustainedChaosMatrix:
    """Sustained wounded-fleet soak: a flapping replica (alternating
    hang/healthy phases) plus a fractionally-delayed replica for 12 s of
    closed-loop deadline traffic — zero client-visible failures and a
    tail bounded by the backend timeout throughout."""

    def test_flap_and_fractional_delay_soak(self, binary_booster,
                                            tmp_path):
        bst, X = binary_booster
        art = PredictorArtifact.from_booster(bst)
        rows = X[:2]
        expected = {1: PackedPredictor(art).predict(rows)}
        reg_dir = str(tmp_path / "reg")
        ModelRegistry(reg_dir).publish(art)

        procs = _spawn_fleet(reg_dir, n=3)
        ports = [p for _, p in procs]
        proxy = FleetProxy(("127.0.0.1", 0),
                           [f"127.0.0.1:{p}" for p in ports],
                           health_poll_s=0.2, retry_deadline_s=20.0,
                           backend_timeout_s=2.0,
                           hedge_delay_ms=60.0, hedge_budget_pct=100.0,
                           breaker_k=3.0, breaker_m=2,
                           breaker_open_ms=1000.0)
        threading.Thread(target=proxy.serve_forever, daemon=True).start()
        port = proxy.server_address[1]
        try:
            _arm_fault(ports[0], "flap:1")
            _arm_fault(ports[1], "delay:300:0.5")
            threads, stats = _deadline_loop(port, rows, expected,
                                            duration_s=12.0)
            for t in threads:
                t.join(timeout=120)
            assert stats["errors"] == [], stats["errors"][:5]
            assert stats["n"] > 0
            assert stats["versions"] == {1}
            assert _p99(stats["lat"]) < proxy.backend_timeout_s
            # both wounds really fired on the replicas
            for p, kind in ((ports[0], "hang"), (ports[1], "delay")):
                c = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{p}/fault", timeout=30).read())
                assert c["injected"].get(kind, 0) >= 1
        finally:
            proxy.shutdown()
            proxy.server_close()
            for p, _ in procs:
                p.kill()
                p.wait(timeout=30)
