"""Partitioned grower tests (CPU via Pallas interpret mode).

Covers the three dynamic-segment kernels (ops/pkernels.py) against their
XLA/numpy reference implementations, one-tree structural parity between
grow_tree_partitioned and the mask-based grow_tree, and the fused
trainer end-to-end against the default path.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops import pkernels as pk
from lightgbm_tpu.ops.pgrow import (
    PGrowParams,
    grow_tree_partitioned,
    leaf_id_from_segments,
    segment_values,
)

INTERP = jax.default_backend() != "tpu"


def _make_packed(n=6000, f=11, b=32, seed=7, weights=False):
    rng = np.random.default_rng(seed)
    lay = pk.PLayout(f)
    bins = rng.integers(0, b, size=(n, f), dtype=np.uint8)
    label = rng.random(n).astype(np.float32)
    P = pk.pack_matrix(bins, lay, label=label,
                       weight=rng.random(n).astype(np.float32) if weights else None)
    g = rng.standard_normal(n).astype(np.float32)
    h = np.abs(rng.standard_normal(n)).astype(np.float32)
    sel = (rng.random(n) < 0.85).astype(np.float32)
    P = P.at[lay.G, :n].set(jnp.asarray(g.view(np.int32)))
    P = P.at[lay.H, :n].set(jnp.asarray(h.view(np.int32)))
    P = P.at[lay.SEL, :n].set(jnp.asarray(sel.view(np.int32)))
    return P, lay, bins, g, h, sel


class TestHistKernel:
    @pytest.mark.parametrize("start,cnt", [(0, 6000), (123, 3000), (7, 77), (5990, 10)])
    def test_matches_reference(self, start, cnt):
        P, lay, *_ = _make_packed()
        hd = np.asarray(pk.hist_dyn(P, start, cnt, lay.F, 32, interpret=INTERP))
        hr = np.asarray(pk.hist_ref(P, start, cnt, lay, 32))
        err = np.abs(hd - hr).max() / max(np.abs(hr).max(), 1.0)
        # interpret-mode bf16 emulation is coarser than the TPU MXU path
        assert err < (2e-3 if INTERP else 1e-5)


class TestPartitionKernel:
    @pytest.mark.parametrize(
        "start,cnt,feat,thr,zb,dbz,cat",
        [
            (0, 6000, 3, 15, 0, 0, 0),
            (123, 3000, 0, 7, 5, 11, 0),   # zero-bin remap
            (1111, 2222, 10, 4, 0, 0, 1),  # categorical (== thr)
            (7, 137, 7, 15, 0, 0, 0),      # tiny unaligned segment
        ],
    )
    def test_matches_reference(self, start, cnt, feat, thr, zb, dbz, cat):
        P, lay, *_ = _make_packed()
        scr = jnp.zeros_like(P)
        P2, _, nl = pk.partition_segment(
            P, scr, start, cnt, feat // 4, (feat % 4) * 8, zb, dbz, thr, cat,
            interpret=INTERP,
        )
        Pref, nlref = pk.partition_ref(P, start, cnt, feat, zb, dbz, thr, bool(cat), lay)
        assert int(nl) == nlref
        assert np.array_equal(np.asarray(P2), np.asarray(Pref))


class TestGrowParity:
    def test_tree_matches_mask_grower(self):
        """grow_tree_partitioned must reproduce grow_tree's split records
        on identical inputs (same histogram math to f32 tolerance; any
        divergence means a partition/subtraction bug)."""
        from lightgbm_tpu.ops.grow import GrowParams, grow_tree
        from lightgbm_tpu.ops.split import FeatureMeta, SplitHyper

        n, f, b, L = 6000, 11, 32, 15
        P, lay, bins, g, h, sel = _make_packed(n, f, b)
        meta = FeatureMeta(
            num_bins=jnp.full((f,), b, jnp.int32),
            default_bin=jnp.zeros((f,), jnp.int32),
            is_categorical=jnp.zeros((f,), bool),
        )
        hyper = SplitHyper(
            lambda_l1=jnp.float32(0.0), lambda_l2=jnp.float32(0.01),
            min_data_in_leaf=jnp.float32(20), min_sum_hessian_in_leaf=jnp.float32(1e-3),
            min_gain_to_split=jnp.float32(0.0),
        )
        fmask = jnp.ones((f,), jnp.float32)
        pres, P2, _ = grow_tree_partitioned(
            P, jnp.zeros_like(P), fmask, meta, hyper,
            PGrowParams(L, b, f, n, -1, True, False), interpret=INTERP,
        )
        gres = grow_tree(
            jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h), jnp.asarray(sel),
            fmask, meta, hyper, GrowParams(num_leaves=L, num_bins=b),
        )
        ns = int(pres.num_splits)
        assert ns == int(gres.num_splits) and ns > 3
        np.testing.assert_array_equal(np.asarray(pres.rec_feat[:ns]), np.asarray(gres.rec_feat[:ns]))
        np.testing.assert_array_equal(np.asarray(pres.rec_thr[:ns]), np.asarray(gres.rec_thr[:ns]))
        np.testing.assert_array_equal(np.asarray(pres.rec_leaf[:ns]), np.asarray(gres.rec_leaf[:ns]))
        np.testing.assert_allclose(
            np.asarray(pres.rec_lval[:ns]), np.asarray(gres.rec_lval[:ns]), rtol=2e-4, atol=1e-6
        )
        # leaf assignment round-trips through the rowid channel
        lid = leaf_id_from_segments(pres, P2, lay, n)
        np.testing.assert_array_equal(np.asarray(lid), np.asarray(gres.leaf_id))

    def test_segment_values(self):
        import types

        starts = jnp.asarray([0, 10, 4, 17], jnp.int32)
        cnts = jnp.asarray([4, 7, 6, 3], jnp.int32)
        tree = types.SimpleNamespace(starts=starts, cnts=cnts, num_splits=jnp.int32(3))
        vals = jnp.asarray([1.0, 2.0, 3.0, 4.0])
        out = np.asarray(segment_values(tree, 20, vals))
        expect = np.concatenate([[1.0] * 4, [3.0] * 6, [2.0] * 7, [4.0] * 3])
        np.testing.assert_allclose(out, expect)


class TestFourBitPacking:
    """max_bin <= 16 -> 4-bit packed words (dense_nbits_bin.hpp:37):
    half the bin rows, identical results."""

    def test_kernel_parity_bits4(self):
        rng = np.random.default_rng(11)
        n, f, b = 5000, 11, 16
        lay = pk.PLayout(f, bits=4)
        assert lay.W == -(-f // 8)  # half the 8-bit word count
        bins = rng.integers(0, b, size=(n, f), dtype=np.uint8)
        P = pk.pack_matrix(bins, lay, label=rng.random(n).astype(np.float32))
        g = rng.standard_normal(n).astype(np.float32)
        h = np.abs(rng.standard_normal(n)).astype(np.float32)
        P = P.at[lay.G, :n].set(jnp.asarray(g.view(np.int32)))
        P = P.at[lay.H, :n].set(jnp.asarray(h.view(np.int32)))
        hd = np.asarray(pk.hist_dyn(P, 123, 3000, f, b, bits=4, interpret=INTERP))
        hr = np.asarray(pk.hist_ref(P, 123, 3000, lay, b))
        err = np.abs(hd - hr).max() / max(np.abs(hr).max(), 1.0)
        assert err < (2e-3 if INTERP else 1e-5)
        scr = jnp.zeros_like(P)
        feat = 5
        P2, _, nl = pk.partition_segment(
            P, scr, 100, 2000, feat // 8, (feat % 8) * 4, 0, 0, 7, 0,
            bits=4, interpret=INTERP,
        )
        Pref, nlref = pk.partition_ref(P, 100, 2000, feat, 0, 0, 7, False, lay)
        assert int(nl) == nlref
        assert np.array_equal(np.asarray(P2), np.asarray(Pref))

    def test_training_parity_bits4(self, monkeypatch):
        import lightgbm_tpu as lgb

        rng = np.random.default_rng(12)
        X = rng.standard_normal((3000, 8)).astype(np.float32)
        w = rng.standard_normal(8)
        y = (rng.random(3000) < 1 / (1 + np.exp(-(X @ w)))).astype(np.float32)
        params = dict(objective="binary", num_leaves=15, learning_rate=0.2,
                      max_bin=15, min_data_in_leaf=20, verbose=-1,
                      enable_bundle=False)
        preds = {}
        monkeypatch.delenv("LIGHTGBM_TPU_FORCE_BITS", raising=False)
        for mode, env in [("pgrow4", "force"), ("default", "0")]:
            monkeypatch.setenv("LIGHTGBM_TPU_PGROW", env)
            bst = lgb.train(params, lgb.Dataset(X, label=y, params=dict(params)), 3)
            if mode == "pgrow4":
                assert bst.boosting.ptrainer.params.bits == 4
                assert bst.boosting.ptrainer.layout.W == 1  # 8 feats, 1 word
            preds[mode] = bst.predict(X)
        np.testing.assert_allclose(preds["pgrow4"], preds["default"], rtol=3e-3, atol=3e-4)


class TestFusedTrainer:
    def _data(self, n=3000, f=8, seed=0):
        rng = np.random.default_rng(seed)
        X = rng.standard_normal((n, f)).astype(np.float32)
        w = rng.standard_normal(f)
        y = (rng.random(n) < 1 / (1 + np.exp(-(X @ w)))).astype(np.float32)
        return X, y

    def test_matches_default_path(self, monkeypatch):
        import lightgbm_tpu as lgb

        X, y = self._data()
        params = dict(objective="binary", num_leaves=7, learning_rate=0.2,
                      max_bin=31, min_data_in_leaf=20, verbose=-1)
        preds = {}
        for mode, env in [("pgrow", "force"), ("default", "0")]:
            monkeypatch.setenv("LIGHTGBM_TPU_PGROW", env)
            bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=3)
            preds[mode] = bst.predict(X)
            if mode == "pgrow":
                assert bst.boosting.ptrainer is not None
            else:
                assert bst.boosting.ptrainer is None
        np.testing.assert_allclose(preds["pgrow"], preds["default"], rtol=3e-3, atol=3e-4)

    def test_regression_weighted(self, monkeypatch):
        import lightgbm_tpu as lgb

        rng = np.random.default_rng(1)
        X = rng.standard_normal((2000, 6)).astype(np.float32)
        y = (X[:, 0] - 0.5 * X[:, 1] + 0.1 * rng.standard_normal(2000)).astype(np.float32)
        w = rng.random(2000).astype(np.float32) + 0.5
        params = dict(objective="regression", num_leaves=7, learning_rate=0.2,
                      max_bin=31, min_data_in_leaf=20, verbose=-1)
        preds = {}
        for mode, env in [("pgrow", "force"), ("default", "0")]:
            monkeypatch.setenv("LIGHTGBM_TPU_PGROW", env)
            ds = lgb.Dataset(X, label=y, weight=w)
            bst = lgb.train(params, ds, num_boost_round=3)
            preds[mode] = bst.predict(X)
        np.testing.assert_allclose(preds["pgrow"], preds["default"], rtol=3e-3, atol=3e-4)

    def test_rank_objective_falls_back(self, monkeypatch):
        import lightgbm_tpu as lgb

        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
        rng = np.random.default_rng(2)
        X = rng.standard_normal((600, 5)).astype(np.float32)
        y = rng.integers(0, 3, 600).astype(np.float32)
        ds = lgb.Dataset(X, label=y, group=[60] * 10)
        bst = lgb.train(
            dict(objective="lambdarank", num_leaves=7, max_bin=31, verbose=-1),
            ds, num_boost_round=2,
        )
        assert bst.boosting.ptrainer is None
        assert bst.boosting.num_trees >= 2
