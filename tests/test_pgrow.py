"""Partitioned grower tests (CPU via Pallas interpret mode).

Covers the dynamic-segment kernels (ops/pkernels.py) against their
XLA/numpy reference implementations, the two-ended partition protocol by
exhaustive host-side simulation, one-tree structural parity between
grow_tree_partitioned and the mask-based grow_tree, and the fused
trainer end-to-end against the default path.
"""

import os
import random

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops import pkernels as pk
from lightgbm_tpu.ops.pgrow import (
    PGrowParams,
    grow_tree_partitioned,
    leaf_id_from_segments,
    segment_values,
)

INTERP = jax.default_backend() != "tpu"


def _make_packed(n=6000, f=11, b=32, seed=7, weights=False):
    rng = np.random.default_rng(seed)
    lay = pk.PLayout(f)
    bins = rng.integers(0, b, size=(n, f), dtype=np.uint8)
    label = rng.random(n).astype(np.float32)
    P = pk.pack_matrix(bins, lay, label=label,
                       weight=rng.random(n).astype(np.float32) if weights else None)
    g = rng.standard_normal(n).astype(np.float32)
    h = np.abs(rng.standard_normal(n)).astype(np.float32)
    sel = (rng.random(n) < 0.85).astype(np.float32)
    P = P.at[lay.G, :n].set(jnp.asarray(g.view(np.int32)))
    P = P.at[lay.H, :n].set(jnp.asarray(h.view(np.int32)))
    P = P.at[lay.SEL, :n].set(jnp.asarray(sel.view(np.int32)))
    return P, lay, bins, g, h, sel


class TestHistKernel:
    @pytest.mark.parametrize("start,cnt", [(0, 6000), (123, 3000), (7, 77), (5990, 10)])
    def test_matches_reference(self, start, cnt):
        P, lay, *_ = _make_packed()
        hd = np.asarray(pk.hist_dyn(P, start, cnt, lay.F, 32, rows=lay.rows,
                                    interpret=INTERP))
        hr = np.asarray(pk.hist_ref(P, start, cnt, lay, 32))
        err = np.abs(hd - hr).max() / max(np.abs(hr).max(), 1.0)
        # interpret-mode bf16 emulation is coarser than the TPU MXU path
        assert err < (2e-3 if INTERP else 1e-5)


def _check_split_stream(P, lay, start, cnt, feat, thr, zb, dbz, cat, bits=8,
                        nbins=32):
    """split_stream vs the stable numpy reference: same left/right row
    SETS (sorted by the rowid channel — the kernel is unordered within a
    side), every channel traveling with its row, untouched columns
    outside the segment, and both returned histograms matching hist_ref
    on the reference-partitioned children."""
    per = 32 // bits
    # reference FIRST: split_stream donates its input buffer (the jit
    # wrapper carries donate_argnums), so P must not be read afterwards —
    # pass a copy so callers can reuse P across checks
    Pref, nlref = pk.partition_ref(P, start, cnt, feat, zb, dbz, thr, bool(cat), lay)
    P2, nl, lh, rh = pk.split_stream(
        jnp.array(P), start, cnt, feat // per, (feat % per) * bits, zb, dbz,
        thr, cat,
        num_features=lay.F, num_bins=nbins, bits=bits, rows=lay.rows,
        interpret=INTERP,
    )
    assert int(nl) == nlref
    P2n, Prefn = np.asarray(P2), np.asarray(Pref)
    # outside the segment: bit-identical
    np.testing.assert_array_equal(P2n[:, :start], Prefn[:, :start])
    np.testing.assert_array_equal(P2n[:, start + cnt:], Prefn[:, start + cnt:])

    def canon(mat, lo, hi):
        seg = mat[:, lo:hi]
        order = np.argsort(seg[lay.ROWID], kind="stable")
        return seg[:, order]

    # each side holds the same rows (all channels) as the stable reference
    np.testing.assert_array_equal(
        canon(P2n, start, start + nlref), canon(Prefn, start, start + nlref))
    np.testing.assert_array_equal(
        canon(P2n, start + nlref, start + cnt), canon(Prefn, start + nlref, start + cnt))
    # histograms of both children from the same pass
    tol = 2e-3 if INTERP else 1e-5
    for hist, lo, hi in ((lh, start, start + nlref), (rh, start + nlref, start + cnt)):
        hrf = np.asarray(pk.hist_ref(Pref, lo, hi - lo, lay, nbins))
        err = np.abs(np.asarray(hist) - hrf).max() / max(np.abs(hrf).max(), 1.0)
        assert err < tol


class TestSplitStreamKernel:
    @pytest.mark.parametrize(
        "start,cnt,feat,thr,zb,dbz,cat",
        [
            (0, 6000, 3, 15, 0, 0, 0),
            (123, 3000, 0, 7, 5, 11, 0),   # zero-bin remap
            (1111, 2222, 10, 4, 0, 0, 1),  # categorical (== thr)
            (7, 137, 7, 15, 0, 0, 0),      # tiny unaligned segment
            (2048, 1024, 2, 9, 0, 0, 0),   # exactly block-aligned
            (4000, 900, 1, 0, 0, 0, 0),    # all-or-nothing thresholds
            (4000, 900, 1, 31, 0, 0, 0),
        ],
    )
    def test_matches_reference(self, start, cnt, feat, thr, zb, dbz, cat):
        P, lay, *_ = _make_packed()
        _check_split_stream(P, lay, start, cnt, feat, thr, zb, dbz, cat)

    def test_randomized_segments(self):
        P, lay, *_ = _make_packed(n=9000)
        rng = random.Random(3)
        for _ in range(6):
            cnt = rng.randrange(2, 8000)
            start = rng.randrange(0, 9000 - cnt)
            _check_split_stream(P, lay, start, cnt, rng.randrange(0, lay.F),
                                rng.randrange(0, 31), 0, 0, 0)


class TestLevelStreamKernel:
    """level_stream (one launch, many segments) must reproduce
    split_stream segment-for-segment: same left counts, same children
    histograms, and the identical in-place partition — including empty,
    tiny-unaligned, and block-aligned segments in one call."""

    def test_matches_split_stream_per_segment(self):
        P, lay, *_ = _make_packed(n=6000)
        F, B = lay.F, 32
        per = 32 // lay.bits
        # disjoint segments covering assorted shapes (cnt=0 is a leaf the
        # level pass must pass through untouched)
        segs = [
            (0, 1024, 3, 15, 0, 0, 0),
            (1024, 0, 0, 7, 0, 0, 0),       # empty, block-aligned start
            (1024, 137, 0, 7, 5, 11, 0),    # tiny + zero-bin remap
            (1161, 2935, 10, 4, 0, 0, 1),   # categorical
            (4096, 1904, 7, 20, 0, 0, 0),
        ]
        smax = 8
        tab = np.zeros((smax, 12), np.int32)
        for i, (s, c, f, t, zb, dbz, cat) in enumerate(segs):
            tab[i] = [s, c, f // per, (f % per) * lay.bits, zb, dbz, t, cat,
                      0, 1 << lay.bits, 0, 0]
        # level_stream donates its input: hand it a copy, the per-segment
        # split_stream chain below still consumes the original P
        pl_, nl, hists = pk.level_stream(
            jnp.array(P), jnp.asarray(tab), jnp.int32(len(segs)), num_features=F,
            num_bins=B, bits=lay.bits, rows=lay.rows, smax=smax,
            interpret=INTERP,
        )
        pl_ = np.asarray(pl_)
        nl = np.asarray(nl)
        hists = np.asarray(hists)

        ps = P
        for i, (s, c, f, t, zb, dbz, cat) in enumerate(segs):
            ps, nls, lh, rh = pk.split_stream(
                ps, s, c, f // per, (f % per) * lay.bits, zb, dbz, t, cat,
                num_features=F, num_bins=B, bits=lay.bits, rows=lay.rows,
                interpret=INTERP,
            )
            assert int(nls) == int(nl[i]), f"seg {i} left count"
            ll = np.asarray(pk._hist_from_rows(jnp.asarray(hists[i]), F, B, row0=0))
            rr = np.asarray(pk._hist_from_rows(jnp.asarray(hists[i]), F, B, row0=7))
            tol = 2e-3 if INTERP else 1e-5
            for got, want in ((ll, np.asarray(lh)), (rr, np.asarray(rh))):
                err = np.abs(got - want).max() / max(np.abs(want).max(), 1.0)
                assert err < tol, f"seg {i} hist mismatch {err}"
        # identical in-place partition (same protocol, same block order)
        np.testing.assert_array_equal(pl_, np.asarray(ps))

    def test_zero_active_is_noop(self):
        P, lay, *_ = _make_packed(n=3000)
        Pn = np.asarray(P)  # snapshot: level_stream donates its input
        tab = jnp.zeros((8, 12), jnp.int32)
        pl_, nl, _ = pk.level_stream(
            P, tab, jnp.int32(0), num_features=lay.F, num_bins=32,
            bits=lay.bits, rows=lay.rows, smax=8, interpret=INTERP,
        )
        np.testing.assert_array_equal(np.asarray(pl_), Pn)


class TestTwoEndProtocol:
    """Host-side block-level simulation of split_stream's two-ended
    read/write protocol (demand reads, force-consume, hand-side prefetch,
    flush-waits) — proves writes only ever land on consumed blocks."""

    BLK = pk.BLK
    RING = pk._RING

    def _run(self, nblk, seed, bias):
        rng = random.Random(seed)
        BLK, RING = self.BLK, self.RING
        head = rng.randrange(0, BLK)
        total = nblk * BLK
        E = total - rng.randrange(0, BLK)
        cnt = E - head
        if cnt <= 0:
            return
        cl, cr = head, total - E
        if_ = ib = cf = cb = kf = kb = fl = fr = 0
        classified = set()

        def flushwait(tgt):
            nonlocal cf, cb
            if if_ > cf and tgt == cf:
                cf += 1
            if ib > cb and tgt == nblk - 1 - cb:
                cb += 1
            assert (tgt < cf) or (tgt >= nblk - cb), "flush to unread block"
            if if_ > cf:
                assert tgt != cf, "flush over in-flight front read"
            if ib > cb:
                assert tgt != nblk - 1 - cb, "flush over in-flight back read"

        for j in range(nblk):
            budget = if_ + ib < nblk
            if (cf - fl == 0) and ((if_ > cf) or budget):
                if if_ == cf:
                    if_ += 1
                cf += 1
            budget = if_ + ib < nblk
            if (cb - fr == 0) and ((ib > cb) or budget):
                if ib == cb:
                    ib += 1
                cb += 1
            budget = if_ + ib < nblk
            if cf - kf == 0 and cb - kb == 0:
                if (if_ > cf) or budget:
                    if if_ == cf:
                        if_ += 1
                    cf += 1
                else:
                    assert (ib > cb) or budget, "deadlock"
                    if ib == cb:
                        ib += 1
                    cb += 1
            useF = (cf - kf) > 0
            if useF:
                hand = kf
                kf += 1
            else:
                assert cb - kb > 0, "no hand block"
                hand = nblk - 1 - kb
                kb += 1
            assert hand not in classified, "block classified twice"
            classified.add(hand)
            lo, hi = hand * BLK, (hand + 1) * BLK
            nvalid = max(0, min(hi, E) - max(lo, head))
            r = rng.random()
            dl = 0 if r < bias else (nvalid if r < 2 * bias else rng.randint(0, nvalid))
            dr = nvalid - dl
            tl, tr = cl + dl, cr + dr
            if tl >= BLK:
                flushwait(fl)
                fl += 1
                tl -= BLK
            if tr >= BLK:
                flushwait(nblk - 1 - fr)
                fr += 1
                tr -= BLK
            cl, cr = tl, tr
            budget = if_ + ib < nblk
            if budget and useF and (if_ - kf) < RING:
                if_ += 1
            budget = if_ + ib < nblk
            if budget and (not useF) and (ib - kb) < RING:
                ib += 1

        assert cl + cr in (0, BLK)
        if cl + cr == BLK:
            flushwait(fl)
            assert fl == nblk - 1 - fr
        assert classified == set(range(nblk))
        assert if_ - cf <= 1 and ib - cb <= 1  # final drain bound

    def test_protocol(self):
        for bias in (0.05, 0.45):
            for nblk in list(range(1, 12)) + [50, 200]:
                for seed in range(300):
                    self._run(nblk, seed, bias)


class TestUpdateChannels:
    def test_grad_score_sel(self):
        n = 3000
        P, lay, bins, g, h, sel = _make_packed(n=n)
        rng = np.random.default_rng(5)
        delta = rng.standard_normal(n).astype(np.float32)
        sel_new = (rng.random(n) < 0.5).astype(np.float32)

        def grad_fn(score, label, weight):
            ps = 1.0 / (1.0 + jnp.exp(-score))
            return (ps - label) * weight, ps * (1.0 - ps) * weight

        P2 = update = pk.update_channels(P, lay, grad_fn, delta=delta, sel=sel_new,
                                         interpret=INTERP)
        P2n = np.asarray(P2)
        label = np.asarray(P, np.int32)[lay.LABEL, :n].view(np.float32)
        weight = np.asarray(P, np.int32)[lay.WEIGHT, :n].view(np.float32)
        score0 = np.asarray(P, np.int32)[lay.SCORE, :n].view(np.float32)
        s = score0 + delta
        ps = 1.0 / (1.0 + np.exp(-s))
        np.testing.assert_allclose(P2n[lay.SCORE, :n].view(np.float32), s, rtol=1e-6)
        np.testing.assert_allclose(
            P2n[lay.G, :n].view(np.float32), (ps - label) * weight, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            P2n[lay.H, :n].view(np.float32), ps * (1 - ps) * weight, rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(P2n[lay.SEL, :n].view(np.float32), sel_new)
        # immutable rows untouched
        np.testing.assert_array_equal(P2n[: lay.W], np.asarray(P)[: lay.W])
        np.testing.assert_array_equal(P2n[lay.ROWID], np.asarray(P)[lay.ROWID])


class TestGrowParity:
    def test_tree_matches_mask_grower(self):
        """grow_tree_partitioned must reproduce grow_tree's split records
        on identical inputs (same histogram math to f32 tolerance; any
        divergence means a partition/histogram bug)."""
        from lightgbm_tpu.ops.grow import GrowParams, grow_tree
        from lightgbm_tpu.ops.split import FeatureMeta, SplitHyper

        n, f, b, L = 6000, 11, 32, 15
        P, lay, bins, g, h, sel = _make_packed(n, f, b)
        meta = FeatureMeta(
            num_bins=jnp.full((f,), b, jnp.int32),
            default_bin=jnp.zeros((f,), jnp.int32),
            is_categorical=jnp.zeros((f,), bool),
        )
        hyper = SplitHyper(
            lambda_l1=jnp.float32(0.0), lambda_l2=jnp.float32(0.01),
            min_data_in_leaf=jnp.float32(20), min_sum_hessian_in_leaf=jnp.float32(1e-3),
            min_gain_to_split=jnp.float32(0.0),
        )
        fmask = jnp.ones((f,), jnp.float32)
        pres, P2 = grow_tree_partitioned(
            P, fmask, meta, hyper,
            PGrowParams(L, b, f, n, -1, True, False), interpret=INTERP,
        )
        gres = grow_tree(
            jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h), jnp.asarray(sel),
            fmask, meta, hyper, GrowParams(num_leaves=L, num_bins=b),
        )
        ns = int(pres.num_splits)
        assert ns == int(gres.num_splits) and ns > 3
        np.testing.assert_array_equal(np.asarray(pres.rec_feat[:ns]), np.asarray(gres.rec_feat[:ns]))
        np.testing.assert_array_equal(np.asarray(pres.rec_thr[:ns]), np.asarray(gres.rec_thr[:ns]))
        np.testing.assert_array_equal(np.asarray(pres.rec_leaf[:ns]), np.asarray(gres.rec_leaf[:ns]))
        np.testing.assert_allclose(
            np.asarray(pres.rec_lval[:ns]), np.asarray(gres.rec_lval[:ns]), rtol=2e-4, atol=1e-6
        )
        # leaf assignment round-trips through the rowid channel
        lid = leaf_id_from_segments(pres, P2, lay, n)
        np.testing.assert_array_equal(np.asarray(lid), np.asarray(gres.leaf_id))

    def test_segment_values(self):
        import types

        starts = jnp.asarray([0, 10, 4, 17], jnp.int32)
        cnts = jnp.asarray([4, 7, 6, 3], jnp.int32)
        tree = types.SimpleNamespace(starts=starts, cnts=cnts, num_splits=jnp.int32(3))
        vals = jnp.asarray([1.0, 2.0, 3.0, 4.0])
        out = np.asarray(segment_values(tree, 20, vals))
        expect = np.concatenate([[1.0] * 4, [3.0] * 6, [2.0] * 7, [4.0] * 3])
        np.testing.assert_allclose(out, expect)


class TestFourBitPacking:
    """max_bin <= 16 -> 4-bit packed words (dense_nbits_bin.hpp:37):
    half the bin rows, identical results."""

    def test_kernel_parity_bits4(self):
        rng = np.random.default_rng(11)
        n, f, b = 5000, 11, 16
        lay = pk.PLayout(f, bits=4)
        assert lay.W == -(-f // 8)  # half the 8-bit word count
        bins = rng.integers(0, b, size=(n, f), dtype=np.uint8)
        P = pk.pack_matrix(bins, lay, label=rng.random(n).astype(np.float32))
        g = rng.standard_normal(n).astype(np.float32)
        h = np.abs(rng.standard_normal(n)).astype(np.float32)
        P = P.at[lay.G, :n].set(jnp.asarray(g.view(np.int32)))
        P = P.at[lay.H, :n].set(jnp.asarray(h.view(np.int32)))
        hd = np.asarray(pk.hist_dyn(P, 123, 3000, f, b, bits=4, rows=lay.rows,
                                    interpret=INTERP))
        hr = np.asarray(pk.hist_ref(P, 123, 3000, lay, b))
        err = np.abs(hd - hr).max() / max(np.abs(hr).max(), 1.0)
        assert err < (2e-3 if INTERP else 1e-5)
        _check_split_stream(P, lay, 100, 2000, 5, 7, 0, 0, 0, bits=4, nbins=b)

    def test_training_parity_bits4(self, monkeypatch):
        import lightgbm_tpu as lgb

        rng = np.random.default_rng(12)
        X = rng.standard_normal((3000, 8)).astype(np.float32)
        w = rng.standard_normal(8)
        y = (rng.random(3000) < 1 / (1 + np.exp(-(X @ w)))).astype(np.float32)
        params = dict(objective="binary", num_leaves=15, learning_rate=0.2,
                      max_bin=15, min_data_in_leaf=20, verbose=-1,
                      enable_bundle=False)
        preds = {}
        monkeypatch.delenv("LIGHTGBM_TPU_FORCE_BITS", raising=False)
        for mode, env in [("pgrow4", "force"), ("default", "0")]:
            monkeypatch.setenv("LIGHTGBM_TPU_PGROW", env)
            bst = lgb.train(params, lgb.Dataset(X, label=y, params=dict(params)), 3)
            if mode == "pgrow4":
                assert bst.boosting.ptrainer.params.bits == 4
                assert bst.boosting.ptrainer.layout.W == 1  # 8 feats, 1 word
            preds[mode] = bst.predict(X)
        np.testing.assert_allclose(preds["pgrow4"], preds["default"], rtol=3e-3, atol=3e-4)


class TestFusedTrainer:
    def _data(self, n=3000, f=8, seed=0):
        rng = np.random.default_rng(seed)
        X = rng.standard_normal((n, f)).astype(np.float32)
        w = rng.standard_normal(f)
        y = (rng.random(n) < 1 / (1 + np.exp(-(X @ w)))).astype(np.float32)
        return X, y

    def test_matches_default_path(self, monkeypatch):
        import lightgbm_tpu as lgb

        X, y = self._data()
        params = dict(objective="binary", num_leaves=7, learning_rate=0.2,
                      max_bin=31, min_data_in_leaf=20, verbose=-1)
        preds = {}
        for mode, env in [("pgrow", "force"), ("default", "0")]:
            monkeypatch.setenv("LIGHTGBM_TPU_PGROW", env)
            bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=3)
            preds[mode] = bst.predict(X)
            if mode == "pgrow":
                assert bst.boosting.ptrainer is not None
            else:
                assert bst.boosting.ptrainer is None
        np.testing.assert_allclose(preds["pgrow"], preds["default"], rtol=3e-3, atol=3e-4)

    def test_regression_weighted(self, monkeypatch):
        import lightgbm_tpu as lgb

        rng = np.random.default_rng(1)
        X = rng.standard_normal((2000, 6)).astype(np.float32)
        y = (X[:, 0] - 0.5 * X[:, 1] + 0.1 * rng.standard_normal(2000)).astype(np.float32)
        w = rng.random(2000).astype(np.float32) + 0.5
        params = dict(objective="regression", num_leaves=7, learning_rate=0.2,
                      max_bin=31, min_data_in_leaf=20, verbose=-1)
        preds = {}
        for mode, env in [("pgrow", "force"), ("default", "0")]:
            monkeypatch.setenv("LIGHTGBM_TPU_PGROW", env)
            ds = lgb.Dataset(X, label=y, weight=w)
            bst = lgb.train(params, ds, num_boost_round=3)
            preds[mode] = bst.predict(X)
        np.testing.assert_allclose(preds["pgrow"], preds["default"], rtol=3e-3, atol=3e-4)

    def test_rank_objective_falls_back(self, monkeypatch):
        import lightgbm_tpu as lgb

        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
        rng = np.random.default_rng(2)
        X = rng.standard_normal((600, 5)).astype(np.float32)
        y = rng.integers(0, 3, 600).astype(np.float32)
        ds = lgb.Dataset(X, label=y, group=[60] * 10)
        bst = lgb.train(
            dict(objective="lambdarank", num_leaves=7, max_bin=31, verbose=-1),
            ds, num_boost_round=2,
        )
        assert bst.boosting.ptrainer is None
        assert bst.boosting.num_trees >= 2


class TestUpdateAndRootHist:
    def test_fused_update_hist(self):
        n = 3000
        P, lay, bins, g, h, sel = _make_packed(n=n)
        rng = np.random.default_rng(9)
        delta = rng.standard_normal(n).astype(np.float32)
        sel_new = (rng.random(n) < 0.6).astype(np.float32)

        def grad_fn(score, label, weight):
            ps = 1.0 / (1.0 + jnp.exp(-score))
            return (ps - label) * weight, ps * (1.0 - ps) * weight

        P2, hist = pk.update_and_root_hist(
            P, lay, grad_fn, delta=delta, sel=sel_new, num_rows=n,
            num_features=lay.F, num_bins=32, interpret=INTERP)
        P2n = np.asarray(P2, np.int32)
        label = np.asarray(P, np.int32)[lay.LABEL, :n].view(np.float32)
        weight = np.asarray(P, np.int32)[lay.WEIGHT, :n].view(np.float32)
        s = np.asarray(P, np.int32)[lay.SCORE, :n].view(np.float32) + delta
        ps = 1.0 / (1.0 + np.exp(-s))
        np.testing.assert_allclose(P2n[lay.SCORE, :n].view(np.float32), s, rtol=1e-6)
        np.testing.assert_allclose(
            P2n[lay.G, :n].view(np.float32), (ps - label) * weight, rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(P2n[lay.SEL, :n].view(np.float32), sel_new)
        np.testing.assert_array_equal(P2n[: lay.W], np.asarray(P)[: lay.W])
        # returned hist matches hist_ref on the UPDATED matrix
        hr = np.asarray(pk.hist_ref(P2, 0, n, lay, 32))
        err = np.abs(np.asarray(hist) - hr).max() / max(np.abs(hr).max(), 1.0)
        assert err < (2e-3 if INTERP else 1e-5)


class TestShardedPartitioned:
    """Data-parallel partitioned trainer (shard_map + hist psum) must
    reproduce the serial partitioned trainer tree-for-tree."""

    def test_dp_matches_serial(self, monkeypatch):
        import lightgbm_tpu as lgb

        if len(jax.devices()) < 4:
            pytest.skip("needs multi-device mesh")
        rng = np.random.default_rng(3)
        X = rng.standard_normal((3000, 8)).astype(np.float32)
        w = rng.standard_normal(8)
        y = (rng.random(3000) < 1 / (1 + np.exp(-(X @ w)))).astype(np.float32)
        params = dict(objective="binary", num_leaves=15, learning_rate=0.2,
                      max_bin=31, min_data_in_leaf=20, verbose=-1)
        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
        preds, models = {}, {}
        for mode in ("serial", "data"):
            p = dict(params, tree_learner=mode)
            bst = lgb.train(p, lgb.Dataset(X, label=y, params=dict(p)), 3)
            if mode == "data":
                from lightgbm_tpu.boosting.ptrainer import ShardedPartitionedTrainer
                assert isinstance(bst.boosting.ptrainer, ShardedPartitionedTrainer)
            preds[mode] = bst.predict(X)
            models[mode] = bst.boosting.save_model_to_string()
        # identical split structure (same hist sums to f32 tolerance)
        np.testing.assert_allclose(preds["data"], preds["serial"], rtol=3e-3, atol=3e-4)

    def test_dp_multiclass_matches_serial(self, monkeypatch):
        """K > 1 under the sharded trainer: K score channels in the
        sharded layout, one multi-hist psum per iteration."""
        import lightgbm_tpu as lgb

        if len(jax.devices()) < 4:
            pytest.skip("needs multi-device mesh")
        rng = np.random.default_rng(13)
        n, f, K = 2400, 6, 3
        X = rng.standard_normal((n, f)).astype(np.float32)
        w = rng.standard_normal((f, K))
        y = np.argmax(X @ w + 0.3 * rng.standard_normal((n, K)), axis=1).astype(np.float32)
        params = dict(objective="multiclass", num_class=K, num_leaves=7,
                      learning_rate=0.2, max_bin=31, min_data_in_leaf=20,
                      verbose=-1)
        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
        preds = {}
        for mode in ("serial", "data"):
            p = dict(params, tree_learner=mode)
            bst = lgb.train(p, lgb.Dataset(X, label=y, params=dict(p)), 3)
            if mode == "data":
                from lightgbm_tpu.boosting.ptrainer import ShardedPartitionedTrainer
                assert isinstance(bst.boosting.ptrainer, ShardedPartitionedTrainer)
                assert bst.boosting.ptrainer.K == K
            preds[mode] = bst.predict(X)
        np.testing.assert_allclose(preds["data"], preds["serial"], rtol=4e-3, atol=5e-4)

    def test_dp_goss_trains(self, monkeypatch):
        """GOSS under the sharded trainer: per-shard local top-k (the
        reference's distributed GOSS is also per-machine local).  Sampling
        draws differ from serial by design, so assert training quality
        rather than tree equality."""
        import lightgbm_tpu as lgb

        if len(jax.devices()) < 4:
            pytest.skip("needs multi-device mesh")
        rng = np.random.default_rng(14)
        n, f = 3000, 8
        X = rng.standard_normal((n, f)).astype(np.float32)
        w = rng.standard_normal(f)
        y = (rng.random(n) < 1 / (1 + np.exp(-(X @ w)))).astype(np.float32)
        params = dict(objective="binary", boosting="goss", num_leaves=15,
                      learning_rate=0.5, max_bin=31, min_data_in_leaf=20,
                      tree_learner="data", verbose=-1)
        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
        bst = lgb.train(params, lgb.Dataset(X, label=y, params=dict(params)), 6)
        from lightgbm_tpu.boosting.ptrainer import ShardedPartitionedTrainer
        assert isinstance(bst.boosting.ptrainer, ShardedPartitionedTrainer)
        from sklearn.metrics import roc_auc_score
        auc = roc_auc_score(y, bst.predict(X))
        assert auc > 0.85, auc


class TestFusedRollback:
    """rollback_one_iter against the fused trainers: the popped tree's
    contribution must leave the score channel exactly (r5 ADVICE fixes:
    last_kept tracking + post-stop no-op iterations keep the physical
    layout the positional rollback needs)."""

    def _problem(self, n=2000, f=6, seed=21):
        rng = np.random.default_rng(seed)
        X = rng.standard_normal((n, f)).astype(np.float32)
        w = rng.standard_normal(f)
        y = (rng.random(n) < 1 / (1 + np.exp(-(X @ w)))).astype(np.float32)
        return X, y

    def test_rollback_matches_shorter_run(self, monkeypatch):
        import lightgbm_tpu as lgb

        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
        X, y = self._problem()
        params = dict(objective="binary", num_leaves=15, learning_rate=0.2,
                      max_bin=31, min_data_in_leaf=20, verbose=-1)
        bst3 = lgb.train(params, lgb.Dataset(X, label=y, params=dict(params)), 3)
        bst3.rollback_one_iter()
        assert bst3.num_trees == 2
        bst2 = lgb.train(params, lgb.Dataset(X, label=y, params=dict(params)), 2)
        np.testing.assert_allclose(bst3.predict(X), bst2.predict(X),
                                   rtol=1e-5, atol=1e-6)
        # the internal score channel must match the 2-tree state too:
        # training ONE more iteration reproduces the deterministic tree 3
        bst3.update()
        ref3 = lgb.train(params, lgb.Dataset(X, label=y, params=dict(params)), 3)
        np.testing.assert_allclose(bst3.predict(X), ref3.predict(X),
                                   rtol=3e-4, atol=3e-5)

    def test_sharded_bagging_uneven_shards(self, monkeypatch):
        """Bagging + rows that don't divide across shards: before the r5
        validity fix, split_stream's permutation let PADDING rows enter
        histograms on later iterations (positional mask), corrupting
        training.  2003 rows over 8 shards leaves 5 shards padded."""
        import jax as _jax
        import lightgbm_tpu as lgb

        if len(_jax.devices()) < 4:
            pytest.skip("needs multi-device mesh")
        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
        X, y = self._problem(n=2003)
        params = dict(objective="binary", num_leaves=15, learning_rate=0.2,
                      max_bin=31, min_data_in_leaf=20, tree_learner="data",
                      bagging_fraction=0.7, bagging_freq=1, verbose=-1)
        bst = lgb.train(params, lgb.Dataset(X, label=y, params=dict(params)), 6)
        from lightgbm_tpu.boosting.ptrainer import ShardedPartitionedTrainer

        assert isinstance(bst.boosting.ptrainer, ShardedPartitionedTrainer)
        from sklearn.metrics import roc_auc_score

        auc = roc_auc_score(y, bst.predict(X))
        assert auc > 0.85, auc


class TestMulticlassFused:
    def test_multiclass_matches_default(self, monkeypatch):
        import lightgbm_tpu as lgb

        rng = np.random.default_rng(7)
        n, f, K = 2400, 6, 3
        X = rng.standard_normal((n, f)).astype(np.float32)
        w = rng.standard_normal((f, K))
        y = np.argmax(X @ w + 0.3 * rng.standard_normal((n, K)), axis=1).astype(np.float32)
        params = dict(objective="multiclass", num_class=K, num_leaves=7,
                      learning_rate=0.2, max_bin=31, min_data_in_leaf=20,
                      verbose=-1)
        preds = {}
        for mode, env in [("pgrow", "force"), ("default", "0")]:
            monkeypatch.setenv("LIGHTGBM_TPU_PGROW", env)
            bst = lgb.train(params, lgb.Dataset(X, label=y, params=dict(params)), 3)
            if mode == "pgrow":
                assert bst.boosting.ptrainer is not None
                assert bst.boosting.ptrainer.K == K
            preds[mode] = bst.predict(X)
        np.testing.assert_allclose(preds["pgrow"], preds["default"], rtol=4e-3, atol=5e-4)

    def test_multiclassova_matches_default(self, monkeypatch):
        import lightgbm_tpu as lgb

        rng = np.random.default_rng(8)
        n, f, K = 1800, 5, 3
        X = rng.standard_normal((n, f)).astype(np.float32)
        w = rng.standard_normal((f, K))
        y = np.argmax(X @ w, axis=1).astype(np.float32)
        params = dict(objective="multiclassova", num_class=K, num_leaves=7,
                      learning_rate=0.2, max_bin=31, min_data_in_leaf=20,
                      verbose=-1)
        preds = {}
        for mode, env in [("pgrow", "force"), ("default", "0")]:
            monkeypatch.setenv("LIGHTGBM_TPU_PGROW", env)
            bst = lgb.train(params, lgb.Dataset(X, label=y, params=dict(params)), 3)
            preds[mode] = bst.predict(X)
        np.testing.assert_allclose(preds["pgrow"], preds["default"], rtol=4e-3, atol=5e-4)


class TestGossFused:
    def test_goss_matches_mask_path(self, monkeypatch):
        import lightgbm_tpu as lgb

        rng = np.random.default_rng(4)
        n, f = 3000, 8
        X = rng.standard_normal((n, f)).astype(np.float32)
        w = rng.standard_normal(f)
        y = (rng.random(n) < 1 / (1 + np.exp(-(X @ w)))).astype(np.float32)
        # learning_rate 0.5 -> GOSS sampling kicks in from iteration 2
        params = dict(objective="binary", boosting="goss", num_leaves=15,
                      learning_rate=0.5, max_bin=31, min_data_in_leaf=20,
                      top_rate=0.3, other_rate=0.2, verbose=-1)
        aucs = {}
        for mode, env in [("pgrow", "force"), ("default", "0")]:
            monkeypatch.setenv("LIGHTGBM_TPU_PGROW", env)
            bst = lgb.train(params, lgb.Dataset(X, label=y, params=dict(params)), 6)
            if mode == "pgrow":
                assert bst.boosting.ptrainer is not None
            pred = bst.predict(X)
            # RNG streams differ (threefry key vs split) -> compare
            # quality, not per-row predictions
            from sklearn.metrics import roc_auc_score
            aucs[mode] = roc_auc_score(y, pred)
        assert aucs["pgrow"] > 0.8 and aucs["default"] > 0.8
        assert abs(aucs["pgrow"] - aucs["default"]) < 0.05

    def test_goss_warm_iters_identical(self, monkeypatch):
        """Before 1/learning_rate iterations GOSS does no sampling, so
        fused and mask paths must agree exactly (to f32 tolerance)."""
        import lightgbm_tpu as lgb

        rng = np.random.default_rng(5)
        n, f = 2500, 6
        X = rng.standard_normal((n, f)).astype(np.float32)
        y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
        params = dict(objective="binary", boosting="goss", num_leaves=7,
                      learning_rate=0.1, max_bin=31, min_data_in_leaf=20,
                      verbose=-1)  # warm window = 10 iters > 3 trained
        preds = {}
        for mode, env in [("pgrow", "force"), ("default", "0")]:
            monkeypatch.setenv("LIGHTGBM_TPU_PGROW", env)
            bst = lgb.train(params, lgb.Dataset(X, label=y, params=dict(params)), 3)
            preds[mode] = bst.predict(X)
        np.testing.assert_allclose(preds["pgrow"], preds["default"], rtol=3e-3, atol=3e-4)


class TestLevelGrowerCaps:
    """Stress the level grower where its static caps bind (VERDICT item
    7): num_leaves=1023 exceeds the default level budget unless MAXLVL
    and the frontier sizing hold up, and the level-batched path must
    stay tree-identical to the per-split grower."""

    def test_num_leaves_1023_parity_with_levelgrow_off(self, monkeypatch):
        import lightgbm_tpu as lgb

        rng = np.random.default_rng(3)
        n, f = 5000, 8
        X = rng.standard_normal((n, f)).astype(np.float32)
        w = rng.standard_normal(f)
        y = (rng.random(n) < 1 / (1 + np.exp(-(X @ w)))).astype(np.float32)
        params = dict(objective="binary", num_leaves=1023, learning_rate=0.2,
                      max_bin=31, min_data_in_leaf=1, verbose=-1)
        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
        preds = {}
        leaves = {}
        for mode in ("1", "0"):
            monkeypatch.setenv("LIGHTGBM_TPU_LEVELGROW", mode)
            bst = lgb.train(params, lgb.Dataset(X, label=y, params=dict(params)), 2)
            assert bst.boosting.ptrainer is not None
            assert bst.boosting.ptrainer.params.levelwise == (mode == "1")
            preds[mode] = bst.predict(X)
            leaves[mode] = [t.num_leaves for t in bst.boosting.models]
        # with min_data_in_leaf=1 and 5000 rows the 1023-leaf cap BINDS
        assert leaves["1"] == leaves["0"]
        assert max(leaves["1"]) == 1023, leaves
        # level-batched growth is tree-identical to per-split growth
        np.testing.assert_array_equal(preds["1"], preds["0"])


class TestScoreAddBand:
    """score_add streams ONLY the 8-aligned mutable band (PR-6 fused
    score-update): exact += on the target score row, every other row —
    including the packed bin words it no longer reads — bit-identical."""

    def test_band_add_exact(self):
        n = 3000
        P, lay, bins, g, h, sel = _make_packed(n=n)
        rng = np.random.default_rng(21)
        delta = rng.standard_normal(n).astype(np.float32)
        P0 = np.asarray(P, np.int32)
        P2 = pk.score_add(jnp.array(P), lay, jnp.asarray(delta), 0,
                          num_rows=n, interpret=INTERP)
        P2n = np.asarray(P2, np.int32)
        want = P0[lay.SCORE, :n].view(np.float32) + delta
        np.testing.assert_array_equal(
            P2n[lay.SCORE, :n].view(np.float32), want)
        # nothing else moved (bin words, g/h, sel, label, rowid, weight)
        other = [r for r in range(lay.C) if r != lay.SCORE]
        np.testing.assert_array_equal(P2n[other][:, :n], P0[other][:, :n])

    def test_multiclass_channel_k(self):
        n = 2000
        rng = np.random.default_rng(22)
        f, K = 6, 3
        lay = pk.PLayout(f, num_score=K)
        bins = rng.integers(0, 16, size=(n, f), dtype=np.uint8)
        P = pk.pack_matrix(bins, lay, label=rng.random(n).astype(np.float32))
        delta = rng.standard_normal(n).astype(np.float32)
        P0 = np.asarray(P, np.int32)
        P2 = pk.score_add(jnp.array(P), lay, jnp.asarray(delta), 1,
                          num_rows=n, interpret=INTERP)
        P2n = np.asarray(P2, np.int32)
        np.testing.assert_array_equal(
            P2n[lay.SCORE + 1, :n].view(np.float32),
            P0[lay.SCORE + 1, :n].view(np.float32) + delta)
        other = [r for r in range(lay.C) if r != lay.SCORE + 1]
        np.testing.assert_array_equal(P2n[other][:, :n], P0[other][:, :n])


class TestUpdateHistFree:
    """update_and_root_hist(with_hist=False) — the GOSS gradient-prep /
    settle fast path — must write the exact same matrix as the
    histogram-carrying pass, just without the discarded histogram."""

    def test_matrix_bit_identical(self):
        n = 3000
        P, lay, bins, g, h, sel = _make_packed(n=n)
        rng = np.random.default_rng(23)
        delta = rng.standard_normal(n).astype(np.float32)
        sel_new = (rng.random(n) < 0.6).astype(np.float32)

        def grad_fn(score, label, weight):
            ps = 1.0 / (1.0 + jnp.exp(-score))
            return (ps - label) * weight, ps * (1.0 - ps) * weight

        Pa, hist = pk.update_and_root_hist(
            jnp.array(P), lay, grad_fn, delta=delta, sel=sel_new, num_rows=n,
            num_features=lay.F, num_bins=32, interpret=INTERP)
        Pb, no_hist = pk.update_and_root_hist(
            jnp.array(P), lay, grad_fn, delta=delta, sel=sel_new, num_rows=n,
            num_features=lay.F, num_bins=32, with_hist=False, interpret=INTERP)
        assert no_hist is None
        assert hist is not None and np.asarray(hist).shape == (lay.F, 32, 3)
        np.testing.assert_array_equal(np.asarray(Pa, np.int32),
                                      np.asarray(Pb, np.int32))
