"""Sequential numpy oracle mirroring the reference's exact split-finding and
tree-growth semantics (float64, scan order, tie-breaking) — the golden
reference for parity tests, per SURVEY §4's GPU_DEBUG_COMPARE strategy.

Mirrors:
- FindBestThresholdNumerical/Sequence (feature_histogram.hpp:78-98, 253-387)
- FindBestThresholdCategorical (feature_histogram.hpp:100-198)
- SerialTreeLearner::Train best-first loop (serial_tree_learner.cpp:152-207)
"""

import numpy as np

K_MIN_SCORE = -np.inf


def leaf_split_gain(G, H, l1, l2):
    reg = max(abs(G) - l1, 0.0)
    return reg * reg / (H + l2)


def leaf_output(G, H, l1, l2):
    reg = max(abs(G) - l1, 0.0)
    return -np.copysign(reg, G) / (H + l2)


class OracleSplit:
    def __init__(self):
        self.gain = K_MIN_SCORE
        self.feature = -1
        self.threshold = 0
        self.dbz = 0
        self.left = (0.0, 0.0, 0)  # sum_g, sum_h, cnt


def find_best_threshold_sequence(hist, sum_g, sum_h, num_data, min_gain_shift,
                                 default_bin, dbz, cfg, best):
    """hist: (B, 3) ndarray for one feature. Mutates/returns best dict with
    the reference's strictly-greater update rule."""
    num_bin = hist.shape[0]
    dir_ = 1 if dbz == num_bin - 1 else -1
    skip_default = not (0 < dbz < num_bin - 1)
    found = False
    b_gain, b_thr, b_left = K_MIN_SCORE, num_bin, None
    if dir_ == -1:
        rg = rh = 0.0
        rc = 0
        for t in range(num_bin - 1, 0, -1):
            if skip_default and t == default_bin:
                continue
            rg += hist[t, 0]
            rh += hist[t, 1]
            rc += int(hist[t, 2])
            if rc < cfg["min_data_in_leaf"] or rh < cfg["min_sum_hessian_in_leaf"]:
                continue
            lc = num_data - rc
            if lc < cfg["min_data_in_leaf"]:
                break
            lh = sum_h - rh
            if lh < cfg["min_sum_hessian_in_leaf"]:
                break
            lg = sum_g - rg
            gain = leaf_split_gain(lg, lh, cfg["lambda_l1"], cfg["lambda_l2"]) + \
                leaf_split_gain(rg, rh, cfg["lambda_l1"], cfg["lambda_l2"])
            if gain <= min_gain_shift:
                continue
            found = True
            if gain > b_gain:
                b_gain, b_thr, b_left = gain, t - 1, (lg, lh, lc)
    else:
        lg = lh = 0.0
        lc = 0
        for t in range(0, num_bin - 1):
            if skip_default and t == default_bin:
                continue
            lg += hist[t, 0]
            lh += hist[t, 1]
            lc += int(hist[t, 2])
            if lc < cfg["min_data_in_leaf"] or lh < cfg["min_sum_hessian_in_leaf"]:
                continue
            rc = num_data - lc
            if rc < cfg["min_data_in_leaf"]:
                break
            rh = sum_h - lh
            if rh < cfg["min_sum_hessian_in_leaf"]:
                break
            rg = sum_g - lg
            gain = leaf_split_gain(lg, lh, cfg["lambda_l1"], cfg["lambda_l2"]) + \
                leaf_split_gain(rg, rh, cfg["lambda_l1"], cfg["lambda_l2"])
            if gain <= min_gain_shift:
                continue
            found = True
            if gain > b_gain:
                b_gain, b_thr, b_left = gain, t, (lg, lh, lc)
    if found and b_gain > best["gain"]:
        best.update(gain=b_gain, threshold=b_thr, dbz=dbz, left=b_left)


def find_best_threshold_numerical(hist, sum_g, sum_h, num_data, default_bin,
                                  cfg, use_missing=True):
    num_bin = hist.shape[0]
    gain_shift = leaf_split_gain(sum_g, sum_h, cfg["lambda_l1"], cfg["lambda_l2"])
    min_gain_shift = gain_shift + cfg["min_gain_to_split"]
    best = dict(gain=K_MIN_SCORE, threshold=num_bin, dbz=default_bin, left=None)
    if use_missing:
        find_best_threshold_sequence(hist, sum_g, sum_h, num_data, min_gain_shift,
                                     default_bin, 0, cfg, best)
        if 0 < default_bin < num_bin - 1:
            find_best_threshold_sequence(hist, sum_g, sum_h, num_data, min_gain_shift,
                                         default_bin, default_bin, cfg, best)
        if num_bin > 2:
            find_best_threshold_sequence(hist, sum_g, sum_h, num_data, min_gain_shift,
                                         default_bin, num_bin - 1, cfg, best)
    else:
        find_best_threshold_sequence(hist, sum_g, sum_h, num_data, min_gain_shift,
                                     default_bin, default_bin, cfg, best)
    if np.isfinite(best["gain"]):
        best["gain"] -= min_gain_shift
    return best


def find_best_threshold_categorical(hist, sum_g, sum_h, num_data, default_bin, cfg):
    num_bin = hist.shape[0]
    gain_shift = leaf_split_gain(sum_g, sum_h, cfg["lambda_l1"], cfg["lambda_l2"])
    min_gain_shift = gain_shift + cfg["min_gain_to_split"]
    best = dict(gain=K_MIN_SCORE, threshold=num_bin, dbz=default_bin, left=None)
    b_gain, b_thr, b_left = K_MIN_SCORE, num_bin, None
    found = False
    for t in range(num_bin - 1, -1, -1):
        cg, chh, cc = hist[t, 0], hist[t, 1], int(hist[t, 2])
        if cc < cfg["min_data_in_leaf"] or chh < cfg["min_sum_hessian_in_leaf"]:
            continue
        oc = num_data - cc
        if oc < cfg["min_data_in_leaf"]:
            continue
        oh = sum_h - chh
        if oh < cfg["min_sum_hessian_in_leaf"]:
            continue
        og = sum_g - cg
        gain = leaf_split_gain(og, oh, cfg["lambda_l1"], cfg["lambda_l2"]) + \
            leaf_split_gain(cg, chh, cfg["lambda_l1"], cfg["lambda_l2"])
        if gain <= min_gain_shift:
            continue
        found = True
        if gain > b_gain:
            b_gain, b_thr, b_left = gain, t, (cg, chh, cc)
    if found:
        best.update(gain=b_gain - min_gain_shift, threshold=b_thr, left=b_left)
    return best


def build_histogram_np(bins, grad, hess, select, num_bins):
    """float64 (F, B, 3) histogram oracle."""
    n, f = bins.shape
    hist = np.zeros((f, num_bins, 3))
    for j in range(f):
        np.add.at(hist[j], bins[:, j], np.stack([grad * select, hess * select, select], 1))
    return hist


def best_split_all_features_np(hist, sum_g, sum_h, num_data, default_bin,
                               is_cat, num_bins_per_feat, cfg, use_missing=True):
    """Cross-feature ArgMax (first max wins) over per-feature bests."""
    best = None
    for j in range(hist.shape[0]):
        h = hist[j, : num_bins_per_feat[j]]
        if is_cat[j]:
            r = find_best_threshold_categorical(h, sum_g, sum_h, num_data,
                                                default_bin[j], cfg)
        else:
            r = find_best_threshold_numerical(h, sum_g, sum_h, num_data,
                                              default_bin[j], cfg, use_missing)
        r["feature"] = j
        if best is None or r["gain"] > best["gain"]:
            best = r
    return best
