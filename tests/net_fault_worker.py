"""Worker for the real-subprocess fault matrix (test_net_fault.py).

argv: ``rank nproc port out mode [ckdir]``.  The harness sets
``LIGHTGBM_TPU_NET_TIMEOUT`` (the deadline under test) for every rank
and ``LIGHTGBM_TPU_FAULT`` only in the target rank's environment.

modes:
  gather   — loop ``allgather_bytes``; the faulted rank dies (SIGKILL
             itself) or wedges at call N; every survivor records the
             typed error + elapsed time and leaves via ``net.hard_exit``
  barrier  — the same loop over ``collect.barrier``
  init     — bounded-bootstrap probe: the coordinator address never
             answers; the watchdogged ``jax.distributed.initialize``
             must fail loudly within the retry budget instead of
             hanging (the BENCH_r05 dead-tunnel class)
  train    — both ranks train the SAME data with a shared
             ``CheckpointManager`` (the multihost ckpt barrier is the
             collective under test); used for the kill -> detect ->
             flush -> auto-resume acceptance proof.  Survivors of a
             peer failure exit with code 75 (cli.EXIT_PEER_FAILURE).
             With ``LIGHTGBM_TPU_TRACE`` set, the survivor's typed
             failure additionally flushes the crash flight recorder
             (obs/flight.py) — the ``report merge``/crash-dump
             acceptance legs ride this mode.
  wfeature / wvoting — full lgb.train over the host-driven
             feature-parallel / voting-parallel learner
             (parallel/hostlearner.py); the faulted rank dies mid-
             collective and every survivor must classify a typed
             PeerFailureError within the bound and leave with exit
             code 75 — the wide learners share the hardened
             transport's failure semantics unchanged.
  mergetrace — clean 2-rank "training" loop (compute span + hardened
             barrier per iteration, KV transport) with per-rank traces;
             MERGETRACE_COMPUTE_S skews one rank into a straggler so
             the test can assert ``report merge`` attribution.
"""

import json
import os
import sys
import time

rank = int(sys.argv[1])
nproc = int(sys.argv[2])
port = sys.argv[3]
out = sys.argv[4]
mode = sys.argv[5]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["LIGHTGBM_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
os.environ["LIGHTGBM_TPU_NUM_PROCESSES"] = str(nproc)
os.environ["LIGHTGBM_TPU_PROCESS_ID"] = str(rank)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from lightgbm_tpu.parallel import net  # noqa: E402
from lightgbm_tpu.parallel.distributed import ensure_initialized  # noqa: E402

DEADLINE = float(os.environ.get("LIGHTGBM_TPU_NET_TIMEOUT", "4"))


def _write(payload: dict) -> None:
    with open(out + f".rank{rank}.json", "w") as fh:
        json.dump(payload, fh)


if mode == "init":
    # nothing listens on the coordinator port: the bootstrap must fail
    # LOUDLY and bounded, not hang
    t0 = time.time()
    try:
        ensure_initialized()
        print("UNEXPECTED: bootstrap succeeded")
        sys.exit(2)
    except net.CollectiveTimeoutError as e:
        _write({"error": "CollectiveTimeoutError",
                "wall": time.time() - t0, "msg": str(e)})
        sys.exit(0)

assert ensure_initialized() is True
import jax  # noqa: E402

# the axon TPU plugin ignores JAX_PLATFORMS; the config knob still wins
jax.config.update("jax_platforms", "cpu")
assert jax.process_count() == nproc

from lightgbm_tpu.parallel import collect  # noqa: E402

if mode == "mergetrace":
    # clean run: per-iteration compute (skewed per rank via
    # MERGETRACE_COMPUTE_S) + the hardened KV barrier, traced per rank —
    # the `report merge` straggler-attribution acceptance leg
    from lightgbm_tpu.obs import tracer

    tracer.refresh_from_env()  # LIGHTGBM_TPU_TRACE + rank/world identity
    assert tracer.enabled, "mergetrace mode needs LIGHTGBM_TPU_TRACE"
    compute_s = float(os.environ.get("MERGETRACE_COMPUTE_S", "0.02"))
    for i in range(4):
        with tracer.iteration(i):
            with tracer.span("histogram"):
                time.sleep(compute_s)
            collect.barrier(tag=f"it{i}")
    tracer.close()
    _write({"error": None, "iters": 4})
    print(f"rank {rank} mergetrace done")
    sys.exit(0)

if mode in ("gather", "barrier"):
    t_enter = time.time()
    try:
        for i in range(5):
            t_enter = time.time()
            if mode == "barrier":
                collect.barrier(tag=f"iter{i}")
            else:
                blobs = collect.allgather_bytes(f"r{rank}i{i}".encode())
                assert len(blobs) == nproc
        print(f"rank {rank} UNEXPECTED: all collectives completed")
        _write({"error": None})
        sys.exit(2)
    except net.PeerFailureError as e:
        _write({"error": "PeerFailureError", "ranks": list(e.ranks),
                "elapsed": e.elapsed_s, "wall": time.time() - t_enter})
    except net.CollectiveTimeoutError as e:
        _write({"error": "CollectiveTimeoutError",
                "elapsed": e.elapsed_s, "wall": time.time() - t_enter})
    print(f"rank {rank} {mode} recorded failure; hard exit")
    net.hard_exit(0)  # the atexit shutdown barrier would hang on the corpse

if mode in ("wfeature", "wvoting"):
    # wide-data learners ride the same hardened collect.allgather_bytes
    # path, so die:N lands inside a histogram/best-split/vote exchange
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.cli import EXIT_PEER_FAILURE

    learner = "feature" if mode == "wfeature" else "voting"
    rng = np.random.default_rng(13)
    N, F = 1200, 20
    X = rng.integers(0, 12, size=(N, F)).astype(np.float32)
    w = rng.standard_normal(F)
    y = (rng.random(N) < 1.0 / (1.0 + np.exp(-((X - 6) @ w * 0.2)))
         ).astype(np.float32)
    p = dict(objective="binary", tree_learner=learner, num_machines=nproc,
             boost_from_average=False, num_leaves=15, min_data_in_leaf=20,
             top_k=4, verbose=-1)
    if learner == "voting":
        p["pre_partition"] = True
        sl = slice(rank * N // nproc, (rank + 1) * N // nproc)
        ds = lgb.Dataset(X[sl], label=y[sl], params=dict(p))
    else:
        ds = lgb.Dataset(X, label=y, params=dict(p))
    t0 = time.time()
    try:
        bst = lgb.train(dict(p), ds, 10, verbose_eval=False)
    except net.PeerFailureError as e:
        _write({"error": "PeerFailureError", "ranks": list(e.ranks),
                "elapsed": e.elapsed_s, "wall": time.time() - t0})
        print(f"rank {rank} {mode}: peer failure after {e.elapsed_s:.1f}s")
        net.hard_exit(EXIT_PEER_FAILURE)
    _write({"error": None, "trees": bst.num_trees})
    print(f"rank {rank} {mode} UNEXPECTED clean finish")
    sys.exit(2)

if mode == "train":
    # acceptance leg (ISSUE 5): each rank trains the SAME data locally
    # (no multi-process XLA — this environment's CPU backend rejects
    # it); the ONLY collective is the multihost checkpoint barrier, so a
    # rank SIGKILLed by die:N dies exactly mid-barrier.
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.ckpt import CheckpointManager
    from lightgbm_tpu.ckpt.store import CheckpointStore
    from lightgbm_tpu.cli import EXIT_PEER_FAILURE

    ckdir = sys.argv[6]
    rng = np.random.default_rng(7)
    N, F = 900, 8
    X = rng.standard_normal((N, F)).astype(np.float32)
    w = rng.standard_normal(F)
    y = (rng.random(N) < 1.0 / (1.0 + np.exp(-(X @ w)))).astype(np.float32)
    p = dict(objective="binary", num_leaves=15, learning_rate=0.2,
             min_data_in_leaf=20, verbose=-1)

    latest = CheckpointStore(ckdir).latest_valid()
    resume_from = latest[0] if latest is not None else None

    mgr = CheckpointManager(ckdir, freq=3)
    try:
        bst = lgb.train(dict(p), lgb.Dataset(X, label=y, params=dict(p)),
                        12, verbose_eval=False, checkpoint_manager=mgr)
    except net.PeerFailureError as e:
        mgr.flush()
        _write({"error": "PeerFailureError", "ranks": list(e.ranks),
                "elapsed": e.elapsed_s, "resume_from": resume_from})
        print(f"rank {rank} detected peer failure after {e.elapsed_s:.1f}s")
        net.hard_exit(EXIT_PEER_FAILURE)
    mgr.close()
    with open(out + f".rank{rank}.txt", "w") as fh:
        fh.write(bst.model_to_string())
    _write({"error": None, "trees": bst.num_trees,
            "resume_from": resume_from})
    print(f"rank {rank} train done (resume_from={resume_from})")
    sys.exit(0)  # clean exit: every rank alive, shutdown barrier passes

print(f"unknown mode {mode}")
sys.exit(2)
