"""Test configuration: run everything on a virtual 8-device CPU mesh so
multi-chip sharding paths (data/feature/voting-parallel learners) are
exercised without TPU pod hardware. Must run before jax is imported."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon TPU plugin ignores JAX_PLATFORMS; the config knob still wins.
jax.config.update("jax_platforms", "cpu")

# Importing the package pulls in Pallas, which triggers the axon plugin's
# registration; that registration OVERWRITES jax_platforms with
# "axon,cpu" (and would make the first jax.devices() in a test module
# initialize the axon client — hanging forever when the tunnel is dead).
# Import it now, re-assert cpu, and pin the backend cache.
import lightgbm_tpu  # noqa: E402,F401

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu"

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def examples_dir():
    for cand in ("/root/repo/examples", "/root/reference/examples"):
        if os.path.isdir(cand):
            return cand
    pytest.skip("no examples directory")


@pytest.fixture(scope="session")
def reference_examples():
    """The reference checkout's example datasets. Hosts without the
    read-only /root/reference mirror must skip the parity/CLI legs
    loudly — an absent checkout is an environment gap, not a code
    failure, and should never surface as np.loadtxt/shutil errors."""
    path = "/root/reference/examples"
    if not os.path.isdir(path):
        pytest.skip("reference examples not present at "
                    "/root/reference/examples (environment lacks the "
                    "reference checkout; not a code failure)")
    return path


@pytest.fixture
def rng():
    return np.random.RandomState(42)
