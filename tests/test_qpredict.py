"""Quantized-serving tests: the int16 rank-quantized traversal
(``ops/qpredict``), the quantized artifact flavor, and the
``LIGHTGBM_TPU_QUANT_PREDICT`` pin.

The accuracy contract under test: route decisions (leaf assignments)
must agree EXACTLY with the f64 reference for every input — the rank
encoding removes the bin-boundary caveat — and raw scores may drift only
by the f16/bf16 leaf narrowing, within ``drift_bound``.
"""

import io

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import compilewatch
from lightgbm_tpu.ops import qpredict as qp
from lightgbm_tpu.serve import (
    BucketedQuantizedPredictor,
    PackedPredictor,
    PredictorArtifact,
    SwappablePredictor,
    pad_qtree_arrays,
    tree_shape_bucket,
)
from lightgbm_tpu.utils.log import LightGBMError


def _train(seed, n=500, f=10, rounds=10, leaves=15, objective="binary",
           num_class=1):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    # plant exact zeros and NaN so the default-value remap is exercised
    X[rng.rand(n, f) < 0.05] = 0.0
    if objective == "binary":
        y = (X[:, 0] + 0.5 * X[:, 1] - X[:, 2] ** 2 > -0.5).astype(np.float32)
    elif objective == "multiclass":
        y = (np.abs(X[:, 0]) + X[:, 1] > 0.7).astype(np.float32) + (
            X[:, 2] > 0.5).astype(np.float32)
    else:
        y = (X[:, 0] + 0.3 * X[:, 1] ** 2).astype(np.float32)
    params = {"objective": objective, "num_leaves": leaves, "verbose": -1}
    if objective == "multiclass":
        params["num_class"] = num_class
    ds = lgb.Dataset(X, label=y, params={"min_data_in_leaf": 5})
    bst = lgb.train(params, ds, num_boost_round=rounds, verbose_eval=False)
    return bst, X, rng


def _eval_rows(X, rng):
    """Adversarial request rows: fresh draws + zeros + NaN + rows copied
    from training data (which sit EXACTLY on split thresholds)."""
    rows = np.concatenate([rng.randn(67, X.shape[1]), X[:40]], axis=0)
    rows[3, 0] = 0.0
    rows[5, 1] = np.nan
    rows[7] = 0.0
    return rows


def _qpredict_scores(q, rows):
    """(N,) single-class raw scores through the direct kernel."""
    import jax.numpy as jnp

    qb = qp.quantize_data(rows, q.qbin_edges, q.qbin_offsets,
                          q.feature_flags)
    args = [jnp.asarray(getattr(q, f)) for f in q.NODE_FIELDS]
    return np.asarray(
        qp.qpredict_raw(jnp.asarray(qb), *args, levels=q.levels), np.float64)


def _qpredict_leaves(q, rows):
    import jax.numpy as jnp

    qb = qp.quantize_data(rows, q.qbin_edges, q.qbin_offsets,
                          q.feature_flags)
    args = [jnp.asarray(getattr(q, f)) for f in q.NODE_FIELDS[:-1]]
    return np.asarray(
        qp.qpredict_leaf(jnp.asarray(qb), *args, levels=q.levels))


# ----------------------------------------------------------------------
# encoding
# ----------------------------------------------------------------------
class TestEncoding:
    def test_even_odd_rank_codes(self):
        table = np.array([-1.5, 0.25, 3.0])
        v = np.array([-2.0, -1.5, -1.0, 0.25, 1.0, 3.0, 4.0])
        got = qp._encode(table, v)
        #      below t0, ==t0, between, ==t1, between, ==t2, above
        assert got.tolist() == [0, 1, 2, 3, 4, 5, 6]
        # the node code for table[i] is 2i+1; v <= t  <=>  code <= 2i+1
        for i, t in enumerate(table):
            assert np.array_equal(got <= 2 * i + 1, v <= t)
            assert np.array_equal(got == 2 * i + 1, v == t)

    def test_empty_table(self):
        assert qp._encode(np.array([]), np.array([1.0, -1.0])).tolist() \
            == [0, 0]

    def test_quantize_data_zero_and_nan_sentinel(self, ):
        edges = np.array([0.5, 2.0])
        off = np.array([0, 2], np.int32)
        flags = np.zeros(1, np.int8)
        rows = np.array([[1.0], [0.0], [np.nan], [1e-40], [3.0]])
        got = qp.quantize_data(rows, edges, off, flags)
        assert got.dtype == np.int16
        assert got[1, 0] == qp.ZERO_CODE
        assert got[2, 0] == qp.ZERO_CODE
        assert got[3, 0] == qp.ZERO_CODE  # inside MISSING_VALUE_RANGE
        assert got[0, 0] == 2 and got[4, 0] == 4


# ----------------------------------------------------------------------
# traversal accuracy: randomized A/B property test vs the exact path
# ----------------------------------------------------------------------
class TestQuantizedAccuracy:
    @pytest.mark.parametrize("seed,objective,leaves,rounds", [
        (0, "binary", 15, 10),
        (1, "binary", 31, 20),
        (2, "regression", 15, 12),
        (3, "binary", 7, 5),
    ])
    def test_leaf_routes_exact_scores_within_bound(self, seed, objective,
                                                   leaves, rounds):
        bst, X, rng = _train(seed, objective=objective, leaves=leaves,
                             rounds=rounds)
        art = PredictorArtifact.from_booster(bst)
        q = qp.quantize_tree_arrays(art.arrays,
                                    num_features=art.num_features)
        rows = _eval_rows(X, rng)
        # route decisions agree EXACTLY with the f64 reference
        ref_leaves = bst.predict(rows, pred_leaf=True)
        if ref_leaves.ndim == 1:
            ref_leaves = ref_leaves.reshape(-1, 1)
        assert np.array_equal(_qpredict_leaves(q, rows).T, ref_leaves)
        # raw scores drift only by the leaf narrowing, within the bound
        ref = bst.predict(rows, raw_score=True)
        bound = qp.drift_bound(art.arrays.leaf_value)
        diff = np.abs(_qpredict_scores(q, rows) - ref).max()
        assert diff <= bound, f"drift {diff} exceeds bound {bound}"

    @pytest.mark.parametrize("leaf_dtype", ["float16", "bfloat16"])
    def test_leaf_dtypes(self, leaf_dtype):
        bst, X, rng = _train(5)
        art = PredictorArtifact.from_booster(bst)
        q = qp.quantize_tree_arrays(art.arrays, leaf_dtype=leaf_dtype,
                                    num_features=art.num_features)
        assert q.leaf_dtype == leaf_dtype
        rows = _eval_rows(X, rng)
        bound = qp.drift_bound(art.arrays.leaf_value, leaf_dtype=leaf_dtype)
        diff = np.abs(_qpredict_scores(q, rows)
                      - bst.predict(rows, raw_score=True)).max()
        assert diff <= bound

    def test_multiclass(self):
        bst, X, rng = _train(6, objective="multiclass", num_class=3)
        art = PredictorArtifact.from_booster(bst)
        pq = PackedPredictor(art, quantized=True)
        rows = _eval_rows(X, rng)
        got = pq.predict(rows)
        ref = bst.predict(rows)
        assert got.shape == ref.shape
        assert np.abs(got - ref).max() < 1e-2
        # probabilities still normalize
        assert np.allclose(got.sum(axis=1), 1.0, atol=1e-6)

    def test_bucketed_predictor_matches_direct_traversal(self):
        bst, X, rng = _train(7)
        art = PredictorArtifact.from_booster(bst)
        q = qp.quantize_tree_arrays(art.arrays,
                                    num_features=art.num_features)
        rows = _eval_rows(X, rng)
        direct = _qpredict_scores(q, rows)
        bq = BucketedQuantizedPredictor.from_qtree_arrays(q, 1)
        assert np.allclose(bq.predict_raw_scores(rows), direct, atol=1e-6)


# ----------------------------------------------------------------------
# artifact flavor + env pin
# ----------------------------------------------------------------------
class TestQuantizedArtifact:
    def test_roundtrip_and_versioning(self, tmp_path):
        bst, X, rng = _train(8)
        exact = PredictorArtifact.from_booster(bst)
        quant = PredictorArtifact.from_booster(bst, quantized=True)
        assert exact.flavor == "exact"
        assert exact.meta["format_version"] == 1
        assert quant.flavor == "quantized"
        assert quant.meta["format_version"] == 2
        assert quant.meta["leaf_dtype"] == "float16"
        path = quant.save(str(tmp_path / "q"))
        loaded = PredictorArtifact.load(path)
        assert loaded.flavor == "quantized"
        rows = _eval_rows(X, rng)
        assert np.array_equal(PackedPredictor(quant).predict(rows),
                              PackedPredictor(loaded).predict(rows))

    def test_bfloat16_roundtrip(self, tmp_path):
        bst, X, rng = _train(9)
        quant = PredictorArtifact.from_booster(bst, quantized=True,
                                               leaf_dtype="bfloat16")
        assert quant.arrays.leaf_dtype == "bfloat16"
        buf = io.BytesIO()
        quant.save_to_bytes(buf)
        loaded = PredictorArtifact.load_bytes(buf.getvalue())
        assert loaded.arrays.leaf_dtype == "bfloat16"
        rows = _eval_rows(X, rng)
        assert np.array_equal(PackedPredictor(quant).predict(rows),
                              PackedPredictor(loaded).predict(rows))

    def test_quantize_from_loaded_exact_is_lossless(self, tmp_path):
        """Triple-float reconstruction is exact, so quantizing a loaded
        exact artifact equals quantizing straight off the booster."""
        bst, X, rng = _train(10)
        direct = PredictorArtifact.from_booster(bst, quantized=True)
        path = PredictorArtifact.from_booster(bst).save(str(tmp_path / "e"))
        via_disk = PredictorArtifact.load(path).quantize()
        rows = _eval_rows(X, rng)
        assert np.array_equal(PackedPredictor(direct).predict(rows),
                              PackedPredictor(via_disk).predict(rows))

    def test_artifact_bytes_reduced(self):
        """The quantized flavor's serialized payload and device-resident
        bytes must both be at least 2x smaller (uncompressed payload; the
        traversal state drops from 11 wide planes to 7 narrow ones)."""
        bst, _, _ = _train(11, rounds=30, leaves=31)
        exact = PredictorArtifact.from_booster(bst)
        quant = exact.quantize()
        ex_payload = sum(a.nbytes for a in exact._payload().values())
        q_payload = sum(a.nbytes for a in quant._payload().values())
        assert q_payload * 2 <= ex_payload, (ex_payload, q_payload)
        assert quant.device_bytes_estimate() * 2 \
            <= exact.device_bytes_estimate()
        ex_dev = PackedPredictor(exact, quantized=False).device_bytes
        q_dev = PackedPredictor(quant).device_bytes
        assert q_dev * 2 <= ex_dev, (ex_dev, q_dev)

    def test_env_pin_off_forces_exact(self, monkeypatch):
        bst, X, rng = _train(12)
        art = PredictorArtifact.from_booster(bst)
        rows = _eval_rows(X, rng)
        ref = PackedPredictor(art).predict(rows)
        monkeypatch.setenv("LIGHTGBM_TPU_QUANT_PREDICT", "0")
        # quantized=True is overridden by the pin: bit-identical output
        pinned = PackedPredictor(art, quantized=True)
        assert not pinned.quantized
        assert np.array_equal(pinned.predict(rows), ref)
        # Booster.predict honors the pin end-to-end
        assert np.array_equal(bst.predict(rows), ref)

    def test_env_pin_on_routes_booster_predict(self, monkeypatch):
        bst, X, rng = _train(13)
        rows = _eval_rows(X, rng)
        ref = bst.predict(rows, raw_score=True)
        monkeypatch.setenv("LIGHTGBM_TPU_QUANT_PREDICT", "1")
        got = bst.predict(rows, raw_score=True)
        bound = qp.drift_bound(
            PredictorArtifact.from_booster(bst).arrays.leaf_value)
        assert np.abs(got - ref).max() <= bound
        # leaf routes are unaffected by the pin (exact by construction)
        assert np.array_equal(bst.predict(rows, pred_leaf=True),
                              _unpinned_leaves(bst, rows, monkeypatch))

    def test_quantized_artifact_with_pin_off_warns_and_serves(
            self, monkeypatch):
        quant = PredictorArtifact.from_booster(_train(14)[0], quantized=True)
        monkeypatch.setenv("LIGHTGBM_TPU_QUANT_PREDICT", "0")
        p = PackedPredictor(quant)  # no exact planes left: stays quantized
        assert p.quantized

    def test_oversized_model_refused(self):
        bst, _, _ = _train(15)
        art = PredictorArtifact.from_booster(bst)
        with pytest.raises(LightGBMError, match="exact artifact"):
            qp.quantize_tree_arrays(art.arrays, num_features=40000)


def _unpinned_leaves(bst, rows, monkeypatch):
    monkeypatch.delenv("LIGHTGBM_TPU_QUANT_PREDICT", raising=False)
    out = bst.predict(rows, pred_leaf=True)
    monkeypatch.setenv("LIGHTGBM_TPU_QUANT_PREDICT", "1")
    return out


# ----------------------------------------------------------------------
# compile-cache integration: level padding + zero-compile swap
# ----------------------------------------------------------------------
class TestQuantizedCompileCache:
    def test_pad_qtree_levels_power_of_two(self):
        bst, _, _ = _train(16)
        art = PredictorArtifact.from_booster(bst)
        q = qp.quantize_tree_arrays(art.arrays,
                                    num_features=art.num_features)
        padded = pad_qtree_arrays(q)
        assert padded.levels == tree_shape_bucket(q.levels)
        assert padded.split_feature.shape[1] \
            == tree_shape_bucket(q.split_feature.shape[1])
        assert padded.leaf_value.shape[1] \
            == tree_shape_bucket(q.leaf_value.shape[1])

    def test_same_shape_quantized_swap_zero_new_compiles(self):
        """The multi-model acceptance contract: retraining with the same
        config and hot-swapping the QUANTIZED artifact must reuse every
        XLA program — zero new compiles."""
        bst, X, _ = _train(17)
        bst2, _, _ = _train(18)  # same config, different data -> same shapes
        a1 = PredictorArtifact.from_booster(bst, quantized=True)
        a2 = PredictorArtifact.from_booster(bst2, quantized=True)
        sw = SwappablePredictor(PackedPredictor(a1), version=1)
        sw.warmup(64)
        stats = sw.swap_to(a2, 2, warmup_max_rows=64)
        assert stats["new_compiles"] == 0, stats
        out, ver = sw.predict(X[:8])
        assert ver == 2 and out.shape == (8,)
