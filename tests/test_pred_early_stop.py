"""Prediction early-stop tests vs the reference semantics
(src/boosting/prediction_early_stop.cpp:74-89 + the Predictor's
round-period wiring): the margin callback fires only every
``round_period`` iterations, binary margin is ``2*|pred|``, multiclass
margin is the top-2 gap, and "none" never stops.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.boosting.pred_early_stop import (
    create_prediction_early_stop_instance,
    predict_with_early_stop,
)
from lightgbm_tpu.model.tree import Tree
from lightgbm_tpu.utils.log import LightGBMError


class _FakeBoosting:
    """Minimal boosting stub: constant trees, so each iteration adds a
    known value per class and the stop point is computable by hand."""

    def __init__(self, values, k):
        # values: flat per-tree outputs, tree i belongs to class i % k
        self.models = [Tree.constant(v) for v in values]
        self.num_tree_per_iteration = k

    def _used_models(self, num_iteration=-1):
        if num_iteration > 0:
            return self.models[: num_iteration * self.num_tree_per_iteration]
        return self.models


ROW = np.zeros((1, 3))


class TestCallbacks:
    def test_binary_margin_formula(self):
        inst = create_prediction_early_stop_instance("binary", 1, 1.0)
        assert inst.round_period == 1
        assert not inst.callback(np.array([0.5]))   # 2*0.5 == margin, not >
        assert inst.callback(np.array([0.51]))
        assert inst.callback(np.array([-0.51]))     # absolute value

    def test_binary_requires_single_output(self):
        inst = create_prediction_early_stop_instance("binary", 1, 1.0)
        with pytest.raises(LightGBMError, match="length one"):
            inst.callback(np.array([0.1, 0.2]))

    def test_multiclass_top2_gap(self):
        inst = create_prediction_early_stop_instance("multiclass", 1, 1.0)
        assert not inst.callback(np.array([2.0, 1.5, 0.0]))  # gap 0.5
        assert inst.callback(np.array([2.6, 1.5, 0.0]))      # gap 1.1

    def test_multiclass_requires_two_outputs(self):
        inst = create_prediction_early_stop_instance("multiclass", 1, 1.0)
        with pytest.raises(LightGBMError, match="length two"):
            inst.callback(np.array([0.1]))

    def test_none_never_stops(self):
        inst = create_prediction_early_stop_instance("none")
        assert inst.round_period == 1 << 30
        assert not inst.callback(np.array([1e9]))

    def test_unknown_type_fatal(self):
        with pytest.raises(LightGBMError, match="Unknown early stopping"):
            create_prediction_early_stop_instance("bogus")


class TestRoundPeriod:
    def test_binary_stops_at_first_checked_round(self):
        # each iteration adds 0.3; margin 1.0 is crossed at iter 2
        # (2*0.6 > 1.0), and period=2 checks iter 2 -> stop with 0.6
        b = _FakeBoosting([0.3] * 6, k=1)
        inst = create_prediction_early_stop_instance("binary", 2, 1.0)
        out = predict_with_early_stop(b, ROW, inst)
        assert out.shape == (1, 1)
        assert np.isclose(out[0, 0], 0.6)

    def test_binary_round_period_delays_stop(self):
        # same trees, but period=4: the margin is crossed at iter 2 and
        # NOT checked until iter 4 -> 4 iterations accumulate (0.3*4)
        b = _FakeBoosting([0.3] * 6, k=1)
        inst = create_prediction_early_stop_instance("binary", 4, 1.0)
        out = predict_with_early_stop(b, ROW, inst)
        assert np.isclose(out[0, 0], 1.2)

    def test_binary_huge_margin_runs_all_trees(self):
        b = _FakeBoosting([0.3] * 6, k=1)
        inst = create_prediction_early_stop_instance("binary", 1, 1e9)
        out = predict_with_early_stop(b, ROW, inst)
        assert np.isclose(out[0, 0], 1.8)

    def test_multiclass_stops_on_top2_gap(self):
        # class 0 gains 0.5/iter, class 1 gains 0.1/iter: gap 0.4*i
        # crosses margin 1.0 at iter 3; period=1 stops there
        b = _FakeBoosting([0.5, 0.1] * 5, k=2)
        inst = create_prediction_early_stop_instance("multiclass", 1, 1.0)
        out = predict_with_early_stop(b, ROW, inst)
        assert np.allclose(out[0], [1.5, 0.3])

    def test_multiclass_round_period(self):
        # period=2 checks iters 2 (gap 0.8, no) and 4 (gap 1.6, stop)
        b = _FakeBoosting([0.5, 0.1] * 5, k=2)
        inst = create_prediction_early_stop_instance("multiclass", 2, 1.0)
        out = predict_with_early_stop(b, ROW, inst)
        assert np.allclose(out[0], [2.0, 0.4])

    def test_per_row_independence(self):
        # rows stop independently: a constant-tree model gives every row
        # the same trajectory, so both rows stop at the same point
        b = _FakeBoosting([0.3] * 6, k=1)
        inst = create_prediction_early_stop_instance("binary", 2, 1.0)
        out = predict_with_early_stop(b, np.zeros((2, 3)), inst)
        assert np.allclose(out[:, 0], 0.6)


class TestBoosterIntegration:
    def test_pred_early_stop_param_matches_full_predict(self):
        """With a huge margin the early-stop path runs every tree; its
        host-side f64 walk must agree with the device predict to float
        tolerance (the device sums leaf values in f32)."""
        rng = np.random.RandomState(9)
        X = rng.randn(120, 6)
        y = (X[:, 0] + X[:, 1] > 0).astype(np.float32)
        ds = lgb.Dataset(X, label=y, params={"min_data_in_leaf": 5})
        bst = lgb.train(
            {"objective": "binary", "num_leaves": 7, "verbose": -1,
             "pred_early_stop": True, "pred_early_stop_freq": 5,
             "pred_early_stop_margin": 1e15},
            ds, num_boost_round=8, verbose_eval=False,
        )
        es = bst.predict(X[:25], raw_score=True)
        full = bst.boosting._predict_raw_scores_unbucketed(
            np.asarray(X[:25], np.float64),
            bst.boosting._used_models(-1),
            bst.boosting.num_tree_per_iteration,
        )[0]
        assert np.allclose(es, full, rtol=1e-5, atol=1e-6)

    def test_pred_early_stop_small_margin_diverges(self):
        """A small margin must actually exit early (different raw scores
        than the full walk for at least some rows)."""
        rng = np.random.RandomState(9)
        X = rng.randn(120, 6)
        y = (X[:, 0] + X[:, 1] > 0).astype(np.float32)
        ds = lgb.Dataset(X, label=y, params={"min_data_in_leaf": 5})
        params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
                  "pred_early_stop": True, "pred_early_stop_freq": 1,
                  "pred_early_stop_margin": 0.01}
        bst = lgb.train(dict(params), ds, num_boost_round=20,
                        verbose_eval=False)
        es = bst.predict(X[:40], raw_score=True)
        full = bst.boosting._predict_raw_scores_unbucketed(
            np.asarray(X[:40], np.float64),
            bst.boosting._used_models(-1),
            bst.boosting.num_tree_per_iteration,
        )[0]
        assert not np.allclose(es, full)
