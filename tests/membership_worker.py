"""Worker for the live-membership matrix (test_membership.py,
bench.py's ``spot`` section, factory/spot.py fleets).

argv: ``member_id fleet_dir out`` — unlike elastic_worker.py there is
NO jax.distributed bootstrap: every member runs single-process JAX and
ALL coordination rides the fleet directory's FileKVClient
(parallel/membership.py).  ``member_id`` of ``join`` means mid-run
arrival (the id is allocated from the store).

The global dataset is generated IDENTICALLY on every member from a
fixed seed (integer-valued features, so bin mappers are bit-identical
on any slice) and doubles as the ``row_provider`` seam: transitions
regenerate row slices in RAM instead of exchanging them.

Env knobs (set by the parent):
  MEMBER_NPROC      — bootstrap world size (launch-time members)
  MEMBER_ROWS / MEMBER_TREES / MEMBER_LEAVES — problem size
  MEMBER_KILL_ITER=i — SIGKILL self in the 0-based iteration-i callback
      (an eviction target: survivors detect the stale heartbeat and
      resize instead of exiting 75)
  MEMBER_LEAVE_ITER=i — request a clean leave at iteration i (same path
      a SIGTERM takes, but deterministic for byte-identity tests)
  MEMBER_SIGTERM_ITER=i — SIGTERM *self* at iteration i: exercises the
      real signal handler -> request_leave path with deterministic timing
  MEMBER_ITER_SLEEP=s — sleep s seconds per finished iteration (paces
      the fleet so a mid-run joiner reliably lands before completion)
  MEMBER_REBALANCE=1 — arm straggler-aware shard rebalancing
  MEMBER_QUANTIZED=0 — disable quantized training (default on)
  MEMBER_PROGRESS=1 — publish write-once ``progress/<iter>`` KV records
      (first finisher claims the slot) plus per-attempt
      ``attempts/<iter>.m<id>.e<epoch>`` keys for the spot cost ledger
plus the standard LIGHTGBM_TPU_FAULT / _TRACE / _NET_* hooks.

Exit codes: 0 on completed model OR clean leave; EXIT_PEER_FAILURE (75)
when membership recovery itself fails.  Writes ``out.mM.json`` always
and ``out.mM.txt`` (final model) on completed training.
"""

import json
import os
import signal
import sys
import time

member_arg = sys.argv[1]
fleet_dir = sys.argv[2]
out = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.cli import EXIT_PEER_FAILURE  # noqa: E402
from lightgbm_tpu.parallel import membership, net  # noqa: E402
from lightgbm_tpu.parallel.shardplan import ShardPlan  # noqa: E402

N = int(os.environ.get("MEMBER_ROWS", "600"))
TREES = int(os.environ.get("MEMBER_TREES", "12"))
LEAVES = int(os.environ.get("MEMBER_LEAVES", "7"))
KILL_ITER = int(os.environ.get("MEMBER_KILL_ITER", "-1"))
LEAVE_ITER = int(os.environ.get("MEMBER_LEAVE_ITER", "-1"))
SIGTERM_ITER = int(os.environ.get("MEMBER_SIGTERM_ITER", "-1"))
ITER_SLEEP = float(os.environ.get("MEMBER_ITER_SLEEP", "0"))
REBALANCE = os.environ.get("MEMBER_REBALANCE", "0") == "1"
QUANTIZED = os.environ.get("MEMBER_QUANTIZED", "1") == "1"
PROGRESS = os.environ.get("MEMBER_PROGRESS", "0") == "1"


def make_data(n):
    """The GLOBAL dataset, identical on every member (few-valued integer
    features: every contiguous slice sees the full value set, so the
    locally-built bin mappers are bit-identical at any world)."""
    rng = np.random.default_rng(42)
    F = 10
    X = rng.integers(0, 5, size=(n, F)).astype(np.float32)
    w = rng.standard_normal(F)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-((X - 2.0) @ w * 0.35)))
         ).astype(np.float32)
    return X, y


X, y = make_data(N)

rt = membership.MembershipRuntime(
    fleet_dir, None if member_arg == "join" else int(member_arg))
rt.row_provider = lambda lo, hi: (X[lo:hi], y[lo:hi])

signal.signal(signal.SIGTERM, lambda *_a: rt.request_leave())

if member_arg == "join":
    rt.join()
else:
    nproc = int(os.environ["MEMBER_NPROC"])
    counts = [(r + 1) * N // nproc - r * N // nproc for r in range(nproc)]
    rt.bootstrap(nproc, counts)

mid = rt.id


def _write(payload: dict) -> None:
    with open(out + f".m{mid}.json", "w") as fh:
        json.dump(payload, fh)


lo, hi = ShardPlan.from_counts(rt.counts).rank_range(rt.rank)
membership.set_runtime(rt)

p = dict(objective="binary", tree_learner="data", pre_partition=True,
         elastic_membership=True, num_leaves=LEAVES, learning_rate=0.2,
         max_bin=31, min_data_in_leaf=20, boost_from_average=False,
         quantized_training=QUANTIZED, seed=7, verbose=-1)
if REBALANCE:
    p.update(rebalance=True, rebalance_threshold=1.5, rebalance_patience=3,
             rebalance_max_move_frac=0.25)
ds = lgb.Dataset(X[lo:hi], label=y[lo:hi], params=dict(p))

epochs_seen = []

try:
    # explicit loop on current_iteration(): a mid-run joiner restores at
    # the fleet's iteration and must train only the REMAINING rounds
    # (lgb.train's range(start, rounds) loop has no notion of that)
    booster = lgb.Booster(params=dict(p), train_set=ds)
    while booster.current_iteration() < TREES:
        booster.update()
        it = booster.current_iteration() - 1
        epochs_seen.append(rt.epoch)
        if PROGRESS:
            # write-once fleet-wide iteration record for the spot cost
            # ledger (factory/spot.py): the FIRST member to finish the
            # iteration claims its slot, so a redone iteration cannot
            # re-claim it and zero_lost_iterations() stays provable
            rt.client.try_create(
                f"progress/{it}",
                json.dumps({"epoch": rt.epoch, "member": mid}).encode())
            # per-attempt record: epoch-keyed, so the SAME member
            # completing the SAME iteration twice (a redo — resizes
            # always bump the epoch) leaves two keys the ledger can see;
            # this is what upgrades "no iteration lost" to "none redone"
            rt.client.try_create(f"attempts/{it}.m{mid}.e{rt.epoch}", b"1")
        if LEAVE_ITER >= 0 and it >= LEAVE_ITER:
            rt.request_leave()
        if SIGTERM_ITER >= 0 and it >= SIGTERM_ITER:
            os.kill(os.getpid(), signal.SIGTERM)
        if KILL_ITER >= 0 and it >= KILL_ITER:
            os.kill(os.getpid(), signal.SIGKILL)
        if ITER_SLEEP > 0:
            time.sleep(ITER_SLEEP)
except membership.CleanLeave as e:
    rt.stop()
    _write({"error": None, "left_at_epoch": e.epoch, "member": mid,
            "epochs_seen": epochs_seen})
    print(f"member {mid} left cleanly at epoch {e.epoch}")
    sys.exit(0)
except net.PeerFailureError as e:
    rt.stop()
    _write({"error": "PeerFailureError", "ranks": list(e.ranks),
            "member": mid, "epochs_seen": epochs_seen})
    print(f"member {mid} unrecoverable peer failure: {e}")
    net.hard_exit(EXIT_PEER_FAILURE)

rt.stop()
with open(out + f".m{mid}.txt", "w") as fh:
    fh.write(booster.model_to_string())
b = booster.boosting
_write({
    "error": None,
    "member": mid,
    "trees": booster.num_trees,
    "iters": booster.current_iteration(),
    "final_epoch": rt.epoch,
    "final_members": list(rt.members),
    "final_counts": list(rt.counts),
    "rows_end": int(b.num_data),
    "epochs_seen": epochs_seen,
    "resize_pauses": [round(s, 4) for s in
                      getattr(b, "_membership_pauses", [])],
})
print(f"member {mid} train done (epoch={rt.epoch}, members={list(rt.members)})")
sys.exit(0)
