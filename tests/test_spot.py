"""Preemptible-capacity economics loop (factory/spot.py,
docs/FACTORY.md "spot").

Unit tests pin the schedule grammar (scripted + seeded traces, both
replayable), the atomic cost-ledger document and its
zero-lost-iterations proof; the e2e leg drives a REAL 2-member elastic
fleet (tests/membership_worker.py) through a preempt-then-respawn
trace and checks the survivors' model, the priced ledger, and the
write-once per-iteration records."""

import json
import os

import pytest

from lightgbm_tpu.factory.spot import (ON_DEMAND_PRICE, CostLedger,
                                       SpotEvent, SpotFleet, SpotSchedule,
                                       run_static_baseline)

pytestmark = pytest.mark.membership


# ----------------------------------------------------------------------
# schedule
# ----------------------------------------------------------------------
def test_schedule_script_grammar():
    s = SpotSchedule.from_script(
        "preempt@2.5;spawn@4;price@6=0.5;preempt@8=1", base_price=0.3)
    assert [e.kind for e in s.events] == ["preempt", "spawn", "price",
                                         "preempt"]
    assert s.events[3].target == 1 and s.events[0].target is None
    assert s.price_at(0.0) == 0.3          # base before the first step
    assert s.price_at(7.0) == 0.5          # stepped
    assert [e.kind for e in s.due(2.0, 4.0)] == ["preempt", "spawn"]
    assert s.due(4.0, 4.0) == []           # window is half-open


@pytest.mark.parametrize("bad", ["preempt", "frob@3", "spawn@4=1",
                                 "price@", "price@3"])
def test_schedule_rejects_bad_tokens(bad):
    with pytest.raises(ValueError):
        SpotSchedule.from_script(bad)


def test_schedule_sample_is_seed_deterministic():
    a = SpotSchedule.sample(11, 60.0)
    b = SpotSchedule.sample(11, 60.0)
    c = SpotSchedule.sample(12, 60.0)
    key = lambda s: [(e.t_s, e.kind, e.value) for e in s.events]  # noqa: E731
    assert key(a) == key(b)
    assert key(a) != key(c)
    # prices stay inside (0, on-demand]: spot never costs MORE than
    # the capacity it undercuts
    for ev in a.events:
        if ev.kind == "price":
            assert 0.0 < ev.value <= ON_DEMAND_PRICE


def test_schedule_rejects_unknown_kind():
    with pytest.raises(ValueError):
        SpotSchedule([SpotEvent(1.0, "evaporate")])


# ----------------------------------------------------------------------
# ledger
# ----------------------------------------------------------------------
def test_ledger_roundtrip_and_cost_math(tmp_path):
    path = str(tmp_path / "ledger.json")
    led = CostLedger(path)
    led.charge(0, 10.0, 0.3)
    led.charge(0, 2.0, 0.5)
    led.charge("join1", 4.0, 0.5)
    led.event(3.0, "preempt", member="1")
    for it in range(6):
        led.iteration(it, epoch=it // 3, t_s=it * 0.5)
    led.finish(trees=6)
    led.flush()
    back = CostLedger.load(path)
    assert back.total_cost == pytest.approx(10 * 0.3 + 2 * 0.5 + 4 * 0.5)
    assert back.cost_per_model() == pytest.approx(back.total_cost)
    assert back.zero_lost_iterations()
    doc = json.load(open(path))
    assert doc["version"] == CostLedger.VERSION
    assert doc["member_seconds"]["0"] == pytest.approx(12.0)
    assert doc["events"][0]["kind"] == "preempt"


def test_ledger_flush_is_atomic(tmp_path):
    path = str(tmp_path / "ledger.json")
    led = CostLedger(path)
    led.charge(0, 1.0, 1.0)
    led.flush()
    # a later torn write may never clobber the published document: the
    # tmp file is a sibling, the publish is os.replace
    led.charge(0, 1.0, 1.0)
    led.flush()
    assert not os.path.exists(path + ".tmp")
    assert CostLedger.load(path).total_cost == pytest.approx(2.0)


def test_ledger_detects_lost_and_incomplete(tmp_path):
    led = CostLedger(str(tmp_path / "l.json"))
    led.iteration(0, 0, 0.0)
    led.iteration(2, 0, 1.0)  # iteration 1 never completed anywhere
    assert not led.zero_lost_iterations()   # not finished
    assert led.cost_per_model() is None
    led.finish(3)
    assert not led.zero_lost_iterations()   # gap
    good = CostLedger(str(tmp_path / "g.json"))
    for it in range(3):
        good.iteration(it, 0, 0.0)
    good.finish(3)
    assert good.zero_lost_iterations()


def test_ledger_iteration_records_are_write_once(tmp_path):
    led = CostLedger(str(tmp_path / "l.json"))
    led.iteration(0, epoch=0, t_s=1.0)
    led.iteration(0, epoch=9, t_s=9.0)  # a redo cannot re-claim the slot
    assert led._doc["iterations"]["0"]["epoch"] == 0


def test_ledger_detects_redone_iteration(tmp_path):
    """The nothing-redone half of the proof: a member that completed the
    same iteration under two epochs (a redo — resizes bump the epoch)
    fails the gate even though the write-once progress slots are
    gap-free."""
    led = CostLedger(str(tmp_path / "l.json"))
    for it in range(3):
        led.iteration(it, 0, 0.0)
        led.attempt(it, "0", 0)
    led.attempt(2, "0", 0)          # idempotent re-harvest: same epoch
    led.finish(3)
    led.flush()
    assert led.zero_lost_iterations()
    assert CostLedger.load(led.path).zero_lost_iterations()
    led.attempt(2, "0", 1)          # the same member redid iteration 2
    assert led._doc["attempts"]["2.m0"] == [0, 1]
    assert not led.zero_lost_iterations()


def test_ledger_version_mismatch_is_loud(tmp_path):
    path = str(tmp_path / "l.json")
    with open(path, "w") as fh:
        json.dump({"version": 99}, fh)
    with pytest.raises(ValueError, match="version"):
        CostLedger.load(path)


# ----------------------------------------------------------------------
# e2e fleet
# ----------------------------------------------------------------------
def test_spot_fleet_preempt_respawn_e2e(tmp_path):
    """2-member fleet, member 1 preempted at t=3, replacement capacity
    at t=4: the fleet must complete the model, the ledger must price
    every member-second at the spot price, and the write-once iteration
    records must prove nothing was redone."""
    fleet_dir = str(tmp_path / "fleet")
    ledger_path = str(tmp_path / "ledger.json")
    fleet = SpotFleet(fleet_dir, SpotSchedule.from_script(
        "preempt@3=1;spawn@4", base_price=0.25), 2, ledger_path,
        trees=10, rows=600,
        extra_env={"MEMBER_ITER_SLEEP": "0.5"})
    summary = fleet.run(timeout_s=180)
    assert summary["cost"] is not None, summary["exits"]
    assert summary["zero_lost_iterations"], summary
    assert summary["models"], "no finisher wrote a model"
    # every finisher converged on the same bytes
    assert len(set(summary["models"].values())) == 1
    # the preempted bootstrap member died by SIGKILL and left no model
    assert summary["exits"]["1"] == -9
    assert "1" not in summary["models"]
    led = CostLedger.load(ledger_path)
    assert led.total_cost == pytest.approx(summary["cost"])
    kinds = [e["kind"] for e in led._doc["events"]]
    assert "preempt" in kinds and "spawn" in kinds
    # per-attempt records were harvested and prove nothing was redone
    assert led._doc["attempts"], "no attempt keys harvested"
    assert all(len(v) == 1 for v in led._doc["attempts"].values())
    # workers log to per-member files (an undrained pipe would stall a
    # chatty worker on the OS buffer); the SIGKILLed member's log stays
    # for the post-mortem
    for key in ("0", "1"):
        assert os.path.exists(os.path.join(fleet_dir, f"worker.{key}.log"))
    # the ledger priced at spot, not on-demand: total member-seconds x
    # base price bounds the document's spend
    secs = sum(led._doc["member_seconds"].values())
    assert led.total_cost == pytest.approx(secs * 0.25, rel=1e-6)


def test_static_baseline_prices_on_demand(tmp_path):
    summary = run_static_baseline(
        str(tmp_path / "fleet"), 2, str(tmp_path / "ledger.json"),
        trees=6, rows=600, extra_env={"MEMBER_ITER_SLEEP": "0"})
    assert summary["cost"] is not None, summary["exits"]
    assert summary["zero_lost_iterations"]
    led = CostLedger.load(str(tmp_path / "ledger.json"))
    secs = sum(led._doc["member_seconds"].values())
    assert led.total_cost == pytest.approx(secs * ON_DEMAND_PRICE, rel=1e-6)


def test_spot_cli_needs_fleet_dir(capsys):
    from lightgbm_tpu.factory.spot import main

    assert main([]) == 2  # EXIT_BAD_ARGS
