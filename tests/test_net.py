"""Unit tests for the hardened transport layer (parallel/net.py):
backoff schedule, retry/deadline accounting, fault-spec parsing, the
bounded KV allgather (classification + lazy key GC), and the
heartbeat/PeerWatch liveness protocol — all against an in-memory fake
KV client, no subprocesses.  The real-subprocess kill matrix lives in
test_net_fault.py."""

import threading
import time

import pytest

from lightgbm_tpu.parallel import net


class FakeClient:
    """In-memory stand-in for jaxlib's DistributedRuntimeClient KV API
    (write-once keys, subtree delete, DEADLINE_EXCEEDED on a missing
    blocking get — the semantics probed on the real client)."""

    def __init__(self):
        self.store = {}
        self.deleted = []
        self.lock = threading.Lock()

    def key_value_set(self, key, val):
        self.key_value_set_bytes(key, val.encode())

    def key_value_set_bytes(self, key, val):
        with self.lock:
            if key in self.store:
                raise RuntimeError(f"ALREADY_EXISTS: Config key {key}")
            self.store[key] = bytes(val)

    def blocking_key_value_get_bytes(self, key, timeout_ms):
        with self.lock:
            if key in self.store:
                return self.store[key]
        time.sleep(timeout_ms / 1e3)
        raise RuntimeError(
            f"DEADLINE_EXCEEDED: GetKeyValue() timed out with key: {key}"
        )

    def key_value_delete(self, key):
        with self.lock:
            self.deleted.append(key)
            if key.endswith("/"):
                for k in [k for k in self.store if k.startswith(key)]:
                    del self.store[k]
            else:
                self.store.pop(key, None)

    def key_value_dir_get(self, prefix):
        with self.lock:
            return [(k, v.decode()) for k, v in sorted(self.store.items())
                    if k.startswith(prefix)]


@pytest.fixture(autouse=True)
def _fresh_settings(monkeypatch):
    for var, _ in net._ENV_FIELDS.values():
        monkeypatch.delenv(var, raising=False)
    monkeypatch.delenv("LIGHTGBM_TPU_FAULT", raising=False)
    monkeypatch.delenv("LIGHTGBM_TPU_FAULT_RANK", raising=False)
    net._reset_for_tests()
    yield
    net._reset_for_tests()


# ----------------------------------------------------------------------
class TestSettings:
    def test_defaults_and_derived(self):
        s = net.settings()
        assert s.deadline_s == 120.0 and s.retries == 3
        assert s.stale_after() == 120.0
        assert s.hb_interval() == 5.0  # deadline/4 capped at 5 s
        assert 0.05 <= s.poll_s() <= 0.5

    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv("LIGHTGBM_TPU_NET_TIMEOUT", "8")
        monkeypatch.setenv("LIGHTGBM_TPU_NET_RETRIES", "1")
        net._reset_for_tests()
        s = net.settings()
        assert s.deadline_s == 8.0 and s.retries == 1
        assert s.hb_interval() == 2.0 and s.stale_after() == 8.0

    def test_config_param_applies_but_env_wins(self, monkeypatch):
        from lightgbm_tpu.config import Config

        cfg = Config.from_params({"network_timeout": 30, "network_retries": 5})
        net.configure_from_config(cfg)
        assert net.settings().deadline_s == 30.0
        assert net.settings().retries == 5
        monkeypatch.setenv("LIGHTGBM_TPU_NET_TIMEOUT", "7")
        net._reset_for_tests()
        net.configure_from_config(cfg)
        assert net.settings().deadline_s == 7.0  # env outranks the param
        assert net.settings().retries == 5

    def test_config_rejects_bad_values(self):
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.utils.log import LightGBMError

        with pytest.raises(LightGBMError, match="network_timeout"):
            Config.from_params({"network_timeout": 0})
        with pytest.raises(LightGBMError, match="bad_row_policy"):
            Config.from_params({"bad_row_policy": "ignore"})


class TestBackoff:
    def test_schedule_doubles_and_caps(self):
        assert net.backoff_schedule(5, 0.1, 0.4) == [0.1, 0.2, 0.4, 0.4, 0.4]
        assert net.backoff_schedule(0, 0.1, 0.4) == []

    def test_retry_succeeds_after_failures(self):
        net.configure(backoff_base_s=0.001, backoff_max_s=0.002)
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        assert net.retry_call(flaky, "unit") == "ok"
        assert len(calls) == 3

    def test_exhaustion_raises_typed_timeout_with_cause(self):
        net.configure(retries=2, backoff_base_s=0.001, backoff_max_s=0.002)

        def dead():
            raise OSError("always down")

        with pytest.raises(net.CollectiveTimeoutError) as ei:
            net.retry_call(dead, "unit")
        assert isinstance(ei.value.__cause__, OSError)
        assert ei.value.elapsed_s >= 0.0

    def test_deadline_caps_the_schedule(self):
        net.configure(backoff_base_s=0.2, backoff_max_s=5.0)
        t0 = time.monotonic()
        with pytest.raises(net.CollectiveTimeoutError):
            net.retry_call(lambda: 1 / 0, "unit", retries=50,
                           deadline_s=0.05, retry_on=(ZeroDivisionError,))
        assert time.monotonic() - t0 < 1.0  # gave up well before 50 retries


class TestFaultSpec:
    def test_parse(self):
        assert net.parse_fault_spec("die:3") == [("die", 3.0)]
        assert net.parse_fault_spec("drop_collective:2,delay:25") == [
            ("drop_collective", 2.0), ("delay", 25.0)]

    def test_rejects_unknown_kind_and_bad_args(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            net.parse_fault_spec("explode:1")
        with pytest.raises(ValueError, match="bad fault argument"):
            net.parse_fault_spec("die:soon")
        with pytest.raises(ValueError, match="1-based"):
            net.parse_fault_spec("die")

    def test_delay_fault_applies(self, monkeypatch):
        monkeypatch.setenv("LIGHTGBM_TPU_FAULT", "delay:30")
        net._reset_for_tests()
        t0 = time.monotonic()
        net.fault_point()
        assert time.monotonic() - t0 >= 0.025

    def test_bad_spec_is_ignored_not_fatal(self, monkeypatch):
        monkeypatch.setenv("LIGHTGBM_TPU_FAULT", "explode:1")
        net._reset_for_tests()
        net.fault_point()  # must not raise


# ----------------------------------------------------------------------
class TestPeerWatch:
    def test_heartbeat_change_resets_age(self):
        c = FakeClient()
        clock = [0.0]
        w = net.PeerWatch(c, rank=0, nproc=2, stale_after_s=5.0,
                          time_fn=lambda: clock[0])
        c.key_value_set("ltpu_hb/1/1", "1")
        assert w.dead_ranks() == []
        clock[0] = 4.0
        assert w.dead_ranks() == []
        clock[0] = 6.0  # key set frozen for > 5 s of observation
        assert w.dead_ranks() == [1]
        c.key_value_delete("ltpu_hb/1/1")  # a beat: rotate the key
        c.key_value_set("ltpu_hb/1/2", "2")
        assert w.dead_ranks() == []  # change observed -> alive again

    def test_never_started_peer_times_out_from_watch_start(self):
        c = FakeClient()
        clock = [0.0]
        w = net.PeerWatch(c, rank=0, nproc=3, stale_after_s=2.0,
                          time_fn=lambda: clock[0])
        assert w.dead_ranks() == []
        clock[0] = 3.0
        assert w.dead_ranks() == [1, 2]

    def test_check_raises_typed_error_with_ranks(self):
        c = FakeClient()
        clock = [0.0]
        w = net.PeerWatch(c, rank=0, nproc=2, stale_after_s=1.0,
                          time_fn=lambda: clock[0])
        clock[0] = 2.0
        with pytest.raises(net.PeerFailureError) as ei:
            w.check("unit", elapsed_s=2.0)
        assert ei.value.ranks == (1,)
        assert ei.value.elapsed_s == 2.0

    def test_unreachable_store_is_coordinator_failure(self):
        class DownClient(FakeClient):
            def key_value_dir_get(self, prefix):
                raise RuntimeError("UNAVAILABLE: socket closed")

        w = net.PeerWatch(DownClient(), rank=1, nproc=2, stale_after_s=1.0)
        with pytest.raises(net.PeerFailureError) as ei:
            w.dead_ranks()
        assert ei.value.ranks == (0,)


class TestHeartbeatWriter:
    def test_rotates_keys_and_cleans_up(self):
        c = FakeClient()
        hb = net.HeartbeatWriter(c, rank=0, interval_s=0.01)
        hb.start()
        time.sleep(0.08)
        hb.stop()
        # always exactly one live key while beating; subtree deleted on stop
        assert not [k for k in c.store if k.startswith("ltpu_hb/0/")]
        assert any(k.endswith("/") for k in c.deleted)


# ----------------------------------------------------------------------
class TestKvGather:
    def test_gather_returns_process_order(self):
        c = FakeClient()
        net.configure(deadline_s=2.0)
        net._kv_put_payload(c, 0, 1, "ltpu_collect/0/1", b"from-rank-1",
                            2.0, "test")
        out = net.kv_gather(0, b"from-rank-0", client=c, rank=0, nproc=2)
        assert out == [b"from-rank-0", b"from-rank-1"]

    def test_empty_blob_roundtrip(self):
        # barrier payloads are b""; the KV frame keeps values >= 2 bytes
        # (jaxlib's bytes API segfaults below that)
        c = FakeClient()
        net._kv_put(c, "k", b"")
        assert len(c.store["k"]) >= 2
        assert net._kv_get(c, "k", 100) == b""

    def test_lazy_gc_deletes_own_previous_uid(self):
        c = FakeClient()
        net.configure(deadline_s=2.0)
        net._kv_put_payload(c, 0, 1, "ltpu_collect/0/1", b"x", 2.0, "test")
        net.kv_gather(0, b"a", client=c, rank=0, nproc=2)
        assert "ltpu_collect/0/0" in c.store  # nothing to GC yet
        net._kv_put_payload(c, 1, 1, "ltpu_collect/1/1", b"y", 2.0, "test")
        net.kv_gather(1, b"b", client=c, rank=0, nproc=2)
        # completing uid 1 proves every rank read our uid-0 key
        assert "ltpu_collect/0/0" not in c.store
        assert "ltpu_collect/1/0" in c.store

    def test_dead_peer_classified_within_budget(self):
        c = FakeClient()
        net.configure(deadline_s=0.3, stale_after_s=0.3)
        w = net.PeerWatch(c, rank=0, nproc=2, stale_after_s=0.3)
        t0 = time.monotonic()
        with pytest.raises(net.PeerFailureError) as ei:
            net.kv_gather(0, b"mine", client=c, rank=0, nproc=2, watch=w)
        assert ei.value.ranks == (1,)
        assert time.monotonic() - t0 <= 2 * 0.3 + 0.5

    def test_live_but_silent_peer_is_collective_timeout(self):
        import itertools

        seq = itertools.count()

        class BeatingClient(FakeClient):
            # rank 1's heartbeat state changes every sweep: alive forever
            def key_value_dir_get(self, prefix):
                return [(f"ltpu_hb/1/{next(seq)}", "x")]

        c = BeatingClient()
        net.configure(deadline_s=0.25, stale_after_s=0.25)
        w = net.PeerWatch(c, rank=0, nproc=2, stale_after_s=0.25)
        t0 = time.monotonic()
        with pytest.raises(net.CollectiveTimeoutError) as ei:
            net.kv_gather(0, b"mine", client=c, rank=0, nproc=2, watch=w)
        wall = time.monotonic() - t0
        assert 0.4 <= wall <= 1.5  # ~deadline + stale_after, bounded
        assert ei.value.elapsed_s >= 0.4

    def test_unreachable_store_is_peer_failure_after_retries(self):
        class DownClient(FakeClient):
            def blocking_key_value_get_bytes(self, key, timeout_ms):
                raise RuntimeError("UNAVAILABLE: connection refused")

        c = DownClient()
        net.configure(deadline_s=1.0, retries=1, backoff_base_s=0.001,
                      backoff_max_s=0.002)
        with pytest.raises(net.PeerFailureError) as ei:
            net.kv_gather(0, b"mine", client=c, rank=1, nproc=2)
        assert ei.value.ranks == (0,)


class TestWatchdog:
    def test_passes_value_and_errors_through(self):
        assert net.watchdog_call(lambda: 41 + 1, "unit") == 42
        with pytest.raises(KeyError):
            net.watchdog_call(lambda: {}["missing"], "unit")

    def test_hang_raises_bounded_timeout(self):
        net.configure(deadline_s=0.1, stale_after_s=0.05)
        t0 = time.monotonic()
        with pytest.raises(net.CollectiveTimeoutError):
            net.watchdog_call(lambda: time.sleep(5), "unit")
        assert time.monotonic() - t0 < 1.0

    def test_stale_peer_during_hang_is_peer_failure(self):
        c = FakeClient()
        net.configure(deadline_s=5.0, stale_after_s=0.05)
        c.key_value_set("ltpu_hb/1/1", "1")  # frozen forever
        w = net.PeerWatch(c, rank=0, nproc=2, stale_after_s=0.05)
        with pytest.raises(net.PeerFailureError):
            net.watchdog_call(lambda: time.sleep(5), "unit", watch=w)


# ----------------------------------------------------------------------
class TestErrorHierarchyAndExitCodes:
    def test_hierarchy(self):
        assert issubclass(net.PeerFailureError, net.NetError)
        assert issubclass(net.CollectiveTimeoutError, net.NetError)
        assert issubclass(net.NetError, RuntimeError)

    def test_cli_exit_codes(self):
        from lightgbm_tpu.cli import EXIT_NET_TIMEOUT, EXIT_PEER_FAILURE

        assert EXIT_PEER_FAILURE == 75  # EX_TEMPFAIL: restart auto-resumes
        assert EXIT_NET_TIMEOUT == 74
        assert EXIT_PEER_FAILURE not in (0, 1)  # distinct from config errors

    def test_package_exports(self):
        from lightgbm_tpu import parallel

        assert parallel.PeerFailureError is net.PeerFailureError
        assert parallel.CollectiveTimeoutError is net.CollectiveTimeoutError


# ----------------------------------------------------------------------
class TestChunkedKv:
    """Chunked KV payloads: multi-MB blobs split across framed
    continuation keys with per-chunk CRC (elected-histogram allgathers
    on the XLA:CPU transport exceed single-value comfort zones)."""

    def _gather(self, nproc, payloads, client=None, uid=0):
        c = client if client is not None else FakeClient()
        net.configure(deadline_s=5.0)
        res = {}

        def run(r):
            res[r] = net.kv_gather(uid, payloads[r], client=c, rank=r,
                                   nproc=nproc)

        ts = [threading.Thread(target=run, args=(r,)) for r in range(nproc)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return res, c

    @pytest.mark.parametrize("size", [1, 1024, 8 * 1024 * 1024])
    def test_roundtrip_sizes(self, size, monkeypatch):
        # 256 KiB chunk limit keeps the 8 MiB leg fast while still
        # forcing a 32-chunk reassembly
        monkeypatch.setenv("LIGHTGBM_TPU_KV_CHUNK", str(256 * 1024))
        payloads = [bytes([r]) * size + bytes([r])  # size+1, rank-tagged
                    for r in range(2)]
        res, _ = self._gather(2, payloads)
        assert res[0] == payloads and res[1] == payloads

    def test_small_payload_stays_single_key(self):
        res, c = self._gather(2, [b"a" * 100, b"b"])
        assert res[0] == [b"a" * 100, b"b"]
        assert not any(k.startswith("ltpu_chunk/") for k in c.store)

    def test_chunk_keys_gced_after_next_gather(self, monkeypatch):
        monkeypatch.setenv("LIGHTGBM_TPU_KV_CHUNK", "64")
        payloads = [b"x" * 500, b"y" * 300]
        res, c = self._gather(2, payloads)
        assert res[1] == payloads
        assert any(k.startswith("ltpu_chunk/0/") for k in c.store)
        res2, _ = self._gather(2, [b"p" * 200, b"q"], client=c, uid=1)
        assert res2[0] == [b"p" * 200, b"q"]
        # completing uid 1 proves every rank read uid 0 -> chunks GC'd
        assert not any(k.startswith("ltpu_chunk/0/") for k in c.store)

    def test_crc_mismatch_is_typed_corruption_error(self, monkeypatch):
        monkeypatch.setenv("LIGHTGBM_TPU_KV_CHUNK", "64")
        c = FakeClient()
        net.configure(deadline_s=2.0)
        net._kv_put_payload(c, 0, 1, "ltpu_collect/0/1", b"z" * 500,
                            2.0, "test")
        key = "ltpu_chunk/0/1/1"
        raw = bytearray(c.store[key])
        raw[-1] ^= 0xFF  # flip a payload byte under the stored CRC
        with c.lock:
            c.store[key] = bytes(raw)
        with pytest.raises(net.NetError, match="CRC mismatch"):
            net.kv_gather(0, b"mine", client=c, rank=0, nproc=2)

    def test_chunk_limit_env_and_default(self, monkeypatch):
        monkeypatch.delenv("LIGHTGBM_TPU_KV_CHUNK", raising=False)
        assert net.kv_chunk_limit() == 4 * 1024 * 1024
        monkeypatch.setenv("LIGHTGBM_TPU_KV_CHUNK", "123")
        assert net.kv_chunk_limit() == 123
        monkeypatch.setenv("LIGHTGBM_TPU_KV_CHUNK", "bogus")
        assert net.kv_chunk_limit() == 4 * 1024 * 1024
