"""Checkpoint/resume subsystem tests (ckpt/, docs/CHECKPOINT.md).

The acceptance contract: resuming from a checkpoint is **bit-identical**
to never having died — same trees, same leaf values, same early-stopping
decision — for every boosting driver, because the checkpoint carries the
full training state (score caches, every RNG stream, bests, the fused
trainer's row permutation).  Process-kill variants live in
test_ckpt_fault.py; the 2-process sharded variant in test_multihost.py.
"""

import os
import shutil
import tempfile

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ckpt import CheckpointManager, CheckpointMismatch
from lightgbm_tpu.ckpt.state import (
    TrainState,
    capture,
    pack_trees,
    unpack_trees,
)
from lightgbm_tpu.ckpt.store import CheckpointStore
from lightgbm_tpu.utils.random import Random


@pytest.fixture(scope="module")
def xy():
    rng = np.random.RandomState(0)
    X = rng.randn(600, 8)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.1 * rng.randn(600) > 0).astype(float)
    return X, y


def _kill_at(k):
    """Callback simulating sudden death at iteration ``k`` (the process
    variants use real SIGKILL; in-process a non-Exception throwable that
    nothing in the engine catches plays the same role)."""
    def cb(env):
        if env.iteration + 1 == k:
            raise KeyboardInterrupt
    cb.order = 99
    return cb


def _train(P, X, y, rounds, ckpt_dir=None, freq=3, callbacks=None, **kw):
    ds = lgb.Dataset(X, label=y, params=dict(P))
    mgr = None
    if ckpt_dir is not None:
        mgr = CheckpointManager(ckpt_dir, freq=freq)
    try:
        bst = lgb.train(dict(P), ds, rounds, verbose_eval=False,
                        checkpoint_manager=mgr, callbacks=callbacks, **kw)
    finally:
        if mgr is not None:
            mgr.close()
    return bst


def _train_killed(P, X, y, rounds, ckpt_dir, kill, freq=3, **kw):
    with pytest.raises(KeyboardInterrupt):
        _train(P, X, y, rounds, ckpt_dir=ckpt_dir, freq=freq,
               callbacks=[_kill_at(kill)], **kw)


# ----------------------------------------------------------------------
# RNG state round trips (satellite: model text cannot carry these)
# ----------------------------------------------------------------------
def test_random_state_roundtrip():
    a = Random(123)
    for _ in range(37):
        a.next_float()
    state = a.get_state()
    seq_a = [a.next_float() for _ in range(20)] + list(a.sample(50, 11))
    b = Random(999).set_state(state)
    seq_b = [b.next_float() for _ in range(20)] + list(b.sample(50, 11))
    assert seq_a == seq_b
    # the state is one LCG word — a fresh seed differs
    assert Random(123).get_state() != state


def test_goss_key_roundtrip():
    import io

    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(3)
    for _ in range(5):
        key, _ = jax.random.split(key)
    # the npz round trip GOSS's export/import hooks ride on
    buf = io.BytesIO()
    np.savez(buf, k=np.asarray(key))
    buf.seek(0)
    k2 = jnp.asarray(np.load(buf)["k"])
    a = jax.random.uniform(jax.random.split(key)[1], (8,))
    b = jax.random.uniform(jax.random.split(k2)[1], (8,))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ----------------------------------------------------------------------
# store: atomicity, CRC, retention, corrupt-tail discovery
# ----------------------------------------------------------------------
def test_store_save_latest_retention(tmp_path):
    st = CheckpointStore(str(tmp_path), keep_last=2)
    for step in (2, 4, 6, 8):
        st.save(step, f"blob-{step}".encode())
    assert st.steps() == [6, 8]  # rolling retention
    files = [f for f in os.listdir(tmp_path) if f.startswith("ckpt_")]
    assert len(files) == 2
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]
    step, blob = st.latest_valid()
    assert step == 8 and blob == b"blob-8"


def test_store_corrupt_tail_skipped(tmp_path):
    st = CheckpointStore(str(tmp_path), keep_last=3)
    st.save(3, b"three")
    st.save(6, b"sixsix")
    # truncate the tail checkpoint (torn write after a SIGKILL)
    with open(st.path_for(6), "wb") as f:
        f.write(b"si")
    step, blob = st.latest_valid()
    assert step == 3 and blob == b"three"
    # CRC failure (size right, bits wrong) is also skipped
    with open(st.path_for(6), "wb") as f:
        f.write(b"sixsex")
    step, _ = st.latest_valid()
    assert step == 3


def test_store_complete_marker(tmp_path):
    st = CheckpointStore(str(tmp_path))
    st.save(5, b"five")
    assert st.complete_step() is None
    st.mark_complete(7)
    assert st.complete_step() == 7
    st.save(9, b"nine")  # a new save voids the marker (run is live)
    assert st.complete_step() is None


# ----------------------------------------------------------------------
# TrainState: binary tree pack/unpack + capture fidelity
# ----------------------------------------------------------------------
def test_tree_pack_unpack_bit_exact(xy):
    X, y = xy
    P = dict(objective="binary", num_leaves=7, learning_rate=0.2, verbose=-1)
    bst = _train(P, X, y, 5)
    models = bst.boosting.models
    back = unpack_trees(pack_trees(models))
    assert len(back) == len(models)
    for a, b in zip(models, back):
        assert a.num_leaves == b.num_leaves
        assert a.to_string() == b.to_string()
        n, m = a.num_leaves, max(a.num_leaves - 1, 1)
        np.testing.assert_array_equal(a.leaf_value[:n], b.leaf_value[:n])
        np.testing.assert_array_equal(a.threshold[:m], b.threshold[:m])
        np.testing.assert_array_equal(a.threshold_in_bin[:m],
                                      b.threshold_in_bin[:m])


def test_trainstate_bytes_roundtrip(xy):
    X, y = xy
    P = dict(objective="binary", num_leaves=7, verbose=-1,
             bagging_fraction=0.7, bagging_freq=2)
    bst = _train(P, X, y, 6)
    state = capture(bst)
    back = TrainState.from_bytes(state.to_bytes())
    assert back.iteration == state.iteration == 6
    assert back.meta == state.meta
    for k, v in state.arrays.items():
        np.testing.assert_array_equal(back.arrays[k], np.asarray(v), err_msg=k)


def test_restore_refuses_config_and_data_mismatch(xy, tmp_path):
    X, y = xy
    P = dict(objective="binary", num_leaves=7, verbose=-1)
    d = str(tmp_path)
    _train_killed(P, X, y, 10, d, kill=6)
    # different math-relevant config -> refused
    P2 = dict(P, num_leaves=15)
    with pytest.raises(CheckpointMismatch):
        _train(P2, X, y, 10, ckpt_dir=d)
    # different dataset -> refused
    with pytest.raises(CheckpointMismatch):
        _train(P, X[:500], y[:500], 10, ckpt_dir=d)
    # volatile knobs (run length) do NOT refuse
    bst = _train(P, X, y, 12, ckpt_dir=d)
    assert bst.current_iteration() == 12


# ----------------------------------------------------------------------
# resume bit-identity across the boosting drivers
# ----------------------------------------------------------------------
def _assert_resume_bit_identical(P, X, y, rounds=10, kill=6, freq=3,
                                 monkeypatch=None, env=None):
    for k, v in (env or {}).items():
        monkeypatch.setenv(k, v)
    ref = _train(P, X, y, rounds).model_to_string()
    d = tempfile.mkdtemp()
    try:
        _train_killed(P, X, y, rounds, d, kill=kill, freq=freq)
        assert CheckpointStore(d).steps(), "no checkpoint written before kill"
        resumed = _train(P, X, y, rounds, ckpt_dir=d, freq=freq)
        assert resumed.model_to_string() == ref
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_resume_bit_identical_gbdt_bagging(xy, monkeypatch):
    X, y = xy
    _assert_resume_bit_identical(
        dict(objective="binary", num_leaves=7, learning_rate=0.2, verbose=-1,
             bagging_fraction=0.7, bagging_freq=2, feature_fraction=0.8),
        X, y, monkeypatch=monkeypatch,
    )


def test_resume_bit_identical_goss(xy, monkeypatch):
    # learning_rate=0.3 ends the GOSS warmup (1/lr ~ 3 iters) before the
    # kill, so the chained PRNGKey is live state when the run dies
    X, y = xy
    _assert_resume_bit_identical(
        dict(objective="binary", boosting="goss", num_leaves=7, verbose=-1,
             learning_rate=0.3, top_rate=0.3, other_rate=0.2),
        X, y, monkeypatch=monkeypatch,
    )


def test_resume_bit_identical_dart(xy, monkeypatch):
    X, y = xy
    _assert_resume_bit_identical(
        dict(objective="binary", boosting="dart", num_leaves=7, verbose=-1,
             learning_rate=0.2, drop_rate=0.4, drop_seed=7),
        X, y, monkeypatch=monkeypatch,
    )


def test_resume_bit_identical_fused_partitioned(xy, monkeypatch):
    """Serial fused trainer (LIGHTGBM_TPU_PGROW=force on CPU interpret):
    the checkpoint must carry the physical row permutation — histogram
    summation order follows the partition layout."""
    X, y = xy
    _assert_resume_bit_identical(
        dict(objective="binary", num_leaves=7, learning_rate=0.2,
             min_data_in_leaf=20, verbose=-1),
        X, y, monkeypatch=monkeypatch, env={"LIGHTGBM_TPU_PGROW": "force"},
    )


def test_resume_bit_identical_fused_goss(xy, monkeypatch):
    X, y = xy
    _assert_resume_bit_identical(
        dict(objective="binary", boosting="goss", num_leaves=7, verbose=-1,
             learning_rate=0.3, top_rate=0.3, other_rate=0.2),
        X, y, monkeypatch=monkeypatch, env={"LIGHTGBM_TPU_PGROW": "force"},
    )


def test_resume_bit_identical_sharded_partitioned(monkeypatch):
    """Sharded fused trainer over the 8-device CPU mesh (single
    controller): the checkpoint carries every shard's physical row
    permutation; resume is bit-identical.  (The 2-process variant —
    cross-process barrier + host-0 container write — is the slow
    test_multihost.py::test_two_process_ckpt_resume_bit_identical.)"""
    monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
    rng = np.random.RandomState(5)
    X = rng.randint(0, 12, size=(3000, 6)).astype(np.float64)
    w = rng.randn(6)
    y = (1.0 / (1.0 + np.exp(-((X - 6) @ w * 0.3))) > rng.rand(3000)).astype(float)
    P = dict(objective="binary", tree_learner="data", num_leaves=15,
             learning_rate=0.2, max_bin=31, min_data_in_leaf=20, verbose=-1)
    ref = _train(P, X, y, 8)
    from lightgbm_tpu.boosting.ptrainer import ShardedPartitionedTrainer

    assert isinstance(ref.boosting.ptrainer, ShardedPartitionedTrainer)
    d = tempfile.mkdtemp()
    try:
        _train_killed(P, X, y, 8, d, kill=5, freq=2)
        resumed = _train(P, X, y, 8, ckpt_dir=d, freq=2)
        assert resumed.model_to_string() == ref.model_to_string()
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_corrupt_tail_checkpoint_falls_back(xy):
    """Kill, corrupt the newest checkpoint, resume: discovery skips the
    torn tail and resumes from the previous one — still bit-identical."""
    X, y = xy
    P = dict(objective="binary", num_leaves=7, learning_rate=0.2, verbose=-1,
             bagging_fraction=0.7, bagging_freq=2)
    ref = _train(P, X, y, 10).model_to_string()
    d = tempfile.mkdtemp()
    try:
        _train_killed(P, X, y, 10, d, kill=8, freq=3)
        st = CheckpointStore(d)
        steps = st.steps()
        assert len(steps) >= 2, steps
        with open(st.path_for(steps[-1]), "r+b") as f:
            f.truncate(128)  # torn write
        resumed = _train(P, X, y, 10, ckpt_dir=d, freq=3)
        assert resumed.model_to_string() == ref
    finally:
        shutil.rmtree(d, ignore_errors=True)


# ----------------------------------------------------------------------
# early stopping across a mid-patience-window kill
# ----------------------------------------------------------------------
def test_early_stopping_patience_survives_kill(xy):
    """Kill inside the patience window: the resumed run must count
    no-improvement rounds from the restored bests, stopping at the SAME
    iteration with the SAME best_iteration as the uninterrupted run."""
    rng = np.random.RandomState(3)
    X, y = xy
    Xv = X[:200] + 0.35 * rng.randn(200, X.shape[1])  # noisy valid set
    yv = y[:200]
    P = dict(objective="binary", metric="binary_logloss", num_leaves=15,
             learning_rate=0.3, verbose=-1)

    def run(ckpt_dir=None, callbacks=None, freq=2, expect_kill=False):
        ds = lgb.Dataset(X, label=y, params=dict(P))
        dv = lgb.Dataset(Xv, label=yv, reference=ds)
        mgr = CheckpointManager(ckpt_dir, freq=freq) if ckpt_dir else None
        hist = {}
        bst = None
        try:
            # evals_result is passed in EVERY leg so the tracked-callback
            # lists line up between the killed and the resumed run
            if expect_kill:
                with pytest.raises(KeyboardInterrupt):
                    lgb.train(dict(P), ds, 40, valid_sets=[dv],
                              early_stopping_rounds=5, evals_result=hist,
                              verbose_eval=False, checkpoint_manager=mgr,
                              callbacks=callbacks)
            else:
                bst = lgb.train(dict(P), ds, 40, valid_sets=[dv],
                                early_stopping_rounds=5, evals_result=hist,
                                verbose_eval=False, checkpoint_manager=mgr,
                                callbacks=callbacks)
        finally:
            if mgr is not None:
                mgr.close()
        return bst, hist

    ref, ref_hist = run()
    stop_iter = ref.current_iteration()
    best = ref.best_iteration
    assert 0 < best < stop_iter < 40, (best, stop_iter)

    # kill mid-patience-window (after the best, before the stop)
    kill = best + 2
    assert kill < stop_iter
    d = tempfile.mkdtemp()
    try:
        run(ckpt_dir=d, callbacks=[_kill_at(kill)], expect_kill=True)
        resumed, res_hist = run(ckpt_dir=d)
        assert resumed.best_iteration == best
        assert resumed.current_iteration() == stop_iter
        assert resumed.model_to_string() == ref.model_to_string()
        # eval history restored through the kill point, identical after
        k = list(ref_hist)[0]
        m = list(ref_hist[k])[0]
        assert res_hist[k][m] == ref_hist[k][m]
    finally:
        shutil.rmtree(d, ignore_errors=True)


# ----------------------------------------------------------------------
# checkpoint-resume vs init_model continued training (parity pin)
# ----------------------------------------------------------------------
def test_checkpoint_resume_vs_init_model_semantics(xy):
    """Pins the semantic difference: checkpoint resume restores the
    score caches and RNG streams (bit-identical); init_model continued
    training (gbdt.cpp input-model semantics) RECOMPUTES scores via
    predict and restarts the RNG streams — statistically equivalent,
    not bit-guaranteed."""
    X, y = xy
    P = dict(objective="binary", num_leaves=7, learning_rate=0.2, verbose=-1,
             bagging_fraction=0.7, bagging_freq=2)
    ref = _train(P, X, y, 10)
    ref_str = ref.model_to_string()

    # checkpoint resume: bit-identical
    d = tempfile.mkdtemp()
    try:
        _train_killed(P, X, y, 10, d, kill=7, freq=5)
        resumed = _train(P, X, y, 10, ckpt_dir=d, freq=5)
        assert resumed.model_to_string() == ref_str
    finally:
        shutil.rmtree(d, ignore_errors=True)

    # init_model continuation: same tree COUNT and the first 5 trees are
    # the reference's own (the text round trip preserves them verbatim
    # in the continued model), but the run is NOT bit-guaranteed —
    # scores are re-seeded via predict, the bagging RNG restarts
    first = _train(P, X, y, 5)
    first_str = first.model_to_string()
    cont = lgb.train(dict(P), lgb.Dataset(X, label=y, params=dict(P)),
                     5, init_model=first, verbose_eval=False)
    assert cont.current_iteration() == 10
    assert cont.num_trees == ref.num_trees
    cont_str = cont.model_to_string()
    for blk in first_str.split("Tree=")[1:3]:
        body = blk.partition("\n")[2].split("\nTree=")[0]
        assert body.split("feature importances")[0].strip() in cont_str
    # predictions agree statistically (same algorithm), not bitwise:
    # the continuation replays different bagging draws after iter 5
    pr, pc = ref.predict(X[:200]), cont.predict(X[:200])
    assert np.mean(np.abs(pr - pc)) < 0.1
    assert np.corrcoef(pr, pc)[0, 1] > 0.9


# ----------------------------------------------------------------------
# manager behaviors
# ----------------------------------------------------------------------
def test_preemption_flush_and_exit(xy, tmp_path):
    """request_preemption (the SIGTERM handler's effect) makes the next
    iteration boundary write a checkpoint and end training cleanly; a
    fresh run resumes from it bit-identically."""
    X, y = xy
    P = dict(objective="binary", num_leaves=7, learning_rate=0.2, verbose=-1)
    ref = _train(P, X, y, 10).model_to_string()
    d = str(tmp_path)
    mgr = CheckpointManager(d, freq=3)

    def preempt(env):
        if env.iteration + 1 == 5:
            mgr.request_preemption()
    preempt.order = 5  # before the manager's boundary check

    ds = lgb.Dataset(X, label=y, params=dict(P))
    bst = lgb.train(dict(P), ds, 10, verbose_eval=False,
                    checkpoint_manager=mgr, callbacks=[preempt])
    mgr.close()
    assert bst.current_iteration() == 5  # stopped at the boundary
    st = CheckpointStore(d)
    assert st.steps()[-1] == 5  # flushed the preemption checkpoint
    assert st.complete_step() is None  # NOT marked complete
    resumed = _train(P, X, y, 10, ckpt_dir=d, freq=3)
    assert resumed.model_to_string() == ref


def test_completed_run_not_auto_resumed(xy, tmp_path):
    """auto resume must not hijack a FRESH run after a prior run in the
    same directory completed normally (the CLI reruns-in-place case)."""
    X, y = xy
    P = dict(objective="binary", num_leaves=7, verbose=-1)
    d = str(tmp_path)
    b1 = _train(P, X, y, 6, ckpt_dir=d, freq=2)
    assert CheckpointStore(d).complete_step() == 6
    b2 = _train(P, X, y, 3, ckpt_dir=d, freq=2)  # shorter fresh run
    assert b2.current_iteration() == 3
    assert b2.num_trees < b1.num_trees


def test_ckpt_obs_spans(xy, tmp_path, monkeypatch):
    """Checkpoint activity shows up in the run trace (docs/OBSERVABILITY.md):
    capture/serialize spans + ckpt.saved events with byte counts."""
    import json

    trace = str(tmp_path / "trace.jsonl")
    monkeypatch.setenv("LIGHTGBM_TPU_TRACE", trace)
    X, y = xy
    P = dict(objective="binary", num_leaves=7, verbose=-1)
    _train(P, X, y, 6, ckpt_dir=str(tmp_path / "ck"), freq=3)
    from lightgbm_tpu.obs import tracer

    tracer.close()
    recs = [json.loads(ln) for ln in open(trace)]
    spans = {r["name"] for r in recs if r["ev"] == "span"}
    assert "ckpt.capture" in spans and "ckpt.serialize" in spans
    saved = [r for r in recs if r["ev"] == "event" and r["name"] == "ckpt.saved"]
    assert saved and all(r["bytes"] > 0 for r in saved)
    assert any(r["ev"] == "counter" and r["name"] == "ckpt.bytes" for r in recs)
