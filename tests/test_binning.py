import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.binning import CATEGORICAL, NUMERICAL, BinMapper, greedy_find_bin
from lightgbm_tpu.io.dataset import BinnedDataset


def test_greedy_find_bin_few_distinct():
    vals = np.array([1.0, 2.0, 3.0])
    counts = np.array([10, 10, 10])
    bounds = greedy_find_bin(vals, counts, max_bin=10, total_cnt=30, min_data_in_bin=1)
    assert bounds[-1] == np.inf
    assert bounds[:-1] == [1.5, 2.5]


def test_greedy_find_bin_min_data_in_bin():
    vals = np.array([1.0, 2.0, 3.0, 4.0])
    counts = np.array([1, 1, 1, 100])
    bounds = greedy_find_bin(vals, counts, max_bin=10, total_cnt=103, min_data_in_bin=3)
    # first bin must absorb 1.0,2.0,3.0 before closing
    assert bounds[0] == 3.5
    assert bounds[-1] == np.inf


def test_greedy_find_bin_many_distinct_equal_count():
    vals = np.arange(1000, dtype=np.float64) + 1.0
    counts = np.ones(1000, dtype=np.int64)
    bounds = greedy_find_bin(vals, counts, max_bin=10, total_cnt=1000, min_data_in_bin=0)
    assert len(bounds) == 10
    # roughly equal-count bins
    binned = np.searchsorted(np.asarray(bounds), vals, side="left")
    _, cnt = np.unique(binned, return_counts=True)
    assert cnt.min() >= 50


def test_bin_mapper_zero_bin_and_default():
    # positive values plus implicit zeros: bin 0 must be the zero bin
    rng = np.random.RandomState(0)
    nonzero = rng.uniform(1.0, 10.0, size=500)
    m = BinMapper()
    m.find_bin(nonzero, total_sample_cnt=1000, max_bin=16, min_data_in_bin=1, min_split_data=1)
    assert not m.is_trivial
    assert m.default_bin == 0
    assert m.value_to_bin(0.0) == 0
    assert m.value_to_bin(100.0) == m.num_bin - 1
    # ordering preserved
    b = m.value_to_bin(np.array([1.0, 5.0, 9.0]))
    assert b[0] <= b[1] <= b[2]


def test_bin_mapper_negative_values_interior_zero():
    rng = np.random.RandomState(1)
    nonzero = np.concatenate([rng.uniform(-5, -1, 300), rng.uniform(1, 5, 300)])
    m = BinMapper()
    m.find_bin(nonzero, total_sample_cnt=700, max_bin=32, min_data_in_bin=1, min_split_data=1)
    d = m.default_bin
    assert 0 < d < m.num_bin - 1
    assert m.value_to_bin(0.0) == d
    assert m.value_to_bin(-10.0) == 0
    assert m.value_to_bin(10.0) == m.num_bin - 1


def test_bin_mapper_trivial():
    m = BinMapper()
    m.find_bin(np.array([]), total_sample_cnt=100, max_bin=16, min_data_in_bin=1, min_split_data=1)
    assert m.is_trivial


def test_bin_mapper_categorical():
    vals = np.array([1.0] * 50 + [2.0] * 30 + [3.0] * 15 + [4.0] * 5)
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=100, max_bin=3, min_data_in_bin=1,
               min_split_data=1, bin_type=CATEGORICAL)
    assert m.bin_type == CATEGORICAL
    assert m.value_to_bin(1.0) == 0  # most frequent first
    assert m.value_to_bin(999.0) == m.num_bin - 1  # unseen -> last bin


def test_value_to_bin_monotone_roundtrip():
    rng = np.random.RandomState(3)
    nonzero = rng.normal(size=2000)
    m = BinMapper()
    m.find_bin(nonzero, total_sample_cnt=2500, max_bin=64, min_data_in_bin=3, min_split_data=3)
    xs = np.sort(rng.normal(size=100))
    bins = m.value_to_bin(xs)
    assert np.all(np.diff(bins) >= 0)
    # values map inside their bin's bounds
    for x, b in zip(xs, bins):
        assert x <= m.bin_upper_bound[b] + 1e-12


def test_binned_dataset_from_raw_and_binary_roundtrip(tmp_path):
    rng = np.random.RandomState(7)
    X = rng.normal(size=(500, 6))
    X[:, 3] = 1.0  # trivial feature
    y = rng.normal(size=500)
    cfg = Config.from_params({"max_bin": 16, "min_data_in_bin": 1})
    ds = BinnedDataset.from_raw(X, cfg, label=y)
    assert ds.num_data == 500
    assert ds.num_features == 5  # trivial feature filtered
    assert ds.num_total_features == 6
    assert ds.binned.dtype == np.uint8

    p = str(tmp_path / "cache.npz")
    ds.save_binary(p)
    ds2 = BinnedDataset.load_binary(p)
    assert np.array_equal(ds.binned, ds2.binned)
    assert np.allclose(ds.metadata.label, ds2.metadata.label)
    assert len(ds2.bin_mappers) == len(ds.bin_mappers)
    assert np.allclose(ds.bin_mappers[0].bin_upper_bound, ds2.bin_mappers[0].bin_upper_bound)


def test_valid_aligned_with_train():
    rng = np.random.RandomState(11)
    X = rng.normal(size=(400, 4))
    Xv = rng.normal(size=(100, 4))
    cfg = Config.from_params({"max_bin": 32})
    ds = BinnedDataset.from_raw(X, cfg, label=rng.normal(size=400))
    dv = ds.create_valid(Xv, label=rng.normal(size=100))
    assert dv.num_features == ds.num_features
    assert dv.bin_mappers is ds.bin_mappers


def test_config_aliases_and_unknown():
    cfg = Config.from_params({"num_leaf": 63, "sub_feature": 0.8, "reg_alpha": 0.5})
    assert cfg.num_leaves == 63
    assert cfg.feature_fraction == 0.8
    assert cfg.lambda_l1 == 0.5
    from lightgbm_tpu.utils.log import LightGBMError

    with pytest.raises(LightGBMError):
        Config.from_params({"definitely_not_a_param": 1})


def test_config_canonical_priority():
    cfg = Config.from_params({"num_iterations": 7, "num_boost_round": 9})
    assert cfg.num_iterations == 7


def test_binary_cache_exact_filename_and_dataset_dispatch(tmp_path):
    """save_binary writes the EXACT filename given (the reference's
    SaveBinaryFile does), and lgb.Dataset(path) detects the cache and
    skips text parsing."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(0)
    X = rng.standard_normal((300, 4)).astype(np.float32)
    y = rng.standard_normal(300).astype(np.float32)
    p = str(tmp_path / "cache.bin")  # no .npz suffix
    lgb.Dataset(X, label=y).save_binary(p)
    import os
    assert os.path.exists(p)
    bst = lgb.train({"objective": "regression", "num_leaves": 7, "verbose": -1},
                    lgb.Dataset(p), 3)
    assert bst.boosting.num_trees >= 3
    # explicit group / init_score supplied alongside a cache path are honored
    ds3 = lgb.Dataset(p, group=[150, 150], init_score=np.zeros(300)).construct()
    assert ds3.metadata.query_boundaries is not None
    assert ds3.metadata.init_score is not None
