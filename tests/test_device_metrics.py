"""Device-metric twins (metric/device.py) must match the host metrics.

The host implementations are the parity-verified reference twins
(binary_metric.hpp / regression_metric.hpp / multiclass_metric.hpp);
the device versions exist so eval points keep scores device-resident
(VERDICT r4 weak-7).  Tie handling in AUC is exercised via rounded
scores (many exact duplicates)."""

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.metric.binary import (
    AUCMetric,
    BinaryErrorMetric,
    BinaryLoglossMetric,
)
from lightgbm_tpu.metric.multiclass import MultiErrorMetric, MultiLoglossMetric
from lightgbm_tpu.metric.regression import L1Metric, L2Metric, RMSEMetric
from lightgbm_tpu.objective.binary import BinaryLogloss
from lightgbm_tpu.objective.multiclass import MulticlassSoftmax


class _Meta:
    pass


def _check(metric, score, objective, rtol=2e-5):
    (_, host) = metric.eval(np.asarray(score, np.float64), objective)[0]
    (_, dev) = metric.eval_device(score, objective)[0]
    assert dev == pytest.approx(host, rel=rtol, abs=1e-6)


@pytest.mark.parametrize("weighted", [False, True])
def test_binary_device_metrics_match_host(rng, weighted):
    n = 20_000
    score = np.round(rng.standard_normal(n), 2).astype(np.float32)  # ties
    meta = _Meta()
    meta.label = (rng.random(n) < 0.4).astype(np.float64)
    meta.weights = rng.random(n) + 0.5 if weighted else None
    cfg = Config()
    obj = BinaryLogloss(cfg)
    for cls in (AUCMetric, BinaryLoglossMetric, BinaryErrorMetric):
        m = cls(cfg)
        m.init(meta, n)
        _check(m, score, obj)


def test_regression_device_metrics_match_host(rng):
    n = 20_000
    score = rng.standard_normal(n).astype(np.float32)
    meta = _Meta()
    meta.label = rng.standard_normal(n)
    meta.weights = rng.random(n) + 0.5
    cfg = Config()
    for cls in (L2Metric, RMSEMetric, L1Metric):
        m = cls(cfg)
        m.init(meta, n)
        _check(m, score, None)


def test_multiclass_device_metrics_match_host(rng):
    n = 20_000
    cfg = Config(num_class=5)
    obj = MulticlassSoftmax(cfg)
    # quantized scores force exact cross-class ties: multi_error counts a
    # tie on the true class as an error (>= sweep), which argmax would miss
    score = np.round(rng.standard_normal((5, n)), 1).astype(np.float32)
    meta = _Meta()
    meta.label = rng.randint(0, 5, n).astype(np.float64)
    meta.weights = None
    for cls in (MultiLoglossMetric, MultiErrorMetric):
        m = cls(cfg)
        m.init(meta, n)
        _check(m, score, obj, rtol=5e-5)


def test_auc_device_all_positive_edge(rng):
    """denominator 0 -> reference returns 1.0 (binary_metric.hpp:249)."""
    n = 256
    meta = _Meta()
    meta.label = np.ones(n)
    meta.weights = None
    m = AUCMetric(Config())
    m.init(meta, n)
    score = rng.standard_normal(n).astype(np.float32)
    (_, host) = m.eval(np.asarray(score, np.float64))[0]
    (_, dev) = m.eval_device(score)[0]
    assert host == 1.0 and dev == 1.0


def test_device_path_gated_by_size_without_x64(rng):
    """Above _DEV_F32_ROW_LIMIT without x64 the device path must refuse
    (NotImplementedError) so gbdt._eval_metric falls back to host f64 —
    f32 accumulation drift at Higgs scale corrupted early-stopping
    comparisons (ADVICE r5)."""
    import jax

    from lightgbm_tpu.metric.device import _DEV_F32_ROW_LIMIT

    n = 1024  # real rows; num_data is lied upward to trip the gate
    meta = _Meta()
    meta.label = (rng.random(n) < 0.4).astype(np.float64)
    meta.weights = None
    m = AUCMetric(Config())
    m.init(meta, n)
    m.num_data = _DEV_F32_ROW_LIMIT + 1
    score = rng.standard_normal(n).astype(np.float32)
    if jax.config.jax_enable_x64:
        pytest.skip("gate only applies without x64")
    with pytest.raises(NotImplementedError):
        m.eval_device(score)
    # under the limit the device path still runs
    m.num_data = n
    (_, dev) = m.eval_device(score)[0]
    (_, host) = m.eval(np.asarray(score, np.float64))[0]
    assert dev == pytest.approx(host, rel=2e-5)
