"""Monotone-constraint plug-in tests (tree/strategy.py SplitGain seam).

The property under test is LightGBM's "basic" monotone mode: with
``monotone_constraints`` +1/-1 on a feature, sweeping that feature over
its whole bin grid (all other features held fixed) must never move the
prediction in the forbidden direction — on the serial learner AND on the
host-driven 2-rank learner (LocalComm).  All-zero constraints must stay
bit-identical to unconstrained training (the strategy seam compiles the
exact pre-strategy graph when inactive).
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.model.tree import Tree
from lightgbm_tpu.objective import create_objective
from lightgbm_tpu.ops.grow import GrowParams
from lightgbm_tpu.ops.split import FeatureMeta, SplitHyper
from lightgbm_tpu.tree.strategy import TreeStrategy


def _problem(seed=0, n=2500, f=6):
    rng = np.random.RandomState(seed)
    X = rng.uniform(-2.0, 2.0, size=(n, f))
    y = (1.2 * X[:, 0] - 0.8 * X[:, 1] + 0.4 * np.sin(3 * X[:, 2])
         + 0.2 * rng.randn(n))
    return X, y


def _assert_monotone(predict, f, feat, sign, rng, grid_n=48, rows=40,
                     tol=1e-6):
    """Sweep ``feat`` over its range for random base rows; the signed
    finite differences must all be >= -tol."""
    base = rng.uniform(-2.0, 2.0, size=(rows, f))
    grid = np.linspace(-2.2, 2.2, grid_n)
    preds = np.stack([predict(_with(base, feat, v)) for v in grid])
    worst = float((np.diff(preds, axis=0) * sign).min())
    assert worst >= -tol, (
        f"monotone constraint {sign:+d} violated on feature {feat}: "
        f"worst signed delta {worst}")


def _with(base, feat, v):
    Z = base.copy()
    Z[:, feat] = v
    return Z


@pytest.mark.parametrize("learner", ["serial", "data"])
def test_monotone_sweep_booster(learner):
    X, y = _problem()
    params = {"objective": "regression", "num_leaves": 31,
              "min_data_in_leaf": 20, "learning_rate": 0.1, "verbose": -1,
              "seed": 3, "monotone_constraints": "1,-1,0,0,0,0",
              "tree_learner": learner}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=25,
                    verbose_eval=False)
    rng = np.random.RandomState(7)
    _assert_monotone(bst.predict, X.shape[1], 0, +1, rng)
    _assert_monotone(bst.predict, X.shape[1], 1, -1, rng)


def test_monotone_sweep_hostlearner_2rank():
    """One tree grown by the 2-rank host-driven data-parallel learner
    (LocalComm) must satisfy the constraints: every rank replays the
    mid-point bound tables host-side, no extra exchange."""
    from lightgbm_tpu.parallel import HostParallelLearner, LocalGroup

    X, y = _problem(seed=4, n=3000)
    f = X.shape[1]
    cfg = Config.from_params(
        {"objective": "regression", "num_leaves": 15,
         "min_data_in_leaf": 20, "verbose": -1,
         "monotone_constraints": "1,-1,0,0,0,0"})
    ds = BinnedDataset.from_raw(X, cfg, label=y)
    obj = create_objective(cfg)
    obj.init(ds.metadata, ds.num_data)
    grad, hess = obj.get_gradients(jnp.zeros((ds.num_data,), jnp.float32))
    grad_np = np.asarray(grad)
    hess_np = np.asarray(hess)
    strategy = TreeStrategy.from_config(cfg, ds)
    assert strategy.split_gain.constrained
    params = GrowParams(num_leaves=15, num_bins=ds.max_num_bin,
                        strategy=strategy)
    meta = FeatureMeta.from_dataset(ds)
    hyper = SplitHyper.from_config(cfg)
    fmask = jnp.ones((f,), jnp.float32)
    bins = np.asarray(ds.binned)
    rows = np.array_split(np.arange(ds.num_data), 2)
    grp = LocalGroup(2)
    out = [None] * 2
    errs = []

    def worker(r, comm):
        try:
            idx = rows[r]
            learner = HostParallelLearner("data", comm, params)
            gr = learner.grow(
                jnp.asarray(bins[idx]), jnp.asarray(grad_np[idx]),
                jnp.asarray(hess_np[idx]),
                jnp.ones((len(idx),), jnp.float32), fmask, meta, hyper)
            out[r] = jax.tree_util.tree_map(np.asarray, gr)
        except BaseException as e:  # surfaced below
            errs.append((r, e))

    ts = [threading.Thread(target=worker, args=(r, c))
          for r, c in enumerate(grp.comms())]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    if errs:
        raise errs[0][1]
    assert int(out[0].num_splits) > 0
    tree = Tree.from_grow_result(out[0], ds)
    rng = np.random.RandomState(11)

    def predict(Z):
        return tree.predict(np.asarray(Z, np.float64))

    _assert_monotone(predict, f, 0, +1, rng, rows=25)
    _assert_monotone(predict, f, 1, -1, rng, rows=25)


def test_all_zero_constraints_bit_identical():
    """monotone_constraints of all zeros must keep training on the
    pre-strategy graph: model bytes identical to no constraints at all."""
    X, y = _problem(seed=9, n=1200)
    base = {"objective": "regression", "num_leaves": 15,
            "min_data_in_leaf": 20, "verbose": -1, "seed": 5}
    b0 = lgb.train(dict(base), lgb.Dataset(X, label=y), num_boost_round=8,
                   verbose_eval=False)
    b1 = lgb.train(dict(base, monotone_constraints="0,0,0,0,0,0"),
                   lgb.Dataset(X, label=y), num_boost_round=8,
                   verbose_eval=False)
    assert b0.model_to_string() == b1.model_to_string()
