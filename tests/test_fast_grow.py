"""FastGrower (host-driven O(N_leaf) grower) must reproduce the jitted
while-loop grower's tree exactly — both implement the identical
SerialTreeLearner algorithm.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.objective import create_objective
from lightgbm_tpu.ops.fast_grow import FastGrower
from lightgbm_tpu.ops.grow import GrowParams, grow_tree
from lightgbm_tpu.ops.split import FeatureMeta, SplitHyper


@pytest.fixture(scope="module", params=["binary", "regression"])
def problem(request):
    rng = np.random.RandomState(3)
    n, f = 5000, 10
    x = rng.randn(n, f)
    x[:, 3] = np.round(x[:, 3])  # ties / default-bin traffic
    if request.param == "binary":
        y = (x[:, 0] + 0.5 * x[:, 1] ** 2 > 0.3).astype(np.float32)
    else:
        y = (x[:, 0] - 2 * x[:, 2] + 0.1 * rng.randn(n)).astype(np.float32)
    cfg = Config.from_params(
        {"objective": request.param, "num_leaves": 31, "verbose": -1}
    )
    ds = BinnedDataset.from_raw(x, cfg, label=y)
    obj = create_objective(cfg)
    obj.init(ds.metadata, ds.num_data)
    grad, hess = obj.get_gradients(jnp.zeros((n,), jnp.float32))
    return {
        "ds": ds,
        "grad": grad,
        "hess": hess,
        "meta": FeatureMeta.from_dataset(ds),
        "hyper": SplitHyper.from_config(cfg),
        "params": GrowParams(num_leaves=31, num_bins=ds.max_num_bin),
    }


def test_fast_grower_matches_jitted(problem):
    p = problem
    n = p["ds"].num_data
    select = jnp.ones((n,), jnp.float32)
    fmask = jnp.ones((p["ds"].num_features,), jnp.float32)
    bins = jnp.asarray(p["ds"].binned)

    ref = grow_tree(bins, p["grad"], p["hess"], select, fmask,
                    p["meta"], p["hyper"], p["params"])
    fg = FastGrower(p["ds"].binned, p["meta"], p["hyper"], p["params"])
    got = fg.grow(p["grad"], p["hess"], select, fmask)

    s = int(ref.num_splits)
    assert int(got.num_splits) == s
    np.testing.assert_array_equal(np.asarray(got.rec_feat[:s]),
                                  np.asarray(ref.rec_feat[:s]))
    np.testing.assert_array_equal(np.asarray(got.rec_thr[:s]),
                                  np.asarray(ref.rec_thr[:s]))
    np.testing.assert_array_equal(np.asarray(got.rec_leaf[:s]),
                                  np.asarray(ref.rec_leaf[:s]))
    np.testing.assert_array_equal(np.asarray(got.rec_dbz[:s]),
                                  np.asarray(ref.rec_dbz[:s]))
    np.testing.assert_allclose(np.asarray(got.leaf_value),
                               np.asarray(ref.leaf_value), atol=1e-5)
    np.testing.assert_array_equal(np.asarray(got.leaf_id),
                                  np.asarray(ref.leaf_id))
    np.testing.assert_allclose(np.asarray(got.leaf_cnt),
                               np.asarray(ref.leaf_cnt), atol=0.5)


def test_fast_grower_with_bagging_mask(problem):
    """Out-of-bag rows must still be routed to leaves (leaf_id covers all
    rows) while histograms see only selected rows."""
    p = problem
    n = p["ds"].num_data
    rng = np.random.RandomState(0)
    select_np = (rng.rand(n) < 0.7).astype(np.float32)
    select = jnp.asarray(select_np)
    fmask = jnp.ones((p["ds"].num_features,), jnp.float32)
    bins = jnp.asarray(p["ds"].binned)

    ref = grow_tree(bins, p["grad"], p["hess"], select, fmask,
                    p["meta"], p["hyper"], p["params"])
    fg = FastGrower(p["ds"].binned, p["meta"], p["hyper"], p["params"])
    got = fg.grow(p["grad"], p["hess"], select, fmask)

    s = int(ref.num_splits)
    assert int(got.num_splits) == s
    np.testing.assert_array_equal(np.asarray(got.rec_feat[:s]),
                                  np.asarray(ref.rec_feat[:s]))
    np.testing.assert_array_equal(np.asarray(got.leaf_id),
                                  np.asarray(ref.leaf_id))


def test_compaction_tiers_match_masked_path():
    """At N large enough for lax.switch compaction tiers, the grower must
    match the masked O(N) path exactly (compact=False)."""
    import jax.numpy as jnp
    from lightgbm_tpu.ops.grow import _tiers

    rng = np.random.RandomState(7)
    n, f = 40000, 6
    assert _tiers(n), "test size must activate tiers"
    x = rng.randn(n, f)
    y = (x[:, 0] - 0.8 * x[:, 2] + 0.2 * rng.randn(n) > 0).astype(np.float32)
    cfg = Config.from_params({"objective": "binary", "num_leaves": 31,
                              "verbose": -1})
    ds = BinnedDataset.from_raw(x, cfg, label=y)
    obj = create_objective(cfg)
    obj.init(ds.metadata, ds.num_data)
    grad, hess = obj.get_gradients(jnp.zeros((n,), jnp.float32))
    meta = FeatureMeta.from_dataset(ds)
    hyper = SplitHyper.from_config(cfg)
    select = jnp.ones((n,), jnp.float32)
    fmask = jnp.ones((ds.num_features,), jnp.float32)
    bins = jnp.asarray(ds.binned)

    params_c = GrowParams(num_leaves=31, num_bins=ds.max_num_bin, compact=True)
    params_m = GrowParams(num_leaves=31, num_bins=ds.max_num_bin, compact=False)
    a = grow_tree(bins, grad, hess, select, fmask, meta, hyper, params_c)
    b = grow_tree(bins, grad, hess, select, fmask, meta, hyper, params_m)
    s = int(b.num_splits)
    assert int(a.num_splits) == s
    np.testing.assert_array_equal(np.asarray(a.rec_feat[:s]), np.asarray(b.rec_feat[:s]))
    np.testing.assert_array_equal(np.asarray(a.rec_thr[:s]), np.asarray(b.rec_thr[:s]))
    np.testing.assert_array_equal(np.asarray(a.leaf_id), np.asarray(b.leaf_id))
    np.testing.assert_allclose(np.asarray(a.leaf_value), np.asarray(b.leaf_value), atol=2e-4)
