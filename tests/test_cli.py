"""CLI tests — the reference's examples/*/train.conf + predict.conf must
run unmodified (SURVEY §7.10; modeled on tests/cpp_test/test.py which
trains from two configs and compares prediction files).
"""

import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

ENV = dict(
    os.environ,
    JAX_PLATFORMS="cpu",
    PYTHONPATH="/root/repo" + os.pathsep + os.environ.get("PYTHONPATH", ""),
)


def _run_cli(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", *args],
        cwd=cwd, env=ENV, capture_output=True, text=True, timeout=900,
    )


@pytest.fixture(scope="module")
def regression_dir(tmp_path_factory, reference_examples):
    """Copy of examples/regression (the originals are read-only)."""
    dst = tmp_path_factory.mktemp("regression_example")
    for name in ("train.conf", "predict.conf", "regression.train", "regression.test"):
        shutil.copy(f"{reference_examples}/regression/{name}", dst)
    return str(dst)


def test_reference_train_conf_runs_unmodified(regression_dir):
    r = _run_cli(["config=train.conf", "num_trees=5"], regression_dir)
    assert r.returncode == 0, r.stdout + r.stderr
    assert os.path.exists(os.path.join(regression_dir, "LightGBM_model.txt"))


def test_reference_predict_conf_runs_unmodified(regression_dir):
    # depends on the model from the train test; rerun train if missing
    if not os.path.exists(os.path.join(regression_dir, "LightGBM_model.txt")):
        _run_cli(["config=train.conf", "num_trees=5"], regression_dir)
    r = _run_cli(["config=predict.conf"], regression_dir)
    assert r.returncode == 0, r.stdout + r.stderr
    out = os.path.join(regression_dir, "LightGBM_predict_result.txt")
    assert os.path.exists(out)
    preds = np.loadtxt(out)
    assert preds.shape[0] == 500  # regression.test rows
    assert np.all(np.isfinite(preds))


def test_cli_param_priority(regression_dir):
    """Command line overrides the config file (application.cpp:87-89)."""
    r = _run_cli(
        ["config=train.conf", "num_trees=2", "output_model=cli_model.txt"],
        regression_dir,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    model = open(os.path.join(regression_dir, "cli_model.txt")).read()
    # 2 iterations + boost_from_average init tree
    assert model.count("Tree=") == 3
