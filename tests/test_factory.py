"""Continuous-training factory tests (docs/FACTORY.md): the crash-safe
supervisor state file (CRC refusal, atomic round-trip), the data-dir
watcher (content fingerprints, debounce, touch is not a change), the
registry lifecycle extensions (publish dedupe, canary pin, quarantine,
lifecycle-aware GC), per-version serving metrics (/stats vs /metrics
parity, prune on swap), the init_model schema-drift guard, the
in-process factory cycle (cold promote -> warm-started promote), crash
replay (kill mid-publish never double-publishes), the eval-gate
rollback verdict, a subprocess SIGKILL mid-retrain that resumes from
its checkpoint, and the tier-1 e2e: a live subprocess fleet under
closed-loop traffic where a data append drives warm retrain -> publish
-> canary -> auto-promote with zero dropped or mis-versioned responses,
and a blind canary auto-rolls-back with a recorded verdict.
"""

import glob
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.factory import FactoryState, FactorySupervisor
from lightgbm_tpu.factory import watch
from lightgbm_tpu.obs.metrics import registry as metrics_registry
from lightgbm_tpu.serve import (
    FleetProxy,
    ModelRegistry,
    PackedPredictor,
    PredictorArtifact,
)
from lightgbm_tpu.serve.fleet import _wait_ready, spawn_replicas
from lightgbm_tpu.utils.log import LightGBMError

N_FEATURES = 8
TRAIN_PARAMS = {"objective": "binary", "num_leaves": 7, "verbose": -1,
                "min_data_in_leaf": 5}
FACTORY_KNOBS = {"num_boost_round": 5, "checkpoint_freq": 2,
                 "debounce_ms": 0.0, "canary_fraction": 0.0}


def _write_chunk(data_dir, name, n, seed, backdate=True):
    """Append ``n`` CSV rows (label first, the parser default) drawn
    from one fixed rule, so every chunk is more signal for the same
    concept — warm starts should help, never regress."""
    rng = np.random.RandomState(seed)
    X = rng.randn(n, N_FEATURES)
    y = (X[:, 0] + 0.5 * X[:, 1] - X[:, 2] > 0).astype(int)
    path = os.path.join(data_dir, name)
    with open(path, "a") as f:
        for yy, row in zip(y, X):
            f.write(",".join([str(yy)] + [f"{v:.6f}" for v in row]) + "\n")
    if backdate:  # move mtime out of the debounce window
        t = time.time() - 60
        os.utime(path, (t, t))
    return path


def _supervisor(tmp_path, **over):
    data_dir = os.path.join(tmp_path, "data")
    os.makedirs(data_dir, exist_ok=True)
    knobs = dict(FACTORY_KNOBS)
    params = dict(TRAIN_PARAMS)
    for k in list(over):
        if k in ("proxy", "host"):
            continue
        knobs[k] = over.pop(k)
    return FactorySupervisor(
        data_dir, os.path.join(tmp_path, "work"),
        os.path.join(tmp_path, "reg"), params=params, **over, **knobs)


@pytest.fixture(scope="module")
def tiny_booster():
    rng = np.random.RandomState(7)
    X = rng.randn(400, N_FEATURES)
    y = (X[:, 0] + 0.5 * X[:, 1] - X[:, 2] > 0).astype(np.float32)
    ds = lgb.Dataset(X, label=y, params={"min_data_in_leaf": 5})
    bst = lgb.train(dict(TRAIN_PARAMS), ds, num_boost_round=8,
                    verbose_eval=False)
    return bst, X


def _scaled(art, scale):
    from lightgbm_tpu.ops.predict import TreeArrays

    fields = {f: np.asarray(getattr(art.arrays, f))
              for f in TreeArrays.FIELDS}
    fields["leaf_value"] = fields["leaf_value"] * scale
    return PredictorArtifact(TreeArrays(**fields), art.meta)


# ----------------------------------------------------------------------
# supervisor state file
# ----------------------------------------------------------------------
class TestFactoryState:
    def test_fresh_when_absent(self, tmp_path):
        st = FactoryState.load(str(tmp_path))
        assert st.ingested == {} and st.run is None
        assert st.history == [] and st.current is None

    def test_round_trip(self, tmp_path):
        st = FactoryState(str(tmp_path))
        st.ingested = {"a.csv": {"size": 3, "mtime_ns": 1, "crc32": 9}}
        st.run = {"run_id": "r000001-abc", "candidate_version": 2}
        st.current = {"version": 1, "model_path": "/x", "metric": 0.1}
        st.retrain_seq = 4
        st.record_verdict({"run_id": "r000001-abc", "verdict": "promoted"})
        st.save()
        back = FactoryState.load(str(tmp_path))
        assert back.ingested == st.ingested
        assert back.run == st.run
        assert back.current == st.current
        assert back.retrain_seq == 4
        assert back.history == st.history

    def test_crc_mismatch_refused(self, tmp_path):
        st = FactoryState(str(tmp_path))
        st.retrain_seq = 1
        st.save()
        with open(st.path) as f:
            doc = json.load(f)
        doc["payload"]["retrain_seq"] = 99  # tamper without re-CRC
        with open(st.path, "w") as f:
            json.dump(doc, f)
        with pytest.raises(LightGBMError, match="CRC"):
            FactoryState.load(str(tmp_path))

    def test_garbage_refused(self, tmp_path):
        st = FactoryState(str(tmp_path))
        with open(st.path, "w") as f:
            f.write("not json{")
        with pytest.raises(LightGBMError, match="unreadable"):
            FactoryState.load(str(tmp_path))

    def test_history_bounded(self, tmp_path):
        st = FactoryState(str(tmp_path))
        for i in range(60):
            st.record_verdict({"run_id": f"r{i}"}, keep=50)
        assert len(st.history) == 50
        assert st.history[-1]["run_id"] == "r59"


# ----------------------------------------------------------------------
# data-dir watcher
# ----------------------------------------------------------------------
class TestWatch:
    def test_scan_filters(self, tmp_path):
        d = str(tmp_path)
        _write_chunk(d, "a.csv", 3, 0)
        _write_chunk(d, ".hidden.csv", 3, 1)
        with open(os.path.join(d, "notes.md"), "w") as f:
            f.write("not data\n")
        os.makedirs(os.path.join(d, "sub.csv"))
        assert list(watch.scan(d)) == ["a.csv"]

    def test_append_changes_touch_does_not(self, tmp_path):
        d = str(tmp_path)
        _write_chunk(d, "a.csv", 5, 0)
        prev = watch.scan(d)
        # a bare touch (mtime only) must NOT retrain
        os.utime(os.path.join(d, "a.csv"))
        assert watch.changed(prev, watch.scan(d)) == []
        # an append moves size + tail CRC -> retrain
        _write_chunk(d, "a.csv", 5, 1)
        assert watch.changed(prev, watch.scan(d)) == ["a.csv"]
        # a new file is a change too
        _write_chunk(d, "b.csv", 2, 2)
        assert "b.csv" in watch.changed(prev, watch.scan(d))

    def test_debounce(self, tmp_path):
        d = str(tmp_path)
        _write_chunk(d, "a.csv", 3, 0, backdate=False)
        cur = watch.scan(d)
        assert not watch.stable(cur, debounce_s=30.0)
        assert watch.stable(cur, debounce_s=0.0)
        t = time.time() - 60
        os.utime(os.path.join(d, "a.csv"), (t, t))
        assert watch.stable(watch.scan(d), debounce_s=30.0)

    def test_combined_fingerprint_tracks_content(self, tmp_path):
        d = str(tmp_path)
        _write_chunk(d, "a.csv", 4, 0)
        fp1 = watch.combined_fingerprint(watch.scan(d))
        assert fp1 == watch.combined_fingerprint(watch.scan(d))
        _write_chunk(d, "a.csv", 1, 9)
        assert watch.combined_fingerprint(watch.scan(d)) != fp1


# ----------------------------------------------------------------------
# registry lifecycle (factory satellites)
# ----------------------------------------------------------------------
class TestRegistryLifecycle:
    def test_publish_dedupe_key(self, tiny_booster, tmp_path):
        bst, _ = tiny_booster
        art = PredictorArtifact.from_booster(bst)
        reg = ModelRegistry(str(tmp_path / "reg"))
        v1 = reg.publish(art, activate=False, dedupe_key="r000001-abc")
        # the replayed publish of a killed run gets the SAME version back
        v2 = reg.publish(_scaled(art, 1.1), activate=False,
                         dedupe_key="r000001-abc")
        assert v1 == v2 == 1
        assert [m["version"] for m in reg.list_models()] == [1]
        # a different run id is a genuinely new publish
        assert reg.publish(art, activate=False, dedupe_key="r2") == 2

    def test_canary_pin_and_clear(self, tiny_booster, tmp_path):
        bst, _ = tiny_booster
        art = PredictorArtifact.from_booster(bst)
        reg = ModelRegistry(str(tmp_path / "reg"))
        reg.publish(art)
        reg.publish(_scaled(art, 1.1), activate=False)
        assert reg.canary_version() is None
        reg.set_canary(2)
        assert reg.canary_version() == 2
        assert [m["canary"] for m in reg.list_models()] == [False, True]
        reg.clear_canary()
        assert reg.canary_version() is None
        with pytest.raises(LightGBMError, match="unknown version"):
            reg.set_canary(99)

    def test_quarantine_records_reason(self, tiny_booster, tmp_path):
        bst, _ = tiny_booster
        art = PredictorArtifact.from_booster(bst)
        reg = ModelRegistry(str(tmp_path / "reg"))
        reg.publish(art)
        reg.publish(_scaled(art, 1.1), activate=False)
        reg.set_canary(2)
        reg.quarantine(2, "canary error rate 0.5 > 0.02")
        assert reg.quarantined() == {2: "canary error rate 0.5 > 0.02"}
        # quarantining the canary clears the canary pin
        assert reg.canary_version() is None
        rows = {m["version"]: m for m in reg.list_models()}
        assert rows[2]["quarantined"] == "canary error rate 0.5 > 0.02"
        assert rows[1]["quarantined"] is None

    def test_gc_protects_lifecycle_versions(self, tiny_booster, tmp_path):
        """Retention must never collect the active version, the pinned
        canary, or the most recent quarantined version (the rollback
        investigation's evidence)."""
        bst, _ = tiny_booster
        art = PredictorArtifact.from_booster(bst)
        reg = ModelRegistry(str(tmp_path / "reg"), keep_last=2)
        reg.publish(art)                                 # v1 (active)
        reg.publish(_scaled(art, 1.1), activate=False)   # v2 -> canary
        reg.set_canary(2)
        reg.publish(_scaled(art, 1.2), activate=False)   # v3 -> quarantined
        reg.quarantine(3, "slo miss")
        reg.publish(_scaled(art, 1.3), activate=False)   # v4
        reg.publish(_scaled(art, 1.4), activate=False)   # v5
        versions = [m["version"] for m in reg.list_models()]
        assert versions == [1, 2, 3, 4, 5]  # all protected or in-window
        # once the canary pin is lifted, v2 becomes collectible
        reg.clear_canary()
        reg.publish(_scaled(art, 1.5), activate=False)   # v6 triggers GC
        versions = [m["version"] for m in reg.list_models()]
        assert 2 not in versions
        assert 1 in versions and 3 in versions  # active + quarantined stay


# ----------------------------------------------------------------------
# per-version serving metrics (satellite 2)
# ----------------------------------------------------------------------
class TestPerVersionMetrics:
    @pytest.fixture()
    def server(self, tiny_booster, tmp_path):
        from lightgbm_tpu.serve.server import make_server

        bst, X = tiny_booster
        model = PredictorArtifact.from_booster(bst).save(str(tmp_path / "m"))
        srv = make_server(model, port=0, warmup_max_rows=64,
                          max_delay_ms=1.0,
                          registry_dir=str(tmp_path / "reg"),
                          registry_poll_ms=50.0)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        yield srv, bst, X
        srv.shutdown()
        srv.server_close()

    def _post(self, port, rows, query=""):
        body = "\n".join(json.dumps(list(map(float, r)))
                         for r in rows).encode()
        return urllib.request.urlopen(
            f"http://127.0.0.1:{port}/predict{query}", data=body,
            timeout=30)

    def _metric_value(self, port, line_prefix):
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30).read().decode()
        for line in text.splitlines():
            if line.startswith(line_prefix):
                return float(line.rsplit(" ", 1)[1]), text
        return None, text

    def test_stats_metrics_parity(self, server):
        srv, bst, X = server
        port = srv.server_address[1]
        for _ in range(3):
            assert self._post(port, X[:2]).status == 200
        st = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=30).read())
        pv = st["per_version"]["1"]
        assert pv["requests"] >= 3 and pv["errors"] == 0
        assert pv["latency_p99_ms"] > 0
        # /metrics must tell the same story, labeled by model_version
        val, text = self._metric_value(
            port,
            'lightgbm_tpu_serve_version_requests_total{model_version="1"}')
        assert val == pv["requests"]
        assert ('lightgbm_tpu_serve_version_latency_seconds_bucket'
                '{model_version="1",le="') in text
        assert ('lightgbm_tpu_serve_version_latency_seconds_count'
                '{model_version="1"}') in text

    def test_swap_prunes_old_version_labels(self, server):
        srv, bst, X = server
        port = srv.server_address[1]
        self._post(port, X[:2])
        reg = ModelRegistry(srv.registry.dir)
        v = reg.publish(_scaled(PredictorArtifact.from_booster(bst), 1.5))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if getattr(srv.predictor, "version", None) == v:
                break
            time.sleep(0.05)
        assert srv.predictor.version == v
        self._post(port, X[:2])
        st = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=30).read())
        # bounded cardinality: only the live version's series remain
        assert list(st["per_version"]) == [str(v)]
        val, text = self._metric_value(
            port,
            f'lightgbm_tpu_serve_version_requests_total'
            f'{{model_version="{v}"}}')
        assert val >= 1
        assert 'model_version="1"' not in text

    def test_pin_version_never_swaps(self, tiny_booster, tmp_path):
        from lightgbm_tpu.serve.server import make_server

        bst, X = tiny_booster
        art = PredictorArtifact.from_booster(bst)
        reg = ModelRegistry(str(tmp_path / "reg"))
        reg.publish(art)                      # v1
        reg.publish(_scaled(art, 2.0))        # v2 active
        srv = make_server(port=0, warmup_max_rows=64, max_delay_ms=1.0,
                          registry_dir=str(tmp_path / "reg"),
                          registry_poll_ms=50.0, pin_version=1)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            port = srv.server_address[1]
            r = self._post(port, X[:3], query="?model_version=1")
            assert r.headers["X-Model-Version"] == "1"
            lines = [json.loads(l) for l in r.read().decode().splitlines()]
            assert all(l["model_version"] == 1 for l in lines)
            assert np.allclose([l["prediction"] for l in lines],
                               PackedPredictor(art).predict(X[:3]))
            # the active version moved on; the pinned replica must not
            reg.activate(1)
            reg.activate(2)
            time.sleep(0.3)  # several poll periods
            st = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/stats", timeout=30).read())
            assert st["model_version"] == 1
            assert st["pin_version"] == 1
        finally:
            srv.shutdown()
            srv.server_close()


# ----------------------------------------------------------------------
# init_model schema-drift guard (satellite 6)
# ----------------------------------------------------------------------
class TestInitModelGuard:
    def test_feature_count_mismatch_is_actionable(self, tiny_booster,
                                                  tmp_path):
        bst, _ = tiny_booster
        model = str(tmp_path / "prev.txt")
        bst.save_model(model)
        rng = np.random.RandomState(11)
        X = rng.randn(200, N_FEATURES + 3)  # drifted schema: wider data
        y = (X[:, 0] > 0).astype(np.float32)
        with pytest.raises(LightGBMError,
                           match=r"trained on 8 features.*has 11"):
            lgb.train(dict(TRAIN_PARAMS),
                      lgb.Dataset(X, label=y,
                                  params={"min_data_in_leaf": 5}),
                      num_boost_round=2, init_model=model,
                      verbose_eval=False)


# ----------------------------------------------------------------------
# in-process factory cycles
# ----------------------------------------------------------------------
@pytest.mark.factory
class TestFactoryCycle:
    def test_cold_then_warm_promote(self, tmp_path):
        sup = _supervisor(str(tmp_path))
        assert sup.run_cycle() is None  # empty data dir -> nothing to do
        _write_chunk(sup.data_dir, "chunk-000.csv", 300, 0)
        v1 = sup.run_cycle()
        assert v1["verdict"] == "promoted" and v1["version"] == 1
        assert v1["warm_start"] is False
        assert v1["detail"]["eval"]["baseline"] is None
        assert sup.registry.active_version() == 1
        assert sup.run_cycle() is None  # unchanged data -> no run
        # appended rows + a new chunk trigger a WARM-started retrain
        _write_chunk(sup.data_dir, "chunk-000.csv", 100, 1)
        _write_chunk(sup.data_dir, "chunk-001.csv", 200, 2)
        v2 = sup.run_cycle()
        assert v2["verdict"] == "promoted" and v2["version"] == 2
        assert v2["warm_start"] is True
        assert v2["detail"]["eval"]["baseline"] is not None
        assert sup.registry.active_version() == 2
        # durable state: a fresh load sees the same world
        back = FactoryState.load(sup.workdir)
        assert back.run is None
        assert [h["verdict"] for h in back.history] == ["promoted"] * 2
        assert back.current["version"] == 2
        assert os.path.exists(back.current["model_path"])
        assert set(back.ingested) == {"chunk-000.csv", "chunk-001.csv"}
        # run scratch space is retired with the run
        assert glob.glob(os.path.join(sup.workdir, "r0*")) == []

    def test_debounce_defers_fresh_writes(self, tmp_path):
        sup = _supervisor(str(tmp_path), debounce_ms=60000.0)
        _write_chunk(sup.data_dir, "chunk-000.csv", 50, 0, backdate=False)
        assert sup.run_cycle() is None  # writer might still be appending
        assert FactoryState.load(sup.workdir).run is None


@pytest.mark.factory
class TestFactoryCrashReplay:
    def test_kill_after_publish_never_double_publishes(self, tmp_path,
                                                       monkeypatch):
        """A crash between publish and the verdict replays the run; the
        dedupe key hands the SAME version back and exactly one model
        enters the registry."""
        sup = _supervisor(str(tmp_path))
        _write_chunk(sup.data_dir, "chunk-000.csv", 300, 0)
        monkeypatch.setattr(
            sup, "_eval_gate",
            lambda *a, **k: (_ for _ in ()).throw(RuntimeError("killed")))
        with pytest.raises(RuntimeError, match="killed"):
            sup.run_cycle()
        # the candidate was published (inactive) and the run is durable
        assert sup.registry.latest_version() == 1
        assert sup.registry.active_version() is None
        mid = FactoryState.load(sup.workdir)
        assert mid.run is not None
        assert mid.run["candidate_version"] == 1
        # "restart": a fresh supervisor re-enters and finishes the run
        sup2 = FactorySupervisor(sup.data_dir, sup.workdir,
                                 sup.registry_dir, params=dict(TRAIN_PARAMS),
                                 **FACTORY_KNOBS)
        verdict = sup2.run_cycle()
        assert verdict["verdict"] == "promoted" and verdict["version"] == 1
        assert verdict["run_id"] == mid.run["run_id"]
        assert [m["version"] for m in sup2.registry.list_models()] == [1]
        assert sup2.registry.active_version() == 1
        assert FactoryState.load(sup.workdir).run is None

    def test_eval_gate_rollback_records_verdict(self, tmp_path,
                                                monkeypatch):
        """A regressed candidate is quarantined WITH the reason, the
        active version does not move, and the next retrain still warm
        starts from the last good model."""
        sup = _supervisor(str(tmp_path))
        _write_chunk(sup.data_dir, "chunk-000.csv", 300, 0)
        assert sup.run_cycle()["verdict"] == "promoted"
        _write_chunk(sup.data_dir, "chunk-001.csv", 150, 1)

        real = sup._eval_metric

        def scripted(model_path, data_path):
            if os.sep + "models" + os.sep in model_path:
                return {"name": "binary_error", "value": 0.02}  # baseline
            return {"name": "binary_error", "value": 0.40}      # candidate
        monkeypatch.setattr(sup, "_eval_metric", scripted)
        verdict = sup.run_cycle()
        monkeypatch.setattr(sup, "_eval_metric", real)
        assert verdict["verdict"] == "rolled_back"
        assert "regressed" in verdict["reason"]
        assert sup.registry.active_version() == 1  # rollback held the fort
        assert sup.registry.quarantined() == {2: verdict["reason"]}
        hist = FactoryState.load(sup.workdir).history
        assert [h["verdict"] for h in hist] == ["promoted", "rolled_back"]
        assert hist[-1]["detail"]["eval"]["reason"] == verdict["reason"]
        # the factory keeps going: the next change retrains from v1
        _write_chunk(sup.data_dir, "chunk-002.csv", 150, 2)
        v3 = sup.run_cycle()
        assert v3["verdict"] == "promoted" and v3["version"] == 3
        assert v3["warm_start"] is True
        assert sup.registry.active_version() == 3


# ----------------------------------------------------------------------
# subprocess SIGKILL mid-retrain (satellite 3)
# ----------------------------------------------------------------------
def _factory_cmd(data_dir, workdir, reg_dir, rounds):
    return [sys.executable, "-m", "lightgbm_tpu", "factory",
            f"data={data_dir}", f"workdir={workdir}", f"registry={reg_dir}",
            "max_cycles=1", "poll_ms=50", "debounce_ms=0",
            f"num_boost_round={rounds}", "checkpoint_freq=1",
            "canary_fraction=0", "objective=binary", "num_leaves=15",
            "min_data_in_leaf=5"]  # default verbosity: the resume
    # assertion greps the "Checkpoint saved at iteration" info lines


@pytest.mark.factory
@pytest.mark.faultinject
class TestFactorySigkill:
    def test_sigkill_mid_retrain_resumes_and_publishes_once(self, tmp_path):
        data_dir = str(tmp_path / "data")
        workdir = str(tmp_path / "work")
        reg_dir = str(tmp_path / "reg")
        os.makedirs(data_dir)
        _write_chunk(data_dir, "chunk-000.csv", 2000, 0)
        rounds = 60
        cmd = _factory_cmd(data_dir, workdir, reg_dir, rounds)
        env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
            "JAX_PLATFORMS", "cpu"))
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        try:
            # wait for the retrain to be demonstrably mid-flight (>= 2
            # durable checkpoints), then SIGKILL with rounds to spare
            deadline = time.monotonic() + 240
            ckpts = []
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    pytest.fail("factory finished before the kill landed — "
                                "raise num_boost_round")
                ckpts = glob.glob(
                    os.path.join(workdir, "r*", "ckpt", "ckpt_*.npz"))
                if len(ckpts) >= 2:
                    break
                time.sleep(0.01)
            assert len(ckpts) >= 2, "no checkpoints before the deadline"
            proc.send_signal(signal.SIGKILL)
            assert proc.wait(timeout=30) == -signal.SIGKILL
        finally:
            if proc.poll() is None:
                proc.kill()
        # killed mid-retrain: run record durable, nothing published
        mid = FactoryState.load(workdir)
        assert mid.run is not None
        run_id = mid.run["run_id"]
        assert ModelRegistry(reg_dir).active_version() is None
        # restart: the SAME run resumes from its checkpoint and finishes
        out = subprocess.run(cmd, env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, timeout=420)
        text = out.stdout.decode(errors="replace")
        assert out.returncode == 0, text[-2000:]
        saves = [int(m) for m in re.findall(
            r"Checkpoint saved at iteration (\d+)", text)]
        assert saves, "restart never checkpointed"
        assert saves[0] > 1, \
            f"restart checkpointed from iteration {saves[0]} — it " \
            "retrained from scratch instead of resuming"
        reg = ModelRegistry(reg_dir)
        assert [m["version"] for m in reg.list_models()] == [1]
        assert reg.active_version() == 1
        done = FactoryState.load(workdir)
        assert done.run is None
        assert [h["run_id"] for h in done.history] == [run_id]
        assert done.history[0]["verdict"] == "promoted"
        booster = reg.load(1)
        assert booster.meta["num_trees"] == rounds


# ----------------------------------------------------------------------
# e2e: live fleet + closed-loop traffic + canary promote / rollback
# ----------------------------------------------------------------------
def _traffic(port, rows, n_threads=2):
    """Closed-loop /predict traffic through the proxy.  Every reply must
    be 200 and stamped with exactly one version; (version, predictions)
    pairs are recorded for post-hoc verification against the registry's
    artifacts."""
    body = "\n".join(json.dumps(list(map(float, r))) for r in rows).encode()
    stop = threading.Event()
    lock = threading.Lock()
    stats = {"n": 0, "errors": [], "replies": []}

    def worker():
        while not stop.is_set():
            try:
                r = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/predict?model_version=1",
                    data=body, timeout=60)
                lines = [json.loads(l)
                         for l in r.read().decode().splitlines()]
            except Exception as e:
                with lock:
                    stats["errors"].append(f"{type(e).__name__}: {e}")
                continue
            vers = {l["model_version"] for l in lines}
            with lock:
                stats["n"] += 1
                if len(vers) != 1:
                    stats["errors"].append(f"reply mixed versions {vers}")
                else:
                    stats["replies"].append(
                        (vers.pop(), [l["prediction"] for l in lines]))

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(n_threads)]
    for t in threads:
        t.start()
    return stop, threads, stats


@pytest.mark.factory
@pytest.mark.fleet
class TestFactoryFleetE2E:
    def test_append_canary_promote_then_blind_rollback(self, tmp_path):
        """The whole loop against a LIVE fleet: data append -> warm
        retrain -> inactive publish -> canary slice -> auto-promote,
        with zero dropped and zero mis-versioned responses; then a
        second run whose canary sees no traffic refuses to promote
        blind, auto-rolls-back, and records the verdict."""
        tmp = str(tmp_path)
        data_dir = os.path.join(tmp, "data")
        reg_dir = os.path.join(tmp, "reg")
        os.makedirs(data_dir)
        _write_chunk(data_dir, "chunk-000.csv", 300, 0)
        # bootstrap v1 (no fleet yet, canary off)
        boot = FactorySupervisor(data_dir, os.path.join(tmp, "work"),
                                 reg_dir, params=dict(TRAIN_PARAMS),
                                 **FACTORY_KNOBS)
        assert boot.run_cycle()["verdict"] == "promoted"

        procs = spawn_replicas(2, {
            "registry": reg_dir, "warmup_max_rows": "64",
            "max_delay_ms": "1", "registry_poll_ms": "100",
        })
        proxy = None
        stop = None
        try:
            for _, port in procs:
                assert _wait_ready("127.0.0.1", port, 120.0), \
                    f"replica on port {port} never became ready"
            proxy = FleetProxy(("127.0.0.1", 0),
                               [f"127.0.0.1:{p}" for _, p in procs],
                               health_poll_s=0.2, retry_deadline_s=20.0)
            threading.Thread(target=proxy.serve_forever,
                             daemon=True).start()
            port = proxy.server_address[1]
            rng = np.random.RandomState(21)
            rows = rng.randn(2, N_FEATURES)
            stop, threads, stats = _traffic(port, rows)
            canary_before = metrics_registry.counter(
                "lightgbm_tpu_proxy_canary_requests_total").value()

            # ---- run 2: append -> warm retrain -> canary -> promote
            _write_chunk(data_dir, "chunk-000.csv", 150, 1)
            _write_chunk(data_dir, "chunk-001.csv", 150, 2)
            sup = FactorySupervisor(
                data_dir, os.path.join(tmp, "work"), reg_dir,
                params=dict(TRAIN_PARAMS), proxy=f"127.0.0.1:{port}",
                num_boost_round=5, checkpoint_freq=2, debounce_ms=0.0,
                canary_fraction=0.5, observe_s=3.0, min_requests=5)
            verdict = sup.run_cycle()
            assert verdict is not None and verdict["verdict"] == "promoted"
            assert verdict["version"] == 2 and verdict["warm_start"]
            canary_obs = verdict["detail"]["canary"]
            assert canary_obs["requests"] >= 5
            assert canary_obs["errors"] == 0
            assert sup.registry.active_version() == 2
            # the canary route really carried proxy traffic...
            assert metrics_registry.counter(
                "lightgbm_tpu_proxy_canary_requests_total").value() \
                > canary_before
            # ...and was torn down after the verdict
            assert proxy.stats()["canary"] is None
            # keep traffic flowing until the fleet serves v2
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if any(r[0] == 2 for r in stats["replies"][-20:]):
                    break
                time.sleep(0.1)

            # ---- run 3: canary sees NO traffic -> refuse to promote
            stop.set()
            for t in threads:
                t.join(timeout=60)
            _write_chunk(data_dir, "chunk-002.csv", 150, 3)
            sup3 = FactorySupervisor(
                data_dir, os.path.join(tmp, "work"), reg_dir,
                params=dict(TRAIN_PARAMS), proxy=f"127.0.0.1:{port}",
                num_boost_round=5, checkpoint_freq=2, debounce_ms=0.0,
                canary_fraction=0.5, observe_s=1.0, min_requests=1000)
            verdict3 = sup3.run_cycle()
            assert verdict3["verdict"] == "rolled_back"
            assert "refusing to promote blind" in verdict3["reason"]
            assert sup3.registry.active_version() == 2  # held the fort
            assert sup3.registry.quarantined() == {3: verdict3["reason"]}
            hist = FactoryState.load(sup3.workdir).history
            assert [h["verdict"] for h in hist] == \
                ["promoted", "promoted", "rolled_back"]

            # ---- zero dropped, zero mis-versioned, outputs bit-checked
            assert stats["errors"] == [], stats["errors"][:5]
            assert stats["n"] > 0
            seen = {v for v, _ in stats["replies"]}
            assert seen <= {1, 2}, seen
            assert 2 in seen, "promotion never reached fleet traffic"
            expected = {v: PackedPredictor(sup.registry.load(v)).predict(rows)
                        for v in seen}
            for ver, preds in stats["replies"]:
                assert np.allclose(preds, expected[ver]), \
                    f"v{ver} reply does not match v{ver} model"
        finally:
            if stop is not None:
                stop.set()
            if proxy is not None:
                proxy.shutdown()
                proxy.server_close()
            for p, _ in procs:
                p.kill()
                p.wait(timeout=30)


@pytest.mark.factory
@pytest.mark.fleet
@pytest.mark.slow
class TestFactorySustained:
    def test_repeated_appends_promote_under_traffic(self, tmp_path):
        """Sustained leg: three successive appends each drive a full
        warm-retrain -> canary -> promote cycle under continuous
        closed-loop traffic; the fleet ends on the last version with a
        clean reply ledger."""
        tmp = str(tmp_path)
        data_dir = os.path.join(tmp, "data")
        reg_dir = os.path.join(tmp, "reg")
        os.makedirs(data_dir)
        _write_chunk(data_dir, "chunk-000.csv", 300, 0)
        boot = FactorySupervisor(data_dir, os.path.join(tmp, "work"),
                                 reg_dir, params=dict(TRAIN_PARAMS),
                                 **FACTORY_KNOBS)
        assert boot.run_cycle()["verdict"] == "promoted"
        procs = spawn_replicas(2, {
            "registry": reg_dir, "warmup_max_rows": "64",
            "max_delay_ms": "1", "registry_poll_ms": "100",
        })
        proxy = None
        stop = None
        try:
            for _, port in procs:
                assert _wait_ready("127.0.0.1", port, 120.0)
            proxy = FleetProxy(("127.0.0.1", 0),
                               [f"127.0.0.1:{p}" for _, p in procs],
                               health_poll_s=0.2, retry_deadline_s=20.0)
            threading.Thread(target=proxy.serve_forever,
                             daemon=True).start()
            port = proxy.server_address[1]
            rng = np.random.RandomState(22)
            rows = rng.randn(2, N_FEATURES)
            stop, threads, stats = _traffic(port, rows, n_threads=3)
            sup = FactorySupervisor(
                data_dir, os.path.join(tmp, "work"), reg_dir,
                params=dict(TRAIN_PARAMS), proxy=f"127.0.0.1:{port}",
                num_boost_round=4, checkpoint_freq=2, debounce_ms=0.0,
                canary_fraction=0.5, observe_s=2.5, min_requests=5)
            for i in range(1, 4):
                _write_chunk(data_dir, f"chunk-{i:03d}.csv", 120, i)
                verdict = sup.run_cycle()
                assert verdict["verdict"] == "promoted", verdict
                assert verdict["version"] == 1 + i
            assert sup.registry.active_version() == 4
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if any(r[0] == 4 for r in stats["replies"][-20:]):
                    break
                time.sleep(0.1)
            stop.set()
            for t in threads:
                t.join(timeout=60)
            assert stats["errors"] == [], stats["errors"][:5]
            seen = {v for v, _ in stats["replies"]}
            assert seen <= {1, 2, 3, 4}
            assert 4 in seen, "final promotion never reached traffic"
            expected = {v: PackedPredictor(sup.registry.load(v)).predict(rows)
                        for v in seen}
            for ver, preds in stats["replies"]:
                assert np.allclose(preds, expected[ver])
        finally:
            if stop is not None:
                stop.set()
            if proxy is not None:
                proxy.shutdown()
                proxy.server_close()
            for p, _ in procs:
                p.kill()
                p.wait(timeout=30)
