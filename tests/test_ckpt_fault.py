"""Kill/resume fault injection (docs/CHECKPOINT.md acceptance matrix).

A real training subprocess (the CLI, exactly what a preemptible node
runs) is SIGKILLed or SIGTERMed once its first checkpoint lands; the
rerun auto-resumes from the latest valid checkpoint and the final model
file must be byte-identical to an uninterrupted run of the same command.

The quick smoke (one SIGKILL + one SIGTERM, gbdt+bagging) runs in
tier-1; the full multi-kill matrix over {gbdt+bagging, GOSS, DART} with
randomized kill points is marked ``slow`` (the 2-process sharded
ptrainer leg lives in test_multihost.py).
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(
    os.environ,
    JAX_PLATFORMS="cpu",
    PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
)

BASE_ARGS = [
    "task=train", "objective=binary", "num_leaves=15", "learning_rate=0.2",
    "min_data_in_leaf=20", "num_trees=60", "snapshot_freq=5", "verbose=1",
]
VARIANTS = {
    "gbdt_bagging": ["bagging_fraction=0.7", "bagging_freq=2",
                     "feature_fraction=0.8"],
    "goss": ["boosting=goss", "learning_rate=0.3", "top_rate=0.3",
             "other_rate=0.2"],
    "dart": ["boosting=dart", "drop_rate=0.4", "drop_seed=7"],
}


@pytest.fixture(scope="module")
def data_file(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("faultdata") / "fault.train")
    rng = np.random.RandomState(0)
    X = rng.randn(2500, 10)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.1 * rng.randn(2500) > 0).astype(int)
    np.savetxt(path, np.column_stack([y, X]), fmt="%.10g", delimiter="\t")
    return path


def _cmd(data_file, workdir, extra):
    model = os.path.join(workdir, "model.txt")
    return (
        [sys.executable, "-m", "lightgbm_tpu",
         f"data={data_file}", f"output_model={model}"]
        + BASE_ARGS + extra,
        model,
    )


def _run_to_completion(data_file, workdir, extra):
    os.makedirs(workdir, exist_ok=True)
    cmd, model = _cmd(data_file, workdir, extra)
    r = subprocess.run(cmd, cwd=workdir, env=ENV, capture_output=True,
                       text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert os.path.exists(model), r.stdout
    return model, r.stdout


def _wait_for_checkpoints(workdir, min_entries, proc, timeout=420):
    """Poll the CRC manifest until >= min_entries checkpoints are
    durable (a manifest entry only exists after the fsync'd rename)."""
    manifest = os.path.join(workdir, "MANIFEST.json")
    deadline = time.time() + timeout
    while time.time() < deadline:
        if proc.poll() is not None:
            return False  # child finished before we could kill it
        try:
            with open(manifest) as f:
                if len(json.load(f).get("entries", {})) >= min_entries:
                    return True
        except (OSError, ValueError):
            pass
        time.sleep(0.02)
    raise TimeoutError("no checkpoint appeared before the kill deadline")


def _kill_and_resume(data_file, workdir, extra, sig, min_entries=1):
    cmd, model = _cmd(data_file, workdir, extra)
    child = subprocess.Popen(cmd, cwd=workdir, env=ENV,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True)
    try:
        armed = _wait_for_checkpoints(workdir, min_entries, child)
    except BaseException:
        child.kill()
        child.communicate()
        raise
    if not armed:
        out, _ = child.communicate()
        pytest.fail("training finished before the kill landed:\n" + out[-2000:])
    child.send_signal(sig)
    out, _ = child.communicate(timeout=300)
    if sig == signal.SIGTERM:
        # graceful preemption: checkpoint flushed, clean exit
        assert child.returncode == 0, out[-2000:]
        assert "preempted" in out.lower(), out[-2000:]
    else:
        assert child.returncode != 0  # SIGKILL: died hard
    assert not os.path.exists(model), "killed run must not have finished"

    # resume: the same command auto-resumes from the latest checkpoint
    r = subprocess.run(cmd, cwd=workdir, env=ENV, capture_output=True,
                       text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "Resuming training from checkpoint" in r.stdout, r.stdout[-2000:]
    return model


def _model_hash(path):
    import hashlib

    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


# ----------------------------------------------------------------------
# tier-1 smoke: one SIGKILL and one SIGTERM leg
# ----------------------------------------------------------------------
@pytest.mark.faultinject
@pytest.mark.parametrize("sig", [signal.SIGKILL, signal.SIGTERM],
                         ids=["sigkill", "sigterm"])
def test_kill_resume_bit_identical_gbdt(data_file, tmp_path, sig):
    extra = VARIANTS["gbdt_bagging"]
    ref_model, _ = _run_to_completion(data_file, str(tmp_path / "ref"), extra)
    wd = str(tmp_path / "killed")
    os.makedirs(wd, exist_ok=True)
    model = _kill_and_resume(data_file, wd, extra, sig)
    assert _model_hash(model) == _model_hash(ref_model)


# ----------------------------------------------------------------------
# the full multi-kill matrix (slow): every driver, both signals,
# randomized kill points
# ----------------------------------------------------------------------
@pytest.mark.slow
@pytest.mark.faultinject
@pytest.mark.parametrize("variant", sorted(VARIANTS))
@pytest.mark.parametrize("sig", [signal.SIGKILL, signal.SIGTERM],
                         ids=["sigkill", "sigterm"])
def test_kill_matrix_bit_identical(data_file, tmp_path, variant, sig):
    extra = VARIANTS[variant]
    ref_model, _ = _run_to_completion(data_file, str(tmp_path / "ref"), extra)
    # randomized kill point: wait for 1-3 durable checkpoints (of ~12)
    rng = np.random.RandomState(
        abs(hash((variant, int(sig)))) % (2 ** 31)
    )
    min_entries = int(rng.randint(1, 4))
    wd = str(tmp_path / "killed")
    os.makedirs(wd, exist_ok=True)
    model = _kill_and_resume(data_file, wd, extra, sig,
                             min_entries=min_entries)
    assert _model_hash(model) == _model_hash(ref_model)


# ----------------------------------------------------------------------
# elastic topology matrix (docs/CHECKPOINT.md canonical layout): a
# world-4 training run is preempted (every rank SIGKILLed at the same
# iteration boundary); the canonical global-layout checkpoint then
# auto-resumes at world 4 (byte-identical), world 2 AND world 8 on real
# subprocess fleets — the old "wrong world size" refusal is gone.
# ----------------------------------------------------------------------
EWORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "elastic_worker.py")
E_ROWS, E_TREES, E_FREQ, E_KILL = 512, 6, 2, 5
E_RESUME_FROM = 4  # last freq boundary durable two iterations pre-kill


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _spawn_fleet(tag, world, ckdir, extra_env=None, per_rank_env=None):
    """Start one world-``world`` phase of the elastic worker; returns
    (out-prefix, procs) without waiting."""
    out = tag
    port = _free_port()
    base = {k: v for k, v in os.environ.items()
            if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "LIGHTGBM_TPU_FAULT",
                         "LIGHTGBM_TPU_FAULT_RANK", "LIGHTGBM_TPU_TRACE",
                         "LIGHTGBM_TPU_AUDIT")}
    base["PYTHONPATH"] = REPO + os.pathsep + base.get("PYTHONPATH", "")
    base.update(ELASTIC_ROWS=str(E_ROWS), ELASTIC_TREES=str(E_TREES),
                ELASTIC_FREQ=str(E_FREQ), ELASTIC_LEAVES="7")
    base.update(extra_env or {})
    procs = []
    for r in range(world):
        env = dict(base)
        env.update((per_rank_env or (lambda _r: {}))(r))
        procs.append(subprocess.Popen(
            [sys.executable, EWORKER, str(r), str(world), str(port), out,
             "train", ckdir],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env))
    return out, procs


def _join_fleet(procs, timeout=600):
    return [p.communicate(timeout=timeout)[0] for p in procs]


def _elastic_fleet(tag, world, ckdir, extra_env=None, per_rank_env=None,
                   timeout=600):
    """Run one fleet phase to completion; (out-prefix, procs, logs)."""
    out, procs = _spawn_fleet(tag, world, ckdir, extra_env, per_rank_env)
    return out, procs, _join_fleet(procs, timeout)


def _eresult(out, rank):
    with open(out + f".rank{rank}.json") as fh:
        return json.load(fh)


def _emodel(out, rank):
    with open(out + f".rank{rank}.txt") as fh:
        return fh.read()


def _elastic_logloss(model_str):
    """Eval-metric parity probe: global train logloss of a final model,
    on the worker's exact global dataset (same seed/recipe)."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(42)
    X = rng.integers(0, 5, size=(E_ROWS, 10)).astype(np.float32)
    w = rng.standard_normal(10)
    y = (rng.random(E_ROWS) < 1.0 / (1.0 + np.exp(-((X - 2.0) @ w * 0.35)))
         ).astype(np.float32)
    p = np.clip(lgb.Booster(model_str=model_str).predict(X), 1e-7, 1 - 1e-7)
    return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))


def _audit_trail(path):
    with open(path) as fh:
        return [json.loads(l) for l in fh if l.strip()]


@pytest.mark.faultinject
@pytest.mark.netfault
def test_elastic_topology_matrix(tmp_path):
    """The elastic acceptance proof on real subprocess fleets:

    1. reference: world-4 trains clean; all ranks emit the same model;
    2. preempt: world-4 rerun where EVERY rank SIGKILLs itself at the
       iteration-``E_KILL`` boundary (whole-job preemption) — the
       iteration-``E_RESUME_FROM`` checkpoint is durable;
    3. same-world resume: world-4 rerun is byte-identical to the
       reference (the existing bit-pinning contract);
    4. elastic resume: the SAME checkpoint resumes at world 2 and at
       world 8 — no refusal, training completes, per-rank audit trails
       are identical across ranks and continue exactly at iteration
       ``E_RESUME_FROM``, and the final models track the reference's
       train logloss."""
    import shutil

    ck = str(tmp_path / "ck")

    # phase A: the clean reference and the preempted run are independent
    # (separate ckpt dirs/ports) — overlap them so the fleets' KV-poll
    # idle gaps interleave on a small CI box
    ref_out, ref_procs = _spawn_fleet(str(tmp_path / "ref"), 4,
                                      str(tmp_path / "ck_ref"))
    kill_out, kill_procs = _spawn_fleet(
        str(tmp_path / "kill"), 4, ck,
        extra_env={"ELASTIC_KILL_ITER": str(E_KILL)})
    ref_logs = _join_fleet(ref_procs)
    kill_logs = _join_fleet(kill_procs)

    assert all(p.returncode == 0 for p in ref_procs), "\n".join(ref_logs)
    out = ref_out
    ref_model = _emodel(out, 0)
    assert all(_emodel(out, r) == ref_model for r in range(4))
    assert _eresult(out, 0)["resume_from"] is None
    ref_ll = _elastic_logloss(ref_model)

    assert all(p.returncode == -signal.SIGKILL for p in kill_procs), \
        "\n".join(l[-2000:] for l in kill_logs)
    assert not os.path.exists(kill_out + ".rank0.txt"), \
        "killed run must not have produced a model"

    # phase B: the three resumes each get their own COPY of the
    # checkpoint directory, so they are independent too — overlap them
    fleets = []
    for world in (4, 2, 8):
        ckw = str(tmp_path / f"ck_w{world}")
        shutil.copytree(ck, ckw)
        tag = str(tmp_path / f"resume{world}")
        out, procs = _spawn_fleet(
            tag, world, ckw,
            per_rank_env=lambda r, tag=tag: {
                "LIGHTGBM_TPU_AUDIT": tag + f".rank{r}.audit.jsonl"})
        fleets.append((world, tag, out, procs))

    for world, tag, out, procs in fleets:
        logs = _join_fleet(procs)
        assert all(p.returncode == 0 for p in procs), "\n".join(
            l[-2000:] for l in logs)
        assert not any("CheckpointMismatch" in l for l in logs), \
            f"world {world} resume was refused"
        trails = []
        for r in range(world):
            res = _eresult(out, r)
            assert res["resume_from"] == E_RESUME_FROM, (world, res)
            assert res["iters"] == E_TREES, (world, res)
            trails.append(_audit_trail(tag + f".rank{r}.audit.jsonl"))
        # data-parallel ranks build the SAME trees: the split-decision
        # audit trail must be identical on every rank of the new world
        assert all(t == trails[0] for t in trails[1:]), \
            f"world {world} ranks diverged after reshard"
        # ...and it must continue exactly where the checkpoint stopped:
        # tree records for the resumed iterations only, nothing earlier
        # re-trained
        tree_iters = sorted(t["it"] for t in trails[0] if t["ev"] == "tree")
        assert tree_iters == list(range(E_RESUME_FROM, E_TREES)), \
            (world, tree_iters)
        model = _emodel(out, 0)
        assert all(_emodel(out, r) == model for r in range(world))
        if world == 4:
            # same partition -> bagging state restored exactly -> the
            # continuation is byte-identical to never having died
            assert model == ref_model, "same-world resume diverged"
        else:
            # cross-world continuations are not bit-comparable (f32
            # accumulation order is world-dependent) — pin eval-metric
            # parity instead
            ll = _elastic_logloss(model)
            assert abs(ll - ref_ll) < 0.05, (world, ll, ref_ll)


@pytest.mark.slow
@pytest.mark.faultinject
def test_double_kill_resume(data_file, tmp_path):
    """Two consecutive kills (the second lands on an already-resumed
    run) still converge to the uninterrupted model."""
    extra = VARIANTS["gbdt_bagging"]
    ref_model, _ = _run_to_completion(data_file, str(tmp_path / "ref"), extra)
    wd = str(tmp_path / "killed")
    os.makedirs(wd, exist_ok=True)
    cmd, model = _cmd(data_file, wd, extra)
    for entries in (1, 3):
        child = subprocess.Popen(cmd, cwd=wd, env=ENV,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT, text=True)
        if not _wait_for_checkpoints(wd, entries, child):
            child.communicate()
            pytest.fail("finished before kill")
        child.send_signal(signal.SIGKILL)
        child.communicate()
    r = subprocess.run(cmd, cwd=wd, env=ENV, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert _model_hash(model) == _model_hash(ref_model)
