"""Kill/resume fault injection (docs/CHECKPOINT.md acceptance matrix).

A real training subprocess (the CLI, exactly what a preemptible node
runs) is SIGKILLed or SIGTERMed once its first checkpoint lands; the
rerun auto-resumes from the latest valid checkpoint and the final model
file must be byte-identical to an uninterrupted run of the same command.

The quick smoke (one SIGKILL + one SIGTERM, gbdt+bagging) runs in
tier-1; the full multi-kill matrix over {gbdt+bagging, GOSS, DART} with
randomized kill points is marked ``slow`` (the 2-process sharded
ptrainer leg lives in test_multihost.py).
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(
    os.environ,
    JAX_PLATFORMS="cpu",
    PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
)

BASE_ARGS = [
    "task=train", "objective=binary", "num_leaves=15", "learning_rate=0.2",
    "min_data_in_leaf=20", "num_trees=60", "snapshot_freq=5", "verbose=1",
]
VARIANTS = {
    "gbdt_bagging": ["bagging_fraction=0.7", "bagging_freq=2",
                     "feature_fraction=0.8"],
    "goss": ["boosting=goss", "learning_rate=0.3", "top_rate=0.3",
             "other_rate=0.2"],
    "dart": ["boosting=dart", "drop_rate=0.4", "drop_seed=7"],
}


@pytest.fixture(scope="module")
def data_file(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("faultdata") / "fault.train")
    rng = np.random.RandomState(0)
    X = rng.randn(2500, 10)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.1 * rng.randn(2500) > 0).astype(int)
    np.savetxt(path, np.column_stack([y, X]), fmt="%.10g", delimiter="\t")
    return path


def _cmd(data_file, workdir, extra):
    model = os.path.join(workdir, "model.txt")
    return (
        [sys.executable, "-m", "lightgbm_tpu",
         f"data={data_file}", f"output_model={model}"]
        + BASE_ARGS + extra,
        model,
    )


def _run_to_completion(data_file, workdir, extra):
    os.makedirs(workdir, exist_ok=True)
    cmd, model = _cmd(data_file, workdir, extra)
    r = subprocess.run(cmd, cwd=workdir, env=ENV, capture_output=True,
                       text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert os.path.exists(model), r.stdout
    return model, r.stdout


def _wait_for_checkpoints(workdir, min_entries, proc, timeout=420):
    """Poll the CRC manifest until >= min_entries checkpoints are
    durable (a manifest entry only exists after the fsync'd rename)."""
    manifest = os.path.join(workdir, "MANIFEST.json")
    deadline = time.time() + timeout
    while time.time() < deadline:
        if proc.poll() is not None:
            return False  # child finished before we could kill it
        try:
            with open(manifest) as f:
                if len(json.load(f).get("entries", {})) >= min_entries:
                    return True
        except (OSError, ValueError):
            pass
        time.sleep(0.02)
    raise TimeoutError("no checkpoint appeared before the kill deadline")


def _kill_and_resume(data_file, workdir, extra, sig, min_entries=1):
    cmd, model = _cmd(data_file, workdir, extra)
    child = subprocess.Popen(cmd, cwd=workdir, env=ENV,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True)
    try:
        armed = _wait_for_checkpoints(workdir, min_entries, child)
    except BaseException:
        child.kill()
        child.communicate()
        raise
    if not armed:
        out, _ = child.communicate()
        pytest.fail("training finished before the kill landed:\n" + out[-2000:])
    child.send_signal(sig)
    out, _ = child.communicate(timeout=300)
    if sig == signal.SIGTERM:
        # graceful preemption: checkpoint flushed, clean exit
        assert child.returncode == 0, out[-2000:]
        assert "preempted" in out.lower(), out[-2000:]
    else:
        assert child.returncode != 0  # SIGKILL: died hard
    assert not os.path.exists(model), "killed run must not have finished"

    # resume: the same command auto-resumes from the latest checkpoint
    r = subprocess.run(cmd, cwd=workdir, env=ENV, capture_output=True,
                       text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "Resuming training from checkpoint" in r.stdout, r.stdout[-2000:]
    return model


def _model_hash(path):
    import hashlib

    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


# ----------------------------------------------------------------------
# tier-1 smoke: one SIGKILL and one SIGTERM leg
# ----------------------------------------------------------------------
@pytest.mark.faultinject
@pytest.mark.parametrize("sig", [signal.SIGKILL, signal.SIGTERM],
                         ids=["sigkill", "sigterm"])
def test_kill_resume_bit_identical_gbdt(data_file, tmp_path, sig):
    extra = VARIANTS["gbdt_bagging"]
    ref_model, _ = _run_to_completion(data_file, str(tmp_path / "ref"), extra)
    wd = str(tmp_path / "killed")
    os.makedirs(wd, exist_ok=True)
    model = _kill_and_resume(data_file, wd, extra, sig)
    assert _model_hash(model) == _model_hash(ref_model)


# ----------------------------------------------------------------------
# the full multi-kill matrix (slow): every driver, both signals,
# randomized kill points
# ----------------------------------------------------------------------
@pytest.mark.slow
@pytest.mark.faultinject
@pytest.mark.parametrize("variant", sorted(VARIANTS))
@pytest.mark.parametrize("sig", [signal.SIGKILL, signal.SIGTERM],
                         ids=["sigkill", "sigterm"])
def test_kill_matrix_bit_identical(data_file, tmp_path, variant, sig):
    extra = VARIANTS[variant]
    ref_model, _ = _run_to_completion(data_file, str(tmp_path / "ref"), extra)
    # randomized kill point: wait for 1-3 durable checkpoints (of ~12)
    rng = np.random.RandomState(
        abs(hash((variant, int(sig)))) % (2 ** 31)
    )
    min_entries = int(rng.randint(1, 4))
    wd = str(tmp_path / "killed")
    os.makedirs(wd, exist_ok=True)
    model = _kill_and_resume(data_file, wd, extra, sig,
                             min_entries=min_entries)
    assert _model_hash(model) == _model_hash(ref_model)


@pytest.mark.slow
@pytest.mark.faultinject
def test_double_kill_resume(data_file, tmp_path):
    """Two consecutive kills (the second lands on an already-resumed
    run) still converge to the uninterrupted model."""
    extra = VARIANTS["gbdt_bagging"]
    ref_model, _ = _run_to_completion(data_file, str(tmp_path / "ref"), extra)
    wd = str(tmp_path / "killed")
    os.makedirs(wd, exist_ok=True)
    cmd, model = _cmd(data_file, wd, extra)
    for entries in (1, 3):
        child = subprocess.Popen(cmd, cwd=wd, env=ENV,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT, text=True)
        if not _wait_for_checkpoints(wd, entries, child):
            child.communicate()
            pytest.fail("finished before kill")
        child.send_signal(signal.SIGKILL)
        child.communicate()
    r = subprocess.run(cmd, cwd=wd, env=ENV, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert _model_hash(model) == _model_hash(ref_model)
