"""Worker for the 2-process distributed parity test (run via subprocess).

Each process: CPU platform with 4 virtual devices, rank from argv,
jax.distributed over localhost.  Grows one data-parallel tree on its
row half and (rank 0) writes the replicated split records to an npz.
"""

import os
import sys

rank = int(sys.argv[1])
port = sys.argv[2]
out = sys.argv[3]
mode = sys.argv[4] if len(sys.argv) > 4 else "grow"

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
)
os.environ["LIGHTGBM_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
os.environ["LIGHTGBM_TPU_NUM_PROCESSES"] = "2"
os.environ["LIGHTGBM_TPU_PROCESS_ID"] = str(rank)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from lightgbm_tpu.parallel.distributed import ensure_initialized  # noqa: E402

assert ensure_initialized() is True
import jax  # noqa: E402

# the axon TPU plugin ignores JAX_PLATFORMS; the config knob still wins
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402

assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8

from lightgbm_tpu.ops.grow import GrowParams  # noqa: E402
from lightgbm_tpu.ops.split import FeatureMeta, SplitHyper  # noqa: E402
from lightgbm_tpu.parallel import ShardedLearner, make_mesh  # noqa: E402

if mode == "sketchmerge":
    # streaming-ingest sketch merge across hosts: each rank folds a
    # DIFFERENT row half into its sketch bank chunk-by-chunk, then
    # merge_across_hosts allgathers + merges.  Exact (unspilled)
    # sketches must come back bit-identical to a single-process sketch
    # of the full data, on BOTH ranks.
    import pickle

    from lightgbm_tpu.data.stats import SketchCollector

    rng = np.random.default_rng(17)
    X = rng.integers(-4, 9, size=(6000, 5)).astype(np.float64)
    X[rng.random((6000, 5)) < 0.05] = np.nan
    half = X[:3000] if rank == 0 else X[3000:]
    coll = SketchCollector(categorical={4}, cap=100_000)
    for lo in range(0, 3000, 700):
        coll.update(half[lo : lo + 700])
    coll.merge_across_hosts()
    if rank == 0:
        banks = [sk.to_distinct_counts() for sk in coll.sketches]
        extras = [(sk.total_cnt, getattr(sk, "zero_cnt", -1),
                   getattr(sk, "nan_cnt", -1)) for sk in coll.sketches]
        with open(out, "wb") as fh:
            pickle.dump({"banks": banks, "extras": extras}, fh)
    print(f"rank {rank} sketchmerge done: {coll.rows_seen} rows")
    sys.exit(0)

if mode == "findbin":
    # distributed find-bin parity: both ranks hold the SAME data; the
    # feature mappers (each found by exactly one rank, then allgathered)
    # must be bit-identical to the single-process mappers
    import pickle

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset

    rng = np.random.default_rng(9)
    X = rng.standard_normal((5000, 13))
    X[:, 3] = np.round(X[:, 3] * 2)  # low-cardinality column
    y = rng.standard_normal(5000)
    cfg_params = {"max_bin": 31, "tree_learner": "data", "num_machines": 2,
                  "verbose": -1}
    cfg = Config.from_params(dict(cfg_params))
    assert cfg.is_parallel_find_bin, "expected parallel find-bin to engage"
    ds = BinnedDataset.from_raw(X, cfg, label=y)
    if rank == 0:
        states = [m.state() for m in ds.bin_mappers]
        with open(out, "wb") as fh:
            pickle.dump({"states": states, "binned": ds.binned,
                         "used": ds.used_feature_map}, fh)
    print(f"rank {rank} findbin done: {len(ds.bin_mappers)} mappers")
    sys.exit(0)

if mode == "ckptresume":
    # 2-process sharded-ptrainer checkpoint/resume: train uninterrupted
    # for 6 iters (reference hash), then a second run that "dies" at
    # iteration 3 (KeyboardInterrupt from a callback — both ranks throw
    # at the same boundary, so no collective is left half-entered), then
    # a third run that auto-resumes from the rank-0-written checkpoint.
    # The resumed model must be BIT-identical to the uninterrupted one
    # on both ranks (exercises the multihost barrier, the host-0 write,
    # the per-rank container unwrap, and the sharded perm export/import).
    import json

    os.environ["LIGHTGBM_TPU_PGROW"] = "force"
    import lightgbm_tpu as lgb
    from lightgbm_tpu.boosting.ptrainer import ShardedPartitionedTrainer
    from lightgbm_tpu.ckpt import CheckpointManager

    rng = np.random.default_rng(5)
    N, F = 3000, 6
    X = rng.integers(0, 12, size=(N, F)).astype(np.float32)
    wv = rng.standard_normal(F)
    yp = 1.0 / (1.0 + np.exp(-((X - 6) @ wv * 0.3)))
    y = (rng.random(N) < yp).astype(np.float32)
    cut = 1700
    sl = slice(0, cut) if rank == 0 else slice(cut, N)
    p = dict(objective="binary", tree_learner="data", num_machines=2,
             pre_partition=True, num_leaves=15, learning_rate=0.2,
             max_bin=31, min_data_in_leaf=20, verbose=-1)

    def mk():
        return lgb.Dataset(X[sl], label=y[sl], params=dict(p))

    ref = lgb.train(dict(p), mk(), 6, verbose_eval=False)
    assert isinstance(ref.boosting.ptrainer, ShardedPartitionedTrainer)
    ref_str = ref.model_to_string()

    ckdir = out + f".ckpt"  # shared tmp dir: both ranks see the same files

    def killer(env):
        if env.iteration + 1 == 3:
            raise KeyboardInterrupt
    killer.order = 99

    mgr = CheckpointManager(ckdir, freq=2)
    try:
        lgb.train(dict(p), mk(), 6, verbose_eval=False,
                  checkpoint_manager=mgr, callbacks=[killer])
        raise AssertionError("expected the simulated death")
    except KeyboardInterrupt:
        pass
    mgr.close()

    mgr2 = CheckpointManager(ckdir, freq=2)
    resumed = lgb.train(dict(p), mk(), 6, verbose_eval=False,
                        checkpoint_manager=mgr2)
    mgr2.close()
    match = resumed.model_to_string() == ref_str
    if rank == 0:
        with open(out, "w") as fh:
            json.dump({"match": bool(match), "trees": resumed.num_trees,
                       "model": resumed.model_to_string()}, fh)
    assert match, f"rank {rank}: resumed model diverged from uninterrupted"
    print(f"rank {rank} ckptresume done: match={match}")
    sys.exit(0)

if mode == "ptrainer":
    # fused data-parallel trainer (ShardedPartitionedTrainer) across two
    # processes: each rank holds a DIFFERENT row half (pre_partition);
    # integer-valued features make the distributed find-bin mappers
    # bit-identical to single-process full-data mappers, so the test can
    # assert tree-for-tree parity against the serial fused trainer.
    os.environ["LIGHTGBM_TPU_PGROW"] = "force"
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(5)
    N, F = 3000, 6
    X = rng.integers(0, 12, size=(N, F)).astype(np.float32)
    wv = rng.standard_normal(F)
    yp = 1.0 / (1.0 + np.exp(-((X - 6) @ wv * 0.3)))
    y = (rng.random(N) < yp).astype(np.float32)
    cut = 1700  # unequal halves exercise the shard-padding branches
    sl = slice(0, cut) if rank == 0 else slice(cut, N)
    p = dict(objective="binary", tree_learner="data", num_machines=2,
             pre_partition=True, num_leaves=15, learning_rate=0.2,
             max_bin=31, min_data_in_leaf=20, verbose=-1)
    ds = lgb.Dataset(X[sl], label=y[sl], params=dict(p))
    bst = lgb.train(p, ds, 4, verbose_eval=False)
    from lightgbm_tpu.boosting.ptrainer import ShardedPartitionedTrainer

    assert isinstance(bst.boosting.ptrainer, ShardedPartitionedTrainer), (
        type(bst.boosting.ptrainer)
    )
    if rank == 0:
        with open(out, "w") as fh:
            fh.write(bst.model_to_string())
    print(f"rank {rank} ptrainer done: {bst.num_trees} trees")
    sys.exit(0)

# identical synthetic dataset on both ranks; each passes its own half
rng = np.random.default_rng(42)
N, F, B = 4096, 6, 16
bins = rng.integers(0, B, size=(N, F), dtype=np.uint8)
grad = rng.standard_normal(N).astype(np.float32)
hess = np.abs(rng.standard_normal(N)).astype(np.float32) + 0.1
# deliberately UNEQUAL shards: exercises the pad-to-global-max path
cut = 2200
sl = slice(0, cut) if rank == 0 else slice(cut, N)
half = sl.stop - sl.start

meta = FeatureMeta(
    num_bins=jnp.full((F,), B, jnp.int32),
    default_bin=jnp.zeros((F,), jnp.int32),
    is_categorical=jnp.zeros((F,), bool),
)
hyper = SplitHyper(
    lambda_l1=jnp.float32(0.0), lambda_l2=jnp.float32(0.01),
    min_data_in_leaf=jnp.float32(20), min_sum_hessian_in_leaf=jnp.float32(1e-3),
    min_gain_to_split=jnp.float32(0.0),
)
params = GrowParams(num_leaves=15, num_bins=B)
learner = ShardedLearner("data", make_mesh(), params)
gr = learner.grow(
    jnp.asarray(bins[sl]), jnp.asarray(grad[sl]), jnp.asarray(hess[sl]),
    jnp.ones((half,), jnp.float32), jnp.ones((F,), jnp.float32), meta, hyper,
)
ns = int(gr.num_splits)
if rank == 0:
    np.savez(
        out,
        num_splits=ns,
        rec_feat=np.asarray(gr.rec_feat[:ns]),
        rec_thr=np.asarray(gr.rec_thr[:ns]),
        rec_leaf=np.asarray(gr.rec_leaf[:ns]),
        rec_lval=np.asarray(gr.rec_lval[:ns]),
        leaf_id_local=np.asarray(gr.leaf_id),
    )
print(f"rank {rank} done: {ns} splits")
