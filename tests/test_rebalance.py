"""Straggler-aware shard rebalancing (parallel/shardplan.py,
docs/ROBUSTNESS.md).

Unit legs pin the pure controller policy — EWMA trigger at exactly
``rebalance_patience``, the ``rebalance_max_move_frac`` clamp,
heartbeat-staleness suppression, largest-remainder conservation — which
must be deterministic because every rank runs it independently on the
identical allgathered table and the plans have to agree.

The integration leg is a REAL 2-rank subprocess run with an injected
per-collective delay on rank 0 (``delay:ms:after:N`` +
``LIGHTGBM_TPU_FAULT_RANK``): the controller must fire, move rows off
the slow rank through the canonical gather/reshard exchange, keep the
data-parallel ranks bit-identical, and leave ``rebalance.plan`` events
that ``report merge`` renders with the rows-owned / barrier-wait-share
trend (docs/OBSERVABILITY.md).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from lightgbm_tpu.parallel.shardplan import (RebalanceController, ShardPlan,
                                             _apply_floor, _largest_remainder)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EWORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "elastic_worker.py")


# ----------------------------------------------------------------------
# ShardPlan
# ----------------------------------------------------------------------
def test_shard_plan_ranges():
    p = ShardPlan.from_counts([300, 500, 200])
    assert p.world == 3 and p.total == 1000
    assert p.starts == (0, 300, 800)
    assert p.rank_range(0) == (0, 300)
    assert p.rank_range(1) == (300, 800)
    assert p.rank_range(2) == (800, 1000)


def test_shard_plan_rejects_bad_counts():
    with pytest.raises(ValueError):
        ShardPlan(())
    with pytest.raises(ValueError):
        ShardPlan((100, -1))


def test_largest_remainder_conserves_total():
    for shares, total in [([333.4, 333.3, 333.3], 1000),
                          ([0.5, 0.5], 7), ([10.9, 0.1], 11)]:
        out = _largest_remainder(shares, total)
        assert sum(out) == total
        assert all(c >= 0 for c in out)


def test_apply_floor_takes_from_largest():
    out = _apply_floor([0, 990, 10], 32, 1000)
    assert sum(out) == 1000
    assert all(c >= 32 for c in out)
    assert out[1] == max(out)


# ----------------------------------------------------------------------
# RebalanceController policy
# ----------------------------------------------------------------------
def _steady(ctl, plan, compute, n):
    fired = []
    for _ in range(n):
        fired.append(ctl.observe(plan, compute))
    return fired


def test_controller_fires_at_exactly_patience():
    ctl = RebalanceController(threshold=1.5, patience=3, max_move_frac=0.25)
    plan = ShardPlan.from_counts([600, 600])
    fired = _steady(ctl, plan, [4.0, 1.0], 5)
    assert fired[0] is None and fired[1] is None  # hot=1, hot=2
    assert fired[2] is not None                   # hot=3 == patience
    new = fired[2]
    assert new.total == 1200 and new.world == 2
    assert new.counts[0] < 600 < new.counts[1]
    # max_move_frac=0.25 bounds the displaced rows to 300
    assert 600 - new.counts[0] <= 300


def test_controller_quiet_fleet_never_fires():
    ctl = RebalanceController(threshold=1.5, patience=3, max_move_frac=0.25)
    plan = ShardPlan.from_counts([512, 512])
    assert all(f is None for f in _steady(ctl, plan, [1.0, 1.1], 10))


def test_controller_transient_spike_resets_patience():
    ctl = RebalanceController(threshold=1.5, patience=3, max_move_frac=0.25)
    plan = ShardPlan.from_counts([512, 512])
    assert ctl.observe(plan, [4.0, 1.0]) is None   # hot=1
    # one-iteration blip (GC pause, page-cache miss) clears: the EWMA
    # decays back under threshold before patience is reached and the
    # hot counter resets — no rows move for transients
    for _ in range(8):
        assert ctl.observe(plan, [1.0, 1.0]) is None


def test_controller_stale_heartbeat_suppresses_move():
    ctl = RebalanceController(threshold=1.5, patience=3, max_move_frac=0.25,
                              stale_s=10.0)
    plan = ShardPlan.from_counts([600, 600])
    for _ in range(6):
        # persistent straggler, but a peer heartbeat is stale: the rank
        # may be dying, not merely slow — never move rows while the
        # failure detector might fire
        assert ctl.observe(plan, [4.0, 1.0], hb_ages=[0.1, 20.0]) is None


def test_controller_deterministic_across_replicas():
    """Two controllers fed the identical table must emit the identical
    plan — ranks never exchange plans, only measurements."""
    plans = []
    for _ in range(2):
        ctl = RebalanceController(threshold=1.5, patience=3,
                                  max_move_frac=0.25)
        plan = ShardPlan.from_counts([700, 500, 600])
        out = _steady(ctl, plan, [3.0, 1.0, 1.2], 6)
        plans.append([p.counts for p in out if p is not None])
    assert plans[0] == plans[1] and plans[0]


def test_rebalance_off_by_default_and_single_process_skips():
    """rebalance=False is the default (exact pre-PR behavior: the
    controller never runs, zero extra collectives); arming it on a
    single-process run downgrades to a warning skip."""
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(3)
    X = rng.randint(0, 8, size=(400, 5)).astype(np.float32)
    y = (X[:, 0] > 3).astype(np.float32)
    p = dict(objective="binary", num_leaves=7, min_data_in_leaf=20,
             verbose=-1)
    bst = lgb.train(dict(p), lgb.Dataset(X, label=y, params=dict(p)), 3,
                    verbose_eval=False)
    assert getattr(bst.boosting, "_rebalance", None) is None
    p2 = dict(p, rebalance=True)
    bst2 = lgb.train(dict(p2), lgb.Dataset(X, label=y, params=dict(p2)), 3,
                     verbose_eval=False)
    assert getattr(bst2.boosting, "_rebalance", None) is None
    assert bst2.num_trees == 3


# ----------------------------------------------------------------------
# integration: real 2-rank run, injected straggler, rebalance ON
# ----------------------------------------------------------------------
def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.mark.faultinject
@pytest.mark.netfault
def test_rebalance_moves_rows_off_injected_straggler(tmp_path):
    """Rank 0 of 2 sleeps 40 ms at every hardened collective from the
    5th on (the new ``delay:ms:after:N`` form, scaled by the rank's
    row-count ratio).  The controller must detect the persistent
    straggler, shift rows to rank 1 at an iteration boundary, finish
    training with both ranks bit-identical, and leave ``rebalance.plan``
    trace events that ``report merge`` summarizes.

    The delay is 40 ms (not the historical 10 ms) so the injected
    straggle dominates scheduler noise on a loaded CI machine — at
    10 ms, OS jitter occasionally swamped the EWMA signal and the
    controller (correctly) never fired, flaking the assertion that
    rows moved."""
    out = str(tmp_path / "rb")
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "LIGHTGBM_TPU_FAULT",
                        "LIGHTGBM_TPU_FAULT_RANK", "LIGHTGBM_TPU_TRACE")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(ELASTIC_ROWS="512", ELASTIC_TREES="12", ELASTIC_FREQ="6",
               ELASTIC_REBALANCE="1",
               LIGHTGBM_TPU_FAULT="delay:40:after:5",
               LIGHTGBM_TPU_FAULT_RANK="0")
    procs = []
    for r in range(2):
        renv = dict(env)
        renv["LIGHTGBM_TPU_TRACE"] = out + f".rank{r}.trace.jsonl"
        procs.append(subprocess.Popen(
            [sys.executable, EWORKER, str(r), "2", str(port), out, "train",
             str(tmp_path / "ck")],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=renv))
    logs = [p.communicate(timeout=420)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), "\n".join(
        l[-2000:] for l in logs)

    res = [json.load(open(out + f".rank{r}.json")) for r in range(2)]
    counts = res[0]["final_counts"]
    assert counts == res[1]["final_counts"], res
    assert counts is not None and sum(counts) == 512, res
    # rows moved OFF the slow rank
    assert counts[0] < 256 < counts[1], res
    assert res[0]["rows_end"] == counts[0], res
    assert res[1]["rows_end"] == counts[1], res
    # data-parallel ranks stay bit-identical through the move
    models = [open(out + f".rank{r}.txt").read() for r in range(2)]
    assert models[0] == models[1], "ranks diverged after rebalance"

    # report merge (satellite: obs/report.py) — the rebalance section
    from lightgbm_tpu.obs import report

    by_rank = report.load_rank_traces(
        [out + f".rank{r}.trace.jsonl" for r in range(2)])
    m = report.merge_summary(by_rank)
    reb = m.get("rebalance")
    assert reb, "merge_summary carries no rebalance events"
    assert reb[0]["rows_before"] == [256, 256], reb
    assert reb[-1]["rows_after"] == counts, reb
    assert reb[0]["wait_share_before"] is not None, reb
    rendered = report.render_merge(m)
    assert "rebalance" in rendered and "->" in rendered, rendered


# ----------------------------------------------------------------------
# row-block wire (framed raw-numpy bytes, no pickle — docs/ROBUSTNESS.md)
# ----------------------------------------------------------------------
def _wire_example():
    from lightgbm_tpu.parallel.shardplan import _pack_row_wire
    out = {
        (5, 9): {"bins": np.arange(8, dtype=np.int8).reshape(4, 2),
                 "label": np.array([0.0, 1.0, 1.0, 0.0], np.float32)},
        (20, 22): {"bins": np.array([[7, 7]], np.int8).repeat(2, 0),
                   "label": np.array([1.0, 0.5], np.float32)},
    }
    return out, _pack_row_wire(out)


# the exact frame for _wire_example(): magic, little-endian headers,
# sorted spans/names, C-order payloads, CRC32 per array.  Pinned so wire
# compatibility breaks loudly (mixed-version fleets exchange this blob).
_WIRE_PIN = (
    "5242310002000000050000000000000009000000000000000200000004000300"
    "000262696e737c69310400000000000000020000000000000008000000000000"
    "009f68aa8800010203040506070500030000016c6162656c3c66340400000000"
    "0000001000000000000000d876f7c6000000000000803f0000803f0000000014"
    "0000000000000016000000000000000200000004000300000262696e737c6931"
    "02000000000000000200000000000000040000000000000044f2f96807070707"
    "0500030000016c6162656c3c663402000000000000000800000000000000dbc9"
    "85ee0000803f0000003f"
)


def test_row_wire_pins_exact_bytes():
    _out, blob = _wire_example()
    assert blob.hex() == _WIRE_PIN.replace("\n", "")


def test_row_wire_roundtrip_exact():
    from lightgbm_tpu.parallel.shardplan import _unpack_row_wire
    out, blob = _wire_example()
    back = _unpack_row_wire(blob)
    assert set(back) == set(out)
    for span, blocks in out.items():
        assert set(back[span]) == set(blocks)
        for name, arr in blocks.items():
            got = back[span][name]
            assert got.dtype == arr.dtype and got.shape == arr.shape
            assert got.tobytes() == arr.tobytes()


def test_row_wire_rejects_corruption():
    from lightgbm_tpu.parallel.shardplan import _unpack_row_wire
    _out, blob = _wire_example()
    with pytest.raises(ValueError, match="bad magic"):
        _unpack_row_wire(b"XX" + blob[2:])
    flipped = bytearray(blob)
    flipped[-1] ^= 0xFF  # corrupt the last payload byte
    with pytest.raises(ValueError, match="CRC"):
        _unpack_row_wire(bytes(flipped))
    with pytest.raises(ValueError, match="CRC|length"):
        _unpack_row_wire(blob[:-1])  # truncated mid-payload


# ----------------------------------------------------------------------
# query-group boundary snapping (whole-group moves for lambdarank)
# ----------------------------------------------------------------------
def test_snap_to_groups_basic():
    from lightgbm_tpu.parallel.shardplan import snap_to_groups
    gb = np.array([0, 10, 30, 60, 100], np.int64)
    # each ideal cut snaps to the nearest group boundary
    assert snap_to_groups([28], gb) == (30,)
    assert snap_to_groups([45, 80], gb) == (30, 60)
    # ties break toward the lower boundary
    assert snap_to_groups([20], gb) == (10,)


def test_snap_to_groups_collision_pushes_forward():
    from lightgbm_tpu.parallel.shardplan import snap_to_groups
    gb = np.array([0, 10, 30, 60, 100], np.int64)
    # both ideals want 30; the second cut must move past it
    assert snap_to_groups([29, 31], gb) == (30, 60)


def test_snap_to_groups_returns_none_when_groups_run_out():
    from lightgbm_tpu.parallel.shardplan import snap_to_groups
    gb = np.array([0, 50, 100], np.int64)  # one interior boundary
    assert snap_to_groups([40, 70], gb) is None  # 2 cuts, 1 boundary


def test_controller_group_bounds_moves_whole_groups():
    from lightgbm_tpu.parallel.shardplan import RebalanceController
    gb = np.array([0, 40, 80, 130, 180, 256], np.int64)
    ctl = RebalanceController(threshold=1.2, patience=1,
                              max_move_frac=0.5, group_bounds=gb)
    plan = ShardPlan.from_counts([128, 128])
    newp = None
    for _ in range(4):
        newp = ctl.observe(plan, [3.0, 1.0]) or newp
    assert newp is not None
    # the cut lands exactly on a group boundary, never mid-group
    assert newp.starts[1] in set(int(g) for g in gb)
    assert newp.counts[0] < newp.counts[1]
    assert sum(newp.counts) == 256


# ----------------------------------------------------------------------
# distributed lambdarank (group-aligned shards; whole-group rebalance)
# ----------------------------------------------------------------------
def _lambdarank_fleet(tmp_path, tag, world, extra_env=None):
    out = str(tmp_path / tag)
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "LIGHTGBM_TPU_FAULT",
                        "LIGHTGBM_TPU_FAULT_RANK", "LIGHTGBM_TPU_TRACE")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(ELASTIC_OBJECTIVE="lambdarank", ELASTIC_QUANTIZED="1",
               ELASTIC_ROWS="512", ELASTIC_TREES="10", ELASTIC_FREQ="100",
               ELASTIC_LEAVES="7")
    env.update(extra_env or {})
    procs = [subprocess.Popen(
        [sys.executable, EWORKER, str(r), str(world), str(port), out,
         "train", str(tmp_path / f"ck_{tag}")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=dict(env)) for r in range(world)]
    logs = [p.communicate(timeout=420)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), "\n".join(
        l[-2500:] for l in logs)
    res = [json.load(open(out + f".rank{r}.json")) for r in range(world)]
    models = [open(out + f".rank{r}.txt").read() for r in range(world)]
    return res, models


def test_lambdarank_two_rank_parity(tmp_path):
    """First distributed lambdarank coverage: data-parallel ranks hold
    whole query groups and train in lockstep; quantized integer
    histograms make the result byte-identical ACROSS world sizes (the
    same world-invariance the binary oocdist tests pin — serial-vs-
    distributed stays structural parity per test_multihost.py)."""
    res2, models2 = _lambdarank_fleet(tmp_path, "w2", 2)
    res4, models4 = _lambdarank_fleet(tmp_path, "w4", 4)
    assert res2[0]["trees"] == res4[0]["trees"] == 10
    # no query group is split: the shard group counts add up to the
    # global group count at every world
    n2 = sum(r["n_local_groups"] for r in res2)
    n4 = sum(r["n_local_groups"] for r in res4)
    assert n2 == n4 > 4
    assert all(r["n_local_groups"] > 0 for r in res2 + res4)
    assert models2[0] == models2[1], "data-parallel ranks diverged"
    assert len(set(models4)) == 1, "world-4 ranks diverged"
    assert models2[0] == models4[0], \
        "lambdarank bytes changed with world size"


def test_lambdarank_rebalance_moves_whole_groups(tmp_path):
    """Rebalance leg: rank 0 is an injected straggler; the controller
    must move load at QUERY-GROUP granularity — every shard edge of the
    final plan is a group boundary and no group spans ranks."""
    res, models = _lambdarank_fleet(
        tmp_path, "rb", 2,
        {"ELASTIC_REBALANCE": "1", "ELASTIC_TREES": "12",
         "LIGHTGBM_TPU_FAULT": "delay:40:after:5",
         "LIGHTGBM_TPU_FAULT_RANK": "0"})
    counts = res[0]["final_counts"]
    assert counts == res[1]["final_counts"], res
    assert counts is not None and sum(counts) == 512, res
    assert counts[0] < counts[1], "rows did not move off the straggler"
    # whole-group invariant, asserted by each rank against the global
    # cumulative group boundaries
    assert res[0]["group_aligned"] is True, res
    assert res[1]["group_aligned"] is True, res
    assert res[0]["rows_end"] == counts[0] and res[1]["rows_end"] == counts[1]
    assert models[0] == models[1], "ranks diverged after group rebalance"
