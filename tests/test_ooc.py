"""Out-of-core training tests (boosting/ooc.py, data/prefetch.py,
data/cache.py — docs/DATA.md "Out-of-core training").

The acceptance contract: with ``chunk_rows`` a ``ROW_BLOCK`` multiple
(the trainer rounds up), streamed training is **byte-identical** to the
in-memory model at any scale where the in-memory grower uses the masked
full scan (``N <= TIER_MIN``) — for gbdt and GOSS, across chunk-boundary
edge cases, and through a mid-run kill/resume.  The v2 binary cache
must refuse stale/foreign/corrupt bytes instead of training them.
"""

import json
import os
import struct
import subprocess
import sys
import zipfile

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.data.cache import (
    CACHE_FORMAT_VERSION,
    CacheReader,
    build_cache_meta,
    chunk_crcs,
    open_cache_reader,
    stale_reason,
)
from lightgbm_tpu.data.prefetch import (
    ArrayChunkSource,
    ChunkPlan,
    ChunkPrefetcher,
    PrefetchStats,
)
from lightgbm_tpu.ops.histogram import ROW_BLOCK

PARAMS = {"objective": "binary", "num_leaves": 15, "verbose": -1,
          "min_data_in_leaf": 20}


@pytest.fixture(scope="module")
def xy():
    rng = np.random.RandomState(3)
    X = rng.randn(2500, 10)
    y = (X[:, 0] + X[:, 1] * X[:, 2] + 0.2 * rng.randn(2500) > 0)
    return X, y.astype(float)


def _train(X, y, extra=None, rounds=6, **kw):
    P = dict(PARAMS)
    if extra:
        P.update(extra)
    bst = lgb.train(dict(P), lgb.Dataset(X, label=y, params=dict(P)),
                    num_boost_round=rounds, verbose_eval=False, **kw)
    return bst


# ======================================================================
# chunk plan / prefetch ring units
# ======================================================================
class TestChunkPlan:
    def test_bounds_tile_the_rows(self):
        plan = ChunkPlan(10_000, 4096)
        assert plan.bounds == [(0, 4096), (4096, 8192), (8192, 10_000)]
        assert plan.num_chunks == 3

    def test_single_chunk_when_rows_fit(self):
        plan = ChunkPlan(100, 4096)
        assert plan.bounds == [(0, 100)]

    def test_fingerprint_pins_the_grid(self):
        a, b = ChunkPlan(10_000, 4096), ChunkPlan(10_000, 8192)
        assert a.fingerprint() != b.fingerprint()
        assert a.fingerprint() == ChunkPlan(10_000, 4096).fingerprint()

    def test_rejects_nonpositive_chunk(self):
        with pytest.raises(ValueError):
            ChunkPlan(100, 0)

    def test_chunk_rows_rounds_up_to_row_block(self):
        from lightgbm_tpu.boosting.ooc import resolve_chunk_rows

        class C:
            ooc_chunk_rows = 1

        # a 1-row request degenerates to one ROW_BLOCK, never to a
        # shorter (different-summation-order) block
        assert resolve_chunk_rows(C(), 10, 1) == ROW_BLOCK
        C.ooc_chunk_rows = ROW_BLOCK + 1
        assert resolve_chunk_rows(C(), 10, 1) == 2 * ROW_BLOCK


class TestPrefetcher:
    def test_streams_every_chunk_in_order(self):
        binned = np.arange(5000 * 3, dtype=np.uint8).reshape(5000, 3)
        plan = ChunkPlan(5000, 1024)
        stats = PrefetchStats()
        pf = ChunkPrefetcher(ArrayChunkSource(binned), plan, 2, stats)
        seen = []
        for i, start, stop, dev in pf.stream():
            assert np.array_equal(np.asarray(dev), binned[start:stop])
            seen.append((i, start, stop))
        assert seen == [(i, s, e) for i, (s, e) in enumerate(plan.bounds)]
        assert stats.chunks == plan.num_chunks
        assert stats.bytes == binned.nbytes
        assert stats.passes == 1

    def test_ring_is_bounded_by_depth(self):
        binned = np.zeros((20_000, 4), np.uint8)
        plan = ChunkPlan(20_000, 1024)
        stats = PrefetchStats()
        pf = ChunkPrefetcher(ArrayChunkSource(binned), plan, 2, stats)
        import time

        for _ in pf.stream():
            time.sleep(0.002)  # slow consumer: the producer must block
        # depth-1 queued + the producer's in-hand chunk
        assert stats.peak_inflight <= 2

    def test_depth_must_be_positive(self):
        with pytest.raises(ValueError):
            ChunkPrefetcher(ArrayChunkSource(np.zeros((8, 2), np.uint8)),
                            ChunkPlan(8, 4), depth=0)

    def test_producer_error_surfaces_in_consumer(self):
        class Bad:
            num_rows, num_cols, dtype = 100, 2, np.dtype(np.uint8)

            def read(self, start, stop):
                raise IOError("disk gone")

            def describe(self):
                return "bad"

        pf = ChunkPrefetcher(Bad(), ChunkPlan(100, 64), 2)
        with pytest.raises(IOError, match="disk gone"):
            list(pf.stream())

    def test_overlap_pct_bounds(self):
        s = PrefetchStats()
        assert s.overlap_pct() == 100.0  # nothing fetched yet
        s.fetch_s, s.stall_s = 1.0, 0.25
        assert s.overlap_pct() == 75.0
        s.stall_s = 5.0
        assert s.overlap_pct() == 0.0


# ======================================================================
# v2 binary cache: round trip, random access, integrity refusals
# ======================================================================
class TestCacheV2:
    @pytest.fixture()
    def cache(self, tmp_path, xy):
        X, y = xy
        path = str(tmp_path / "train.bin")
        ds = lgb.Dataset(X, label=y, params=dict(PARAMS))
        ds.construct(dict(PARAMS)).save_binary(path)
        return path

    def test_reader_random_access_matches_memmap(self, cache):
        with CacheReader(cache) as r:
            mm = r.memmap()
            assert int(r.meta["format_version"]) == CACHE_FORMAT_VERSION
            for lo, hi in ((0, 5), (100, 612), (2400, 2500)):
                assert np.array_equal(r.read_rows(lo, hi), mm[lo:hi])
            r.verify_all()

    def test_loaded_dataset_streams_from_the_cache(self, cache, xy):
        X, y = xy
        ds = lgb.Dataset(cache, params=dict(PARAMS))
        built = ds.construct(dict(PARAMS))
        assert built.cache_path == cache
        assert isinstance(built.binned, np.memmap)

    def test_corrupt_block_refused_with_block_address(self, cache):
        r = CacheReader(cache)
        off = r.data_offset  # first byte of row 0
        r.close()
        with open(cache, "r+b") as f:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]))
        with CacheReader(cache) as r:
            with pytest.raises(IOError, match="CRC mismatch.*block 0"):
                r.read_rows(0, r.num_rows)

    def test_v1_cache_without_header_is_refused(self, tmp_path, cache):
        # strip the v2 header members -> the PR-3 format
        v1 = str(tmp_path / "v1.bin")
        with zipfile.ZipFile(cache) as zin, \
                zipfile.ZipFile(v1, "w", zipfile.ZIP_STORED) as zout:
            for info in zin.infolist():
                if info.filename in ("__cache_meta__.npy", "chunk_crc.npy"):
                    continue
                zout.writestr(info, zin.read(info.filename))
        with pytest.raises(lgb.LightGBMError, match="predates cache"):
            lgb.Dataset(v1, params=dict(PARAMS)).construct(dict(PARAMS))

    def test_newer_format_version_is_refused(self, tmp_path, cache):
        newer = str(tmp_path / "newer.bin")
        with zipfile.ZipFile(cache) as zin, \
                zipfile.ZipFile(newer, "w", zipfile.ZIP_STORED) as zout:
            for info in zin.infolist():
                data = zin.read(info.filename)
                if info.filename == "__cache_meta__.npy":
                    import io as _io

                    meta = json.loads(str(np.lib.format.read_array(
                        _io.BytesIO(data))))
                    meta["format_version"] = CACHE_FORMAT_VERSION + 1
                    buf = _io.BytesIO()
                    np.lib.format.write_array(buf, np.asarray(
                        json.dumps(meta)))
                    data = buf.getvalue()
                zout.writestr(info, data)
        with pytest.raises(lgb.LightGBMError, match="newer than"):
            lgb.Dataset(newer, params=dict(PARAMS)).construct(dict(PARAMS))

    def test_stale_source_is_refused(self, tmp_path):
        src = tmp_path / "src.csv"
        src.write_text("1,2\n")
        meta = build_cache_meta(np.zeros((8, 2), np.uint8), None,
                                source_path=str(src))
        assert stale_reason(meta) is None
        src.write_text("1,2,3\n")  # regenerate the source
        assert "size changed" in stale_reason(meta)

    def test_crc_blocks_align_with_row_block(self):
        from lightgbm_tpu.data.cache import CRC_ROWS

        assert CRC_ROWS == ROW_BLOCK
        crcs = chunk_crcs(np.arange(2 * ROW_BLOCK + 5,
                                    dtype=np.uint8).reshape(-1, 1))
        assert crcs.shape == (3,)


# ======================================================================
# streamed-vs-resident parity (the bit-identity acceptance gate)
# ======================================================================
class TestOocParity:
    def test_gbdt_byte_identical(self, xy):
        X, y = xy
        m_mem = _train(X, y).model_to_string()
        m_ooc = _train(X, y, {"out_of_core": "true",
                              "ooc_chunk_rows": 1024}).model_to_string()
        assert m_ooc == m_mem

    @pytest.mark.parametrize("chunk_rows", [1, 1000, 2048, 2500, 9999])
    def test_chunk_boundary_cases(self, xy, chunk_rows):
        """Rounding-up-to-ROW_BLOCK (1), a last partial chunk (1000,
        2048), chunk == nrows and chunk > nrows (single-chunk stream)
        all reproduce the same bytes."""
        X, y = xy
        m_mem = _train(X, y, rounds=3).model_to_string()
        m = _train(X, y, {"out_of_core": "true",
                          "ooc_chunk_rows": chunk_rows},
                   rounds=3).model_to_string()
        assert m == m_mem

    def test_goss_byte_identical(self, xy):
        """GOSS's top-k is over the resident gradient vectors, so the
        top set is global across chunks by construction."""
        X, y = xy
        g = {"boosting": "goss"}
        m_mem = _train(X, y, g).model_to_string()
        m_ooc = _train(X, y, {**g, "out_of_core": "true",
                              "ooc_chunk_rows": 1024}).model_to_string()
        assert m_ooc == m_mem

    def test_train_from_binary_cache_streams_checksummed(self, tmp_path, xy):
        """ingest -> cache -> train: the OOC trainer streams straight
        from the v2 cache (CacheChunkSource) and still reproduces the
        in-memory bytes."""
        X, y = xy
        path = str(tmp_path / "train.bin")
        lgb.Dataset(X, label=y, params=dict(PARAMS)).construct(
            dict(PARAMS)).save_binary(path)
        P = dict(PARAMS, out_of_core="true", ooc_chunk_rows=1024)
        bst = lgb.train(dict(P), lgb.Dataset(path, params=dict(P)),
                        num_boost_round=4, verbose_eval=False)
        ooc = bst.boosting.ooc
        assert ooc is not None
        assert "cache(" in ooc.source.describe()
        m_mem = _train(X, y, rounds=4).model_to_string()
        assert bst.model_to_string() == m_mem

    def test_predictions_match_too(self, xy):
        X, y = xy
        b_mem = _train(X, y, rounds=4)
        b_ooc = _train(X, y, {"out_of_core": "true",
                              "ooc_chunk_rows": 1024}, rounds=4)
        np.testing.assert_array_equal(b_mem.predict(X), b_ooc.predict(X))


# ======================================================================
# routing decision
# ======================================================================
class TestOocRouting:
    def test_off_by_default_without_budget_pressure(self, xy, monkeypatch):
        monkeypatch.delenv("LIGHTGBM_TPU_OOC", raising=False)
        monkeypatch.setenv("LIGHTGBM_TPU_DEVICE_BUDGET", str(1 << 40))
        X, y = xy
        assert _train(X, y, rounds=1).boosting.ooc is None

    def test_auto_engages_past_device_budget(self, xy, monkeypatch):
        monkeypatch.delenv("LIGHTGBM_TPU_OOC", raising=False)
        monkeypatch.setenv("LIGHTGBM_TPU_DEVICE_BUDGET", "1024")
        X, y = xy
        bst = _train(X, y, rounds=1)
        assert bst.boosting.ooc is not None

    def test_env_var_overrides_config(self, xy, monkeypatch):
        monkeypatch.setenv("LIGHTGBM_TPU_OOC", "false")
        X, y = xy
        bst = _train(X, y, {"out_of_core": "true"}, rounds=1)
        assert bst.boosting.ooc is None

    def test_unknown_mode_is_refused(self, xy):
        X, y = xy
        with pytest.raises(lgb.LightGBMError, match="out_of_core"):
            _train(X, y, {"out_of_core": "sideways"}, rounds=1)

    def test_dart_forced_is_refused(self, xy):
        """DART mutates past trees (its score rebuild assumes resident
        bins): an explicit out_of_core=true it cannot honour is an
        error, never a silent downgrade."""
        X, y = xy
        with pytest.raises(lgb.LightGBMError, match="not supported"):
            _train(X, y, {"boosting": "dart", "out_of_core": "true"},
                   rounds=1)

    def test_dart_auto_falls_back_to_memory(self, xy, monkeypatch):
        """Auto-routing (budget pressure, nothing forced) downgrades to
        in-memory with a warning instead of crashing."""
        monkeypatch.delenv("LIGHTGBM_TPU_OOC", raising=False)
        monkeypatch.setenv("LIGHTGBM_TPU_DEVICE_BUDGET", "1024")
        X, y = xy
        bst = _train(X, y, {"boosting": "dart"}, rounds=1)
        assert bst.boosting.ooc is None


# ======================================================================
# checkpoint/resume under streaming
# ======================================================================
class TestOocCkpt:
    OOC = {"out_of_core": "true", "ooc_chunk_rows": 1024}

    def _train_ckpt(self, X, y, rounds, ckpt_dir, extra=None, callbacks=None):
        from lightgbm_tpu.ckpt import CheckpointManager

        P = dict(PARAMS, **self.OOC)
        if extra:
            P.update(extra)
        mgr = CheckpointManager(ckpt_dir, freq=2)
        try:
            return lgb.train(dict(P), lgb.Dataset(X, label=y,
                                                  params=dict(P)),
                             rounds, verbose_eval=False,
                             checkpoint_manager=mgr, callbacks=callbacks)
        finally:
            mgr.close()

    def test_kill_resume_byte_identical(self, tmp_path, xy):
        X, y = xy
        d_ref = str(tmp_path / "ref")
        d_kill = str(tmp_path / "kill")
        m_ref = self._train_ckpt(X, y, 6, d_ref).model_to_string()

        def kill(env):
            if env.iteration + 1 == 4:
                raise KeyboardInterrupt
        kill.order = 99
        with pytest.raises(KeyboardInterrupt):
            self._train_ckpt(X, y, 6, d_kill, callbacks=[kill])
        m_res = self._train_ckpt(X, y, 6, d_kill).model_to_string()
        assert m_res == m_ref

    def test_resume_with_different_grid_is_refused(self, tmp_path, xy):
        from lightgbm_tpu.ckpt import CheckpointMismatch

        X, y = xy
        d = str(tmp_path / "grid")

        def kill(env):
            if env.iteration + 1 == 4:
                raise KeyboardInterrupt
        kill.order = 99
        with pytest.raises(KeyboardInterrupt):
            self._train_ckpt(X, y, 6, d, callbacks=[kill])
        # the config fingerprint (which covers ooc_chunk_rows) refuses
        # first; the meta["ooc_schedule"] check backstops auto-resolved
        # grids that shift without a config change
        with pytest.raises(CheckpointMismatch,
                           match="chunk schedule|different training config"):
            self._train_ckpt(X, y, 6, d, extra={"ooc_chunk_rows": 8192})

    def test_schedule_backstop_refuses_shifted_grid(self, xy):
        """The meta["ooc_schedule"] check itself: an auto-resolved grid
        that shifts without any config change (e.g. a different device
        budget on the resuming host) must refuse, not resume into a
        different float summation order."""
        from lightgbm_tpu.ckpt import CheckpointMismatch, capture, restore

        X, y = xy
        P = dict(PARAMS, **self.OOC)
        bst = lgb.train(dict(P), lgb.Dataset(X, label=y, params=dict(P)),
                        2, verbose_eval=False)
        st = capture(bst)
        assert st.meta["ooc_schedule"] == \
            bst.boosting.ooc.schedule_fingerprint()
        st.meta["ooc_schedule"] = "999r/512c/2"
        with pytest.raises(CheckpointMismatch, match="chunk schedule"):
            restore(bst, st)


# ======================================================================
# residency smoke (tier-1) + the at-scale leg (slow)
# ======================================================================
@pytest.mark.ooc
class TestResidency:
    def test_stream_accounting_bounds_residency(self, xy):
        """Peak in-flight chunks never exceed the ring depth — the
        O(2 chunks) device-residency contract — and every grow pass
        streams the full grid exactly once."""
        X, y = xy
        bst = _train(X, y, {"out_of_core": "true", "ooc_chunk_rows": 1024},
                     rounds=3)
        ooc = bst.boosting.ooc
        assert ooc is not None
        st = ooc.stats
        assert st.peak_inflight <= ooc.depth
        assert st.chunks == st.passes * ooc.plan.num_chunks
        assert st.bytes > 0


_RSS_CHILD = r"""
import os, resource, sys
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import lightgbm_tpu as lgb

n, f = 400_000, 40
rng = np.random.RandomState(0)
# column-wise generation: never materialize the float matrix twice
X = np.empty((n, f), np.float32)
for j in range(f):
    X[:, j] = rng.randn(n).astype(np.float32)
y = (X[:, 0] + X[:, 1] > 0).astype(np.float32)
P = {"objective": "binary", "num_leaves": 31, "verbose": -1,
     "out_of_core": "true", "ooc_chunk_rows": 65536}
path = sys.argv[1]
ds = lgb.Dataset(X, label=y, params=dict(P))
ds.construct(dict(P)).save_binary(path)
del ds, X
bst = lgb.train(dict(P), lgb.Dataset(path, label=y, params=dict(P)),
                num_boost_round=3, verbose_eval=False)
assert bst.boosting.ooc is not None
st = bst.boosting.ooc.stats
print("RSS_MB", resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024)
print("CHUNKS", st.chunks, "PEAK", st.peak_inflight)
"""


@pytest.mark.ooc
@pytest.mark.slow
def test_large_stream_subprocess(tmp_path):
    """The at-scale leg: 400k x 40 from a binary cache, streamed in
    64k-row chunks.  Asserts the run completes, streams the whole grid
    each pass, and keeps the bounded ring."""
    out = subprocess.run(
        [sys.executable, "-c", _RSS_CHILD, str(tmp_path / "big.bin")],
        capture_output=True, text=True, timeout=1800,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = dict(l.split(" ", 1) for l in out.stdout.strip().splitlines()
                 if " " in l)
    assert int(lines["CHUNKS"].split()[0]) > 0
    assert int(lines["CHUNKS"].split()[2]) <= 2
