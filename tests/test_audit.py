"""Split-decision audit-trail tests (obs/audit.py + ``report diff``).

The acceptance contract: audit trails from a LEVELGROW=0 and a
LEVELGROW=1 run of the same config are BYTE-identical — both at the
original known-parity config and at the formerly-divergent one (ROADMAP
item 1: 15 leaves / min_data_in_leaf=20 / 6 rounds).  That config used
to diverge by ONE leaf value of iteration 2's tree (1 ULP).  Root
cause: the two modes leave different physical row orders behind (the
level grower speculatively partitions candidate levels best-first
acceptance never takes), and ``segment_values``' float range-add
cumsum carried position-dependent 1-ULP residue — so training scores,
and from round 2 on the gradients, depended on partition history.
Fixed by an exact integer-rank gather in ``segment_values`` plus a
canonical row order at every tree start, so the repro class asserts
parity; ``report diff`` localization is covered on synthetic trails in
TestReportDiff.
"""

import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs.audit import AuditWriter, audit

# the ROADMAP-pinned shape: 15 leaves / min_data_in_leaf=20 / 6 rounds.
# Seed 0 of this generator is a measured-parity config; seed 1 is the
# measured-divergent config (reproduced at PR 7 time on this tree).
PARAMS = {"objective": "binary", "num_leaves": 15, "verbose": -1,
          "min_data_in_leaf": 20}
PARITY_SEED = 0
DIVERGENT_SEED = 1


def _data(seed):
    rng = np.random.RandomState(seed)
    X = rng.randn(1200, 8)
    y = (X[:, 0] + X[:, 1] > 0).astype(float)
    return X, y


def _train_audited(tmp_path, tag, levelgrow, seed, monkeypatch):
    path = str(tmp_path / f"{tag}.jsonl")
    monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
    monkeypatch.setenv("LIGHTGBM_TPU_LEVELGROW", levelgrow)
    monkeypatch.setenv("LIGHTGBM_TPU_AUDIT", path)
    X, y = _data(seed)
    try:
        bst = lgb.train(dict(PARAMS),
                        lgb.Dataset(X, label=y, params=dict(PARAMS)),
                        num_boost_round=6, verbose_eval=False)
        model = bst.model_to_string()
    finally:
        audit.close()
        audit.path = None
    return path, model


class TestAuditStream:
    def test_disabled_by_default(self, monkeypatch):
        monkeypatch.delenv("LIGHTGBM_TPU_AUDIT", raising=False)
        w = AuditWriter()
        w.refresh_from_env()
        assert not w.enabled
        w.record_tree(0, 0, None, None)  # no-op, must not touch view/tree

    def test_records_schema_and_split_count(self, tmp_path, monkeypatch):
        path, model = _train_audited(tmp_path, "schema", "0",
                                     PARITY_SEED, monkeypatch)
        recs = [json.loads(l) for l in open(path)]
        splits = [r for r in recs if r["ev"] == "split"]
        trees = [r for r in recs if r["ev"] == "tree"]
        assert trees and splits
        assert len(trees) == 6  # one per boosting round (single class)
        # per-tree: leaves == splits + 1, and the leaf-value vector
        # length matches
        for t in trees:
            n_splits = sum(1 for s in splits if s["it"] == t["it"]
                           and s["k"] == t["k"])
            assert t["leaves"] == n_splits + 1
            assert len(t["values"]) == t["leaves"]
        # split fields: the full decision
        for s in splits:
            assert {"ev", "it", "k", "s", "leaf", "feat", "bin", "thr",
                    "gain", "dl", "dbz", "lcnt", "rcnt"} <= set(s)
            assert s["gain"] > 0
            assert s["lcnt"] > 0 and s["rcnt"] > 0
        # deterministic: records carry NO timestamps
        assert all("ts" not in r for r in recs)

    def test_mask_and_fused_paths_both_emit(self, tmp_path, monkeypatch):
        """The audit hook covers every trainer path: the mask grower
        (PGROW off) emits the same schema as the fused path."""
        path = str(tmp_path / "mask.jsonl")
        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "0")
        monkeypatch.setenv("LIGHTGBM_TPU_AUDIT", path)
        X, y = _data(PARITY_SEED)
        try:
            lgb.train(dict(PARAMS),
                      lgb.Dataset(X, label=y, params=dict(PARAMS)),
                      num_boost_round=2, verbose_eval=False)
        finally:
            audit.close()
            audit.path = None
        recs = [json.loads(l) for l in open(path)]
        assert any(r["ev"] == "split" for r in recs)
        assert any(r["ev"] == "tree" for r in recs)

    def test_levelgrow_parity_config_byte_identical(self, tmp_path,
                                                    monkeypatch):
        """At the known-parity config the two LEVELGROW modes must
        produce BYTE-identical audit trails (the determinism contract:
        repr floats, no timestamps, acceptance order)."""
        p0, m0 = _train_audited(tmp_path, "p0", "0", PARITY_SEED,
                                monkeypatch)
        p1, m1 = _train_audited(tmp_path, "p1", "1", PARITY_SEED,
                                monkeypatch)
        assert m0 == m1, "parity config regressed: models differ"
        with open(p0, "rb") as a, open(p1, "rb") as b:
            assert a.read() == b.read()
        from lightgbm_tpu.cli import main

        assert main(["report", "diff", p0, p1]) == 0


class TestLevelgrowDivergenceRepro:
    """The formerly-divergent LEVELGROW=1 vs =0 config (ROADMAP item 1).

    The two modes leave different within-segment row orders (the level
    grower partitions speculative candidates), and the old
    ``segment_values`` float-cumsum range-add gave different rows
    1-ULP-different score deltas depending on position — so from round
    2 on, gradients (hence one leaf value of tree 2) diverged.  Fixed
    by the exact integer-rank ``segment_values`` gather plus canonical
    row order at each tree start; this class pins the parity (the
    synthetic-trail localization coverage lives in TestReportDiff)."""

    @pytest.fixture(scope="class")
    def trails(self, tmp_path_factory):
        td = tmp_path_factory.mktemp("audit_div")
        mp = pytest.MonkeyPatch()
        try:
            p0, m0 = _train_audited(td, "d0", "0", DIVERGENT_SEED, mp)
            p1, m1 = _train_audited(td, "d1", "1", DIVERGENT_SEED, mp)
        finally:
            mp.undo()
        return p0, m0, p1, m1

    def test_levelgrow_models_match_at_divergent_config(self, trails):
        p0, m0, p1, m1 = trails
        assert m0 == m1

    def test_trails_byte_identical_at_divergent_config(self, trails):
        """Beyond the model string: the full audit trails (every split
        decision, every leaf value) must be byte-identical, and
        ``report diff`` must agree."""
        p0, m0, p1, m1 = trails
        with open(p0, "rb") as a, open(p1, "rb") as b:
            assert a.read() == b.read()
        from lightgbm_tpu.cli import main

        assert main(["report", "diff", p0, p1]) == 0
