"""Split-decision audit-trail tests (obs/audit.py + ``report diff``).

The acceptance contract: audit trails from a LEVELGROW=0 and a
LEVELGROW=1 run of the same config are BYTE-identical at a known-parity
config, and at the known-divergent config (ROADMAP item 1: 15 leaves /
min_data_in_leaf=20 / 6 rounds) ``report diff`` localizes the first
divergent decision — turning "the models differ" into a pinned minimal
repro.  What the diff pins at that config: every split decision
(feature / bin threshold / gain) MATCHES across the two modes, and the
first divergence is ONE leaf value of iteration 2's tree differing by
1 ULP — the level-batched selection replay rounds a leaf value
differently, it does not pick different splits.  The parity assertion
itself is marked xfail(strict=True) so a future fix flips it loudly.
"""

import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs.audit import AuditWriter, audit

# the ROADMAP-pinned shape: 15 leaves / min_data_in_leaf=20 / 6 rounds.
# Seed 0 of this generator is a measured-parity config; seed 1 is the
# measured-divergent config (reproduced at PR 7 time on this tree).
PARAMS = {"objective": "binary", "num_leaves": 15, "verbose": -1,
          "min_data_in_leaf": 20}
PARITY_SEED = 0
DIVERGENT_SEED = 1


def _data(seed):
    rng = np.random.RandomState(seed)
    X = rng.randn(1200, 8)
    y = (X[:, 0] + X[:, 1] > 0).astype(float)
    return X, y


def _train_audited(tmp_path, tag, levelgrow, seed, monkeypatch):
    path = str(tmp_path / f"{tag}.jsonl")
    monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
    monkeypatch.setenv("LIGHTGBM_TPU_LEVELGROW", levelgrow)
    monkeypatch.setenv("LIGHTGBM_TPU_AUDIT", path)
    X, y = _data(seed)
    try:
        bst = lgb.train(dict(PARAMS),
                        lgb.Dataset(X, label=y, params=dict(PARAMS)),
                        num_boost_round=6, verbose_eval=False)
        model = bst.model_to_string()
    finally:
        audit.close()
        audit.path = None
    return path, model


class TestAuditStream:
    def test_disabled_by_default(self, monkeypatch):
        monkeypatch.delenv("LIGHTGBM_TPU_AUDIT", raising=False)
        w = AuditWriter()
        w.refresh_from_env()
        assert not w.enabled
        w.record_tree(0, 0, None, None)  # no-op, must not touch view/tree

    def test_records_schema_and_split_count(self, tmp_path, monkeypatch):
        path, model = _train_audited(tmp_path, "schema", "0",
                                     PARITY_SEED, monkeypatch)
        recs = [json.loads(l) for l in open(path)]
        splits = [r for r in recs if r["ev"] == "split"]
        trees = [r for r in recs if r["ev"] == "tree"]
        assert trees and splits
        assert len(trees) == 6  # one per boosting round (single class)
        # per-tree: leaves == splits + 1, and the leaf-value vector
        # length matches
        for t in trees:
            n_splits = sum(1 for s in splits if s["it"] == t["it"]
                           and s["k"] == t["k"])
            assert t["leaves"] == n_splits + 1
            assert len(t["values"]) == t["leaves"]
        # split fields: the full decision
        for s in splits:
            assert {"ev", "it", "k", "s", "leaf", "feat", "bin", "thr",
                    "gain", "dl", "dbz", "lcnt", "rcnt"} <= set(s)
            assert s["gain"] > 0
            assert s["lcnt"] > 0 and s["rcnt"] > 0
        # deterministic: records carry NO timestamps
        assert all("ts" not in r for r in recs)

    def test_mask_and_fused_paths_both_emit(self, tmp_path, monkeypatch):
        """The audit hook covers every trainer path: the mask grower
        (PGROW off) emits the same schema as the fused path."""
        path = str(tmp_path / "mask.jsonl")
        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "0")
        monkeypatch.setenv("LIGHTGBM_TPU_AUDIT", path)
        X, y = _data(PARITY_SEED)
        try:
            lgb.train(dict(PARAMS),
                      lgb.Dataset(X, label=y, params=dict(PARAMS)),
                      num_boost_round=2, verbose_eval=False)
        finally:
            audit.close()
            audit.path = None
        recs = [json.loads(l) for l in open(path)]
        assert any(r["ev"] == "split" for r in recs)
        assert any(r["ev"] == "tree" for r in recs)

    def test_levelgrow_parity_config_byte_identical(self, tmp_path,
                                                    monkeypatch):
        """At the known-parity config the two LEVELGROW modes must
        produce BYTE-identical audit trails (the determinism contract:
        repr floats, no timestamps, acceptance order)."""
        p0, m0 = _train_audited(tmp_path, "p0", "0", PARITY_SEED,
                                monkeypatch)
        p1, m1 = _train_audited(tmp_path, "p1", "1", PARITY_SEED,
                                monkeypatch)
        assert m0 == m1, "parity config regressed: models differ"
        with open(p0, "rb") as a, open(p1, "rb") as b:
            assert a.read() == b.read()
        from lightgbm_tpu.cli import main

        assert main(["report", "diff", p0, p1]) == 0


class TestLevelgrowDivergenceRepro:
    """The pinned repro for the open LEVELGROW=1 vs =0 divergence
    (ROADMAP item 1)."""

    @pytest.fixture(scope="class")
    def trails(self, tmp_path_factory):
        td = tmp_path_factory.mktemp("audit_div")
        mp = pytest.MonkeyPatch()
        try:
            p0, m0 = _train_audited(td, "d0", "0", DIVERGENT_SEED, mp)
            p1, m1 = _train_audited(td, "d1", "1", DIVERGENT_SEED, mp)
        finally:
            mp.undo()
        return p0, m0, p1, m1

    @pytest.mark.xfail(
        strict=True,
        reason="open LEVELGROW=1 vs =0 divergence (ROADMAP item 1): the "
               "level-batched replay rounds one leaf value of iteration "
               "2 differently by 1 ULP at 15 leaves/min_data_in_leaf=20/"
               "6 rounds; strict so a fix flips this loudly")
    def test_levelgrow_models_match_at_divergent_config(self, trails):
        p0, m0, p1, m1 = trails
        assert m0 == m1

    def test_diff_localizes_first_divergent_decision(self, trails,
                                                     capsys):
        """``report diff`` must pin the divergence to a single record
        with iteration context — the minimal repro the ISSUE asks for —
        and every split DECISION before it must match (the divergence
        is a leaf-value rounding, not a different split)."""
        p0, m0, p1, m1 = trails
        assert m0 != m1, "divergent config unexpectedly reached parity " \
            "(if a fix landed, flip the xfail above and retire this)"
        from lightgbm_tpu.cli import main
        from lightgbm_tpu.obs import report

        rc = main(["report", "diff", p0, p1, "--json"])
        out = capsys.readouterr().out
        assert rc == 1
        div = json.loads(out)
        assert div["identical"] is False
        assert div["a"]["ev"] in ("split", "tree")
        assert "it" in div["a"] and div["fields"]
        # localization value: no split decision diverges before the
        # first divergent record — feature/threshold/gain all match
        a = report.load_trace(p0, warn=False)
        b = report.load_trace(p1, warn=False)
        for ra, rb in zip(a[: div["index"]], b[: div["index"]]):
            assert ra == rb
        # human rendering names the iteration and the differing field
        rc = main(["report", "diff", p0, p1])
        out = capsys.readouterr().out
        assert rc == 1
        assert f"record {div['index']}" in out
        assert f"it={div['a']['it']}" in out
