"""Tests for auxiliary subsystems: plotting (Agg, modeled on the
reference's test_plotting.py), PMML export, prediction early stop,
phase timers, and the text parser formats + side files.
"""

import os

import matplotlib

matplotlib.use("Agg")

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.parser import load_text_file, sniff_format

EXAMPLES = "/root/reference/examples"


@pytest.fixture(scope="module")
def small_booster():
    rng = np.random.RandomState(0)
    x = rng.randn(400, 5)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float32)
    ev = {}
    ds = lgb.Dataset(x, label=y, feature_name=[f"f{i}" for i in range(5)])
    bst = lgb.train(
        {"objective": "binary", "metric": "binary_logloss", "verbose": -1,
         "min_data_in_leaf": 5},
        ds, num_boost_round=5,
        valid_sets=[lgb.Dataset(x, label=y, reference=ds)],
        evals_result=ev, verbose_eval=False,
    )
    return bst, ev


def test_plot_importance(small_booster):
    bst, _ = small_booster
    ax = lgb.plotting.plot_importance(bst)
    assert len(ax.patches) > 0
    assert ax.get_title() == "Feature importance"


def test_plot_metric(small_booster):
    _, ev = small_booster
    ax = lgb.plotting.plot_metric(ev)
    assert len(ax.lines) == 1


def test_create_tree_digraph_and_plot_tree(small_booster):
    bst, _ = small_booster
    g = lgb.plotting.create_tree_digraph(bst, 1, show_info=["split_gain"])
    assert "f" in g.source  # feature names appear
    ax = lgb.plotting.plot_tree(bst, 1)
    assert ax is not None


def test_pmml_export(small_booster, tmp_path):
    from lightgbm_tpu.pmml import model_to_pmml, pmml_from_model_file

    bst, _ = small_booster
    pmml = model_to_pmml(bst)
    assert pmml.startswith('<?xml version="1.0"')
    assert "<Segmentation" in pmml and "</PMML>" in pmml
    assert pmml.count("<Segment id=") == bst.num_trees
    # from a saved model file, like the reference script
    path = str(tmp_path / "m.txt")
    bst.save_model(path)
    pmml2 = pmml_from_model_file(path)
    assert pmml2.count("<Segment id=") == bst.num_trees


def test_prediction_early_stop(small_booster):
    from lightgbm_tpu.boosting.pred_early_stop import (
        create_prediction_early_stop_instance,
        predict_with_early_stop,
    )

    bst, _ = small_booster
    rng = np.random.RandomState(1)
    x = rng.randn(20, 5)
    full = bst.predict(x, raw_score=True)
    es = create_prediction_early_stop_instance("binary", round_period=1,
                                               margin_threshold=0.0)
    early = predict_with_early_stop(bst.boosting, x, es)[:, 0]
    # margin 0 stops after the first round on any nonzero row
    assert early.shape == full.shape
    es_none = create_prediction_early_stop_instance("none")
    none_pred = predict_with_early_stop(bst.boosting, x, es_none)[:, 0]
    np.testing.assert_allclose(none_pred, full, rtol=1e-5)


def test_phase_timers():
    from lightgbm_tpu.utils.profiling import PhaseTimers

    t = PhaseTimers()
    t.enable()
    with t.phase("hist"):
        pass
    with t.phase("hist"):
        pass
    assert t.counts["hist"] == 2
    assert t.totals["hist"] >= 0.0
    t.reset()
    assert not t.totals


# ----------------------------------------------------------------------
# parser (ADVICE r1 asked for direct tests over all formats + side files)
# ----------------------------------------------------------------------
def test_sniff_formats(tmp_path):
    tsv = tmp_path / "a.tsv"
    tsv.write_text("1.0\t2.0\t3.0\n0.0\t1.0\t2.0\n")
    csv = tmp_path / "a.csv"
    csv.write_text("1.0,2.0,3.0\n0.0,1.0,2.0\n")
    svm = tmp_path / "a.svm"
    svm.write_text("1 0:2.0 2:3.0\n0 1:1.0\n")
    assert sniff_format(str(tsv))[0] == "tsv"
    assert sniff_format(str(csv))[0] == "csv"
    assert sniff_format(str(svm))[0] == "libsvm"


def test_load_tsv_with_label(tmp_path):
    p = tmp_path / "d.tsv"
    p.write_text("1.0\t5.0\t6.0\n0.0\t7.0\t8.0\n")
    X, y, w, g, names, li = load_text_file(str(p), Config())
    np.testing.assert_array_equal(y, [1.0, 0.0])
    np.testing.assert_array_equal(X, [[5.0, 6.0], [7.0, 8.0]])


def test_load_with_weight_and_group_columns(tmp_path):
    """Numeric weight/group specs are label-relative and shift past the
    label column (the ADVICE r1 translation fix)."""
    p = tmp_path / "d.csv"
    # cols: label, f0, weight, qid
    p.write_text("1,10,0.5,0\n0,20,1.5,0\n1,30,2.5,1\n")
    cfg = Config.from_params({"weight_column": "1", "group_column": "2"})
    X, y, w, g, names, li = load_text_file(str(p), cfg)
    np.testing.assert_array_equal(y, [1, 0, 1])
    np.testing.assert_allclose(w, [0.5, 1.5, 2.5])
    np.testing.assert_array_equal(g, [2, 1])  # qid runs 0,0,1
    np.testing.assert_array_equal(X.ravel(), [10, 20, 30])


def test_load_named_columns_with_header(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("lab,a,wt,b\n1,10,0.5,40\n0,20,1.5,50\n")
    cfg = Config.from_params(
        {"has_header": True, "label_column": "name:lab",
         "weight_column": "name:wt", "ignore_column": "name:b"}
    )
    X, y, w, g, names, li = load_text_file(str(p), cfg)
    np.testing.assert_array_equal(y, [1, 0])
    np.testing.assert_allclose(w, [0.5, 1.5])
    assert names == ["a"]
    np.testing.assert_array_equal(X.ravel(), [10, 20])


def test_side_files(tmp_path):
    p = tmp_path / "d.tsv"
    p.write_text("1\t5\t4\n0\t7\t3\n1\t9\t2\n")
    (tmp_path / "d.tsv.weight").write_text("0.1\n0.2\n0.3\n")
    (tmp_path / "d.tsv.query").write_text("2\n1\n")
    X, y, w, g, names, li = load_text_file(str(p), Config())
    np.testing.assert_allclose(w, [0.1, 0.2, 0.3])
    np.testing.assert_array_equal(g, [2, 1])


def test_libsvm_loading(tmp_path):
    p = tmp_path / "d.svm"
    p.write_text("1 0:1.5 2:2.5\n0 1:3.5\n")
    X, y, w, g, names, li = load_text_file(str(p), Config())
    np.testing.assert_array_equal(y, [1, 0])
    np.testing.assert_allclose(X, [[1.5, 0, 2.5], [0, 3.5, 0]])


def test_pred_early_stop_wired_into_predict():
    """pred_early_stop config keys drive Booster.predict: early-stopped
    predictions match full predictions for high-margin rows and the keys
    are no longer dead (predictor.hpp:24-120)."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(3)
    X = rng.standard_normal((800, 6)).astype(np.float32)
    w = rng.standard_normal(6) * 3.0
    y = ((X @ w) > 0).astype(np.float32)  # separable -> large margins
    bst = lgb.train({"objective": "binary", "num_leaves": 15, "verbose": -1},
                    lgb.Dataset(X, label=y), 30)
    full = bst.predict(X[:50])
    bst.boosting.config.pred_early_stop = True
    bst.boosting.config.pred_early_stop_freq = 5
    bst.boosting.config.pred_early_stop_margin = 10.0
    es = bst.predict(X[:50])
    # high-margin rows: sign/class decisions identical, values close for
    # confident rows (stop only fires beyond the margin)
    assert np.array_equal(full > 0.5, es > 0.5)
    conf = np.abs(full - 0.5) > 0.45
    assert conf.any()
    np.testing.assert_allclose(es[conf], full[conf], atol=2e-2)
    # huge margin threshold => never stops => exactly equal
    bst.boosting.config.pred_early_stop_margin = 1e9
    never = bst.predict(X[:50])
    np.testing.assert_allclose(never, full, rtol=1e-6, atol=1e-7)


def test_convert_model_cpp_compiles_and_matches(tmp_path):
    """task=convert_model emits standalone C++ whose predictions match the
    Python predictor (GBDT::ModelToIfElse counterpart)."""
    import ctypes
    import shutil
    import subprocess

    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")

    import lightgbm_tpu as lgb
    from lightgbm_tpu.cli import main as cli_main

    rng = np.random.default_rng(5)
    X = rng.standard_normal((1200, 5)).astype(np.float64)
    X[:30, 0] = 0.0  # exercise the zero/missing remap
    w = rng.standard_normal(5)
    y = (rng.random(1200) < 1 / (1 + np.exp(-(X @ w)))).astype(np.float32)
    bst = lgb.train({"objective": "binary", "num_leaves": 15, "verbose": -1},
                    lgb.Dataset(X, label=y), 5)
    model_path = str(tmp_path / "model.txt")
    bst.save_model(model_path)
    cpp_path = str(tmp_path / "pred.cpp")
    rc = cli_main(["task=convert_model", f"input_model={model_path}",
                   f"convert_model={cpp_path}"])
    assert not rc
    so_path = str(tmp_path / "pred.so")
    subprocess.run(["g++", "-O2", "-shared", "-fPIC", "-o", so_path, cpp_path],
                   check=True)
    lib = ctypes.CDLL(so_path)
    lib.Predict.argtypes = [ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double)]
    assert lib.GetNumClasses() == 1
    assert lib.GetNumFeatures() == 5
    expect = bst.predict(X[:64])
    out = np.zeros(1, np.float64)
    got = np.zeros(64)
    for i in range(64):
        row = np.ascontiguousarray(X[i], np.float64)
        lib.Predict(row.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        got[i] = out[0]
    # the Python predictor accumulates in float32 on device; the C code
    # is full float64 — tolerance covers the f32 rounding
    np.testing.assert_allclose(got, expect, rtol=2e-6, atol=2e-7)


def test_scipy_sparse_input():
    """CSR/CSC matrices are accepted (densified; LGBM_DatasetCreateFromCSR
    counterpart at the python surface)."""
    scipy = pytest.importorskip("scipy.sparse")
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(0)
    dense = rng.standard_normal((500, 8)) * (rng.random((500, 8)) < 0.3)
    y = rng.standard_normal(500).astype(np.float32)
    for conv in (scipy.csr_matrix, scipy.csc_matrix):
        bst = lgb.train({"objective": "regression", "num_leaves": 7, "verbose": -1},
                        lgb.Dataset(conv(dense), label=y), 3)
        p_sparse = bst.predict(conv(dense[:50]))
        p_dense = bst.predict(dense[:50])
        np.testing.assert_allclose(p_sparse, p_dense, rtol=1e-7)
