"""Subprocess byte-identity for the wide-data distributed learners
(parallel/hostlearner.py over real jax.distributed + KV collectives):

  * feature-parallel (rows replicated, columns sharded) trains a model
    BYTE-identical to single-process serial at 2 and 4 ranks;
  * voting-parallel with 2k >= F trains a model BYTE-identical to the
    host data-parallel learner on the same row shards.
"""

import os
import socket
import subprocess
import sys

import pytest


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _run_world(tmp_path, mode, nproc, tag):
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "wide_worker.py")
    out = str(tmp_path / f"{tag}.txt")
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(r), str(port), out, mode,
             str(nproc)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        )
        for r in range(nproc)
    ]
    logs = []
    for p in procs:
        o, _ = p.communicate(timeout=900)
        logs.append(o.decode())
    assert all(p.returncode == 0 for p in procs), "\n".join(logs)
    with open(out) as fh:
        return fh.read()


@pytest.mark.slow
@pytest.mark.parametrize("nproc", [2, 4])
def test_feature_parallel_byte_identical_to_serial(tmp_path, nproc):
    got = _run_world(tmp_path, "feature", nproc, f"feature{nproc}")
    # the serial reference runs as a subprocess with the SAME XLA env:
    # XLA:CPU's f32 matmul accumulation order follows its thread-pool
    # partitioning, so bitwise comparison only makes sense within one
    # environment (the worker docstring has the full story)
    ref = _run_world(tmp_path, "serial", 1, f"serial{nproc}")
    assert got == ref
    assert got.count("Tree=") >= 4


@pytest.mark.slow
def test_voting_full_k_byte_identical_to_data_parallel(tmp_path):
    data = _run_world(tmp_path, "datahost", 2, "datahost")
    vote = _run_world(tmp_path, "voting", 2, "voting")
    assert vote == data
    assert data.count("Tree=") >= 4
