"""Distributed learner tests on the 8-device virtual CPU mesh: each
parallel mode must reproduce the serial learner's tree (the reference's
parallel learners are mathematically exact reformulations, not
approximations — except voting, which is top-k approximate and only
checked for quality).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.objective import create_objective
from lightgbm_tpu.ops.grow import GrowParams, grow_tree
from lightgbm_tpu.ops.split import FeatureMeta, SplitHyper
from lightgbm_tpu.parallel import ShardedLearner, make_mesh

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


@pytest.fixture(scope="module")
def problem():
    rng = np.random.RandomState(0)
    n, f = 1024, 8
    x = rng.randn(n, f)
    y = (x[:, 0] + 0.5 * x[:, 1] ** 2 + 0.1 * rng.randn(n) > 0.3).astype(np.float32)
    cfg = Config.from_params(
        {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
         "verbose": -1}
    )
    ds = BinnedDataset.from_raw(x, cfg, label=y)
    obj = create_objective(cfg)
    obj.init(ds.metadata, ds.num_data)
    grad, hess = obj.get_gradients(jnp.zeros((ds.num_data,), jnp.float32))
    return {
        "cfg": cfg,
        "ds": ds,
        "bins": jnp.asarray(ds.binned),
        "grad": grad,
        "hess": hess,
        "select": jnp.ones((ds.num_data,), jnp.float32),
        "fmask": jnp.ones((ds.num_features,), jnp.float32),
        "meta": FeatureMeta.from_dataset(ds),
        "hyper": SplitHyper.from_config(cfg),
        "params": GrowParams(num_leaves=15, num_bins=ds.max_num_bin),
    }


def _serial(p):
    return grow_tree(p["bins"], p["grad"], p["hess"], p["select"], p["fmask"],
                     p["meta"], p["hyper"], p["params"])


def _assert_same_tree(a, b, atol=1e-4):
    assert int(a.num_splits) == int(b.num_splits)
    s = int(a.num_splits)
    np.testing.assert_array_equal(np.asarray(a.rec_feat[:s]), np.asarray(b.rec_feat[:s]))
    np.testing.assert_array_equal(np.asarray(a.rec_thr[:s]), np.asarray(b.rec_thr[:s]))
    np.testing.assert_array_equal(np.asarray(a.rec_leaf[:s]), np.asarray(b.rec_leaf[:s]))
    np.testing.assert_allclose(np.asarray(a.leaf_value), np.asarray(b.leaf_value),
                               atol=atol)
    np.testing.assert_array_equal(np.asarray(a.leaf_id), np.asarray(b.leaf_id))


def test_data_parallel_matches_serial(problem):
    """Per-shard histograms + psum must reproduce the serial tree
    (data_parallel_tree_learner.cpp semantics: exact, not approximate)."""
    serial = _serial(problem)
    learner = ShardedLearner("data", make_mesh(8), problem["params"])
    sharded = learner.grow(problem["bins"], problem["grad"], problem["hess"],
                           problem["select"], problem["fmask"],
                           problem["meta"], problem["hyper"])
    _assert_same_tree(serial, sharded)


def test_feature_parallel_matches_serial(problem):
    """Feature-sharded search + cross-shard argmax must reproduce the
    serial tree (feature_parallel_tree_learner.cpp: every machine has all
    data; only the search is sharded)."""
    serial = _serial(problem)
    learner = ShardedLearner("feature", make_mesh(8), problem["params"])
    sharded = learner.grow(problem["bins"], problem["grad"], problem["hess"],
                           problem["select"], problem["fmask"],
                           problem["meta"], problem["hyper"])
    _assert_same_tree(serial, sharded)


def test_voting_parallel_quality(problem):
    """Voting is top-k approximate (voting_parallel_tree_learner.cpp); with
    top_k >= F it must also be exact."""
    serial = _serial(problem)
    params_full = problem["params"]._replace(top_k=8)  # == num features
    learner = ShardedLearner("voting", make_mesh(8), params_full)
    sharded = learner.grow(problem["bins"], problem["grad"], problem["hess"],
                           problem["select"], problem["fmask"],
                           problem["meta"], problem["hyper"])
    _assert_same_tree(serial, sharded)
    # and with top_k < F it still grows a usable tree
    learner2 = ShardedLearner("voting", make_mesh(8),
                              problem["params"]._replace(top_k=3))
    gr2 = learner2.grow(problem["bins"], problem["grad"], problem["hess"],
                        problem["select"], problem["fmask"],
                        problem["meta"], problem["hyper"])
    assert int(gr2.num_splits) > 0


def test_end_to_end_data_parallel_training(problem):
    """Full training through the API with tree_learner=data matches
    serial training predictions."""
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(1)
    x = rng.randn(600, 6)
    y = (x[:, 0] - x[:, 1] > 0).astype(np.float32)
    params_serial = {"objective": "binary", "num_leaves": 7, "verbose": -1,
                     "min_data_in_leaf": 5}
    params_dp = dict(params_serial, tree_learner="data")
    b1 = lgb.train(params_serial, lgb.Dataset(x, label=y), num_boost_round=5,
                   verbose_eval=False)
    b2 = lgb.train(params_dp, lgb.Dataset(x, label=y), num_boost_round=5,
                   verbose_eval=False)
    np.testing.assert_allclose(b1.predict(x), b2.predict(x), atol=1e-5)
