"""Perf regression gate (bench.py): fires on a synthetic slow result,
passes on a fast one, and skips silently when there is nothing
comparable to gate against."""

import importlib.util
import json
import os

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "bench_under_test",
    os.path.join(os.path.dirname(__file__), "..", "bench.py"),
)
bench = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(bench)

METRIC = "sec/iteration (binary, 1000000x28, max_bin=63, num_leaves=255)"


def _capture(tmp_path, name, value, metric=METRIC, **parsed_extra):
    doc = {"n": 1, "rc": 0,
           "parsed": dict({"metric": metric, "value": value}, **parsed_extra)}
    (tmp_path / name).write_text(json.dumps(doc))


def test_gate_fires_on_synthetic_slow_result(tmp_path):
    _capture(tmp_path, "BENCH_r01.json", 0.20)
    _capture(tmp_path, "BENCH_r02.json", 0.10)  # the best prior
    out = {"metric": METRIC, "value": 0.1366}
    rc = bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={})
    assert rc == 1
    assert out["regression"] is True
    assert out["gate"]["best_prior_s_per_iter"] == 0.10
    assert out["gate"]["best_prior_source"] == "BENCH_r02.json"
    assert out["gate"]["threshold_s_per_iter"] == pytest.approx(0.11)


def test_gate_passes_within_threshold(tmp_path):
    _capture(tmp_path, "BENCH_r01.json", 0.10)
    out = {"metric": METRIC, "value": 0.105}  # 5% slower: within the 10% band
    rc = bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={})
    assert rc == 0
    assert "regression" not in out
    assert out["gate"]["best_prior_s_per_iter"] == 0.10


def test_gate_passes_on_improvement(tmp_path):
    _capture(tmp_path, "BENCH_r01.json", 0.1366)
    out = {"metric": METRIC, "value": 0.1000}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert "regression" not in out


def test_silent_skip_without_comparable_priors(tmp_path):
    # no files at all
    out = {"metric": METRIC, "value": 9.9}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert "gate" not in out and "regression" not in out
    # a dead capture (parsed: null, the BENCH_r05 shape) + garbage file
    (tmp_path / "BENCH_r05.json").write_text(
        json.dumps({"n": 5, "rc": 1, "parsed": None}))
    (tmp_path / "BENCH_r06.json").write_text("{torn json")
    # and a different-metric capture (other row count: not comparable)
    _capture(tmp_path, "BENCH_r04.json", 0.01,
             metric="sec/iteration (binary, 120000x28, max_bin=63, num_leaves=255)")
    out = {"metric": METRIC, "value": 9.9}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert "gate" not in out and "regression" not in out


def test_backend_fallback_runs_never_gate(tmp_path):
    _capture(tmp_path, "BENCH_r01.json", 0.10)
    # a fallback CPU run is not comparable to device captures
    out = {"metric": METRIC, "value": 9.9, "backend_fallback": True}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert "regression" not in out
    # ... and fallback PRIORS are not a baseline either
    _capture(tmp_path, "BENCH_r02.json", 0.001, backend_fallback=True)
    out = {"metric": METRIC, "value": 0.105}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert out["gate"]["best_prior_s_per_iter"] == 0.10  # r02 ignored


def test_opt_out(tmp_path):
    _capture(tmp_path, "BENCH_r01.json", 0.10)
    out = {"metric": METRIC, "value": 9.9}
    rc = bench.apply_regression_gate(out, bench_dir=str(tmp_path),
                                     env={"BENCH_GATE": "0"})
    assert rc == 0 and "regression" not in out and "gate" not in out


def test_raw_bench_format_accepted(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"metric": METRIC, "value": 0.10, "unit": "s/iter"}))
    out = {"metric": METRIC, "value": 0.2}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 1
    assert out["regression"] is True


def _ooc(rows=200_000, chunk_rows=65_536, s_per_iter=1.0):
    return {"rows": rows, "chunk_rows": chunk_rows,
            "stream_s_per_iter": s_per_iter}


def test_ooc_gate_fires_on_slow_stream(tmp_path):
    """The streamed s/iter gates independently of the headline metric —
    an OOC regression with a healthy fused number still fails."""
    _capture(tmp_path, "BENCH_r01.json", 0.10, out_of_core=_ooc(s_per_iter=1.0))
    out = {"metric": METRIC, "value": 0.10,  # headline: fine
           "out_of_core": _ooc(s_per_iter=1.2)}  # stream: 20% slower
    rc = bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={})
    assert rc == 1
    assert out.get("regression_ooc") is True
    assert "regression" not in out
    assert out["gate_ooc"]["best_prior_stream_s_per_iter"] == 1.0


def test_ooc_gate_requires_same_grid(tmp_path):
    # a prior at a different chunk grid is a different summation/stream
    # schedule: not comparable
    _capture(tmp_path, "BENCH_r01.json", 0.10,
             out_of_core=_ooc(chunk_rows=4096, s_per_iter=0.5))
    out = {"metric": METRIC, "value": 0.10, "out_of_core": _ooc(s_per_iter=9.9)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert "gate_ooc" not in out and "regression_ooc" not in out


def test_ooc_gate_runs_without_headline_prior(tmp_path):
    # first capture of a new main config, but the ooc grid has history
    _capture(tmp_path, "BENCH_r01.json", 0.10, out_of_core=_ooc(s_per_iter=1.0),
             metric="sec/iteration (binary, 120000x28, max_bin=63, num_leaves=255)")
    out = {"metric": METRIC, "value": 0.10, "out_of_core": _ooc(s_per_iter=1.2)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 1
    assert out.get("regression_ooc") is True
    assert "gate" not in out  # headline leg silently skipped


def test_ooc_section_error_never_gates(tmp_path):
    _capture(tmp_path, "BENCH_r01.json", 0.10, out_of_core=_ooc(s_per_iter=1.0))
    out = {"metric": METRIC, "value": 0.10,
           "out_of_core": {"error": "RuntimeError: boom"}}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert "gate_ooc" not in out


def _factory(rows=8_000, rounds=10, e2e_s=1.0):
    return {"rows": rows, "num_boost_round": rounds,
            "append_to_promoted_s": e2e_s}


def test_factory_gate_fires_on_slow_cycle(tmp_path):
    """The factory append->promoted latency gates independently of the
    headline, at the wider 1.5x host-work threshold."""
    _capture(tmp_path, "BENCH_r01.json", 0.10, factory=_factory(e2e_s=1.0))
    out = {"metric": METRIC, "value": 0.10,
           "factory": _factory(e2e_s=1.6)}  # 60% slower: over the band
    rc = bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={})
    assert rc == 1
    assert out.get("regression_factory") is True
    assert "regression" not in out
    assert out["gate_factory"]["best_prior_append_to_promoted_s"] == 1.0
    assert out["gate_factory"]["threshold_s"] == pytest.approx(1.5)


def test_factory_gate_passes_within_band(tmp_path):
    _capture(tmp_path, "BENCH_r01.json", 0.10, factory=_factory(e2e_s=1.0))
    out = {"metric": METRIC, "value": 0.10, "factory": _factory(e2e_s=1.4)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert "regression_factory" not in out
    assert out["gate_factory"]["best_prior_append_to_promoted_s"] == 1.0


def test_factory_gate_requires_same_grid(tmp_path):
    # a prior at a different (rows, rounds) grid is a different cycle
    _capture(tmp_path, "BENCH_r01.json", 0.10,
             factory=_factory(rows=80_000, e2e_s=0.5))
    out = {"metric": METRIC, "value": 0.10, "factory": _factory(e2e_s=9.9)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert "gate_factory" not in out and "regression_factory" not in out


def test_factory_section_error_never_gates(tmp_path):
    _capture(tmp_path, "BENCH_r01.json", 0.10, factory=_factory(e2e_s=1.0))
    out = {"metric": METRIC, "value": 0.10,
           "factory": {"error": "RuntimeError: boom",
                       "append_to_promoted_s": 9.9,
                       "rows": 8_000, "num_boost_round": 10}}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert "gate_factory" not in out


# ----------------------------------------------------------------------
# quantized-serving leg
# ----------------------------------------------------------------------
def _quantized(speedup=2.0, swap_compiles=0, within_bound=True, ratio=2.5):
    return {
        "artifact_bytes": {"payload_ratio": ratio},
        "drift": {"max_abs": 1e-4, "bound": 1e-3,
                  "within_bound": within_bound},
        "batch2048": {"exact": {"rows_per_s": 1e6},
                      "quantized": {"rows_per_s": 1e6 * speedup},
                      "speedup": speedup},
        "swap": {"swaps": 3, "swap_latency_p50_ms": 1.0,
                 "swap_new_compiles": swap_compiles},
    }


def test_quantized_swap_compiles_gate_fires_without_prior(tmp_path):
    out = {"metric": METRIC, "value": 0.10,
           "quantized": _quantized(swap_compiles=2)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 1
    assert out["regression_quant_swap_compiles"] is True


def test_quantized_drift_gate_fires_without_prior(tmp_path):
    out = {"metric": METRIC, "value": 0.10,
           "quantized": _quantized(within_bound=False)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 1
    assert out["regression_quant_drift"] is True


def test_quantized_bytes_gate_fires_without_prior(tmp_path):
    out = {"metric": METRIC, "value": 0.10,
           "quantized": _quantized(ratio=1.4)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 1
    assert out["regression_quant_bytes"] is True


def test_quantized_speedup_gates_against_prior(tmp_path):
    _capture(tmp_path, "BENCH_r01.json", 0.10, quantized=_quantized(2.0))
    out = {"metric": METRIC, "value": 0.10, "quantized": _quantized(1.5)}
    rc = bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={})
    assert rc == 1
    assert out["regression_quantized"] is True
    assert out["gate_quantized"]["best_prior_speedup_batch2048"] == 2.0
    # within the 1.10 band passes
    out = {"metric": METRIC, "value": 0.10, "quantized": _quantized(1.85)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert "regression_quantized" not in out


def test_quantized_section_error_never_gates(tmp_path):
    out = {"metric": METRIC, "value": 0.10,
           "quantized": {"error": "RuntimeError: boom",
                         "swap": {"swap_new_compiles": 9}}}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0


def test_quantized_clean_run_passes(tmp_path):
    out = {"metric": METRIC, "value": 0.10, "quantized": _quantized()}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    for k in list(out):
        assert not k.startswith("regression"), k


# ----------------------------------------------------------------------
# comms leg (wide-data learners, docs/PARALLEL.md)
# ----------------------------------------------------------------------
def _comms(ratio=48.0, rows=3000, features=2000, ranks=2,
           data_s=0.9, feature_s=0.1, voting_s=0.2):
    return {
        "rows": rows, "features": features, "ranks": ranks,
        "voting_vs_data_payload_ratio": ratio,
        "feature_vs_data_payload_ratio": 1800.0,
        "per_learner": {
            "data": {"bytes_per_iter": 5_568_062, "s_per_iter": data_s},
            "feature": {"bytes_per_iter": 3_031, "s_per_iter": feature_s},
            "voting": {"bytes_per_iter": 114_902, "s_per_iter": voting_s},
        },
    }


def test_comms_payload_gate_fires_without_prior(tmp_path):
    """Voting must cut the data-parallel allreduce payload >=5x; the
    ratio is protocol arithmetic, so it gates with no prior capture."""
    out = {"metric": METRIC, "value": 0.10, "comms": _comms(ratio=3.2)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 1
    assert out["regression_comms_payload"] is True
    assert out["gate_comms"]["min_voting_vs_data_payload_ratio"] == 5.0
    assert out["gate_comms"]["voting_vs_data_payload_ratio"] == pytest.approx(3.2)


def test_comms_payload_gate_is_device_independent(tmp_path):
    # bytes/iter do not depend on the backend: the leg runs (and fires)
    # even on a backend_fallback capture that skips every other gate
    out = {"metric": METRIC, "value": 9.9, "backend_fallback": True,
           "comms": _comms(ratio=3.2)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 1
    assert out["regression_comms_payload"] is True
    assert "regression" not in out  # headline leg still skipped
    out = {"metric": METRIC, "value": 9.9, "backend_fallback": True,
           "comms": _comms(ratio=48.0)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert "gate_comms" in out


def test_comms_payload_gate_passes(tmp_path):
    out = {"metric": METRIC, "value": 0.10, "comms": _comms(ratio=48.46)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert out["gate_comms"]["voting_vs_data_payload_ratio"] == pytest.approx(48.46)
    for k in list(out):
        assert not k.startswith("regression"), k


def _elastic(recovery=2.5):
    return {
        "rows": 1024, "trees": 14, "ranks": 2,
        "delay_ms_per_collective": 30,
        "no_straggler_s_per_iter": 0.16,
        "straggler_off_s_per_iter": 1.5,
        "straggler_rebalance_s_per_iter": round(1.5 / recovery, 4),
        "straggler_slowdown": 9.2,
        "recovery_ratio": recovery,
        "final_counts": [154, 870],
    }


def test_elastic_gate_fires_without_prior(tmp_path):
    """Rebalance-on must beat rebalance-off >=1.3x under the injected
    straggler; the stall dominates on any backend, so the leg gates
    outright with no prior capture."""
    out = {"metric": METRIC, "value": 0.10, "elastic": _elastic(recovery=1.1)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 1
    assert out["regression_elastic_recovery"] is True
    assert out["gate_elastic"]["min_recovery_ratio"] == 1.3
    assert out["gate_elastic"]["recovery_ratio"] == pytest.approx(1.1)


def test_elastic_gate_is_device_independent(tmp_path):
    # the recovery ratio gates even on a backend_fallback capture that
    # skips every wall-clock gate (CPU fallback included, by contract)
    out = {"metric": METRIC, "value": 9.9, "backend_fallback": True,
           "elastic": _elastic(recovery=1.2)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 1
    assert out["regression_elastic_recovery"] is True
    assert "regression" not in out  # headline leg still skipped
    out = {"metric": METRIC, "value": 9.9, "backend_fallback": True,
           "elastic": _elastic(recovery=2.7)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert "gate_elastic" in out


def test_elastic_gate_passes(tmp_path):
    out = {"metric": METRIC, "value": 0.10, "elastic": _elastic(recovery=2.67)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert out["gate_elastic"]["recovery_ratio"] == pytest.approx(2.67)
    for k in list(out):
        assert not k.startswith("regression"), k


def test_elastic_section_error_never_gates(tmp_path):
    out = {"metric": METRIC, "value": 0.10,
           "elastic": {"error": "RuntimeError: fleet failed"}}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert "gate_elastic" not in out
    assert "regression_elastic_recovery" not in out


def _oocdist(parity=True):
    return {
        "rows": 16384, "trees": 3, "ranks": 2,
        "chunk_grids": [2048, 9999],
        "chunks_per_pass": {2048: 2, 9999: 1},
        "fleet_wall_s": {2048: 21.0, 9999: 19.5},
        "quantized_parity_ok": parity,
    }


def test_oocdist_gate_fires_on_parity_break(tmp_path):
    """Quantized streamed folds are associative int32 adds, so the model
    bytes must match EXACTLY across chunk grids — any mismatch gates
    outright with no prior capture."""
    out = {"metric": METRIC, "value": 0.10,
           "ooc_distributed": _oocdist(parity=False)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 1
    assert out["regression_oocdist_parity"] is True
    assert out["gate_oocdist"]["require_quantized_parity"] is True
    assert out["gate_oocdist"]["chunk_grids"] == [2048, 9999]


def test_oocdist_gate_is_device_independent(tmp_path):
    # parity is protocol arithmetic: it gates even on a
    # backend_fallback / device_tunnel_dead capture that skips every
    # wall-clock gate (ISSUE contract: gate OUTRIGHT on dead tunnels)
    out = {"metric": METRIC, "value": 9.9, "backend_fallback": True,
           "device_tunnel_dead": True,
           "ooc_distributed": _oocdist(parity=False)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 1
    assert out["regression_oocdist_parity"] is True
    assert "regression" not in out  # headline leg still skipped
    out = {"metric": METRIC, "value": 9.9, "backend_fallback": True,
           "ooc_distributed": _oocdist(parity=True)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert "gate_oocdist" in out


def test_oocdist_gate_passes(tmp_path):
    out = {"metric": METRIC, "value": 0.10,
           "ooc_distributed": _oocdist(parity=True)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert out["gate_oocdist"]["quantized_parity_ok"] is True
    for k in list(out):
        assert not k.startswith("regression"), k


def test_oocdist_section_error_never_gates(tmp_path):
    out = {"metric": METRIC, "value": 0.10,
           "ooc_distributed": {"error": "RuntimeError: fleet failed"}}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert "gate_oocdist" not in out
    assert "regression_oocdist_parity" not in out


def test_comms_wall_gate_against_prior(tmp_path):
    _capture(tmp_path, "BENCH_r01.json", 0.10, comms=_comms(data_s=1.0))
    out = {"metric": METRIC, "value": 0.10,
           "comms": _comms(data_s=1.2)}  # 20% slower: over the band
    rc = bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={})
    assert rc == 1
    assert out["regression_comms_wall"] is True
    assert out["gate_comms_wall"]["data"]["best_prior_s_per_iter"] == 1.0
    # within the 1.10 band passes
    out = {"metric": METRIC, "value": 0.10, "comms": _comms(data_s=1.05)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert "regression_comms_wall" not in out


def test_comms_wall_gate_requires_same_grid(tmp_path):
    # a prior at another (rows, features, ranks) grid is not comparable,
    # and fallback priors are never a wall-clock baseline
    _capture(tmp_path, "BENCH_r01.json", 0.10,
             comms=_comms(features=500, data_s=0.01))
    _capture(tmp_path, "BENCH_r02.json", 0.10,
             comms=_comms(data_s=0.01), backend_fallback=True)
    out = {"metric": METRIC, "value": 0.10, "comms": _comms(data_s=9.9)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert "gate_comms_wall" not in out and "regression_comms_wall" not in out


def test_comms_section_error_never_gates(tmp_path):
    out = {"metric": METRIC, "value": 0.10,
           "comms": {"error": "RuntimeError: boom",
                     "voting_vs_data_payload_ratio": 0.1}}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    assert "gate_comms" not in out


def test_comms_opt_out(tmp_path):
    out = {"metric": METRIC, "value": 0.10, "comms": _comms(ratio=0.1)}
    rc = bench.apply_regression_gate(out, bench_dir=str(tmp_path),
                                     env={"BENCH_GATE": "0"})
    assert rc == 0 and "gate_comms" not in out


# ----------------------------------------------------------------------
# multi-model leg
# ----------------------------------------------------------------------
def test_multimodel_admission_gate(tmp_path):
    out = {"metric": METRIC, "value": 0.10,
           "multimodel": {"n_models": 4, "admission_refusal_ok": False}}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 1
    assert out["regression_multimodel_admission"] is True
    out = {"metric": METRIC, "value": 0.10,
           "multimodel": {"n_models": 4, "admission_refusal_ok": True}}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0
    out = {"metric": METRIC, "value": 0.10,
           "multimodel": {"error": "RuntimeError: boom",
                          "admission_refusal_ok": False}}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={}) == 0


# ----------------------------------------------------------------------
# spot-economics leg
# ----------------------------------------------------------------------
def _spot(ratio=0.4, zero_lost=True):
    return {"rows": 600, "trees": 16, "members": 2,
            "cost_ratio_spot_vs_static": ratio,
            "zero_lost_iterations": zero_lost}


def test_spot_gate_fires_on_lost_iterations(tmp_path):
    """Losing a completed iteration to churn voids the elastic premise:
    the leg gates OUTRIGHT, priors or not, fallback or not."""
    out = {"metric": METRIC, "value": 0.10, "backend_fallback": True,
           "spot": _spot(zero_lost=False)}
    rc = bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={})
    assert rc == 1
    assert out["regression_spot_lost_iterations"] is True
    assert out["gate_spot"]["require_zero_lost_iterations"] is True


def test_spot_gate_fires_on_cost_above_static(tmp_path):
    out = {"metric": METRIC, "value": 0.10, "spot": _spot(ratio=0.95)}
    rc = bench.apply_regression_gate(out, bench_dir=str(tmp_path), env={})
    assert rc == 1
    assert out["regression_spot_cost"] is True
    assert out["gate_spot"]["max_cost_ratio_spot_vs_static"] == 0.8


def test_spot_gate_passes_on_cheap_clean_run(tmp_path):
    out = {"metric": METRIC, "value": 0.10, "spot": _spot()}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path),
                                       env={}) == 0
    assert "regression_spot_cost" not in out
    assert "regression_spot_lost_iterations" not in out
    assert out["gate_spot"]["cost_ratio_spot_vs_static"] == 0.4


def test_spot_section_error_never_gates(tmp_path):
    out = {"metric": METRIC, "value": 0.10,
           "spot": {"error": "RuntimeError: boom",
                    "zero_lost_iterations": False,
                    "cost_ratio_spot_vs_static": 9.9}}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path),
                                       env={}) == 0
    assert "gate_spot" not in out


def _serving_tail(hedged_ratio=1.7):
    return {
        "requests_per_leg": 90, "injected_delay_ms": 300.0,
        "hedge_delay_ms": 25.0, "gate_floor_ms": 20.0,
        "healthy_p99_ms": 8.1, "unhedged_chaos_p99_ms": 305.0,
        "hedged_chaos_p99_ms": round(20.0 * hedged_ratio, 2),
        "unhedged_chaos_over_healthy_p99": 15.25,
        "hedged_chaos_over_healthy_p99": hedged_ratio,
        "hedges_launched": 3, "hedge_wins": 3,
    }


def test_serving_tail_gate_fires_without_prior(tmp_path):
    """Hedged p99 under an injected-delay replica must stay <= 3x the
    healthy baseline; the contract is protocol-level, so the leg gates
    outright with no prior capture."""
    out = {"metric": METRIC, "value": 0.10,
           "serving_tail": _serving_tail(hedged_ratio=4.2)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path),
                                       env={}) == 1
    assert out["regression_serving_tail"] is True
    assert out["gate_serving_tail"][
        "max_hedged_chaos_over_healthy_p99"] == 3.0
    assert out["gate_serving_tail"][
        "hedged_chaos_over_healthy_p99"] == pytest.approx(4.2)


def test_serving_tail_gate_is_device_independent(tmp_path):
    # the ratio gates even on a backend_fallback capture that skips
    # every wall-clock gate (the injected delay dominates any backend)
    out = {"metric": METRIC, "value": 9.9, "backend_fallback": True,
           "serving_tail": _serving_tail(hedged_ratio=3.5)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path),
                                       env={}) == 1
    assert out["regression_serving_tail"] is True
    assert "regression" not in out  # headline leg still skipped
    out = {"metric": METRIC, "value": 9.9, "backend_fallback": True,
           "serving_tail": _serving_tail(hedged_ratio=1.5)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path),
                                       env={}) == 0
    assert "gate_serving_tail" in out


def test_serving_tail_gate_passes(tmp_path):
    out = {"metric": METRIC, "value": 0.10,
           "serving_tail": _serving_tail(hedged_ratio=1.66)}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path),
                                       env={}) == 0
    assert out["gate_serving_tail"][
        "hedged_chaos_over_healthy_p99"] == pytest.approx(1.66)
    for k in list(out):
        assert not k.startswith("regression"), k


def test_serving_tail_section_error_never_gates(tmp_path):
    out = {"metric": METRIC, "value": 0.10,
           "serving_tail": {"error": "RuntimeError: replica never ready",
                            "hedged_chaos_over_healthy_p99": 9.9}}
    assert bench.apply_regression_gate(out, bench_dir=str(tmp_path),
                                       env={}) == 0
    assert "gate_serving_tail" not in out
    assert "regression_serving_tail" not in out
