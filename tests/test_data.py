"""Out-of-core streaming ingest (lightgbm_tpu/data/).

Acceptance contract (ISSUE 3): a Dataset streamed in chunks is
bit-identical to the in-memory construction of the same file — BinMapper
bounds, packed bin matrix, and the trained model string — under the same
bin_construct_sample_cnt sample.  Tier-1 runs the small-chunk
(chunk_rows~1k) configuration; the multi-GB stress lives behind the
``slow`` marker.
"""

import hashlib
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.basic import Dataset
from lightgbm_tpu.config import Config
from lightgbm_tpu.data.ingest import should_stream, stream_dataset
from lightgbm_tpu.data.reader import DenseChunkReader, LibSVMChunkReader
from lightgbm_tpu.data.sketch import (
    CategoricalSketch,
    GKSketch,
    NumericSketch,
    merge_sketch_lists,
)
from lightgbm_tpu.data.stats import (
    SampleCollector,
    SketchCollector,
    mappers_from_sketches,
)
from lightgbm_tpu.io.binning import BinMapper
from lightgbm_tpu.io.parser import load_text_file


# ----------------------------------------------------------------------
# file fixtures
# ----------------------------------------------------------------------
def _write_csv(path, X, y, header=False, weight=None, gid=None, fmt="%.8g"):
    cols = [np.asarray(y, np.float64)]
    names = ["lab"]
    if weight is not None:
        cols.append(np.asarray(weight, np.float64))
        names.append("wt")
    if gid is not None:
        cols.append(np.asarray(gid, np.float64))
        names.append("qid")
    for i in range(X.shape[1]):
        cols.append(np.asarray(X[:, i], np.float64))
        names.append(f"f{i}")
    mat = np.column_stack(cols)
    with open(path, "w") as f:
        if header:
            f.write(",".join(names) + "\n")
        for row in mat:
            f.write(",".join("" if np.isnan(v) else (fmt % v) for v in row) + "\n")
    return names


def _binary_problem(n=5000, f=6, seed=0, with_nan=True):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    X[:, f - 1] = rng.randint(0, 7, n)  # low-cardinality / tie-heavy
    X[rng.rand(n) < 0.02, 1] = 0.0      # exact zeros hit the zero-bin path
    if with_nan:
        X[rng.rand(n) < 0.03, 2] = np.nan
    w = rng.randn(f)
    logits = np.nansum(X[:, :4] * w[:4], axis=1)
    y = (rng.rand(n) < 1 / (1 + np.exp(-logits))).astype(np.float32)
    return X, y


def _assert_mappers_equal(a, b):
    assert len(a) == len(b)
    for ma, mb in zip(a, b):
        assert ma.num_bin == mb.num_bin
        assert ma.bin_type == mb.bin_type
        assert ma.is_trivial == mb.is_trivial
        assert ma.default_bin == mb.default_bin
        np.testing.assert_array_equal(ma.bin_upper_bound, mb.bin_upper_bound)
        np.testing.assert_array_equal(ma.bin_2_categorical, mb.bin_2_categorical)


# ----------------------------------------------------------------------
# chunked readers
# ----------------------------------------------------------------------
class TestChunkedReader:
    def test_chunk_boundaries_do_not_change_values(self, tmp_path):
        X, y = _binary_problem(n=2000)
        p = str(tmp_path / "d.csv")
        _write_csv(p, X, y)
        one = DenseChunkReader(p, ",", False, chunk_rows=10**9).read_all()[0]
        chunks = list(DenseChunkReader(p, ",", False, chunk_rows=137).iter_chunks())
        assert len(chunks) == -(-2000 // 137)
        many = np.vstack([c for _, c in chunks])
        np.testing.assert_array_equal(one, many)
        starts = [s for s, _ in chunks]
        assert starts == [i * 137 for i in range(len(chunks))]

    def test_count_rows_skips_blank_and_header(self, tmp_path):
        p = tmp_path / "d.csv"
        p.write_text("a,b\n1,2\n\n3,4\n   \n5,6\n")
        r = DenseChunkReader(str(p), ",", True)
        assert r.count_rows() == 3
        assert r.header_names == ["a", "b"]
        mat, names = r.read_all()
        np.testing.assert_array_equal(mat, [[1, 2], [3, 4], [5, 6]])

    def test_libsvm_width_grows_across_chunks(self, tmp_path):
        p = tmp_path / "d.svm"
        # later lines reference higher feature indices than earlier ones
        p.write_text("1 0:1.5\n0 1:2.5\n1 4:3.5\n0 2:0.5\n")
        r = LibSVMChunkReader(str(p), chunk_rows=2)
        feats, labels = r.read_all()
        assert feats.shape == (4, 5)
        assert r.ncols_seen == 5
        np.testing.assert_array_equal(labels, [1, 0, 1, 0])
        assert feats[2, 4] == 3.5 and feats[0, 0] == 1.5


# ----------------------------------------------------------------------
# sketches
# ----------------------------------------------------------------------
class TestSketches:
    def test_numeric_exact_matches_unique(self):
        rng = np.random.RandomState(1)
        col = rng.randint(0, 50, 3000).astype(np.float64)
        sk = NumericSketch(cap=1000)
        for lo in range(0, 3000, 250):
            sk.update(col[lo : lo + 250])
        assert not sk.spilled
        vals, cnts = sk.to_distinct_counts()
        ref = col[col != 0.0]
        rv, rc = np.unique(ref, return_counts=True)
        np.testing.assert_array_equal(vals, rv)
        np.testing.assert_array_equal(cnts, rc)
        assert sk.zero_cnt == int((col == 0.0).sum())
        assert sk.total_cnt == 3000

    def test_numeric_merge_order_independent_exact(self):
        rng = np.random.RandomState(2)
        cols = [rng.randint(0, 30, 500).astype(np.float64) for _ in range(4)]
        def build(order):
            sks = []
            for c in order:
                s = NumericSketch(cap=10_000)
                s.update(c)
                sks.append([s])
            return merge_sketch_lists(sks)[0].to_distinct_counts()
        v1, c1 = build(cols)
        v2, c2 = build(cols[::-1])
        np.testing.assert_array_equal(v1, v2)
        np.testing.assert_array_equal(c1, c2)

    def test_numeric_spill_bounds_memory_and_rank_error(self):
        rng = np.random.RandomState(3)
        n = 60_000
        col = rng.randn(n)
        sk = NumericSketch(cap=512, eps=0.01)
        for lo in range(0, n, 5000):
            sk.update(col[lo : lo + 5000])
        assert sk.spilled
        # summary stays small
        assert len(sk.gk.vals) < 5000
        vals, cnts = sk.to_distinct_counts()
        assert int(cnts.sum()) == n  # no mass lost
        # rank error of the implied CDF within a few eps*n
        order = np.sort(col)
        for q in (0.1, 0.25, 0.5, 0.75, 0.9):
            est = sk.gk.quantile(q)
            true_rank = np.searchsorted(order, est) / n
            assert abs(true_rank - q) < 5 * 0.01, (q, est, true_rank)

    def test_gk_merge_mass_conserved(self):
        rng = np.random.RandomState(4)
        a, b = GKSketch(eps=0.02), GKSketch(eps=0.02)
        xa, xb = rng.randn(5000), rng.randn(7000) + 1.0
        va, ca = np.unique(xa, return_counts=True)
        vb, cb = np.unique(xb, return_counts=True)
        a.insert_batch(va, ca)
        b.insert_batch(vb, cb)
        a.merge(b)
        assert a.n == 12000
        _, g = a.to_distinct_counts()
        assert int(g.sum()) == 12000
        med = a.quantile(0.5)
        true = np.median(np.concatenate([xa, xb]))
        order = np.sort(np.concatenate([xa, xb]))
        rank = np.searchsorted(order, med) / 12000
        assert abs(rank - 0.5) < 0.1, (med, true)

    def test_categorical_exact_and_mg_undercount_bound(self):
        rng = np.random.RandomState(5)
        col = rng.zipf(1.5, 5000).astype(np.float64)
        col[col > 1000] = 1000
        sk = CategoricalSketch(cap=32)
        for lo in range(0, 5000, 500):
            sk.update(col[lo : lo + 500])
        vals, cnts = sk.to_distinct_counts()
        true = {int(v): int(c) for v, c in
                zip(*np.unique(col.astype(np.int64), return_counts=True))}
        # Misra-Gries: surviving counters undercount by at most `error`
        for v, c in zip(vals.astype(np.int64), cnts):
            assert true[int(v)] >= c
            assert true[int(v)] - c <= sk.error

    def test_exact_sketch_mappers_bit_identical_to_find_bin(self):
        rng = np.random.RandomState(6)
        X = np.column_stack([
            rng.randn(4000),
            rng.randint(0, 40, 4000).astype(np.float64),
            np.where(rng.rand(4000) < 0.3, 0.0, rng.randn(4000)),
        ])
        cfg = Config.from_params({"max_bin": 63, "min_data_in_leaf": 1})
        coll = SketchCollector(cap=100_000)
        for lo in range(0, 4000, 333):
            coll.update(X[lo : lo + 333])
        sk_mappers = mappers_from_sketches(coll, 4000, cfg)
        direct = []
        for f in range(X.shape[1]):
            col = X[:, f]
            col = col[~np.isnan(col)]
            m = BinMapper()
            m.find_bin(col[col != 0.0], 4000, cfg.max_bin,
                       cfg.min_data_in_bin, cfg.min_data_in_leaf)
            direct.append(m)
        _assert_mappers_equal(sk_mappers, direct)

    def test_sample_collector_matches_fancy_indexing(self):
        rng = np.random.RandomState(7)
        data = rng.randn(1000, 4)
        idx = np.sort(rng.choice(1000, 200, replace=False))
        c = SampleCollector(idx, ncols=4)
        for lo in range(0, 1000, 90):
            c.offer(lo, data[lo : lo + 90])
        np.testing.assert_array_equal(c.finish(), data[idx])


# ----------------------------------------------------------------------
# streaming <-> in-memory parity (the tier-1 small-chunk configuration)
# ----------------------------------------------------------------------
class TestStreamingParity:
    def _construct_both(self, path, params=None, chunk_rows=1000, **dskw):
        cfg_params = dict(params or {})
        os.environ.pop("LIGHTGBM_TPU_STREAM_CHUNK_ROWS", None)
        os.environ["LIGHTGBM_TPU_STREAM_INGEST"] = "0"
        try:
            mem = Dataset(path, params=dict(cfg_params), **dskw).construct()
        finally:
            os.environ.pop("LIGHTGBM_TPU_STREAM_INGEST", None)
        os.environ["LIGHTGBM_TPU_STREAM_INGEST"] = "1"
        os.environ["LIGHTGBM_TPU_STREAM_CHUNK_ROWS"] = str(chunk_rows)
        try:
            stream = Dataset(path, params=dict(cfg_params), **dskw).construct()
        finally:
            os.environ.pop("LIGHTGBM_TPU_STREAM_INGEST", None)
            os.environ.pop("LIGHTGBM_TPU_STREAM_CHUNK_ROWS", None)
        return mem, stream

    def test_csv_bit_identical(self, tmp_path):
        X, y = _binary_problem()
        p = str(tmp_path / "d.csv")
        _write_csv(p, X, y)
        mem, stream = self._construct_both(p, {"max_bin": 63})
        assert getattr(stream, "ingest_report", {}).get("chunks_pass2", 0) > 3
        _assert_mappers_equal(mem.bin_mappers, stream.bin_mappers)
        np.testing.assert_array_equal(mem.used_feature_map, stream.used_feature_map)
        np.testing.assert_array_equal(mem.binned, stream.binned)
        np.testing.assert_array_equal(mem.metadata.label, stream.metadata.label)
        assert mem.feature_names == stream.feature_names
        assert mem.num_total_features == stream.num_total_features

    def test_header_weight_group_columns(self, tmp_path):
        X, y = _binary_problem(n=3000)
        rng = np.random.RandomState(8)
        w = rng.rand(3000) + 0.5
        gid = np.sort(rng.randint(0, 50, 3000))
        p = str(tmp_path / "d.csv")
        _write_csv(p, X, y, header=True, weight=w, gid=gid)
        params = {"has_header": True, "label_column": "name:lab",
                  "weight_column": "name:wt", "group_column": "name:qid"}
        mem, stream = self._construct_both(p, params)
        np.testing.assert_array_equal(mem.binned, stream.binned)
        np.testing.assert_array_equal(mem.metadata.label, stream.metadata.label)
        np.testing.assert_array_equal(mem.metadata.weights, stream.metadata.weights)
        np.testing.assert_array_equal(
            mem.metadata.query_boundaries, stream.metadata.query_boundaries
        )
        assert mem.feature_names == stream.feature_names

    def test_side_files(self, tmp_path):
        X, y = _binary_problem(n=1500)
        p = str(tmp_path / "d.csv")
        _write_csv(p, X, y)
        rng = np.random.RandomState(9)
        np.savetxt(p + ".weight", rng.rand(1500) + 0.5, fmt="%.6g")
        with open(p + ".query", "w") as f:
            f.write("700\n800\n")
        mem, stream = self._construct_both(p)
        np.testing.assert_array_equal(mem.metadata.weights, stream.metadata.weights)
        np.testing.assert_array_equal(
            mem.metadata.query_boundaries, stream.metadata.query_boundaries
        )

    def test_libsvm_bit_identical(self, tmp_path):
        rng = np.random.RandomState(10)
        p = str(tmp_path / "d.svm")
        with open(p, "w") as f:
            for i in range(2500):
                y = rng.randint(0, 2)
                nnz = rng.randint(1, 6)
                idx = np.sort(rng.choice(12, nnz, replace=False))
                pairs = " ".join(f"{j}:{rng.randn():.6g}" for j in idx)
                f.write(f"{y} {pairs}\n")
        mem, stream = self._construct_both(p, {"max_bin": 31}, chunk_rows=200)
        _assert_mappers_equal(mem.bin_mappers, stream.bin_mappers)
        np.testing.assert_array_equal(mem.binned, stream.binned)
        np.testing.assert_array_equal(mem.metadata.label, stream.metadata.label)

    def test_trained_model_hash_identical_50k(self, tmp_path):
        """The end-to-end acceptance check: the model TRAINED from a
        streamed ~50k-row dataset is byte-identical to one trained from
        the in-memory load of the same file."""
        X, y = _binary_problem(n=50_000, f=8, seed=11)
        p = str(tmp_path / "big.csv")
        _write_csv(p, X, y)
        params = {"objective": "binary", "num_leaves": 31, "max_bin": 63,
                  "learning_rate": 0.1, "min_data_in_leaf": 20, "verbose": -1}
        hashes = {}
        for mode, chunk in (("0", None), ("1", 4096)):
            os.environ["LIGHTGBM_TPU_STREAM_INGEST"] = mode
            if chunk:
                os.environ["LIGHTGBM_TPU_STREAM_CHUNK_ROWS"] = str(chunk)
            try:
                ds = lgb.Dataset(p, params=dict(params))
                bst = lgb.train(dict(params), ds, num_boost_round=5)
                hashes[mode] = hashlib.sha256(
                    bst.model_to_string().encode()
                ).hexdigest()
            finally:
                os.environ.pop("LIGHTGBM_TPU_STREAM_INGEST", None)
                os.environ.pop("LIGHTGBM_TPU_STREAM_CHUNK_ROWS", None)
        assert hashes["0"] == hashes["1"]

    def test_valid_set_streams_with_reference_mappers(self, tmp_path):
        X, y = _binary_problem(n=4000, seed=12)
        ptr = str(tmp_path / "train.csv")
        pva = str(tmp_path / "valid.csv")
        _write_csv(ptr, X[:3000], y[:3000])
        _write_csv(pva, X[3000:], y[3000:])
        params = {"objective": "binary", "max_bin": 63, "verbose": -1}
        os.environ["LIGHTGBM_TPU_STREAM_INGEST"] = "1"
        os.environ["LIGHTGBM_TPU_STREAM_CHUNK_ROWS"] = "500"
        try:
            dtr = lgb.Dataset(ptr, params=dict(params))
            dva = dtr.create_valid(pva)
            binned_tr = dtr.construct()
            binned_va = dva.construct()
        finally:
            os.environ.pop("LIGHTGBM_TPU_STREAM_INGEST", None)
            os.environ.pop("LIGHTGBM_TPU_STREAM_CHUNK_ROWS", None)
        # valid set must share the TRAIN mappers (CreateValid contract)
        assert binned_va.bin_mappers is binned_tr.bin_mappers
        # and bin with them exactly like the in-memory align path
        os.environ["LIGHTGBM_TPU_STREAM_INGEST"] = "0"
        try:
            dtr2 = lgb.Dataset(ptr, params=dict(params))
            ref = dtr2.construct()
            va2 = dtr2.create_valid(pva).construct()
        finally:
            os.environ.pop("LIGHTGBM_TPU_STREAM_INGEST", None)
        np.testing.assert_array_equal(binned_va.binned, va2.binned)

    def test_raw_matrix_not_materialized(self, tmp_path):
        """The Dataset object never holds the raw float matrix on the
        streaming path (peak-memory contract; the full-scale RSS bound
        is asserted in the slow test / bench ingest section)."""
        X, y = _binary_problem(n=2000)
        p = str(tmp_path / "d.csv")
        _write_csv(p, X, y)
        os.environ["LIGHTGBM_TPU_STREAM_INGEST"] = "1"
        try:
            d = Dataset(p)
            binned = d.construct()
        finally:
            os.environ.pop("LIGHTGBM_TPU_STREAM_INGEST", None)
        assert d.data is None
        assert binned.ingest_report["streamed"] is True


# ----------------------------------------------------------------------
# routing / gating
# ----------------------------------------------------------------------
class TestShouldStream:
    def test_env_forces(self, tmp_path, monkeypatch):
        p = tmp_path / "d.csv"
        p.write_text("1,2\n3,4\n")
        cfg = Config()
        monkeypatch.setenv("LIGHTGBM_TPU_STREAM_INGEST", "1")
        assert should_stream(str(p), cfg)
        monkeypatch.setenv("LIGHTGBM_TPU_STREAM_INGEST", "0")
        assert not should_stream(str(p), cfg)

    def test_auto_threshold(self, tmp_path, monkeypatch):
        p = tmp_path / "d.csv"
        p.write_text("1,2\n" * 4000)  # ~16 KB
        cfg = Config()
        monkeypatch.delenv("LIGHTGBM_TPU_STREAM_INGEST", raising=False)
        assert not should_stream(str(p), cfg)  # far below auto threshold
        monkeypatch.setenv("LIGHTGBM_TPU_STREAM_INGEST", "0.001")  # 1 KB
        assert should_stream(str(p), cfg)

    def test_two_round_loading_forces_streaming(self, tmp_path, monkeypatch):
        p = tmp_path / "d.csv"
        p.write_text("1,2\n3,4\n")
        monkeypatch.delenv("LIGHTGBM_TPU_STREAM_INGEST", raising=False)
        cfg = Config.from_params({"use_two_round_loading": True})
        assert should_stream(str(p), cfg)

    def test_config_param_surface(self, tmp_path, monkeypatch):
        p = tmp_path / "d.csv"
        p.write_text("1,2\n3,4\n")
        monkeypatch.delenv("LIGHTGBM_TPU_STREAM_INGEST", raising=False)
        cfg = Config.from_params({"stream_ingest": "true"})
        assert should_stream(str(p), cfg)
        cfg = Config.from_params({"stream_ingest": "false",
                                  "use_two_round_loading": True})
        assert not should_stream(str(p), cfg)


class TestIngestCLI:
    def test_task_ingest_writes_loadable_binary(self, tmp_path, monkeypatch):
        from lightgbm_tpu.cli import main as cli_main
        from lightgbm_tpu.io.dataset import BinnedDataset

        X, y = _binary_problem(n=1200)
        p = str(tmp_path / "d.csv")
        _write_csv(p, X, y)
        monkeypatch.setenv("LIGHTGBM_TPU_STREAM_CHUNK_ROWS", "250")
        assert cli_main(["task=ingest", f"data={p}", "max_bin=63"]) == 0
        cache = p + ".bin"
        assert BinnedDataset.is_binary_cache(cache)
        ds = Dataset(cache).construct()
        os.environ["LIGHTGBM_TPU_STREAM_INGEST"] = "0"
        try:
            ref = Dataset(p, params={"max_bin": 63}).construct()
        finally:
            os.environ.pop("LIGHTGBM_TPU_STREAM_INGEST", None)
        np.testing.assert_array_equal(ds.binned, ref.binned)
        np.testing.assert_array_equal(ds.metadata.label, ref.metadata.label)

    def test_ingest_trace_records(self, tmp_path, monkeypatch):
        """Ingest spans/counters/gauges land in the obs trace and the
        report CLI surfaces the ingest section."""
        from lightgbm_tpu.obs import tracer
        from lightgbm_tpu.obs.report import load_trace, summarize

        X, y = _binary_problem(n=1500)
        p = str(tmp_path / "d.csv")
        _write_csv(p, X, y)
        trace = str(tmp_path / "trace.jsonl")
        tracer.configure(trace)
        try:
            stream_dataset(p, Config(), chunk_rows=300)
        finally:
            tracer.close()
            tracer.path = None
        records = load_trace(trace)
        spans = {r["name"] for r in records if r.get("ev") == "span"}
        assert {"ingest.pass0_count", "ingest.pass1_stats",
                "ingest.find_bin", "ingest.pass2_bin"} <= spans
        assert any(r.get("ev") == "counter" and r["name"] == "ingest.chunks"
                   for r in records)
        assert any(r.get("ev") == "gauge" and r["name"] == "ingest.host_rss_mb"
                   for r in records)
        summary = summarize(records)
        assert summary["ingest"]["rows"] == 1500
        assert summary["ingest"]["chunks_pass2"] == 5


# ----------------------------------------------------------------------
# multi-GB stress: out of tier-1 (slow marker)
# ----------------------------------------------------------------------
@pytest.mark.slow
def test_large_ingest_memory_bound(tmp_path):
    """Streaming a large synthetic file keeps peak RSS near the packed
    matrix + O(chunk) — the raw float matrix (8x larger) never exists.
    SLOW_INGEST_ROWS=10500000 reproduces the Higgs-scale entry."""
    rows = int(os.environ.get("SLOW_INGEST_ROWS", 2_000_000))
    f = 28
    rng = np.random.RandomState(0)
    p = str(tmp_path / "big.csv")
    with open(p, "w") as fh:
        for lo in range(0, rows, 100_000):
            k = min(100_000, rows - lo)
            X = rng.randn(k, f).astype(np.float32)
            y = (rng.rand(k) < 0.5).astype(np.float32)
            block = np.column_stack([y, X])
            fh.write("\n".join(
                ",".join("%.6g" % v for v in r) for r in block
            ) + "\n")
    os.environ["LIGHTGBM_TPU_STREAM_INGEST"] = "1"
    try:
        ds = Dataset(p, params={"max_bin": 63}).construct()
    finally:
        os.environ.pop("LIGHTGBM_TPU_STREAM_INGEST", None)
    rep = ds.ingest_report
    assert rep["rows"] == rows
    chunk_raw_mb = rep["chunk_rows"] * (f + 1) * 8 / 1e6
    raw_mb = rows * (f + 1) * 8 / 1e6
    increase = rep["rss_peak_mb"] - rep["rss_start_mb"]
    bound = rep["packed_mb"] + 8 * chunk_raw_mb + 256
    assert increase <= bound, (increase, bound)
    assert bound < raw_mb  # the bound itself rules out the raw matrix


class TestBadRowPolicy:
    """ISSUE-5 satellite: malformed/ragged rows fail loudly naming the
    file and data-row number under bad_row_policy='error' (the default),
    are dropped-and-counted under 'skip', and the clean-file path stays
    bit-identical under both policies."""

    def _write(self, tmp_path, rows, name="d.tsv"):
        p = str(tmp_path / name)
        with open(p, "w") as f:
            f.write("\n".join(rows) + "\n")
        return p

    def _clean_rows(self, n=400, f=5, seed=11):
        rng = np.random.RandomState(seed)
        X = rng.randn(n, f)
        y = (X[:, 0] > 0).astype(int)
        return ["\t".join([f"{y[i]:d}"] + [f"{v:.6g}" for v in X[i]])
                for i in range(n)]

    def test_error_policy_names_file_and_row(self, tmp_path):
        from lightgbm_tpu.utils.log import LightGBMError

        rows = self._clean_rows(40)
        rows.insert(7, rows[0] + "\t9.9")  # ragged: extra field at row 8
        path = self._write(tmp_path, rows)
        with pytest.raises(LightGBMError) as ei:
            DenseChunkReader(path, "\t", False).read_all()
        msg = str(ei.value)
        assert path in msg and "row 8" in msg and "bad_row_policy" in msg

    def test_skip_policy_drops_and_counts(self, tmp_path):
        rows = self._clean_rows(60)
        clean_path = self._write(tmp_path, rows, "clean.tsv")
        ref, _ = DenseChunkReader(clean_path, "\t", False).read_all()
        dirty = list(rows)
        dirty.insert(5, "1\tgarbage\t2\t3\t4\t5")     # unparsable token
        dirty.insert(20, rows[0] + "\t1\t2")          # extra fields
        dirty_path = self._write(tmp_path, dirty, "dirty.tsv")
        r = DenseChunkReader(dirty_path, "\t", False, bad_row_policy="skip")
        got, _ = r.read_all()
        assert r.bad_rows == 2
        np.testing.assert_array_equal(got, ref)  # exactly the clean rows

    def test_clean_file_bit_identical_under_both_policies(self, tmp_path):
        path = self._write(tmp_path, self._clean_rows(300))
        a, _ = DenseChunkReader(path, "\t", False).read_all()
        r = DenseChunkReader(path, "\t", False, bad_row_policy="skip")
        b, _ = r.read_all()
        np.testing.assert_array_equal(a, b)
        assert r.bad_rows == 0

    def test_streaming_ingest_skips_and_trims(self, tmp_path):
        rows = self._clean_rows(500)
        dirty = list(rows)
        dirty.insert(100, "nope\tnope")
        dirty.insert(300, rows[1] + "\textra")
        path = self._write(tmp_path, dirty)
        cfg = Config.from_params({"bad_row_policy": "skip", "verbose": -1})
        ds = stream_dataset(path, cfg, chunk_rows=128)
        assert ds.num_data == 500
        assert ds.ingest_report["bad_rows"] == 2
        assert ds.ingest_report["rows"] == 500
        # error policy on the same file names the first bad row
        from lightgbm_tpu.utils.log import LightGBMError

        cfg_err = Config.from_params({"verbose": -1})
        with pytest.raises(LightGBMError, match="row 101"):
            stream_dataset(path, cfg_err, chunk_rows=128)

    def test_streaming_skip_trains_and_matches_clean_rows(self, tmp_path):
        """The surviving rows bin and train exactly like a file that
        never had the bad rows (same rows -> same packed matrix)."""
        rows = self._clean_rows(500)
        clean = self._write(tmp_path, rows, "c.tsv")
        dirty = list(rows)
        dirty.insert(250, "xx\tyy\tzz")
        dirty_p = self._write(tmp_path, dirty, "d.tsv")
        cfg = Config.from_params({"bad_row_policy": "skip", "verbose": -1})
        ds_clean = stream_dataset(clean, cfg, chunk_rows=64)
        ds_dirty = stream_dataset(dirty_p, cfg, chunk_rows=64)
        np.testing.assert_array_equal(ds_dirty.binned, ds_clean.binned)
        np.testing.assert_array_equal(
            np.asarray(ds_dirty.metadata.label),
            np.asarray(ds_clean.metadata.label),
        )

    def test_libsvm_policies(self, tmp_path):
        from lightgbm_tpu.utils.log import LightGBMError

        rng = np.random.RandomState(3)
        rows = []
        for i in range(50):
            feats = " ".join(f"{j}:{rng.randn():.4g}" for j in range(4))
            rows.append(f"{i % 2} {feats}")
        clean = self._write(tmp_path, rows, "c.svm")
        ref_X, ref_y = LibSVMChunkReader(clean).read_all()
        dirty = list(rows)
        dirty.insert(9, "1 0:1.5 broken_token 2:2.0")
        path = self._write(tmp_path, dirty, "d.svm")
        with pytest.raises(LightGBMError) as ei:
            LibSVMChunkReader(path).read_all()
        assert "row 10" in str(ei.value)
        r = LibSVMChunkReader(path, bad_row_policy="skip")
        X, y = r.read_all()
        assert r.bad_rows == 1
        np.testing.assert_array_equal(X, ref_X)
        np.testing.assert_array_equal(y, ref_y)

    def test_obs_counter_counts_skips(self, tmp_path, monkeypatch):
        from lightgbm_tpu.obs import tracer

        rows = self._clean_rows(50)
        rows.insert(3, "bad\trow")
        path = self._write(tmp_path, rows)
        trace_path = str(tmp_path / "trace.jsonl")
        tracer.configure(trace_path)
        try:
            r = DenseChunkReader(path, "\t", False, bad_row_policy="skip")
            r.read_all()
        finally:
            tracer.close()
        import json as _json

        recs = [_json.loads(l) for l in open(trace_path)]
        hits = [r for r in recs
                if r["ev"] == "counter" and r["name"] == "data.bad_rows"]
        assert hits and hits[0]["value"] == 1
