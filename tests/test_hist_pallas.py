"""Parity suite for the packed-bin Pallas histogram kernels
(ops/histogram_pallas.py) against the pure-XLA fallback
(ops/histogram.build_histogram), runnable in interpret mode under tier-1.

Covers the edge shapes the tile machinery can get wrong: bin counts that
are not a multiple of the 128-lane tile, single-feature matrices,
zero-gradient rows, empty/unaligned segments, and the multi-leaf
``hist_segments`` variant (one launch covering every active leaf of a
level).  Also pins the ``tune_fchunk`` autotuner contract — including
that fchunk is bit-INVARIANT (it only groups which cells share a
dot_general, never the per-cell contraction order).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops import histogram_pallas as hp
from lightgbm_tpu.ops import pkernels as pk
from lightgbm_tpu.ops.histogram import build_histogram

INTERP = jax.default_backend() != "tpu"
# interpret-mode bf16 emulation is coarser than the TPU MXU path
TOL = 2e-3 if INTERP else 1e-5


def _data(n, f, b, seed=0, zero_grad_frac=0.0):
    rng = np.random.default_rng(seed)
    bins = rng.integers(0, b, size=(n, f), dtype=np.uint8)
    g = rng.standard_normal(n).astype(np.float32)
    h = np.abs(rng.standard_normal(n)).astype(np.float32)
    sel = (rng.random(n) < 0.85).astype(np.float32)
    if zero_grad_frac:
        z = rng.random(n) < zero_grad_frac
        g[z] = 0.0
        h[z] = 0.0
    return bins, g, h, sel


def _ref(bins, g, h, sel, b, lo, hi):
    return np.asarray(build_histogram(
        jnp.asarray(bins[lo:hi]), jnp.asarray(g[lo:hi]), jnp.asarray(h[lo:hi]),
        jnp.asarray(sel[lo:hi]), b,
    ))


def _relerr(got, want):
    return np.abs(np.asarray(got) - want).max() / max(np.abs(want).max(), 1.0)


class TestHistSegment:
    @pytest.mark.parametrize(
        "n,f,b,lo,hi",
        [
            (4096, 11, 32, 100, 3000),
            (2048, 7, 33, 0, 2048),     # bin count not a tile multiple
            (2048, 5, 63, 17, 1951),    # the bench max_bin shape
            (1024, 1, 32, 3, 1000),     # single feature
            (1024, 3, 17, 0, 7),        # tiny segment, odd bin count
            (1024, 3, 32, 500, 500),    # empty segment
        ],
    )
    def test_matches_xla_fallback(self, n, f, b, lo, hi):
        bins, g, h, sel = _data(n, f, b)
        P = hp.pack_columns(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                            jnp.asarray(sel))
        got = hp.hist_segment(P, jnp.int32(lo), jnp.int32(hi), f, b,
                              interpret=INTERP)
        want = _ref(bins, g, h, sel, b, lo, hi)
        assert _relerr(got, want) < TOL

    def test_zero_gradient_rows(self):
        n, f, b = 2048, 6, 32
        bins, g, h, sel = _data(n, f, b, seed=5, zero_grad_frac=0.5)
        P = hp.pack_columns(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                            jnp.asarray(sel))
        got = hp.hist_segment(P, jnp.int32(0), jnp.int32(n), f, b,
                              interpret=INTERP)
        want = _ref(bins, g, h, sel, b, 0, n)
        assert _relerr(got, want) < TOL
        # counts stay ROW counts: zero-gradient selected rows still count
        np.testing.assert_allclose(
            np.asarray(got)[:, :, 2].sum(axis=1), np.full(f, sel.sum()),
            rtol=1e-6)

    def test_pgrow_layout_rows(self):
        """hist_segment on the WPAD-padded pgrow packed matrix via the
        explicit ``rows`` triple — bit-identical to hist_dyn."""
        n, f, b = 3072, 9, 32
        bins, g, h, sel = _data(n, f, b, seed=7)
        lay = pk.PLayout(f)
        P = pk.pack_matrix(bins, lay)
        P = P.at[lay.G, :n].set(jnp.asarray(g.view(np.int32)))
        P = P.at[lay.H, :n].set(jnp.asarray(h.view(np.int32)))
        P = P.at[lay.SEL, :n].set(jnp.asarray(sel.view(np.int32)))
        # trim to a BLK multiple (pack_matrix pads by BLK)
        got = hp.hist_segment(P[:, : n + 1024], jnp.int32(40), jnp.int32(2900),
                              f, b, rows=lay.rows, interpret=INTERP)
        via_dyn = pk.hist_dyn(P, 40, 2860, f, b, rows=lay.rows,
                              interpret=INTERP)
        want = _ref(bins, g, h, sel, b, 40, 2900)
        assert _relerr(got, want) < TOL
        np.testing.assert_allclose(np.asarray(got), np.asarray(via_dyn),
                                   rtol=1e-6, atol=1e-6)


class TestHistSegments:
    """Multi-leaf variant: one launch covers all active leaves."""

    def test_matches_per_leaf_bit_identical(self):
        """hist_segments must be BIT-identical to per-segment hist_dyn
        launches (same per-block accumulation order, same fchunk): the
        contract that lets the level path adopt it without moving the
        model."""
        n, f, b = 6000, 11, 32
        bins, g, h, sel = _data(n, f, b, seed=3)
        lay = pk.PLayout(f)
        P = pk.pack_matrix(bins, lay)
        P = P.at[lay.G, :n].set(jnp.asarray(g.view(np.int32)))
        P = P.at[lay.H, :n].set(jnp.asarray(h.view(np.int32)))
        P = P.at[lay.SEL, :n].set(jnp.asarray(sel.view(np.int32)))
        segs = np.array(
            [[0, 1024], [1024, 137], [1161, 0], [1161, 2935], [4096, 1904],
             [0, 0], [0, 0], [0, 0]], np.int32)
        n_active = 5
        got = hp.hist_segments(P, jnp.asarray(segs), jnp.int32(n_active),
                               num_features=f, num_bins=b, rows=lay.rows,
                               smax=8, interpret=INTERP)
        for s in range(n_active):
            lo, cnt = segs[s]
            via_dyn = pk.hist_dyn(P, int(lo), int(cnt), f, b, rows=lay.rows,
                                  interpret=INTERP)
            np.testing.assert_array_equal(np.asarray(got[s]),
                                          np.asarray(via_dyn))
            want = _ref(bins, g, h, sel, b, int(lo), int(lo + cnt))
            assert _relerr(got[s], want) < TOL

    def test_edge_shapes(self):
        """Odd bin count + single feature + zero-gradient rows through
        the multi-leaf path."""
        n, f, b = 2048, 1, 33
        bins, g, h, sel = _data(n, f, b, seed=9, zero_grad_frac=0.4)
        P = hp.pack_columns(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                            jnp.asarray(sel))
        segs = np.array([[0, 700], [700, 1348], [0, 0], [0, 0]], np.int32)
        got = hp.hist_segments(P, jnp.asarray(segs), jnp.int32(2),
                               num_features=f, num_bins=b, smax=4,
                               interpret=INTERP)
        for s in range(2):
            lo, cnt = segs[s]
            want = _ref(bins, g, h, sel, b, int(lo), int(lo + cnt))
            assert _relerr(got[s], want) < TOL

    def test_pgrow_level_hists_helper(self):
        from lightgbm_tpu.ops.pgrow import PGrowParams, level_hists

        n, f, b = 3000, 7, 16
        bins, g, h, sel = _data(n, f, b, seed=11)
        lay = pk.PLayout(f)
        P = pk.pack_matrix(bins, lay)
        P = P.at[lay.G, :n].set(jnp.asarray(g.view(np.int32)))
        P = P.at[lay.H, :n].set(jnp.asarray(h.view(np.int32)))
        P = P.at[lay.SEL, :n].set(jnp.asarray(sel.view(np.int32)))
        params = PGrowParams(num_leaves=7, num_bins=b, num_features=f,
                             num_rows=n)
        segs = np.array([[0, 1500], [1500, 1500], [0, 0], [0, 0]], np.int32)
        got = level_hists(P, jnp.asarray(segs), jnp.int32(2), params,
                          rows=lay.rows, interpret=INTERP)
        for s in range(2):
            lo, cnt = segs[s]
            want = _ref(bins, g, h, sel, b, int(lo), int(lo + cnt))
            assert _relerr(got[s], want) < TOL


class TestTuneFchunk:
    def test_bounds_and_budget(self):
        for nf in (1, 7, 28, 200):
            for nb in (16, 32, 63, 64, 256):
                f = hp.tune_fchunk(nf, nb)
                assert 1 <= f <= nf
                assert f * nb * hp.BLK * 2 <= 2 * 1024 * 1024 or f == 1
        # crowded-VMEM budget keeps the historical 512-row cap
        assert hp.tune_fchunk(28, 63, max_tile_bytes=1024 * 1024) == 8

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("LIGHTGBM_TPU_HIST_FCHUNK", "3")
        assert hp.tune_fchunk(28, 63) == 3
        monkeypatch.setenv("LIGHTGBM_TPU_HIST_FCHUNK", "9999")
        assert hp.tune_fchunk(28, 63) == 28  # clamped to F
        monkeypatch.setenv("LIGHTGBM_TPU_HIST_FCHUNK", "junk")
        assert hp.tune_fchunk(28, 63) >= 1  # falls back to the tuner

    def test_prefers_lane_aligned_even_division(self):
        # F=28, B=64: 2 chunks of 14 (14*64=896=7*128) beat the legacy
        # 8/8/8/4 split; the tuner must not pick a ragged-tail width
        f = hp.tune_fchunk(28, 64)
        assert hp.fchunk_cost(28, 64, f) <= hp.fchunk_cost(28, 64, 8)

    def test_fchunk_is_bit_invariant(self, monkeypatch):
        """Different fchunk widths must produce bit-identical histograms
        (each (feature, bin) cell contracts the same BLK lanes in the
        same order regardless of grouping)."""
        n, f, b = 2048, 6, 32
        bins, g, h, sel = _data(n, f, b, seed=13)
        P = hp.pack_columns(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                            jnp.asarray(sel))
        outs = []
        for width in ("1", "3", "6"):
            monkeypatch.setenv("LIGHTGBM_TPU_HIST_FCHUNK", width)
            jax.clear_caches()  # fchunk is read at trace time
            outs.append(np.asarray(hp.hist_segment(
                P, jnp.int32(0), jnp.int32(n), f, b, interpret=INTERP)))
        monkeypatch.delenv("LIGHTGBM_TPU_HIST_FCHUNK")
        jax.clear_caches()
        np.testing.assert_array_equal(outs[0], outs[1])
        np.testing.assert_array_equal(outs[0], outs[2])
