"""EFB feature bundling (io/bundle.py ↔ dataset.cpp:64-208)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.bundle import (
    build_bundled_matrix,
    decode_bundled_column,
    find_bundles,
)
from lightgbm_tpu.io.dataset import BinnedDataset


def _sparse_exclusive(n=4000, blocks=50, per_block=8, seed=0):
    """blocks*per_block one-hot-style features: inside a block exactly one
    feature is non-zero per row — perfectly bundleable."""
    rng = np.random.default_rng(seed)
    f = blocks * per_block
    X = np.zeros((n, f), np.float64)
    signal = np.zeros(n)
    for b in range(blocks):
        which = rng.integers(0, per_block, n)
        vals = rng.random(n) + 0.5
        X[np.arange(n), b * per_block + which] = vals
        signal += (which == 0) * vals
    y = (signal + 0.3 * rng.standard_normal(n) > np.median(signal)).astype(np.float32)
    return X, y


class TestFindBundles:
    def test_exclusive_features_bundle(self):
        X, y = _sparse_exclusive()
        cfg = Config.from_params({"max_bin": 15, "verbose": -1})
        ds = BinnedDataset.from_raw(X, cfg, label=y)
        ds.ensure_bundles(cfg)
        assert ds.bundle is not None
        info = ds.bundle
        assert info.num_cols < ds.num_features / 3  # G << F
        assert info.max_col_bin <= 256
        # decode each feature's bins back from its bundle column — exact
        # (zero conflicts by construction)
        for fe in range(ds.num_features):
            got = decode_bundled_column(
                ds.bundled[:, info.col[fe]], fe, info,
                ds.bin_mappers[fe].default_bin,
            )
            np.testing.assert_array_equal(got, ds.binned[:, fe].astype(np.int32))

    def test_conflicting_features_stay_separate(self):
        rng = np.random.default_rng(1)
        X = rng.random((2000, 6)) + 0.5  # fully dense: every pair conflicts
        cfg = Config.from_params({"max_bin": 15, "verbose": -1})
        mappers_ds = BinnedDataset.from_raw(X, cfg, label=rng.random(2000))
        mappers_ds.ensure_bundles(cfg)
        assert mappers_ds.bundle is None  # G == F -> no bundling

    def test_conflict_budget_allows_mild_overlap(self):
        rng = np.random.default_rng(2)
        n = 4000
        X = np.zeros((n, 2))
        X[: n // 2, 0] = rng.random(n // 2) + 0.5
        X[n // 2 :, 1] = rng.random(n // 2) + 0.5
        # 1% of rows conflict
        k = n // 100
        X[:k, 1] = rng.random(k) + 0.5
        cfg0 = Config.from_params({"max_bin": 15, "max_conflict_rate": 0.0, "verbose": -1})
        cfg5 = Config.from_params({"max_bin": 15, "max_conflict_rate": 0.05, "verbose": -1})
        m = BinnedDataset.from_raw(X, cfg0, label=rng.random(n))
        m.ensure_bundles(cfg0)
        assert m.bundle is None  # zero budget: the 1% overlap blocks it
        m5 = BinnedDataset.from_raw(X, cfg5, label=rng.random(n))
        m5.ensure_bundles(cfg5)
        assert m5.bundle is not None and m5.bundle.num_cols == 1


class TestBundledTraining:
    def test_prediction_parity_bundled_vs_unbundled(self, monkeypatch):
        """Zero-conflict bundles must reproduce the unbundled model: same
        histograms -> same trees -> same predictions."""
        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
        X, y = _sparse_exclusive(n=3000, blocks=25, per_block=8)
        params = dict(objective="binary", num_leaves=15, learning_rate=0.2,
                      max_bin=15, min_data_in_leaf=20, verbose=-1)
        preds = {}
        trees = {}
        for mode, extra in [("bundled", {}), ("plain", {"enable_bundle": False})]:
            ds = lgb.Dataset(X, label=y, params=dict(params, **extra))
            bst = lgb.train(dict(params, **extra), ds, num_boost_round=3)
            if mode == "bundled":
                assert ds.construct().bundle is not None  # built lazily by eligibility
                assert bst.boosting.ptrainer.bmeta is not None
            preds[mode] = bst.predict(X)
            trees[mode] = bst.boosting.models[-1].to_string_lines() if hasattr(
                bst.boosting.models[-1], "to_string_lines") else None
        np.testing.assert_allclose(preds["bundled"], preds["plain"], rtol=3e-3, atol=3e-4)


    def test_mixed_dense_sparse_singletons(self, monkeypatch):
        """Dense features become raw-layout singleton columns next to real
        bundles; training parity must hold across the mix."""
        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
        rng = np.random.default_rng(7)
        n = 3000
        Xs, y = _sparse_exclusive(n=n, blocks=10, per_block=6, seed=7)
        Xd = rng.standard_normal((n, 4))  # dense: forced singletons
        X = np.concatenate([Xd, Xs], axis=1)
        params = dict(objective="binary", num_leaves=15, learning_rate=0.2,
                      max_bin=15, min_data_in_leaf=20, verbose=-1)
        ds = lgb.Dataset(X, label=y, params=dict(params))
        bst = lgb.train(params, ds, num_boost_round=3)
        c = ds.construct()
        assert c.bundle is not None
        sizes = sorted(len(g) for g in c.bundle.groups)
        assert sizes[0] == 1 and sizes[-1] > 1  # singletons AND bundles
        # singleton raw columns decode identically
        for g, feats in enumerate(c.bundle.groups):
            if len(feats) == 1:
                fe = feats[0]
                got = decode_bundled_column(c.bundled[:, g], fe, c.bundle,
                                            c.bin_mappers[fe].default_bin)
                np.testing.assert_array_equal(got, c.binned[:, fe].astype(np.int32))
        p2 = dict(params, enable_bundle=False)
        bst2 = lgb.train(p2, lgb.Dataset(X, label=y, params=p2), num_boost_round=3)
        np.testing.assert_allclose(bst.predict(X), bst2.predict(X), rtol=3e-3, atol=3e-4)
