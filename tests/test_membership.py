"""Live elastic membership unit tests (parallel/membership.py plus the
boosting/gbdt.py membership seams — docs/ROBUSTNESS.md "Live elastic
membership").

In-process coverage: the FileKVClient store (write-once, framed,
crash-safe tmp+link publish), the sparse-id MemberWatch, the
three-runtime sync/commit protocol including deterministic coordinator
re-election, and the epoch-scoped uid seams.  With
``elastic_membership`` off (the default) nothing here is reachable and
the pre-existing bounded fail-fast semantics hold (test_net_fault.py
pins those).

The subprocess-fleet acceptance runs (SIGTERM leave + SIGKILL evict +
join in one run with byte-identity, coordinator-kill re-election, and
the ``slow`` churn matrix) live in tests/test_zmembership_fleet.py —
named to sort last so the expensive fleets run after the cheap suites.
"""

import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.membership


# ----------------------------------------------------------------------
# FileKVClient (the externalized coordination store)
# ----------------------------------------------------------------------
def test_filekv_roundtrip_and_encoding(tmp_path):
    from lightgbm_tpu.parallel.membership import FileKVClient

    kv = FileKVClient(str(tmp_path / "kv"))
    kv.key_value_set_bytes("a/b", b"\x00\x01binary\xff")
    assert kv.blocking_key_value_get_bytes("a/b", 500) == b"\x00\x01binary\xff"
    kv.key_value_set("plain", "text")
    assert kv.blocking_key_value_get("plain", 500) == "text"
    # tiny and empty values survive (the jaxlib client segfaults <2B —
    # the file store must not inherit that trap)
    kv.key_value_set_bytes("tiny", b"x")
    assert kv.blocking_key_value_get_bytes("tiny", 500) == b"x"
    kv.key_value_set_bytes("empty", b"")
    assert kv.blocking_key_value_get_bytes("empty", 500) == b""
    # hostile key components are percent-encoded per path segment
    kv.key_value_set_bytes("we ird/%41/..", b"v")
    assert kv.blocking_key_value_get_bytes("we ird/%41/..", 500) == b"v"


def test_filekv_blocking_get_times_out(tmp_path):
    from lightgbm_tpu.parallel.membership import FileKVClient

    kv = FileKVClient(str(tmp_path / "kv"))
    t0 = time.monotonic()
    with pytest.raises(Exception, match="DEADLINE_EXCEEDED"):
        kv.blocking_key_value_get_bytes("never", 200)
    assert time.monotonic() - t0 < 5.0


def test_filekv_try_create_is_exclusive(tmp_path):
    from lightgbm_tpu.parallel.membership import FileKVClient

    kv = FileKVClient(str(tmp_path / "kv"))
    assert kv.try_create("members/0", b"1") is True
    assert kv.try_create("members/0", b"2") is False
    assert kv.blocking_key_value_get_bytes("members/0", 500) == b"1"


def test_filekv_dir_get_and_prefix_delete(tmp_path):
    from lightgbm_tpu.parallel.membership import FileKVClient

    kv = FileKVClient(str(tmp_path / "kv"))
    for i in range(3):
        kv.key_value_set_bytes(f"hb/7/{i}", str(i).encode())
    kv.key_value_set_bytes("hb/9/0", b"0")
    got = {k for k, _v in kv.key_value_dir_get("hb/")}
    assert got == {"hb/7/0", "hb/7/1", "hb/7/2", "hb/9/0"}
    kv.key_value_delete("hb/7/")
    got = {k for k, _v in kv.key_value_dir_get("hb/")}
    assert got == {"hb/9/0"}
    kv.key_value_delete("hb/9/0")
    assert kv.key_value_dir_get("hb/") == []


# ----------------------------------------------------------------------
# MemberWatch (sparse ids after churn)
# ----------------------------------------------------------------------
def test_memberwatch_sparse_ids_and_eviction(tmp_path):
    from lightgbm_tpu.parallel import net
    from lightgbm_tpu.parallel.membership import FileKVClient, MemberWatch

    kv = FileKVClient(str(tmp_path / "kv"))
    clock = [0.0]
    watch = MemberWatch(kv, member_id=0, members=(0, 3, 7),
                        stale_after_s=10.0, time_fn=lambda: clock[0])
    kv.key_value_set(f"{net._HB_DIR}3/1", "1")
    kv.key_value_set(f"{net._HB_DIR}7/1", "1")
    assert watch.dead_ranks() == []
    # member 7 freezes; member 3 keeps rotating its beat
    clock[0] = 8.0
    kv.key_value_set(f"{net._HB_DIR}3/2", "2")
    kv.key_value_delete(f"{net._HB_DIR}3/1")
    assert watch.dead_ranks() == []
    clock[0] = 15.0  # 7 has been frozen 15s; 3 beat 7s ago
    assert watch.dead_ranks() == [7]
    # epoch transition evicts 7 from the roster: bookkeeping follows
    watch.set_members((0, 3, 9))
    kv.key_value_set(f"{net._HB_DIR}9/1", "1")
    assert watch.dead_ranks() == []


# ----------------------------------------------------------------------
# sync / commit protocol (in-process, three runtimes, real store)
# ----------------------------------------------------------------------
def _bootstrap_trio(tmp_path):
    from lightgbm_tpu.parallel.membership import MembershipRuntime

    rts = [MembershipRuntime(str(tmp_path), m) for m in range(3)]
    threads = [threading.Thread(target=rt.bootstrap,
                                args=(3, (200, 200, 200))) for rt in rts]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive()
    return rts


def test_sync_no_churn_returns_none(tmp_path):
    rts = _bootstrap_trio(tmp_path)
    try:
        out = [None] * 3
        ts = [threading.Thread(target=lambda i=i: out.__setitem__(
            i, rts[i].sync())) for i in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
            assert not t.is_alive()
        assert out == [None, None, None]
        assert [rt.epoch for rt in rts] == [0, 0, 0]
    finally:
        for rt in rts:
            rt.stop()


def test_sync_leave_join_and_commit(tmp_path):
    """Member 1 requests a clean leave while a joiner posts intent: every
    participant derives the identical decision and the commit moves the
    fleet to epoch 1 with the re-derived roster."""
    from lightgbm_tpu.parallel.membership import MembershipRuntime

    rts = _bootstrap_trio(tmp_path)
    joiner = MembershipRuntime(str(tmp_path))
    try:
        rts[1].request_leave()
        jt = threading.Thread(target=joiner.join, kwargs={"timeout_s": 60})
        jt.start()
        while not joiner.client.key_value_dir_get("intent/join/"):
            time.sleep(0.01)
        decisions = [None] * 3
        ts = [threading.Thread(target=lambda i=i: decisions.__setitem__(
            i, rts[i].sync())) for i in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
            assert not t.is_alive()
        for d in decisions:
            assert d is not None
            assert d.leavers == (1,)
            assert d.dead == ()
            assert d.joiners == (3,)
            assert d.participants == (0, 1, 2)
            assert d.new_members == (0, 2, 3)
            assert d.survivors == (0, 2)
        for rt, d in zip(rts, decisions):
            rt.commit_epoch(d, (200, 200, 200), iteration=4, num_data=600,
                            handoff_bytes=b"handoff-bytes"
                            if rt.id == min(d.new_members) else None)
        jt.join(timeout=30)
        assert not jt.is_alive()
        assert joiner.joined_mid_run
        assert joiner.id == 3 and joiner.epoch == 1
        assert joiner.members == (0, 2, 3) and joiner.start_iter == 4
        assert joiner.read_handoff() == b"handoff-bytes"
        assert [rt.epoch for rt in rts] == [1, 1, 1]
        # the join intent was consumed at commit
        assert joiner.client.key_value_dir_get("intent/join/") == []
    finally:
        for rt in rts + [joiner]:
            rt.stop()


def test_sync_evicts_dead_and_reelects_coordinator(tmp_path):
    """Member 0 (the coordinator) dies: survivors converge on the same
    eviction decision and the NEW coordinator is the lowest surviving id
    — re-election is by construction, not by vote."""
    rts = _bootstrap_trio(tmp_path)
    try:
        rts[0].stop()  # heartbeat freezes — 0 is now "dead"
        decisions = [None, None]
        ts = [threading.Thread(target=lambda i=i: decisions.__setitem__(
            i - 1, rts[i].sync(known_dead=(0,)))) for i in (1, 2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
            assert not t.is_alive()
        for d in decisions:
            assert d is not None
            assert d.dead == (0,)
            assert d.new_members == (1, 2)
            assert d.participants == (1, 2)
        for rt, d in zip(rts[1:], decisions):
            rt.commit_epoch(d, (300, 300), iteration=2, num_data=600)
        assert rts[1].is_coordinator and not rts[2].is_coordinator
        assert rts[1].members == (1, 2) and rts[1].epoch == 1
        assert rts[1].rank == 0 and rts[2].rank == 1
    finally:
        for rt in rts:
            rt.stop()


def test_member_ids_are_monotonic_never_reused(tmp_path):
    from lightgbm_tpu.parallel.membership import MembershipRuntime

    rts = _bootstrap_trio(tmp_path)
    try:
        j1 = MembershipRuntime(str(tmp_path))
        j2 = MembershipRuntime(str(tmp_path))
        # allocate ids without completing the join handshake
        for j in (j1, j2):
            i = 0
            while not j.client.try_create(f"members/{i}", b"1"):
                i += 1
            j.id = i
        assert (j1.id, j2.id) == (3, 4)
    finally:
        for rt in rts:
            rt.stop()


# ----------------------------------------------------------------------
# training-path guards
# ----------------------------------------------------------------------
def test_membership_rejects_query_grouped_data(tmp_path):
    import lightgbm_tpu as lgb
    from lightgbm_tpu import LightGBMError
    from lightgbm_tpu.parallel import membership
    from lightgbm_tpu.parallel.membership import MembershipRuntime

    rt = MembershipRuntime(str(tmp_path / "fleet"), 0)
    rt.bootstrap(1, (120,))
    membership.set_runtime(rt)
    try:
        rng = np.random.default_rng(5)
        X = rng.integers(0, 5, size=(120, 4)).astype(np.float32)
        y = rng.integers(0, 3, size=120).astype(np.float32)
        p = dict(objective="lambdarank", tree_learner="data",
                 pre_partition=True, elastic_membership=True,
                 num_leaves=5, verbose=-1)
        ds = lgb.Dataset(X, label=y, group=[30, 40, 50], params=dict(p))
        with pytest.raises(LightGBMError, match="query"):
            lgb.train(p, ds, num_boost_round=2)
    finally:
        membership.set_runtime(None)
        rt.stop()


def test_membership_synthesize_uses_live_rebalance_plan(tmp_path):
    """Eviction synthesis must regenerate the dead member's rows from
    the LIVE shard layout: after a runtime rebalance the epoch record's
    counts are stale, and synthesizing from them would duplicate some
    rows and drop others in the canonical merge."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.parallel import membership
    from lightgbm_tpu.parallel.membership import MembershipRuntime
    from lightgbm_tpu.parallel.shardplan import ShardPlan

    rng = np.random.default_rng(9)
    X = rng.integers(0, 5, size=(600, 6)).astype(np.float32)
    y = (rng.random(600) < 0.5).astype(np.float32)
    rt = MembershipRuntime(str(tmp_path / "fleet"), 0)
    rt.bootstrap(1, (600,))
    rt.row_provider = lambda lo, hi: (X[lo:hi], y[lo:hi])
    membership.set_runtime(rt)
    try:
        p = dict(objective="binary", tree_learner="data",
                 pre_partition=True, elastic_membership=True,
                 num_leaves=5, min_data_in_leaf=20,
                 boost_from_average=False, verbose=-1)
        ds = lgb.Dataset(X, label=y, params=dict(p))
        bst = lgb.train(p, ds, num_boost_round=3)
        g = bst.boosting
        assert g._membership is rt
        import zlib

        def _crc_label(lo):
            lab = y[lo:].astype(
                np.asarray(g.train_set.metadata.label).dtype)
            return zlib.crc32(np.ascontiguousarray(lab).tobytes()) \
                & 0xFFFFFFFF

        # pretend this is a 2-member fleet whose epoch record says
        # (360, 240) ...
        rt.members = (0, 1)
        rt.counts = (360, 240)
        own = g._membership_capture()
        stale = g._membership_synthesize(1, own)
        assert stale.meta["num_data"] == 240
        assert stale.meta["data_fingerprint_parts"]["crc_label"] \
            == _crc_label(360)
        # ... but a runtime rebalance has since moved the cut to
        # (200, 400): the armed plan, not the stale epoch counts, must
        # drive the regeneration
        g._rebalance = {"plan": ShardPlan.from_counts((200, 400)),
                        "ctl": None, "rank": 0, "group_bounds": None}
        live = g._membership_synthesize(1, own)
        assert live.meta["num_data"] == 400
        assert live.meta["data_fingerprint_parts"]["crc_label"] \
            == _crc_label(200)
        assert live.arrays["scores"].shape == (1, 400)
        # rows [360, 600) appear in both regenerations: their replayed
        # scores must agree bit-for-bit (per-row-independent replay)
        assert np.array_equal(live.arrays["scores"][:, 160:],
                              stale.arrays["scores"])
    finally:
        membership.set_runtime(None)
        rt.stop()


def test_membership_rollback_restores_boundary_state_bitwise(tmp_path):
    """A mid-grow rollback must replay from a bit-identical boundary
    state.  Multi-class is the sharp case: un-adding a tree from the f32
    score cache arithmetically (fl(fl(a+v)-v)) does not round-trip, so
    the snapshot restores the caches by reference instead."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(17)
    X = rng.integers(0, 6, size=(400, 5)).astype(np.float32)
    y = rng.integers(0, 3, size=400).astype(np.float32)
    p = dict(objective="multiclass", num_class=3, num_leaves=6,
             min_data_in_leaf=15, learning_rate=0.2, seed=3, verbose=-1)

    ds = lgb.Dataset(X, label=y, params=dict(p))
    ref = lgb.Booster(params=dict(p), train_set=ds)
    for _ in range(6):
        ref.update()
    ref_model = ref.model_to_string()

    ds2 = lgb.Dataset(X, label=y, params=dict(p))
    bst = lgb.Booster(params=dict(p), train_set=ds2)
    for _ in range(4):
        bst.update()
    g = bst.boosting
    # the boundary snapshot _train_one_iter_impl takes under membership
    snap = {
        "bag_rng": g.bag_rng.get_state(),
        "feature_rng": g.feature_rng.get_state(),
        "select": g.select,
        "num_models": len(g.models),
        "boost_from_average": g.boost_from_average_,
        "scores": g.scores,
        "valid_scores": tuple(g.valid_scores),
    }
    boundary_scores = np.asarray(g.scores, np.float32).copy()
    bst.update()  # iteration 5 grows 3 trees and advances the caches
    assert len(g.models) > snap["num_models"]
    g._member_iter_snapshot = snap
    g._membership_rollback_partial()
    g.iter -= 1  # the real path fails BEFORE the boundary increments it
    assert len(g.models) == snap["num_models"]
    assert (np.asarray(g.scores, np.float32).tobytes()
            == boundary_scores.tobytes()), "score cache not bit-restored"
    for _ in range(2):  # replay iteration 5, then train 6
        bst.update()
    assert bst.model_to_string() == ref_model


# ----------------------------------------------------------------------
# epoch-scoped uid seams (net.epoch_uid layout, collect.set_epoch,
# comm.epoch, distributed.current_epoch)
# ----------------------------------------------------------------------
def test_epoch_uid_layout_roundtrip():
    from lightgbm_tpu.parallel import net

    ns = 1 << 58
    uid = net.epoch_uid(7, (3 << 16) | 0xBEEF, ns=ns)
    assert net.uid_epoch(uid) == 7
    assert uid & 0xFFFF == 0xBEEF and uid & ns
    assert net.uid_epoch(12345) == 0  # static-world uids: no epoch field
    with pytest.raises(ValueError):
        net.epoch_uid(1 << 30, 0)


def test_collect_epoch_scoping_never_reuses_uids():
    from lightgbm_tpu.parallel import collect, net

    prev_epoch, prev_uid = collect._kv_epoch, collect._kv_uid
    try:
        collect.set_epoch(0)
        a = net.epoch_uid(collect._kv_epoch, next(collect._kv_uid))
        collect.set_epoch(3)
        b = net.epoch_uid(collect._kv_epoch, next(collect._kv_uid))
        assert net.uid_epoch(b) == 3 and b != a
        # re-announcing the SAME epoch must not restart the sequence
        seq_before = next(collect._kv_uid)
        collect.set_epoch(3)
        assert next(collect._kv_uid) == seq_before + 1
    finally:
        collect._kv_epoch, collect._kv_uid = prev_epoch, prev_uid


def test_comm_epoch_surface(tmp_path):
    from lightgbm_tpu.parallel.comm import Comm, LocalComm, LocalGroup
    from lightgbm_tpu.parallel.distributed import current_epoch
    from lightgbm_tpu.parallel.membership import (MembershipComm,
                                                  MembershipRuntime,
                                                  runtime, set_runtime)

    assert Comm.epoch == 0
    # static comms never bump it
    assert LocalComm(0, LocalGroup(2)).epoch == 0
    rt = MembershipRuntime(str(tmp_path), 0)
    try:
        threading.Thread(target=rt.bootstrap, args=(1, (10,))).start()
        deadline = time.monotonic() + 30
        while rt.epoch < 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert MembershipComm(rt).epoch == rt.epoch == 0
        prev = runtime()
        try:
            set_runtime(rt)
            assert current_epoch() == 0
        finally:
            set_runtime(prev)
    finally:
        rt.stop()


def test_current_epoch_is_zero_when_unarmed():
    from lightgbm_tpu.parallel.distributed import current_epoch
    from lightgbm_tpu.parallel.membership import runtime

    if runtime() is None:
        assert current_epoch() == 0
