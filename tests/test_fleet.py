"""Serving-fleet tests: the versioned model registry (atomic publish,
CRC refusal, rollback, watch token), zero-downtime hot swap (same-shape
retrain => ZERO new XLA compiles — the tree-shape-bucket acceptance
contract), concurrent-swap version attribution (every request answered
by exactly one model version), the load-balancing proxy (health
ejection, retry-on-failure, 503 re-route), and the multi-replica smoke:
2 subprocess replicas behind the proxy surviving a hot swap AND a
SIGKILL with zero dropped or mis-versioned responses.
"""

import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import compilewatch
from lightgbm_tpu.ops.predict import TreeArrays
from lightgbm_tpu.serve import (
    FleetProxy,
    ModelRegistry,
    PackedPredictor,
    PredictorArtifact,
    SwappablePredictor,
    pad_tree_arrays,
    tree_shape_bucket,
)
from lightgbm_tpu.utils.log import LightGBMError


@pytest.fixture(scope="module")
def binary_booster():
    rng = np.random.RandomState(3)
    X = rng.randn(600, 12)
    y = (X[:, 0] + 0.5 * X[:, 1] - X[:, 2] ** 2 > -0.5).astype(np.float32)
    ds = lgb.Dataset(X, label=y, params={"min_data_in_leaf": 5})
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "verbose": -1},
        ds, num_boost_round=12, verbose_eval=False,
    )
    return bst, X


def _retrain_artifact(art: PredictorArtifact, scale: float) -> PredictorArtifact:
    """A same-shape 'retrain': identical tree geometry, scaled leaves."""
    fields = {f: np.asarray(getattr(art.arrays, f))
              for f in TreeArrays.FIELDS}
    fields["leaf_value"] = fields["leaf_value"] * scale
    return PredictorArtifact(TreeArrays(**fields), art.meta)


def _artifact_bytes(art: PredictorArtifact) -> bytes:
    import io

    buf = io.BytesIO()
    art.save_to_bytes(buf)
    return buf.getvalue()


# ----------------------------------------------------------------------
# tree-shape compile-cache buckets
# ----------------------------------------------------------------------
class TestTreeShapeBuckets:
    def test_bucket_ladder(self):
        assert tree_shape_bucket(1) == 2
        assert tree_shape_bucket(2) == 2
        assert tree_shape_bucket(3) == 4
        assert tree_shape_bucket(15) == 16
        assert tree_shape_bucket(16) == 16
        assert tree_shape_bucket(17) == 32

    def test_pad_is_canonical_and_bit_identical(self, binary_booster,
                                                monkeypatch):
        bst, X = binary_booster
        art = PredictorArtifact.from_booster(bst)
        padded = pad_tree_arrays(art.arrays)
        m = padded.split_feature.shape[1]
        L = padded.leaf_value.shape[1]
        assert m == tree_shape_bucket(art.arrays.split_feature.shape[1])
        assert L == tree_shape_bucket(art.arrays.leaf_value.shape[1])
        # padded predictor output is bit-identical to the opt-out path
        got = PackedPredictor(art).predict(X[:40])
        monkeypatch.setenv("LIGHTGBM_TPU_TREE_SHAPE_BUCKETS", "0")
        exact = PackedPredictor(art).predict(X[:40])
        assert np.array_equal(got, exact)

    def test_pad_noop_when_canonical(self):
        kw = {f: np.zeros((3, 4), np.int32) for f in TreeArrays.FIELDS}
        kw["leaf_value"] = np.zeros((3, 8), np.float32)
        arrays = TreeArrays(**kw)
        assert pad_tree_arrays(arrays) is arrays


# ----------------------------------------------------------------------
# model registry
# ----------------------------------------------------------------------
class TestRegistry:
    def test_publish_list_activate(self, binary_booster, tmp_path):
        bst, _ = binary_booster
        art = PredictorArtifact.from_booster(bst)
        reg = ModelRegistry(str(tmp_path / "reg"))
        assert reg.active_version() is None
        v1 = reg.publish(art)
        v2 = reg.publish(_retrain_artifact(art, 1.1))
        assert (v1, v2) == (1, 2)
        assert reg.active_version() == 2
        models = reg.list_models()
        assert [m["version"] for m in models] == [1, 2]
        assert [m["active"] for m in models] == [False, True]
        assert models[0]["num_trees"] == art.meta["num_trees"]
        # rollback is just activating the older version
        reg.activate(1)
        assert reg.active_version() == 1
        with pytest.raises(LightGBMError, match="unknown version"):
            reg.activate(99)

    def test_publish_without_activate(self, binary_booster, tmp_path):
        bst, _ = binary_booster
        art = PredictorArtifact.from_booster(bst)
        reg = ModelRegistry(str(tmp_path / "reg"))
        reg.publish(art)
        reg.publish(_retrain_artifact(art, 1.1), activate=False)
        assert reg.active_version() == 1
        assert reg.latest_version() == 2

    def test_load_roundtrip(self, binary_booster, tmp_path):
        bst, X = binary_booster
        art = PredictorArtifact.from_booster(bst)
        reg = ModelRegistry(str(tmp_path / "reg"))
        reg.publish(art)
        ver, loaded = reg.load_active()
        assert ver == 1
        assert loaded.meta == art.meta
        assert np.array_equal(
            PackedPredictor(loaded).predict(X[:8]), bst.predict(X[:8]))

    def test_corrupt_artifact_refused_by_crc(self, binary_booster, tmp_path):
        bst, _ = binary_booster
        reg = ModelRegistry(str(tmp_path / "reg"))
        v = reg.publish(PredictorArtifact.from_booster(bst))
        path = os.path.join(reg.dir, f"v{v:08d}.npz")
        with open(path, "r+b") as f:  # flip bytes mid-file (torn write)
            f.seek(100)
            f.write(b"\xff\xff\xff\xff")
        with pytest.raises(LightGBMError, match="corrupt or torn"):
            reg.load(v)

    def test_corrupt_upload_never_enters_manifest(self, tmp_path):
        reg = ModelRegistry(str(tmp_path / "reg"))
        with pytest.raises(LightGBMError):
            reg.publish_bytes(b"not an artifact")
        assert reg.list_models() == []
        assert [n for n in os.listdir(reg.dir) if n.endswith(".npz")] == []

    def test_watch_token_changes_on_publish_and_activate(
            self, binary_booster, tmp_path):
        bst, _ = binary_booster
        art = PredictorArtifact.from_booster(bst)
        reg = ModelRegistry(str(tmp_path / "reg"))
        t0 = reg.watch_token()
        reg.publish(art)
        t1 = reg.watch_token()
        assert t1 != t0
        reg.publish(_retrain_artifact(art, 1.1))
        t2 = reg.watch_token()
        assert t2 != t1
        reg.activate(1)
        assert reg.watch_token() != t2
        assert reg.watch_token() == reg.watch_token()  # stable when idle

    def test_gc_keeps_last_and_never_active(self, binary_booster, tmp_path):
        bst, _ = binary_booster
        art = PredictorArtifact.from_booster(bst)
        reg = ModelRegistry(str(tmp_path / "reg"), keep_last=2)
        reg.publish(art)                                   # v1
        reg.publish(_retrain_artifact(art, 1.1))           # v2
        reg.activate(1)
        reg.publish(_retrain_artifact(art, 1.2), activate=False)  # v3
        reg.publish(_retrain_artifact(art, 1.3), activate=False)  # v4
        versions = [m["version"] for m in reg.list_models()]
        # v1 survives retention because it is ACTIVE; v2 was collected
        assert 1 in versions and 2 not in versions
        assert len(versions) <= 3

    def test_concurrent_seed_publishes_exactly_one_version(
            self, binary_booster, tmp_path):
        """N replicas pointed at the same empty registry all seed it on
        startup; the emptiness re-check under the publish lock must
        collapse the race to ONE published version."""
        bst, _ = binary_booster
        art = PredictorArtifact.from_booster(bst)
        reg_dir = str(tmp_path / "reg")
        n = 4
        barrier = threading.Barrier(n)
        got = []

        def seed():
            reg = ModelRegistry(reg_dir)
            barrier.wait()
            got.append(reg.seed(art))

        threads = [threading.Thread(target=seed) for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert got == [1] * n
        reg = ModelRegistry(reg_dir)
        assert [m["version"] for m in reg.list_models()] == [1]
        assert reg.active_version() == 1
        # a seed against a populated registry is a no-op returning the
        # active version, not a new publish
        reg.activate(1)
        assert reg.seed(_retrain_artifact(art, 1.1)) == 1
        assert [m["version"] for m in reg.list_models()] == [1]

    def test_routes_set_remove_and_watch_token(self, binary_booster,
                                               tmp_path):
        bst, _ = binary_booster
        art = PredictorArtifact.from_booster(bst)
        reg = ModelRegistry(str(tmp_path / "reg"))
        v1 = reg.publish(art)
        v2 = reg.publish(_retrain_artifact(art, 1.1), activate=False)
        assert reg.routes() == {}
        t0 = reg.watch_token()
        reg.set_route("shadow", v2)
        assert reg.routes() == {"shadow": v2}
        assert reg.route_version("shadow") == v2
        assert reg.watch_token() != t0  # replicas must see route changes
        # independent re-point (per-route hot swap)
        reg.set_route("shadow", v1)
        assert reg.route_version("shadow") == v1
        # list_models surfaces which routes serve each version
        by_ver = {m["version"]: m for m in reg.list_models()}
        assert by_ver[v1]["routes"] == ["shadow"]
        assert by_ver[v2]["routes"] == []
        t1 = reg.watch_token()
        assert reg.remove_route("shadow") is True
        assert reg.remove_route("shadow") is False
        assert reg.watch_token() != t1
        assert reg.route_version("shadow") is None

    def test_route_validation(self, binary_booster, tmp_path):
        bst, _ = binary_booster
        reg = ModelRegistry(str(tmp_path / "reg"))
        v = reg.publish(PredictorArtifact.from_booster(bst))
        with pytest.raises(LightGBMError, match="unknown version"):
            reg.set_route("r", 99)
        for bad in ("", "a/b", "..", ".hidden", "x" * 65, "a b"):
            with pytest.raises(LightGBMError, match="invalid route name"):
                reg.set_route(bad, v)

    def test_gc_never_collects_any_routed_version(self, binary_booster,
                                                  tmp_path):
        """Multi-model retention: EVERY routed version is a live serving
        dependency and must survive GC, no matter how old — collecting
        one would 404 the route on its next replica load."""
        bst, _ = binary_booster
        art = PredictorArtifact.from_booster(bst)
        reg = ModelRegistry(str(tmp_path / "reg"), keep_last=2)
        v1 = reg.publish(art)
        v2 = reg.publish(_retrain_artifact(art, 1.1), activate=False)
        reg.set_route("a", v1)
        reg.set_route("b", v2)
        # churn far past keep_last: v1/v2 are the OLDEST versions and
        # would be collected first were routes not protected
        for i in range(5):
            reg.publish(_retrain_artifact(art, 2.0 + i))
        versions = [m["version"] for m in reg.list_models()]
        assert v1 in versions and v2 in versions
        reg.load(v1)  # artifacts really are still on disk + CRC-clean
        reg.load(v2)
        # dropping a route releases its version to normal retention
        reg.remove_route("a")
        reg.publish(_retrain_artifact(art, 9.0))
        versions = [m["version"] for m in reg.list_models()]
        assert v1 not in versions and v2 in versions

    def test_orphan_file_never_overwritten(self, binary_booster, tmp_path):
        """A crashed publisher's orphan data file (no manifest entry)
        must not be clobbered by version-number reuse."""
        bst, _ = binary_booster
        reg = ModelRegistry(str(tmp_path / "reg"))
        orphan = os.path.join(reg.dir, "v00000005.npz")
        with open(orphan, "wb") as f:
            f.write(b"orphan from a crashed publisher")
        v = reg.publish(PredictorArtifact.from_booster(bst))
        assert v == 6
        with open(orphan, "rb") as f:
            assert f.read() == b"orphan from a crashed publisher"


# ----------------------------------------------------------------------
# hot swap
# ----------------------------------------------------------------------
class TestSwappablePredictor:
    def test_predict_returns_version(self, binary_booster):
        bst, X = binary_booster
        packed = PackedPredictor(PredictorArtifact.from_booster(bst))
        sw = SwappablePredictor(packed, version=3)
        out, ver = sw.predict(X[:5])
        assert ver == 3
        assert np.array_equal(out, bst.predict(X[:5]))

    def test_same_shape_swap_zero_new_compiles(self, binary_booster):
        """THE tentpole contract: a warmed predictor hot-swapped to a
        same-shape retrain compiles NOTHING — the compile cache is keyed
        on tree shape buckets, not model identity."""
        bst, X = binary_booster
        art = PredictorArtifact.from_booster(bst)
        packed = PackedPredictor(art)
        packed.warmup(256)
        sw = SwappablePredictor(packed, version=1)
        retrain = _retrain_artifact(art, 1.25)
        c0 = compilewatch.total_compiles()
        stats = sw.swap_to(retrain, version=2, warmup_max_rows=256)
        assert stats["new_compiles"] == 0, \
            "same-shape hot swap paid an XLA compile"
        assert compilewatch.total_compiles() == c0
        assert stats["old_drained"] is True
        out, ver = sw.predict(X[:7])
        assert ver == 2
        assert np.array_equal(out, PackedPredictor(retrain).predict(X[:7]))

    def test_same_config_retrain_shares_programs(self, binary_booster):
        """A REAL retrain (different data -> different observed node
        counts) lands in the same shape bucket and inherits the warm
        programs."""
        bst, X = binary_booster
        rng = np.random.RandomState(17)  # different rows, same config
        X2 = rng.randn(500, 12)
        y2 = (X2[:, 0] - X2[:, 1] > 0).astype(np.float32)
        bst2 = lgb.train(
            {"objective": "binary", "num_leaves": 15, "verbose": -1},
            lgb.Dataset(X2, label=y2, params={"min_data_in_leaf": 5}),
            num_boost_round=12, verbose_eval=False,
        )
        art1 = PredictorArtifact.from_booster(bst)
        art2 = PredictorArtifact.from_booster(bst2)
        packed = PackedPredictor(art1)
        packed.warmup(256)
        sw = SwappablePredictor(packed, version=1)
        stats = sw.swap_to(art2, version=2, warmup_max_rows=256)
        assert stats["new_compiles"] == 0, \
            "same-config retrain missed the warm shape-bucket programs"
        out, ver = sw.predict(X[:9])
        assert ver == 2
        assert np.array_equal(out, bst2.predict(X[:9]))

    def test_concurrent_swap_exactly_one_version(self, binary_booster):
        """Satellite 3 (unit level): requests racing a hot swap each get
        a response from exactly one model version, and the outputs match
        that version's model bit-for-bit."""
        bst, X = binary_booster
        art = PredictorArtifact.from_booster(bst)
        packed = PackedPredictor(art)
        packed.warmup(64)
        retrain = _retrain_artifact(art, 2.0)
        expected = {
            1: bst.predict(X[:4]),
            2: PackedPredictor(retrain).predict(X[:4]),
        }
        sw = SwappablePredictor(packed, version=1)
        stop = threading.Event()
        errors, seen = [], set()

        def hammer():
            while not stop.is_set():
                out, ver = sw.predict(X[:4])
                seen.add(ver)
                if ver not in expected:
                    errors.append(f"unknown version {ver}")
                elif not np.array_equal(out, expected[ver]):
                    errors.append(f"v{ver} output does not match v{ver} model")

        threads = [threading.Thread(target=hammer, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.15)
        sw.swap_to(retrain, version=2, warmup_max_rows=64)
        time.sleep(0.15)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors, errors[:3]
        assert seen == {1, 2}  # traffic really straddled the swap
        assert sw.draining_versions == 0  # old version fully drained


# ----------------------------------------------------------------------
# proxy (in-process fake backends — no jax involved)
# ----------------------------------------------------------------------
class _FakeBackend:
    """Minimal replica double: /readyz 200, /predict echoes a canned
    version, optional forced-503 mode (a draining replica)."""

    def __init__(self, version=1, always_503=False):
        fake = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, body, headers=()):
                self.send_response(code)
                for k, v in headers:
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._send(200 if self.path == "/readyz" else 404, b"{}\n")

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(n)
                if fake.always_503:
                    self._send(503, b'{"error": "draining"}\n')
                else:
                    self._send(200, b"0.5\n",
                               [("X-Model-Version", str(fake.version))])

        self.version = version
        self.always_503 = always_503
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self.addr = f"127.0.0.1:{self.port}"
        self._t = threading.Thread(target=self.httpd.serve_forever,
                                   daemon=True)
        self._t.start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _start_proxy(backends, **kw):
    proxy = FleetProxy(("127.0.0.1", 0), [b.addr for b in backends],
                       health_poll_s=0.1, retry_deadline_s=5.0, **kw)
    t = threading.Thread(target=proxy.serve_forever, daemon=True)
    t.start()
    return proxy, proxy.server_address[1]


def _proxy_predict(port, timeout=30):
    r = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/predict", data=b"[1.0, 2.0]\n",
        timeout=timeout)
    return r.status, r.headers.get("X-Model-Version")


class TestFleetProxy:
    def test_balances_and_relays_headers(self):
        backends = [_FakeBackend(version=7), _FakeBackend(version=7)]
        proxy, port = _start_proxy(backends)
        try:
            for _ in range(8):
                status, ver = _proxy_predict(port)
                assert (status, ver) == (200, "7")
            st = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/fleet/stats", timeout=30).read())
            assert st["healthy"] == 2
            reqs = [b["requests"] for b in st["backends"]]
            assert all(r > 0 for r in reqs), "one backend never picked"
        finally:
            proxy.shutdown()
            proxy.server_close()
            for b in backends:
                b.stop()

    def test_dead_backend_ejected_and_retried(self):
        """A SIGKILLed replica costs a retry, never a dropped response:
        connection failures eject the backend and the request re-routes
        within the same call."""
        backends = [_FakeBackend(), _FakeBackend()]
        proxy, port = _start_proxy(backends)
        try:
            backends[0].stop()  # dead: connection refused from now on
            for _ in range(6):
                status, _ = _proxy_predict(port)
                assert status == 200  # zero dropped
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                st = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/fleet/stats",
                    timeout=30).read())
                if st["healthy"] == 1:
                    break
                time.sleep(0.05)
            assert st["healthy"] == 1
        finally:
            proxy.shutdown()
            proxy.server_close()
            backends[1].stop()

    def test_503_reroutes_to_another_backend(self):
        """A draining replica's 503 re-routes; the client sees 200."""
        backends = [_FakeBackend(always_503=True), _FakeBackend()]
        proxy, port = _start_proxy(backends, policy="rr")
        try:
            for _ in range(6):
                status, _ = _proxy_predict(port)
                assert status == 200
        finally:
            proxy.shutdown()
            proxy.server_close()
            for b in backends:
                b.stop()

    def test_all_503_relayed(self):
        backends = [_FakeBackend(always_503=True)]
        proxy, port = _start_proxy(backends)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _proxy_predict(port)
            assert ei.value.code == 503
        finally:
            proxy.shutdown()
            proxy.server_close()
            backends[0].stop()


# ----------------------------------------------------------------------
# registry-backed server (in-process, HTTP)
# ----------------------------------------------------------------------
class TestServerRegistryMode:
    @pytest.fixture()
    def server(self, binary_booster, tmp_path):
        from lightgbm_tpu.serve.server import make_server

        bst, X = binary_booster
        model = PredictorArtifact.from_booster(bst).save(str(tmp_path / "m"))
        srv = make_server(model, port=0, warmup_max_rows=64,
                          max_delay_ms=1.0,
                          registry_dir=str(tmp_path / "reg"),
                          registry_poll_ms=50.0)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        yield srv, bst, X
        srv.shutdown()
        srv.server_close()

    def _post_rows(self, port, rows, query=""):
        body = "\n".join(json.dumps(list(map(float, r))) for r in rows).encode()
        return urllib.request.urlopen(
            f"http://127.0.0.1:{port}/predict{query}", data=body, timeout=30)

    def test_seeded_from_model_and_lists(self, server):
        srv, bst, X = server
        port = srv.server_address[1]
        r = self._post_rows(port, X[:3])
        assert r.headers["X-Model-Version"] == "1"
        listing = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/models", timeout=30).read())
        assert listing["active_version"] == 1
        assert listing["serving_version"] == 1
        assert [m["version"] for m in listing["models"]] == [1]

    def test_post_models_hot_swaps(self, server):
        srv, bst, X = server
        port = srv.server_address[1]
        retrain = _retrain_artifact(
            PredictorArtifact.from_booster(bst), 1.5)
        r = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/models",
            data=_artifact_bytes(retrain), timeout=60)
        reply = json.loads(r.read())
        assert reply["version"] == 2
        assert reply["serving_version"] == 2
        assert reply["swap"]["new_compiles"] == 0  # same-shape retrain
        r = self._post_rows(port, X[:5], query="?model_version=1")
        lines = [json.loads(l) for l in r.read().decode().splitlines()]
        assert all(l["model_version"] == 2 for l in lines)
        assert np.allclose(
            [l["prediction"] for l in lines],
            PackedPredictor(retrain).predict(X[:5]))
        st = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=30).read())
        assert st["model_version"] == 2
        assert st["swap"]["swaps"] >= 1
        assert st["registry"]["active_version"] == 2

    def test_post_models_rejects_garbage(self, server):
        srv, _, _ = server
        port = srv.server_address[1]
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/models",
                                   data=b"garbage bytes", timeout=30)
        assert ei.value.code == 400
        listing = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/models", timeout=30).read())
        assert len(listing["models"]) == 1  # nothing entered the registry

    def test_watcher_follows_out_of_band_publish(self, server):
        """Another process publishing into the shared registry directory
        is picked up by the poll watcher without any HTTP involvement."""
        srv, bst, X = server
        port = srv.server_address[1]
        reg = ModelRegistry(srv.registry.dir)  # an independent publisher
        retrain = _retrain_artifact(PredictorArtifact.from_booster(bst), 0.5)
        v = reg.publish(retrain)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if getattr(srv.predictor, "version", None) == v:
                break
            time.sleep(0.05)
        assert srv.predictor.version == v
        r = self._post_rows(port, X[:2])
        assert r.headers["X-Model-Version"] == str(v)

    def test_models_404_without_registry(self, binary_booster, tmp_path):
        from lightgbm_tpu.serve.server import make_server

        bst, _ = binary_booster
        model = PredictorArtifact.from_booster(bst).save(str(tmp_path / "m"))
        srv = make_server(model, port=0, warmup_max_rows=64)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            port = srv.server_address[1]
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/models", timeout=30)
            assert ei.value.code == 404
        finally:
            srv.shutdown()
            srv.server_close()


# ----------------------------------------------------------------------
# multi-model serving: named routes + admission control (in-process)
# ----------------------------------------------------------------------
class TestServerMultiModel:
    @pytest.fixture()
    def packed(self, binary_booster, tmp_path):
        """A server packing 4 models on one device: the default route
        plus 3 named routes, one of them quantized-flavor."""
        from lightgbm_tpu.serve.server import make_server

        bst, X = binary_booster
        art = PredictorArtifact.from_booster(bst)
        reg = ModelRegistry(str(tmp_path / "reg"))
        v1 = reg.publish(art)
        v2 = reg.publish(_retrain_artifact(art, 1.5), activate=False)
        v3 = reg.publish(_retrain_artifact(art, 0.5), activate=False)
        vq = reg.publish(PredictorArtifact.from_booster(bst, quantized=True),
                         activate=False)
        reg.set_route("retrain", v2)
        reg.set_route("rollback", v3)
        reg.set_route("quant", vq)
        srv = make_server(registry_dir=reg.dir, port=0, warmup_max_rows=64,
                          max_delay_ms=1.0, registry_poll_ms=50.0)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        yield srv, reg, bst, X, {"v1": v1, "v2": v2, "v3": v3, "vq": vq}
        srv.shutdown()
        srv.server_close()

    def _post(self, port, path, rows):
        body = "\n".join(json.dumps(list(map(float, r))) for r in rows).encode()
        return urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", data=body, timeout=30)

    def _get_json(self, port, path):
        return json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30).read())

    def test_four_models_pack_and_answer_independently(self, packed):
        srv, reg, bst, X, v = packed
        port = srv.server_address[1]
        art = PredictorArtifact.from_booster(bst)
        rows = X[:6]
        want = {
            "/predict": (PackedPredictor(art).predict(rows), v["v1"], None),
            "/predict/retrain": (
                PackedPredictor(_retrain_artifact(art, 1.5)).predict(rows),
                v["v2"], "retrain"),
            "/predict/rollback": (
                PackedPredictor(_retrain_artifact(art, 0.5)).predict(rows),
                v["v3"], "rollback"),
            "/predict/quant": (
                PackedPredictor(art.quantize()).predict(rows),
                v["vq"], "quant"),
        }
        for path, (expect, ver, route) in want.items():
            r = self._post(port, path, rows)
            assert r.headers["X-Model-Version"] == str(ver), path
            assert r.headers.get("X-Model-Route") == route, path
            got = [json.loads(l) for l in r.read().decode().splitlines()]
            assert np.allclose(got, expect), path
        table = self._get_json(port, "/routes")
        assert set(table["routes"]) == {"retrain", "rollback", "quant"}
        assert table["routes"]["quant"]["quantized"] is True
        assert table["routes"]["retrain"]["quantized"] is False
        # the quantized model packs smaller on device
        assert table["routes"]["quant"]["device_bytes"] * 2 \
            <= table["routes"]["retrain"]["device_bytes"]
        assert table["admission"]["used_bytes"] > 0

    def test_unknown_route_404(self, packed):
        srv, *_ = packed
        port = srv.server_address[1]
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post(port, "/predict/nope", [[0.0] * 12])
        assert ei.value.code == 404

    def test_admission_refusal_is_loud_and_recovers(self, packed):
        srv, reg, bst, X, v = packed
        port = srv.server_address[1]
        # shrink the budget below what another model needs and route it
        srv.route_budget_bytes = srv.device_bytes_used() + 1
        reg.set_route("overflow", v["v2"])
        srv.sync_routes()
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post(port, "/predict/overflow", X[:2])
        assert ei.value.code == 503
        assert "refused admission" in ei.value.read().decode()
        table = self._get_json(port, "/routes")
        assert "overflow" in table["admission"]["refused"]
        assert "route_budget_mb" in table["admission"]["refused"]["overflow"]
        # existing routes keep serving through the refusal
        self._post(port, "/predict/retrain", X[:2])
        # raising the budget admits the route on the next sync
        srv.route_budget_bytes = 0
        srv.sync_routes()
        r = self._post(port, "/predict/overflow", X[:2])
        assert r.headers["X-Model-Route"] == "overflow"
        assert "overflow" not in self._get_json(
            port, "/routes")["admission"]["refused"]
        reg.remove_route("overflow")
        srv.sync_routes()

    def test_route_swap_follows_registry(self, packed):
        srv, reg, bst, X, v = packed
        port = srv.server_address[1]
        reg.set_route("retrain", v["v3"])  # re-point an existing route
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            r = self._post(port, "/predict/retrain", X[:2])
            if r.headers["X-Model-Version"] == str(v["v3"]):
                break
            time.sleep(0.05)
        assert r.headers["X-Model-Version"] == str(v["v3"])
        reg.set_route("retrain", v["v2"])

    def test_per_route_stats_match_metrics(self, packed):
        """/stats per_route and the model_route-labeled /metrics families
        are the same counters — the parity contract."""
        srv, reg, bst, X, v = packed
        port = srv.server_address[1]
        for path in ("/predict", "/predict/retrain", "/predict/retrain",
                     "/predict/quant"):
            self._post(port, path, X[:2])
        st = srv.stats()
        per_route = st["per_route"]
        assert per_route["retrain"]["requests"] >= 2
        assert per_route["quant"]["requests"] >= 1
        assert per_route["default"]["requests"] >= 1
        assert st["routes"]["quant"]["quantized"] is True
        assert st["admission"]["used_bytes"] > 0
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30).read().decode()
        scraped = {}
        for line in text.splitlines():
            if line.startswith("lightgbm_tpu_serve_route_requests_total{"):
                label, val = line.split("} ")
                scraped[label.split('"')[1]] = int(float(val))
        for route, s in per_route.items():
            assert scraped.get(route) == s["requests"], (route, scraped)

    def test_removed_route_prunes_metrics(self, packed):
        srv, reg, bst, X, v = packed
        port = srv.server_address[1]
        reg.set_route("ephemeral", v["v2"])
        srv.sync_routes()
        self._post(port, "/predict/ephemeral", X[:2])
        assert "ephemeral" in srv.stats()["per_route"]
        reg.remove_route("ephemeral")
        srv.sync_routes()
        assert "ephemeral" not in srv.stats()["per_route"]
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30).read().decode()
        assert 'model_route="ephemeral"' not in text
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post(port, "/predict/ephemeral", X[:2])
        assert ei.value.code == 404

    def test_routes_admin_endpoint(self, packed):
        srv, reg, bst, X, v = packed
        port = srv.server_address[1]
        body = json.dumps({"route": "viahttp", "version": v["v3"]}).encode()
        r = urllib.request.urlopen(f"http://127.0.0.1:{port}/routes",
                                   data=body, timeout=60)
        reply = json.loads(r.read())
        assert reply["registry_routes"]["viahttp"] == v["v3"]
        assert reply["sync"]["routes"]["viahttp"] == v["v3"]
        r = self._post(port, "/predict/viahttp", X[:2])
        assert r.headers["X-Model-Route"] == "viahttp"
        # bad requests are refused without touching the manifest
        for bad in (b"{}", b'{"route": "x", "version": 99}',
                    b'{"route": "a/b", "version": 1}'):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"http://127.0.0.1:{port}/routes",
                                       data=bad, timeout=30)
            assert ei.value.code == 400
        body = json.dumps({"route": "viahttp", "remove": True}).encode()
        r = urllib.request.urlopen(f"http://127.0.0.1:{port}/routes",
                                   data=body, timeout=60)
        assert "viahttp" not in json.loads(r.read())["registry_routes"]
        body = json.dumps({"route": "viahttp", "remove": True}).encode()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/routes",
                                   data=body, timeout=30)
        assert ei.value.code == 404


# ----------------------------------------------------------------------
# multi-replica fleet (subprocess replicas + proxy)
# ----------------------------------------------------------------------
def _spawn_fleet(registry_dir, n=2):
    from lightgbm_tpu.serve.fleet import _wait_ready, spawn_replicas

    procs = spawn_replicas(n, {
        "registry": registry_dir,
        "warmup_max_rows": "64",
        "max_delay_ms": "1",
        "registry_poll_ms": "100",
    })
    try:
        for _, port in procs:
            assert _wait_ready("127.0.0.1", port, 120.0), \
                f"replica on port {port} never became ready"
    except BaseException:
        for p, _ in procs:
            p.kill()
        raise
    return procs


def _closed_loop(port, rows, expected, duration_s, n_threads=4, route=None):
    """Drive closed-loop traffic through the proxy; every reply must be
    200 and stamped with exactly one KNOWN version whose predictions it
    matches.  ``route`` targets ``/predict/<route>`` (multi-model).
    Returns (responses, errors, versions_seen, latencies)."""
    body = "\n".join(json.dumps(list(map(float, r))) for r in rows).encode()
    path = "/predict" if route is None else f"/predict/{route}"
    stop = time.monotonic() + duration_s
    lock = threading.Lock()
    stats = {"n": 0, "errors": [], "versions": set(), "lat": []}

    def worker():
        while time.monotonic() < stop:
            t0 = time.perf_counter()
            try:
                r = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}?model_version=1",
                    data=body, timeout=60)
                lines = [json.loads(l)
                         for l in r.read().decode().splitlines()]
            except Exception as e:
                with lock:
                    stats["errors"].append(f"{type(e).__name__}: {e}")
                continue
            lat = time.perf_counter() - t0
            vers = {l["model_version"] for l in lines}
            err = None
            if len(vers) != 1:
                err = f"reply mixed versions {vers}"
            else:
                ver = vers.pop()
                if ver not in expected:
                    err = f"unknown version {ver}"
                elif not np.allclose([l["prediction"] for l in lines],
                                     expected[ver]):
                    err = f"v{ver} reply does not match v{ver} model"
            with lock:
                stats["n"] += 1
                stats["lat"].append(lat)
                if err:
                    stats["errors"].append(err)
                else:
                    stats["versions"].add(ver)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(n_threads)]
    for t in threads:
        t.start()
    return threads, stats


@pytest.mark.fleet
class TestFleetSmoke:
    """Tier-1 smoke: 2 subprocess replicas sharing a registry behind the
    proxy; one hot swap and one SIGKILL under live traffic — zero
    dropped and zero mis-versioned responses."""

    def test_two_replicas_swap_and_kill(self, binary_booster, tmp_path):
        bst, X = binary_booster
        art = PredictorArtifact.from_booster(bst)
        retrain = _retrain_artifact(art, 1.75)
        rows = X[:2]
        expected = {
            1: PackedPredictor(art).predict(rows),
            2: PackedPredictor(retrain).predict(rows),
        }
        reg_dir = str(tmp_path / "reg")
        ModelRegistry(reg_dir).publish(art)  # v1 pre-seeded

        procs = _spawn_fleet(reg_dir, n=2)
        proxy = FleetProxy(("127.0.0.1", 0),
                           [f"127.0.0.1:{p}" for _, p in procs],
                           health_poll_s=0.2, retry_deadline_s=20.0)
        pt = threading.Thread(target=proxy.serve_forever, daemon=True)
        pt.start()
        port = proxy.server_address[1]
        try:
            threads, stats = _closed_loop(port, rows, expected,
                                          duration_s=6.0)
            time.sleep(1.0)
            # hot swap the whole fleet through the proxy: one replica
            # publishes + swaps, the other follows via the registry poll
            r = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/models",
                data=_artifact_bytes(retrain), timeout=60)
            assert json.loads(r.read())["version"] == 2
            time.sleep(1.0)
            # SIGKILL one replica mid-traffic
            procs[0][0].send_signal(signal.SIGKILL)
            for t in threads:
                t.join(timeout=60)
            assert stats["errors"] == [], stats["errors"][:5]
            assert stats["n"] > 0
            assert 2 in stats["versions"], "swap never reached traffic"
            # the survivor must be on v2
            st = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{procs[1][1]}/stats", timeout=30).read())
            assert st["model_version"] == 2
        finally:
            proxy.shutdown()
            proxy.server_close()
            for p, _ in procs:
                p.kill()
                p.wait(timeout=30)


@pytest.mark.fleet
class TestMultiModelFleetSmoke:
    """Tier-1 smoke for multi-model serving: 2 subprocess replicas each
    packing 2 models (default route + a quantized named route) behind
    the proxy, with one quantized hot swap under live closed-loop
    traffic on BOTH routes — zero dropped or mis-versioned responses."""

    def test_two_model_routes_and_quantized_swap(self, binary_booster,
                                                 tmp_path):
        bst, X = binary_booster
        art = PredictorArtifact.from_booster(bst)
        quant1 = art.quantize()
        quant2 = _retrain_artifact(art, 1.75).quantize()
        rows = X[:2]
        expected_default = {1: PackedPredictor(art).predict(rows)}
        expected_q = {
            2: PackedPredictor(quant1).predict(rows),
            4: PackedPredictor(quant2).predict(rows),
        }
        reg_dir = str(tmp_path / "reg")
        reg = ModelRegistry(reg_dir)
        assert reg.publish(art) == 1
        assert reg.publish(quant1, activate=False) == 2
        reg.set_route("q", 2)

        procs = _spawn_fleet(reg_dir, n=2)
        proxy = FleetProxy(("127.0.0.1", 0),
                           [f"127.0.0.1:{p}" for _, p in procs],
                           health_poll_s=0.2, retry_deadline_s=20.0)
        pt = threading.Thread(target=proxy.serve_forever, daemon=True)
        pt.start()
        port = proxy.server_address[1]
        try:
            threads_d, stats_d = _closed_loop(port, rows, expected_default,
                                              duration_s=6.0, n_threads=2)
            threads_q, stats_q = _closed_loop(port, rows, expected_q,
                                              duration_s=6.0, n_threads=2,
                                              route="q")
            time.sleep(1.5)
            # an unrelated publish mid-traffic (registry churn the routes
            # must shrug off), then a quantized hot swap on the named
            # route only — the default route must be untouched
            assert reg.publish(_retrain_artifact(art, 0.9),
                               activate=False) == 3
            vq = reg.publish(quant2, activate=False)
            assert vq == 4
            reg.set_route("q", vq)
            for t in threads_d + threads_q:
                t.join(timeout=60)
            assert stats_d["errors"] == [], stats_d["errors"][:5]
            assert stats_q["errors"] == [], stats_q["errors"][:5]
            assert stats_d["n"] > 0 and stats_q["n"] > 0
            assert stats_d["versions"] == {1}, "default route was disturbed"
            assert 4 in stats_q["versions"], "route swap never hit traffic"
            # both replicas converged to the swapped route version
            for _, rport in procs:
                st = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{rport}/stats", timeout=30).read())
                assert st["routes"]["q"]["version"] == vq
                assert st["routes"]["q"]["quantized"] is True
                assert st["per_route"]["q"]["requests"] > 0
        finally:
            proxy.shutdown()
            proxy.server_close()
            for p, _ in procs:
                p.kill()
                p.wait(timeout=30)


@pytest.mark.fleet
@pytest.mark.slow
class TestFleetLoad:
    """Closed-loop load test: sustained traffic over 3 replicas through
    the proxy while models hot-swap repeatedly and a replica is
    SIGKILLed — zero dropped responses, zero mis-versioned replies, and
    a bounded p99."""

    def test_closed_loop_under_churn(self, binary_booster, tmp_path):
        bst, X = binary_booster
        art = PredictorArtifact.from_booster(bst)
        rows = X[:4]
        retrains = {v: _retrain_artifact(art, 1.0 + 0.25 * (v - 1))
                    for v in range(2, 5)}
        expected = {1: PackedPredictor(art).predict(rows)}
        for v, a in retrains.items():
            expected[v] = PackedPredictor(a).predict(rows)
        reg_dir = str(tmp_path / "reg")
        ModelRegistry(reg_dir).publish(art)

        procs = _spawn_fleet(reg_dir, n=3)
        proxy = FleetProxy(("127.0.0.1", 0),
                           [f"127.0.0.1:{p}" for _, p in procs],
                           health_poll_s=0.2, retry_deadline_s=30.0)
        pt = threading.Thread(target=proxy.serve_forever, daemon=True)
        pt.start()
        port = proxy.server_address[1]
        try:
            threads, stats = _closed_loop(port, rows, expected,
                                          duration_s=15.0, n_threads=8)
            time.sleep(1.5)
            for v, a in sorted(retrains.items()):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/models",
                    data=_artifact_bytes(a), timeout=60)
                time.sleep(1.5)
            procs[0][0].send_signal(signal.SIGKILL)
            for t in threads:
                t.join(timeout=120)
            assert stats["errors"] == [], stats["errors"][:5]
            assert stats["n"] > 50
            assert max(stats["versions"]) == 4
            lat = sorted(stats["lat"])
            p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))]
            # generous CI bound: the point is that retries + swaps keep
            # latency bounded, not a hardware-grade SLO
            assert p99 < 30.0, f"p99 {p99:.2f}s under churn"
        finally:
            proxy.shutdown()
            proxy.server_close()
            for p, _ in procs:
                p.kill()
                p.wait(timeout=30)
