"""Native C++ parser: reference-exact Atof semantics and file parsing.

Covers the knife-edge class that motivated the native parser: the
reference's Common::Atof (common.h:163-261) is NOT correctly rounded, and
bin thresholds are midpoints of Atof-parsed values, so parity requires
bit-identical parsing (see native/parser.cpp header).
"""

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.parser import load_text_file
from lightgbm_tpu.native import atof, get_lib


@pytest.fixture(scope="module")
def lib():
    lib = get_lib()
    if lib is None:
        pytest.skip("no compiler for native parser")
    return lib


def test_atof_non_correctly_rounded(lib):
    # 1.413: digit accumulation gives 1 ulp below strtod
    assert atof("1.413") == 1.4129999999999998
    assert atof("1.413") != float("1.413")
    # exact cases agree
    for s in ["2", "0", "-7", "0.5", "123.25", "1e3", "-2.5e-2"]:
        assert atof(s) == float(s), s


def test_atof_word_tokens(lib):
    assert atof("na") == 0.0
    assert atof("NaN") == 0.0
    assert atof("inf") == 1e308
    assert atof("-inf") == -1e308
    assert atof("") == 0.0  # empty token keeps the 0 init (common.h:232)


def test_csv_empty_fields(tmp_path, lib):
    # empty fields parse as 0.0 exactly like the reference, NOT NaN
    p = tmp_path / "d.csv"
    p.write_text("1,,3\n4,5,\n,,\n")
    feats, label, _, _, _, _ = load_text_file(str(p), Config())
    mat = np.column_stack([label, feats])
    np.testing.assert_array_equal(mat, [[1, 0, 3], [4, 5, 0], [0, 0, 0]])


def test_tsv_and_blank_lines(tmp_path, lib):
    p = tmp_path / "d.tsv"
    p.write_text("1\t2\t3\n\n4\t5\t6\n   \n")
    feats, label, _, _, _, _ = load_text_file(str(p), Config())
    assert feats.shape == (2, 2)
    np.testing.assert_array_equal(label, [1, 4])


def test_header_names(tmp_path, lib):
    p = tmp_path / "d.csv"
    p.write_text("y,a,b\n0,1.5,2.5\n1,3.5,na\n")
    cfg = Config.from_params({"has_header": True})
    feats, label, _, _, names, _ = load_text_file(str(p), cfg)
    assert names == ["a", "b"]
    np.testing.assert_array_equal(label, [0, 1])
    np.testing.assert_array_equal(feats, [[1.5, 2.5], [3.5, 0.0]])


def test_libsvm_matches_python_fallback(tmp_path, lib):
    p = tmp_path / "d.svm"
    p.write_text("1 0:1.413 3:2.5\n0 1:-7\n2 2:1e-3 3:4\n")
    feats, label, _, _, _, _ = load_text_file(str(p), Config())
    assert feats.shape == (3, 4)
    assert feats[0, 0] == atof("1.413")
    assert feats[1, 1] == -7.0
    assert feats[2, 3] == 4.0
    np.testing.assert_array_equal(label, [1, 0, 2])


def test_large_random_matches_pandas_within_ulp(tmp_path, lib):
    rng = np.random.default_rng(0)
    vals = rng.normal(size=(500, 8)).round(4)
    p = tmp_path / "big.csv"
    np.savetxt(p, vals, delimiter=",", fmt="%.4f")
    feats, label, _, _, _, _ = load_text_file(str(p), Config())
    # Atof differs from strtod by <= a few ulps; the label column is
    # downcast to f32 by design (Metadata stores float labels)
    np.testing.assert_allclose(feats, vals[:, 1:], rtol=1e-14)
    np.testing.assert_allclose(label, vals[:, 0], rtol=1e-6)
