"""Observability-layer tests: tracer unit behavior (span nesting, JSONL
round-trip, disabled-mode overhead), the report CLI, per-iteration record
schema through real ``engine.train`` runs (mask path and the traced
partitioned path with its histogram/split/partition phase breakdown),
and the JitWatch retrace detector.
"""

import json
import os
import re
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import report
from lightgbm_tpu.obs.compilewatch import JitWatch
from lightgbm_tpu.obs.trace import Tracer, _NULL_SPAN


@pytest.fixture
def fresh_tracer(tmp_path):
    tr = Tracer()
    tr.configure(str(tmp_path / "trace.jsonl"))
    yield tr
    tr.close()


@pytest.fixture
def global_trace(tmp_path, monkeypatch):
    """Route the process-global tracer to a temp file for one test, and
    restore the disabled state afterwards."""
    from lightgbm_tpu.obs import tracer

    path = str(tmp_path / "trace.jsonl")
    monkeypatch.setenv("LIGHTGBM_TPU_TRACE", path)
    yield path
    tracer.close()
    tracer.path = None
    tracer.reset_aggregates()


def _read(path):
    return [json.loads(l) for l in open(path) if l.strip()]


class TestTracerUnit:
    def test_span_nesting_and_jsonl_roundtrip(self, fresh_tracer, tmp_path):
        tr = fresh_tracer
        with tr.span("outer", tag="a"):
            with tr.span("inner"):
                pass
            with tr.span("inner"):
                pass
        tr.counter("widgets", 3)
        tr.gauge("temp", 1.5, unit="C")
        tr.event("boom", detail="x")
        tr.close()
        recs = _read(tr.path)
        assert recs[0]["ev"] == "meta" and recs[0]["version"] == 1
        spans = [r for r in recs if r["ev"] == "span"]
        # children close (and are written) before the parent
        assert [s["name"] for s in spans] == ["inner", "inner", "outer"]
        assert all(s["parent"] == "outer" and s["depth"] == 1
                   for s in spans[:2])
        assert spans[2]["parent"] is None and spans[2]["depth"] == 0
        assert spans[2]["tag"] == "a"
        assert all(s["dur_s"] >= 0 for s in spans)
        counter = next(r for r in recs if r["ev"] == "counter")
        assert counter["name"] == "widgets" and counter["value"] == 3
        gauge = next(r for r in recs if r["ev"] == "gauge")
        assert gauge["value"] == 1.5 and gauge["unit"] == "C"
        assert any(r["ev"] == "event" and r["name"] == "boom" for r in recs)

    def test_iteration_record(self, fresh_tracer):
        tr = fresh_tracer
        with tr.iteration(7) as rec:
            with tr.span("histogram"):
                pass
            with tr.span("split"):
                pass
            rec["leaves"] = 31
        tr.close()
        it = next(r for r in _read(tr.path) if r["ev"] == "iter")
        assert it["iter"] == 7 and it["leaves"] == 31
        assert set(it["phases"]) == {"histogram", "split"}
        assert it["wall_s"] >= 0 and "host_rss_mb" in it
        assert "compiles" in it

    def test_disabled_mode_is_noop_and_cheap(self):
        tr = Tracer()
        assert not tr.enabled
        # structural near-zero-overhead proof: the SAME singleton no-op
        # context manager is returned for every disabled span
        assert tr.span("x") is _NULL_SPAN
        assert tr.span("y", attr=1) is _NULL_SPAN
        tr.counter("c")
        tr.gauge("g", 1.0)
        tr.event("e")
        with tr.iteration(0) as rec:
            assert rec is None
        t0 = time.perf_counter()
        for _ in range(100_000):
            with tr.span("hot"):
                pass
        assert time.perf_counter() - t0 < 1.0  # ~µs/op budget, loose

    def test_snapshot_aggregates(self, fresh_tracer):
        tr = fresh_tracer
        for _ in range(3):
            with tr.span("phase_a"):
                pass
        snap = tr.snapshot()
        assert snap["spans"]["phase_a"]["count"] == 3
        assert snap["spans"]["phase_a"]["total_s"] >= 0


class TestReportCli:
    def _make_trace(self, tmp_path):
        tr = Tracer()
        p = str(tmp_path / "t.jsonl")
        tr.configure(p)
        for i in range(4):
            with tr.iteration(i) as rec:
                with tr.span("histogram"):
                    pass
                with tr.span("split"):
                    pass
                rec["leaves"] = 15
        tr.close()
        return p

    def test_report_renders_table(self, tmp_path, capsys):
        from lightgbm_tpu.cli import main

        p = self._make_trace(tmp_path)
        assert main(["report", p]) == 0
        out = capsys.readouterr().out
        assert "run-trace report" in out
        assert "histogram" in out and "split" in out
        assert "iterations: 4" in out
        assert "compiles:" in out

    def test_report_json_mode(self, tmp_path, capsys):
        from lightgbm_tpu.cli import main

        p = self._make_trace(tmp_path)
        assert main(["report", p, "--json"]) == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["iterations"] == 4
        assert "histogram" in summary["phases"]

    def test_report_tolerates_torn_tail(self, tmp_path):
        p = self._make_trace(tmp_path)
        with open(p, "a") as f:
            f.write('{"ev":"iter","iter":99,"wa')  # killed mid-write
        summary = report.summarize(report.load_trace(p))
        assert summary["iterations"] == 4

    def test_report_missing_file(self, capsys):
        from lightgbm_tpu.cli import main

        assert main(["report", "/nonexistent/trace.jsonl"]) == 1
        assert main(["report"]) == 2


def _toy(n=500, f=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + X[:, 1] > 0).astype(float)
    return X, y


class TestEngineTraceSchema:
    def test_mask_path_iteration_records(self, global_trace):
        X, y = _toy()
        lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1},
                  lgb.Dataset(X, label=y), num_boost_round=5,
                  verbose_eval=False)
        recs = _read(global_trace)
        iters = [r for r in recs if r["ev"] == "iter"]
        assert len(iters) == 5
        for i, r in enumerate(iters):
            assert r["iter"] == i
            assert r["leaves"] > 0 and r["trees"] == 1
            assert r["wall_s"] > 0 and r["host_rss_mb"] > 0
            assert "compiles" in r
            # mask-path phases: the fused grow_tree is one program, so
            # the breakdown is at driver granularity
            assert {"boosting", "tree", "train_score"} <= set(r["phases"])
        assert any(r["ev"] == "event" and r["name"] == "train_begin"
                   for r in recs)

    def test_traced_partitioned_phase_breakdown(self, global_trace,
                                                monkeypatch):
        """The acceptance-criteria run: engine.train with
        LIGHTGBM_TPU_TRACE produces per-iteration records whose phases
        carry real device-fenced histogram/split/partition timings."""
        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
        monkeypatch.setenv("LIGHTGBM_TPU_TRACE_PHASES", "1")
        X, y = _toy(600)
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbose": -1},
                        lgb.Dataset(X, label=y), num_boost_round=3,
                        verbose_eval=False)
        assert bst.boosting.ptrainer is not None
        recs = _read(global_trace)
        iters = [r for r in recs if r["ev"] == "iter"]
        assert len(iters) == 3
        for r in iters:
            assert {"histogram", "split", "partition", "score_update"} <= set(
                r["phases"]
            )
            assert r["phases"]["histogram"] > 0
            assert r["phases"]["partition"] > 0
            assert r["leaves"] > 1
            assert r["mode"] == "traced"
        # the report CLI digests it
        summary = report.summarize(recs)
        assert summary["iterations"] == 3
        assert "partition" in summary["phases"]

    def test_fused_chunk_amortized_records(self, global_trace, monkeypatch):
        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
        monkeypatch.setenv("LIGHTGBM_TPU_TRACE_PHASES", "0")
        X, y = _toy(600)
        lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1},
                  lgb.Dataset(X, label=y), num_boost_round=3,
                  verbose_eval=False)
        recs = _read(global_trace)
        iters = [r for r in recs if r["ev"] == "iter"]
        assert len(iters) == 3
        assert all(r.get("amortized") for r in iters)
        assert all("fused_chunk" in r["phases"] for r in iters)
        # the chunk program itself is spanned and watched
        assert any(r["ev"] == "span" and r["name"] == "chunk_program"
                   for r in recs)

    def test_traced_matches_fused_classic(self, tmp_path, monkeypatch):
        """Traced mode must not change the model: bit-identical to the
        fused classic (LEVELGROW=0) path on a bagged+feature-sampled
        config."""
        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
        monkeypatch.setenv("LIGHTGBM_TPU_LEVELGROW", "0")
        X, y = _toy(1200, 8)
        params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
                  "min_data_in_leaf": 20, "bagging_fraction": 0.8,
                  "bagging_freq": 1, "feature_fraction": 0.7}
        preds = {}
        from lightgbm_tpu.obs import tracer

        try:
            for mode in ("0", "1"):
                monkeypatch.setenv(
                    "LIGHTGBM_TPU_TRACE", str(tmp_path / f"t{mode}.jsonl")
                )
                monkeypatch.setenv("LIGHTGBM_TPU_TRACE_PHASES", mode)
                bst = lgb.train(dict(params),
                                lgb.Dataset(X, label=y, params=dict(params)),
                                num_boost_round=4, verbose_eval=False)
                preds[mode] = bst.predict(X)
        finally:
            tracer.close()
            tracer.path = None
        np.testing.assert_array_equal(preds["0"], preds["1"])


class TestRetraceDetector:
    def test_flags_cache_growth_on_seen_signature(self):
        """The env-var-read-at-trace-time bug class: the jit cache key
        changes while the visible ARRAY signature does not — JitWatch
        must flag the recompile as an unexpected retrace."""
        import jax
        import jax.numpy as jnp

        fn = jax.jit(lambda x, mode: x * mode, static_argnames=("mode",))
        w = JitWatch(fn, name="test.retrace")
        x = jnp.ones((4,))
        w(x, mode=2)
        assert w.compiles == 1 and w.retraces == 0
        w(x, mode=2)  # cache hit
        assert w.compiles == 1
        w(x, mode=3)  # same arrays, new static value -> hidden retrace
        assert w.compiles == 2 and w.retraces == 1

    def test_new_shapes_are_not_retraces(self):
        import jax
        import jax.numpy as jnp

        w = JitWatch(jax.jit(lambda x: x + 1), name="test.shapes")
        w(jnp.ones((3,)))
        w(jnp.ones((5,)))
        assert w.compiles == 2 and w.retraces == 0
        assert len(w._sigs) == 2

    def test_cleared_cache_rewarm_is_not_a_retrace(self):
        """jax.clear_caches() empties every jit cache but the watch's
        seen-signature set used to survive it, so the re-warm of each
        already-seen signature was falsely flagged as a retrace (first
        seen as test-order pollution: a module clearing caches between
        two serve modules sharing model shapes).  A shrunken cache must
        reset the seen set."""
        import jax
        import jax.numpy as jnp

        fn = jax.jit(lambda x, mode: x * mode, static_argnames=("mode",))
        w = JitWatch(fn, name="test.cleared")
        x = jnp.ones((4,))
        w(x, mode=2)
        assert w.compiles == 1 and w.retraces == 0
        jax.clear_caches()
        w(x, mode=2)  # legitimate recompile of a seen signature
        assert w.compiles == 2 and w.retraces == 0
        w(x, mode=3)  # real hidden retrace still detected after a clear
        assert w.retraces == 1

    def test_levelgrow_env_participates_in_program_identity(self,
                                                            monkeypatch):
        """Satellite regression: LIGHTGBM_TPU_LEVELGROW is read at
        trainer construction into PGrowParams (static, part of the jit
        cache key), not at trace time inside the grower."""
        from lightgbm_tpu.ops.pgrow import levelgrow_env_params

        monkeypatch.setenv("LIGHTGBM_TPU_LEVELGROW", "0")
        monkeypatch.setenv("LIGHTGBM_TPU_MAXLVL", "7")
        assert levelgrow_env_params() == {"levelwise": False, "max_levels": 7}
        monkeypatch.setenv("LIGHTGBM_TPU_LEVELGROW", "1")
        assert levelgrow_env_params()["levelwise"] is True

        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
        X, y = _toy(600)
        params = {"objective": "binary", "num_leaves": 7, "verbose": -1}
        monkeypatch.setenv("LIGHTGBM_TPU_LEVELGROW", "0")
        bst = lgb.train(dict(params), lgb.Dataset(X, label=y),
                        num_boost_round=1, verbose_eval=False)
        assert bst.boosting.ptrainer.params.levelwise is False
        monkeypatch.setenv("LIGHTGBM_TPU_LEVELGROW", "1")
        bst = lgb.train(dict(params), lgb.Dataset(X, label=y),
                        num_boost_round=1, verbose_eval=False)
        assert bst.boosting.ptrainer.params.levelwise is True


class TestDisabledOverheadEndToEnd:
    def test_training_emits_nothing_when_disabled(self, tmp_path,
                                                  monkeypatch):
        """With tracing off the instrumented paths must not write records
        or block dispatch (fence is a no-op)."""
        from lightgbm_tpu.obs import tracer
        from lightgbm_tpu.obs.trace import fence

        monkeypatch.delenv("LIGHTGBM_TPU_TRACE", raising=False)
        tracer.close()
        tracer.path = None
        tracer.refresh_from_env()
        assert not tracer.enabled
        assert fence(None) is None
        X, y = _toy()
        lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1},
                  lgb.Dataset(X, label=y), num_boost_round=2,
                  verbose_eval=False)
        assert not tracer.enabled and tracer.path is None

    def test_tracing_off_does_zero_tracer_work(self, monkeypatch):
        """The overhead guard (ISSUE 7 satellite): training with tracing
        fully off must not allocate a flight ring nor process a single
        tracer-side record.  Pinned on the tracer WORK COUNTER (every
        emitted/mirrored record increments it), not wall clock, so a
        widened hot path cannot hide in timing noise."""
        from lightgbm_tpu.obs import flight, tracer

        monkeypatch.delenv("LIGHTGBM_TPU_TRACE", raising=False)
        monkeypatch.delenv("LIGHTGBM_TPU_AUDIT", raising=False)
        tracer.close()
        tracer.path = None
        tracer.refresh_from_env()
        work_before = tracer.work_ops
        X, y = _toy()
        for force in ("0", "force"):  # mask path AND the fused path
            monkeypatch.setenv("LIGHTGBM_TPU_PGROW", force)
            lgb.train({"objective": "binary", "num_leaves": 7,
                       "verbose": -1},
                      lgb.Dataset(X, label=y), num_boost_round=2,
                      verbose_eval=False)
        assert tracer.work_ops == work_before, (
            "tracer-side work happened with tracing off")
        assert flight.recorder.ring is None, (
            "flight ring allocated with tracing off")


class TestFlightRecorder:
    def test_ring_bounded_and_dump_contents(self, tmp_path, monkeypatch):
        from lightgbm_tpu.obs import flight
        from lightgbm_tpu.obs.trace import Tracer

        monkeypatch.setenv("LIGHTGBM_TPU_FLIGHT_RING", "64")
        tr = Tracer()
        tr.configure(str(tmp_path / "t.jsonl"))
        assert flight.recorder.ring is not None
        assert flight.recorder.ring.maxlen == 64
        for i in range(200):
            tr.event("tick", i=i)
        tr.event("boom", last=True)
        p = flight.recorder.dump("unit_test", error=RuntimeError("x"),
                                 extra=1)
        assert p == str(tmp_path / "t.crash.jsonl")
        recs = _read(p)
        meta = recs[0]
        assert meta["ev"] == "meta" and meta["kind"] == "flight"
        assert meta["reason"] == "unit_test"
        assert meta["error"] == "RuntimeError: x" and meta["extra"] == 1
        # bounded: ring capacity + the meta line, keeping the NEWEST
        assert len(recs) == 65
        assert recs[-1]["name"] == "boom"
        assert all(r["name"] == "tick" and r["i"] >= 136
                   for r in recs[1:-1])
        tr.close()
        assert flight.recorder.ring is None  # deactivated with the tracer
        assert flight.recorder.dump("after_close") is None

    def test_net_failure_dumps_ring(self, tmp_path, monkeypatch):
        """The net.py wiring: a typed PeerFailureError raise flushes the
        ring — the survivor's crash dump contains the final records
        before the failure (here driven through PeerWatch.check with a
        fake KV client)."""
        from lightgbm_tpu.obs import flight, tracer
        from lightgbm_tpu.parallel.net import PeerFailureError, PeerWatch

        monkeypatch.setenv("LIGHTGBM_TPU_TRACE",
                           str(tmp_path / "net.jsonl"))
        tracer.refresh_from_env()
        try:
            with tracer.span("net.heartbeat", rank=0):
                pass

            class DeadKV:
                def key_value_dir_get(self, prefix):
                    return [("ltpu_hb/1/5", "5")]

            clock = {"t": 0.0}
            watch = PeerWatch(DeadKV(), rank=0, nproc=2, stale_after_s=1.0,
                              time_fn=lambda: clock["t"])
            watch.ages()
            clock["t"] = 10.0  # rank 1's key set frozen for 10 s
            with pytest.raises(PeerFailureError):
                watch.check("unit_collective")
        finally:
            crash = str(tmp_path / "net.crash.jsonl")
            found = os.path.exists(crash)
            recs = _read(crash) if found else []
            tracer.close()
            tracer.path = None
        assert found, "typed failure left no crash dump"
        assert recs[0]["reason"] == "peer_failure"
        assert any(r.get("ev") == "span" and r.get("name") == "net.heartbeat"
                   for r in recs)
        assert any(r.get("ev") == "event"
                   and r.get("name") == "net.peer_failure" for r in recs)

    def test_sigusr1_dumps(self, tmp_path, monkeypatch):
        import signal

        from lightgbm_tpu.obs import flight, tracer

        monkeypatch.setenv("LIGHTGBM_TPU_TRACE",
                           str(tmp_path / "s.jsonl"))
        tracer.refresh_from_env()
        try:
            tracer.event("before_signal")
            assert flight.install_signal_handler()
            os.kill(os.getpid(), signal.SIGUSR1)
            crash = str(tmp_path / "s.crash.jsonl")
            recs = _read(crash)
        finally:
            signal.signal(signal.SIGUSR1, signal.SIG_DFL)
            tracer.close()
            tracer.path = None
        assert recs[0]["reason"] == "sigusr1"
        assert any(r.get("name") == "before_signal" for r in recs)


class TestTraceIdentity:
    def test_records_carry_rank_world_run_id(self, tmp_path):
        from lightgbm_tpu.obs.trace import Tracer

        tr = Tracer()
        tr.set_identity(rank=3, world_size=8, run_id="host:1234")
        tr.configure(str(tmp_path / "i.jsonl"))
        with tr.span("histogram"):
            pass
        tr.counter("net.retry")
        tr.close()
        recs = _read(str(tmp_path / "i.jsonl"))
        assert recs, "no records written"
        for r in recs:
            assert r["rank"] == 3 and r["world"] == 8
            assert r["run_id"] == "host:1234"

    def test_single_process_records_stay_clean(self, fresh_tracer):
        tr = fresh_tracer
        tr.event("x")
        tr.close()
        recs = _read(tr.path)
        assert all("rank" not in r and "world" not in r for r in recs)


class TestReportGarbageLines:
    def test_garbage_lines_skip_with_warning(self, tmp_path, capsys):
        """Crash-cut traces: unparsable lines ANYWHERE in the file (not
        just a torn tail) must be skipped with a warning, never raise."""
        p = str(tmp_path / "g.jsonl")
        with open(p, "w") as f:
            f.write('{"ev":"meta","version":1}\n')
            f.write("\x00\x00garbage not json\n")
            f.write('{"ev":"iter","iter":0,"wall_s":0.5,"phases":{}}\n')
            f.write('["not", "an", "object"]\n')
            f.write('{"ev":"iter","iter":1,"wa')  # torn tail
        recs = report.load_trace(p)
        err = capsys.readouterr().err
        assert len(recs) == 2
        assert err.count("warning:") == 3
        assert "skipping" in err
        summary = report.summarize(recs)
        assert summary["iterations"] == 1

    def test_report_cli_survives_garbage(self, tmp_path, capsys):
        from lightgbm_tpu.cli import main

        p = str(tmp_path / "g.jsonl")
        with open(p, "w") as f:
            f.write("not json at all\n")
            f.write('{"ev":"iter","iter":0,"wall_s":0.1,"phases":{}}\n')
        assert main(["report", p]) == 0
        out = capsys.readouterr().out
        assert "iterations: 1" in out


def _make_rank_trace(tmp_path, rank, compute_s, wait_s, iters=3):
    """Synthesize one rank's trace with controlled compute/wait spans."""
    from lightgbm_tpu.obs.trace import Tracer

    tr = Tracer()
    tr.set_identity(rank=rank, world_size=2, run_id="merge:test")
    path = str(tmp_path / f"rank{rank}.jsonl")
    tr.configure(path)
    for i in range(iters):
        with tr.iteration(i):
            with tr.span("histogram"):
                time.sleep(compute_s)
            with tr.span("net.barrier", tag=f"it{i}"):
                with tr.span("net.allgather", transport="kv", bytes=4):
                    time.sleep(wait_s)
    tr.close()
    return path


class TestReportMerge:
    def test_straggler_attribution(self, tmp_path):
        # rank 1 computes 4x longer; rank 0 waits in the barrier
        _make_rank_trace(tmp_path, 0, compute_s=0.01, wait_s=0.04)
        _make_rank_trace(tmp_path, 1, compute_s=0.04, wait_s=0.01)
        by = report.load_rank_traces(
            [str(tmp_path / "rank0.jsonl"), str(tmp_path / "rank1.jsonl")])
        m = report.merge_summary(by)
        assert m["ranks"] == [0, 1]
        assert m["world_size"] == 2
        assert m["run_id"] == "merge:test"
        assert m["aligned_iterations"] == 3
        st = m["straggler"]
        assert st["rank"] == 1
        assert st["slowest_rank_share"] > 0.5
        assert st["slowest_in_iters"] == 3
        # barrier-wait attribution: the FAST rank carries the wait
        assert (m["per_rank"][0]["barrier_wait_s"]
                > m["per_rank"][1]["barrier_wait_s"])
        # nested barrier/allgather must not double count: per-iteration
        # wait can never exceed the iteration wall
        for t in m["timeline"]:
            for r in (0, 1):
                assert t["wait_s"][r] <= t["wall_s"][r] + 1e-9
        # per-phase per-rank timeline includes the compute phase
        assert "histogram" in m["phases"]
        assert m["phases"]["histogram"][1] > m["phases"]["histogram"][0]

    def test_alignment_shrinks_to_common_iterations(self, tmp_path):
        """A rank whose trace was cut short (crash) only contributes the
        iterations every rank completed."""
        _make_rank_trace(tmp_path, 0, 0.005, 0.005, iters=5)
        _make_rank_trace(tmp_path, 1, 0.005, 0.005, iters=3)
        by = report.load_rank_traces(
            [str(tmp_path / "rank0.jsonl"), str(tmp_path / "rank1.jsonl")])
        m = report.merge_summary(by)
        assert m["aligned_iterations"] == 3
        assert m["per_rank"][0]["iterations"] == 5

    def test_merge_cli_renders_and_json(self, tmp_path, capsys):
        from lightgbm_tpu.cli import main

        _make_rank_trace(tmp_path, 0, 0.002, 0.01)
        _make_rank_trace(tmp_path, 1, 0.01, 0.002)
        assert main(["report", "merge", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "cross-rank report" in out
        assert "straggler: rank 1" in out
        assert "barrier wait" in out
        assert main(["report", "merge", str(tmp_path), "--json"]) == 0
        m = json.loads(capsys.readouterr().out)
        assert m["straggler"]["rank"] == 1

    def test_mismatched_run_ids_warn(self, tmp_path, capsys):
        from lightgbm_tpu.obs.trace import Tracer

        for rank, rid in ((0, "run:a"), (1, "run:b")):
            tr = Tracer()
            tr.set_identity(rank=rank, world_size=2, run_id=rid)
            tr.configure(str(tmp_path / f"rank{rank}.jsonl"))
            with tr.iteration(0):
                pass
            tr.close()
        by = report.load_rank_traces(
            [str(tmp_path / "rank0.jsonl"), str(tmp_path / "rank1.jsonl")])
        report.merge_summary(by)
        assert "distinct run_ids" in capsys.readouterr().err


class TestReportDiff:
    def test_identical_and_divergent_and_truncated(self, tmp_path,
                                                   capsys):
        from lightgbm_tpu.cli import main

        a = str(tmp_path / "a.jsonl")
        b = str(tmp_path / "b.jsonl")
        recs = [{"ev": "split", "it": 0, "s": 0, "feat": 3, "gain": 1.5},
                {"ev": "split", "it": 0, "s": 1, "feat": 2, "gain": 0.5},
                {"ev": "tree", "it": 0, "leaves": 3,
                 "values": [0.1, 0.2, 0.3]}]
        with open(a, "w") as f:
            f.writelines(json.dumps(r) + "\n" for r in recs)
        with open(b, "w") as f:
            f.writelines(json.dumps(r) + "\n" for r in recs)
        assert main(["report", "diff", a, b]) == 0
        capsys.readouterr()

        recs2 = [dict(r) for r in recs]
        recs2[1] = dict(recs2[1], feat=7, gain=0.25)
        with open(b, "w") as f:
            f.writelines(json.dumps(r) + "\n" for r in recs2)
        assert main(["report", "diff", a, b]) == 1
        out = capsys.readouterr().out
        assert "diverge at record 1" in out
        assert "feat: a=2  b=7" in out
        assert "gain: a=0.5  b=0.25" in out

        # truncated stream: divergence at the cut
        with open(b, "w") as f:
            f.writelines(json.dumps(r) + "\n" for r in recs[:1])
        assert main(["report", "diff", a, b]) == 1
        assert "ends early" in capsys.readouterr().out

    def test_values_divergence_names_the_leaf(self, tmp_path, capsys):
        from lightgbm_tpu.cli import main

        a = str(tmp_path / "a.jsonl")
        b = str(tmp_path / "b.jsonl")
        ra = {"ev": "tree", "it": 2, "k": 0, "leaves": 3,
              "values": [0.1, 0.2, 0.3]}
        rb = dict(ra, values=[0.1, 0.25, 0.3])
        with open(a, "w") as f:
            f.write(json.dumps(ra) + "\n")
        with open(b, "w") as f:
            f.write(json.dumps(rb) + "\n")
        assert main(["report", "diff", a, b]) == 1
        out = capsys.readouterr().out
        assert "values[1]: a=0.2  b=0.25" in out
        assert "it=2" in out


class TestNameRegistryLint:
    """Span/counter/gauge/event and Prometheus metric names are an
    interface (dashboards, report merge, the bench JSON key on them):
    every literal name emitted from the source must appear in the
    docs/OBSERVABILITY.md name registry."""

    TRACER_PAT = re.compile(
        r'tracer\.(?:span|counter|gauge|event)\(\s*[\'"]([A-Za-z0-9_.]+)[\'"]')
    METRIC_PAT = re.compile(
        r'(?:registry|reg)\.(?:labeled_)?(?:counter|gauge|histogram)\(\s*\n?\s*'
        r'[\'"]([A-Za-z0-9_:]+)[\'"]')
    # JitWatch program names are an interface too: the cost model keys
    # its inventory (and `report costs` its efficiency join) on them, so
    # a watched program whose name is missing from the registry silently
    # escapes cost accounting.  The pattern tolerates a positional fn
    # arg with one nested call level (JitWatch(self._build_program(...),
    # name=f"...")) and stops capture at "(" so the f-string chunk names
    # contribute their stable prefix (ptrainer.chunk, ...).
    JITWATCH_PAT = re.compile(
        r'JitWatch\((?:[^()\'"]|\([^()]*\))*?'
        r'(?:name\s*=\s*)?f?[\'"]([A-Za-z0-9_.]+)')

    def _source_names(self):
        import pathlib

        repo = pathlib.Path(__file__).resolve().parent.parent
        names = {}
        files = list((repo / "lightgbm_tpu").rglob("*.py"))
        files.append(repo / "bench.py")
        jitwatch_names = 0
        for p in files:
            src = p.read_text()
            for name in self.TRACER_PAT.findall(src):
                names.setdefault(name, str(p))
            for name in self.METRIC_PAT.findall(src):
                names.setdefault(name, str(p))
            for name in self.JITWATCH_PAT.findall(src):
                names.setdefault(name, str(p))
                jitwatch_names += 1
        assert len(names) > 40, "lint scan found suspiciously few names"
        assert jitwatch_names >= 10, (
            "lint scan found suspiciously few JitWatch constructions — "
            "did the JITWATCH_PAT regex rot?")
        return names, repo

    def test_every_emitted_name_is_documented(self):
        names, repo = self._source_names()
        doc = (repo / "docs" / "OBSERVABILITY.md").read_text()
        missing = {n: f for n, f in names.items() if f"`{n}`" not in doc}
        assert not missing, (
            "emitted observability names missing from the "
            "docs/OBSERVABILITY.md name registry table (names are an "
            f"interface — document them): {missing}")

    def test_lint_catches_an_undocumented_name(self, tmp_path):
        """The lint must actually bite: a name not in the doc table is
        reported missing."""
        doc = "| `documented.name` | span | x | y |"
        names = {"documented.name": "a.py", "brand.new.span": "b.py"}
        missing = {n for n in names if f"`{n}`" not in doc}
        assert missing == {"brand.new.span"}

    def test_jitwatch_pattern_catches_real_construction_shapes(self):
        """JITWATCH_PAT must survive every construction idiom the repo
        uses: positional name, name= kwarg, a nested-call fn argument,
        and the f-string chunk names (capturing their stable prefix)."""
        src = '\n'.join([
            'w = JitWatch(predict_raw, "serve.predict_raw",',
            '             phase="serve_batch")',
            'x = JitWatch(upd, name="ptrainer.traced.update",',
            '             phase="histogram")',
            'self._progs[k] = JitWatch(',
            '    self._build_program(alloc, bag_on, bag_freq, ff),',
            '    name=f"ptrainer.chunk(bag={int(bag_on)},ff={ff})",',
            ')',
        ])
        got = set(self.JITWATCH_PAT.findall(src))
        assert got == {"serve.predict_raw", "ptrainer.traced.update",
                       "ptrainer.chunk"}
        # and an undocumented watched program is reported missing
        doc = "| `serve.predict_raw` | program | x | y |"
        missing = {n for n in got if f"`{n}`" not in doc}
        assert missing == {"ptrainer.traced.update", "ptrainer.chunk"}
