"""Observability-layer tests: tracer unit behavior (span nesting, JSONL
round-trip, disabled-mode overhead), the report CLI, per-iteration record
schema through real ``engine.train`` runs (mask path and the traced
partitioned path with its histogram/split/partition phase breakdown),
and the JitWatch retrace detector.
"""

import json
import os
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import report
from lightgbm_tpu.obs.compilewatch import JitWatch
from lightgbm_tpu.obs.trace import Tracer, _NULL_SPAN


@pytest.fixture
def fresh_tracer(tmp_path):
    tr = Tracer()
    tr.configure(str(tmp_path / "trace.jsonl"))
    yield tr
    tr.close()


@pytest.fixture
def global_trace(tmp_path, monkeypatch):
    """Route the process-global tracer to a temp file for one test, and
    restore the disabled state afterwards."""
    from lightgbm_tpu.obs import tracer

    path = str(tmp_path / "trace.jsonl")
    monkeypatch.setenv("LIGHTGBM_TPU_TRACE", path)
    yield path
    tracer.close()
    tracer.path = None
    tracer.reset_aggregates()


def _read(path):
    return [json.loads(l) for l in open(path) if l.strip()]


class TestTracerUnit:
    def test_span_nesting_and_jsonl_roundtrip(self, fresh_tracer, tmp_path):
        tr = fresh_tracer
        with tr.span("outer", tag="a"):
            with tr.span("inner"):
                pass
            with tr.span("inner"):
                pass
        tr.counter("widgets", 3)
        tr.gauge("temp", 1.5, unit="C")
        tr.event("boom", detail="x")
        tr.close()
        recs = _read(tr.path)
        assert recs[0]["ev"] == "meta" and recs[0]["version"] == 1
        spans = [r for r in recs if r["ev"] == "span"]
        # children close (and are written) before the parent
        assert [s["name"] for s in spans] == ["inner", "inner", "outer"]
        assert all(s["parent"] == "outer" and s["depth"] == 1
                   for s in spans[:2])
        assert spans[2]["parent"] is None and spans[2]["depth"] == 0
        assert spans[2]["tag"] == "a"
        assert all(s["dur_s"] >= 0 for s in spans)
        counter = next(r for r in recs if r["ev"] == "counter")
        assert counter["name"] == "widgets" and counter["value"] == 3
        gauge = next(r for r in recs if r["ev"] == "gauge")
        assert gauge["value"] == 1.5 and gauge["unit"] == "C"
        assert any(r["ev"] == "event" and r["name"] == "boom" for r in recs)

    def test_iteration_record(self, fresh_tracer):
        tr = fresh_tracer
        with tr.iteration(7) as rec:
            with tr.span("histogram"):
                pass
            with tr.span("split"):
                pass
            rec["leaves"] = 31
        tr.close()
        it = next(r for r in _read(tr.path) if r["ev"] == "iter")
        assert it["iter"] == 7 and it["leaves"] == 31
        assert set(it["phases"]) == {"histogram", "split"}
        assert it["wall_s"] >= 0 and "host_rss_mb" in it
        assert "compiles" in it

    def test_disabled_mode_is_noop_and_cheap(self):
        tr = Tracer()
        assert not tr.enabled
        # structural near-zero-overhead proof: the SAME singleton no-op
        # context manager is returned for every disabled span
        assert tr.span("x") is _NULL_SPAN
        assert tr.span("y", attr=1) is _NULL_SPAN
        tr.counter("c")
        tr.gauge("g", 1.0)
        tr.event("e")
        with tr.iteration(0) as rec:
            assert rec is None
        t0 = time.perf_counter()
        for _ in range(100_000):
            with tr.span("hot"):
                pass
        assert time.perf_counter() - t0 < 1.0  # ~µs/op budget, loose

    def test_snapshot_aggregates(self, fresh_tracer):
        tr = fresh_tracer
        for _ in range(3):
            with tr.span("phase_a"):
                pass
        snap = tr.snapshot()
        assert snap["spans"]["phase_a"]["count"] == 3
        assert snap["spans"]["phase_a"]["total_s"] >= 0


class TestReportCli:
    def _make_trace(self, tmp_path):
        tr = Tracer()
        p = str(tmp_path / "t.jsonl")
        tr.configure(p)
        for i in range(4):
            with tr.iteration(i) as rec:
                with tr.span("histogram"):
                    pass
                with tr.span("split"):
                    pass
                rec["leaves"] = 15
        tr.close()
        return p

    def test_report_renders_table(self, tmp_path, capsys):
        from lightgbm_tpu.cli import main

        p = self._make_trace(tmp_path)
        assert main(["report", p]) == 0
        out = capsys.readouterr().out
        assert "run-trace report" in out
        assert "histogram" in out and "split" in out
        assert "iterations: 4" in out
        assert "compiles:" in out

    def test_report_json_mode(self, tmp_path, capsys):
        from lightgbm_tpu.cli import main

        p = self._make_trace(tmp_path)
        assert main(["report", p, "--json"]) == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["iterations"] == 4
        assert "histogram" in summary["phases"]

    def test_report_tolerates_torn_tail(self, tmp_path):
        p = self._make_trace(tmp_path)
        with open(p, "a") as f:
            f.write('{"ev":"iter","iter":99,"wa')  # killed mid-write
        summary = report.summarize(report.load_trace(p))
        assert summary["iterations"] == 4

    def test_report_missing_file(self, capsys):
        from lightgbm_tpu.cli import main

        assert main(["report", "/nonexistent/trace.jsonl"]) == 1
        assert main(["report"]) == 2


def _toy(n=500, f=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + X[:, 1] > 0).astype(float)
    return X, y


class TestEngineTraceSchema:
    def test_mask_path_iteration_records(self, global_trace):
        X, y = _toy()
        lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1},
                  lgb.Dataset(X, label=y), num_boost_round=5,
                  verbose_eval=False)
        recs = _read(global_trace)
        iters = [r for r in recs if r["ev"] == "iter"]
        assert len(iters) == 5
        for i, r in enumerate(iters):
            assert r["iter"] == i
            assert r["leaves"] > 0 and r["trees"] == 1
            assert r["wall_s"] > 0 and r["host_rss_mb"] > 0
            assert "compiles" in r
            # mask-path phases: the fused grow_tree is one program, so
            # the breakdown is at driver granularity
            assert {"boosting", "tree", "train_score"} <= set(r["phases"])
        assert any(r["ev"] == "event" and r["name"] == "train_begin"
                   for r in recs)

    def test_traced_partitioned_phase_breakdown(self, global_trace,
                                                monkeypatch):
        """The acceptance-criteria run: engine.train with
        LIGHTGBM_TPU_TRACE produces per-iteration records whose phases
        carry real device-fenced histogram/split/partition timings."""
        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
        monkeypatch.setenv("LIGHTGBM_TPU_TRACE_PHASES", "1")
        X, y = _toy(600)
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbose": -1},
                        lgb.Dataset(X, label=y), num_boost_round=3,
                        verbose_eval=False)
        assert bst.boosting.ptrainer is not None
        recs = _read(global_trace)
        iters = [r for r in recs if r["ev"] == "iter"]
        assert len(iters) == 3
        for r in iters:
            assert {"histogram", "split", "partition", "score_update"} <= set(
                r["phases"]
            )
            assert r["phases"]["histogram"] > 0
            assert r["phases"]["partition"] > 0
            assert r["leaves"] > 1
            assert r["mode"] == "traced"
        # the report CLI digests it
        summary = report.summarize(recs)
        assert summary["iterations"] == 3
        assert "partition" in summary["phases"]

    def test_fused_chunk_amortized_records(self, global_trace, monkeypatch):
        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
        monkeypatch.setenv("LIGHTGBM_TPU_TRACE_PHASES", "0")
        X, y = _toy(600)
        lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1},
                  lgb.Dataset(X, label=y), num_boost_round=3,
                  verbose_eval=False)
        recs = _read(global_trace)
        iters = [r for r in recs if r["ev"] == "iter"]
        assert len(iters) == 3
        assert all(r.get("amortized") for r in iters)
        assert all("fused_chunk" in r["phases"] for r in iters)
        # the chunk program itself is spanned and watched
        assert any(r["ev"] == "span" and r["name"] == "chunk_program"
                   for r in recs)

    def test_traced_matches_fused_classic(self, tmp_path, monkeypatch):
        """Traced mode must not change the model: bit-identical to the
        fused classic (LEVELGROW=0) path on a bagged+feature-sampled
        config."""
        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
        monkeypatch.setenv("LIGHTGBM_TPU_LEVELGROW", "0")
        X, y = _toy(1200, 8)
        params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
                  "min_data_in_leaf": 20, "bagging_fraction": 0.8,
                  "bagging_freq": 1, "feature_fraction": 0.7}
        preds = {}
        from lightgbm_tpu.obs import tracer

        try:
            for mode in ("0", "1"):
                monkeypatch.setenv(
                    "LIGHTGBM_TPU_TRACE", str(tmp_path / f"t{mode}.jsonl")
                )
                monkeypatch.setenv("LIGHTGBM_TPU_TRACE_PHASES", mode)
                bst = lgb.train(dict(params),
                                lgb.Dataset(X, label=y, params=dict(params)),
                                num_boost_round=4, verbose_eval=False)
                preds[mode] = bst.predict(X)
        finally:
            tracer.close()
            tracer.path = None
        np.testing.assert_array_equal(preds["0"], preds["1"])


class TestRetraceDetector:
    def test_flags_cache_growth_on_seen_signature(self):
        """The env-var-read-at-trace-time bug class: the jit cache key
        changes while the visible ARRAY signature does not — JitWatch
        must flag the recompile as an unexpected retrace."""
        import jax
        import jax.numpy as jnp

        fn = jax.jit(lambda x, mode: x * mode, static_argnames=("mode",))
        w = JitWatch(fn, name="test.retrace")
        x = jnp.ones((4,))
        w(x, mode=2)
        assert w.compiles == 1 and w.retraces == 0
        w(x, mode=2)  # cache hit
        assert w.compiles == 1
        w(x, mode=3)  # same arrays, new static value -> hidden retrace
        assert w.compiles == 2 and w.retraces == 1

    def test_new_shapes_are_not_retraces(self):
        import jax
        import jax.numpy as jnp

        w = JitWatch(jax.jit(lambda x: x + 1), name="test.shapes")
        w(jnp.ones((3,)))
        w(jnp.ones((5,)))
        assert w.compiles == 2 and w.retraces == 0
        assert len(w._sigs) == 2

    def test_levelgrow_env_participates_in_program_identity(self,
                                                            monkeypatch):
        """Satellite regression: LIGHTGBM_TPU_LEVELGROW is read at
        trainer construction into PGrowParams (static, part of the jit
        cache key), not at trace time inside the grower."""
        from lightgbm_tpu.ops.pgrow import levelgrow_env_params

        monkeypatch.setenv("LIGHTGBM_TPU_LEVELGROW", "0")
        monkeypatch.setenv("LIGHTGBM_TPU_MAXLVL", "7")
        assert levelgrow_env_params() == {"levelwise": False, "max_levels": 7}
        monkeypatch.setenv("LIGHTGBM_TPU_LEVELGROW", "1")
        assert levelgrow_env_params()["levelwise"] is True

        monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
        X, y = _toy(600)
        params = {"objective": "binary", "num_leaves": 7, "verbose": -1}
        monkeypatch.setenv("LIGHTGBM_TPU_LEVELGROW", "0")
        bst = lgb.train(dict(params), lgb.Dataset(X, label=y),
                        num_boost_round=1, verbose_eval=False)
        assert bst.boosting.ptrainer.params.levelwise is False
        monkeypatch.setenv("LIGHTGBM_TPU_LEVELGROW", "1")
        bst = lgb.train(dict(params), lgb.Dataset(X, label=y),
                        num_boost_round=1, verbose_eval=False)
        assert bst.boosting.ptrainer.params.levelwise is True


class TestDisabledOverheadEndToEnd:
    def test_training_emits_nothing_when_disabled(self, tmp_path,
                                                  monkeypatch):
        """With tracing off the instrumented paths must not write records
        or block dispatch (fence is a no-op)."""
        from lightgbm_tpu.obs import tracer
        from lightgbm_tpu.obs.trace import fence

        monkeypatch.delenv("LIGHTGBM_TPU_TRACE", raising=False)
        tracer.close()
        tracer.path = None
        tracer.refresh_from_env()
        assert not tracer.enabled
        assert fence(None) is None
        X, y = _toy()
        lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1},
                  lgb.Dataset(X, label=y), num_boost_round=2,
                  verbose_eval=False)
        assert not tracer.enabled and tracer.path is None
