"""Two-process distributed data-parallel parity (VERDICT #5 / SURVEY §2.6:
the reference's machine-list + socket Allreduce collapses to
jax.distributed.initialize + XLA collectives over the global mesh).

Spawns two localhost CPU processes (4 virtual devices each -> one
8-device global mesh), grows one data-parallel tree with each process
holding half the rows, and asserts the replicated split records equal a
single-process serial grow over the full data.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.mark.slow
def test_two_process_data_parallel_parity(tmp_path):
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "multihost_worker.py")
    out = str(tmp_path / "rank0.npz")
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(r), str(port), out],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        )
        for r in (0, 1)
    ]
    logs = []
    for p in procs:
        o, _ = p.communicate(timeout=600)
        logs.append(o.decode())
    assert all(p.returncode == 0 for p in procs), "\n".join(logs)

    got = np.load(out)

    # single-process serial ground truth on the full data
    from lightgbm_tpu.ops.grow import GrowParams, grow_tree
    from lightgbm_tpu.ops.split import FeatureMeta, SplitHyper

    rng = np.random.default_rng(42)
    N, F, B = 4096, 6, 16
    bins = rng.integers(0, B, size=(N, F), dtype=np.uint8)
    grad = rng.standard_normal(N).astype(np.float32)
    hess = np.abs(rng.standard_normal(N)).astype(np.float32) + 0.1
    meta = FeatureMeta(
        num_bins=jnp.full((F,), B, jnp.int32),
        default_bin=jnp.zeros((F,), jnp.int32),
        is_categorical=jnp.zeros((F,), bool),
    )
    hyper = SplitHyper(
        lambda_l1=jnp.float32(0.0), lambda_l2=jnp.float32(0.01),
        min_data_in_leaf=jnp.float32(20), min_sum_hessian_in_leaf=jnp.float32(1e-3),
        min_gain_to_split=jnp.float32(0.0),
    )
    gr = grow_tree(
        jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
        jnp.ones((N,), jnp.float32), jnp.ones((F,), jnp.float32),
        meta, hyper, GrowParams(num_leaves=15, num_bins=B),
    )
    ns = int(gr.num_splits)
    assert int(got["num_splits"]) == ns and ns > 3
    np.testing.assert_array_equal(got["rec_feat"], np.asarray(gr.rec_feat[:ns]))
    np.testing.assert_array_equal(got["rec_thr"], np.asarray(gr.rec_thr[:ns]))
    np.testing.assert_array_equal(got["rec_leaf"], np.asarray(gr.rec_leaf[:ns]))
    np.testing.assert_allclose(
        got["rec_lval"], np.asarray(gr.rec_lval[:ns]), rtol=1e-4, atol=1e-6
    )
    # rank 0's local leaf assignment matches the serial grower's rows
    # (unequal 2200/1896 shards exercise the pad-to-global-max path)
    np.testing.assert_array_equal(
        got["leaf_id_local"], np.asarray(gr.leaf_id[:2200])
    )


@pytest.mark.slow
def test_two_process_fused_data_parallel_parity(tmp_path, monkeypatch):
    """The fused ShardedPartitionedTrainer's process_count>1 branches
    (cross-process shard assembly, addressable_shards gather, padded-row
    bookkeeping — VERDICT r4 weak-4) must produce the same trees as the
    single-process serial fused trainer on the same data."""
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "multihost_worker.py")
    out = str(tmp_path / "ptrainer_model.txt")
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(r), str(port), out, "ptrainer"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        )
        for r in (0, 1)
    ]
    logs = []
    for p in procs:
        o, _ = p.communicate(timeout=900)
        logs.append(o.decode())
    assert all(p.returncode == 0 for p in procs), "\n".join(logs)

    # single-process serial fused trainer on the full data (same integer
    # dataset as the worker -> identical bin mappers)
    import lightgbm_tpu as lgb

    monkeypatch.setenv("LIGHTGBM_TPU_PGROW", "force")
    rng = np.random.default_rng(5)
    N, F = 3000, 6
    X = rng.integers(0, 12, size=(N, F)).astype(np.float32)
    wv = rng.standard_normal(F)
    yp = 1.0 / (1.0 + np.exp(-((X - 6) @ wv * 0.3)))
    y = (rng.random(N) < yp).astype(np.float32)
    p = dict(objective="binary", tree_learner="serial", num_leaves=15,
             learning_rate=0.2, max_bin=31, min_data_in_leaf=20, verbose=-1)
    ref = lgb.train(p, lgb.Dataset(X, label=y, params=dict(p)), 4,
                    verbose_eval=False)

    with open(out) as fh:
        got = lgb.Booster(model_str=fh.read())
    gi, ri = got.dump_model()["tree_info"], ref.dump_model()["tree_info"]
    assert len(gi) == len(ri) and len(gi) == 4

    def walk(node, acc):
        if "split_feature" in node:
            acc.append((node["split_feature"], node["threshold"]))
            walk(node["left_child"], acc)
            walk(node["right_child"], acc)

    for tg, tr in zip(gi, ri):
        ag, ar = [], []
        walk(tg["tree_structure"], ag)
        walk(tr["tree_structure"], ar)
        assert ag == ar  # identical split structure, tree for tree
    np.testing.assert_allclose(got.predict(X), ref.predict(X),
                               rtol=3e-3, atol=3e-4)


@pytest.mark.slow
def test_two_process_distributed_find_bin_bit_identical(tmp_path):
    """dataset_loader.cpp:733-835: feature-sharded find-bin + mapper
    allgather produces mappers bit-identical to single-process find-bin
    when both ranks see the same data."""
    import pickle

    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "multihost_worker.py")
    out = str(tmp_path / "findbin0.pkl")
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(r), str(port), out, "findbin"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        )
        for r in (0, 1)
    ]
    logs = []
    for p in procs:
        o, _ = p.communicate(timeout=600)
        logs.append(o.decode())
    assert all(p.returncode == 0 for p in procs), "\n".join(logs)
    with open(out, "rb") as fh:
        got = pickle.load(fh)

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset

    rng = np.random.default_rng(9)
    X = rng.standard_normal((5000, 13))
    X[:, 3] = np.round(X[:, 3] * 2)
    y = rng.standard_normal(5000)
    cfg = Config.from_params({"max_bin": 31, "verbose": -1})
    ref = BinnedDataset.from_raw(X, cfg, label=y)
    assert len(got["states"]) == len(ref.bin_mappers)
    for sg, mr in zip(got["states"], ref.bin_mappers):
        sr = mr.state()
        assert set(sg) == set(sr)
        for k in sr:
            np.testing.assert_array_equal(np.asarray(sg[k]), np.asarray(sr[k]), err_msg=k)
    np.testing.assert_array_equal(got["binned"], ref.binned)
    np.testing.assert_array_equal(got["used"], ref.used_feature_map)


@pytest.mark.slow
@pytest.mark.faultinject
def test_two_process_ckpt_resume_bit_identical(tmp_path):
    """Checkpoint/resume on the 2-process sharded fused trainer
    (docs/CHECKPOINT.md multihost protocol): both ranks barrier on the
    checkpointed iteration, rank 0 writes one container blob holding
    every rank's state (incl. each shard's physical row permutation),
    and the resumed run is bit-identical to the uninterrupted one on
    BOTH ranks (the worker asserts rank-locally; rank 0 reports)."""
    import json

    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "multihost_worker.py")
    out = str(tmp_path / "ckptresume0.json")
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(r), str(port), out, "ckptresume"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        )
        for r in (0, 1)
    ]
    logs = []
    for p in procs:
        o, _ = p.communicate(timeout=900)
        logs.append(o.decode())
    assert all(p.returncode == 0 for p in procs), "\n".join(logs)
    with open(out) as fh:
        got = json.load(fh)
    assert got["match"] is True
    assert got["trees"] >= 6


@pytest.mark.slow
def test_two_process_sketch_merge_bit_identical(tmp_path):
    """Streaming-ingest sketch banks merged across two hosts
    (parallel/collect.py allgather, the ingest mirror of distributed
    find-bin) equal a single-process sketch of the full data exactly
    while unspilled."""
    import pickle

    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "multihost_worker.py")
    out = str(tmp_path / "sketch0.pkl")
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(r), str(port), out, "sketchmerge"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        )
        for r in (0, 1)
    ]
    logs = []
    for p in procs:
        o, _ = p.communicate(timeout=600)
        logs.append(o.decode())
    assert all(p.returncode == 0 for p in procs), "\n".join(logs)
    with open(out, "rb") as fh:
        got = pickle.load(fh)

    from lightgbm_tpu.data.stats import SketchCollector

    rng = np.random.default_rng(17)
    X = rng.integers(-4, 9, size=(6000, 5)).astype(np.float64)
    X[rng.random((6000, 5)) < 0.05] = np.nan
    ref = SketchCollector(categorical={4}, cap=100_000)
    for lo in range(0, 6000, 700):
        ref.update(X[lo : lo + 700])
    assert len(got["banks"]) == len(ref.sketches) == 5
    for (gv, gc), sk, (tot, zc, nc) in zip(
        got["banks"], ref.sketches, got["extras"]
    ):
        rv, rc = sk.to_distinct_counts()
        np.testing.assert_array_equal(gv, rv)
        np.testing.assert_array_equal(gc, rc)
        assert tot == sk.total_cnt
        assert zc == getattr(sk, "zero_cnt", -1)
        assert nc == getattr(sk, "nan_cnt", -1)
