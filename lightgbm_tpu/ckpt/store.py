"""On-disk checkpoint store: atomic writes, CRC manifest, retention.

Layout of a checkpoint directory:

  ckpt_00000010.npz      TrainState blob for step 10 (multihost runs
                         wrap one blob per host in a container npz)
  MANIFEST.json          {"entries": {name: {step, crc32, size, ts}},
                          "complete_step": int|null}

Write protocol (crash-safe at every point):

  1. blob -> ``<name>.tmp.<pid>`` in the same directory, flush+fsync;
  2. ``os.rename`` onto the final name (atomic within a filesystem);
  3. directory fsync (the rename itself must survive a crash);
  4. manifest rewritten through the same tmp+fsync+rename dance.

A checkpoint is *valid* only when its manifest entry exists and the
file's size+CRC32 match — a crash between (2) and (4) leaves a data
file without an entry, which discovery ignores; a torn/corrupt tail
file fails the CRC and is skipped with a warning, falling back to the
previous checkpoint (the acceptance contract for kill/resume).
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import Dict, List, Optional, Tuple

from ..utils.log import Log

_PREFIX = "ckpt_"
_SUFFIX = ".npz"
_MANIFEST = "MANIFEST.json"


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:  # pragma: no cover - platform without dir fsync
        pass


def _atomic_write(path: str, data: bytes) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


class CheckpointStore:
    """Rolling checkpoint files + CRC manifest in one directory."""

    def __init__(self, directory: str, keep_last: int = 3):
        self.dir = directory
        self.keep_last = max(1, int(keep_last))

    # -- manifest ------------------------------------------------------
    def _manifest_path(self) -> str:
        return os.path.join(self.dir, _MANIFEST)

    def read_manifest(self) -> Dict:
        try:
            with open(self._manifest_path()) as f:
                m = json.load(f)
            if isinstance(m, dict) and isinstance(m.get("entries"), dict):
                return m
        except (OSError, ValueError):
            pass
        return {"entries": {}, "complete_step": None}

    def _write_manifest(self, manifest: Dict) -> None:
        _atomic_write(self._manifest_path(),
                      json.dumps(manifest, indent=1).encode())

    # -- naming --------------------------------------------------------
    def path_for(self, step: int) -> str:
        return os.path.join(self.dir, f"{_PREFIX}{int(step):08d}{_SUFFIX}")

    @staticmethod
    def step_of(name: str) -> Optional[int]:
        base = os.path.basename(name)
        if not (base.startswith(_PREFIX) and base.endswith(_SUFFIX)):
            return None
        try:
            return int(base[len(_PREFIX): -len(_SUFFIX)])
        except ValueError:
            return None

    # -- write side ----------------------------------------------------
    def save(self, step: int, blob: bytes) -> str:
        """Atomically persist ``blob`` as the step-``step`` checkpoint,
        update the manifest, and apply rolling retention."""
        os.makedirs(self.dir, exist_ok=True)
        path = self.path_for(step)
        _atomic_write(path, blob)
        manifest = self.read_manifest()
        manifest["entries"][os.path.basename(path)] = {
            "step": int(step),
            "crc32": zlib.crc32(blob) & 0xFFFFFFFF,
            "size": len(blob),
            "ts": round(time.time(), 3),
        }
        # a new checkpoint means the run is live again — any stale
        # completion marker from a previous finished run is void
        manifest["complete_step"] = None
        self._gc(manifest)
        self._write_manifest(manifest)
        return path

    def mark_complete(self, step: int) -> None:
        """Record that training finished normally at ``step`` — the
        auto-resume policy then leaves the next fresh run alone.  A run
        that never wrote a checkpoint has nothing to mark (and should
        not litter its output directory with a manifest)."""
        manifest = self.read_manifest()
        if not manifest["entries"] and not os.path.exists(self._manifest_path()):
            return
        manifest["complete_step"] = int(step)
        try:
            self._write_manifest(manifest)
        except OSError:  # pragma: no cover - completion marker best-effort
            pass

    def complete_step(self) -> Optional[int]:
        return self.read_manifest().get("complete_step")

    def _gc(self, manifest: Dict) -> None:
        entries = manifest["entries"]
        steps = sorted((e["step"], name) for name, e in entries.items())
        while len(steps) > self.keep_last:
            _, name = steps.pop(0)
            entries.pop(name, None)
            try:
                os.unlink(os.path.join(self.dir, name))
            except OSError:
                pass

    # -- read side -----------------------------------------------------
    def steps(self) -> List[int]:
        return sorted(e["step"] for e in self.read_manifest()["entries"].values())

    def _verify(self, name: str, entry: Dict) -> Optional[bytes]:
        path = os.path.join(self.dir, name)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError as e:
            Log.warning("Checkpoint %s unreadable (%s); skipping", path, e)
            return None
        if len(blob) != int(entry.get("size", -1)):
            Log.warning(
                "Checkpoint %s is truncated (%d bytes, manifest says %s); "
                "skipping", path, len(blob), entry.get("size"),
            )
            return None
        if (zlib.crc32(blob) & 0xFFFFFFFF) != int(entry.get("crc32", -1)):
            Log.warning("Checkpoint %s fails its CRC; skipping", path)
            return None
        return blob

    def latest_valid(self) -> Optional[Tuple[int, bytes]]:
        """Newest checkpoint that passes size+CRC verification — a
        corrupt/truncated tail falls back to the previous one."""
        manifest = self.read_manifest()
        ordered = sorted(
            manifest["entries"].items(), key=lambda kv: -kv[1]["step"]
        )
        for name, entry in ordered:
            blob = self._verify(name, entry)
            if blob is not None:
                return int(entry["step"]), blob
        return None

    def load_step(self, step: int) -> Optional[bytes]:
        entries = self.read_manifest()["entries"]
        for name, entry in entries.items():
            if int(entry["step"]) == int(step):
                return self._verify(name, entry)
        return None
