"""``CheckpointManager`` — the training-side checkpoint driver.

Used two ways:

  - as an after-iteration **callback** (``engine.train`` threads it into
    the callback list; ``order=40`` puts it after ``early_stopping`` so
    the captured callback state is current through the iteration);
  - **directly** by the CLI's training loop via :meth:`maybe_save`.

Capture is synchronous (device arrays are pulled at a consistent
iteration boundary); serialization + the fsync'd write happen on a
single background worker thread, so steady-state training overlaps the
disk write — the bench ``checkpoint`` section measures the residual
per-iteration overhead.  At most one write is in flight: the next save
waits for the previous one, bounding buffered checkpoint memory to one
blob.

Preemption: :meth:`install_signal_handlers` arms SIGTERM (the shape of
a preemptible-VM warning).  The flag is checked at the next iteration
boundary, where the manager writes a final checkpoint *synchronously*
and raises :class:`PreemptionExit`; ``engine.train`` / the CLI catch it,
finalize, and return — the next run auto-resumes bit-identically.

Multihost protocol: every host captures its local state and enters an
allgather barrier carrying its iteration number (``parallel/collect.py``
— KV-store transport on XLA:CPU, device allgather elsewhere).  The
barrier proves all hosts sit on the same iteration; host 0 then writes
one container blob holding every host's state.  On resume each host
reads the same file and restores its own rank's entry.
"""

from __future__ import annotations

import concurrent.futures
import io
import json
import signal
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..obs import tracer
from ..utils.log import Log
from .state import (CheckpointMismatch, TrainState, capture,
                    combine_fingerprint_parts, data_fingerprint_parts,
                    merge_to_canonical, reshard_to_local, restore)
from .store import CheckpointStore


class PreemptionExit(RuntimeError):
    """Raised at an iteration boundary after a preemption signal once
    the final checkpoint is safely on disk."""

    def __init__(self, step: int):
        super().__init__(f"preempted; checkpoint flushed at iteration {step}")
        self.step = step


def _wrap_hosts(blobs: List[bytes]) -> bytes:
    """Per-host TrainState blobs -> one container npz."""
    payload = {f"rank_{r}": np.frombuffer(b, np.uint8) for r, b in enumerate(blobs)}
    payload["__hosts__"] = np.asarray(len(blobs), np.int64)
    buf = io.BytesIO()
    np.savez(buf, **payload)
    return buf.getvalue()


def _unwrap_host(blob: bytes, rank: int) -> bytes:
    """Extract this host's TrainState blob (identity for single-host)."""
    with np.load(io.BytesIO(blob), allow_pickle=False) as z:
        if "__hosts__" not in z.files:
            return blob
        hosts = int(z["__hosts__"])
        if rank >= hosts:
            raise ValueError(
                f"checkpoint holds {hosts} host states but this is rank {rank}"
            )
        return z[f"rank_{rank}"].tobytes()


class CheckpointManager:
    """Periodic TrainState checkpointing with background writes."""

    order = 40  # after early_stopping (30): its state is current
    before_iteration = False

    def __init__(self, directory: str, freq: int = 0, keep_last: int = 3,
                 background: bool = True):
        self.store = CheckpointStore(directory, keep_last=keep_last)
        self.freq = int(freq)
        self.background = bool(background)
        self._executor: Optional[concurrent.futures.ThreadPoolExecutor] = None
        self._pending: Optional[concurrent.futures.Future] = None
        self._preempt = threading.Event()
        self._tracked: List[Any] = []
        self._last_saved = -1
        self.stats: Dict[str, Any] = {
            "saves": 0, "bytes": 0, "save_s": [], "capture_s": [],
        }

    # -- wiring --------------------------------------------------------
    def track_callbacks(self, callbacks) -> None:
        """Register callbacks whose closure state must survive resume
        (those exposing ``ckpt_state``/``ckpt_restore``)."""
        self._tracked = [cb for cb in callbacks
                         if hasattr(cb, "ckpt_state") and cb is not self]

    def install_signal_handlers(self, signals=(signal.SIGTERM,)) -> None:
        """Arm preemption signals: the handler only sets a flag; the
        flush happens at the next iteration boundary on the main
        thread (signal-safe by construction)."""
        def _handler(signum, frame):
            Log.warning(
                "Received signal %d: flushing a checkpoint at the next "
                "iteration boundary, then exiting", signum,
            )
            self._preempt.set()

        for sig in signals:
            signal.signal(sig, _handler)

    def request_preemption(self) -> None:
        """Programmatic preemption (tests / embedding runtimes)."""
        self._preempt.set()

    @property
    def preempted(self) -> bool:
        return self._preempt.is_set()

    # -- callback protocol ---------------------------------------------
    def __call__(self, env) -> None:
        self.maybe_save(env.model)

    # -- core ----------------------------------------------------------
    def maybe_save(self, booster, force: bool = False) -> bool:
        """Checkpoint when the iteration counter sits on a ``freq``
        boundary (or ``force``).  Raises :class:`PreemptionExit` after a
        flush triggered by a preemption signal."""
        step = int(booster.boosting.iter)
        if self._preempt.is_set():
            if step != self._last_saved:
                self.save(booster, sync=True)
            else:
                self.flush()
            raise PreemptionExit(step)
        if not force:
            if self.freq <= 0 or step <= 0 or step % self.freq != 0:
                return False
        if step == self._last_saved:
            return False
        self.save(booster)
        return True

    def save(self, booster, sync: bool = False) -> int:
        """Capture + write one checkpoint; returns the step."""
        t0 = time.perf_counter()
        state = capture(booster, extra_py=self._callback_state())
        self.stats["capture_s"].append(time.perf_counter() - t0)
        step = state.iteration
        with tracer.span("ckpt.serialize", iter=step):
            blob = state.to_bytes()

        import jax

        nproc = jax.process_count()
        if nproc > 1:
            from ..parallel.collect import allgather_bytes
            from ..parallel.net import NetError

            try:
                with tracer.span("ckpt.barrier", iter=step):
                    gathered = allgather_bytes(step.to_bytes(8, "little") + blob)
            except NetError as e:
                # a peer died or the collective timed out mid-barrier:
                # nothing from THIS boundary is durable, but the last
                # completed checkpoint is — flush the writer so it is
                # fully on disk and surface the failure for the
                # cooperative abort path (engine/cli auto-resume)
                self.flush()
                Log.warning(
                    "Checkpoint barrier at iteration %d failed (%s); the "
                    "last completed checkpoint remains the resume point",
                    step, e,
                )
                raise
            steps = [int.from_bytes(g[:8], "little") for g in gathered]
            if len(set(steps)) != 1:
                Log.fatal(
                    "Checkpoint barrier saw divergent iterations across "
                    "hosts: %s", steps,
                )
            self._last_saved = step
            if jax.process_index() != 0:
                return step  # host 0 owns the write
            # canonical global layout (docs/CHECKPOINT.md): merge the
            # rank states into one global-row-order container so the
            # checkpoint resumes at ANY world size, not just this one
            with tracer.span("ckpt.merge_canonical", iter=step,
                             world=nproc):
                blob = merge_to_canonical(
                    [TrainState.from_bytes(g[8:]) for g in gathered]
                ).to_bytes()

        self._last_saved = step
        if self.background and not sync:
            self._submit_write(step, blob, t0)
        else:
            self.flush()
            self._write(step, blob, t0)
        return step

    def _submit_write(self, step: int, blob: bytes, t0: float) -> None:
        if self._executor is None:
            self._executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt-writer"
            )
        self.flush()  # one write in flight: bounds buffered blobs to one
        self._pending = self._executor.submit(self._write, step, blob, t0)

    def _write(self, step: int, blob: bytes, t0: float) -> None:
        try:
            path = self.store.save(step, blob)
        except Exception as e:  # pragma: no cover - disk-full etc.
            Log.warning("Checkpoint write for iteration %d failed: %s", step, e)
            return
        dur = time.perf_counter() - t0
        self.stats["saves"] += 1
        self.stats["bytes"] = len(blob)
        self.stats["save_s"].append(dur)
        tracer.counter("ckpt.bytes", len(blob))
        tracer.event("ckpt.saved", iter=step, bytes=len(blob),
                     secs=round(dur, 4), path=path)
        Log.info("Checkpoint saved at iteration %d (%d bytes)", step, len(blob))

    def flush(self) -> None:
        """Wait for the in-flight background write, if any."""
        if self._pending is not None:
            self._pending.result()
            self._pending = None

    def close(self) -> None:
        self.flush()
        if self._executor is not None:
            self._executor.shutdown(wait=True)
            self._executor = None

    def mark_complete(self, booster) -> None:
        """Training finished normally: flush and leave a completion
        marker so the next fresh run doesn't auto-resume a done run."""
        self.flush()
        self.store.mark_complete(int(booster.boosting.iter))

    # -- resume --------------------------------------------------------
    def try_restore(self, booster, require: bool = False,
                    ignore_complete: bool = False) -> Optional[TrainState]:
        """Restore the latest valid checkpoint into ``booster``.

        Returns the restored state, or ``None`` when there is nothing to
        resume (no valid checkpoint, or the previous run completed and
        ``ignore_complete`` is not set).  Fingerprint mismatches raise
        ``CheckpointMismatch`` — resume never silently retrains."""
        latest = self.store.latest_valid()
        if latest is None:
            if require:
                Log.fatal("No valid checkpoint found in %s", self.store.dir)
            return None
        if not ignore_complete and self.store.complete_step() is not None:
            Log.info(
                "Checkpoints in %s belong to a completed run; starting fresh",
                self.store.dir,
            )
            return None
        step, blob = latest

        import jax

        rank, nproc = jax.process_index(), jax.process_count()
        blob = _unwrap_host(blob, rank)  # legacy per-rank containers only
        state = TrainState.from_bytes(blob)
        if "world_size" in state.meta:
            state = self._reshard_to_current(booster, state, rank, nproc)
        restore(booster, state)
        self._restore_callbacks(state)
        self._last_saved = step
        return state

    def _reshard_to_current(self, booster, state: TrainState, rank: int,
                            nproc: int) -> TrainState:
        """Adapt a canonical global-layout checkpoint to the current
        topology.  All ranks enter in lockstep (they all read the same
        container): a tiny allgather of per-rank row counts + CRC
        primitives establishes the current partition and proves the
        concatenated shards are byte-for-byte the saved global dataset
        before any state is sliced."""
        b = booster.boosting
        local_rows = int(b.num_data)
        valid_rows = [int(np.asarray(vs).shape[1]) for vs in b.valid_scores]
        parts = data_fingerprint_parts(b.train_set)
        entry = {"rows": local_rows, "valid": valid_rows, "parts": parts}
        if nproc > 1:
            from ..parallel.collect import allgather_bytes

            gathered = [
                json.loads(g)
                for g in allgather_bytes(
                    json.dumps(entry).encode(), purpose="ckpt_reshard")
            ]
        else:
            gathered = [entry]
        shard_rows = [int(g["rows"]) for g in gathered]
        valid_shard = [[int(g["valid"][i]) for g in gathered]
                       for i in range(len(valid_rows))]
        global_fp = combine_fingerprint_parts([g["parts"] for g in gathered])
        if global_fp != state.meta["data_fingerprint"]:
            raise CheckpointMismatch(
                "checkpoint was written against a different global dataset "
                f"(checkpoint {state.meta['data_fingerprint']}, run "
                f"{global_fp}); refusing to resume"
            )
        local_fp = combine_fingerprint_parts([parts])
        saved_w = int(state.meta.get("world_size", 1))
        if saved_w != nproc:
            Log.info(
                "Resharding checkpoint from world size %d to %d "
                "(canonical global layout)", saved_w, nproc,
            )
        return reshard_to_local(
            state, rank, shard_rows, valid_shard, local_fp,
            bag_seed=int(getattr(b.config, "bagging_seed", 0)),
        )

    # -- tracked-callback state ----------------------------------------
    def _callback_state(self) -> Dict[str, Any]:
        out = {}
        for i, cb in enumerate(self._tracked):
            name = getattr(cb, "ckpt_name", type(cb).__name__)
            try:
                out[f"cb/{i}/{name}"] = cb.ckpt_state()
            except Exception as e:  # pragma: no cover - defensive
                Log.warning("callback %s state capture failed: %s", name, e)
        return {"callbacks": json.loads(json.dumps(out, default=str))} if out else {}

    def _restore_callbacks(self, state: TrainState) -> None:
        saved = state.py.get("callbacks") or {}
        for i, cb in enumerate(self._tracked):
            name = getattr(cb, "ckpt_name", type(cb).__name__)
            st = saved.get(f"cb/{i}/{name}")
            if st is not None and hasattr(cb, "ckpt_restore"):
                cb.ckpt_restore(st)
