"""Versioned training-state snapshots (``TrainState``).

A checkpoint must reproduce training *exactly*, so the state is the
closure of everything the boosting drivers read across an iteration
boundary:

  - the ensemble's trees in **binary** — stacked SoA arrays in the same
    spirit as the serving ``PredictorArtifact`` npz layout (one entry
    per ``Tree`` field, ``(T, M)``/``(T, L)`` padded), but *complete*:
    training needs bin-space thresholds, leaf counts/parents and
    per-tree shrinkage that the inference artifact drops, and a text
    round-trip through ``%g`` formatting would not be bit-faithful;
  - the device score caches (train + every valid set) in f32;
  - every RNG stream: the bagging ``RandomState``, the
    feature-fraction ``utils.random.Random``, DART's drop ``Random``,
    GOSS's chained ``PRNGKey`` (the fused partitioned trainers need no
    RNG state — they fold a static base key with the iteration number);
  - early-stopping bests / messages and the iteration counter;
  - the fused partitioned trainer's physical row permutation (histogram
    accumulation order follows the partition layout, so restarting from
    an identity layout would change float summation order);
  - config + dataset fingerprints: resume **refuses** to run on a
    mismatch instead of silently training a different problem.

Serialization is one ``.npz`` (uncompressed — checkpoint cadence beats
bytes) with a ``__meta__`` JSON entry, mirroring ``serve/artifact.py``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import zlib
from typing import Any, Dict, Optional

import numpy as np

from ..model.tree import Tree
from ..utils.log import Log

FORMAT_VERSION = 1

# Tree SoA fields: (name, dtype, padded-axis) where axis "m" arrays hold
# num_leaves-1 node records and "l" arrays hold num_leaves leaf records.
_TREE_FIELDS = (
    ("left_child", np.int32, "m"),
    ("right_child", np.int32, "m"),
    ("split_feature_inner", np.int32, "m"),
    ("split_feature", np.int32, "m"),
    ("threshold_in_bin", np.int32, "m"),
    ("threshold", np.float64, "m"),
    ("decision_type", np.int8, "m"),
    ("default_value", np.float64, "m"),
    ("zero_bin", np.int32, "m"),
    ("default_bin_for_zero", np.int32, "m"),
    ("split_gain", np.float64, "m"),
    ("internal_value", np.float64, "m"),
    ("internal_count", np.int64, "m"),
    ("leaf_parent", np.int32, "l"),
    ("leaf_value", np.float64, "l"),
    ("leaf_count", np.int64, "l"),
)

# Config fields that may legitimately differ between the original run
# and its resume (paths, task plumbing, run length, verbosity) — they
# never change the per-iteration math, so they stay out of the
# fingerprint.
_FP_VOLATILE = {
    "task", "config_file", "data", "valid_data", "input_model",
    "output_model", "output_result", "convert_model",
    "convert_model_language", "num_iterations", "num_iteration_predict",
    "snapshot_freq", "verbose", "num_threads", "is_save_binary_file",
    "is_predict_leaf_index", "is_predict_raw_score", "output_freq",
    "metric_freq", "machine_list_file", "local_listen_port", "time_out",
    "checkpoint_dir", "checkpoint_freq", "checkpoint_keep",
    "checkpoint_resume", "is_training_metric", "pred_early_stop",
    "pred_early_stop_freq", "pred_early_stop_margin",
    # prefetch depth only changes pipelining, never the math (the
    # math-relevant out_of_core/ooc_chunk_rows stay fingerprinted, and
    # the chunk grid itself is checked via meta["ooc_schedule"])
    "ooc_prefetch_depth",
}


class CheckpointMismatch(RuntimeError):
    """Resume refused: the checkpoint was written by a different
    config or against a different dataset."""


# ----------------------------------------------------------------------
# fingerprints
# ----------------------------------------------------------------------
def config_fingerprint(config) -> str:
    """Stable digest of the math-relevant configuration."""
    d = dataclasses.asdict(config)
    for key in _FP_VOLATILE:
        d.pop(key, None)
    blob = json.dumps(d, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def data_fingerprint(binned_ds) -> str:
    """Digest of the constructed dataset (binned matrix + label).  CRC32
    keeps this cheap even at large N; cached on the dataset object so
    periodic checkpoints don't rescan the matrix."""
    cached = getattr(binned_ds, "_ckpt_fingerprint", None)
    if cached is not None:
        return cached
    binned = np.asarray(binned_ds.binned)
    # block-wise CRC: chunked zlib.crc32 equals the whole-buffer value,
    # and never materializes a memmapped (out-of-core) matrix
    crc = 0
    step = 65536
    for s in range(0, binned.shape[0], step):
        crc = zlib.crc32(
            np.ascontiguousarray(binned[s: s + step]).tobytes(), crc)
    label = binned_ds.metadata.label
    if label is not None:
        crc = zlib.crc32(np.ascontiguousarray(np.asarray(label)).tobytes(), crc)
    fp = f"{binned.shape[0]}x{binned.shape[1]}:{crc & 0xFFFFFFFF:08x}"
    binned_ds._ckpt_fingerprint = fp
    return fp


# ----------------------------------------------------------------------
# binary tree pack/unpack (bit-exact round trip)
# ----------------------------------------------------------------------
def pack_trees(models) -> Dict[str, np.ndarray]:
    """List[Tree] -> stacked ``(T, M)``/``(T, L)`` arrays + per-tree
    scalars, prefixed ``tree_``.  Only the live slices (``num_leaves``)
    are meaningful; padding is zero."""
    t = len(models)
    m = max(max((tr.num_leaves - 1 for tr in models), default=1), 1)
    li = max(max((tr.num_leaves for tr in models), default=2), 2)
    out: Dict[str, np.ndarray] = {
        "tree_num_leaves": np.asarray([tr.num_leaves for tr in models], np.int32),
        "tree_shrinkage": np.asarray(
            [tr.shrinkage_rate for tr in models], np.float64
        ),
    }
    for name, dtype, axis in _TREE_FIELDS:
        width = m if axis == "m" else li
        arr = np.zeros((t, width), dtype)
        for i, tr in enumerate(models):
            n = tr.num_leaves
            k = max(n - 1, 1) if axis == "m" else n
            src = getattr(tr, name)
            arr[i, : min(k, len(src))] = src[: min(k, len(src))]
        out["tree_" + name] = arr
    return out


def unpack_trees(arrays: Dict[str, np.ndarray]):
    """Inverse of :func:`pack_trees` — rebuilds host ``Tree`` objects
    field-for-field (no text round trip)."""
    num_leaves = np.asarray(arrays["tree_num_leaves"])
    shrinkage = np.asarray(arrays["tree_shrinkage"])
    models = []
    for i in range(len(num_leaves)):
        n = int(num_leaves[i])
        tree = Tree(max(n, 2))
        tree.num_leaves = n
        for name, dtype, axis in _TREE_FIELDS:
            k = max(n - 1, 1) if axis == "m" else n
            dst = getattr(tree, name)
            src = np.asarray(arrays["tree_" + name][i][:k], dtype)
            dst[: len(src)] = src
        tree.shrinkage_rate = float(shrinkage[i])
        tree.has_categorical = bool(np.any(tree.decision_type[: max(n - 1, 1)] == 1))
        models.append(tree)
    return models


# ----------------------------------------------------------------------
# TrainState
# ----------------------------------------------------------------------
class TrainState:
    """One host's complete training state at an iteration boundary."""

    def __init__(self, meta: Dict[str, Any], py: Dict[str, Any],
                 arrays: Dict[str, np.ndarray]):
        self.meta = dict(meta)
        self.py = dict(py)
        self.arrays = dict(arrays)

    @property
    def iteration(self) -> int:
        return int(self.meta["iteration"])

    # -- serialization -------------------------------------------------
    def to_bytes(self) -> bytes:
        payload = dict(self.arrays)
        header = {"meta": self.meta, "py": self.py}
        payload["__meta__"] = np.asarray(json.dumps(header, default=str))
        buf = io.BytesIO()
        np.savez(buf, **payload)
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, blob: bytes) -> "TrainState":
        with np.load(io.BytesIO(blob), allow_pickle=False) as z:
            if "__meta__" not in z:
                raise ValueError("not a TrainState blob (no __meta__)")
            header = json.loads(str(z["__meta__"]))
            arrays = {k: z[k] for k in z.files if k != "__meta__"}
        meta = header["meta"]
        if int(meta.get("format_version", -1)) != FORMAT_VERSION:
            raise ValueError(
                f"unsupported TrainState format_version "
                f"{meta.get('format_version')} (supported: {FORMAT_VERSION})"
            )
        return cls(meta, header["py"], arrays)


# ----------------------------------------------------------------------
# capture / restore
# ----------------------------------------------------------------------
def capture(booster, extra_py: Optional[Dict[str, Any]] = None) -> TrainState:
    """Snapshot a live ``Booster`` into a :class:`TrainState`.

    Pure reads — device arrays are pulled to host, nothing is mutated.
    ``extra_py`` lets the manager attach callback state (early stopping,
    eval history) captured at the same boundary."""
    from ..obs import tracer

    b = booster.boosting
    with tracer.span("ckpt.capture"):
        arrays, py = b.export_train_state()
        arrays.update(pack_trees(b.models))
        meta = {
            "format_version": FORMAT_VERSION,
            "iteration": int(b.iter),
            "boosting_type": type(b).__name__.lower(),
            "num_models": len(b.models),
            "num_tree_per_iteration": int(b.num_tree_per_iteration),
            "num_data": int(b.num_data),
            "config_fingerprint": config_fingerprint(b.config),
            "data_fingerprint": data_fingerprint(b.train_set),
            "num_valid": len(b.valid_scores),
            "best_iteration": int(getattr(booster, "best_iteration", -1)),
        }
        ooc = getattr(b, "ooc", None)
        if ooc is not None:
            # chunk-schedule identity: a resume streaming a different
            # grid would change float summation order
            meta["ooc_schedule"] = ooc.schedule_fingerprint()
        if extra_py:
            py.update(extra_py)
    return TrainState(meta, py, arrays)


def restore(booster, state: TrainState) -> TrainState:
    """Load a :class:`TrainState` into a freshly-constructed ``Booster``
    (same params, same dataset, valid sets already added).  Refuses on a
    config/dataset fingerprint mismatch."""
    from ..obs import tracer

    b = booster.boosting
    cfp, dfp = config_fingerprint(b.config), data_fingerprint(b.train_set)
    if state.meta["config_fingerprint"] != cfp:
        raise CheckpointMismatch(
            "checkpoint was written under a different training config "
            f"(checkpoint {state.meta['config_fingerprint']}, run {cfp}); "
            "refusing to resume — clear the checkpoint directory to start over"
        )
    if state.meta["data_fingerprint"] != dfp:
        raise CheckpointMismatch(
            "checkpoint was written against a different dataset "
            f"(checkpoint {state.meta['data_fingerprint']}, run {dfp}); "
            "refusing to resume"
        )
    want_bt = type(b).__name__.lower()
    if state.meta["boosting_type"] != want_bt:
        raise CheckpointMismatch(
            f"checkpoint boosting type {state.meta['boosting_type']} != {want_bt}"
        )
    if int(state.meta["num_valid"]) != len(b.valid_scores):
        raise CheckpointMismatch(
            f"checkpoint has {state.meta['num_valid']} valid sets, "
            f"run registered {len(b.valid_scores)}"
        )
    ooc = getattr(b, "ooc", None)
    want_sched = state.meta.get("ooc_schedule")
    have_sched = ooc.schedule_fingerprint() if ooc is not None else None
    if want_sched != have_sched:
        raise CheckpointMismatch(
            "checkpoint out-of-core chunk schedule "
            f"{want_sched!r} != this run's {have_sched!r}; resuming "
            "with a different streaming grid would change float "
            "summation order — rerun with the original "
            "out_of_core/ooc_chunk_rows settings"
        )
    with tracer.span("ckpt.restore", iter=state.iteration):
        b.models = unpack_trees(state.arrays)
        b.import_train_state(state.arrays, state.py)
        bi = int(state.meta.get("best_iteration", -1))
        if bi > 0:
            booster.best_iteration = bi
    tracer.event("ckpt.restored", iter=state.iteration,
                 num_models=len(b.models))
    Log.info("Resumed training state at iteration %d (%d trees)",
             state.iteration, len(b.models))
    return state
