"""Versioned training-state snapshots (``TrainState``).

A checkpoint must reproduce training *exactly*, so the state is the
closure of everything the boosting drivers read across an iteration
boundary:

  - the ensemble's trees in **binary** — stacked SoA arrays in the same
    spirit as the serving ``PredictorArtifact`` npz layout (one entry
    per ``Tree`` field, ``(T, M)``/``(T, L)`` padded), but *complete*:
    training needs bin-space thresholds, leaf counts/parents and
    per-tree shrinkage that the inference artifact drops, and a text
    round-trip through ``%g`` formatting would not be bit-faithful;
  - the device score caches (train + every valid set) in f32;
  - every RNG stream: the bagging ``RandomState``, the
    feature-fraction ``utils.random.Random``, DART's drop ``Random``,
    GOSS's chained ``PRNGKey`` (the fused partitioned trainers need no
    RNG state — they fold a static base key with the iteration number);
  - early-stopping bests / messages and the iteration counter;
  - the fused partitioned trainer's physical row permutation (histogram
    accumulation order follows the partition layout, so restarting from
    an identity layout would change float summation order);
  - config + dataset fingerprints: resume **refuses** to run on a
    mismatch instead of silently training a different problem.

Serialization is one ``.npz`` (uncompressed — checkpoint cadence beats
bytes) with a ``__meta__`` JSON entry, mirroring ``serve/artifact.py``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import zlib
from typing import Any, Dict, Optional

import numpy as np

from ..model.tree import Tree
from ..utils.log import Log

FORMAT_VERSION = 1

# Tree SoA fields: (name, dtype, padded-axis) where axis "m" arrays hold
# num_leaves-1 node records and "l" arrays hold num_leaves leaf records.
_TREE_FIELDS = (
    ("left_child", np.int32, "m"),
    ("right_child", np.int32, "m"),
    ("split_feature_inner", np.int32, "m"),
    ("split_feature", np.int32, "m"),
    ("threshold_in_bin", np.int32, "m"),
    ("threshold", np.float64, "m"),
    ("decision_type", np.int8, "m"),
    ("default_value", np.float64, "m"),
    ("zero_bin", np.int32, "m"),
    ("default_bin_for_zero", np.int32, "m"),
    ("split_gain", np.float64, "m"),
    ("internal_value", np.float64, "m"),
    ("internal_count", np.int64, "m"),
    ("leaf_parent", np.int32, "l"),
    ("leaf_value", np.float64, "l"),
    ("leaf_count", np.int64, "l"),
)

# Config fields that may legitimately differ between the original run
# and its resume (paths, task plumbing, run length, verbosity) — they
# never change the per-iteration math, so they stay out of the
# fingerprint.
_FP_VOLATILE = {
    "task", "config_file", "data", "valid_data", "input_model",
    "output_model", "output_result", "convert_model",
    "convert_model_language", "num_iterations", "num_iteration_predict",
    "snapshot_freq", "verbose", "num_threads", "is_save_binary_file",
    "is_predict_leaf_index", "is_predict_raw_score", "output_freq",
    "metric_freq", "machine_list_file", "local_listen_port", "time_out",
    "checkpoint_dir", "checkpoint_freq", "checkpoint_keep",
    "checkpoint_resume", "is_training_metric", "pred_early_stop",
    "pred_early_stop_freq", "pred_early_stop_margin",
    # prefetch depth only changes pipelining, never the math (the
    # math-relevant out_of_core/ooc_chunk_rows stay fingerprinted, and
    # the chunk grid itself is checked via meta["ooc_schedule"])
    "ooc_prefetch_depth",
    # topology-portable checkpoints: the world size is recorded in the
    # canonical container's metadata, not in the config fingerprint — a
    # world-4 checkpoint must resume at world 2/8 (docs/CHECKPOINT.md).
    # The rebalance policy knobs only steer WHEN shards move, never the
    # per-iteration math on a given shard layout.
    "num_machines", "rebalance", "rebalance_threshold",
    "rebalance_patience", "rebalance_max_move_frac",
    # live membership is a transport/topology property, not math: a
    # checkpoint written by an elastic fleet must resume on a static
    # one and vice versa (parallel/membership.py, docs/ROBUSTNESS.md)
    "elastic_membership",
}


class CheckpointMismatch(RuntimeError):
    """Resume refused: the checkpoint was written by a different
    config or against a different dataset."""


# ----------------------------------------------------------------------
# fingerprints
# ----------------------------------------------------------------------
def config_fingerprint(config) -> str:
    """Stable digest of the math-relevant configuration."""
    d = dataclasses.asdict(config)
    for key in _FP_VOLATILE:
        d.pop(key, None)
    blob = json.dumps(d, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def data_fingerprint(binned_ds) -> str:
    """Digest of the constructed dataset (binned matrix + label).  CRC32
    keeps this cheap even at large N; cached on the dataset object so
    periodic checkpoints don't rescan the matrix."""
    cached = getattr(binned_ds, "_ckpt_fingerprint", None)
    if cached is not None:
        return cached
    binned = np.asarray(binned_ds.binned)
    # block-wise CRC: chunked zlib.crc32 equals the whole-buffer value,
    # and never materializes a memmapped (out-of-core) matrix
    crc = 0
    step = 65536
    for s in range(0, binned.shape[0], step):
        crc = zlib.crc32(
            np.ascontiguousarray(binned[s: s + step]).tobytes(), crc)
    label = binned_ds.metadata.label
    if label is not None:
        crc = zlib.crc32(np.ascontiguousarray(np.asarray(label)).tobytes(), crc)
    fp = f"{binned.shape[0]}x{binned.shape[1]}:{crc & 0xFFFFFFFF:08x}"
    binned_ds._ckpt_fingerprint = fp
    return fp


# -- shard-composable fingerprints -------------------------------------
# Under the pre-partition contract the global dataset is the row-order
# concatenation of the rank shards, so the global data_fingerprint is
# derivable from per-shard CRC primitives via zlib's crc32_combine
# identity crc(A||B) = combine(crc(A), crc(B), len(B)) — no rank ever
# has to materialize (or even see) another rank's rows.

def _gf2_matrix_times(mat, vec: int) -> int:
    out = 0
    i = 0
    while vec:
        if vec & 1:
            out ^= mat[i]
        vec >>= 1
        i += 1
    return out


def _gf2_matrix_square(square, mat) -> None:
    for n in range(32):
        square[n] = _gf2_matrix_times(mat, mat[n])


def crc32_combine(crc1: int, crc2: int, len2: int) -> int:
    """zlib's crc32_combine: CRC of the concatenation A||B from
    ``crc32(A)``, ``crc32(B)`` and ``len(B)`` (GF(2) matrix powering of
    the CRC polynomial over len2 zero bytes)."""
    if len2 <= 0:
        return crc1 & 0xFFFFFFFF
    even = [0] * 32
    odd = [0] * 32
    odd[0] = 0xEDB88320  # CRC-32 polynomial, reflected
    row = 1
    for n in range(1, 32):
        odd[n] = row
        row <<= 1
    _gf2_matrix_square(even, odd)
    _gf2_matrix_square(odd, even)
    crc1 &= 0xFFFFFFFF
    while True:
        _gf2_matrix_square(even, odd)
        if len2 & 1:
            crc1 = _gf2_matrix_times(even, crc1)
        len2 >>= 1
        if len2 == 0:
            break
        _gf2_matrix_square(odd, even)
        if len2 & 1:
            crc1 = _gf2_matrix_times(odd, crc1)
        len2 >>= 1
        if len2 == 0:
            break
    return (crc1 ^ (crc2 & 0xFFFFFFFF)) & 0xFFFFFFFF


def data_fingerprint_parts(binned_ds) -> Dict[str, int]:
    """CRC primitives of one shard, composable across shards: separate
    binned-matrix and label CRCs plus their byte lengths and the row
    grid.  :func:`combine_fingerprint_parts` folds a rank-ordered list
    of these into the exact string :func:`data_fingerprint` would
    produce over the concatenated rows."""
    cached = getattr(binned_ds, "_ckpt_fp_parts", None)
    if cached is not None:
        return dict(cached)
    binned = np.asarray(binned_ds.binned)
    crc_b = 0
    step = 65536
    for s in range(0, binned.shape[0], step):
        crc_b = zlib.crc32(
            np.ascontiguousarray(binned[s: s + step]).tobytes(), crc_b)
    label = binned_ds.metadata.label
    crc_l, len_l = 0, 0
    if label is not None:
        lab = np.ascontiguousarray(np.asarray(label)).tobytes()
        crc_l, len_l = zlib.crc32(lab), len(lab)
    parts = {
        "rows": int(binned.shape[0]), "cols": int(binned.shape[1]),
        "crc_binned": crc_b & 0xFFFFFFFF, "len_binned": int(binned.nbytes),
        "crc_label": crc_l & 0xFFFFFFFF, "len_label": int(len_l),
    }
    binned_ds._ckpt_fp_parts = dict(parts)
    return parts


def combine_fingerprint_parts(parts) -> str:
    """Rank-ordered shard parts -> the global-dataset fingerprint (equal
    to :func:`data_fingerprint` over the row concatenation)."""
    parts = [dict(p) for p in parts]
    rows = sum(int(p["rows"]) for p in parts)
    cols = int(parts[0]["cols"]) if parts else 0
    crc_b = 0
    for p in parts:
        if int(p["cols"]) != cols:
            raise CheckpointMismatch(
                f"shard column counts disagree: {cols} vs {p['cols']}")
        crc_b = crc32_combine(crc_b, int(p["crc_binned"]),
                              int(p["len_binned"]))
    crc_l, len_l = 0, 0
    for p in parts:
        crc_l = crc32_combine(crc_l, int(p["crc_label"]),
                              int(p["len_label"]))
        len_l += int(p["len_label"])
    crc = crc32_combine(crc_b, crc_l, len_l)
    return f"{rows}x{cols}:{crc & 0xFFFFFFFF:08x}"


# ----------------------------------------------------------------------
# binary tree pack/unpack (bit-exact round trip)
# ----------------------------------------------------------------------
def pack_trees(models) -> Dict[str, np.ndarray]:
    """List[Tree] -> stacked ``(T, M)``/``(T, L)`` arrays + per-tree
    scalars, prefixed ``tree_``.  Only the live slices (``num_leaves``)
    are meaningful; padding is zero."""
    t = len(models)
    m = max(max((tr.num_leaves - 1 for tr in models), default=1), 1)
    li = max(max((tr.num_leaves for tr in models), default=2), 2)
    out: Dict[str, np.ndarray] = {
        "tree_num_leaves": np.asarray([tr.num_leaves for tr in models], np.int32),
        "tree_shrinkage": np.asarray(
            [tr.shrinkage_rate for tr in models], np.float64
        ),
    }
    for name, dtype, axis in _TREE_FIELDS:
        width = m if axis == "m" else li
        arr = np.zeros((t, width), dtype)
        for i, tr in enumerate(models):
            n = tr.num_leaves
            k = max(n - 1, 1) if axis == "m" else n
            src = getattr(tr, name)
            arr[i, : min(k, len(src))] = src[: min(k, len(src))]
        out["tree_" + name] = arr
    if any(getattr(tr, "is_linear", False) for tr in models):
        out.update(_pack_linear(models, t, li))
    return out


def _pack_linear(models, t: int, li: int) -> Dict[str, np.ndarray]:
    """Linear-leaf model planes (tree/linear.py plug-in) — emitted only
    when at least one tree carries them, so constant-tree checkpoints
    keep the exact pre-strategy key set (bit-identical containers)."""
    kmax = 1
    for tr in models:
        if getattr(tr, "is_linear", False):
            for fs in tr.leaf_features:
                kmax = max(kmax, len(fs))
    is_lin = np.zeros(t, np.int8)
    const = np.zeros((t, li), np.float64)
    leaf_lin = np.zeros((t, li), np.int8)
    cnt = np.zeros((t, li), np.int32)
    feat = np.zeros((t, li, kmax), np.int32)
    feat_inner = np.zeros((t, li, kmax), np.int32)
    coeff = np.zeros((t, li, kmax), np.float64)
    for i, tr in enumerate(models):
        if not getattr(tr, "is_linear", False):
            continue
        is_lin[i] = 1
        n = tr.num_leaves
        const[i, :n] = tr.leaf_const[:n]
        leaf_lin[i, :n] = tr.leaf_is_linear[:n]
        for lj in range(min(n, len(tr.leaf_features))):
            fs = tr.leaf_features[lj]
            cnt[i, lj] = len(fs)
            if fs:
                feat[i, lj, : len(fs)] = fs
                feat_inner[i, lj, : len(fs)] = tr.leaf_features_inner[lj]
                coeff[i, lj, : len(fs)] = tr.leaf_coeff[lj]
    return {
        "tree_is_linear": is_lin,
        "tree_leaf_const": const,
        "tree_leaf_is_linear": leaf_lin,
        "tree_leaf_feat_cnt": cnt,
        "tree_leaf_feat": feat,
        "tree_leaf_feat_inner": feat_inner,
        "tree_leaf_coeff": coeff,
    }


def unpack_trees(arrays: Dict[str, np.ndarray]):
    """Inverse of :func:`pack_trees` — rebuilds host ``Tree`` objects
    field-for-field (no text round trip)."""
    num_leaves = np.asarray(arrays["tree_num_leaves"])
    shrinkage = np.asarray(arrays["tree_shrinkage"])
    models = []
    for i in range(len(num_leaves)):
        n = int(num_leaves[i])
        tree = Tree(max(n, 2))
        tree.num_leaves = n
        for name, dtype, axis in _TREE_FIELDS:
            k = max(n - 1, 1) if axis == "m" else n
            dst = getattr(tree, name)
            src = np.asarray(arrays["tree_" + name][i][:k], dtype)
            dst[: len(src)] = src
        tree.shrinkage_rate = float(shrinkage[i])
        tree.has_categorical = bool(np.any(tree.decision_type[: max(n - 1, 1)] == 1))
        if "tree_is_linear" in arrays and int(arrays["tree_is_linear"][i]):
            tree.is_linear = True
            tree.leaf_const[:n] = np.asarray(
                arrays["tree_leaf_const"][i][:n], np.float64)
            tree.leaf_is_linear[:n] = (
                np.asarray(arrays["tree_leaf_is_linear"][i][:n]) != 0)
            cnt = np.asarray(arrays["tree_leaf_feat_cnt"][i], np.int64)
            tree.leaf_features = []
            tree.leaf_features_inner = []
            tree.leaf_coeff = []
            for lj in range(n):
                c = int(cnt[lj])
                tree.leaf_features.append(
                    tuple(int(v) for v in arrays["tree_leaf_feat"][i][lj][:c]))
                tree.leaf_features_inner.append(
                    tuple(int(v)
                          for v in arrays["tree_leaf_feat_inner"][i][lj][:c]))
                tree.leaf_coeff.append(
                    tuple(np.asarray(arrays["tree_leaf_coeff"][i][lj][:c],
                                     np.float64)))
        models.append(tree)
    return models


# ----------------------------------------------------------------------
# TrainState
# ----------------------------------------------------------------------
class TrainState:
    """One host's complete training state at an iteration boundary."""

    def __init__(self, meta: Dict[str, Any], py: Dict[str, Any],
                 arrays: Dict[str, np.ndarray]):
        self.meta = dict(meta)
        self.py = dict(py)
        self.arrays = dict(arrays)

    @property
    def iteration(self) -> int:
        return int(self.meta["iteration"])

    # -- serialization -------------------------------------------------
    def to_bytes(self) -> bytes:
        payload = dict(self.arrays)
        header = {"meta": self.meta, "py": self.py}
        payload["__meta__"] = np.asarray(json.dumps(header, default=str))
        buf = io.BytesIO()
        np.savez(buf, **payload)
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, blob: bytes) -> "TrainState":
        with np.load(io.BytesIO(blob), allow_pickle=False) as z:
            if "__meta__" not in z:
                raise ValueError("not a TrainState blob (no __meta__)")
            header = json.loads(str(z["__meta__"]))
            arrays = {k: z[k] for k in z.files if k != "__meta__"}
        meta = header["meta"]
        if int(meta.get("format_version", -1)) != FORMAT_VERSION:
            raise ValueError(
                f"unsupported TrainState format_version "
                f"{meta.get('format_version')} (supported: {FORMAT_VERSION})"
            )
        return cls(meta, header["py"], arrays)


# ----------------------------------------------------------------------
# capture / restore
# ----------------------------------------------------------------------
def capture(booster, extra_py: Optional[Dict[str, Any]] = None) -> TrainState:
    """Snapshot a live ``Booster`` into a :class:`TrainState`.

    Pure reads — device arrays are pulled to host, nothing is mutated.
    ``extra_py`` lets the manager attach callback state (early stopping,
    eval history) captured at the same boundary."""
    from ..obs import tracer

    b = booster.boosting
    with tracer.span("ckpt.capture"):
        arrays, py = b.export_train_state()
        arrays.update(pack_trees(b.models))
        meta = {
            "format_version": FORMAT_VERSION,
            "iteration": int(b.iter),
            "boosting_type": type(b).__name__.lower(),
            "num_models": len(b.models),
            "num_tree_per_iteration": int(b.num_tree_per_iteration),
            "num_data": int(b.num_data),
            "config_fingerprint": config_fingerprint(b.config),
            "data_fingerprint": data_fingerprint(b.train_set),
            # shard-composable CRC primitives: lets host 0 derive the
            # GLOBAL dataset fingerprint for the canonical multi-host
            # container without seeing any other rank's rows
            "data_fingerprint_parts": data_fingerprint_parts(b.train_set),
            "num_valid": len(b.valid_scores),
            "best_iteration": int(getattr(booster, "best_iteration", -1)),
        }
        ooc = getattr(b, "ooc", None)
        if ooc is not None:
            # chunk-schedule identity: a resume streaming a different
            # grid would change float summation order
            meta["ooc_schedule"] = ooc.schedule_fingerprint()
        if extra_py:
            py.update(extra_py)
    return TrainState(meta, py, arrays)


def restore(booster, state: TrainState) -> TrainState:
    """Load a :class:`TrainState` into a freshly-constructed ``Booster``
    (same params, same dataset, valid sets already added).  Refuses on a
    config/dataset fingerprint mismatch."""
    from ..obs import tracer

    b = booster.boosting
    cfp, dfp = config_fingerprint(b.config), data_fingerprint(b.train_set)
    if state.meta["config_fingerprint"] != cfp:
        raise CheckpointMismatch(
            "checkpoint was written under a different training config "
            f"(checkpoint {state.meta['config_fingerprint']}, run {cfp}); "
            "refusing to resume — clear the checkpoint directory to start over"
        )
    if state.meta["data_fingerprint"] != dfp:
        raise CheckpointMismatch(
            "checkpoint was written against a different dataset "
            f"(checkpoint {state.meta['data_fingerprint']}, run {dfp}); "
            "refusing to resume"
        )
    want_bt = type(b).__name__.lower()
    if state.meta["boosting_type"] != want_bt:
        raise CheckpointMismatch(
            f"checkpoint boosting type {state.meta['boosting_type']} != {want_bt}"
        )
    if int(state.meta["num_valid"]) != len(b.valid_scores):
        raise CheckpointMismatch(
            f"checkpoint has {state.meta['num_valid']} valid sets, "
            f"run registered {len(b.valid_scores)}"
        )
    ooc = getattr(b, "ooc", None)
    want_sched = state.meta.get("ooc_schedule")
    have_sched = ooc.schedule_fingerprint() if ooc is not None else None
    if (isinstance(want_sched, str) and isinstance(have_sched, str)
            and want_sched.startswith("dist/")
            and have_sched.startswith("dist/")):
        # rank-sharded streaming (boosting/oocdist.py): the schedule is
        # per-RANK, so an elastic resume at a different world size
        # legitimately streams a different local grid.  That is sound —
        # quantized integer folds are associative and f32 folds stay
        # ROW_BLOCK-aligned within each rank — and the GLOBAL dataset
        # fingerprint above still gates the resume.
        pass
    elif want_sched != have_sched:
        raise CheckpointMismatch(
            "checkpoint out-of-core chunk schedule "
            f"{want_sched!r} != this run's {have_sched!r}; resuming "
            "with a different streaming grid would change float "
            "summation order — rerun with the original "
            "out_of_core/ooc_chunk_rows settings"
        )
    with tracer.span("ckpt.restore", iter=state.iteration):
        b.models = unpack_trees(state.arrays)
        b.import_train_state(state.arrays, state.py)
        bi = int(state.meta.get("best_iteration", -1))
        if bi > 0:
            booster.best_iteration = bi
    tracer.event("ckpt.restored", iter=state.iteration,
                 num_models=len(b.models))
    Log.info("Resumed training state at iteration %d (%d trees)",
             state.iteration, len(b.models))
    return state


# ----------------------------------------------------------------------
# topology-portable canonical layout (multi-host save / elastic resume)
# ----------------------------------------------------------------------
# Under the pre-partition contract the global row order is the rank-order
# concatenation of the shards, so one canonical global-row-order
# TrainState represents the fleet regardless of world size: save gathers
# every rank's local state and merges row arrays by concatenation;
# restore slices the SAME container to whatever partition the current
# topology uses.  Shard rebalancing reuses this pair as "checkpoint
# reshape in RAM" (parallel/shardplan.py) — one mechanism, tested two
# ways.

def merge_to_canonical(states) -> TrainState:
    """Per-rank ``TrainState``s (rank order) -> one canonical global
    TrainState.  Row arrays are concatenated in rank order; replicated
    state (trees, feature RNG, GOSS key) comes from rank 0; genuinely
    per-rank state (bagging RNG stream, early-stopping bests, callback
    closures) is kept per rank so a same-partition resume stays
    byte-identical."""
    if not states:
        raise ValueError("merge_to_canonical needs at least one state")
    base = states[0]
    iters = {int(s.meta["iteration"]) for s in states}
    if len(iters) != 1:
        raise CheckpointMismatch(
            f"cannot merge rank states from divergent iterations: {sorted(iters)}")
    nv = int(base.meta["num_valid"])
    shard_rows = [int(s.meta["num_data"]) for s in states]
    parts = []
    for r, s in enumerate(states):
        p = s.meta.get("data_fingerprint_parts")
        if not p:
            raise ValueError(
                f"rank {r} state lacks data_fingerprint_parts; cannot "
                "derive the global dataset fingerprint")
        parts.append(p)
    valid_shard = [
        [int(np.asarray(s.arrays[f"valid_scores_{i}"]).shape[1])
         for s in states]
        for i in range(nv)
    ]
    arrays = dict(base.arrays)
    arrays["scores"] = np.concatenate(
        [np.asarray(s.arrays["scores"]) for s in states], axis=1)
    arrays["select"] = np.concatenate(
        [np.asarray(s.arrays["select"]) for s in states], axis=0)
    for i in range(nv):
        arrays[f"valid_scores_{i}"] = np.concatenate(
            [np.asarray(s.arrays[f"valid_scores_{i}"]) for s in states],
            axis=1)
    arrays.pop("bag_rng_keys", None)
    for r, s in enumerate(states):
        arrays[f"bag_rng_keys_r{r}"] = np.asarray(
            s.arrays["bag_rng_keys"], np.uint32)
    py = dict(base.py)
    py["per_rank"] = {
        str(r): {
            "py": {k: v for k, v in s.py.items() if k != "per_rank"},
            "best_iteration": int(s.meta.get("best_iteration", -1)),
        }
        for r, s in enumerate(states)
    }
    meta = dict(base.meta)
    meta.pop("data_fingerprint_parts", None)
    meta["world_size"] = len(states)
    meta["shard_rows"] = shard_rows
    meta["valid_shard_rows"] = valid_shard
    meta["num_data"] = int(sum(shard_rows))
    meta["data_fingerprint"] = combine_fingerprint_parts(parts)
    return TrainState(meta, py, arrays)


def reshard_to_local(state: TrainState, rank: int, shard_rows,
                     valid_shard_rows, local_fp: str,
                     bag_seed: int = 0) -> TrainState:
    """Slice a canonical global TrainState down to one rank of the
    CURRENT topology (``shard_rows``/``valid_shard_rows`` describe the
    current contiguous partition, in rank order; the caller has already
    verified the global fingerprint and row totals).

    When the current partition equals the saved one, the rank's own
    bagging stream / bests / callback state are restored exactly —
    same-world resume stays byte-identical.  Otherwise the row arrays
    are resliced (a valid continuation: score caches and the bagging
    mask travel with their rows) and the bagging RNG is reseeded
    deterministically from ``(bag_seed, iteration, rank)`` — replaying
    a sibling rank's stream on a different row count would be
    meaningless anyway."""
    from ..obs import tracer

    meta = dict(state.meta)
    saved_rows = [int(x) for x in meta.get("shard_rows", [])]
    saved_valid = [[int(x) for x in v]
                   for v in meta.get("valid_shard_rows", [])]
    shard_rows = [int(x) for x in shard_rows]
    valid_shard_rows = [[int(x) for x in v] for v in valid_shard_rows]
    total = sum(shard_rows)
    if total != int(meta["num_data"]):
        raise CheckpointMismatch(
            f"checkpoint holds {meta['num_data']} global rows but the "
            f"current topology partitions {total}")
    for i, v in enumerate(valid_shard_rows):
        if i < len(saved_valid) and sum(v) != sum(saved_valid[i]):
            raise CheckpointMismatch(
                f"valid set {i} holds {sum(saved_valid[i])} global rows "
                f"but the current topology partitions {sum(v)}")
    same_partition = (saved_rows == shard_rows
                      and saved_valid == valid_shard_rows)
    start = sum(shard_rows[:rank])
    stop = start + shard_rows[rank]
    with tracer.span("ckpt.reshard", rank=rank,
                     saved_world=int(meta.get("world_size", 1)),
                     world=len(shard_rows),
                     same_partition=same_partition):
        arrays: Dict[str, np.ndarray] = {}
        for key, val in state.arrays.items():
            if key == "scores":
                arrays[key] = np.asarray(val)[:, start:stop]
            elif key == "select":
                arrays[key] = np.asarray(val)[start:stop]
            elif key.startswith("valid_scores_"):
                i = int(key[len("valid_scores_"):])
                vs = sum(valid_shard_rows[i][:rank])
                ve = vs + valid_shard_rows[i][rank]
                arrays[key] = np.asarray(val)[:, vs:ve]
            elif key.startswith("bag_rng_keys_r"):
                continue  # per-rank streams, resolved below
            else:
                arrays[key] = val
        py = {k: v for k, v in state.py.items() if k != "per_rank"}
        if same_partition:
            pr = (state.py.get("per_rank") or {}).get(str(rank))
            if pr is not None:
                py = dict(pr["py"])
                meta["best_iteration"] = int(pr.get("best_iteration", -1))
            arrays["bag_rng_keys"] = np.asarray(
                state.arrays[f"bag_rng_keys_r{rank}"], np.uint32)
        else:
            rs = np.random.RandomState([
                int(bag_seed) & 0xFFFFFFFF,
                int(meta["iteration"]) & 0xFFFFFFFF,
                int(rank),
            ])
            st = rs.get_state()
            arrays["bag_rng_keys"] = np.asarray(st[1], np.uint32)
            py["bag_rng"] = [str(st[0]), int(st[2]), int(st[3]),
                             float(st[4])]
            py["need_re_bagging"] = True
        meta["num_data"] = shard_rows[rank]
        meta["data_fingerprint"] = local_fp
        for key in ("world_size", "shard_rows", "valid_shard_rows"):
            meta.pop(key, None)
    return TrainState(meta, py, arrays)
