"""Fault-tolerant training: checkpoint/resume subsystem.

The reference's only fault-tolerance story is ``snapshot_freq`` — a
periodic synchronous model-text dump (gbdt.cpp Application::Train) that
cannot restore TRAINING state: DART's drop RNG, GOSS's PRNG key, the
bagging/feature-fraction RNG streams, score caches and early-stopping
bests all restart from scratch, so a resumed run silently diverges.

Here a checkpoint is the complete training state, and resume is
**bit-identical** to never having died:

  ``state.py``    versioned ``TrainState`` — ensemble trees in binary
                  (stacked SoA arrays, no model-text reparse), train and
                  valid score caches, every RNG stream, early-stopping
                  bests, plus config/dataset fingerprints that refuse
                  resume on mismatch.
  ``store.py``    atomic tmp+fsync+rename writes with a CRC manifest,
                  rolling retention, and latest-valid discovery that
                  skips a corrupt tail checkpoint.
  ``manager.py``  ``CheckpointManager`` — a training callback with
                  off-thread background writes, SIGTERM/preemption
                  flush-and-exit, and multihost coordination (all hosts
                  barrier on the checkpointed iteration; host 0 writes).

See docs/CHECKPOINT.md for the state layout, atomicity guarantees,
multihost protocol and the preemption flow.
"""

from .manager import CheckpointManager, PreemptionExit  # noqa: F401
from .state import CheckpointMismatch, TrainState, capture, restore  # noqa: F401
from .store import CheckpointStore  # noqa: F401

__all__ = [
    "CheckpointManager",
    "CheckpointMismatch",
    "CheckpointStore",
    "PreemptionExit",
    "TrainState",
    "capture",
    "restore",
]
