"""Training callbacks — counterpart of python-package/lightgbm/callback.py
(print_evaluation:35, record_evaluation:73, reset_parameter:106,
early_stopping:141).
"""

from __future__ import annotations

import collections
from typing import Callable, List

from .utils.log import Log


class EarlyStopException(Exception):
    """Raised by early_stopping to halt train() (callback.py:11-19)."""

    def __init__(self, best_iteration: int, best_score=None):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"],
)


def _format_eval_result(value, show_stdv: bool = True) -> str:
    if len(value) == 4:
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    if len(value) == 5:
        if show_stdv:
            return f"{value[0]}'s {value[1]}: {value[2]:g} + {value[4]:g}"
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    raise ValueError("Wrong metric value")


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    """Log evaluation results every ``period`` iterations
    (callback.py:35-70)."""

    def callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list and (env.iteration + 1) % period == 0:
            result = "\t".join(
                _format_eval_result(x, show_stdv) for x in env.evaluation_result_list
            )
            Log.info("[%d]\t%s", env.iteration + 1, result)

    callback.order = 10
    return callback


log_evaluation = print_evaluation  # modern alias


def record_evaluation(eval_result: dict) -> Callable:
    """Record eval history into ``eval_result`` (callback.py:73-103)."""
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")
    eval_result.clear()

    def init(env: CallbackEnv) -> None:
        for item in env.evaluation_result_list:
            data_name, eval_name = item[0], item[1]
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])

    def callback(env: CallbackEnv) -> None:
        if not eval_result:
            init(env)
        for item in env.evaluation_result_list:
            data_name, eval_name, result = item[0], item[1], item[2]
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])
            eval_result[data_name][eval_name].append(result)

    # checkpoint hooks (ckpt/manager.py): the recorded history lives in
    # the caller's dict and must survive a kill/resume
    def ckpt_state():
        return {d: {m: list(v) for m, v in dd.items()}
                for d, dd in eval_result.items()}

    def ckpt_restore(state):
        eval_result.clear()
        for d, dd in state.items():
            eval_result[d] = collections.OrderedDict(
                (m, [float(x) for x in v]) for m, v in dd.items()
            )

    callback.order = 20
    callback.ckpt_name = "record_evaluation"
    callback.ckpt_state = ckpt_state
    callback.ckpt_restore = ckpt_restore
    return callback


def reset_parameter(**kwargs) -> Callable:
    """Reset parameters (e.g. learning_rate) per iteration from a list or
    a function of the iteration index (callback.py:106-138)."""

    def callback(env: CallbackEnv) -> None:
        new_parameters = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        f"Length of list {key!r} has to equal to 'num_boost_round'."
                    )
                new_param = value[env.iteration - env.begin_iteration]
            else:
                new_param = value(env.iteration - env.begin_iteration)
            new_parameters[key] = new_param
        if new_parameters:
            # push into the live config and re-derive dependent state
            # (the reference resets the model config via ResetConfig)
            env.model.boosting.config.update(new_parameters)
            env.model.boosting.refresh_config()
            env.params.update(new_parameters)

    callback.before_iteration = True
    callback.order = 10
    return callback


def early_stopping(stopping_rounds: int, verbose: bool = True) -> Callable:
    """Stop when no validation metric improves in ``stopping_rounds``
    rounds (callback.py:141-187)."""
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List[list] = []
    cmp_op: List[Callable] = []

    def init(env: CallbackEnv) -> None:
        if not env.evaluation_result_list:
            raise ValueError(
                "For early stopping, at least one dataset and eval metric is "
                "required for evaluation"
            )
        if verbose:
            Log.info(
                "Training until validation scores don't improve for %d rounds.",
                stopping_rounds,
            )
        for eval_ret in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            if eval_ret[3]:  # bigger is better
                best_score.append(float("-inf"))
                cmp_op.append(lambda x, y: x > y)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda x, y: x < y)

    def callback(env: CallbackEnv) -> None:
        if not cmp_op:
            init(env)
        for i, eval_ret in enumerate(env.evaluation_result_list):
            score = eval_ret[2]
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            elif env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    Log.info(
                        "Early stopping, best iteration is:\n[%d]\t%s",
                        best_iter[i] + 1,
                        "\t".join(_format_eval_result(x) for x in best_score_list[i]),
                    )
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if env.iteration == env.end_iteration - 1:
                if verbose:
                    Log.info(
                        "Did not meet early stopping. Best iteration is:\n[%d]\t%s",
                        best_iter[i] + 1,
                        "\t".join(_format_eval_result(x) for x in best_score_list[i]),
                    )
                raise EarlyStopException(best_iter[i], best_score_list[i])

    # checkpoint hooks (ckpt/manager.py): the closure's bests/counters
    # are the patience state — without them a resumed run would restart
    # the stopping_rounds window and stop late
    def ckpt_state():
        return {
            "best_score": list(best_score),
            "best_iter": list(best_iter),
            "best_score_list": [
                None if b is None else [list(x) for x in b]
                for b in best_score_list
            ],
            "bigger": [bool(op(1.0, 0.0)) for op in cmp_op],
        }

    def ckpt_restore(state):
        best_score[:] = [float(x) for x in state["best_score"]]
        best_iter[:] = [int(x) for x in state["best_iter"]]
        best_score_list[:] = [
            None if b is None else [tuple(x) for x in b]
            for b in state["best_score_list"]
        ]
        cmp_op[:] = [
            (lambda x, y: x > y) if big else (lambda x, y: x < y)
            for big in state["bigger"]
        ]

    callback.order = 30
    callback.ckpt_name = "early_stopping"
    callback.ckpt_state = ckpt_state
    callback.ckpt_restore = ckpt_restore
    return callback
