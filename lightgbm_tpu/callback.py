"""Placeholder — implemented in a later milestone."""
def early_stopping(*a, **k):
    raise NotImplementedError


def log_evaluation(*a, **k):
    raise NotImplementedError


def record_evaluation(*a, **k):
    raise NotImplementedError


def reset_parameter(*a, **k):
    raise NotImplementedError
