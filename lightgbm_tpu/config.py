"""Parameter handling — the counterpart of the reference's config layer
(include/LightGBM/config.h, src/io/config.cpp).

The reference splits parameters into nested sub-config structs
(IOConfig/TreeConfig/BoostingConfig/ObjectiveConfig/MetricConfig/
NetworkConfig wired into OverallConfig).  Here a single flat dataclass holds
every parameter under its canonical name — the layering in the reference is
an artifact of C++ struct ownership, not semantics — while the alias table
(config.h:359–487) and the unknown-parameter rejection are reproduced
exactly so that `lgb.train(params=...)` dicts written for the reference work
unchanged.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .utils.log import Log

# Alias -> canonical name. Parity with config.h:361-443.
PARAM_ALIASES: Dict[str, str] = {
    "config": "config_file",
    "nthread": "num_threads",
    "random_seed": "seed",
    "num_thread": "num_threads",
    "boosting": "boosting_type",
    "boost": "boosting_type",
    "application": "objective",
    "app": "objective",
    "train_data": "data",
    "train": "data",
    "model_output": "output_model",
    "model_out": "output_model",
    "model_input": "input_model",
    "model_in": "input_model",
    "predict_result": "output_result",
    "prediction_result": "output_result",
    "valid": "valid_data",
    "test_data": "valid_data",
    "test": "valid_data",
    "is_sparse": "is_enable_sparse",
    "enable_sparse": "is_enable_sparse",
    "pre_partition": "is_pre_partition",
    "tranining_metric": "is_training_metric",
    "train_metric": "is_training_metric",
    "ndcg_at": "ndcg_eval_at",
    "eval_at": "ndcg_eval_at",
    "min_data_per_leaf": "min_data_in_leaf",
    "min_data": "min_data_in_leaf",
    "min_child_samples": "min_data_in_leaf",
    "min_sum_hessian_per_leaf": "min_sum_hessian_in_leaf",
    "min_sum_hessian": "min_sum_hessian_in_leaf",
    "min_hessian": "min_sum_hessian_in_leaf",
    "min_child_weight": "min_sum_hessian_in_leaf",
    "num_leaf": "num_leaves",
    "sub_feature": "feature_fraction",
    "colsample_bytree": "feature_fraction",
    "num_iteration": "num_iterations",
    "num_tree": "num_iterations",
    "num_round": "num_iterations",
    "num_trees": "num_iterations",
    "num_rounds": "num_iterations",
    "num_boost_round": "num_iterations",
    "sub_row": "bagging_fraction",
    "subsample": "bagging_fraction",
    "subsample_freq": "bagging_freq",
    "shrinkage_rate": "learning_rate",
    "tree": "tree_learner",
    "tree_learner_type": "tree_learner",
    "tree_type": "tree_learner",
    "num_machine": "num_machines",
    "local_port": "local_listen_port",
    "two_round_loading": "use_two_round_loading",
    "two_round": "use_two_round_loading",
    "mlist": "machine_list_file",
    "is_save_binary": "is_save_binary_file",
    "save_binary": "is_save_binary_file",
    "early_stopping_rounds": "early_stopping_round",
    "early_stopping": "early_stopping_round",
    "verbosity": "verbose",
    "header": "has_header",
    "label": "label_column",
    "weight": "weight_column",
    "group": "group_column",
    "query": "group_column",
    "query_column": "group_column",
    "ignore_feature": "ignore_column",
    "blacklist": "ignore_column",
    "categorical_feature": "categorical_column",
    "cat_column": "categorical_column",
    "cat_feature": "categorical_column",
    "predict_raw_score": "is_predict_raw_score",
    "predict_leaf_index": "is_predict_leaf_index",
    "raw_score": "is_predict_raw_score",
    "leaf_index": "is_predict_leaf_index",
    "min_split_gain": "min_gain_to_split",
    "topk": "top_k",
    "reg_alpha": "lambda_l1",
    "reg_lambda": "lambda_l2",
    "num_classes": "num_class",
    "unbalanced_sets": "is_unbalance",
    "bagging_fraction_seed": "bagging_seed",
    "use_quantized_grad": "quantized_training",
    "linear_trees": "linear_tree",
    "monotone_constraint": "monotone_constraints",
    "mc": "monotone_constraints",
}


@dataclass
class Config:
    """All canonical parameters with reference defaults (config.h:85–290)."""

    # --- task / global (OverallConfig)
    task: str = "train"
    seed: int = 0
    num_threads: int = 0
    boosting_type: str = "gbdt"
    objective: str = "regression"
    metric: List[str] = field(default_factory=list)
    tree_learner: str = "serial"
    device: str = "tpu"  # reference default "cpu"; here TPU is the device story
    config_file: str = ""
    convert_model_language: str = ""

    # --- IO (IOConfig, config.h:87–148)
    max_bin: int = 255
    num_class: int = 1
    data_random_seed: int = 1
    data: str = ""
    valid_data: List[str] = field(default_factory=list)
    snapshot_freq: int = 100
    output_model: str = "LightGBM_model.txt"
    output_result: str = "LightGBM_predict_result.txt"
    convert_model: str = "gbdt_prediction.cpp"
    input_model: str = ""
    verbose: int = 1
    num_iteration_predict: int = -1
    is_pre_partition: bool = False
    is_enable_sparse: bool = True
    sparse_threshold: float = 0.8
    use_two_round_loading: bool = False
    is_save_binary_file: bool = False
    enable_load_from_binary_file: bool = True
    bin_construct_sample_cnt: int = 200000
    is_predict_leaf_index: bool = False
    is_predict_raw_score: bool = False
    min_data_in_bin: int = 5
    max_conflict_rate: float = 0.0
    enable_bundle: bool = True
    has_header: bool = False
    label_column: str = ""
    weight_column: str = ""
    group_column: str = ""
    ignore_column: str = ""
    categorical_column: str = ""
    pred_early_stop: bool = False
    pred_early_stop_freq: int = 10
    pred_early_stop_margin: float = 10.0
    # --- fault tolerance (ckpt/; TPU-specific extension).  The CLI
    # writes full training-state checkpoints at snapshot_freq (real
    # resume, not just a model dump); checkpoint_freq overrides the
    # cadence, checkpoint_dir the location (default: output_model's
    # directory), checkpoint_keep the rolling retention, and
    # checkpoint_resume is auto/true/false (auto resumes only an
    # interrupted run; see docs/CHECKPOINT.md).
    checkpoint_dir: str = ""
    checkpoint_freq: int = 0
    checkpoint_keep: int = 3
    checkpoint_resume: str = "auto"

    # --- malformed-input policy (data/reader.py; TPU-specific
    # extension).  'error' (default) fails loudly naming the file and
    # data-row number; 'skip' drops malformed/ragged rows, counts them
    # on the `data.bad_rows` obs counter, and stays bit-identical to
    # 'error' whenever no rows are bad.
    bad_row_policy: str = "error"

    # --- streaming ingest (data/ingest.py; TPU-specific extension).
    # stream_ingest: 'auto' streams text loads above the size threshold
    # (or always under use_two_round_loading), 'true'/'false' force;
    # the LIGHTGBM_TPU_STREAM_INGEST env knob overrides this param.
    stream_ingest: str = "auto"
    stream_chunk_rows: int = 0  # 0 = auto-size chunks (~32 MiB raw)

    # --- out-of-core training (boosting/ooc.py; TPU-specific
    # extension).  out_of_core: 'auto' streams the bin matrix from host
    # when its packed size exceeds the device budget
    # (LIGHTGBM_TPU_DEVICE_BUDGET or the backend's reported limit),
    # 'true'/'false' force; the LIGHTGBM_TPU_OOC env knob overrides.
    # ooc_chunk_rows: rows per streamed chunk (0 = auto ~64 MiB packed;
    # always rounded up to the histogram ROW_BLOCK for bit-identity).
    # ooc_prefetch_depth: in-flight host->device chunk buffers (2 =
    # double buffering) — this bounds peak device residency.
    out_of_core: str = "auto"
    ooc_chunk_rows: int = 0
    ooc_prefetch_depth: int = 2

    # --- quantized training (ops/qhist.py; TPU-specific extension
    # mirroring the reference's use_quantized_grad).  Off by default —
    # and OFF is bit-identical to builds without the feature.  On:
    # per-row grad/hess quantize to int16 levels under a per-iteration
    # global scale with stochastic rounding, histograms accumulate in
    # exact int32 (deterministic across row orders, chunkings and rank
    # counts), distributed histogram exchanges ship the 3x-smaller
    # int16 hist_q wire, and dequantization happens at split-scan time.
    # quantized_grad_bits: signed level width (2..15; 5 = QMAX 15).
    quantized_training: bool = False
    quantized_grad_bits: int = 5

    # --- leaf-model / split-constraint plug-ins (tree/strategy.py;
    # docs/TREES.md).  linear_tree fits per-leaf ridge least-squares
    # models over each leaf's path features (tree/linear.py) with
    # linear_lambda the ridge strength on the slope terms.
    # monotone_constraints is a per-feature +1/0/-1 direction surface:
    # a comma list ("+1,0,-1", one entry per raw feature) or a
    # {feature index or name: direction} dict.  Supported matrix:
    # linear_tree -> gbdt/goss boosting, f32 histograms,
    # tree_learner=serial or data on ONE process (in-memory or
    # out-of-core); monotone_constraints -> every learner except the
    # fused ptrainer (which declines and falls back, like quantized).
    linear_tree: bool = False
    linear_lambda: float = 0.0
    monotone_constraints: Any = ""

    # --- tree (TreeConfig, config.h:189–234)
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    num_leaves: int = 31
    feature_fraction_seed: int = 2
    feature_fraction: float = 1.0
    histogram_pool_size: float = -1.0
    max_depth: int = -1
    top_k: int = 20
    gpu_platform_id: int = -1
    gpu_device_id: int = -1
    gpu_use_dp: bool = False
    use_missing: bool = True

    # --- boosting (BoostingConfig, config.h:236–266)
    output_freq: int = 1
    is_training_metric: bool = False
    num_iterations: int = 100
    learning_rate: float = 0.1
    bagging_fraction: float = 1.0
    bagging_seed: int = 3
    bagging_freq: int = 0
    early_stopping_round: int = 0
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    xgboost_dart_mode: bool = False
    uniform_drop: bool = False
    drop_seed: int = 4
    top_rate: float = 0.2
    other_rate: float = 0.1
    boost_from_average: bool = True

    # --- objective (ObjectiveConfig, config.h:153–172)
    sigmoid: float = 1.0
    huber_delta: float = 1.0
    fair_c: float = 1.0
    gaussian_eta: float = 1.0
    poisson_max_delta_step: float = 0.7
    label_gain: List[float] = field(default_factory=list)
    max_position: int = 20
    is_unbalance: bool = False
    scale_pos_weight: float = 1.0

    # --- metric (MetricConfig, config.h:176–186)
    ndcg_eval_at: List[int] = field(default_factory=lambda: [1, 2, 3, 4, 5])
    metric_freq: int = 1

    # --- network (NetworkConfig, config.h:261–268)
    num_machines: int = 1
    local_listen_port: int = 12400
    time_out: int = 120
    machine_list_file: str = ""
    # --- hardened transport (parallel/net.py; TPU-specific extension,
    # docs/ROBUSTNESS.md).  network_timeout is the per-collective wait
    # window in SECONDS (the TPU-era replacement of the reference's
    # socket time_out, which is in minutes); a dead peer surfaces within
    # ~2x this bound.  network_retries bounds transient-error retries on
    # an exponential backoff; network_heartbeat_interval=0 auto-derives
    # (timeout/4, capped at 5 s).  Env vars LIGHTGBM_TPU_NET_TIMEOUT /
    # _NET_RETRIES / _NET_HEARTBEAT override these params.
    network_timeout: float = 120.0
    network_retries: int = 3
    network_heartbeat_interval: float = 0.0
    # --- straggler-aware shard rebalancing (parallel/shardplan.py;
    # docs/ROBUSTNESS.md).  Off by default: rebalance=False keeps the
    # exact static-shard behavior (zero extra collectives).  When on, a
    # rank whose EWMA compute time stays above rebalance_threshold x the
    # fleet median for rebalance_patience consecutive iterations
    # triggers a shard-boundary move at the next iteration boundary; at
    # most rebalance_max_move_frac of the global rows move per event.
    rebalance: bool = False
    rebalance_threshold: float = 1.5
    rebalance_patience: int = 3
    rebalance_max_move_frac: float = 0.25
    # --- live elastic membership (parallel/membership.py;
    # docs/ROBUSTNESS.md).  Off by default: elastic_membership=False
    # compiles the exact static-fleet path (jax.distributed transport,
    # documented bounded fail-fast on coordinator death).  When on, the
    # worker must have armed a MembershipRuntime (or set
    # LIGHTGBM_TPU_MEMBER_DIR) before Booster construction; collectives
    # then ride the shared-directory KV fleet, workers may join/leave
    # mid-run at iteration boundaries, and a dead member is evicted
    # (survivors resize via the in-RAM canonical merge/reshard path)
    # instead of the whole fleet exiting 75.
    elastic_membership: bool = False

    # --- derived
    is_parallel: bool = False
    is_parallel_find_bin: bool = False

    def copy(self) -> "Config":
        return dataclasses.replace(self)

    # ------------------------------------------------------------------
    @classmethod
    def from_params(cls, params: Optional[Dict[str, Any]]) -> "Config":
        cfg = cls()
        cfg.update(params or {})
        return cfg

    def update(self, params: Dict[str, Any]) -> None:
        canon = canonicalize_params(params)
        for key, value in canon.items():
            self._set_one(key, value)
        self._check_conflicts()

    def _set_one(self, key: str, value: Any) -> None:
        if key in ("metric",):
            self.metric = _parse_list(value, str)
            return
        if key in ("valid_data",):
            self.valid_data = _parse_list(value, str)
            return
        if key == "ndcg_eval_at":
            self.ndcg_eval_at = _parse_list(value, int)
            return
        if key == "label_gain":
            self.label_gain = _parse_list(value, float)
            return
        if key == "monotone_constraints":
            # two accepted forms (docs/TREES.md): comma list (one
            # direction per raw feature) or {feature: direction} dict;
            # python lists normalize to the comma form
            if isinstance(value, dict):
                self.monotone_constraints = dict(value)
            elif isinstance(value, (list, tuple)):
                self.monotone_constraints = ",".join(
                    str(int(v)) for v in value)
            else:
                self.monotone_constraints = str(value)
            return
        if not hasattr(self, key):
            Log.fatal("Unknown parameter: %s", key)
        cur = getattr(self, key)
        try:
            if isinstance(cur, bool):
                setattr(self, key, _parse_bool(key, value))
            elif isinstance(cur, int):
                setattr(self, key, int(value))
            elif isinstance(cur, float):
                setattr(self, key, float(value))
            else:
                setattr(self, key, str(value))
        except (TypeError, ValueError):
            Log.fatal("Parameter %s received an unparsable value \"%s\"", key, value)

    def _monotone_active(self) -> bool:
        """True when monotone_constraints names at least one nonzero
        direction (either surface form)."""
        mc = self.monotone_constraints
        if isinstance(mc, dict):
            return any(int(v) != 0 for v in mc.values())
        s = str(mc).strip()
        if not s:
            return False
        return any(p.strip() not in ("", "0") for p in s.split(","))

    def _check_conflicts(self) -> None:
        """CheckParamConflict (config.cpp): parallel learners imply
        is_parallel; bagging requires fraction<1 and freq>0; etc."""
        learner = self.tree_learner.lower()
        if learner not in ("serial", "data", "feature", "voting"):
            Log.fatal(
                "tree_learner must be one of serial/data/feature/voting, "
                "got %s", self.tree_learner)
        if learner in ("feature", "data", "voting") and self.num_machines > 1:
            self.is_parallel = True
        else:
            self.is_parallel = False
        if learner == "data" or learner == "voting":
            self.is_parallel_find_bin = self.is_parallel
        if self.top_k < 1:
            Log.fatal("top_k must be >= 1 for voting-parallel, got %d",
                      self.top_k)
        if (learner in ("voting", "feature")
                and str(self.out_of_core).lower() in ("true", "1", "on",
                                                      "yes")):
            Log.fatal(
                "tree_learner=%s cannot run with out_of_core=true: "
                "the %s needs the full resident bin matrix. Streaming "
                "supports tree_learner=serial (single process) or "
                "tree_learner=data (each rank streams its own row "
                "shard). Set out_of_core=false (or auto) or switch to "
                "tree_learner=data.",
                learner,
                "voting learner's per-node elected-histogram exchange"
                if learner == "voting"
                else "feature-parallel learner's column blocks")
        if self.num_leaves < 2:
            Log.fatal("num_leaves must be >= 2, got %d", self.num_leaves)
        if not (0.0 < self.feature_fraction <= 1.0):
            Log.fatal("feature_fraction must be in (0, 1], got %s", self.feature_fraction)
        if not (0.0 < self.bagging_fraction <= 1.0):
            Log.fatal("bagging_fraction must be in (0, 1], got %s", self.bagging_fraction)
        if self.bad_row_policy not in ("error", "skip"):
            Log.fatal("bad_row_policy must be 'error' or 'skip', got %s",
                      self.bad_row_policy)
        if str(self.out_of_core).lower() not in (
                "auto", "true", "false", "1", "0", "on", "off", "yes", "no"):
            Log.fatal("out_of_core must be auto/true/false, got %s",
                      self.out_of_core)
        if self.ooc_chunk_rows < 0:
            Log.fatal(
                "ooc_chunk_rows must be >= 0 (0 = auto-size; any "
                "positive value is rounded up to a ROW_BLOCK multiple, "
                "per rank over that rank's shard rows under "
                "tree_learner=data), got %d", self.ooc_chunk_rows)
        if self.ooc_prefetch_depth < 1:
            Log.fatal(
                "ooc_prefetch_depth must be >= 1 (chunks in flight in "
                "each rank's prefetch ring), got %d",
                self.ooc_prefetch_depth)
        if not (2 <= self.quantized_grad_bits <= 15):
            # >15 would let a single row overflow the int16 wire plane;
            # <2 leaves no signed levels at all
            Log.fatal("quantized_grad_bits must be in [2, 15], got %d",
                      self.quantized_grad_bits)
        if self.linear_lambda < 0:
            Log.fatal(
                "linear_lambda must be >= 0 (ridge strength on the "
                "linear-leaf slope terms), got %s", self.linear_lambda)
        if self.linear_tree:
            # supported matrix (docs/TREES.md): linear leaves need f32
            # leaf sums and post-grow refits against the resident (or
            # serially streamed) row shard of ONE process
            matrix = ("linear_tree supports: boosting_type=gbdt/goss, "
                      "quantized_training=false, tree_learner=serial or "
                      "data on a single process (in-memory or "
                      "out_of_core serial streaming)")
            if self.quantized_training:
                Log.fatal(
                    "linear_tree=true cannot run with "
                    "quantized_training=true: the per-leaf least-squares "
                    "refit needs f32 gradient/hessian rows, not int16 "
                    "levels. %s.", matrix)
            if self.boosting_type.lower() == "dart":
                Log.fatal(
                    "linear_tree=true cannot run with boosting=dart: "
                    "DART's per-tree drop/renormalize rescales leaf "
                    "outputs after the fit, which would silently skew "
                    "the fitted slopes. %s.", matrix)
            if self.num_machines > 1:
                Log.fatal(
                    "linear_tree=true cannot run with num_machines=%d: "
                    "the leaf refit solves against rows the coordinator "
                    "does not hold. %s.", self.num_machines, matrix)
        if self._monotone_active() and self.objective == "lambdarank":
            Log.fatal(
                "monotone_constraints cannot be combined with "
                "objective=lambdarank: listwise rank gradients are not "
                "per-row monotone in feature direction. Supported: "
                "row-wise objectives (regression/binary/multiclass/"
                "xentropy family) on every learner except the fused "
                "ptrainer (which declines and falls back).")
        if self.network_timeout <= 0:
            Log.fatal("network_timeout must be > 0, got %s", self.network_timeout)
        if self.network_retries < 0:
            Log.fatal("network_retries must be >= 0, got %d", self.network_retries)
        if self.rebalance_threshold <= 1.0:
            Log.fatal("rebalance_threshold must be > 1, got %s",
                      self.rebalance_threshold)
        if self.rebalance_patience < 1:
            Log.fatal("rebalance_patience must be >= 1, got %d",
                      self.rebalance_patience)
        if not (0.0 < self.rebalance_max_move_frac <= 1.0):
            Log.fatal("rebalance_max_move_frac must be in (0, 1], got %s",
                      self.rebalance_max_move_frac)
        if self.elastic_membership:
            if self.tree_learner not in ("data", "serial"):
                Log.fatal(
                    "elastic_membership=true requires tree_learner=data "
                    "(got %s): feature-parallel shards columns, and a "
                    "membership change re-partitions ROWS through the "
                    "canonical merge/reshard path.", self.tree_learner)
            if self.num_machines > 1:
                Log.fatal(
                    "elastic_membership=true cannot run with "
                    "num_machines=%d: the membership fleet replaces the "
                    "static socket world.", self.num_machines)
        Log.reset_level(self.verbose)


# canonical parameter names beyond the alias table; mirrors the
# parameter_set whitelist at config.h:444-474 (extended with TPU-specific
# names; unknown keys are rejected like the reference's Log::Fatal).
_EXTRA_ALLOWED = {
    "machine_list_filename",
    "data_filename",
    "valid_data_filenames",
    "poission_max_delta_step",  # reference's own typo, kept accepted
    "is_provide_training_metric",
}


def canonicalize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Alias resolution with canonical-name priority: an explicitly-passed
    canonical key wins over a value arriving via an alias
    (ParameterAlias::KeyAliasTransform, config.h:475-486)."""
    cfg_fields = {f.name for f in dataclasses.fields(Config)}
    out: Dict[str, Any] = {}
    aliased: Dict[str, Any] = {}
    for key, value in params.items():
        if value is None:
            continue
        if key in PARAM_ALIASES:
            aliased[PARAM_ALIASES[key]] = value
        elif key in cfg_fields or key in _EXTRA_ALLOWED:
            out[key] = value
        else:
            Log.fatal("Unknown parameter: %s", key)
    for key, value in aliased.items():
        out.setdefault(key, value)
    # normalize the reference's *_filename spellings
    if "machine_list_filename" in out:
        out.setdefault("machine_list_file", out.pop("machine_list_filename"))
    if "data_filename" in out:
        out["data"] = out.pop("data_filename")
    if "valid_data_filenames" in out:
        out["valid_data"] = out.pop("valid_data_filenames")
    if "is_provide_training_metric" in out:
        out["is_training_metric"] = out.pop("is_provide_training_metric")
    if "poission_max_delta_step" in out:
        out["poisson_max_delta_step"] = out.pop("poission_max_delta_step")
    return out


def _parse_bool(key: str, value: Any) -> bool:
    if isinstance(value, bool):
        return value
    v = str(value).lower()
    if v in ("true", "+", "1"):
        return True
    if v in ("false", "-", "0"):
        return False
    Log.fatal('Parameter %s should be "true"/"+" or "false"/"-", got "%s"', key, value)
    raise AssertionError  # unreachable


def _parse_list(value: Any, typ) -> list:
    if isinstance(value, (list, tuple)):
        return [typ(v) for v in value]
    s = str(value).strip()
    if not s:
        return []
    return [typ(v) for v in s.replace(",", " ").split()]


def params_to_str(params: Dict[str, Any]) -> str:
    """Serialize a param dict to 'k=v k=v' (basic.py param_dict_to_str)."""
    pairs = []
    for key, value in params.items():
        if isinstance(value, (list, tuple)):
            value = ",".join(str(v) for v in value)
        pairs.append(f"{key}={value}")
    return " ".join(pairs)
