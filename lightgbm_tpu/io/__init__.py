from .binning import BinMapper, greedy_find_bin
from .dataset import BinnedDataset, Metadata
