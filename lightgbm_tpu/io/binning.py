"""Feature binning — counterpart of the reference's BinMapper
(src/io/bin.cpp, include/LightGBM/bin.h).

Behavioral parity targets:
- ``greedy_find_bin``   ↔ GreedyFindBin (bin.cpp:66–135): equal-count greedy
  binning with big-count values pinned to their own bin.
- ``BinMapper.find_bin`` ↔ BinMapper::FindBin (bin.cpp:137–290): zero/missing
  range handling (|v| <= kMissingValueRange treated as the default/zero bin),
  separate greedy binning of the negative and positive ranges, categorical
  count-ordered bin assignment with a 98% coverage cut, trivial-feature
  filtering via NeedFilter (bin.cpp:47-65).
- ``BinMapper.value_to_bin`` ↔ ValueToBin (bin.h:419–441): first upper bound
  >= value; unseen categoricals map to the last bin.

All of this is host-side numpy on the sampled rows — binning happens once at
dataset construction, so there is nothing to accelerate on the TPU; the
output (the binned uint8/uint16 matrix) is what lives in HBM.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..utils.log import Log

# |value| <= this is treated as zero/missing (reference meta.h:22)
MISSING_VALUE_RANGE = 1e-20

NUMERICAL = 0
CATEGORICAL = 1


def greedy_find_bin(
    distinct_values: np.ndarray,
    counts: np.ndarray,
    max_bin: int,
    total_cnt: int,
    min_data_in_bin: int,
) -> List[float]:
    """Equal-count greedy binning over sorted distinct values.

    Returns the list of bin upper bounds; the last is +inf.
    Parity with GreedyFindBin (bin.cpp:66–135).
    """
    num_distinct = len(distinct_values)
    bounds: List[float] = []
    if num_distinct == 0:
        return bounds
    if num_distinct <= max_bin:
        cur_cnt = 0
        for i in range(num_distinct - 1):
            cur_cnt += int(counts[i])
            if cur_cnt >= min_data_in_bin:
                bounds.append((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                cur_cnt = 0
        bounds.append(np.inf)
        return bounds

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin

    # values whose count alone exceeds the mean bin size get a private bin
    is_big = counts >= mean_bin_size
    rest_bin_cnt = max_bin - int(np.sum(is_big))
    rest_sample_cnt = total_cnt - int(np.sum(counts[is_big]))
    mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)

    upper: List[float] = []
    lower: List[float] = [distinct_values[0]]
    cur_cnt = 0
    for i in range(num_distinct - 1):
        if not is_big[i]:
            rest_sample_cnt -= int(counts[i])
        cur_cnt += int(counts[i])
        need_new = (
            is_big[i]
            or cur_cnt >= mean_bin_size
            or (is_big[i + 1] and cur_cnt >= max(1.0, mean_bin_size * 0.5))
        )
        if need_new:
            upper.append(float(distinct_values[i]))
            lower.append(float(distinct_values[i + 1]))
            if len(upper) >= max_bin - 1:
                break
            cur_cnt = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)

    bounds = [(upper[i] + lower[i + 1]) / 2.0 for i in range(len(upper))]
    bounds.append(np.inf)
    return bounds


def _need_filter(cnt_in_bin: np.ndarray, total_cnt: int, filter_cnt: int, bin_type: int) -> bool:
    """True when no split of this feature can satisfy min_data_in_leaf on
    both sides (NeedFilter, bin.cpp:47–65)."""
    if len(cnt_in_bin) <= 1:
        return True
    if bin_type == NUMERICAL:
        left = np.cumsum(cnt_in_bin[:-1])
        ok = (left >= filter_cnt) & (total_cnt - left >= filter_cnt)
        return not bool(np.any(ok))
    one = cnt_in_bin[:-1]
    ok = (one >= filter_cnt) & (total_cnt - one >= filter_cnt)
    return not bool(np.any(ok))


class BinMapper:
    """Maps one feature's raw values to small integer bins."""

    def __init__(self):
        self.num_bin: int = 1
        self.bin_type: int = NUMERICAL
        self.is_trivial: bool = True
        self.sparse_rate: float = 0.0
        self.bin_upper_bound: np.ndarray = np.array([np.inf])
        self.bin_2_categorical: np.ndarray = np.array([], dtype=np.int64)
        self.categorical_2_bin: Dict[int, int] = {}
        self.default_bin: int = 0
        self.min_val: float = 0.0
        self.max_val: float = 0.0

    # ------------------------------------------------------------------
    def find_bin(
        self,
        sample_values: np.ndarray,
        total_sample_cnt: int,
        max_bin: int,
        min_data_in_bin: int,
        min_split_data: int,
        bin_type: int = NUMERICAL,
    ) -> None:
        """Build the bin mapping from sampled *non-zero* values.

        ``total_sample_cnt`` = len(sample_values) + number of zero entries,
        exactly as the reference passes them (FindBin, bin.cpp:137).
        """
        values = np.asarray(sample_values, dtype=np.float64)
        distinct_arr, counts_arr = np.unique(values, return_counts=True)
        self.find_bin_from_distinct(
            distinct_arr, counts_arr.astype(np.int64), total_sample_cnt,
            max_bin, min_data_in_bin, min_split_data, bin_type,
        )

    def find_bin_from_distinct(
        self,
        distinct_values: np.ndarray,
        counts: np.ndarray,
        total_sample_cnt: int,
        max_bin: int,
        min_data_in_bin: int,
        min_split_data: int,
        bin_type: int = NUMERICAL,
    ) -> None:
        """``find_bin`` over pre-aggregated (distinct non-zero value,
        count) pairs — the entry point for mergeable streaming sketches
        (data/sketch.py): a sketch that is still exact reproduces the
        raw-sample mapper bit-for-bit, a spilled one feeds its summary
        representatives.  ``total_sample_cnt - counts.sum()`` is the
        implied zero/missing count, same contract as ``find_bin``."""
        self.bin_type = bin_type
        self.default_bin = 0
        distinct_arr = np.asarray(distinct_values, dtype=np.float64)
        counts_arr = np.asarray(counts, dtype=np.int64)
        zero_cnt = int(total_sample_cnt - counts_arr.sum())
        insert_at: Optional[int] = None
        if len(distinct_arr) == 0 or (distinct_arr[0] > 0.0 and zero_cnt > 0):
            insert_at = 0
        elif distinct_arr[-1] < 0.0 and zero_cnt > 0:
            insert_at = len(distinct_arr)
        else:
            pos = int(np.searchsorted(distinct_arr, 0.0, side="left"))
            if 0 < pos < len(distinct_arr) and distinct_arr[pos - 1] < 0.0 < distinct_arr[pos]:
                insert_at = pos
        if insert_at is not None:
            distinct_arr = np.insert(distinct_arr, insert_at, 0.0)
            counts_arr = np.insert(counts_arr, insert_at, zero_cnt)
        self.min_val = float(distinct_arr[0]) if len(distinct_arr) else 0.0
        self.max_val = float(distinct_arr[-1]) if len(distinct_arr) else 0.0

        if bin_type == NUMERICAL:
            cnt_in_bin = self._find_bin_numerical(
                distinct_arr, counts_arr, total_sample_cnt, max_bin, min_data_in_bin
            )
        else:
            cnt_in_bin = self._find_bin_categorical(distinct_arr, counts_arr, total_sample_cnt, max_bin)

        self.is_trivial = self.num_bin <= 1 or _need_filter(
            cnt_in_bin, total_sample_cnt, min_split_data, bin_type
        )
        if not self.is_trivial:
            self.default_bin = int(self.value_to_bin(0.0))
        # sparse_rate computed even for trivial features (bin.cpp:289)
        if len(cnt_in_bin) > self.default_bin:
            self.sparse_rate = float(cnt_in_bin[self.default_bin]) / max(total_sample_cnt, 1)

    def _find_bin_numerical(self, distinct, counts, total_cnt, max_bin, min_data_in_bin):
        # partition distinct values into negative / zero-range / positive
        left_mask = distinct <= -MISSING_VALUE_RANGE
        right_mask = distinct > MISSING_VALUE_RANGE
        zero_mask = ~left_mask & ~right_mask
        left_cnt_data = int(np.sum(counts[left_mask]))
        missing_cnt_data = int(np.sum(counts[zero_mask]))
        right_cnt_data = int(np.sum(counts[right_mask]))
        # Intentional divergence from bin.cpp:196-204: there, left_cnt stays
        # 0 when NO value > -kMissingValueRange exists (strictly-negative
        # feature), so the reference emits a single [inf] bin and drops the
        # feature as trivial.  Here such features are binned normally —
        # strictly better behavior, at the cost of bit-parity with reference
        # models on strictly-negative features (documented per ADVICE r1).
        left_cnt = int(np.sum(left_mask))

        bounds: List[float] = []
        if left_cnt > 0:
            denom = max(total_cnt - missing_cnt_data, 1)
            left_max_bin = int(left_cnt_data / denom * (max_bin - 1))
            left_bounds = greedy_find_bin(
                distinct[:left_cnt], counts[:left_cnt], left_max_bin, left_cnt_data, min_data_in_bin
            )
            if left_bounds:
                left_bounds[-1] = -MISSING_VALUE_RANGE
            bounds.extend(left_bounds)

        right_idx = np.nonzero(right_mask)[0]
        if len(right_idx) > 0:
            rs = int(right_idx[0])
            right_max_bin = max_bin - 1 - len(bounds)
            right_bounds = greedy_find_bin(
                distinct[rs:], counts[rs:], right_max_bin, right_cnt_data, min_data_in_bin
            )
            bounds.append(MISSING_VALUE_RANGE)  # the zero/default bin
            bounds.extend(right_bounds)
        else:
            bounds.append(np.inf)

        self.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
        self.num_bin = len(bounds)
        if self.num_bin > max_bin:
            Log.fatal("bin count %d exceeds max_bin %d", self.num_bin, max_bin)
        # histogram of sampled data over the final bins
        bin_of_distinct = np.searchsorted(self.bin_upper_bound, distinct, side="left")
        cnt_in_bin = np.zeros(self.num_bin, dtype=np.int64)
        np.add.at(cnt_in_bin, bin_of_distinct, counts)
        return cnt_in_bin

    def _find_bin_categorical(self, distinct, counts, total_cnt, max_bin):
        # fold to ints, then order by count descending (stable)
        distinct_int = distinct.astype(np.int64)
        uniq, inv = np.unique(distinct_int, return_inverse=True)
        cnt = np.zeros(len(uniq), dtype=np.int64)
        np.add.at(cnt, inv, counts)
        order = np.argsort(-cnt, kind="stable")
        uniq, cnt = uniq[order], cnt[order]

        cut_cnt = int(total_cnt * 0.98)
        max_bin = min(len(uniq), max_bin)
        used_cnt = 0
        num_bin = 0
        while num_bin < len(uniq) and (used_cnt < cut_cnt or num_bin < max_bin):
            used_cnt += int(cnt[num_bin])
            num_bin += 1
        self.num_bin = num_bin
        self.bin_2_categorical = uniq[:num_bin].copy()
        self.categorical_2_bin = {int(v): i for i, v in enumerate(self.bin_2_categorical)}
        # Parity quirk (bin.cpp:269-271): cnt_in_bin is the FULL distinct
        # counts — the unseen-value fold `counts_int.back() += ...` lands in
        # the truncated copy that is immediately discarded — so NeedFilter
        # and sparse_rate see untruncated per-category counts.
        return cnt.copy()

    # ------------------------------------------------------------------
    def value_to_bin(self, value) -> np.ndarray:
        """Vectorized value→bin (ValueToBin, bin.h:419–441)."""
        value = np.asarray(value, dtype=np.float64)
        if self.bin_type == NUMERICAL:
            v = np.where(np.isnan(value), 0.0, value)  # NaN rides the zero bin
            return np.minimum(
                np.searchsorted(self.bin_upper_bound, v, side="left"), self.num_bin - 1
            ).astype(np.int32)
        out = np.full(value.shape, self.num_bin - 1, dtype=np.int32)
        iv = value.astype(np.int64)
        for cat, b in self.categorical_2_bin.items():
            out[iv == cat] = b
        return out

    def bin_to_value(self, b: int) -> float:
        """Representative value of a bin (BinToValue, bin.h:98-104)."""
        if self.bin_type == NUMERICAL:
            return float(self.bin_upper_bound[b])
        return float(self.bin_2_categorical[b])

    # ------------------------------------------------------------------
    def to_string(self) -> str:
        """Feature-info string used in the model file ("min:max" for
        numerical, colon-joined categories otherwise) — matches the
        feature_infos= field the reference writes (dataset.cpp)."""
        if self.is_trivial:
            return "none"
        if self.bin_type == NUMERICAL:
            return f"[{self.min_val}:{self.max_val}]"
        return ":".join(str(int(v)) for v in self.bin_2_categorical)

    def state(self) -> dict:
        return {
            "num_bin": self.num_bin,
            "bin_type": self.bin_type,
            "is_trivial": self.is_trivial,
            "sparse_rate": self.sparse_rate,
            "bin_upper_bound": self.bin_upper_bound,
            "bin_2_categorical": self.bin_2_categorical,
            "default_bin": self.default_bin,
            "min_val": self.min_val,
            "max_val": self.max_val,
        }

    @classmethod
    def from_state(cls, st: dict) -> "BinMapper":
        m = cls()
        m.num_bin = int(st["num_bin"])
        m.bin_type = int(st["bin_type"])
        m.is_trivial = bool(st["is_trivial"])
        m.sparse_rate = float(st["sparse_rate"])
        m.bin_upper_bound = np.asarray(st["bin_upper_bound"], dtype=np.float64)
        m.bin_2_categorical = np.asarray(st["bin_2_categorical"], dtype=np.int64)
        m.categorical_2_bin = {int(v): i for i, v in enumerate(m.bin_2_categorical)}
        m.default_bin = int(st["default_bin"])
        m.min_val = float(st["min_val"])
        m.max_val = float(st["max_val"])
        return m
