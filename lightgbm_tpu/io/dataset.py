"""Binned dataset — counterpart of the reference's Dataset/Metadata
(src/io/dataset.cpp, src/io/metadata.cpp, include/LightGBM/dataset.h).

TPU-first design: instead of per-feature-group Bin objects (dense /
sparse / 4-bit / ordered variants, feature_group.h), the whole dataset is
ONE dense row-major ``(N, F)`` uint8/uint16 matrix of bin indices that is
transferred to HBM once and stays resident.  Histogram construction over it
is a single XLA/Pallas kernel (ops/histogram.py) rather than per-group
virtual dispatch.  Sparse/EFB storage optimizations are deliberately
deferred: on TPU, dense with ``sparse_threshold=1.0`` is the recommended
configuration in the reference's own GPU docs (docs/GPU-Performance.md:112).

Parity notes:
- trivial-feature filtering and used-feature mapping ↔ Dataset::Construct
  (dataset.cpp:210)
- metadata (labels/weights/query boundaries/init score) ↔ Metadata
  (dataset.h:36–248, metadata.cpp)
- binary cache save/load ↔ SaveBinaryFile/LoadFromBinFile
  (dataset.cpp, dataset_loader.cpp:263) — here an .npz with a magic key.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..config import Config
from ..utils.log import Log
from ..utils.random import Random
from .binning import CATEGORICAL, NUMERICAL, BinMapper

_BINARY_MAGIC = "lightgbm_tpu.dataset.v1"


class Metadata:
    """Labels, weights, query boundaries, init scores (dataset.h:36–248)."""

    def __init__(self, num_data: int = 0):
        self.num_data = num_data
        self.label: np.ndarray = np.zeros(num_data, dtype=np.float32)
        self.weights: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None
        self.query_weights: Optional[np.ndarray] = None
        self.init_score: Optional[np.ndarray] = None

    def set_label(self, label: Sequence[float]) -> None:
        label = np.asarray(label, dtype=np.float32).ravel()
        if len(label) != self.num_data:
            Log.fatal("Length of label (%d) != num_data (%d)", len(label), self.num_data)
        self.label = label

    def set_weights(self, weights: Optional[Sequence[float]]) -> None:
        if weights is None:
            self.weights = None
            return
        weights = np.asarray(weights, dtype=np.float32).ravel()
        if len(weights) != self.num_data:
            Log.fatal("Length of weights (%d) != num_data (%d)", len(weights), self.num_data)
        self.weights = weights

    def set_query(self, group: Optional[Sequence[int]]) -> None:
        """``group`` is per-query sizes (python API convention); builds
        cumulative query boundaries like Metadata::SetQuery."""
        if group is None:
            self.query_boundaries = None
            return
        group = np.asarray(group, dtype=np.int64).ravel()
        if int(group.sum()) != self.num_data:
            Log.fatal("Sum of query counts (%d) != num_data (%d)", int(group.sum()), self.num_data)
        self.query_boundaries = np.concatenate([[0], np.cumsum(group)]).astype(np.int64)

    def set_init_score(self, init_score: Optional[Sequence[float]]) -> None:
        if init_score is None:
            self.init_score = None
            return
        self.init_score = np.asarray(init_score, dtype=np.float64).ravel()

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1


class BinnedDataset:
    """The device-ready binned training data.

    Attributes
    ----------
    binned : (num_data, num_used_features) np.uint8 or np.uint16
        Bin index of each (row, used-feature).
    bin_mappers : list[BinMapper], one per used feature.
    used_feature_map : original feature index of each used feature.
    num_total_features : raw feature count before trivial filtering.
    """

    def __init__(self):
        self.binned: np.ndarray = np.zeros((0, 0), dtype=np.uint8)
        self.bin_mappers: List[BinMapper] = []
        self.used_feature_map: np.ndarray = np.array([], dtype=np.int32)
        self.num_total_features: int = 0
        self.metadata = Metadata(0)
        self.feature_names: List[str] = []
        self.max_bin: int = 255
        self.label_idx: int = 0
        self.bundle = None  # EFB BundleInfo (io/bundle.py); None = unbundled
        self.bundled: Optional[np.ndarray] = None  # (N, G) uint8 bundle bins
        # set when loaded from a v2 binary cache: the out-of-core trainer
        # streams checksummed chunks straight from this file
        self.cache_path: Optional[str] = None
        # raw (unbinned) copy is not kept — predictions on training data run
        # on the binned representation like the reference's score updater.

    # ------------------------------------------------------------------
    @property
    def num_data(self) -> int:
        return self.binned.shape[0]

    @property
    def num_features(self) -> int:
        """Number of used (non-trivial) features."""
        return self.binned.shape[1]

    def num_bin(self, fidx: int) -> int:
        return self.bin_mappers[fidx].num_bin

    @property
    def max_num_bin(self) -> int:
        return max((m.num_bin for m in self.bin_mappers), default=1)

    def real_threshold(self, fidx: int, bin_idx: int) -> float:
        return self.bin_mappers[fidx].bin_to_value(int(bin_idx))

    def inner_to_real_feature(self, fidx: int) -> int:
        return int(self.used_feature_map[fidx])

    # ------------------------------------------------------------------
    @classmethod
    def from_raw(
        cls,
        data: np.ndarray,
        config: Config,
        *,
        label: Optional[Sequence[float]] = None,
        weight: Optional[Sequence[float]] = None,
        group: Optional[Sequence[int]] = None,
        init_score: Optional[Sequence[float]] = None,
        feature_names: Optional[List[str]] = None,
        categorical_features: Optional[Sequence[int]] = None,
        reference: Optional["BinnedDataset"] = None,
        sample_indices: Optional[np.ndarray] = None,
    ) -> "BinnedDataset":
        """Construct from a raw dense float matrix.

        Mirrors DatasetLoader::ConstructBinMappersFromTextData +
        ExtractFeaturesFromMemory (dataset_loader.cpp:661, :840): sample rows,
        find bins per feature, then push every row through the mappers.
        With ``reference`` given, reuses its bin mappers (CreateValid /
        LoadFromFileAlignWithOtherDataset path).
        """
        data = np.asarray(data, dtype=np.float64)
        if data.ndim != 2:
            Log.fatal("data must be 2-dimensional")
        n, num_features = data.shape
        ds = cls()
        ds.num_total_features = num_features
        ds.max_bin = config.max_bin
        ds.metadata = Metadata(n)
        if label is not None:
            ds.metadata.set_label(label)
        ds.metadata.set_weights(weight)
        ds.metadata.set_query(group)
        ds.metadata.set_init_score(init_score)
        ds.feature_names = list(feature_names) if feature_names else [
            f"Column_{i}" for i in range(num_features)
        ]

        if reference is not None:
            ds.bin_mappers = reference.bin_mappers
            ds.used_feature_map = reference.used_feature_map
            ds.num_total_features = reference.num_total_features
            ds.feature_names = reference.feature_names
            ds.max_bin = reference.max_bin
        else:
            cat_set = set(int(c) for c in categorical_features) if categorical_features else set()
            mappers = _find_bin_mappers_distributed(data, config, cat_set, sample_indices)
            used = [i for i, m in enumerate(mappers) if not m.is_trivial]
            if not used:
                Log.fatal("Cannot construct Dataset: all features are trivial (constant)")
            ds.bin_mappers = [mappers[i] for i in used]
            ds.used_feature_map = np.asarray(used, dtype=np.int32)

        ds.binned = _bin_matrix(data, ds.bin_mappers, ds.used_feature_map)
        return ds

    def ensure_bundles(self, config) -> None:
        """Lazily build EFB bundles (io/bundle.py).  Deferred out of
        construction because only the partitioned trainer consumes them —
        CPU runs, ranking, multiclass and distributed configs should not
        pay the grouping scan or hold the extra (N, G) matrix."""
        if self.bundle is not None or getattr(self, "_bundle_checked", False):
            return
        self._bundle_checked = True
        if not getattr(config, "enable_bundle", True) or self.binned.dtype != np.uint8:
            return
        from .bundle import build_bundled_matrix, find_bundles

        info = find_bundles(self.binned, self.bin_mappers, config)
        if info is not None:
            self.bundle = info
            self.bundled = build_bundled_matrix(self.binned, self.bin_mappers, info)

    def create_valid(self, data, **kwargs) -> "BinnedDataset":
        """Validation dataset aligned with this dataset's bin mappers
        (Dataset::CreateValid, dataset.cpp)."""
        from ..config import Config as _C

        return BinnedDataset.from_raw(data, _C(), reference=self, **kwargs)

    def subset(self, indices: np.ndarray) -> "BinnedDataset":
        """Row subset sharing bin mappers (Dataset::CopySubset)."""
        indices = np.asarray(indices)
        ds = BinnedDataset()
        ds.binned = self.binned[indices]
        ds.bin_mappers = self.bin_mappers
        ds.used_feature_map = self.used_feature_map
        ds.num_total_features = self.num_total_features
        ds.feature_names = self.feature_names
        ds.max_bin = self.max_bin
        ds.metadata = Metadata(len(indices))
        ds.metadata.set_label(self.metadata.label[indices])
        if self.metadata.weights is not None:
            ds.metadata.set_weights(self.metadata.weights[indices])
        if self.metadata.query_boundaries is not None:
            # map each retained row to its query and count per-query
            # retained rows, keeping only non-empty queries in order
            # (Metadata::CheckOrPartition query partitioning)
            qb = self.metadata.query_boundaries
            row_query = np.searchsorted(qb, indices, side="right") - 1
            per_query = np.bincount(row_query, minlength=len(qb) - 1)
            ds.metadata.set_query(per_query[per_query > 0])
        if self.metadata.init_score is not None:
            ns = len(self.metadata.init_score) // max(self.metadata.num_data, 1)
            sc = self.metadata.init_score.reshape(ns, -1)[:, indices] if ns > 1 else None
            if ns > 1:
                ds.metadata.set_init_score(sc.ravel())
            else:
                ds.metadata.set_init_score(self.metadata.init_score[indices])
        return ds

    # ------------------------------------------------------------------
    def feature_infos(self) -> List[str]:
        """feature_infos= strings for the model file, indexed by ORIGINAL
        feature id (trivial features report 'none')."""
        infos = ["none"] * self.num_total_features
        for inner, real in enumerate(self.used_feature_map):
            infos[int(real)] = self.bin_mappers[inner].to_string()
        return infos

    # ------------------------------------------------------------------
    def save_binary(self, path: str, source_path: str = None) -> None:
        """Binary dataset cache (↔ Dataset::SaveBinaryFile), format v2.

        Members are stored UNCOMPRESSED so the bin matrix's bytes are
        contiguous in the file — the out-of-core trainer seeks straight
        into them (data/cache.py).  The ``__cache_meta__`` header records
        the format version, per-block CRCs and — when ``source_path`` is
        given — the source file's identity, so a cache that no longer
        matches its source is refused instead of silently trusted."""
        from ..data.cache import build_cache_meta, chunk_crcs

        meta = build_cache_meta(self.binned, self.metadata.label,
                                source_path=source_path)
        import json

        payload: Dict[str, np.ndarray] = {
            "magic": np.asarray(_BINARY_MAGIC),
            "__cache_meta__": np.asarray(json.dumps(meta)),
            "chunk_crc": chunk_crcs(self.binned),
            "binned": self.binned,
            "used_feature_map": self.used_feature_map,
            "num_total_features": np.asarray(self.num_total_features),
            "feature_names": np.asarray(self.feature_names),
            "max_bin": np.asarray(self.max_bin),
            "label": self.metadata.label,
            "num_mappers": np.asarray(len(self.bin_mappers)),
        }
        if self.metadata.weights is not None:
            payload["weights"] = self.metadata.weights
        if self.metadata.query_boundaries is not None:
            payload["query_boundaries"] = self.metadata.query_boundaries
        if self.metadata.init_score is not None:
            payload["init_score"] = self.metadata.init_score
        for i, m in enumerate(self.bin_mappers):
            st = m.state()
            payload[f"m{i}_meta"] = np.asarray(
                [
                    st["num_bin"],
                    st["bin_type"],
                    int(st["is_trivial"]),
                    st["default_bin"],
                ],
                dtype=np.int64,
            )
            payload[f"m{i}_fl"] = np.asarray(
                [st["sparse_rate"], st["min_val"], st["max_val"]], dtype=np.float64
            )
            payload[f"m{i}_bounds"] = st["bin_upper_bound"]
            payload[f"m{i}_cats"] = st["bin_2_categorical"]
        # write to the EXACT path (np.savez appends .npz to bare names;
        # the reference's SaveBinaryFile writes the filename it was given).
        # Uncompressed on purpose: random access into "binned" needs the
        # raw bytes on disk (and bin matrices barely compress anyway).
        with open(path, "wb") as f:
            np.savez(f, **payload)

    @staticmethod
    def is_binary_cache(path: str) -> bool:
        """True when ``path`` is a saved binary dataset (zip magic +
        our payload) — DatasetLoader checks the binary header before
        falling back to text parsing (dataset_loader.cpp LoadFromBinFile)."""
        try:
            with open(path, "rb") as f:
                if f.read(4) != b"PK\x03\x04":
                    return False
            with np.load(path, allow_pickle=False) as z:
                return "magic" in z and str(z["magic"]) == _BINARY_MAGIC
        except Exception:
            return False

    @classmethod
    def load_binary(cls, path: str) -> "BinnedDataset":
        from ..data.cache import (
            CACHE_FORMAT_VERSION,
            open_cache_reader,
            read_cache_meta,
            stale_reason,
        )

        with np.load(path, allow_pickle=False) as z:
            if str(z["magic"]) != _BINARY_MAGIC:
                Log.fatal("File %s is not a lightgbm_tpu binary dataset", path)
            meta = read_cache_meta(z)
            if meta is None:
                Log.fatal(
                    "Binary dataset %s predates cache format v%d (no "
                    "version/fingerprint header) — regenerate it with "
                    "task=ingest", path, CACHE_FORMAT_VERSION)
            if int(meta.get("format_version", 0)) > CACHE_FORMAT_VERSION:
                Log.fatal(
                    "Binary dataset %s has cache format v%s, newer than "
                    "this build supports (v%d)", path,
                    meta.get("format_version"), CACHE_FORMAT_VERSION)
            stale = stale_reason(meta)
            if stale:
                Log.fatal(
                    "Refusing stale binary dataset %s: %s — regenerate "
                    "the cache with task=ingest (or delete it)", path, stale)
            ds = cls()
            # prefer a read-only memmap of the stored matrix: demand-paged
            # host residency, and the out-of-core trainer can stream
            # checksummed chunks straight from the same file
            reader = open_cache_reader(path)
            if reader is not None:
                ds.binned = reader.memmap()
                ds.cache_path = path
                reader.close()
            else:
                ds.binned = z["binned"]
            ds.used_feature_map = z["used_feature_map"]
            ds.num_total_features = int(z["num_total_features"])
            ds.feature_names = [str(s) for s in z["feature_names"]]
            ds.max_bin = int(z["max_bin"])
            ds.metadata = Metadata(ds.binned.shape[0])
            ds.metadata.set_label(z["label"])
            if "weights" in z:
                ds.metadata.set_weights(z["weights"])
            if "query_boundaries" in z:
                ds.metadata.query_boundaries = z["query_boundaries"].astype(np.int64)
            if "init_score" in z:
                ds.metadata.set_init_score(z["init_score"])
            for i in range(int(z["num_mappers"])):
                meta = z[f"m{i}_meta"]
                fl = z[f"m{i}_fl"]
                ds.bin_mappers.append(
                    BinMapper.from_state(
                        {
                            "num_bin": meta[0],
                            "bin_type": meta[1],
                            "is_trivial": bool(meta[2]),
                            "default_bin": meta[3],
                            "sparse_rate": fl[0],
                            "min_val": fl[1],
                            "max_val": fl[2],
                            "bin_upper_bound": z[f"m{i}_bounds"],
                            "bin_2_categorical": z[f"m{i}_cats"],
                        }
                    )
                )
        return ds


# ----------------------------------------------------------------------
def _find_bin_mappers_distributed(
    data: np.ndarray,
    config: Config,
    categorical: set,
    sample_indices: Optional[np.ndarray],
) -> List[BinMapper]:
    """Distributed find-bin (dataset_loader.cpp:733-835): in a
    multi-process runtime each process finds bins only for its contiguous
    feature block [start_r, start_r + len_r) — step = ceil(F/M), exactly
    the reference's assignment — then the serialized mappers are
    allgathered so every process ends with the identical full list.  The
    reference's max_bin Allreduce exists only to size its fixed-width
    copy buffers; here the pickled states are length-prefixed instead.
    Falls through to the single-process path otherwise."""
    if not getattr(config, "is_parallel_find_bin", False):
        return _find_bin_mappers(data, config, categorical, sample_indices)

    import jax

    from ..parallel.distributed import ensure_initialized

    if not ensure_initialized(config):
        return _find_bin_mappers(data, config, categorical, sample_indices)

    import pickle

    from ..parallel.collect import allgather_blob_lists

    nproc = jax.process_count()
    rank = jax.process_index()
    f_total = data.shape[1]
    step = max(1, -(-f_total // nproc))
    start = min(rank * step, f_total)
    stop = min(start + step, f_total)

    local_cats = {c - start for c in categorical if start <= c < stop}
    if stop > start:
        local = _find_bin_mappers(data[:, start:stop], config, local_cats, sample_indices)
    else:
        local = []
    blobs = [pickle.dumps(m.state()) for m in local]
    gathered = allgather_blob_lists(blobs, list_len=step)
    mappers: List[BinMapper] = []
    for f in range(f_total):
        r, i = divmod(f, step)
        mappers.append(BinMapper.from_state(pickle.loads(gathered[r][i])))
    return mappers


def _find_bin_mappers(
    data: np.ndarray,
    config: Config,
    categorical: set,
    sample_indices: Optional[np.ndarray],
) -> List[BinMapper]:
    """Sample rows then FindBin per feature (dataset_loader.cpp:661–776)."""
    n = data.shape[0]
    if sample_indices is None:
        sample_indices = bin_sample_indices(n, config)
    return find_bin_mappers_from_sample(data[sample_indices], n, config, categorical)


def bin_sample_indices(n: int, config: Config) -> np.ndarray:
    """The deterministic bin-construction row sample (DatasetLoader's
    ``random_.Sample(num_data, bin_construct_sample_cnt)``).  Sorted
    ascending, so a streaming pass can collect the rows with a single
    forward cursor and end up with EXACTLY the matrix the in-memory path
    samples — the anchor of streaming/in-memory bit-parity."""
    rng = Random(config.data_random_seed)
    sample_cnt = min(config.bin_construct_sample_cnt, n)
    return rng.sample(n, sample_cnt)


def find_bin_mappers_from_sample(
    sampled: np.ndarray,
    total_rows: int,
    config: Config,
    categorical: set,
) -> List[BinMapper]:
    """FindBin per feature over an already-collected sample matrix.
    ``total_rows`` is the FULL dataset row count — min_data_in_leaf is
    scaled by the sampling fraction, exactly like
    dataset_loader.cpp:491-492 / :709-710 (sampled per-bin counts are
    proportionally smaller than full-data counts)."""
    total = sampled.shape[0]
    filter_cnt = int(config.min_data_in_leaf * total / max(total_rows, 1))
    mappers: List[BinMapper] = []
    for f in range(sampled.shape[1]):
        col = sampled[:, f]
        col = col[~np.isnan(col)]
        nonzero = col[col != 0.0]
        m = BinMapper()
        m.find_bin(
            nonzero,
            total,
            config.max_bin,
            config.min_data_in_bin,
            filter_cnt,
            CATEGORICAL if f in categorical else NUMERICAL,
        )
        mappers.append(m)
    return mappers


def packed_bin_dtype(mappers: List[BinMapper]):
    """uint8 unless some feature needs >256 bins (the packed-matrix
    sizing rule, shared with the streaming pass-2 preallocation)."""
    max_bins = max((m.num_bin for m in mappers), default=2)
    return np.uint8 if max_bins <= 256 else np.uint16


def bin_rows_into(
    out: np.ndarray,
    start: int,
    data: np.ndarray,
    mappers: List[BinMapper],
    used_map: np.ndarray,
) -> None:
    """Bin raw rows directly into ``out[start:start+len(data)]`` — the
    pass-2 streaming write: each chunk lands in the preallocated packed
    matrix and the raw floats are dropped."""
    stop = start + data.shape[0]
    for inner, real in enumerate(used_map):
        out[start:stop, inner] = (
            mappers[inner].value_to_bin(data[:, int(real)]).astype(out.dtype)
        )


def _bin_matrix(data: np.ndarray, mappers: List[BinMapper], used_map: np.ndarray) -> np.ndarray:
    out = np.empty((data.shape[0], len(mappers)), dtype=packed_bin_dtype(mappers))
    bin_rows_into(out, 0, data, mappers, used_map)
    return out
