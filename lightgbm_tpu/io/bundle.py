"""Exclusive Feature Bundling (EFB) — counterpart of
Dataset::FindGroups / FastFeatureBundling (src/io/dataset.cpp:64-208) and
the FeatureGroup bin-offset layout (include/LightGBM/feature_group.h:30-76).

Sparse-wide data (Bosch 968, Expo 700 features) stores mostly-default
columns; bundling packs mutually-(almost-)exclusive features into one
dense column so histogram and partition cost scale with the number of
BUNDLES, not features — the memory/compute win the reference gets from
sparse bins, in the dense form the TPU MXU rewards (see README's sparse
storage decision).

Bundle bin layout (feature_group.h:34-48, PushData :128-136):
    bin 0            : every feature at its default bin
    feature i's bins : offset_i + b  (b != default_i), where offset_i is
                       the running total and a feature whose default bin
                       is 0 drops that bin (bias 1: stored value is
                       offset_i + b - 1 for b in 1..nb-1)
On conflicts (two non-default features in one row) the later feature in
group order wins, exactly like consecutive Bin::Push calls.

Deliberate simplifications vs the reference (documented):
- conflict search scans ALL candidate groups instead of sampling
  max_search_group=100 of them (F is small enough in numpy);
- the final group shuffle (Random(12) swap loop) is skipped — group
  order only affects the reference's threading layout;
- the "take apart small sparse group" branch never fires because sparse
  bin storage is rejected by design (is_enable_sparse is always false).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from ..utils.log import Log

# cap each bundle's total bin count so bundled columns stay uint8 — the
# same bound the reference applies on GPU (gpu_max_bin_per_group = 256,
# dataset.cpp:74)
MAX_BIN_PER_BUNDLE = 256


@dataclass
class BundleInfo:
    """Static bundling description for F inner features over G columns."""

    groups: List[List[int]]  # inner feature ids per bundle
    col: np.ndarray  # (F,) bundle column of each feature
    off_lo: np.ndarray  # (F,) first bundle value of the feature's range
    off_hi: np.ndarray  # (F,) one past the last bundle value
    bias: np.ndarray  # (F,) 1 when default_bin==0 (bin dropped), else 0
    num_bin_col: np.ndarray  # (G,) total bins per bundle column
    max_col_bin: int = 0

    @property
    def num_cols(self) -> int:
        return len(self.groups)


if hasattr(np, "bitwise_count"):  # numpy >= 2.0
    def _popcount64(x: np.ndarray) -> int:
        return int(np.bitwise_count(x).sum())
else:
    def _popcount64(x: np.ndarray) -> int:
        return int(np.unpackbits(x.view(np.uint8)).sum())


def _find_groups(nonzero: List[np.ndarray], order: np.ndarray,
                 max_error_cnt: int, num_bin: np.ndarray, default0: np.ndarray) -> List[List[int]]:
    """Greedy conflict-bounded grouping (FindGroups, dataset.cpp:64-134);
    ``nonzero[f]`` is the sampled-row non-default bitmask of feature f,
    packed to uint64 words (the conflict count is a popcount of the AND —
    64x less memory traffic than bool masks; ~1 s at 1000x200k)."""
    groups: List[List[int]] = []
    marks: List[np.ndarray] = []
    conflict: List[int] = []
    bins_in_group: List[int] = []
    for f in order:
        nz = nonzero[f]
        fbins = int(num_bin[f]) - (1 if default0[f] else 0)
        placed = False
        for g in range(len(groups)):
            if bins_in_group[g] + fbins > MAX_BIN_PER_BUNDLE - 1:
                continue
            rest = max_error_cnt - conflict[g]
            if rest < 0:
                continue
            cnt = _popcount64(marks[g] & nz)
            if cnt <= rest:
                groups[g].append(int(f))
                conflict[g] += cnt
                marks[g] |= nz
                bins_in_group[g] += fbins
                placed = True
                break
        if not placed:
            groups.append([int(f)])
            marks.append(nz.copy())
            conflict.append(0)
            bins_in_group.append(fbins)
    return groups


def find_bundles(binned: np.ndarray, mappers, config) -> Optional[BundleInfo]:
    """FastFeatureBundling (dataset.cpp:136-208) over the binned matrix.

    Returns None when bundling gains nothing (G == F) or is disabled."""
    n, f = binned.shape
    if f < 2:
        return None
    sample_cnt = min(n, int(getattr(config, "bin_construct_sample_cnt", 200000)))
    rng = np.random.RandomState(getattr(config, "data_random_seed", 1))
    rows = rng.choice(n, size=sample_cnt, replace=False) if sample_cnt < n else np.arange(n)
    sub = binned[rows]

    default_bin = np.asarray([m.default_bin for m in mappers], np.int64)
    num_bin = np.asarray([m.num_bin for m in mappers], np.int64)
    default0 = default_bin == 0

    nonzero_b = [sub[:, i] != default_bin[i] for i in range(f)]
    nz_cnt = np.asarray([int(m.sum()) for m in nonzero_b])
    # pack to uint64 words for fast AND+popcount conflict tests
    nonzero = [np.packbits(m).view(np.uint8) for m in nonzero_b]
    pad = (-len(nonzero[0])) % 8
    nonzero = [np.pad(m, (0, pad)).view(np.uint64) for m in nonzero]
    max_error_cnt = int(sample_cnt * float(getattr(config, "max_conflict_rate", 0.0)))

    natural = np.arange(f)
    by_cnt = np.argsort(-nz_cnt, kind="stable")
    g1 = _find_groups(nonzero, natural, max_error_cnt, num_bin, default0)
    g2 = _find_groups(nonzero, by_cnt, max_error_cnt, num_bin, default0)
    groups = g2 if len(g2) < len(g1) else g1

    if len(groups) >= f:
        return None

    col = np.zeros(f, np.int32)
    off_lo = np.zeros(f, np.int32)
    off_hi = np.zeros(f, np.int32)
    bias = np.zeros(f, np.int32)
    num_bin_col = np.zeros(len(groups), np.int32)
    for g, feats in enumerate(groups):
        if len(feats) == 1:
            # singleton column stores the RAW bin (off_lo == 0 marks it):
            # no shared zero slot, no offset — also the only layout that
            # fits a full 256-bin feature in uint8
            fe = feats[0]
            col[fe] = g
            off_lo[fe] = 0
            off_hi[fe] = int(num_bin[fe])
            bias[fe] = 0
            num_bin_col[g] = int(num_bin[fe])
            continue
        total = 1  # bin 0 = all-default (feature_group.h:35)
        for fe in feats:
            col[fe] = g
            off_lo[fe] = total
            w = int(num_bin[fe]) - (1 if default0[fe] else 0)
            off_hi[fe] = total + w
            bias[fe] = 1 if default0[fe] else 0
            total += w
        num_bin_col[g] = total
    info = BundleInfo(
        groups=[list(map(int, g)) for g in groups],
        col=col, off_lo=off_lo, off_hi=off_hi, bias=bias,
        num_bin_col=num_bin_col, max_col_bin=int(num_bin_col.max()),
    )
    Log.info(
        "EFB: bundled %d features into %d columns (max %d bins/column)",
        f, info.num_cols, info.max_col_bin,
    )
    return info


def build_bundled_matrix(binned: np.ndarray, mappers, info: BundleInfo) -> np.ndarray:
    """(N, G) uint8 bundled bins from the (N, F) per-feature bins
    (FeatureGroup::PushData, feature_group.h:128-136: value -> bin,
    skip default, add offset, minus one when default_bin == 0; later
    features overwrite on conflict)."""
    n, f = binned.shape
    out = np.zeros((n, info.num_cols), np.uint8)
    default_bin = np.asarray([m.default_bin for m in mappers], np.int64)
    for g, feats in enumerate(info.groups):
        if len(feats) == 1 and info.off_lo[feats[0]] == 0:
            out[:, g] = binned[:, feats[0]]  # singleton: raw bins
            continue
        colv = out[:, g]  # view: assignments below mutate ``out``
        for fe in feats:
            b = binned[:, fe].astype(np.int32)
            nz = b != default_bin[fe]
            vals = b + int(info.off_lo[fe]) - int(info.bias[fe])
            colv[nz] = vals[nz].astype(np.uint8)
    return out


def decode_bundled_column(colv: np.ndarray, fe: int, info: BundleInfo, default_bin: int) -> np.ndarray:
    """Recover feature fe's bin from its bundle column (test helper —
    exact except where another feature's conflict overwrote the slot)."""
    lo, hi, bias = int(info.off_lo[fe]), int(info.off_hi[fe]), int(info.bias[fe])
    v = colv.astype(np.int32)
    if lo == 0:  # singleton raw column
        return v
    in_range = (v >= lo) & (v < hi)
    return np.where(in_range, v - lo + bias, default_bin).astype(np.int32)
