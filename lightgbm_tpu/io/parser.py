"""Text data loading — counterpart of the reference's Parser
(src/io/parser.cpp) and the text-file half of DatasetLoader
(src/io/dataset_loader.cpp).

Format auto-detection mirrors Parser::CreateParser: sniff the first
non-empty lines; ':'-separated index:value tokens ⇒ LibSVM, otherwise the
delimiter (tab/comma/space) picks TSV/CSV.  Side files ``<data>.weight``
and ``<data>.query`` are picked up like Metadata::Init (metadata.cpp).

Parsing is delegated to the chunked readers in data/reader.py (native
multithreaded parser per block, pandas C engine fallback) — the SAME code
path the out-of-core streaming ingest uses, so single-shot and streaming
loads cannot drift.  This module keeps the column-role slicing
(label/weight/group/ignore) and side-file conventions.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from ..config import Config
from ..utils.log import Log


def sniff_format(path: str, max_lines: int = 32) -> Tuple[str, Optional[str]]:
    """Returns (kind, sep) where kind in {'libsvm','csv','tsv'}."""
    lines: List[str] = []
    with open(path, "r") as f:
        for line in f:
            line = line.strip()
            if line:
                lines.append(line)
            if len(lines) >= max_lines:
                break
    if not lines:
        Log.fatal("Data file %s is empty", path)
    colon_hits = 0
    for ln in lines:
        toks = ln.replace("\t", " ").split()
        # LibSVM: all tokens after the first look like idx:value
        if len(toks) > 1 and all(":" in t for t in toks[1:]):
            colon_hits += 1
    if colon_hits == len(lines):
        return "libsvm", None
    first = lines[0]
    if "\t" in first:
        return "tsv", "\t"
    if "," in first:
        return "csv", ","
    return "tsv", r"\s+"


def load_text_file(
    path: str, config: Config
) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray], Optional[np.ndarray], List[str], int]:
    """Load a training/validation text file.

    Returns (features, label, weights, group_sizes, feature_names, label_idx).
    ``features`` excludes the label/weight/group/ignored columns, matching how
    the reference's parsers emit (feature_idx, value) pairs with the label
    split out.
    """
    # one parsing code path with the streaming ingest (data/reader.py):
    # single-shot loads read through the SAME chunked readers (native
    # parser per block when a compiler is available, pandas C engine
    # otherwise), so dense and streaming loads cannot drift in dtype or
    # missing-value semantics.  Imported lazily — data/ sits above io/.
    from ..data.reader import DenseChunkReader, LibSVMChunkReader

    policy = getattr(config, "bad_row_policy", "error")
    kind, sep = sniff_format(path)
    if kind == "libsvm":
        raw, label = LibSVMChunkReader(path, bad_row_policy=policy).read_all()
        names = [f"Column_{i}" for i in range(raw.shape[1])]
        label_idx = 0
        weights, group = _side_files(path, raw.shape[0])
        return raw, label, weights, group, names, label_idx

    mat, names = DenseChunkReader(path, sep, config.has_header,
                                  bad_row_policy=policy).read_all()

    label_idx, _ = _resolve_column(config.label_column, names, default=0)
    weight_idx, weight_abs = _resolve_column(config.weight_column, names, default=-1)
    group_idx, group_abs = _resolve_column(config.group_column, names, default=-1)
    ignore = _resolve_columns(config.ignore_column, names)

    label = mat[:, label_idx].astype(np.float32)

    # Numeric column indices for weight/group/ignore in the reference do NOT
    # count the label column (config.h:119-133) and need a +1 shift past it;
    # name:-resolved indices are already header-absolute (per-spec tracking,
    # ADVICE r1 fix for the global weight_column short-circuit).
    def absolute(idx: int, is_name: bool) -> int:
        if idx < 0 or is_name:
            return idx
        return idx if idx < label_idx else idx + 1

    drop = {label_idx}
    weights = None
    if weight_idx >= 0:
        ai = absolute(weight_idx, weight_abs)
        weights = mat[:, ai].astype(np.float32)
        drop.add(ai)
    group = None
    if group_idx >= 0:
        ai = absolute(group_idx, group_abs)
        gid = mat[:, ai]
        # group column holds query ids; convert runs to sizes
        change = np.nonzero(np.diff(gid))[0] + 1
        bounds = np.concatenate([[0], change, [len(gid)]])
        group = np.diff(bounds).astype(np.int64)
        drop.add(ai)
    for ig, ig_abs in ignore:
        drop.add(absolute(ig, ig_abs))

    keep = [i for i in range(mat.shape[1]) if i not in drop]
    features = mat[:, keep]
    feat_names = (
        [names[i] for i in keep] if names else [f"Column_{i}" for i in range(len(keep))]
    )

    fweights, fgroup = _side_files(path, features.shape[0])
    if weights is None:
        weights = fweights
    if group is None:
        group = fgroup
    return features, label, weights, group, feat_names, label_idx


def _resolve_column(spec: str, names: Optional[List[str]], default: int) -> Tuple[int, bool]:
    """Returns (index, is_header_absolute).  name:-resolved indices are
    header-absolute; numeric specs are label-relative (config.h:119-133)."""
    if not spec:
        return default, False
    if spec.startswith("name:"):
        name = spec[5:]
        if not names:
            Log.fatal("Column name '%s' given but the file has no header", name)
        if name not in names:
            Log.fatal("Column '%s' not found in header", name)
        return names.index(name), True
    return int(spec), False


def _resolve_columns(spec: str, names: Optional[List[str]]) -> List[Tuple[int, bool]]:
    if not spec:
        return []
    if spec.startswith("name:"):
        assert names is not None
        return [(names.index(s), True) for s in spec[5:].split(",")]
    return [(int(s), False) for s in spec.split(",")]


def _side_files(path: str, num_data: int):
    """<data>.weight and <data>.query companions (metadata.cpp LoadWeights/
    LoadQueryBoundaries)."""
    weights = None
    group = None
    wpath = path + ".weight"
    if os.path.exists(wpath):
        weights = np.loadtxt(wpath, dtype=np.float32).ravel()
        if len(weights) != num_data:
            Log.fatal("Weight file length mismatch: %d vs %d", len(weights), num_data)
    qpath = path + ".query"
    if os.path.exists(qpath):
        group = np.loadtxt(qpath, dtype=np.int64).ravel()
        if int(group.sum()) != num_data:
            Log.fatal("Query file row total mismatch")
    return weights, group


def _load_libsvm(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """Whole-file LibSVM load through the chunked reader (the block
    parsers — native and python — live in data/reader.py now)."""
    from ..data.reader import LibSVMChunkReader

    return LibSVMChunkReader(path).read_all()
