"""scikit-learn API wrappers — counterpart of
python-package/lightgbm/sklearn.py (LGBMModel:123, LGBMRegressor:468,
LGBMClassifier:491, LGBMRanker:582), including the custom-objective
adapter (_objective_function_wrapper, sklearn.py:15-121).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from .basic import Booster, Dataset
from .engine import train
from .utils.log import Log


def _objective_function_wrapper(func: Callable):
    """Wrap sklearn-style fobj(y_true, y_pred[, group]) -> (grad, hess)
    into the engine's fobj(preds, dataset) (sklearn.py:15-80)."""

    def inner(preds, dataset):
        labels = dataset.get_label()
        argc = func.__code__.co_argcount
        if argc == 2:
            grad, hess = func(labels, preds)
        elif argc == 3:
            grad, hess = func(labels, preds, dataset.get_group())
        else:
            raise TypeError(f"Self-defined objective should have 2 or 3 arguments, got {argc}")
        return grad, hess

    return inner


def _eval_function_wrapper(func: Callable):
    """Wrap feval(y_true, y_pred[, weight[, group]]) ->
    (name, value, is_bigger_better) (sklearn.py:82-121)."""

    def inner(preds, dataset):
        labels = dataset.get_label()
        argc = func.__code__.co_argcount
        if argc == 2:
            return func(labels, preds)
        if argc == 3:
            return func(labels, preds, dataset.get_weight())
        if argc == 4:
            return func(labels, preds, dataset.get_weight(), dataset.get_group())
        raise TypeError(f"Self-defined eval function should have 2, 3 or 4 arguments, got {argc}")

    return inner


class LGBMModel:
    """Base sklearn-style estimator (sklearn.py:123-466)."""

    def __init__(
        self,
        boosting_type: str = "gbdt",
        num_leaves: int = 31,
        max_depth: int = -1,
        learning_rate: float = 0.1,
        n_estimators: int = 100,
        max_bin: int = 255,
        subsample_for_bin: int = 200000,
        objective: Optional[str] = None,
        min_split_gain: float = 0.0,
        min_child_weight: float = 1e-3,
        min_child_samples: int = 20,
        subsample: float = 1.0,
        subsample_freq: int = 0,
        colsample_bytree: float = 1.0,
        reg_alpha: float = 0.0,
        reg_lambda: float = 0.0,
        random_state: int = 0,
        n_jobs: int = -1,
        silent: bool = True,
        **kwargs,
    ):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.max_bin = max_bin
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.silent = silent
        self._other_params: Dict[str, Any] = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._evals_result: Optional[dict] = None
        self._best_iteration = -1
        self._classes = None
        self._n_classes = -1

    _default_objective = "regression"

    # -- sklearn plumbing ------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = {
            "boosting_type": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "n_estimators": self.n_estimators,
            "max_bin": self.max_bin,
            "subsample_for_bin": self.subsample_for_bin,
            "objective": self.objective,
            "min_split_gain": self.min_split_gain,
            "min_child_weight": self.min_child_weight,
            "min_child_samples": self.min_child_samples,
            "subsample": self.subsample,
            "subsample_freq": self.subsample_freq,
            "colsample_bytree": self.colsample_bytree,
            "reg_alpha": self.reg_alpha,
            "reg_lambda": self.reg_lambda,
            "random_state": self.random_state,
            "n_jobs": self.n_jobs,
            "silent": self.silent,
        }
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for key, value in params.items():
            if hasattr(self, key):
                setattr(self, key, value)
            else:
                self._other_params[key] = value
        return self

    def _booster_params(self, objective_override: Optional[str] = None):
        objective = objective_override if objective_override else self.objective
        fobj = None
        if callable(objective):
            fobj = _objective_function_wrapper(objective)
            objective = "none"
        elif objective is None:
            objective = self._default_objective
        params = {
            "boosting_type": self.boosting_type,
            "objective": objective,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "max_bin": self.max_bin,
            "bin_construct_sample_cnt": self.subsample_for_bin,
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "seed": self.random_state if self.random_state is not None else 0,
            "verbose": 0 if self.silent else 1,
        }
        params.update(self._other_params)
        return params, fobj

    # -- core fit --------------------------------------------------------
    def fit(
        self,
        X,
        y,
        sample_weight=None,
        init_score=None,
        group=None,
        eval_set=None,
        eval_names=None,
        eval_sample_weight=None,
        eval_init_score=None,
        eval_group=None,
        eval_metric=None,
        early_stopping_rounds=None,
        verbose=False,
        feature_name="auto",
        categorical_feature="auto",
        callbacks=None,
        _objective_override=None,
        _extra_params=None,
    ) -> "LGBMModel":
        params, fobj = self._booster_params(_objective_override)
        if _extra_params:
            params.update(_extra_params)
        feval = _eval_function_wrapper(eval_metric) if callable(eval_metric) else None
        if eval_metric is not None and not callable(eval_metric):
            params["metric"] = eval_metric

        train_ds = Dataset(
            X, label=y, weight=sample_weight, group=group, init_score=init_score,
            params=params, feature_name=feature_name,
            categorical_feature=categorical_feature,
        )
        valid_sets = []
        valid_names = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                if vx is X and vy is y:
                    valid_sets.append(train_ds)
                else:
                    vw = eval_sample_weight[i] if eval_sample_weight else None
                    vg = eval_group[i] if eval_group else None
                    vi = eval_init_score[i] if eval_init_score else None
                    valid_sets.append(
                        Dataset(vx, label=vy, weight=vw, group=vg, init_score=vi,
                                reference=train_ds, params=params)
                    )
                valid_names.append(
                    eval_names[i] if eval_names and i < len(eval_names) else f"valid_{i}"
                )
        self._evals_result = {}
        self._Booster = train(
            params,
            train_ds,
            num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None,
            valid_names=valid_names or None,
            fobj=fobj,
            feval=feval,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=self._evals_result,
            verbose_eval=verbose,
            callbacks=callbacks,
        )
        self._best_iteration = self._Booster.best_iteration
        return self

    def predict(self, X, raw_score: bool = False, num_iteration: int = -1):
        if self._Booster is None:
            Log.fatal("Estimator not fitted, call fit before predict")
        return self._Booster.predict(X, raw_score=raw_score, num_iteration=num_iteration)

    @property
    def booster_(self) -> Booster:
        return self._Booster

    @property
    def best_iteration_(self) -> int:
        return self._best_iteration

    @property
    def evals_result_(self) -> dict:
        return self._evals_result

    @property
    def feature_importances_(self) -> np.ndarray:
        return self._Booster.feature_importance()

    @property
    def n_features_(self) -> int:
        return self._Booster.boosting.max_feature_idx + 1


class LGBMRegressor(LGBMModel):
    _default_objective = "regression"


class LGBMClassifier(LGBMModel):
    _default_objective = "binary"

    def fit(self, X, y, **kwargs):
        y = np.asarray(y)
        self._classes, y_enc = np.unique(y, return_inverse=True)
        self._n_classes = len(self._classes)
        # fit-local overrides only — constructor params stay untouched so
        # refitting on different data / sklearn clone() behave correctly
        if self._n_classes > 2:
            override = None
            if self.objective is None or self.objective == "binary":
                override = "multiclass"
            super().fit(X, y_enc, _objective_override=override,
                        _extra_params={"num_class": self._n_classes}, **kwargs)
        else:
            super().fit(X, y_enc, **kwargs)
        return self

    def predict(self, X, raw_score: bool = False, num_iteration: int = -1):
        prob = self.predict_proba(X, raw_score=raw_score, num_iteration=num_iteration)
        if raw_score:
            return prob
        if prob.ndim == 1:
            idx = (prob > 0.5).astype(np.int64)
        else:
            idx = np.argmax(prob, axis=1)
        return self._classes[idx]

    def predict_proba(self, X, raw_score: bool = False, num_iteration: int = -1):
        out = self._Booster.predict(X, raw_score=raw_score, num_iteration=num_iteration)
        if not raw_score and out.ndim == 1:
            # binary: (N, 2) column convention (sklearn.py predict_proba)
            return np.vstack([1.0 - out, out]).T
        return out

    @property
    def classes_(self):
        return self._classes

    @property
    def n_classes_(self) -> int:
        return self._n_classes


class LGBMRanker(LGBMModel):
    _default_objective = "lambdarank"

    def fit(self, X, y, group=None, **kwargs):
        if group is None:
            Log.fatal("Should set group for ranking task")
        super().fit(X, y, group=group, **kwargs)
        return self
