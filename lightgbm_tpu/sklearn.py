"""Placeholder — implemented in a later milestone."""
class LGBMModel:
    pass


class LGBMRegressor:
    pass


class LGBMClassifier:
    pass


class LGBMRanker:
    pass
