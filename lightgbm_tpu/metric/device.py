"""Device-side metric evaluation.

The host metric path pulls the full (K, N) f64 score vector and sorts on
host per eval point (metric/binary.py AUC mergesort) — at Higgs-11M with
a valid set this rivals tree-build time and forces the fused trainer off
its fast path.  These jnp twins keep scores device-resident and transfer
ONE scalar per metric.  Counterpart of src/metric/binary_metric.hpp /
regression_metric.hpp / multiclass_metric.hpp evaluated on-accelerator.

Numerics: the REDUCTIONS (sums / cumsums) accumulate in float64 whenever
jax x64 is enabled, so the values that feed early-stopping comparisons
match the host f64 path; per-row math stays f32.  When x64 is
unavailable (the default TPU config) the f32 accumulation drifts to
~1e-4..1e-5 at Higgs scale, so the device path is GATED by size:
``eval_device`` refuses datasets above ``_DEV_F32_ROW_LIMIT`` rows and
the caller (gbdt._eval_metric) falls back to the host f64 path.  The
AUC tie handling is exact either way (the tie-grouped sweep below
mirrors binary_metric.hpp:193-259 group order).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_EPS = 1e-15

# above this, f32 accumulation error rivals real metric deltas between
# early-stopping rounds; without x64 the host path takes over
_DEV_F32_ROW_LIMIT = 1 << 22


def _acc():
    """Accumulation dtype for reductions: f64 when available.  Evaluated
    at trace time — flipping jax_enable_x64 mid-process would need a jit
    cache clear, which nothing in this codebase does."""
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


@jax.jit
def _binary_logloss_dev(prob, label, weights, sum_weights):
    lab_pos = label > 0
    p = jnp.where(lab_pos, prob, 1.0 - prob)
    pt = -jnp.log(jnp.maximum(p, _EPS))
    return jnp.sum(pt * weights, dtype=_acc()) / sum_weights


@jax.jit
def _binary_error_dev(prob, label, weights, sum_weights):
    err = jnp.where(prob <= 0.5, label > 0, label <= 0).astype(jnp.float32)
    return jnp.sum(err * weights, dtype=_acc()) / sum_weights


@jax.jit
def _auc_dev(score, label, weights, sum_weights):
    """Tie-grouped AUC (binary_metric.hpp:193-259) without host sorts.

    Per sorted-descending row i: its negatives pair with all positives of
    strictly-greater score plus half the positives of its own tie group.
    Group boundaries propagate via running-max scans instead of the host
    path's segment scatter."""
    acc = _acc()
    order = jnp.argsort(-score)
    s = score[order]
    lab = label[order]
    w = weights[order].astype(acc)
    pos = jnp.where(lab > 0, w, 0.0)
    neg = jnp.where(lab <= 0, w, 0.0)
    cum_pos = jnp.cumsum(pos, dtype=acc)
    cum_pos_excl = cum_pos - pos
    n = s.shape[0]
    new_thr = jnp.concatenate(
        [jnp.ones((1,), bool), s[1:] != s[:-1]]
    )
    is_end = jnp.concatenate([s[1:] != s[:-1], jnp.ones((1,), bool)])
    # positives before this row's tie group: the group-start exclusive
    # cumsum, forward-propagated to every member (running max works
    # because cum_pos_excl is nondecreasing)
    start = jax.lax.cummax(jnp.where(new_thr, cum_pos_excl, -1.0))
    # positives through the group end, propagated backward to members:
    # cum_pos is nondecreasing, so the FIRST end at-or-after each row
    # (this group's end) is the reversed running MIN over end sentinels
    endv = jax.lax.cummin(
        jnp.where(is_end, cum_pos, acc(jnp.inf)), reverse=True
    )
    pos_g = endv - start
    accum = jnp.sum(neg * (start + 0.5 * pos_g), dtype=acc)
    sum_pos = cum_pos[n - 1]
    denom = sum_pos * (sum_weights - sum_pos)
    return jnp.where(denom > 0.0, accum / denom, 1.0)


@jax.jit
def _l2_dev(score, label, weights, sum_weights):
    d = score - label
    return jnp.sum(d * d * weights, dtype=_acc()) / sum_weights


@jax.jit
def _l1_dev(score, label, weights, sum_weights):
    return jnp.sum(jnp.abs(score - label) * weights, dtype=_acc()) / sum_weights


@jax.jit
def _multi_logloss_dev(prob, label, weights, sum_weights):
    """prob (K, N) softmax outputs; label (N,) class ids."""
    k = prob.shape[0]
    lab = jnp.clip(label.astype(jnp.int32), 0, k - 1)
    p = jnp.take_along_axis(prob, lab[None, :], axis=0)[0]
    pt = -jnp.log(jnp.maximum(p, _EPS))
    return jnp.sum(pt * weights, dtype=_acc()) / sum_weights


@jax.jit
def _multi_error_dev(prob, label, weights, sum_weights):
    """Ties on the true class count as errors (>= sweep excluding the true
    class itself — multiclass_metric.hpp:136-144; the host twin's ge
    semantics, NOT argmax)."""
    k = prob.shape[0]
    lab = jnp.clip(label.astype(jnp.int32), 0, k - 1)
    true_score = jnp.take_along_axis(prob, lab[None, :], axis=0)  # (1, N)
    n_ge = jnp.sum((prob >= true_score).astype(jnp.int32), axis=0)
    err = (n_ge > 1).astype(jnp.float32)  # the true class always counts once
    return jnp.sum(err * weights, dtype=_acc()) / sum_weights


class DeviceEval:
    """Mixin: device-resident twin of Metric.eval.

    ``eval_device(score, objective)`` takes a DEVICE (N,)/(K, N) score
    array and returns the same [(name, value)] contract with one scalar
    transfer.  Metrics opt in by setting ``_dev_fn`` and (optionally)
    ``_dev_needs_prob``."""

    _dev_fn = None
    _dev_needs_prob = False

    def _dev_cached(self):
        if not hasattr(self, "_dev_label"):
            self._dev_label = jnp.asarray(self.label, jnp.float32)
            if self.weights is not None:
                self._dev_weights = jnp.asarray(self.weights, jnp.float32)
            else:
                self._dev_weights = jnp.ones((self.num_data,), jnp.float32)
            self._dev_sum_w = jnp.asarray(self.sum_weights, _acc())
        return self._dev_label, self._dev_weights, self._dev_sum_w

    def eval_device(self, score, objective=None):
        fn = type(self)._dev_fn
        if fn is None:
            raise NotImplementedError
        if not jax.config.jax_enable_x64 and self.num_data > _DEV_F32_ROW_LIMIT:
            # f32 accumulation drifts past early-stopping deltas at this
            # scale; the caller falls back to the host f64 path
            raise NotImplementedError(
                f"device metric gated: {self.num_data} rows > "
                f"{_DEV_F32_ROW_LIMIT} without x64"
            )
        label, w, sw = self._dev_cached()
        s = jnp.asarray(score, jnp.float32)
        if self._dev_needs_prob and objective is not None:
            s = objective.convert_output(s)
        return [(self.name, float(fn(s, label, w, sw)))]
