"""Ranking metrics NDCG@k and MAP@k — parity with
src/metric/rank_metric.hpp:16 / map_metric.hpp:16 and DCGCalculator
(src/metric/dcg_calculator.cpp).
"""

from __future__ import annotations

import numpy as np

from ..objective.rank import dcg_discounts, default_label_gain
from .base import Metric


class NDCGMetric(Metric):
    name = "ndcg"
    bigger_is_better = True

    def __init__(self, config):
        self.eval_at = [int(k) for k in (config.ndcg_eval_at or [1, 2, 3, 4, 5])]
        lg = config.label_gain
        self.label_gain = np.asarray(lg, np.float64) if lg else default_label_gain()

    def init(self, metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            from ..utils.log import Log

            Log.fatal("For NDCG metric, there should be query information")
        self.qb = np.asarray(metadata.query_boundaries, np.int64)
        self.num_queries = len(self.qb) - 1
        self.query_weights = metadata.query_weights
        self.sum_query_weights = (
            float(np.sum(self.query_weights))
            if self.query_weights is not None
            else float(self.num_queries)
        )
        # per-query ideal DCG at each k (CalMaxDCG, dcg_calculator.cpp:53-84)
        self.inv_max_dcg = np.zeros((self.num_queries, len(self.eval_at)))
        for i in range(self.num_queries):
            lab = self.label[self.qb[i]: self.qb[i + 1]]
            gains = np.sort(self.label_gain[lab.astype(np.int64)])[::-1]
            disc = dcg_discounts(len(lab))
            cum = np.cumsum(gains * disc)
            for j, k in enumerate(self.eval_at):
                kk = min(k, len(lab))
                m = cum[kk - 1] if kk > 0 else 0.0
                self.inv_max_dcg[i, j] = 1.0 / m if m > 0.0 else -1.0

    def eval(self, score, objective=None):
        score = np.asarray(score, np.float64)
        sums = np.zeros(len(self.eval_at))
        for i in range(self.num_queries):
            lab = self.label[self.qb[i]: self.qb[i + 1]]
            sc = score[self.qb[i]: self.qb[i + 1]]
            qw = float(self.query_weights[i]) if self.query_weights is not None else 1.0
            if self.inv_max_dcg[i, 0] <= 0.0:
                # all-negative query counts as NDCG=1 (rank_metric.hpp:95-99)
                sums += qw
                continue
            order = np.argsort(-sc, kind="mergesort")
            gains = self.label_gain[lab[order].astype(np.int64)]
            disc = dcg_discounts(len(lab))
            cum = np.cumsum(gains * disc)
            for j, k in enumerate(self.eval_at):
                kk = min(k, len(lab))
                dcg = cum[kk - 1] if kk > 0 else 0.0
                sums[j] += qw * dcg * self.inv_max_dcg[i, j]
        return [
            (f"ndcg@{k}", float(sums[j] / self.sum_query_weights))
            for j, k in enumerate(self.eval_at)
        ]


class MapMetric(Metric):
    name = "map"
    bigger_is_better = True

    def __init__(self, config):
        self.eval_at = [int(k) for k in (config.ndcg_eval_at or [1, 2, 3, 4, 5])]

    def init(self, metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            from ..utils.log import Log

            Log.fatal("For MAP metric, there should be query information")
        self.qb = np.asarray(metadata.query_boundaries, np.int64)
        self.num_queries = len(self.qb) - 1
        self.query_weights = metadata.query_weights
        self.sum_query_weights = (
            float(np.sum(self.query_weights))
            if self.query_weights is not None
            else float(self.num_queries)
        )

    def eval(self, score, objective=None):
        """CalMapAtK (map_metric.hpp:69-95) per query, averaged."""
        score = np.asarray(score, np.float64)
        sums = np.zeros(len(self.eval_at))
        for i in range(self.num_queries):
            lab = self.label[self.qb[i]: self.qb[i + 1]]
            sc = score[self.qb[i]: self.qb[i + 1]]
            qw = float(self.query_weights[i]) if self.query_weights is not None else 1.0
            order = np.argsort(-sc, kind="mergesort")
            hits = lab[order] > 0.5
            num_hit = 0
            sum_ap = 0.0
            cur_left = 0
            for j, k in enumerate(self.eval_at):
                kk = min(k, len(lab))
                for pos in range(cur_left, kk):
                    if hits[pos]:
                        num_hit += 1
                        # reference quirk (map_metric.hpp:88): divides by the
                        # eval_at slot index + 1, not the rank position
                        sum_ap += num_hit / (j + 1.0)
                sums[j] += qw * (sum_ap / kk if kk > 0 else 0.0)
                cur_left = kk
        return [
            (f"map@{k}", float(sums[j] / self.sum_query_weights))
            for j, k in enumerate(self.eval_at)
        ]
