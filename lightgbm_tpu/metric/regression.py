"""Regression metrics — parity with src/metric/regression_metric.hpp
(RMSE:115, L2:134, L1:153, Huber:166, Fair:188, Poisson:205).
"""

from __future__ import annotations

import numpy as np

from .base import Metric, convert_scores
from .device import DeviceEval, _l1_dev, _l2_dev

_EPS = 1e-15


class _RegressionMetric(Metric):
    bigger_is_better = False

    def __init__(self, config):
        self.huber_delta = float(config.huber_delta)
        self.fair_c = float(config.fair_c)

    def loss(self, label, score):
        raise NotImplementedError

    def average(self, sum_loss, sum_weights):
        return sum_loss / sum_weights

    def eval(self, score, objective=None):
        score = convert_scores(np.asarray(score, np.float64), objective)
        pt = self.loss(self.label, score)
        if self.weights is not None:
            pt = pt * self.weights
        return [(self.name, float(self.average(float(np.sum(pt)), self.sum_weights)))]


class L2Metric(DeviceEval, _RegressionMetric):
    name = "l2"
    _dev_fn = staticmethod(_l2_dev)

    def loss(self, label, score):
        d = score - label
        return d * d


class RMSEMetric(L2Metric):
    name = "rmse"

    def average(self, sum_loss, sum_weights):
        return np.sqrt(sum_loss / sum_weights)

    def eval_device(self, score, objective=None):
        [(name, val)] = super().eval_device(score, objective)
        return [(self.name, float(np.sqrt(val)))]


class L1Metric(DeviceEval, _RegressionMetric):
    name = "l1"
    _dev_fn = staticmethod(_l1_dev)

    def loss(self, label, score):
        return np.abs(score - label)


class HuberMetric(_RegressionMetric):
    """0.5*d^2 inside delta, delta*(|d| - 0.5*delta) outside
    (regression_metric.hpp:166-185)."""

    name = "huber"

    def loss(self, label, score):
        d = score - label
        ad = np.abs(d)
        return np.where(
            ad <= self.huber_delta,
            0.5 * d * d,
            self.huber_delta * (ad - 0.5 * self.huber_delta),
        )


class FairMetric(_RegressionMetric):
    """c^2 * (|d|/c - log(1 + |d|/c)) (regression_metric.hpp:188-202)."""

    name = "fair"

    def loss(self, label, score):
        x = np.abs(score - label)
        c = self.fair_c
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_RegressionMetric):
    """score - label*log(score) with eps floor
    (regression_metric.hpp:205-226)."""

    name = "poisson"

    def loss(self, label, score):
        eps = 1e-10
        s = np.where(score < eps, eps, score)
        return s - label * np.log(s)
