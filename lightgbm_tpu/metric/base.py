"""Abstract metric interface (include/LightGBM/metric.h)."""

from __future__ import annotations

from typing import List, Tuple

import numpy as np


class Metric:
    """``eval(score, objective)`` returns [(name, value), ...]; score is a
    host float64 array — (N,) or (K, N) for multiclass."""

    name = "none"
    bigger_is_better = False  # factor_to_bigger_better sign

    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = np.asarray(metadata.label, np.float64)
        self.weights = (
            np.asarray(metadata.weights, np.float64)
            if metadata.weights is not None
            else None
        )
        self.sum_weights = (
            float(np.sum(self.weights)) if self.weights is not None else float(num_data)
        )

    def eval(self, score: np.ndarray, objective=None) -> List[Tuple[str, float]]:
        raise NotImplementedError


def convert_scores(score: np.ndarray, objective) -> np.ndarray:
    """Apply the objective's ConvertOutput host-side (sigmoid/softmax)."""
    if objective is None:
        return score
    import numpy as _np

    return _np.asarray(objective.convert_output(score), _np.float64)
