"""Metrics — counterpart of src/metric/ (factory metric.cpp:10-41).

Metrics run host-side in float64 numpy: they execute once per
``metric_freq`` iterations on scores pulled from device, exactly where the
reference runs its OpenMP loops, and double accumulation preserves parity
with the reference's `double sum_loss` reductions.
"""

from .regression import (
    L1Metric,
    L2Metric,
    RMSEMetric,
    HuberMetric,
    FairMetric,
    PoissonMetric,
)
from .binary import BinaryLoglossMetric, BinaryErrorMetric, AUCMetric
from .multiclass import MultiErrorMetric, MultiLoglossMetric
from .rank import NDCGMetric, MapMetric

_FACTORY = {
    "l1": L1Metric,
    "mean_absolute_error": L1Metric,
    "mae": L1Metric,
    "regression_l1": L1Metric,
    "l2": L2Metric,
    "mean_squared_error": L2Metric,
    "mse": L2Metric,
    "regression": L2Metric,
    "regression_l2": L2Metric,
    "rmse": RMSEMetric,
    "root_mean_squared_error": RMSEMetric,
    "l2_root": RMSEMetric,
    "huber": HuberMetric,
    "fair": FairMetric,
    "poisson": PoissonMetric,
    "binary_logloss": BinaryLoglossMetric,
    "binary": BinaryLoglossMetric,
    "binary_error": BinaryErrorMetric,
    "auc": AUCMetric,
    "multi_logloss": MultiLoglossMetric,
    "multiclass": MultiLoglossMetric,
    "softmax": MultiLoglossMetric,
    "multiclassova": MultiLoglossMetric,
    "multiclass_ova": MultiLoglossMetric,
    "ova": MultiLoglossMetric,
    "ovr": MultiLoglossMetric,
    "multi_error": MultiErrorMetric,
    "ndcg": NDCGMetric,
    "lambdarank": NDCGMetric,
    "map": MapMetric,
    "mean_average_precision": MapMetric,
}


def create_metric(name: str, config):
    """Metric::CreateMetric (src/metric/metric.cpp:10-41); returns None for
    unknown names like the reference (caller warns)."""
    cls = _FACTORY.get(name.lower())
    return cls(config) if cls is not None else None


def metric_names_for_objective(objective_name: str):
    """Default metric when none specified — the reference maps the
    objective name through the same factory (config.cpp metric defaulting)."""
    return [objective_name]


__all__ = [
    "create_metric",
    "metric_names_for_objective",
    "L1Metric",
    "L2Metric",
    "RMSEMetric",
    "HuberMetric",
    "FairMetric",
    "PoissonMetric",
    "BinaryLoglossMetric",
    "BinaryErrorMetric",
    "AUCMetric",
    "MultiLoglossMetric",
    "MultiErrorMetric",
    "NDCGMetric",
    "MapMetric",
]
