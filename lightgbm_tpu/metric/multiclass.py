"""Multiclass metrics — parity with src/metric/multiclass_metric.hpp
(error:132, logloss:152).  Score layout (K, N).
"""

from __future__ import annotations

import numpy as np

from .base import Metric, convert_scores
from .device import DeviceEval, _multi_error_dev, _multi_logloss_dev

_EPS = 1e-15


class _MulticlassMetric(Metric):
    bigger_is_better = False

    def __init__(self, config):
        self.num_class = int(config.num_class)

    def eval(self, score, objective=None):
        score = np.asarray(score, np.float64)
        if score.ndim == 1:
            score = score.reshape(self.num_class, -1)
        prob = convert_scores(score, objective)
        pt = self.loss(self.label, prob)
        if self.weights is not None:
            pt = pt * self.weights
        return [(self.name, float(np.sum(pt) / self.sum_weights))]


class MultiErrorMetric(DeviceEval, _MulticlassMetric):
    """1 when any other class's score >= the true class's
    (multiclass_metric.hpp:136-144)."""

    name = "multi_error"
    _dev_fn = staticmethod(_multi_error_dev)
    _dev_needs_prob = True

    def loss(self, label, prob):
        k = label.astype(np.int64)
        n = prob.shape[1]
        true_score = prob[k, np.arange(n)]
        # ties on the true class count as errors (>=, excluding itself)
        ge = prob >= true_score[None, :]
        ge[k, np.arange(n)] = False
        return np.any(ge, axis=0).astype(np.float64)


class MultiLoglossMetric(DeviceEval, _MulticlassMetric):
    name = "multi_logloss"
    _dev_fn = staticmethod(_multi_logloss_dev)
    _dev_needs_prob = True

    def loss(self, label, prob):
        k = label.astype(np.int64)
        p = prob[k, np.arange(prob.shape[1])]
        return -np.log(np.maximum(p, _EPS))
