"""Binary metrics + AUC — parity with src/metric/binary_metric.hpp
(logloss:113, error:137, AUC:157-262).
"""

from __future__ import annotations

import numpy as np

from .base import Metric, convert_scores
from .device import (
    DeviceEval,
    _auc_dev,
    _binary_error_dev,
    _binary_logloss_dev,
)

_EPS = 1e-15


class BinaryLoglossMetric(DeviceEval, Metric):
    name = "binary_logloss"
    bigger_is_better = False
    _dev_fn = staticmethod(_binary_logloss_dev)
    _dev_needs_prob = True

    def __init__(self, config):
        pass

    def eval(self, score, objective=None):
        prob = convert_scores(np.asarray(score, np.float64), objective)
        lab_pos = self.label > 0
        p = np.where(lab_pos, prob, 1.0 - prob)
        pt = -np.log(np.maximum(p, _EPS))
        if self.weights is not None:
            pt = pt * self.weights
        return [(self.name, float(np.sum(pt) / self.sum_weights))]


class BinaryErrorMetric(DeviceEval, Metric):
    name = "binary_error"
    bigger_is_better = False
    _dev_fn = staticmethod(_binary_error_dev)
    _dev_needs_prob = True

    def __init__(self, config):
        pass

    def eval(self, score, objective=None):
        prob = convert_scores(np.asarray(score, np.float64), objective)
        # LossOnPoint (binary_metric.hpp:141-147): prob<=0.5 counts as
        # predicting negative
        err = np.where(prob <= 0.5, self.label > 0, self.label <= 0).astype(np.float64)
        if self.weights is not None:
            err = err * self.weights
        return [(self.name, float(np.sum(err) / self.sum_weights))]


class AUCMetric(DeviceEval, Metric):
    """Threshold-sweep AUC with tie grouping (binary_metric.hpp:193-259);
    raw scores — no sigmoid needed (monotone)."""

    name = "auc"
    bigger_is_better = True
    _dev_fn = staticmethod(_auc_dev)

    def __init__(self, config):
        pass

    def eval(self, score, objective=None):
        score = np.asarray(score, np.float64)
        order = np.argsort(-score, kind="mergesort")
        s = score[order]
        lab = self.label[order]
        w = self.weights[order] if self.weights is not None else np.ones_like(lab)
        pos = (lab > 0) * w
        neg = (lab <= 0) * w
        # group ties: segment boundaries where the score changes
        new_thr = np.empty(len(s), dtype=bool)
        if len(s):
            new_thr[0] = True
            new_thr[1:] = s[1:] != s[:-1]
        seg = np.cumsum(new_thr) - 1  # tie-group id per row
        nseg = seg[-1] + 1 if len(s) else 0
        pos_per = np.zeros(nseg)
        neg_per = np.zeros(nseg)
        np.add.at(pos_per, seg, pos)
        np.add.at(neg_per, seg, neg)
        # accum += cur_neg * (cur_pos*0.5 + sum_pos_before)
        sum_pos_before = np.concatenate([[0.0], np.cumsum(pos_per)[:-1]])
        accum = float(np.sum(neg_per * (pos_per * 0.5 + sum_pos_before)))
        sum_pos = float(np.sum(pos_per))
        auc = 1.0
        if sum_pos > 0.0 and sum_pos != self.sum_weights:
            auc = accum / (sum_pos * (self.sum_weights - sum_pos))
        return [(self.name, auc)]
