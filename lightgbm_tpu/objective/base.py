"""Abstract objective interface (include/LightGBM/objective_function.h)."""

from __future__ import annotations

import jax.numpy as jnp


class ObjectiveFunction:
    """Mirrors the reference's ObjectiveFunction virtuals.

    ``get_gradients(score) -> (grad, hess)`` is a pure jnp function: score
    is ``(N,)`` (or ``(K, N)`` for multiclass), outputs match its shape.
    It is safe to close over in a jitted training step.
    """

    name = "none"
    # True when gradients depend only on the row's own (score, label,
    # weight) — the property the partitioned trainer needs to compute
    # gradients in permuted row space (boosting/ptrainer.py).  Ranking
    # objectives (query-grouped pairs) must leave this False.
    rowwise = False

    def init(self, metadata, num_data: int) -> None:
        """Bind label/weight device arrays (ObjectiveFunction::Init)."""
        import numpy as np

        self.num_data = num_data
        self.label = jnp.asarray(np.asarray(metadata.label, np.float32))
        self.weights = (
            jnp.asarray(np.asarray(metadata.weights, np.float32))
            if metadata.weights is not None
            else None
        )

    def get_gradients(self, score):
        raise NotImplementedError

    def gradients_rowwise(self, score, label, weight):
        """get_gradients with explicit label/weight arrays in ARBITRARY
        row order (the partitioned trainer's channels).  The default
        rebinds the bound attributes around get_gradients — valid for
        any ``rowwise`` objective whose math reads only self.label /
        self.weights elementwise."""
        if not self.rowwise:
            raise NotImplementedError(f"{self.name} is not a row-local objective")
        old = (getattr(self, "label", None), getattr(self, "weights", None))
        try:
            self.label = label
            self.weights = weight
            return self.get_gradients(score)
        finally:
            self.label, self.weights = old

    def convert_output(self, score):
        """Raw score -> prediction space (ConvertOutput); identity default."""
        return score

    @property
    def num_tree_per_iteration(self) -> int:
        return 1

    @property
    def num_predict_one_row(self) -> int:
        return 1

    @property
    def is_constant_hessian(self) -> bool:
        return False

    @property
    def boost_from_average(self) -> bool:
        return False

    def to_string(self) -> str:
        """Objective line of the model file (ToString)."""
        return self.name

    def _apply_weights(self, grad, hess):
        if self.weights is not None:
            return grad * self.weights, hess * self.weights
        return grad, hess
