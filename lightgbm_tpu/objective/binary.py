"""Binary logloss objective — parity with
src/objective/binary_objective.hpp:13-154.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..utils.log import Log
from .base import ObjectiveFunction


class BinaryLogloss(ObjectiveFunction):
    name = "binary"
    rowwise = True

    def __init__(self, config, is_pos=None):
        self.is_unbalance = bool(config.is_unbalance)
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0.0:
            Log.fatal("Sigmoid parameter %f should be greater than zero", self.sigmoid)
        self.scale_pos_weight = float(config.scale_pos_weight)
        self._is_pos = is_pos if is_pos is not None else (lambda lab: lab > 0)

    def init(self, metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        lab = np.asarray(metadata.label, np.float32)
        pos_mask = self._is_pos(lab)
        cnt_positive = int(np.sum(pos_mask))
        cnt_negative = num_data - cnt_positive
        if cnt_positive == 0 or cnt_negative == 0:
            Log.warning("Only contain one class.")
            self.num_data = 0  # "not need to boost" (hpp:61-64)
        Log.info("Number of positive: %d, number of negative: %d", cnt_positive, cnt_negative)
        # +-1 label values and per-class weights (hpp:67-84)
        weight_pos, weight_neg = 1.0, 1.0
        if self.is_unbalance and cnt_positive > 0 and cnt_negative > 0:
            if cnt_positive > cnt_negative:
                weight_neg = cnt_positive / cnt_negative
            else:
                weight_pos = cnt_negative / cnt_positive
        weight_pos *= self.scale_pos_weight
        self._weight_pos = float(weight_pos)
        self._weight_neg = float(weight_neg)
        self.sign = jnp.asarray(np.where(pos_mask, 1.0, -1.0).astype(np.float32))
        self.label_weight = jnp.asarray(
            np.where(pos_mask, weight_pos, weight_neg).astype(np.float32)
        )

    def get_gradients(self, score):
        # response = -y*sig / (1 + exp(y*sig*score)) (hpp:95-99)
        response = -self.sign * self.sigmoid / (1.0 + jnp.exp(self.sign * self.sigmoid * score))
        abs_response = jnp.abs(response)
        grad = response * self.label_weight
        hess = abs_response * (self.sigmoid - abs_response) * self.label_weight
        return self._apply_weights(grad, hess)

    def gradients_rowwise(self, score, label, weight):
        """Row-local variant for the partitioned trainer: sign and class
        weight recomputed from the label channel (same math as
        get_gradients; the class-balance scalars come from init)."""
        pos = self._is_pos(label)
        sign = jnp.where(pos, 1.0, -1.0)
        lw = jnp.where(pos, self._weight_pos, self._weight_neg)
        response = -sign * self.sigmoid / (1.0 + jnp.exp(sign * self.sigmoid * score))
        abs_response = jnp.abs(response)
        grad = response * lw
        hess = abs_response * (self.sigmoid - abs_response) * lw
        if weight is not None:
            grad = grad * weight
            hess = hess * weight
        return grad, hess

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * score))

    def to_string(self) -> str:
        return f"{self.name} sigmoid:{self.sigmoid:g}"
