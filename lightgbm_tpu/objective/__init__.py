"""Objective functions — counterpart of src/objective/ (factory at
objective_function.cpp:9-56).

TPU-first design: ``GetGradients`` is a pure jnp function evaluated on
device inside the boosting step; labels/weights live in HBM as jnp arrays.
The reference's OpenMP elementwise loops become vectorized expressions;
lambdarank's per-query pairwise loop becomes a vmapped padded-matrix
computation (ops in rank.py).
"""

from .base import ObjectiveFunction
from .regression import (
    RegressionL2Loss,
    RegressionL1Loss,
    RegressionHuberLoss,
    RegressionFairLoss,
    RegressionPoissonLoss,
)
from .binary import BinaryLogloss
from .multiclass import MulticlassSoftmax, MulticlassOVA
from .rank import LambdarankNDCG

_FACTORY = {
    "regression": RegressionL2Loss,
    "regression_l2": RegressionL2Loss,
    "mean_squared_error": RegressionL2Loss,
    "mse": RegressionL2Loss,
    "l2": RegressionL2Loss,
    "regression_l1": RegressionL1Loss,
    "mean_absolute_error": RegressionL1Loss,
    "mae": RegressionL1Loss,
    "l1": RegressionL1Loss,
    "huber": RegressionHuberLoss,
    "fair": RegressionFairLoss,
    "poisson": RegressionPoissonLoss,
    "binary": BinaryLogloss,
    "lambdarank": LambdarankNDCG,
    "multiclass": MulticlassSoftmax,
    "softmax": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "multiclass_ova": MulticlassOVA,
    "ova": MulticlassOVA,
    "ovr": MulticlassOVA,
}


def create_objective(config) -> ObjectiveFunction:
    """ObjectiveFunction::CreateObjectiveFunction
    (src/objective/objective_function.cpp:9-56)."""
    from ..utils.log import Log

    name = config.objective.lower()
    if name in ("none", "null", "custom", ""):
        return None
    if name not in _FACTORY:
        Log.fatal("Unknown objective type name: %s", name)
    return _FACTORY[name](config)


def objective_from_string(obj_str: str):
    """Rebuild an objective from its model-file line — ``name key:value
    ...`` tokens, the inverse of ``ObjectiveFunction.to_string()``
    (used when loading model text and packed serving artifacts)."""
    if not obj_str:
        return None
    from ..config import Config

    toks = obj_str.split()
    params = {"objective": toks[0]}
    for t in toks[1:]:
        if ":" in t:
            k, _, v = t.partition(":")
            params[k] = v
    return create_objective(Config.from_params(params))


__all__ = [
    "ObjectiveFunction",
    "create_objective",
    "RegressionL2Loss",
    "RegressionL1Loss",
    "RegressionHuberLoss",
    "RegressionFairLoss",
    "RegressionPoissonLoss",
    "BinaryLogloss",
    "MulticlassSoftmax",
    "MulticlassOVA",
    "LambdarankNDCG",
]
