"""Regression objectives — parity with
src/objective/regression_objective.hpp (L2:11-77, L1:78-145,
Huber:147-232, Fair:236-295, Poisson:298-357) as jnp elementwise math.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from .base import ObjectiveFunction


def _gaussian_hessian(score, label, grad, eta, w=1.0):
    """Common::ApproximateHessianWithGaussian (utils/common.h:486-496)."""
    diff = score - label
    x = jnp.abs(diff)
    a = 2.0 * jnp.abs(grad) * w
    c = jnp.maximum((jnp.abs(score) + jnp.abs(label)) * eta, 1.0e-10)
    return w * jnp.exp(-x * x / (2.0 * c * c)) * a / (c * math.sqrt(2.0 * math.pi))


class RegressionL2Loss(ObjectiveFunction):
    """grad = score - label, hess = 1 (regression_objective.hpp:29-44)."""

    name = "regression"
    rowwise = True

    def __init__(self, config):
        pass

    def get_gradients(self, score):
        grad = score - self.label
        hess = jnp.ones_like(score)
        return self._apply_weights(grad, hess)

    @property
    def is_constant_hessian(self) -> bool:
        return self.weights is None

    @property
    def boost_from_average(self) -> bool:
        return True


class RegressionL1Loss(ObjectiveFunction):
    """grad = sign(diff), hess = Gaussian approximation scaled by
    gaussian_eta (regression_objective.hpp:96-118)."""

    name = "regression_l1"
    rowwise = True

    def __init__(self, config):
        self.eta = float(config.gaussian_eta)

    def get_gradients(self, score):
        diff = score - self.label
        w = self.weights if self.weights is not None else 1.0
        grad = jnp.where(diff >= 0.0, 1.0, -1.0) * w
        hess = _gaussian_hessian(score, self.label, grad, self.eta, w)
        return grad, hess

    @property
    def boost_from_average(self) -> bool:
        return True


class RegressionHuberLoss(ObjectiveFunction):
    """Quadratic inside huber_delta, linear outside with Gaussian hessian
    (regression_objective.hpp:169-206)."""

    name = "huber"
    rowwise = True

    def __init__(self, config):
        self.delta = float(config.huber_delta)
        self.eta = float(config.gaussian_eta)

    def get_gradients(self, score):
        diff = score - self.label
        w = self.weights if self.weights is not None else 1.0
        inside = jnp.abs(diff) <= self.delta
        grad_out = jnp.where(diff >= 0.0, self.delta, -self.delta) * w
        hess_out = _gaussian_hessian(score, self.label, grad_out, self.eta, w)
        grad = jnp.where(inside, diff * w, grad_out)
        hess = jnp.where(inside, jnp.ones_like(score) * w, hess_out)
        return grad, hess

    @property
    def boost_from_average(self) -> bool:
        return True


class RegressionFairLoss(ObjectiveFunction):
    """grad = c*x/(|x|+c), hess = c^2/(|x|+c)^2
    (regression_objective.hpp:254-272)."""

    name = "fair"
    rowwise = True

    def __init__(self, config):
        self.c = float(config.fair_c)

    def get_gradients(self, score):
        x = score - self.label
        ax_c = jnp.abs(x) + self.c
        grad = self.c * x / ax_c
        hess = self.c * self.c / (ax_c * ax_c)
        return self._apply_weights(grad, hess)

    @property
    def boost_from_average(self) -> bool:
        return True


class RegressionPoissonLoss(ObjectiveFunction):
    """grad = score - label, hess = score + poisson_max_delta_step —
    the reference's raw-score-space Poisson
    (regression_objective.hpp:319-337)."""

    name = "poisson"
    rowwise = True

    def __init__(self, config):
        self.max_delta_step = float(config.poisson_max_delta_step)

    def get_gradients(self, score):
        grad = score - self.label
        hess = score + self.max_delta_step
        return self._apply_weights(grad, hess)

    @property
    def boost_from_average(self) -> bool:
        return True
