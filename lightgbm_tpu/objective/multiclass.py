"""Multiclass objectives — parity with
src/objective/multiclass_objective.hpp (softmax:16-136, OVA:139-225).

Score layout is ``(K, N)`` — the reference's flat ``num_data*k + i``
indexing reshaped; the softmax runs across the class axis on device.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..utils.log import Log
from .base import ObjectiveFunction
from .binary import BinaryLogloss


class MulticlassSoftmax(ObjectiveFunction):
    name = "multiclass"
    # per-class row-local gradients (gradients_rowwise_class): the fused
    # partitioned trainer can drive K trees/iteration from the packed
    # matrix's K score channels (GBDT per-class loop, gbdt.cpp:445-480)
    rowwise_multi = True

    def __init__(self, config):
        self.num_class = int(config.num_class)

    def init(self, metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        lab = np.asarray(metadata.label, np.int32)
        if lab.size and (lab.min() < 0 or lab.max() >= self.num_class):
            Log.fatal(
                "Label must be in [0, %d), but found %d in label",
                self.num_class,
                int(lab.min() if lab.min() < 0 else lab.max()),
            )
        self.onehot = jnp.asarray(
            (lab[None, :] == np.arange(self.num_class, dtype=np.int32)[:, None]).astype(
                np.float32
            )
        )  # (K, N)

    def get_gradients(self, score):
        # (K, N): softmax over classes; grad = p - 1[y=k]; hess = 2p(1-p)
        p = jnp.exp(score - jnp.max(score, axis=0, keepdims=True))
        p = p / jnp.sum(p, axis=0, keepdims=True)
        grad = p - self.onehot
        hess = 2.0 * p * (1.0 - p)
        if self.weights is not None:
            grad = grad * self.weights[None, :]
            hess = hess * self.weights[None, :]
        return grad, hess

    def gradients_rowwise_all(self, scores, label, weight):
        """All K gradient planes from the score rows in ARBITRARY row
        order (the partitioned trainer's channels): scores (K, n), label
        the raw class index; returns ((K, n), (K, n))."""
        p = jnp.exp(scores - jnp.max(scores, axis=0, keepdims=True))
        p = p / jnp.sum(p, axis=0, keepdims=True)
        classes = jnp.arange(self.num_class, dtype=jnp.float32)
        onehot = (label.reshape(1, -1) == classes[:, None]).astype(jnp.float32)
        onehot = onehot.reshape(p.shape)
        grad = p - onehot
        hess = 2.0 * p * (1.0 - p)
        if weight is not None:
            grad = grad * weight
            hess = hess * weight
        return grad, hess

    def convert_output(self, score):
        p = jnp.exp(score - jnp.max(score, axis=0, keepdims=True))
        return p / jnp.sum(p, axis=0, keepdims=True)

    @property
    def num_tree_per_iteration(self) -> int:
        return self.num_class

    @property
    def num_predict_one_row(self) -> int:
        return self.num_class

    def to_string(self) -> str:
        return f"{self.name} num_class:{self.num_class}"


class MulticlassOVA(ObjectiveFunction):
    """K independent BinaryLogloss objectives
    (multiclass_objective.hpp:139-225)."""

    name = "multiclassova"
    rowwise_multi = True

    def __init__(self, config):
        self.num_class = int(config.num_class)
        self.sigmoid = float(config.sigmoid)
        self._config = config

    def init(self, metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        self.binary = []
        for k in range(self.num_class):
            b = BinaryLogloss(self._config, is_pos=lambda lab, kk=k: lab == kk)
            b.init(metadata, num_data)
            self.binary.append(b)

    def get_gradients(self, score):
        outs = [self.binary[k].get_gradients(score[k]) for k in range(self.num_class)]
        grad = jnp.stack([g for g, _ in outs])
        hess = jnp.stack([h for _, h in outs])
        return grad, hess

    def gradients_rowwise_all(self, scores, label, weight):
        # the raw class-index label goes through: binary[k]'s is_pos
        # closure tests ``label == k`` itself
        outs = [
            self.binary[k].gradients_rowwise(scores[k : k + 1], label, weight)
            for k in range(self.num_class)
        ]
        grad = jnp.concatenate([g for g, _ in outs], axis=0)
        hess = jnp.concatenate([h for _, h in outs], axis=0)
        return grad, hess

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * score))

    @property
    def num_tree_per_iteration(self) -> int:
        return self.num_class

    @property
    def num_predict_one_row(self) -> int:
        return self.num_class

    def to_string(self) -> str:
        return f"{self.name} num_class:{self.num_class} sigmoid:{self.sigmoid:g}"
