"""LambdarankNDCG objective — parity with
src/objective/rank_objective.hpp:19-244 (pair loop at 115-160).

TPU-first design: the reference walks each query's sorted docs with a
nested pairwise loop under OpenMP.  Here queries are padded to the max
query length S and vmapped: per query an (S, S) pairwise lambda matrix is
formed over the score-sorted docs, masked to (high_label > low_label)
pairs, row/column-reduced, and scattered back to document order.  All
queries evaluate as one (Q, S, S) batched program on the VPU — no ragged
shapes, no host loop.

The sigmoid lookup table (ConstructSigmoidTable, hpp:187-201) is replaced
by computing 2/(1+exp(2*sigmoid*x)) directly — on TPU the transcendental
is cheaper than a 1M-entry gather.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.log import Log
from .base import ObjectiveFunction


def default_label_gain(max_label: int = 31) -> np.ndarray:
    """label_gain = 2^i - 1 (config.cpp:271-277)."""
    g = [0.0] + [float((1 << i) - 1) for i in range(1, max_label)]
    return np.asarray(g, dtype=np.float64)


def dcg_discounts(max_position: int) -> np.ndarray:
    """discount[i] = 1/log2(2+i) (dcg_calculator.cpp:23-26)."""
    return 1.0 / np.log2(2.0 + np.arange(max_position, dtype=np.float64))


def max_dcg_at_k(k: int, labels: np.ndarray, label_gain: np.ndarray) -> float:
    """DCGCalculator::CalMaxDCGAtK (dcg_calculator.cpp:28-50): ideal DCG
    from sorted label counts."""
    k = min(k, len(labels))
    gains = np.sort(label_gain[labels.astype(np.int64)])[::-1][:k]
    disc = dcg_discounts(k)
    return float(np.sum(gains * disc[: len(gains)]))


def pad_queries(query_boundaries: np.ndarray, pad_to: int | None = None):
    """(Q, S) padded doc-index matrix + (Q, S) valid mask + (Q,) counts.

    ``pad_to`` overrides the pad width S.  Sharded training MUST pass the
    GLOBAL max group size here: padding to the local max would give each
    world size (and each post-rebalance shard) a different (Q, S, S)
    program shape, hence a different f32 reduction order and ulp-level
    gradient drift that quantized stochastic rounding amplifies into
    different trees.  The global max is a dataset constant, invariant
    under whole-group moves, so one gather at init covers every reshard.
    """
    q = len(query_boundaries) - 1
    sizes = np.diff(query_boundaries)
    s = int(sizes.max()) if q else 1
    if pad_to is not None:
        if pad_to < s:
            Log.fatal("pad_queries: pad_to=%d below local max group size %d",
                      int(pad_to), s)
        s = int(pad_to)
    doc_idx = np.zeros((q, s), dtype=np.int32)
    valid = np.zeros((q, s), dtype=bool)
    for i in range(q):
        c = sizes[i]
        doc_idx[i, :c] = np.arange(query_boundaries[i], query_boundaries[i + 1])
        valid[i, :c] = True
    return doc_idx, valid, sizes.astype(np.int32)


class LambdarankNDCG(ObjectiveFunction):
    name = "lambdarank"

    def __init__(self, config):
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0.0:
            Log.fatal("Sigmoid param %f should be greater than zero", self.sigmoid)
        self.optimize_pos_at = int(config.max_position)
        lg = config.label_gain
        self.label_gain = (
            np.asarray(lg, np.float64) if lg else default_label_gain()
        )

    def init(self, metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            Log.fatal("Lambdarank tasks require query information")
        qb = np.asarray(metadata.query_boundaries, np.int64)
        lab = np.asarray(metadata.label, np.float32)
        self.num_queries = len(qb) - 1
        doc_idx, valid, sizes = pad_queries(
            qb, getattr(metadata, "pad_group_size", None))
        s = doc_idx.shape[1]
        # inverse max DCG per query (hpp:58-69)
        inv = np.zeros(self.num_queries, np.float64)
        for i in range(self.num_queries):
            m = max_dcg_at_k(self.optimize_pos_at, lab[qb[i]: qb[i + 1]], self.label_gain)
            inv[i] = 1.0 / m if m > 0.0 else 0.0
        self.doc_idx = jnp.asarray(doc_idx)
        self.valid = jnp.asarray(valid)
        self.inverse_max_dcg = jnp.asarray(inv.astype(np.float32))
        self.gain_of_doc = jnp.asarray(
            self.label_gain[lab.astype(np.int64)].astype(np.float32)
        )
        self.discount = jnp.asarray(dcg_discounts(s).astype(np.float32))

    # ------------------------------------------------------------------
    def _one_query(self, score_q, label_q, gain_q, valid_q, inv_max_dcg):
        """(S,) padded arrays -> (S,) lambdas/hessians in padded doc order.

        Mirrors GetGradientsForOneQuery (hpp:85-170) with the pair loop as
        an (S, S) matrix; [i] indexes sorted position, high along rows.
        """
        s = score_q.shape[0]
        neg_inf = jnp.float32(-jnp.inf)
        skey = jnp.where(valid_q, score_q, neg_inf)
        order = jnp.argsort(-skey)  # stable: score desc, pads last
        sc = skey[order]
        lb = label_q[order]
        gains = gain_q[order]
        vd = valid_q[order]
        disc = self.discount[:s]

        cnt = jnp.sum(vd.astype(jnp.int32))
        best_score = sc[0]
        worst_idx = jnp.maximum(cnt - 1, 0)
        worst_score = sc[worst_idx]
        score_spread = best_score != worst_score

        # pairwise (high=i rows, low=j cols)
        delta_score = sc[:, None] - sc[None, :]
        dcg_gap = gains[:, None] - gains[None, :]
        paired_discount = jnp.abs(disc[:, None] - disc[None, :])
        delta_ndcg = dcg_gap * paired_discount * inv_max_dcg
        # regularize by score distance (hpp:145-147)
        delta_ndcg = jnp.where(
            score_spread, delta_ndcg / (0.01 + jnp.abs(delta_score)), delta_ndcg
        )
        # GetSigmoid(delta) = 2/(1+exp(2*sigmoid*delta)) (hpp:197-200)
        p_lambda = 2.0 / (1.0 + jnp.exp(2.0 * self.sigmoid * delta_score))
        p_hessian = p_lambda * (2.0 - p_lambda)
        lam = -delta_ndcg * p_lambda
        hes = 2.0 * delta_ndcg * p_hessian

        mask = (lb[:, None] > lb[None, :]) & vd[:, None] & vd[None, :]
        lam = jnp.where(mask, lam, 0.0)
        hes = jnp.where(mask, hes, 0.0)

        lam_sorted = jnp.sum(lam, axis=1) - jnp.sum(lam, axis=0)
        hes_sorted = jnp.sum(hes, axis=1) + jnp.sum(hes, axis=0)

        # scatter back from sorted order to padded doc order
        lam_doc = jnp.zeros(s).at[order].set(lam_sorted)
        hes_doc = jnp.zeros(s).at[order].set(hes_sorted)
        return lam_doc, hes_doc

    def get_gradients(self, score):
        sq = score[self.doc_idx]  # (Q, S)
        lq = self.label[self.doc_idx]
        gq = self.gain_of_doc[self.doc_idx]
        lam, hes = jax.vmap(self._one_query)(
            sq, lq, gq, self.valid, self.inverse_max_dcg
        )
        n = score.shape[0]
        flat_idx = self.doc_idx.reshape(-1)
        w = self.valid.reshape(-1).astype(score.dtype)
        grad = jnp.zeros(n, score.dtype).at[flat_idx].add(lam.reshape(-1) * w)
        hess = jnp.zeros(n, score.dtype).at[flat_idx].add(hes.reshape(-1) * w)
        if self.weights is not None:
            grad = grad * self.weights
            hess = hess * self.weights
        return grad, hess
