"""DART (Dropouts meet Multiple Additive Regression Trees) — counterpart of
src/boosting/dart.hpp (TrainOneIter:49-63, DroppingTrees:84-120,
Normalize:122-170).

Dropped trees are subtracted from the device score arrays via binned
traversal (the reference's Shrinkage(-1)+AddScore dance), the new tree
trains on the dropped scores, then everything is re-normalized.
"""

from __future__ import annotations

import numpy as np

from ..utils.random import Random
from .gbdt import GBDT


class DART(GBDT):
    supports_partitioned = False  # host-side drop/normalize hooks
    # dropping re-scores dropped trees over the whole train set each
    # iteration — under streaming that would multiply matrix passes
    supports_ooc = False

    def init(self, config, train_set, objective, training_metrics=()):
        super().init(config, train_set, objective, training_metrics)
        self.random_for_drop = Random(config.drop_seed)
        self.tree_weight = []
        self.sum_weight = 0.0
        self.drop_index = []
        self.is_update_score_cur_iter = False
        self.shrinkage_rate = config.learning_rate

    def train_one_iter(self, gradients=None, hessians=None, is_eval=True) -> bool:
        """dart.hpp:49-63: train (without eval), normalize, then eval."""
        self.is_update_score_cur_iter = False
        stopped = super().train_one_iter(gradients, hessians, is_eval=False)
        if stopped:
            return True
        self._normalize()
        if not self.config.uniform_drop:
            self.tree_weight.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
        if is_eval:
            return self.eval_and_check_early_stopping()
        return False

    def get_training_score(self):
        """GetTrainingScore (dart.hpp:66-76): drop trees once per iter
        before gradients are computed."""
        if not self.is_update_score_cur_iter:
            self._dropping_trees()
            self.is_update_score_cur_iter = True
        return self.scores

    # ------------------------------------------------------------------
    def _model_offset(self) -> int:
        """Trees before iteration 0 (the boost_from_average init tree)."""
        return 1 if self.boost_from_average_ else 0

    def _dropping_trees(self):
        """DroppingTrees (dart.hpp:84-120)."""
        cfg = self.config
        self.drop_index = []
        is_skip = self.random_for_drop.next_float() < cfg.skip_drop
        if not is_skip and self.iter > 0:
            drop_rate = cfg.drop_rate
            if not cfg.uniform_drop:
                inv_avg = len(self.tree_weight) / self.sum_weight if self.sum_weight else 0.0
                if cfg.max_drop > 0:
                    drop_rate = min(drop_rate, cfg.max_drop * inv_avg / max(self.sum_weight, 1e-30))
                for i in range(self.iter):
                    if self.random_for_drop.next_float() < drop_rate * self.tree_weight[i] * inv_avg:
                        self.drop_index.append(i)
            else:
                if cfg.max_drop > 0:
                    drop_rate = min(drop_rate, cfg.max_drop / float(self.iter))
                for i in range(self.iter):
                    if self.random_for_drop.next_float() < drop_rate:
                        self.drop_index.append(i)
        # subtract dropped trees from training scores
        k = self.num_tree_per_iteration
        off = self._model_offset()
        for i in self.drop_index:
            for tree_id in range(k):
                tree = self.models[off + i * k + tree_id]
                tree.shrinkage(-1.0)
                self._add_tree_to_train_scores(tree, tree_id)
        ndrop = len(self.drop_index)
        if not cfg.xgboost_dart_mode:
            self.shrinkage_rate = cfg.learning_rate / (1.0 + ndrop)
        else:
            if ndrop == 0:
                self.shrinkage_rate = cfg.learning_rate
            else:
                self.shrinkage_rate = cfg.learning_rate / (cfg.learning_rate + ndrop)

    def _normalize(self):
        """Normalize (dart.hpp:122-170)."""
        cfg = self.config
        k_drop = float(len(self.drop_index))
        k = self.num_tree_per_iteration
        off = self._model_offset()
        for i in self.drop_index:
            for tree_id in range(k):
                tree = self.models[off + i * k + tree_id]
                if not cfg.xgboost_dart_mode:
                    tree.shrinkage(1.0 / (k_drop + 1.0))
                    self._add_tree_to_valid(tree, tree_id)
                    tree.shrinkage(-k_drop)
                    self._add_tree_to_train_scores(tree, tree_id)
                else:
                    tree.shrinkage(self.shrinkage_rate)
                    self._add_tree_to_valid(tree, tree_id)
                    tree.shrinkage(-k_drop / cfg.learning_rate)
                    self._add_tree_to_train_scores(tree, tree_id)
            if not cfg.uniform_drop:
                if not cfg.xgboost_dart_mode:
                    self.sum_weight -= self.tree_weight[i] * (1.0 / (k_drop + 1.0))
                    self.tree_weight[i] *= k_drop / (k_drop + 1.0)
                else:
                    self.sum_weight -= self.tree_weight[i] * (
                        1.0 / (k_drop + cfg.learning_rate)
                    )
                    self.tree_weight[i] *= k_drop / (k_drop + cfg.learning_rate)

    def _add_tree_to_valid(self, tree, tree_id):
        self._add_tree_to_valid_scores(tree, tree_id)

    # ------------------------------------------------------------------
    def export_train_state(self):
        """Checkpoint hook: DART's per-iteration drop decisions come
        from a stateful LCG (``random_for_drop``) and the accumulated
        tree-weight ledger — none of which the model text can carry."""
        arrays, py = super().export_train_state()
        py["dart"] = {
            "drop_rng": self.random_for_drop.get_state(),
            "tree_weight": [float(w) for w in self.tree_weight],
            "sum_weight": float(self.sum_weight),
        }
        return arrays, py

    def import_train_state(self, arrays, py) -> None:
        super().import_train_state(arrays, py)
        st = py["dart"]
        self.random_for_drop.set_state(st["drop_rng"])
        self.tree_weight = [float(w) for w in st["tree_weight"]]
        self.sum_weight = float(st["sum_weight"])
        self.drop_index = []
        self.is_update_score_cur_iter = False

    def sub_model_name(self) -> str:
        return "tree"
