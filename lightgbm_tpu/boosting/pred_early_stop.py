"""Prediction early stopping — counterpart of
src/boosting/prediction_early_stop.cpp: margin-based early exit across
trees during row-at-a-time prediction.

On TPU the batched vmapped traversal (ops/predict.py) is usually faster
than any early exit; this host path exists for API parity and for
latency-sensitive single-row serving, mirroring the reference's
round_period/margin_threshold semantics.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import numpy as np

from ..utils.log import Log


class PredictionEarlyStopInstance(NamedTuple):
    """(callback, round_period) — callback(pred_row) -> stop?"""

    callback: Callable[[np.ndarray], bool]
    round_period: int


def create_prediction_early_stop_instance(
    type_: str, round_period: int = 10, margin_threshold: float = 10.0
) -> PredictionEarlyStopInstance:
    """CreatePredictionEarlyStopInstance (prediction_early_stop.cpp:74-89)."""
    if type_ == "none":
        return PredictionEarlyStopInstance(lambda pred: False, 1 << 30)
    if type_ == "binary":

        def cb_binary(pred: np.ndarray) -> bool:
            if len(pred) != 1:
                Log.fatal("Binary early stopping needs predictions to be of length one")
            return 2.0 * abs(float(pred[0])) > margin_threshold

        return PredictionEarlyStopInstance(cb_binary, round_period)
    if type_ == "multiclass":

        def cb_multiclass(pred: np.ndarray) -> bool:
            if len(pred) < 2:
                Log.fatal(
                    "Multiclass early stopping needs predictions to be of "
                    "length two or larger"
                )
            top2 = np.partition(pred, -2)[-2:]
            return float(top2[1] - top2[0]) > margin_threshold

        return PredictionEarlyStopInstance(cb_multiclass, round_period)
    Log.fatal("Unknown early stopping type: %s", type_)


def predict_with_early_stop(
    boosting, data: np.ndarray, early_stop: PredictionEarlyStopInstance,
    num_iteration: int = -1,
) -> np.ndarray:
    """Row-at-a-time raw prediction with the margin exit
    (GBDT::PredictRaw + early stop, gbdt_prediction.cpp)."""
    k = boosting.num_tree_per_iteration
    models = boosting._used_models(num_iteration)
    n = data.shape[0]
    out = np.zeros((n, k))
    for r in range(n):
        row = data[r: r + 1]
        pred = np.zeros(k)
        for i in range(0, len(models), k):
            for kk in range(k):
                pred[kk] += float(models[i + kk].predict(row)[0])
            iter_idx = i // k + 1
            if iter_idx % early_stop.round_period == 0 and early_stop.callback(pred):
                break
        out[r] = pred
    return out
