"""Out-of-core tree growth: the bin matrix streams, the vectors stay.

The mask grower (ops/grow.py) needs the whole ``(N, F)`` bin matrix
device-resident, which caps one chip at HBM size.  This trainer drops
that requirement with a "vector-resident, matrix-streamed" split of the
training state:

  - every per-row VECTOR — scores, grad, hess, select, ``leaf_id`` — is
    a handful of N-floats and stays device-resident, so the objective,
    GOSS re-weighting, bagging masks and score updates run the exact
    same programs as the in-memory path;
  - the ``(N, F)`` MATRIX is the only O(N·F) tensor, and the histogram
    is the only thing that reads it — "Out-of-Core GPU Gradient
    Boosting" (PAPERS.md) rests on the same observation — so it streams
    through the double-buffered prefetch ring (data/prefetch.py) in
    row-chunks and peak device residency is O(2 chunks), not O(dataset).

Per tree the trainer replays the grower's best-first loop on the host:
one streamed pass builds the root histogram, then each split makes one
pass that partitions the chunk's ``leaf_id`` slice and folds BOTH
children's histogram partials (ops/ooc.py ``split_chunk`` — 2x flops for
1x transfer, and transfers bound the out-of-core regime).  The directly-
accumulated histogram of the *smaller* child is kept and the larger is
derived by the subtraction trick, exactly as in-memory.

The streaming machinery itself — source selection, the prefetch ring,
and the per-chunk fold loops — lives in ``data/chunksource.py``
(:class:`ChunkStream` / :class:`ChunkFolder`), the seam this trainer
shares with the rank-sharded :class:`~..boosting.oocdist.DistributedOocTrainer`.

Bit-identity contract: with ``chunk_rows`` a ``ROW_BLOCK`` multiple
(enforced by rounding up), the streamed histogram folds reproduce the
in-memory scan's left-to-right block adds bit-for-bit, and every other
op is elementwise/integer or runs on scalars at the in-memory shapes —
so at any scale where the in-memory grower uses the masked full scan
(``N <= TIER_MIN``; above it the in-memory path switches to tiered
gather compaction, which reorders row summation), the out-of-core model
string is byte-identical.  tests/test_ooc.py pins this for gbdt and
GOSS, plus mid-run checkpoint kill/resume.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..data.chunksource import (
    ChunkFolder,
    ChunkPlan,
    ChunkStream,
    PrefetchStats,
    make_chunk_source,
)
from ..obs import tracer
from ..ops.grow import GrowResult
from ..ops.histogram import ROW_BLOCK
from ..ops.ooc import child_leaf_values, find_best_split, root_totals
from ..ops.qhist import dequantize_hist, dequantize_sums
from ..ops.split import NEG_INF
from ..utils.log import Log

# auto chunk sizing aims each chunk at ~64 MiB of packed bins: big enough
# to amortize dispatch, small enough that two in-flight buffers are noise
# next to HBM.
_AUTO_CHUNK_BYTES = 64 << 20


def _device_budget_bytes() -> Optional[int]:
    """The device-memory budget the auto mode compares the packed matrix
    against: LIGHTGBM_TPU_DEVICE_BUDGET (bytes) when set, else the
    backend's reported per-device limit, else None (auto stays off)."""
    env = os.environ.get("LIGHTGBM_TPU_DEVICE_BUDGET", "").strip()
    if env:
        try:
            return int(env)
        except ValueError:
            Log.warning("LIGHTGBM_TPU_DEVICE_BUDGET=%r is not an integer "
                        "byte count; ignoring", env)
    try:
        import jax

        stats = jax.devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
        return int(limit) if limit else None
    except Exception:
        return None


def resolve_chunk_rows(config, num_features: int, itemsize: int) -> int:
    """The streaming chunk height: ``ooc_chunk_rows`` when set, else
    ~64 MiB of packed rows — always rounded UP to a ``ROW_BLOCK``
    multiple, the bit-identity alignment contract (a 1-row request
    degenerates to one block, never to a shorter summation).  Under the
    distributed trainer the same rounding applies per rank, over that
    rank's shard rows."""
    rows = int(getattr(config, "ooc_chunk_rows", 0) or 0)
    if rows <= 0:
        row_bytes = max(num_features * itemsize, 1)
        rows = max(_AUTO_CHUNK_BYTES // row_bytes, 1)
    return -(-rows // ROW_BLOCK) * ROW_BLOCK


def resolve_out_of_core(config, train_set) -> Tuple[bool, int, str]:
    """Routing decision: ``(enabled, chunk_rows, reason)``.

    ``out_of_core`` = true/false forces; "auto" turns streaming on only
    when the packed matrix exceeds the device budget.  The
    LIGHTGBM_TPU_OOC env var overrides the config knob per-run.  In a
    multi-process run ``train_set`` is this rank's shard, so the budget
    comparison (and the chunk grid) is naturally per rank."""
    mode = os.environ.get("LIGHTGBM_TPU_OOC", "").strip().lower()
    if not mode:
        mode = str(getattr(config, "out_of_core", "auto")).strip().lower()
    if mode in ("false", "0", "off", "no"):
        return False, 0, "out_of_core=false"
    if mode not in ("true", "1", "on", "yes", "auto"):
        Log.fatal("Unknown out_of_core mode %r (expected true/false/auto)",
                  mode)
    binned = train_set.binned
    packed = int(train_set.num_data) * int(train_set.num_features) * \
        int(binned.dtype.itemsize)
    if mode == "auto":
        budget = _device_budget_bytes()
        if budget is None:
            return False, 0, "auto: no device budget known"
        if packed <= budget:
            return False, 0, (f"auto: packed bins {packed} B fit the "
                              f"{budget} B device budget")
        reason = (f"auto: packed bins {packed} B exceed the {budget} B "
                  "device budget")
    else:
        reason = "out_of_core=true (forced)"
    chunk_rows = resolve_chunk_rows(
        config, train_set.num_features, binned.dtype.itemsize)
    return True, chunk_rows, reason


class OocTrainer:
    """Drop-in ``learner`` for GBDT: ``grow()`` matches ShardedLearner's
    signature (the ``bins`` argument is ignored — the matrix is streamed
    from this trainer's chunk source, never device-resident)."""

    def __init__(self, train_set, config, grow_params, chunk_rows: int):
        if grow_params.parallel != "serial":
            raise ValueError("out-of-core training is serial-only")
        self.params = grow_params._replace(compact=False)
        self.num_rows = int(train_set.num_data)
        self.num_features = int(train_set.num_features)
        self.plan = ChunkPlan(self.num_rows, chunk_rows)
        self.stats = PrefetchStats()
        self.depth = max(int(getattr(config, "ooc_prefetch_depth", 2) or 2), 1)
        self.source = make_chunk_source(train_set)
        self.chunks = ChunkStream(self.source, self.plan, self.depth,
                                  self.stats)
        self.folder = ChunkFolder(self.chunks, self.num_features,
                                  self.params.num_bins,
                                  self.params.row_block)
        self._trees_grown = 0
        tracer.event(
            "ooc.plan",
            rows=self.num_rows, features=self.num_features,
            chunk_rows=self.plan.chunk_rows, chunks=self.plan.num_chunks,
            depth=self.depth, source=self.source.describe(),
        )
        Log.info(
            "Out-of-core training: %d rows in %d chunks of %d (%s, "
            "prefetch depth %d)", self.num_rows, self.plan.num_chunks,
            self.plan.chunk_rows, self.source.describe(), self.depth,
        )

    def schedule_fingerprint(self) -> str:
        """Chunk-schedule identity for checkpoints: a resume streaming a
        different grid would change float summation order."""
        return self.plan.fingerprint()

    # ------------------------------------------------------------------
    def grow(self, bins_ignored, grad, hess, select, feature_mask,
             meta, hyper, qscale=None) -> GrowResult:
        """Grow one leaf-wise tree, streaming the matrix per pass.

        Host-driven replay of ``grow_tree``'s best-first loop: the
        per-leaf tables live on host as np.float32 (f32 round-trips are
        exact; ``np.argmax`` keeps the same first-max tie-break), the
        histograms live on device and accumulate chunk-by-chunk through
        the ChunkFolder's streamed folds.

        Quantized training: int16 ``grad``/``hess`` (plus the (2,)
        ``qscale``) switch the streamed folds to exact int32 — integer
        adds are associative, so the chunk grid cannot perturb the
        histogram AT ALL (the f32 contract needs ROW_BLOCK-aligned
        boundaries for that) — and dequantization happens once per
        node, just before the split scan."""
        L = self.params.num_leaves
        use_missing = self.params.use_missing
        stats0 = dict(self.stats.as_dict())
        quant = jnp.issubdtype(grad.dtype, jnp.integer)
        if quant and qscale is None:
            raise ValueError("integer grad/hess require the qscale argument")
        deq = (lambda h: dequantize_hist(h, qscale)) if quant else (lambda h: h)
        # monotone-constraint strategy seam (tree/strategy.py): the
        # host-driven replay carries per-leaf output bounds in the same
        # np.float32 tables as the split state; unconstrained keeps the
        # exact pre-strategy call graph (None kwargs)
        mono_t = self.params.strategy.split_gain.monotone
        use_mono = any(c != 0 for c in mono_t)
        if use_mono and len(mono_t) != self.num_features:
            raise ValueError(
                f"monotone constraint vector has {len(mono_t)} entries "
                f"but the dataset has {self.num_features} inner features")
        mono = jnp.asarray(mono_t, jnp.int32) if use_mono else None
        leaf_lo = np.full((self.params.num_leaves,), NEG_INF, np.float32)
        leaf_hi = np.full((self.params.num_leaves,), np.inf, np.float32)

        with tracer.span("ooc.grow", tree=self._trees_grown,
                         chunks=self.plan.num_chunks):
            # ---- root: LeafSplits::Init on the resident vectors + one
            # streamed histogram pass
            sums_dev = root_totals(grad, hess, select)
            if quant:
                sums_dev = dequantize_sums(sums_dev, qscale)
            hist = self.folder.fold_root(grad, hess, select)
            root_sums = np.asarray(sums_dev, np.float32)
            if use_mono:
                root_res = find_best_split(
                    deq(hist), sums_dev, feature_mask, True, meta, hyper,
                    use_missing, monotone=mono,
                    leaf_lo=leaf_lo[0], leaf_hi=leaf_hi[0])
            else:
                root_res = find_best_split(deq(hist), sums_dev,
                                           feature_mask, True, meta,
                                           hyper, use_missing)

            # host-side per-leaf tables (np.float32 throughout: any f64
            # promotion here would change the replayed arithmetic)
            bs_gain = np.full((L,), NEG_INF, np.float32)
            bs_feat = np.zeros((L,), np.int32)
            bs_thr = np.zeros((L,), np.int32)
            bs_dbz = np.zeros((L,), np.int32)
            bs_left = np.zeros((L, 3), np.float32)
            leaf_sum = np.zeros((L, 3), np.float32)
            leaf_value = np.zeros((L,), np.float32)
            leaf_cnt = np.zeros((L,), np.float32)
            leaf_depth = np.zeros((L,), np.int32)
            leaf_rows = np.zeros((L,), np.int64)
            rec_i = {k: np.zeros((L - 1,), np.int32)
                     for k in ("leaf", "feat", "thr", "dbz")}
            rec_f = {k: np.zeros((L - 1,), np.float32)
                     for k in ("gain", "lval", "rval", "lcnt", "rcnt",
                               "internal_value")}
            leaf_sum[0] = root_sums
            leaf_cnt[0] = root_sums[2]
            leaf_rows[0] = self.num_rows

            def store(leaf: int, res) -> None:
                bs_gain[leaf] = np.float32(res.gain)
                bs_feat[leaf] = np.int32(res.feature)
                bs_thr[leaf] = np.int32(res.threshold_bin)
                bs_dbz[leaf] = np.int32(res.default_bin_for_zero)
                bs_left[leaf] = np.asarray(
                    [res.left_sum_g, res.left_sum_h, res.left_cnt],
                    np.float32)

            store(0, root_res)
            pool = {0: hist}
            leaf_id = jnp.zeros((self.num_rows,), jnp.int32)
            default_bin = np.asarray(meta.default_bin)
            is_categorical = np.asarray(meta.is_categorical)

            num_splits = 0
            while num_splits < L - 1:
                bl = int(np.argmax(bs_gain))
                gain = bs_gain[bl]
                # "No further splits with positive gain"
                if not (gain > 0.0):
                    break
                s = num_splits
                rl = s + 1
                feat = int(bs_feat[bl])
                thr = int(bs_thr[bl])
                dbz = int(bs_dbz[bl])
                left = bs_left[bl].copy()
                right = leaf_sum[bl] - left
                if use_mono:
                    plo, phi = leaf_lo[bl], leaf_hi[bl]
                    lval_d, rval_d = child_leaf_values(
                        left, right, hyper.lambda_l1, hyper.lambda_l2,
                        plo, phi)
                    lval = np.float32(lval_d)
                    rval = np.float32(rval_d)
                    # BasicLeafConstraints mid-point tightening: splitting
                    # a constrained feature bounds the children at the
                    # midpoint of the two (clipped) outputs
                    cdir = int(mono_t[feat])
                    mid = np.float32((lval + rval) * np.float32(0.5))
                    child_lhi = mid if cdir > 0 else phi
                    child_llo = mid if cdir < 0 else plo
                    child_rlo = mid if cdir > 0 else plo
                    child_rhi = mid if cdir < 0 else phi
                    leaf_lo[bl], leaf_hi[bl] = child_llo, child_lhi
                    leaf_lo[rl], leaf_hi[rl] = child_rlo, child_rhi
                else:
                    lval_d, rval_d = child_leaf_values(
                        left, right, hyper.lambda_l1, hyper.lambda_l2)
                    lval = np.float32(lval_d)
                    rval = np.float32(rval_d)

                # ---- one streamed pass: partition + both children hists
                leaf_id, hist_l, hist_r, n_left = self.folder.fold_split(
                    leaf_id, pool[bl], grad, hess, select, feat,
                    int(default_bin[feat]), dbz, thr,
                    bool(is_categorical[feat]), bl, rl,
                )
                n_rows_left = int(n_left)
                n_rows_right = int(leaf_rows[bl]) - n_rows_left
                # smaller child keeps its DIRECT accumulation; the larger
                # is parent - smaller, matching the in-memory numerics
                left_hist, right_hist = ChunkFolder.pick_children(
                    pool[bl], hist_l, hist_r, n_rows_left, n_rows_right)
                pool[bl] = left_hist
                pool[rl] = right_hist

                child_depth = int(leaf_depth[bl]) + 1
                depth_ok = (self.params.max_depth <= 0
                            or child_depth < self.params.max_depth)
                if use_mono:
                    lres = find_best_split(
                        deq(left_hist), left, feature_mask, depth_ok,
                        meta, hyper, use_missing, monotone=mono,
                        leaf_lo=leaf_lo[bl], leaf_hi=leaf_hi[bl])
                    rres = find_best_split(
                        deq(right_hist), right, feature_mask, depth_ok,
                        meta, hyper, use_missing, monotone=mono,
                        leaf_lo=leaf_lo[rl], leaf_hi=leaf_hi[rl])
                else:
                    lres = find_best_split(deq(left_hist), left,
                                           feature_mask, depth_ok, meta,
                                           hyper, use_missing)
                    rres = find_best_split(deq(right_hist), right,
                                           feature_mask, depth_ok, meta,
                                           hyper, use_missing)

                rec_i["leaf"][s] = bl
                rec_i["feat"][s] = feat
                rec_i["thr"][s] = thr
                rec_i["dbz"][s] = dbz
                rec_f["gain"][s] = gain
                rec_f["lval"][s] = lval
                rec_f["rval"][s] = rval
                rec_f["lcnt"][s] = left[2]
                rec_f["rcnt"][s] = right[2]
                rec_f["internal_value"][s] = leaf_value[bl]
                leaf_sum[bl] = left
                leaf_sum[rl] = right
                leaf_value[bl] = lval
                leaf_value[rl] = rval
                leaf_cnt[bl] = left[2]
                leaf_cnt[rl] = right[2]
                leaf_depth[bl] = child_depth
                leaf_depth[rl] = child_depth
                leaf_rows[bl] = n_rows_left
                leaf_rows[rl] = n_rows_right
                store(bl, lres)
                store(rl, rres)
                num_splits += 1

        self._trees_grown += 1
        self._emit_stream_obs(stats0)
        return GrowResult(
            num_splits=np.int32(num_splits),
            leaf_id=leaf_id,
            leaf_value=leaf_value,
            leaf_cnt=leaf_cnt,
            rec_leaf=rec_i["leaf"], rec_feat=rec_i["feat"],
            rec_thr=rec_i["thr"], rec_dbz=rec_i["dbz"],
            rec_gain=rec_f["gain"], rec_lval=rec_f["lval"],
            rec_rval=rec_f["rval"], rec_lcnt=rec_f["lcnt"],
            rec_rcnt=rec_f["rcnt"],
            rec_internal_value=rec_f["internal_value"],
        )

    # ------------------------------------------------------------------
    def add_tree_scores(self, score_k, arrays):
        """Streamed ``predict_binned`` over the chunk grid: the rollback /
        DART score path when the matrix is not device-resident."""
        return self.folder.streamed_scores(score_k, arrays)

    def _emit_stream_obs(self, before: dict, **attrs) -> None:
        if not tracer.enabled:
            return
        now = self.stats.as_dict()
        tracer.counter("ooc.chunks", now["chunks"] - before["chunks"],
                       **attrs)
        tracer.counter("ooc.bytes", now["bytes"] - before["bytes"], **attrs)
        tracer.gauge("ooc.fetch_ms",
                     (now["fetch_s"] - before["fetch_s"]) * 1e3, **attrs)
        tracer.gauge("ooc.stall_ms",
                     (now["stall_s"] - before["stall_s"]) * 1e3, **attrs)
        tracer.gauge("ooc.overlap_pct", now["overlap_pct"], **attrs)
